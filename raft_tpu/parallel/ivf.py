"""Distributed IVF indexes — SPMD sharded build + search over a mesh.

The reference's raison-d'être for its comms stack: raft-dask sharded-index
patterns (SURVEY.md §2.15; raft_dask/common/comms.py:39) where each worker
builds an IVF index over its shard of the dataset and search merges
per-shard top-k candidates (``knn_merge_parts``,
neighbors/detail/knn_merge_parts.cuh). BASELINE config 5 (sharded IVF-PQ,
SIFT-1B on v5e-64) is this module's target shape.

TPU-native structure — everything is ``shard_map`` over one mesh axis:

- **coarse centers**: ONE distributed Lloyd program (local fused-L2
  assign + ``psum``-merged centroid sums — the reference's MNMG kmeans
  allreduce, SURVEY.md §3.5) over the row-sharded dataset, so every
  shard trains against the *global* data distribution, not its slice;
- **codebooks / rotation** (PQ): replicated. Codebooks train on an
  ``all_gather``-ed cross-shard subsample (the reference also trains on
  a trainset fraction, ivf_pq_build.cuh:1511);
- **encode + pack**: per shard, fully on device — ``ivf_common.pack_lists``
  (one stable sort + scatter) replaces the host packers, because inside
  an SPMD program there is no host round-trip. Stored ids are *global*
  row ids (shard offset baked in at build), so search needs no
  translation step;
- **search**: queries replicated; each shard scans its local lists with
  the single-device search kernel, then ``all_gather`` + final select_k
  merges candidates over ICI — the sharded brute-force pattern
  (parallel/knn.py) applied to IVF.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence, Tuple, Union

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from raft_tpu.core.errors import expects
from raft_tpu.core import ids as _ids
from raft_tpu.cluster import KMeansParams
from raft_tpu.cluster import distributed as dkm
from raft_tpu.distance import SELECT_MIN
from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.neighbors import ivf_flat as _flat
from raft_tpu.neighbors import ivf_pq as _pq
from raft_tpu.neighbors import ivf_common as ic
from raft_tpu.parallel import merge as _merge
from raft_tpu.parallel.comms import Comms
from raft_tpu.robust import faults as _faults


class ShardedIvfPq(flax.struct.PyTreeNode):
    """IVF-PQ index sharded over a mesh axis: quantizers replicated,
    packed lists carrying a leading device axis (sharded)."""

    centers: jax.Array        # [n_lists, dim] replicated
    centers_rot: jax.Array    # [n_lists, rot_dim] replicated
    rotation: jax.Array       # [rot_dim, dim] replicated
    codebooks: jax.Array      # [pq_dim, K, pq_len] replicated
    packed_codes: jax.Array   # [n_dev, n_lists, L, nbytes] u8, sharded
    packed_ids: jax.Array     # [n_dev, n_lists, L] i32 global ids, -1 pad
    packed_norms: jax.Array   # [n_dev, n_lists, L] f32
    list_sizes: jax.Array     # [n_dev, n_lists] i32
    metric: str = flax.struct.field(pytree_node=False, default="sqeuclidean")
    pq_bits: int = flax.struct.field(pytree_node=False, default=8)
    pq_dim: int = flax.struct.field(pytree_node=False, default=0)
    # rows per shard of the (padded) BUILD dataset — the global ids baked
    # into packed_ids are rank·shard_rows + local, so the refined search
    # can validate a caller-passed dataset against the build geometry
    # (0 = unknown, for indexes assembled by hand)
    shard_rows: int = flax.struct.field(pytree_node=False, default=0)
    # the GLOBAL list capacity a single-host build of the same dataset
    # would fit (stamped by parallel.build's distributed builders; 0 =
    # unknown) — parallel.build.assemble_ivf_pq truncates the rank-order
    # concat of per-shard list prefixes at exactly this capacity to
    # reproduce the single-host pack bit-identically
    global_list_cap: int = flax.struct.field(pytree_node=False, default=0)

    @property
    def n_shards(self) -> int:
        return self.packed_codes.shape[0]

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))


class ShardedIvfFlat(flax.struct.PyTreeNode):
    """IVF-Flat index sharded over a mesh axis (raw-vector lists)."""

    centers: jax.Array       # [n_lists, dim] replicated
    packed_data: jax.Array   # [n_dev, n_lists, L, dim] sharded
    packed_ids: jax.Array    # [n_dev, n_lists, L] i32 global ids
    packed_norms: jax.Array  # [n_dev, n_lists, L] f32
    list_sizes: jax.Array    # [n_dev, n_lists] i32
    metric: str = flax.struct.field(pytree_node=False, default="sqeuclidean")
    # see ShardedIvfPq.global_list_cap (parallel.build.assemble_ivf_flat)
    global_list_cap: int = flax.struct.field(pytree_node=False, default=0)

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]


def _warn_dropped(what: str, dropped: jax.Array) -> None:
    """Surface device-side pack overflow on the host (the host packers'
    warn path, ivf_flat._pack_lists:134)."""
    total = int(jnp.sum(dropped))
    if total:
        from raft_tpu.core import logging as _log
        _log.warn("sharded %s build: dropped %d overflow vectors (raise "
                  "list_size_cap_factor)", what, total)


def _pad_shard(x: jax.Array, n_dev: int) -> Tuple[jax.Array, int]:
    n = x.shape[0]
    padded = -(-n // n_dev) * n_dev
    if padded != n:
        x = jnp.pad(x, ((0, padded - n), (0, 0)))
    return x, n


def _coarse_centers(n_lists: int, n_iters: int, seed: int,
                    x: jax.Array, mesh: Mesh, axis: str,
                    spherical: bool) -> jax.Array:
    """Distributed Lloyd coarse fit (the reference trains kmeans_balanced
    per ivf_pq_build.cuh:1618; distributed it becomes the MNMG psum
    pattern). ``x`` must be UNPADDED — dkm.fit pads with zero weights
    itself. Spherical metrics re-normalize the centers."""
    km = KMeansParams(n_clusters=n_lists, max_iter=n_iters, seed=seed)
    centers, _, _ = dkm.fit(km, x, mesh, axis=axis)
    if spherical:
        centers = centers / jnp.sqrt(
            jnp.maximum(jnp.sum(centers**2, -1, keepdims=True), 1e-12))
    return centers


def _gather_trainset(x: jax.Array, mesh: Mesh, axis: str, t: int,
                     seed: int, n_real: int) -> jax.Array:
    """Replicated trainset [n_dev·t, d] sampled uniformly (with
    replacement) from the *global real* rows of the sharded dataset (the
    PQ codebooks' trainset fraction, SURVEY §3.1).

    Every shard draws the SAME global row ids (same key), keeps the ones
    it owns, and a ``psum`` assembles the replicated result — so the zero
    rows `_pad_shard` appends never reach codebook training (even when a
    whole shard is padding), and the sample is uniform over the dataset
    rather than per-shard (which would overweight short shards)."""
    n_dev = mesh.shape[axis]
    total = n_dev * t
    comms = Comms(axis)

    def local(x_shard):
        rank = comms.get_rank()
        shard_n = x_shard.shape[0]
        key = jax.random.PRNGKey(seed)  # identical on every shard
        gidx = jax.random.randint(key, (total,), 0, n_real,
                                  dtype=_ids.id_dtype(n_real))
        local_idx = _ids.local_ids(gidx, rank, shard_n)
        owned = (local_idx >= 0) & (local_idx < shard_n)
        rows = x_shard[jnp.clip(local_idx, 0, shard_n - 1)]
        contrib = jnp.where(owned[:, None], rows, 0.0)
        return comms.allreduce(contrib)

    fn = shard_map(local, mesh=mesh, in_specs=(P(axis, None),),
                   out_specs=P(), check_vma=False)
    return fn(x)


# Cross-shard candidate merges route through parallel/merge.py — the
# one dispatch point shared with parallel/knn.py (allgather-and-select
# vs the ring reduce-scatter-of-top-k tier; reference:
# knn_merge_parts.cuh). All merge traffic rides the Comms facade so it
# lands in the ``comms.ops``/``comms.bytes`` counters per axis.


# ---------------------------------------------------------------------------
# fused scan-in-ring tier (ROADMAP item 5): per-shard LUT scan folded
# into the ring exchange — one persistent kernel from packed codes to
# the merged top-k; the per-shard [m, k] candidate table never exists
# ---------------------------------------------------------------------------

def _ring_fused_wanted(index: "ShardedIvfPq", m: int, k: int,
                       n_probes: int, n_dev: int, whole_mesh: bool,
                       merge: str, mt: DistanceType, lut_dtype: str,
                       scan_select: str,
                       filtered: bool = False) -> Tuple[bool, str]:
    """Dispatch for the fused scan-in-ring tier. Returns
    ``(take_it, decline_reason)`` — reason is non-empty only when the
    tier was WANTED (env force, or auto on an eligible ring setup) but
    a capability check declined it; those land in
    ``parallel.merge.fallback{reason=...}`` so "why isn't the sharded
    scan fused?" is one counter query.

    ``RAFT_TPU_RING_FUSED`` = auto | on | off: auto takes the tier
    exactly where the ring KERNEL would have carried the merge (TPU,
    whole-mesh axis, ring-winning shape) — the fused kernel is the same
    exchange with the scan moved inside; "on" forces it (interpret mode
    off-TPU — tests), "off" never. The tier declines (fallback to the
    unfused scan + merge path, preserving every existing dispatch rung,
    including the int64-id ppermute decline):

    - ``scan_select``: the fused kernel carries the LUT-bin tier's
      recall-targeted selection semantics, so it only serves searches
      the single-chip dispatch would route there anyway — an explicit
      ``scan_select="pallas"``, or ``"approx"`` at the oversampled
      auto-upgrade shape. The default ``"exact"`` keeps exact-selection
      semantics on the unfused path, even under env force;
    - ``id_width``: int64 id tables — the kernel is int32-only;
    - ``metric``: cosine (the fused epilogue serves l2/ip keys);
    - ``kernel_ineligible``: unsupported packed layout, k past the
      merge budget, VMEM budget, or a union-segment table past
      ``RING_FUSED_MAX_SEGS``;
    - ``latency_bound``: shapes where auto mode keeps the single
      allgather (``ring_auto_wanted``).

    ``filtered`` admits the per-shard filter-byte stream: the kernel's
    VMEM model grows the filter slots + unpack selection matrix
    (``ring_lut_scan_kernel_ok``) and the HBM transient — the shard's
    ``[n_lists, ceil(L/8)]`` packed byte rows — must pass
    ``ivf_common.filtered_scan_mem_ok`` (``mem_guard`` decline).
    """
    from raft_tpu.obs import spans as _obs_spans
    from raft_tpu.ops import pallas_kernels as _pk

    force = _obs_spans.env_tristate("RAFT_TPU_RING_FUSED")
    if force == "off" or merge == "allgather":
        return False, ""
    if force != "on" and not (_pk._on_tpu() and whole_mesh):
        return False, ""
    if not (scan_select == "pallas"
            or (scan_select == "approx"
                and (n_probes >= 64 or k >= 400))):
        # never swap exact-selection semantics for the bin tier's —
        # mirror of the single-chip LUT-tier routing
        return False, "scan_select"
    if mt not in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                  DistanceType.InnerProduct):
        return False, "metric"
    if jnp.dtype(index.packed_ids.dtype).itemsize >= 8:
        return False, "id_width"
    if force != "on" and not _merge.ring_auto_wanted(m, k, n_dev):
        return False, "latency_bound"
    mc = _pk.ring_chunk_rows(m, n_dev)
    NS = min(mc * n_probes, index.n_lists)
    # nb from pq geometry, Wb from the stored layout — the same pair
    # ring_lut_scan_merge derives, so the admission check and the
    # kernel agree on lane-folded layouts (Wb > nb) instead of only on
    # the unfolded sharded build where the two coincide
    nb = (index.pq_dim * index.pq_bits + 7) // 8
    Wb = index.packed_codes.shape[3]
    ok = _pk.ring_lut_scan_kernel_ok(
        index.pq_dim, 1 << index.pq_bits,
        index.codebooks.shape[2], nb, Wb, mc, NS, k, n_dev,
        index.centers_rot.shape[1], lut_dtype=lut_dtype,
        filtered=filtered)
    if not ok:
        return False, "kernel_ineligible"
    if filtered and not ic.filtered_scan_mem_ok(
            index.n_lists, index.packed_ids.shape[2]):
        return False, "mem_guard"
    return True, ""


def _search_fused_ring(index: "ShardedIvfPq", q: jax.Array, k: int,
                       n_probes: int, mesh: Mesh, axis: str,
                       lut_dtype: str, mt: DistanceType,
                       filter_bits=None
                       ) -> Tuple[jax.Array, jax.Array]:
    """The fused scan-in-ring search: probes + chunk unions + one
    persistent Pallas kernel per shard (``ring_lut_scan_merge``), then
    the LUT-key → metric epilogue. Results are query-sharded like the
    ring merge tier's.

    ``filter_bits`` (replicated, GLOBAL row ids): each shard composes
    the global bitset with its own global-id table — the per-shard
    bitset slice — into the packed per-candidate byte rows the ring
    kernel streams beside the codes (``sample_filter.list_filter_bytes``
    over ``packed_ids[shard]``, whose global ids bake in the shard
    offset), so filtered pod-scale search rides the ring kernel too."""
    from raft_tpu.obs import spans as _obs_spans
    from raft_tpu.ops import pallas_kernels as _pk

    m = q.shape[0]
    n_dev = index.n_shards
    mc = _pk.ring_chunk_rows(m, n_dev)
    mq = mc * n_dev
    ip_like = mt == DistanceType.InnerProduct
    NS = min(mc * n_probes, index.n_lists)
    L = index.packed_codes.shape[2]
    qp = jnp.pad(q, ((0, mq - m), (0, 0))) if mq > m else q
    comms = Comms(axis)
    interpret = not _pk._on_tpu()

    def body(codes, ids, norms, sizes, qp, centers, centers_rot,
             rotation, codebooks, *fb):
        local = _pq.IvfPqIndex(
            centers=centers, centers_rot=centers_rot, rotation=rotation,
            codebooks=codebooks, packed_codes=codes[0],
            packed_ids=ids[0], packed_norms=norms[0],
            list_sizes=sizes[0], metric=index.metric,
            pq_bits=index.pq_bits, pq_dim_static=index.pq_dim)
        # probes on replicated operands: identical on every shard
        _, probes = _pq._coarse_probes(local, qp, n_probes, ip_like)
        q_rot = qp @ rotation.T
        lists, ind = _chunk_unions(
            probes.reshape(n_dev, mc, n_probes), NS)
        qv = q_rot.reshape(n_dev, mc, q_rot.shape[1])
        fbytes = None
        if fb:
            from raft_tpu.neighbors import sample_filter as _sf

            # the per-shard bitset slice: this shard's id table carries
            # GLOBAL ids (the shard offset baked in at build), so one
            # passes() gather over it composes the replicated global
            # bitset with the global→local remap — re-packed to the
            # per-list byte rows the ring kernel streams per code tile
            fbytes = _sf.list_filter_bytes(fb[0], ids[0])
        # the kernel's remote DMAs bypass lax — attribute the hop
        # traffic through the facade at trace time, the same [mc, k]
        # logical block per hop as the plain ring merge (the fusion
        # moves compute, not bytes)
        comms.count_ring_topk(
            n_dev - 1,
            jax.ShapeDtypeStruct((mc, k), jnp.float32),
            jax.ShapeDtypeStruct((mc, k), jnp.int32))
        kv, ki = _pk.ring_lut_scan_merge(
            lists, ind, qv, codes[0], ids[0], norms[0], centers_rot,
            codebooks, k, "ip" if ip_like else "l2",
            pq_bits=index.pq_bits, pq_dim=index.pq_dim, L=L,
            axis_name=axis, n_dev=n_dev, lut_dtype=lut_dtype,
            filter_bytes=fbytes, interpret=interpret)
        return kv[:, :k], ki[:, :k]

    in_specs = [P(axis, None, None, None), P(axis, None, None),
                P(axis, None, None), P(axis, None), P(),
                P(), P(), P(), P()]
    operands = [index.packed_codes, index.packed_ids, index.packed_norms,
                index.list_sizes, qp, index.centers, index.centers_rot,
                index.rotation, index.codebooks]
    if filter_bits is not None:
        in_specs.append(P())   # the global bitset rides replicated
        operands.append(filter_bits)
    out_spec = P(axis, None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(out_spec, out_spec),
        check_vma=False)
    rv, ri = fn(*operands)
    rv, ri = rv[:m], ri[:m]
    # LUT-key → metric epilogue (the _finish_candidates conventions)
    if ip_like:
        dists = jnp.where(ri < 0, -jnp.inf, -rv)
    else:
        q_sq = jnp.sum((q @ index.rotation.T) ** 2, axis=1)
        dists = jnp.maximum(rv + q_sq[:, None], 0.0)
        if mt == DistanceType.L2SqrtExpanded:
            dists = jnp.sqrt(dists)
        dists = jnp.where(ri < 0, jnp.inf, dists)
    return dists, ri


def _chunk_unions(pc: jax.Array, NS: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """Per ring chunk, the padded union of probed lists and the
    per-(list, query) membership indicator the fused kernel masks with.

    ``pc [n_dev, mc, n_probes]`` i32 → (``lists [n_dev, NS]`` i32, −1
    pad; ``ind [n_dev, NS, mc]`` f32 0/1). Sort + first-occurrence +
    one bounded scatter — ``NS = min(mc·n_probes, n_lists)`` bounds the
    distinct count by construction, so the scatter never drops a real
    list."""
    def one(p):
        flat = jnp.sort(p.reshape(-1).astype(jnp.int32))
        first = jnp.concatenate(
            [jnp.ones((1,), bool), flat[1:] != flat[:-1]])
        rank = jnp.cumsum(first) - 1
        lists = jnp.full((NS,), -1, jnp.int32)
        lists = lists.at[jnp.where(first, rank, NS)].set(flat,
                                                         mode="drop")
        ind = jnp.any(p[None, :, :] == lists[:, None, None], axis=2)
        ind = ind & (lists >= 0)[:, None]
        return lists, ind.astype(jnp.float32)

    return jax.vmap(one)(pc)


def build_ivf_pq(params: _pq.IndexParams, dataset: jax.Array, mesh: Mesh,
                 axis: str = "shard") -> ShardedIvfPq:
    """Distributed IVF-PQ build over a row-sharded dataset.

    reference: the raft-dask sharded-index pattern (each worker an
    ivf_pq::build over its shard) with the coarse quantizer trained
    globally (MNMG kmeans) instead of per-shard — sharper lists than the
    reference's per-worker quantizers at zero extra comms beyond psum.
    """
    mt = resolve_metric(params.metric)
    expects(params.codebook_kind == "per_subspace",
            "distributed build supports per_subspace codebooks")
    x = jnp.asarray(dataset, jnp.float32)
    n, dim = x.shape
    n_dev = mesh.shape[axis]
    spherical = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    if mt == DistanceType.CosineExpanded:
        x = x / jnp.sqrt(jnp.maximum(jnp.sum(x * x, -1, keepdims=True), 1e-12))

    pq_dim = params.pq_dim or _pq._default_pq_dim(dim)
    pq_len = -(-dim // pq_dim)
    rot_dim = pq_dim * pq_len
    K = 1 << params.pq_bits

    # 1. global coarse centers (ONE psum Lloyd over the sharded rows;
    #    dkm.fit zero-weights its own padding)
    centers = _coarse_centers(params.n_lists, params.kmeans_n_iters,
                              params.seed, x, mesh, axis, spherical)

    x, n_real = _pad_shard(x, n_dev)
    shard_n = x.shape[0] // n_dev

    # 2. rotation + codebooks on a replicated cross-shard subsample sized
    #    by kmeans_trainset_fraction (parity with the single-device build)
    key = jax.random.PRNGKey(params.seed)
    rotation = _pq.make_rotation_matrix(jax.random.fold_in(key, 1),
                                        rot_dim, dim)
    centers_rot = centers @ rotation.T
    t = min(shard_n,
            max(int(shard_n * params.kmeans_trainset_fraction),
                -(-4 * K // n_dev), 256))
    expects(t * n_dev >= K,
            "trainset too small for pq_bits=%d: %d < %d codebook entries",
            params.pq_bits, t * n_dev, K)
    trainset = _gather_trainset(x, mesh, axis, t, params.seed, n_real)
    _, tr_labels = fused_l2_nn_argmin(trainset, centers)
    tr_res = trainset @ rotation.T - centers_rot[tr_labels]
    sub = jnp.transpose(tr_res.reshape(-1, pq_dim, pq_len), (1, 0, 2))
    codebooks = _pq._vmapped_lloyd(sub, K, params.kmeans_n_iters,
                                   jax.random.fold_in(key, 2))

    # 3. per-shard encode + device-side pack (global ids baked in)
    avg = max(1, shard_n // params.n_lists)
    L = max(8, -(-int(avg * params.list_size_cap_factor) // 8) * 8)
    n_lists = params.n_lists
    comms = Comms(axis)

    def encode_pack(x_blk, centers, centers_rot, rotation, codebooks):
        xs = x_blk
        rank = comms.get_rank()
        # global ids in the policy dtype of the POD row count (core.ids):
        # rank·shard_n overflows int32 past 2³¹ total rows
        gid = _ids.global_ids(rank, shard_n, _ids.make_ids(shard_n),
                              n_total=n_dev * shard_n)
        _, labels = fused_l2_nn_argmin(xs, centers)
        labels = jnp.where(gid < n_real, labels, n_lists)  # drop pad rows
        safe = jnp.clip(labels, 0, n_lists - 1)
        x_rot = xs @ rotation.T
        codes = _pq._encode_rows(x_rot, centers_rot, safe, codebooks)
        decoded = _pq._decode_codes(codes, codebooks)
        recon = centers_rot[safe] + decoded
        norms = jnp.sum(recon * recon, axis=1)
        codes_p = _pq.pack_bits(codes, params.pq_bits)  # n-bit device pack
        (pcodes, pnorms), ids, sizes, dropped, _ = ic.pack_lists(
            (codes_p, norms), labels, gid, n_lists, L,
            (jnp.uint8(0), jnp.float32(0)))
        return pcodes[None], ids[None], pnorms[None], sizes[None], dropped[None]

    fn = shard_map(
        encode_pack, mesh=mesh,
        in_specs=(P(axis, None), P(), P(), P(), P()),
        out_specs=(P(axis, None, None, None), P(axis, None, None),
                   P(axis, None, None), P(axis, None), P(axis)),
        check_vma=False)
    pcodes, pids, pnorms, sizes, dropped = fn(x, centers, centers_rot,
                                              rotation, codebooks)
    _warn_dropped("ivf_pq", dropped)
    return ShardedIvfPq(
        centers=centers, centers_rot=centers_rot, rotation=rotation,
        codebooks=codebooks, packed_codes=pcodes, packed_ids=pids,
        packed_norms=pnorms, list_sizes=sizes, metric=mt.value,
        pq_bits=params.pq_bits, pq_dim=pq_dim, shard_rows=shard_n)


def search_ivf_pq(params: _pq.SearchParams, index: ShardedIvfPq,
                  queries: jax.Array, k: int, mesh: Mesh,
                  axis: Union[str, Sequence[str]] = "shard", dataset=None,
                  merge: str = "auto",
                  filter_bitset=None) -> Tuple[jax.Array, jax.Array]:
    """Sharded IVF-PQ search: per-shard list scan + cross-shard top-k
    merge (reference: per-worker search + knn_merge_parts.cuh). Queries
    are replicated; returns (distances [m, k], global ids [m, k]) —
    replicated under the allgather merge tier, query-sharded under the
    ring tier (``merge`` = auto | allgather | ring, see
    ``parallel.merge``).

    With ``params.refine="f32_regen"`` and ``dataset`` (the build
    dataset, row-sharded over the mesh) this is the end-to-end fused
    pipeline per shard: the oversampled scan rides whatever tier
    ``ivf_pq.search`` picks (incl. the Pallas LUT-scan kernel), the
    exact re-rank rides the gather-refine dispatch tier against the
    shard's own rows, and only each shard's k refined survivors enter
    the merge — BASELINE config 5's shape (sharded IVF-PQ, SIFT-1B on
    v5e-64) end to end.

    ``filter_bitset`` (packed uint32 words over GLOBAL row ids,
    replicated): every per-shard tier composes it with the shard's
    global-id tables — the fused ring kernel streams the per-shard
    byte slice beside the codes, the unfused scan and the refined
    pipeline's oversampled scan mask in their own tiers — so filtered
    pod-scale search stays on whatever fast path the unfiltered shape
    would ride."""
    mt = resolve_metric(index.metric)
    select_min = SELECT_MIN[mt]
    n_probes = min(params.n_probes, index.n_lists)
    q = jnp.asarray(queries, jnp.float32)
    # same entry contract as the single-chip search: validate queries
    # up front (not deep inside shard_map) and expose the PR-7 fault
    # point so chaos plans cover the sharded tier too
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "queries must be [m, %d]", index.dim)
    _faults.faultpoint("ivf_pq.search")
    m = q.shape[0]
    n_dev = index.n_shards
    ax_dev, whole_mesh, hier_axes = _merge.resolve_exchange(mesh, axis)
    expects(n_dev == ax_dev,
            "index sharded over %d devices, mesh axis has %d",
            n_dev, ax_dev)
    refined = params.refine != "none"
    filtered = filter_bitset is not None
    if params.lut_dtype == "auto" and not refined:
        # direct sharded calls resolve the fp8-default policy here (the
        # neighbors entry resolves before dispatching to this tier).
        # Refined searches stay "auto" so the per-shard oversampled
        # scan resolves against its ACTUAL selection width k_cand —
        # the slack the fp8 floor is defined over. A filter's
        # selectivity discounts the slack (surviving candidates only)
        params = dataclasses.replace(
            params, lut_dtype=_pq.resolve_lut_dtype(
                "auto", n_probes, k,
                selectivity=_pq._filter_selectivity(filter_bitset)))
    if not refined:
        from raft_tpu.obs import spans as _obs_spans

        fused, fused_reason = _ring_fused_wanted(
            index, m, k, n_probes, n_dev,
            whole_mesh=whole_mesh, merge=merge, mt=mt,
            lut_dtype=params.lut_dtype, scan_select=params.scan_select,
            filtered=filtered)
        if fused:
            # codes → merged top-k in one persistent kernel: the scan
            # IS the merge's compute phase, no per-shard candidate
            # table, no separate merge dispatch
            _obs_spans.count_dispatch("parallel.merge", "ring_fused_scan")
            _pq._count_scan_dispatch("ring_lut_fused", filtered=filtered)
            rv, ri = _search_fused_ring(index, q, k, n_probes, mesh,
                                        axis, params.lut_dtype, mt,
                                        filter_bits=filter_bitset)
            return rv, ri
        if fused_reason:
            _obs_spans.count_fallback("parallel.merge", fused_reason)
    tier, impl = _merge.merge_tier(
        n_dev, m, k, explicit=merge,
        whole_mesh=whole_mesh, hier_axes=hier_axes)
    comms = Comms(axis)
    if refined:
        from raft_tpu.neighbors import refine as _refine

        expects(dataset is not None,
                "refine=%r needs search(..., dataset=...): the sharded "
                "rows to re-rank against (the build dataset)",
                params.refine)
        xd = jnp.asarray(dataset, jnp.float32)
        expects(xd.ndim == 2 and xd.shape[1] == index.dim,
                "refine dataset shape %s does not match the index dim %d",
                tuple(xd.shape), index.dim)
        if mt == DistanceType.CosineExpanded:
            xd = xd / jnp.sqrt(
                jnp.maximum(jnp.sum(xd * xd, -1, keepdims=True), 1e-12))
        xd, _ = _pad_shard(xd, n_dev)
        shard_n = xd.shape[0] // n_dev
        # the gid → local-row remap below is only correct against the
        # BUILD dataset's shard geometry — a row-count mismatch would
        # refine against the wrong vectors silently (JAX clamps
        # out-of-range gathers)
        expects(index.shard_rows == 0 or shard_n == index.shard_rows,
                "refine dataset has %d rows/shard but the index was "
                "built with %d — pass the build dataset",
                shard_n, index.shard_rows)
        k_cand = max(k, int(round(k * params.refine_ratio)))
        scan_params = dataclasses.replace(params, refine="none")

    def local_search(codes, ids, norms, sizes, q,
                     centers, centers_rot, rotation, codebooks, *rest):
        rest = list(rest)
        ds = rest.pop(0) if refined else None
        fb = rest.pop(0) if filtered else None
        local = _pq.IvfPqIndex(
            centers=centers, centers_rot=centers_rot, rotation=rotation,
            codebooks=codebooks, packed_codes=codes[0], packed_ids=ids[0],
            packed_norms=norms[0], list_sizes=sizes[0], metric=index.metric,
            pq_bits=index.pq_bits, pq_dim_static=index.pq_dim)
        if refined:
            # per-shard fused pipeline: oversampled scan through the
            # full single-chip dispatch stack (LUT-scan tier included —
            # a filter rides it as the streamed per-candidate mask: the
            # shard's id tables are global, so the replicated bitset
            # composes directly), exact re-rank against this shard's
            # own rows (ids are global with the shard offset baked in
            # at build)
            _, i0 = _pq.search(local, q, k_cand, scan_params,
                               filter_bitset=fb)
            rank = comms.get_rank()
            # global↔local remap through the one id-dtype policy
            # (core.ids): the offset math overflows int32 past 2³¹ pod
            # rows, and the incoming id width is never narrowed. i0 is
            # already filter-clean — the refine re-rank needs no filter
            li = _ids.local_ids(i0, rank, shard_n)
            vals, lids = _refine.refine(ds, q, li, k,
                                        metric=index.metric)
            gids = _ids.global_ids(rank, shard_n, lids,
                                   n_total=n_dev * shard_n)
        else:
            vals, gids = _pq._search_impl(local, q, k, n_probes,
                                          params.query_tile,
                                          filter_bits=fb,
                                          lut_dtype=params.lut_dtype)
        return _merge.merge_topk(vals, gids, axis, m, k, n_dev,
                                 select_min, tier=tier, impl=impl)

    in_specs = [P(axis, None, None, None), P(axis, None, None),
                P(axis, None, None), P(axis, None), P(),
                P(), P(), P(), P()]
    operands = [index.packed_codes, index.packed_ids, index.packed_norms,
                index.list_sizes, q, index.centers, index.centers_rot,
                index.rotation, index.codebooks]
    if refined:
        in_specs.append(P(axis, None))
        operands.append(xd)
    if filtered:
        in_specs.append(P())   # global bitset, replicated
        operands.append(filter_bitset)
    out_spec = _merge.merge_out_spec(tier, axis)
    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(out_spec, out_spec),
        check_vma=False)
    rv, ri = fn(*operands)
    return rv[:m], ri[:m]


def build_ivf_flat(params: _flat.IndexParams, dataset: jax.Array, mesh: Mesh,
                   axis: str = "shard") -> ShardedIvfFlat:
    """Distributed IVF-Flat build: global coarse centers (psum Lloyd) +
    per-shard device-side raw-vector packing."""
    mt = resolve_metric(params.metric)
    x = jnp.asarray(dataset, jnp.float32)
    n, dim = x.shape
    n_dev = mesh.shape[axis]
    spherical = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    if mt == DistanceType.CosineExpanded:
        x = x / jnp.sqrt(jnp.maximum(jnp.sum(x * x, -1, keepdims=True), 1e-12))
    n_lists = params.n_lists

    centers = _coarse_centers(n_lists, params.kmeans_n_iters,
                              params.seed, x, mesh, axis, spherical)

    x, n_real = _pad_shard(x, n_dev)
    shard_n = x.shape[0] // n_dev

    avg = max(1, shard_n // n_lists)
    L = max(8, -(-int(avg * params.list_size_cap_factor) // 8) * 8)
    comms = Comms(axis)

    def assign_pack(x_blk, centers):
        rank = comms.get_rank()
        gid = _ids.global_ids(rank, shard_n, _ids.make_ids(shard_n),
                              n_total=n_dev * shard_n)
        _, labels = fused_l2_nn_argmin(x_blk, centers)
        labels = jnp.where(gid < n_real, labels, n_lists)
        norms = jnp.sum(x_blk * x_blk, axis=1)
        (pdata, pnorms), ids, sizes, dropped, _ = ic.pack_lists(
            (x_blk, norms), labels, gid, n_lists, L,
            (jnp.float32(0), jnp.float32(0)))
        return pdata[None], ids[None], pnorms[None], sizes[None], dropped[None]

    fn = shard_map(
        assign_pack, mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=(P(axis, None, None, None), P(axis, None, None),
                   P(axis, None, None), P(axis, None), P(axis)),
        check_vma=False)
    pdata, pids, pnorms, sizes, dropped = fn(x, centers)
    _warn_dropped("ivf_flat", dropped)
    return ShardedIvfFlat(centers=centers, packed_data=pdata,
                          packed_ids=pids, packed_norms=pnorms,
                          list_sizes=sizes, metric=mt.value)


def search_ivf_flat(params: _flat.SearchParams, index: ShardedIvfFlat,
                    queries: jax.Array, k: int, mesh: Mesh,
                    axis: Union[str, Sequence[str]] = "shard",
                    merge: str = "auto",
                    filter_bitset=None) -> Tuple[jax.Array, jax.Array]:
    """Sharded IVF-Flat search: per-shard scan + cross-shard merge
    through the shared tier (``merge`` = auto | allgather | ring).

    ``filter_bitset`` (packed words over GLOBAL row ids, replicated)
    masks each shard's scan through the same per-shard global-id
    composition as the PQ tier."""
    mt = resolve_metric(index.metric)
    select_min = SELECT_MIN[mt]
    n_probes = min(params.n_probes, index.n_lists)
    q = jnp.asarray(queries, jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "queries must be [m, %d]", index.dim)
    _faults.faultpoint("ivf_flat.search")
    m = q.shape[0]
    n_dev = index.packed_data.shape[0]
    ax_dev, whole_mesh, hier_axes = _merge.resolve_exchange(mesh, axis)
    expects(n_dev == ax_dev,
            "index sharded over %d devices, mesh axis has %d",
            n_dev, ax_dev)
    tier, impl = _merge.merge_tier(
        n_dev, m, k, explicit=merge,
        whole_mesh=whole_mesh, hier_axes=hier_axes)

    def local_search(data, ids, norms, sizes, q, centers, *fb):
        local = _flat.IvfFlatIndex(
            centers=centers, packed_data=data[0], packed_ids=ids[0],
            packed_norms=norms[0], list_sizes=sizes[0], metric=index.metric)
        vals, gids = _flat._search_impl(local, q, k, n_probes,
                                        params.query_tile,
                                        filter_bits=fb[0] if fb else None)
        return _merge.merge_topk(vals, gids, axis, m, k, n_dev,
                                 select_min, tier=tier, impl=impl)

    in_specs = [P(axis, None, None, None), P(axis, None, None),
                P(axis, None, None), P(axis, None), P(), P()]
    operands = [index.packed_data, index.packed_ids, index.packed_norms,
                index.list_sizes, q, index.centers]
    if filter_bitset is not None:
        in_specs.append(P())   # global bitset, replicated
        operands.append(filter_bitset)
    out_spec = _merge.merge_out_spec(tier, axis)
    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(out_spec, out_spec),
        check_vma=False)
    rv, ri = fn(*operands)
    return rv[:m], ri[:m]
