"""Communicator facade — comms_t-shaped API over ``jax.lax`` collectives.

TPU-native re-design of the reference's comms stack (SURVEY.md §5.8):

- abstract ``comms_iface``/``comms_t`` (core/comms.hpp:123,242) → :class:`Comms`,
  a thin named-axis wrapper whose methods are the same verbs (allreduce /
  bcast / reduce / allgather / reducescatter / alltoall / send-recv /
  comm_split) lowered to ``lax.psum`` / ``lax.all_gather`` / ``ppermute`` /
  etc. **Methods must be called inside** ``shard_map`` (or jitted code with
  the axis bound) — XLA then schedules them on ICI/DCN;
- NCCL/UCX backends (comms/std_comms.hpp) → none needed: the XLA runtime is
  the backend;
- bootstrap (raft-dask Comms.init, NCCL uid exchange) →
  :func:`initialize_distributed` wrapping ``jax.distributed.initialize``;
- sub-communicators (core/resource/sub_comms.hpp, comm_split) → operating
  over a subset of mesh axis names;
- stream-sync failure propagation (comms_t::sync_stream, core/comms.hpp:290)
  → XLA surfaces collective failures as program errors; :meth:`sync_stream`
  exists for API parity.

Reduction ops mirror ``op_t`` (core/comms.hpp:36): SUM, PROD, MIN, MAX.

**Comms telemetry** (docs/observability.md): when observability is on
(:func:`raft_tpu.obs.enable`), every collective counts one op and its
per-rank bytes into ``comms.ops{op=...,axis=...}`` /
``comms.bytes{op=...,axis=...}``, labeled by collective verb and axis
name — a 2-axis DCN×ICI mesh attributes traffic per axis. The byte
model charges what each rank actually moves over the interconnect:
fixed-size-result collectives (allreduce, reducescatter, alltoall,
ppermute, send_recv_ring) count their per-rank payload; gather-family
collectives (allgather, gather, bcast, allgatherv, gatherv) count
``axis_size × payload`` — the materialized gathered table every rank
assembles over ICI, the O(n_dev·m·k) cost the ring top-k exchange
exists to avoid; the ring exchange itself (``ring_topk``) counts one op
and one surviving-block payload PER HOP (n_dev−1 hops per merge),
whether the hops ride :meth:`Comms.ring_topk_hop` (ppermute fallback)
or the Pallas kernel's in-kernel remote DMAs (attributed via
:meth:`Comms.count_ring_topk` — no collective escapes telemetry,
GL10). Counting reads only STATIC shape/dtype at trace time (once per
jit trace, the same per-dispatch-decision semantics as
``obs.count_dispatch``): zero host syncs, zero runtime cost in the
compiled program, and a single flag check when observability is off.
Each collective also lowers under a ``raft_tpu.comms.<verb>`` named
scope (``core.tracing.annotate``) so profiler op timelines attribute
ICI/DCN time to the verb.
"""

from __future__ import annotations

import enum
import math
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.compat import axis_size as _axis_size
from raft_tpu.core.tracing import annotate as _annotate
from raft_tpu.obs import fleet as _fleet
from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _obs
from raft_tpu.robust import faults as _faults


class Op(enum.Enum):
    """Reduction op (reference: core/comms.hpp:36 ``op_t``)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


class Status(enum.Enum):
    """Collective status (reference: core/comms.hpp:39 ``status_t``)."""

    SUCCESS = 0
    ERROR = 1
    ABORT = 2


_REDUCERS = {
    Op.SUM: lax.psum,
    Op.MAX: lax.pmax,
    Op.MIN: lax.pmin,
}


def _axis_label(axis_name: Union[str, Sequence[str]]) -> str:
    """Canonical label for one axis name or a multi-axis tuple
    (``("dcn", "ici")`` → ``"dcn+ici"``)."""
    if isinstance(axis_name, str):
        return axis_name
    return "+".join(str(a) for a in axis_name)


# Collectives whose RESULT (and interconnect traffic) grows with the
# axis: each rank materializes the size×payload gathered table, so the
# byte model scales their payload by the static axis size.
_GATHER_FAMILY = frozenset(
    {"allgather", "gather", "bcast", "allgatherv", "gatherv"})


def _payload_bytes(*arrays) -> int:
    """Per-rank payload bytes from STATIC shape/dtype — works on
    tracers (shapes are always concrete under shard_map), never touches
    values, so counting introduces no host syncs (GL01-clean)."""
    total = 0
    for a in arrays:
        shape = getattr(a, "shape", None)
        if shape is None:  # python scalar payload
            total += 8
            continue
        dtype = getattr(a, "dtype", None)
        try:
            itemsize = np.dtype(dtype).itemsize
        except TypeError:
            itemsize = 4
        total += int(math.prod(shape)) * itemsize
    return total


class Comms:
    """Named-axis communicator (reference: ``comms_t``, core/comms.hpp:242).

    Bound to one or more mesh axis names; all methods are collective and must
    run inside the matching ``shard_map``/``pjit`` scope.
    """

    def __init__(self, axis_name: Union[str, Sequence[str]]):
        self.axis_name = axis_name

    # -- topology ----------------------------------------------------------
    def get_size(self) -> jax.Array:
        return _axis_size(self.axis_name)

    def get_rank(self) -> jax.Array:
        return lax.axis_index(self.axis_name)

    def comm_split(self, axis_name: Union[str, Sequence[str]]) -> "Comms":
        """Sub-communicator over a subset of mesh axes (reference:
        comms_t::comm_split, std_comms.hpp:145 — here: zero-cost renaming)."""
        return Comms(axis_name)

    # -- telemetry ---------------------------------------------------------
    def _count(self, op_name: str, *arrays) -> None:
        """Count one collective + its per-rank payload bytes into
        ``comms.ops`` / ``comms.bytes`` labeled ``{op=...,axis=...}``.
        Runs at trace time from static shape/dtype only — once per jit
        trace (the obs.count_dispatch semantics), zero host syncs, one
        flag check when observability is off. The sanitize lane's
        collective-schedule recorder taps the same per-trace event.

        Every collective is also a named fault point
        (``comms.<verb>``, robust.faults): a fault plan can fail a
        collective *at trace time* — aborting the trace exactly where a
        wedged ICI link would abort the program — so distributed
        failure handling is CI-testable without breaking hardware.

        Multi-axis communicators attribute PER AXIS (ISSUE 19): a
        collective over ``("dcn", "ici")`` lowers to one stage per
        axis (inner reduce/gather, then outer over the inner result),
        so it counts one op on each constituent axis — ``axis=ici`` and
        ``axis=dcn`` series, never a lumped ``dcn+ici`` label. Fixed-
        size verbs charge each stage the per-rank payload; gather-
        family verbs charge each stage its materialized table (the
        inner stage gathers size(inner)×payload, the outer stage
        size(outer)× that) — the hierarchical-schedule byte model that
        lets the scoreboard separate cheap-ICI from scarce-DCN traffic.
        The sanitize-lane schedule recorder keeps the joined label (one
        collective, one schedule slot)."""
        _faults.faultpoint(f"comms.{op_name}")
        recording = _sanitize.comms_schedule_recording()
        counting = _obs.enabled()
        if not (recording or counting):
            return
        payload = _payload_bytes(*arrays)
        nbytes = payload
        if op_name in _GATHER_FAMILY:
            # the materialized gathered table (axis size is static at
            # trace time — same int() the ring perms rely on)
            nbytes *= int(_axis_size(self.axis_name))
        if recording:
            _sanitize.note_collective(op_name,
                                      _axis_label(self.axis_name), nbytes)
        if not counting:
            return
        # host identity (ISSUE 15): in a launcher-ranked pod process
        # (RAFT_TPU_RANK set) every comms series carries the host's
        # rank, so per-host flight/JSONL dumps merged by obs.fleet
        # attribute collective traffic to the process that issued it.
        # One extra label per process (its own rank) — cardinality 1.
        rank = _fleet.rank()
        reg = _obs.registry()
        if isinstance(self.axis_name, str):
            per_axis = [(self.axis_name, nbytes)]
        else:
            per_axis = []
            mult = 1
            # innermost stage first: its gathered table is what the
            # next (outer) stage's gather moves
            for a in reversed(tuple(self.axis_name)):
                if op_name in _GATHER_FAMILY:
                    mult *= int(_axis_size(a))
                    per_axis.append((a, payload * mult))
                else:
                    per_axis.append((a, payload))
        # cost attribution (ISSUE 20): when this trace runs on behalf
        # of a served tenant (the dispatch path brackets searches with
        # neighbors.tiered.serving_tenant), charge the bytes to the
        # tenant per axis. Same trace-time semantics as the series
        # above — static shapes only, zero host syncs (GL01-clean).
        # sys.modules lookup, not an import: build-path traces with no
        # serving layer loaded pay a dict probe, nothing else.
        import sys

        tiered = sys.modules.get("raft_tpu.neighbors.tiered")
        tenant = tiered.current_tenant() if tiered is not None else "-"
        for axis, stage_bytes in per_axis:
            labels = {"op": op_name, "axis": axis}
            if rank is not None:
                labels["rank"] = str(rank)
            reg.inc("comms.ops", 1.0, labels=labels)
            reg.inc("comms.bytes", float(stage_bytes), labels=labels)
            if tenant != "-":
                reg.inc("cost.comms_bytes", float(stage_bytes),
                        labels={"tenant": tenant, "axis": axis})

    # -- collectives -------------------------------------------------------
    def _allreduce_raw(self, x, op: Op):
        if op == Op.PROD:
            return jnp.exp(lax.psum(jnp.log(x), self.axis_name))  # rarely used
        return _REDUCERS[op](x, self.axis_name)

    def allreduce(self, x, op: Op = Op.SUM):
        """reference: comms_t::allreduce (core/comms.hpp:344)."""
        self._count("allreduce", x)
        with _annotate("raft_tpu.comms.allreduce"):
            return self._allreduce_raw(x, op)

    def reduce(self, x, root: int = 0, op: Op = Op.SUM):
        """reference: comms_t::reduce — XLA has no rooted reduce; allreduce
        and mask off non-roots (same wire cost on ICI)."""
        self._count("reduce", x)
        with _annotate("raft_tpu.comms.reduce"):
            full = self._allreduce_raw(x, op)
            rank = self.get_rank()
            return jnp.where(rank == root, full, jnp.zeros_like(full))

    def bcast(self, x, root: int = 0):
        """reference: comms_t::bcast — select the root's shard and replicate."""
        self._count("bcast", x)
        with _annotate("raft_tpu.comms.bcast"):
            gathered = lax.all_gather(x, self.axis_name, axis=0)
            return gathered[root]

    def allgather(self, x, axis: int = 0, tiled: bool = False):
        """reference: comms_t::allgather."""
        self._count("allgather", x)
        with _annotate("raft_tpu.comms.allgather"):
            return lax.all_gather(x, self.axis_name, axis=axis, tiled=tiled)

    def gather(self, x, root: int = 0, axis: int = 0):
        """reference: comms_t::gather — SPMD programs have no cheaper rooted
        gather; all ranks hold the result and root semantics are a no-op."""
        self._count("gather", x)
        with _annotate("raft_tpu.comms.gather"):
            return lax.all_gather(x, self.axis_name, axis=axis)

    def _allgatherv_impl(self, x, count, compact: bool):
        counts = lax.all_gather(count, self.axis_name)           # [size]
        g = lax.all_gather(x, self.axis_name, axis=0, tiled=True)
        if not compact:
            return g, counts
        cap = x.shape[0]
        total = g.shape[0]
        local = jnp.arange(total, dtype=jnp.int32) % cap
        rank_of = jnp.arange(total, dtype=jnp.int32) // cap
        invalid = local >= counts[rank_of]
        order = jnp.argsort(invalid, stable=True)  # valid first, rank order
        return jnp.take(g, order, axis=0), counts

    def allgatherv(self, x, count, compact: bool = True):
        """Variable-length allgather (reference: comms_t::allgatherv,
        core/comms.hpp:423-444). Ragged shard sizes are what real
        sharded datasets produce; XLA collectives are statically shaped,
        so each rank contributes a PADDED shard ``x [cap, ...]`` plus
        its valid row ``count``. Returns ``(gathered [size·cap, ...],
        counts [size])`` with every rank's valid rows stable-packed to
        the front in rank order — ``jnp.sum(counts)`` rows are valid,
        the tail is pad. ``compact=False`` skips the packing sort and
        returns the raw padded concatenation (cheaper when the caller
        masks instead of slicing)."""
        self._count("allgatherv", x, count)
        with _annotate("raft_tpu.comms.allgatherv"):
            return self._allgatherv_impl(x, count, compact)

    def gatherv(self, x, count, root: int = 0, compact: bool = True):
        """Variable-length gather (reference: comms_t::gatherv,
        core/comms.hpp:449-470) — rooted semantics are a no-op in SPMD
        (see :meth:`gather`); identical wire cost to allgatherv."""
        self._count("gatherv", x, count)
        with _annotate("raft_tpu.comms.gatherv"):
            return self._allgatherv_impl(x, count, compact)

    def reducescatter(self, x, op: Op = Op.SUM, scatter_dimension: int = 0):
        """reference: comms_t::reducescatter."""
        self._count("reducescatter", x)
        with _annotate("raft_tpu.comms.reducescatter"):
            return lax.psum_scatter(x, self.axis_name,
                                    scatter_dimension=scatter_dimension,
                                    tiled=True)

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        """reference: std_comms nccl alltoall (device_multicast analog)."""
        self._count("alltoall", x)
        with _annotate("raft_tpu.comms.alltoall"):
            return lax.all_to_all(x, self.axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    def ppermute(self, x, perm):
        """Point-to-point ring/permute transfer — the structured replacement
        for comms_t::device_send/device_recv pairs (core/comms.hpp:505,531):
        SPMD programs express p2p as a permutation collective."""
        self._count("ppermute", x)
        with _annotate("raft_tpu.comms.ppermute"):
            return lax.ppermute(x, self.axis_name, perm=perm)

    def send_recv_ring(self, x, shift: int = 1):
        """Ring shift by ``shift`` (send to rank+shift, recv from rank-shift).
        Axis sizes are static at trace time, so the permutation is concrete."""
        self._count("send_recv_ring", x)
        with _annotate("raft_tpu.comms.send_recv_ring"):
            size = int(_axis_size(self.axis_name))
            perm = [(i, (i + shift) % size) for i in range(size)]
            return lax.ppermute(x, self.axis_name, perm=perm)

    def ring_topk_hop(self, vals, ids, shift: int = 1):
        """One hop of the ring top-k exchange: the surviving
        ``(vals, ids)`` block moves to rank+``shift`` (recv from
        rank−``shift``). The CPU-mesh / sub-axis fallback of the Pallas
        ``ring_topk_merge`` kernel (``ops/pallas_kernels``) — identical
        schedule, counted identically: one ``comms.ops{op=ring_topk}``
        and one surviving-block ``comms.bytes`` per hop."""
        self._count("ring_topk", vals, ids)
        with _annotate("raft_tpu.comms.ring_topk"):
            size = int(_axis_size(self.axis_name))
            perm = [(i, (i + shift) % size) for i in range(size)]
            return (lax.ppermute(vals, self.axis_name, perm=perm),
                    lax.ppermute(ids, self.axis_name, perm=perm))

    def count_ring_topk(self, n_hops: int, *arrays) -> None:
        """Attribute the Pallas ring kernel's in-kernel exchange to the
        comms telemetry: ``n_hops`` ops and ``n_hops`` surviving-block
        payloads under ``op=ring_topk``, at trace time. The kernel's
        remote DMAs never pass through ``lax``, so without this call
        they would escape ``comms.ops``/``comms.bytes`` — the GL10
        "no collective escapes telemetry" invariant. ``arrays`` carry
        only static shape/dtype (``jax.ShapeDtypeStruct`` works)."""
        for _ in range(int(n_hops)):
            self._count("ring_topk", *arrays)

    def sync_stream(self) -> Status:
        """reference: comms_t::sync_stream (core/comms.hpp:283-290) — XLA
        surfaces collective failure by failing the program; parity no-op."""
        return Status.SUCCESS


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (reference: raft-dask ``Comms.init``,
    comms.py:172 — NCCL uid exchange over Dask RPC). On TPU this is one
    call into JAX's distributed runtime; no uid plumbing exists."""
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
