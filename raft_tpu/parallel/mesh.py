"""Device mesh construction — ICI/DCN-aware mesh helpers.

Replaces the reference's communicator-clique construction (raft-dask worker
enumeration + NCCL clique): on TPU the topology object is a
``jax.sharding.Mesh``; intra-slice axes ride ICI, the inter-slice axis
rides DCN (``create_hybrid_device_mesh``). Algorithms take a mesh + axis
names instead of a comms handle.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Axis names the stack treats as the slow (cross-pod / data-center
# network) interconnect. Everything topology-aware keys off the NAME:
# the hier merge tier auto-enables when a 2-D mesh's outer axis is
# DCN-labeled, obsdump picks the per-axis bandwidth peak by it, and
# hier_mesh refuses outer axes that are not. Canonical 2-D naming is
# HIER_AXIS_NAMES = (outer, inner) = ("dcn", "ici").
DCN_AXIS_PREFIXES = ("dcn", "pod", "slice")
HIER_AXIS_NAMES = ("dcn", "ici")


def is_dcn_axis(name: object) -> bool:
    """True when ``name`` labels a slow (cross-pod) mesh axis — the
    naming convention the hier merge's auto-dispatch and the per-axis
    roofline peaks key off (:data:`DCN_AXIS_PREFIXES`)."""
    return isinstance(name, str) and name.lower().startswith(DCN_AXIS_PREFIXES)


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("shard",),
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    Default: one flat "shard" axis over all devices — the data/index
    sharding axis used by distributed kmeans and sharded ANN search (the
    TPU analog of the reference's one-GPU-per-Dask-worker clique).
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    arr = np.asarray(devices).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def make_hybrid_mesh(ici_shape: Sequence[int], dcn_shape: Sequence[int],
                     axis_names: Sequence[str]) -> Mesh:
    """Multi-slice mesh: leading axes over DCN, trailing over ICI
    (wraps ``jax.experimental.mesh_utils.create_hybrid_device_mesh``)."""
    from jax.experimental import mesh_utils

    devices = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_shape), tuple(dcn_shape))
    return Mesh(devices, tuple(axis_names))


def hier_mesh(ici_size: int, dcn_size: int,
              axis_names: Sequence[str] = HIER_AXIS_NAMES,
              devices=None) -> Mesh:
    """A 2-D ``(dcn_size, ici_size)`` mesh with the slow axis outermost
    — the topology object of the hierarchical merge tier (pods of
    ``ici_size`` devices joined over DCN).

    Validation is by NAME, because everything downstream dispatches by
    name: the outer axis must be DCN-labeled (:func:`is_dcn_axis`) and
    the inner must not be — a mesh whose outer axis is the fast one
    would silently route the bulky per-pod exchange over the slow
    interconnect. On a real multislice platform build the device grid
    with :func:`make_hybrid_mesh` and pass it via ``devices``; on one
    slice (or the CPU CI mesh) the plain reshape below is the same
    topology simulation the scaling legs use."""
    outer, inner = _hier_axis_pair(axis_names)
    if ici_size < 1 or dcn_size < 1:
        raise ValueError(f"hier_mesh sizes must be >= 1, got "
                         f"ici_size={ici_size} dcn_size={dcn_size}")
    if devices is None:
        devices = jax.devices()
    flat = list(np.asarray(devices).reshape(-1))
    need = ici_size * dcn_size
    if need > len(flat):
        raise ValueError(f"hier_mesh needs {need} devices "
                         f"({dcn_size}x{ici_size}), have {len(flat)}")
    return make_mesh(shape=(dcn_size, ici_size),
                     axis_names=(outer, inner), devices=flat[:need])


def _hier_axis_pair(axis_names: Sequence[str]) -> Sequence[str]:
    """Validate a 2-D (outer, inner) axis naming: outer slow, inner
    fast. Shared by :func:`hier_mesh` and the named-axis ``submesh``."""
    names = tuple(axis_names)
    if len(names) != 2:
        raise ValueError(f"expected (outer, inner) axis names, "
                         f"got {names!r}")
    outer, inner = names
    if not is_dcn_axis(outer):
        raise ValueError(
            f"outer axis {outer!r} is not DCN-labeled (prefixes "
            f"{DCN_AXIS_PREFIXES}): the slow axis must be outermost, or "
            "the hier tier would ship the per-pod exchange cross-pod")
    if is_dcn_axis(inner):
        raise ValueError(
            f"inner axis {inner!r} is DCN-labeled: the intra-pod (fast) "
            "axis must be innermost")
    return names


def submesh(mesh: Mesh, n_dev: int, axis_names: Sequence[str] = ("shard",),
            shape: Optional[Sequence[int]] = None) -> Mesh:
    """A mesh over the first ``n_dev`` devices of ``mesh`` — the
    scaling-study helper (weak/strong legs at n_dev ∈ {2, 4, 8} reuse
    one device pool instead of re-enumerating the platform).

    Default is the 1-D carve. With ``shape`` (and matching
    ``axis_names``) it carves a named multi-axis submesh — the 2-level
    scaling legs' ``submesh(full, 8, ("dcn", "ici"), shape=(2, 4))``;
    2-D carves get the same outer-slow naming validation as
    :func:`hier_mesh`."""
    if shape is None:
        if len(axis_names) != 1:
            raise ValueError(
                f"submesh with {len(axis_names)} axis names needs an "
                "explicit shape (the 1-D default cannot be inferred)")
        shape = (n_dev,)
    else:
        shape = tuple(shape)
        if len(shape) != len(axis_names):
            raise ValueError(f"shape {shape} does not match axis names "
                             f"{tuple(axis_names)}")
        if math.prod(shape) != n_dev:
            raise ValueError(f"shape {shape} covers {math.prod(shape)} "
                             f"devices, asked for {n_dev}")
        if len(shape) == 2:
            _hier_axis_pair(axis_names)
    flat = list(np.asarray(mesh.devices).reshape(-1))
    if n_dev > len(flat):
        raise ValueError(f"submesh of {n_dev} devices from a "
                         f"{len(flat)}-device mesh")
    return make_mesh(shape=shape, axis_names=axis_names,
                     devices=flat[:n_dev])


def shard_rows(x: jax.Array, mesh: Mesh, axis: str = "shard") -> jax.Array:
    """Place a [n, …] array row-sharded over ``axis`` (replicated on the
    rest). Pads implicitly via XLA if n is not divisible."""
    spec = P(axis, *([None] * (x.ndim - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Fully replicate an array over the mesh."""
    return jax.device_put(x, NamedSharding(mesh, P()))
