"""Device mesh construction — ICI/DCN-aware mesh helpers.

Replaces the reference's communicator-clique construction (raft-dask worker
enumeration + NCCL clique): on TPU the topology object is a
``jax.sharding.Mesh``; intra-slice axes ride ICI, the inter-slice axis
rides DCN (``create_hybrid_device_mesh``). Algorithms take a mesh + axis
names instead of a comms handle.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = ("shard",),
              devices=None) -> Mesh:
    """Build a mesh over the available devices.

    Default: one flat "shard" axis over all devices — the data/index
    sharding axis used by distributed kmeans and sharded ANN search (the
    TPU analog of the reference's one-GPU-per-Dask-worker clique).
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    arr = np.asarray(devices).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def make_hybrid_mesh(ici_shape: Sequence[int], dcn_shape: Sequence[int],
                     axis_names: Sequence[str]) -> Mesh:
    """Multi-slice mesh: leading axes over DCN, trailing over ICI
    (wraps ``jax.experimental.mesh_utils.create_hybrid_device_mesh``)."""
    from jax.experimental import mesh_utils

    devices = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_shape), tuple(dcn_shape))
    return Mesh(devices, tuple(axis_names))


def submesh(mesh: Mesh, n_dev: int, axis_names: Sequence[str] = ("shard",)
            ) -> Mesh:
    """A 1-D mesh over the first ``n_dev`` devices of ``mesh`` — the
    scaling-study helper (weak/strong legs at n_dev ∈ {2, 4, 8} reuse
    one device pool instead of re-enumerating the platform)."""
    flat = list(np.asarray(mesh.devices).reshape(-1))
    if n_dev > len(flat):
        raise ValueError(f"submesh of {n_dev} devices from a "
                         f"{len(flat)}-device mesh")
    return make_mesh(axis_names=axis_names, devices=flat[:n_dev])


def shard_rows(x: jax.Array, mesh: Mesh, axis: str = "shard") -> jax.Array:
    """Place a [n, …] array row-sharded over ``axis`` (replicated on the
    rest). Pads implicitly via XLA if n is not divisible."""
    spec = P(axis, *([None] * (x.ndim - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def replicate(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Fully replicate an array over the mesh."""
    return jax.device_put(x, NamedSharding(mesh, P()))
