"""Distributed ANN search patterns — sharded & replicated index search.

The multi-GPU patterns the reference enables downstream (SURVEY.md §2.15):
*sharded-index* search = per-shard top-k + cross-shard merge via
``knn_merge_parts``, and *replicated-index* search = data-parallel query
fan-out. Here both are single SPMD programs: ``shard_map`` over a mesh axis
with ``lax`` collectives doing the merge on ICI — no NCCL, no Dask.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from raft_tpu.core.compat import shard_map

from raft_tpu.core.errors import expects
from raft_tpu.core import ids as _ids
from raft_tpu.distance import DistanceType, SELECT_MIN, resolve_metric
from raft_tpu.neighbors import brute_force
from raft_tpu.parallel import merge as _merge
from raft_tpu.parallel.comms import Comms


def _pad_rows(x: jax.Array, multiple: int) -> Tuple[jax.Array, int]:
    n = x.shape[0]
    padded = -(-n // multiple) * multiple
    if padded == n:
        return x, n
    return jnp.pad(x, ((0, padded - n), (0, 0))), n


def sharded_knn(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    mesh: Mesh,
    axis: Union[str, Sequence[str]] = "shard",
    metric="sqeuclidean",
    merge: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN over an index sharded across a mesh axis.

    Each device scans its local shard (tiled brute force on the MXU),
    takes a local top-k, and the per-shard candidates merge through the
    shared cross-shard merge tier (``parallel.merge``) — allgather-and-
    select or the ring reduce-scatter-of-top-k exchange, picked by
    ``merge`` ("auto" defers to ``RAFT_TPU_RING_TOPK``) — the
    reference's sharded-index pattern (per-shard select +
    ``knn_merge_parts``, knn_brute_force.cuh:276) as one SPMD program.

    ``axis`` may be a 2-tuple ``(outer, inner)`` over a 2-D hier mesh
    (:func:`raft_tpu.parallel.mesh.hier_mesh`): the index shards over
    both axes jointly (outer-major) and, when the outer axis is
    DCN-labeled, the merge auto-escalates to the two-level ``hier``
    tier (per-pod ring over ICI, one sparse survivor exchange over
    DCN).

    Returns (distances [m, k], global indices [m, k]) — replicated
    under the allgather tier, query-sharded under the ring/hier tiers.
    """
    mt = resolve_metric(metric)
    select_min = SELECT_MIN[mt]
    n_dev, whole_mesh, hier_axes = _merge.resolve_exchange(mesh, axis)
    n = dataset.shape[0]
    m = queries.shape[0]
    padded, _ = _pad_rows(dataset, n_dev)
    shard_size = padded.shape[0] // n_dev
    expects(k <= shard_size, "k=%d exceeds shard size %d", k, shard_size)
    pad_val = jnp.inf if select_min else -jnp.inf
    comms = Comms(axis)  # counted collectives (comms.ops/comms.bytes)
    tier, impl = _merge.merge_tier(
        n_dev, m, k, explicit=merge,
        whole_mesh=whole_mesh, hier_axes=hier_axes)

    def local_search(ds_shard, q):
        rank = comms.get_rank()
        idx = brute_force.build(ds_shard, metric=mt)
        vals, ids = brute_force.knn(idx, q, k)
        # global-id remap in the policy dtype of the PADDED total row
        # count — rank·shard_size overflows int32 past 2³¹ pod rows
        # even though every per-shard id fits it, and pad-row gids
        # reach n_dev·shard_size − 1 > n, so the width must cover the
        # padding or the `gids < n` mask below sees wrapped negatives
        gids = _ids.global_ids(rank, shard_size, ids,
                               n_total=n_dev * shard_size)
        vals = jnp.where(gids < n, vals, pad_val)  # mask padded rows
        gids = jnp.where(gids < n, gids, -1)
        return _merge.merge_topk(vals, gids, axis, m, k, n_dev,
                                 select_min, tier=tier, impl=impl)

    out_spec = _merge.merge_out_spec(tier, axis)
    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=(out_spec, out_spec),
        check_vma=False,
    )
    rv, ri = fn(padded, queries)
    return rv[:m], ri[:m]


def replicated_knn(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    mesh: Mesh,
    axis: str = "shard",
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN with a replicated index and queries sharded over the mesh —
    the reference's replicated-index throughput pattern (each worker holds
    the full index, queries split). Returns sharded (dists, indices)."""
    mt = resolve_metric(metric)
    n_dev = mesh.shape[axis]
    q_padded, m = _pad_rows(queries, n_dev)

    def local_search(q_shard, ds):
        idx = brute_force.build(ds, metric=mt)
        return brute_force.knn(idx, q_shard, k)

    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=(P(axis, None), P(axis, None)),
        check_vma=False,
    )
    vals, ids = fn(q_padded, dataset)
    return vals[:m], ids[:m]
