"""Distributed ANN search patterns — sharded & replicated index search.

The multi-GPU patterns the reference enables downstream (SURVEY.md §2.15):
*sharded-index* search = per-shard top-k + cross-shard merge via
``knn_merge_parts``, and *replicated-index* search = data-parallel query
fan-out. Here both are single SPMD programs: ``shard_map`` over a mesh axis
with ``lax`` collectives doing the merge on ICI — no NCCL, no Dask.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from raft_tpu.core.compat import shard_map

from raft_tpu.core.errors import expects
from raft_tpu.distance import DistanceType, SELECT_MIN, resolve_metric
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.neighbors import brute_force
from raft_tpu.parallel.comms import Comms


def _pad_rows(x: jax.Array, multiple: int) -> Tuple[jax.Array, int]:
    n = x.shape[0]
    padded = -(-n // multiple) * multiple
    if padded == n:
        return x, n
    return jnp.pad(x, ((0, padded - n), (0, 0))), n


def sharded_knn(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    mesh: Mesh,
    axis: str = "shard",
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN over an index sharded across a mesh axis.

    Each device scans its local shard (tiled brute force on the MXU), takes
    a local top-k, all-gathers the [n_dev, m, k] candidates over ICI, and
    merges with a final select_k — the reference's sharded-index pattern
    (per-shard select + ``knn_merge_parts``, knn_brute_force.cuh:276)
    as one SPMD program.

    Returns replicated (distances [m, k], global indices [m, k]).
    """
    mt = resolve_metric(metric)
    select_min = SELECT_MIN[mt]
    n_dev = mesh.shape[axis]
    n = dataset.shape[0]
    padded, _ = _pad_rows(dataset, n_dev)
    shard_size = padded.shape[0] // n_dev
    expects(k <= shard_size, "k=%d exceeds shard size %d", k, shard_size)
    pad_val = jnp.inf if select_min else -jnp.inf
    comms = Comms(axis)  # counted collectives (comms.ops/comms.bytes)

    def local_search(ds_shard, q):
        rank = comms.get_rank()
        idx = brute_force.build(ds_shard, metric=mt)
        vals, ids = brute_force.knn(idx, q, k)
        gids = ids.astype(jnp.int32) + rank.astype(jnp.int32) * shard_size
        vals = jnp.where(gids < n, vals, pad_val)  # mask padded rows
        # cross-shard merge: gather all candidates, select final top-k
        all_vals = comms.allgather(vals)             # [n_dev, m, k]
        all_ids = comms.allgather(gids)
        m = q.shape[0]
        flat_v = jnp.transpose(all_vals, (1, 0, 2)).reshape(m, n_dev * k)
        flat_i = jnp.transpose(all_ids, (1, 0, 2)).reshape(m, n_dev * k)
        return _select_k(flat_v, k, select_min=select_min, input_indices=flat_i)

    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return fn(padded, queries)


def replicated_knn(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    mesh: Mesh,
    axis: str = "shard",
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN with a replicated index and queries sharded over the mesh —
    the reference's replicated-index throughput pattern (each worker holds
    the full index, queries split). Returns sharded (dists, indices)."""
    mt = resolve_metric(metric)
    n_dev = mesh.shape[axis]
    q_padded, m = _pad_rows(queries, n_dev)

    def local_search(q_shard, ds):
        idx = brute_force.build(ds, metric=mt)
        return brute_force.knn(idx, q_shard, k)

    fn = shard_map(
        local_search, mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=(P(axis, None), P(axis, None)),
        check_vma=False,
    )
    vals, ids = fn(q_padded, dataset)
    return vals[:m], ids[:m]
