"""raft_tpu.parallel — distributed: comms facade, meshes, sharded search.

Replaces the reference's entire comms stack (raft/comms NCCL/UCX/MPI +
raft-dask bootstrap) with JAX-native SPMD: ``Mesh`` + ``shard_map`` +
``lax`` collectives over ICI/DCN.
"""

from raft_tpu.parallel.comms import Comms, Op, Status, initialize_distributed  # noqa: F401
from raft_tpu.parallel.mesh import (  # noqa: F401
    HIER_AXIS_NAMES,
    hier_mesh,
    is_dcn_axis,
    make_hybrid_mesh,
    make_mesh,
    replicate,
    shard_rows,
    submesh,
)
from raft_tpu.parallel.merge import (  # noqa: F401
    MERGE_TIERS,
    hier_chunk_rows,
    merge_out_spec,
    merge_tier,
    merge_topk,
    merged_rows,
    resolve_exchange,
)
from raft_tpu.parallel.knn import replicated_knn, sharded_knn  # noqa: F401
from raft_tpu.parallel.ivf import (  # noqa: F401
    ShardedIvfFlat,
    ShardedIvfPq,
    build_ivf_flat,
    build_ivf_pq,
    search_ivf_flat,
    search_ivf_pq,
)
from raft_tpu.parallel.build import (  # noqa: F401
    ChunkPrefetcher,
    assemble_ivf_flat,
    assemble_ivf_pq,
    build_ivf_flat_distributed,
    build_ivf_pq_distributed,
    index_sha16,
    shard_ranges,
)
