"""Label utilities — TPU-native counterpart of `raft/label/` (SURVEY.md §2.7)."""

from .classlabels import (
    connected_components,
    make_monotonic,
    merge_labels,
    unique_labels,
)

__all__ = [
    "connected_components",
    "make_monotonic",
    "merge_labels",
    "unique_labels",
]
