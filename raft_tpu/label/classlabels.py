"""Label utilities — unique labels, monotonic relabeling, label merging,
connected-component labeling.

TPU-native counterpart of the reference's `raft/label/`
(label/classlabels.cuh: getUniquelabels/make_monotonic,
label/merge_labels.cuh) plus the connected-component labeling the
reference reaches through its sparse/linkage stack
(cpp/test/label/label.cu).  Propagation-style algorithms use
min-label pointer jumping: pure jnp rounds driven by a host loop with
early exit (component diameter halves per round).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..sparse.types import CSR


def unique_labels(labels) -> jnp.ndarray:
    """Sorted unique labels — reference: label/classlabels.cuh getUniquelabels."""
    return jnp.unique(jnp.asarray(labels))


def make_monotonic(labels, ignore: int | None = None) -> Tuple[jnp.ndarray, int]:
    """Relabel arbitrary int labels to a dense 0..k-1 range
    (reference: label/classlabels.cuh make_monotonic).  ``ignore`` (e.g.
    a noise marker) is preserved as-is and not counted.  Returns
    (new_labels, n_classes)."""
    lab = np.asarray(jax.device_get(jnp.asarray(labels)))
    mask = np.ones(lab.shape, dtype=bool) if ignore is None else lab != ignore
    uniq, inv = np.unique(lab[mask], return_inverse=True)
    out = lab.copy()
    out[mask] = inv
    return jnp.asarray(out), int(uniq.size)


@jax.jit
def _merge_round(labels, rows, cols):
    """One min-label propagation round over the edge list."""
    n = labels.shape[0]
    neigh_min = jax.ops.segment_min(labels[cols], rows, num_segments=n)
    cand = jnp.minimum(labels, neigh_min)
    # pointer jump through the label graph: treat label as parent
    cand = jnp.minimum(cand, cand[cand])
    return cand


def _propagate(lab, rows, cols, max_rounds: int = 64) -> jnp.ndarray:
    """Min-label propagation to fixpoint: jnp rounds, host early-exit."""
    prev = None
    for _ in range(max_rounds):
        lab = _merge_round(lab, rows, cols)
        lab_h = np.asarray(jax.device_get(lab))
        if prev is not None and np.array_equal(lab_h, prev):
            break
        prev = lab_h
    return lab


def merge_labels(labels_a, labels_b) -> jnp.ndarray:
    """Union two labelings: vertices sharing a label in either input end
    up in one merged class (reference: label/merge_labels.cuh, used when
    batched connected-components halves meet).  Labels must be in
    0..n-1 vertex-id space (e.g. "root vertex id")."""
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    n = a.shape[0]
    verts = jnp.arange(n, dtype=jnp.int32)
    # bipartite-ish union: edges vertex→its label representative in both
    rows = jnp.concatenate([verts, a, verts, b])
    cols = jnp.concatenate([a, verts, b, verts])
    return _propagate(jnp.minimum(a, b), rows, cols)


def connected_components(adj: CSR) -> Tuple[jnp.ndarray, int]:
    """Weakly-connected components of a symmetric adjacency: labels are
    the min vertex id of each component, then made monotonic.
    Returns (labels [n] in 0..k-1, k)."""
    from ..sparse.types import csr_to_coo

    coo = csr_to_coo(adj)
    n = adj.shape[0]
    rows = jnp.concatenate([coo.rows, coo.cols])
    cols = jnp.concatenate([coo.cols, coo.rows])
    lab = _propagate(jnp.arange(n, dtype=jnp.int32), rows, cols)
    mono, k = make_monotonic(lab)
    return mono, k
