"""raft_tpu.stats — descriptive statistics + model/clustering metrics.

Counterpart of the reference stats layer (cpp/include/raft/stats, 7.5k LoC).
"""

from raft_tpu.stats.descriptive import (  # noqa: F401
    cov,
    histogram,
    mean,
    mean_center,
    meanvar,
    minmax,
    stddev,
    sum_op,
    weighted_mean,
)
from raft_tpu.stats.metrics import (  # noqa: F401
    InformationCriterion,
    accuracy,
    adjusted_rand_index,
    completeness_score,
    contingency_matrix,
    dispersion,
    entropy,
    homogeneity_score,
    information_criterion_batched,
    kl_divergence,
    mutual_info_score,
    neighborhood_recall,
    r2_score,
    rand_index,
    regression_metrics,
    silhouette_score,
    trustworthiness_score,
    v_measure,
)
