"""Model & clustering quality metrics (reference: stats/accuracy.cuh,
r2_score.cuh, regression_metrics.cuh, silhouette_score.cuh,
trustworthiness_score.cuh, adjusted_rand_index.cuh, rand_index.cuh,
mutual_info_score.cuh, entropy.cuh, homogeneity_score.cuh,
completeness_score.cuh, v_measure.cuh, kl_divergence.cuh,
information_criterion.cuh, dispersion.cuh, contingency_matrix.cuh,
neighborhood_recall.cuh)."""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.distance.pairwise import l2_expanded
from raft_tpu.utils.precision import get_precision


# ---------------------------------------------------------------------------
# regression / classification
# ---------------------------------------------------------------------------

def accuracy(pred: jax.Array, ref: jax.Array) -> jax.Array:
    """reference: stats/accuracy.cuh."""
    return jnp.mean((pred == ref).astype(jnp.float32))


def r2_score(y: jax.Array, y_hat: jax.Array) -> jax.Array:
    """reference: stats/r2_score.cuh."""
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-30)


def regression_metrics(pred: jax.Array, ref: jax.Array
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(mean_abs_error, mean_squared_error, median_abs_error)
    (reference: stats/regression_metrics.cuh)."""
    err = pred - ref
    return (jnp.mean(jnp.abs(err)), jnp.mean(err * err),
            jnp.median(jnp.abs(err)))


# ---------------------------------------------------------------------------
# clustering comparison metrics (contingency-based)
# ---------------------------------------------------------------------------

def contingency_matrix(a: jax.Array, b: jax.Array, n_classes_a: int,
                       n_classes_b: int) -> jax.Array:
    """reference: stats/contingency_matrix.cuh."""
    idx = a.astype(jnp.int32) * n_classes_b + b.astype(jnp.int32)
    flat = jax.ops.segment_sum(jnp.ones_like(idx, jnp.float32), idx,
                               num_segments=n_classes_a * n_classes_b)
    return flat.reshape(n_classes_a, n_classes_b)


def _comb2(x):
    return x * (x - 1.0) / 2.0


def rand_index(a: jax.Array, b: jax.Array, n_classes: int) -> jax.Array:
    """reference: stats/rand_index.cuh."""
    c = contingency_matrix(a, b, n_classes, n_classes)
    n = a.shape[0]
    sum_comb = jnp.sum(_comb2(c))
    sum_a = jnp.sum(_comb2(jnp.sum(c, axis=1)))
    sum_b = jnp.sum(_comb2(jnp.sum(c, axis=0)))
    total = _comb2(jnp.float32(n))
    return (total + 2.0 * sum_comb - sum_a - sum_b) / total


def adjusted_rand_index(a: jax.Array, b: jax.Array, n_classes: int) -> jax.Array:
    """reference: stats/adjusted_rand_index.cuh."""
    c = contingency_matrix(a, b, n_classes, n_classes)
    n = a.shape[0]
    sum_comb = jnp.sum(_comb2(c))
    sum_a = jnp.sum(_comb2(jnp.sum(c, axis=1)))
    sum_b = jnp.sum(_comb2(jnp.sum(c, axis=0)))
    total = _comb2(jnp.float32(n))
    expected = sum_a * sum_b / jnp.maximum(total, 1e-30)
    max_index = 0.5 * (sum_a + sum_b)
    # degenerate case (both labelings a single class, or all singletons):
    # numerator and denominator are both 0 → perfect agreement by
    # convention (matches sklearn)
    denom = max_index - expected
    return jnp.where(
        jnp.abs(denom) < 1e-12,
        1.0,
        (sum_comb - expected) / jnp.maximum(denom, 1e-30),
    )


def entropy(labels: jax.Array, n_classes: int) -> jax.Array:
    """reference: stats/entropy.cuh."""
    counts = jax.ops.segment_sum(jnp.ones_like(labels, jnp.float32),
                                 labels.astype(jnp.int32),
                                 num_segments=n_classes)
    p = counts / jnp.maximum(jnp.sum(counts), 1e-30)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30)), 0.0))


def mutual_info_score(a: jax.Array, b: jax.Array, n_classes: int) -> jax.Array:
    """reference: stats/mutual_info_score.cuh."""
    c = contingency_matrix(a, b, n_classes, n_classes)
    n = jnp.sum(c)
    pij = c / jnp.maximum(n, 1e-30)
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    ratio = pij / jnp.maximum(pi * pj, 1e-30)
    return jnp.sum(jnp.where(pij > 0,
                             pij * jnp.log(jnp.maximum(ratio, 1e-30)), 0.0))


def homogeneity_score(truth: jax.Array, pred: jax.Array, n_classes: int) -> jax.Array:
    """reference: stats/homogeneity_score.cuh."""
    h_c = entropy(truth, n_classes)
    mi = mutual_info_score(truth, pred, n_classes)
    return jnp.where(h_c > 0, mi / jnp.maximum(h_c, 1e-30), 1.0)


def completeness_score(truth: jax.Array, pred: jax.Array, n_classes: int) -> jax.Array:
    """reference: stats/completeness_score.cuh."""
    return homogeneity_score(pred, truth, n_classes)


def v_measure(truth: jax.Array, pred: jax.Array, n_classes: int,
              beta: float = 1.0) -> jax.Array:
    """reference: stats/v_measure.cuh."""
    h = homogeneity_score(truth, pred, n_classes)
    c = completeness_score(truth, pred, n_classes)
    return (1 + beta) * h * c / jnp.maximum(beta * h + c, 1e-30)


def kl_divergence(p: jax.Array, q: jax.Array) -> jax.Array:
    """reference: stats/kl_divergence.cuh."""
    safe = (p > 0) & (q > 0)
    return jnp.sum(jnp.where(
        safe, p * jnp.log(jnp.maximum(p, 1e-30) / jnp.maximum(q, 1e-30)), 0.0))


# ---------------------------------------------------------------------------
# cluster-quality metrics
# ---------------------------------------------------------------------------

def dispersion(x: jax.Array, centroids: jax.Array, labels: jax.Array) -> jax.Array:
    """Global cluster dispersion (reference: stats/dispersion.cuh): sum of
    squared distances of cluster centers to the global mean, weighted by
    cluster size."""
    k = centroids.shape[0]
    counts = jax.ops.segment_sum(jnp.ones_like(labels, jnp.float32),
                                 labels.astype(jnp.int32), num_segments=k)
    g_mean = jnp.mean(x, axis=0)
    d2 = jnp.sum((centroids - g_mean[None, :]) ** 2, axis=1)
    return jnp.sum(counts * d2)


def silhouette_score(x: jax.Array, labels: jax.Array, n_clusters: int) -> jax.Array:
    """Mean silhouette coefficient (reference: stats/silhouette_score.cuh).

    Uses the per-cluster mean-distance formulation: for each sample, mean
    distance to every cluster via one [n, k] segment-reduced distance
    matrix — O(n²) distances but O(n·k) memory, the batched analog of the
    reference's batched variant."""
    n = x.shape[0]
    d = jnp.sqrt(jnp.maximum(l2_expanded(x, x, sqrt=False), 0.0))
    onehot = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)  # [n, k]
    sums = jnp.matmul(d, onehot, precision=get_precision())         # [n, k]
    counts = jnp.sum(onehot, axis=0)                                # [k]
    own = labels.astype(jnp.int32)
    own_count = counts[own]
    # a: mean distance to own cluster (excluding self)
    a = jnp.where(own_count > 1,
                  jnp.take_along_axis(sums, own[:, None], 1)[:, 0]
                  / jnp.maximum(own_count - 1, 1),
                  0.0)
    # b: min over other clusters of mean distance
    mean_to = sums / jnp.maximum(counts[None, :], 1.0)
    mean_to = mean_to.at[jnp.arange(n), own].set(jnp.inf)
    mean_to = jnp.where(counts[None, :] > 0, mean_to, jnp.inf)
    b = jnp.min(mean_to, axis=1)
    s = jnp.where(own_count > 1,
                  (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
    return jnp.mean(s)


def trustworthiness_score(x: jax.Array, x_embedded: jax.Array,
                          n_neighbors: int) -> jax.Array:
    """Trustworthiness of an embedding (reference:
    stats/trustworthiness_score.cuh): penalizes embedded-space neighbors
    that are far in the original space."""
    n = x.shape[0]
    d_orig = l2_expanded(x, x, sqrt=False)
    d_emb = l2_expanded(x_embedded, x_embedded, sqrt=False)
    big = jnp.finfo(jnp.float32).max
    d_orig = d_orig.at[jnp.arange(n), jnp.arange(n)].set(big)
    d_emb = d_emb.at[jnp.arange(n), jnp.arange(n)].set(big)
    # rank of each point j in i's original-space ordering
    orig_order = jnp.argsort(d_orig, axis=1)
    ranks = jnp.zeros((n, n), jnp.int32)
    ranks = jnp.take_along_axis(
        jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (n, n)),
        jnp.argsort(orig_order, axis=1), axis=1)
    emb_knn = jnp.argsort(d_emb, axis=1)[:, :n_neighbors]
    r = jnp.take_along_axis(ranks, emb_knn, axis=1)  # orig ranks of emb nbrs
    penalty = jnp.maximum(r - n_neighbors + 1, 0).astype(jnp.float32)
    t = 1.0 - (2.0 / (n * n_neighbors * (2.0 * n - 3.0 * n_neighbors - 1.0))
               ) * jnp.sum(penalty)
    return t


class InformationCriterion(enum.Enum):
    """reference: stats/information_criterion.cuh ``IC_Type``."""

    AIC = "aic"
    AICc = "aicc"
    BIC = "bic"


def information_criterion_batched(log_likelihood: jax.Array, n_params: int,
                                  n_samples: int,
                                  ic: InformationCriterion = InformationCriterion.AIC
                                  ) -> jax.Array:
    """reference: stats/information_criterion.cuh."""
    ll = log_likelihood
    k = jnp.float32(n_params)
    n = jnp.float32(n_samples)
    if ic == InformationCriterion.AIC:
        return -2.0 * ll + 2.0 * k
    if ic == InformationCriterion.AICc:
        return -2.0 * ll + 2.0 * k + 2.0 * k * (k + 1) / jnp.maximum(n - k - 1, 1e-30)
    return -2.0 * ll + k * jnp.log(n)


# ---------------------------------------------------------------------------
# ANN quality
# ---------------------------------------------------------------------------

def neighborhood_recall(got_indices: jax.Array, ref_indices: jax.Array,
                        got_distances: Optional[jax.Array] = None,
                        ref_distances: Optional[jax.Array] = None,
                        eps: float = 1e-3) -> jax.Array:
    """ANN recall@k (reference: stats/neighborhood_recall.cuh): fraction of
    reference neighbors found, counting distance-ties as hits when
    distances are provided."""
    m, k = got_indices.shape
    match = got_indices[:, :, None] == ref_indices[:, None, :]
    hit = jnp.any(match, axis=1)  # [m, k] per reference entry
    if got_distances is not None and ref_distances is not None:
        # a ref entry also counts if some returned distance ties it
        tie = jnp.any(jnp.abs(got_distances[:, :, None]
                              - ref_distances[:, None, :]) <= eps, axis=1)
        hit = hit | tie
    return jnp.mean(hit.astype(jnp.float32))
