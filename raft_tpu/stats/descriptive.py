"""Descriptive statistics (reference: stats/mean.cuh, meanvar.cuh,
stddev.cuh, sum.cuh, minmax.cuh, cov.cuh, histogram.cuh,
weighted_mean.cuh, mean_center.cuh)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.utils.precision import get_precision


def mean(x: jax.Array, axis: int = 0) -> jax.Array:
    """Column/row means (reference: stats/mean.cuh)."""
    return jnp.mean(x, axis=axis)


def meanvar(x: jax.Array, axis: int = 0, sample: bool = True
            ) -> Tuple[jax.Array, jax.Array]:
    """Mean + variance in one pass (reference: stats/meanvar.cuh)."""
    mu = jnp.mean(x, axis=axis)
    var = jnp.var(x, axis=axis, ddof=1 if sample else 0)
    return mu, var


def stddev(x: jax.Array, axis: int = 0, sample: bool = True) -> jax.Array:
    """reference: stats/stddev.cuh."""
    return jnp.std(x, axis=axis, ddof=1 if sample else 0)


def sum_op(x: jax.Array, axis: int = 0) -> jax.Array:
    """reference: stats/sum.cuh."""
    return jnp.sum(x, axis=axis)


def minmax(x: jax.Array, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    """reference: stats/minmax.cuh."""
    return jnp.min(x, axis=axis), jnp.max(x, axis=axis)


def cov(x: jax.Array, center: bool = True, sample: bool = True) -> jax.Array:
    """Covariance matrix of rows-as-samples (reference: stats/cov.cuh)."""
    n = x.shape[0]
    xc = x - jnp.mean(x, axis=0, keepdims=True) if center else x
    denom = (n - 1) if sample else n
    return jnp.matmul(xc.T, xc, precision=get_precision()) / denom


def histogram(x: jax.Array, n_bins: int, lo: float, hi: float) -> jax.Array:
    """Fixed-range histogram (reference: stats/histogram.cuh)."""
    edges = (x - lo) / (hi - lo) * n_bins
    idx = jnp.clip(jnp.floor(edges).astype(jnp.int32), 0, n_bins - 1)
    valid = (x >= lo) & (x <= hi)
    return jax.ops.segment_sum(valid.astype(jnp.int32).reshape(-1),
                               idx.reshape(-1), num_segments=n_bins)


def weighted_mean(x: jax.Array, weights: jax.Array, axis: int = 0) -> jax.Array:
    """reference: stats/weighted_mean.cuh."""
    if axis == 0:
        return jnp.sum(x * weights[:, None], axis=0) / jnp.sum(weights)
    return jnp.sum(x * weights[None, :], axis=1) / jnp.sum(weights)


def mean_center(x: jax.Array, axis: int = 0) -> jax.Array:
    """reference: stats/mean_center.cuh."""
    return x - jnp.mean(x, axis=axis, keepdims=True)
