"""Cost plane — per-tenant resource attribution (ISSUE 20).

The obs layer can trace one request (ISSUE 15) and score answer
quality (ISSUE 16); this module answers the question a multi-tenant
fleet gets asked daily: *which tenant is consuming what share of chip
time, HBM, host-tier IO, and interconnect*. Per-workload usage
attribution is the substrate that turns admission/eviction/placement
knobs into control loops (Autopilot, Rzadca et al., EuroSys '20;
Monarch, Adams et al., VLDB '20) — :mod:`raft_tpu.obs.capacity` is the
forecasting half that consumes this ledger.

Every number is attributed from signals that already exist — the
ledger adds bookkeeping, not instrumentation:

- **device time** — the serving plane times each dispatched batch
  (``serve.dispatch`` wall time, device-inclusive: dispatch blocks on
  the result) and calls :meth:`CostLedger.note_batch` with the batch's
  coalesced :class:`~raft_tpu.obs.trace.RequestContext` member list.
  The batch's time is prorated equally across its *live* members —
  deadline-shed members were dropped before dispatch and get nothing;
  padding waste rides the members that produced the fill (the tenant
  chose the traffic). Σ per-tenant ``cost.device_s`` equals Σ measured
  batch time by construction — the **conservation invariant** CI
  asserts within ε.
- **HBM byte-seconds** — :meth:`CostLedger.tick` integrates the
  registry's ``index.bytes{index=,tier=hbm}`` gauges over wall time
  (rectangle rule between ticks; admission/demotion events move the
  gauge, the next tick picks the new level up).
- **host-tier IO bytes** — the tiered reader
  (:mod:`raft_tpu.neighbors.tiered`) counts ``cost.io_bytes{tenant=}``
  at its ``serve.row_read`` fetch; the ledger folds the counter in.
- **comms bytes** — :meth:`raft_tpu.parallel.comms.Comms._count`
  emits ``cost.comms_bytes{tenant=,axis=ici|dcn}`` at trace time
  (GL01-clean, no host syncs) using the ``serving_tenant``
  thread-local the dispatch path already brackets searches with.
- **shed / degrade / verify counts** — folded in from the existing
  ``serve.*`` / ``quality.*`` counters.

Published series: ``cost.device_s{tenant=}``,
``cost.hbm_byte_s{tenant=}``, ``cost.io_bytes{tenant=}`` (counter,
from tiered), ``cost.comms_bytes{tenant=,axis=}`` (counter, from
comms), and the normalized ``cost.share{tenant=}`` gauge the router's
placement scoring reads.

Overhead contract (mirrors ISSUE 1): the serving tap is guarded by
``spans.enabled()`` — obs off costs one flag check per batch and the
ledger attributes nothing. :meth:`note_batch` itself accumulates
unconditionally (unit tests exercise proration without global obs),
but publishes gauges only while recording is on.

The ledger is registered process-globally (:func:`set_ledger`, the
SLO-monitor install pattern) so dispatch — which cannot see the server
object — can reach it without plumbing. All locks ride
``monitored_lock`` so the ISSUE-18 sanitize lane covers them.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _spans
from raft_tpu.obs.metrics import counter_sum

__all__ = ["CostLedger", "set_ledger", "get_ledger", "clear_ledger"]

#: counter families folded into :meth:`CostLedger.describe` per tenant
#: (name, label carrying the tenant, output key)
_FOLDED_COUNTERS: Tuple[Tuple[str, str, str], ...] = (
    ("serve.requests", "tenant", "requests"),
    ("cost.io_bytes", "tenant", "io_bytes"),
    ("quality.verified", "tenant", "verified"),
    ("serve.registry.demote", "tenant", "demotions"),
    ("serve.registry.preemptive_demote", "tenant", "preemptive_demotions"),
)


class CostLedger:
    """Thread-safe per-``(tenant, resource)`` attribution ledger.

    One instance per serving plane; the server creates it at start,
    installs it globally, and tears it down at stop. ``clock`` is
    injectable for deterministic byte-second integration in tests."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = _sanitize.monitored_lock("obs.cost")
        self._device_s: Dict[str, float] = {}
        self._members: Dict[str, int] = {}
        self._batch_wall_s = 0.0
        self._batches = 0
        self._hbm_byte_s: Dict[str, float] = {}
        # tenant -> (last tick monotonic time, last observed hbm bytes)
        self._hbm_last: Dict[str, Tuple[float, float]] = {}
        self._started = clock()

    # -- device time ---------------------------------------------------------
    def note_batch(self, device_s: float,
                   members: Sequence[str]) -> None:
        """Attribute one dispatched batch's wall time across its live
        member list (one entry per coalesced request, repeated tenant
        names allowed — a cross-tenant batch splits by member count).
        Shed members must not appear in ``members``: attribution
        follows work actually dispatched."""
        if device_s < 0.0 or not members:
            return
        per = float(device_s) / len(members)
        publish = _spans.enabled()
        with self._lock:
            self._batch_wall_s += float(device_s)
            self._batches += 1
            for t in members:
                self._device_s[t] = self._device_s.get(t, 0.0) + per
                self._members[t] = self._members.get(t, 0) + 1
            if publish:
                reg = _spans.registry()
                for t in set(members):
                    reg.gauge("cost.device_s",
                              labels={"tenant": t}).set(self._device_s[t])
                self._publish_shares_locked(reg)

    def _publish_shares_locked(self, reg: Any) -> None:
        total = sum(self._device_s.values())
        if total <= 0.0:
            return
        for t, v in self._device_s.items():
            reg.gauge("cost.share", labels={"tenant": t}).set(v / total)

    # -- HBM byte-second integration ----------------------------------------
    def tick(self) -> None:
        """Advance the HBM byte-second integrals from the current
        ``index.bytes{tier=hbm}`` gauge levels. Driven from scrapes,
        ``/costz``, admission events, and the flight section — the
        rectangle rule holds the *previous* level across the interval,
        so a demotion is charged at the pre-move level until observed."""
        if not _spans.enabled():
            return
        now = self._clock()
        levels: Dict[str, float] = {}
        for r in _spans.registry().collect():
            if r.get("kind") != "gauge" or r.get("name") != "index.bytes":
                continue
            labels = r.get("labels") or {}
            if labels.get("tier") == "hbm" and labels.get("index"):
                levels[str(labels["index"])] = float(r.get("value", 0.0))
        with self._lock:
            reg = _spans.registry()
            for t, level in levels.items():
                last_ts, last_level = self._hbm_last.get(t, (now, level))
                self._hbm_byte_s[t] = (self._hbm_byte_s.get(t, 0.0)
                                       + last_level * (now - last_ts))
                self._hbm_last[t] = (now, level)
                reg.gauge("cost.hbm_byte_s",
                          labels={"tenant": t}).set(self._hbm_byte_s[t])

    # -- reads ---------------------------------------------------------------
    def device_seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._device_s)

    def shares(self) -> Dict[str, float]:
        """Normalized device-time shares (the placement signal). Falls
        back to HBM byte-second shares before any batch has run, so a
        freshly admitted fleet still ranks pods by real residency."""
        with self._lock:
            basis = self._device_s if sum(self._device_s.values()) > 0 \
                else self._hbm_byte_s
            total = sum(basis.values())
            if total <= 0.0:
                return {}
            return {t: v / total for t, v in basis.items()}

    def conservation(self) -> Dict[str, float]:
        """The invariant CI gates on: Σ per-tenant device time must
        equal total measured batch time (within float noise — equality
        holds by construction; the 5% CI ε absorbs only the comparison
        against an *externally* measured load-generator total)."""
        with self._lock:
            attributed = sum(self._device_s.values())
            total = self._batch_wall_s
        err = abs(attributed - total) / total if total > 0 else 0.0
        return {"attributed_device_s": attributed,
                "batch_wall_s": total, "rel_err": err}

    def describe(self) -> Dict[str, Any]:
        """JSON-ready per-tenant ledger — the ``/costz`` body and the
        ``"cost"`` flight-dump section. Folds the registry's
        tenant-labeled counters (io, comms, sheds, verifies) in beside
        the ledger's own device/HBM attribution."""
        self.tick()
        rows: List[Dict[str, Any]] = []
        if _spans.enabled():
            rows = _spans.registry().collect()
        with self._lock:
            tenants = set(self._device_s) | set(self._hbm_byte_s)
            device = dict(self._device_s)
            members = dict(self._members)
            hbm = dict(self._hbm_byte_s)
            batches = self._batches
            wall = self._batch_wall_s
        for r in rows:
            labels = r.get("labels") or {}
            if labels.get("tenant"):
                tenants.add(str(labels["tenant"]))
        shares = self.shares()
        per_tenant: Dict[str, Any] = {}
        for t in sorted(tenants):
            comms = {
                axis: counter_sum(rows, "cost.comms_bytes",
                                  tenant=t, axis=axis)
                for axis in ("ici", "dcn")}
            entry: Dict[str, Any] = {
                "device_s": device.get(t, 0.0),
                "members": members.get(t, 0),
                "hbm_byte_s": hbm.get(t, 0.0),
                "comms_bytes": comms,
                "share": shares.get(t, 0.0),
            }
            for name, label, key in _FOLDED_COUNTERS:
                entry[key] = counter_sum(rows, name, **{label: t})
            per_tenant[t] = entry
        cons = self.conservation()
        return {
            "tenants": per_tenant,
            "totals": {"batches": batches, "batch_wall_s": wall,
                       "uptime_s": self._clock() - self._started,
                       "shed": counter_sum(rows, "serve.shed")},
            "conservation": cons,
        }


# -- process-global ledger (the slo-monitor install pattern) ----------------

_ledger: Optional[CostLedger] = None
_ledger_lock = _sanitize.monitored_lock("obs.cost.global")


def set_ledger(ledger: Optional[CostLedger]) -> Optional[CostLedger]:
    """Install the process-global ledger (returns the previous one).
    The server installs at start and clears at stop so dispatch can
    attribute batches without plumbing."""
    global _ledger
    with _ledger_lock:
        prev = _ledger
        _ledger = ledger
        return prev


def get_ledger() -> Optional[CostLedger]:
    return _ledger


def clear_ledger(ledger: Optional[CostLedger] = None) -> None:
    """Remove the global ledger; with an argument, only when it is
    still the installed one (a stop() racing a newer start() must not
    clear the newer server's ledger)."""
    global _ledger
    with _ledger_lock:
        if ledger is None or _ledger is ledger:
            _ledger = None
