"""Runtime sanitizer harness — jax-native guards for the test suite.

The static half of the correctness tooling (``tools/graftlint``) catches
what the AST shows; this module wires up what only shows at runtime —
the TPU-native analog of running the reference's tests under
compute-sanitizer (RAFT ci/test.sh) :

- :func:`apply_sanitize_config` — the ``RAFT_TPU_SANITIZE=1`` mode:
  ``jax_numpy_rank_promotion="raise"`` (implicit rank promotion is how
  a [n]-vs-[n,1] slip silently broadcasts into an O(n²) intermediate)
  and ``jax_debug_nans`` (NaNs surface at the op that made them, not
  three layers later in a recall number).
- :func:`no_host_transfers` — scopes
  ``jax.transfer_guard("disallow")`` around a search/build hot path:
  any implicit device↔host round-trip inside raises instead of
  silently serializing the dispatch pipeline. Prepare inputs on device
  BEFORE the scope: eager ``jnp.asarray(host_data)`` and Python-scalar
  lifting inside count as implicit and raise; ``jax.device_get`` /
  ``jax.device_put`` remain allowed.
- :func:`recompile_budget` / :func:`compile_count` — a jit-cache-miss
  counter fed by ``jax.monitoring``'s backend-compile event: a test
  wraps its steady-state calls in ``recompile_budget(0)`` and an
  unexpected retrace fails loudly with the count, instead of costing
  seconds per call in production three PRs later.
- :func:`assert_uniform_collective_schedule` /
  :func:`collective_schedule` — the collective-schedule checker, the
  runtime complement of graftlint's SPMD pass (GL06–GL10): traces a
  program on the 8-device CPU mesh, derives each device's sequence of
  collectives, and raises :class:`CollectiveScheduleDivergence` when
  the schedules can differ across devices (a collective issued in only
  some branches of an ``axis_index``-gated ``lax.cond``/``switch`` —
  exactly the class the AST pass cannot prove absent, and the class
  that deadlocks a real v5e mesh while CPU tests stay green).
- :func:`record_comms_schedule` — records the trace-time sequence of
  comms-facade calls (verb, axis, payload bytes) per traced program,
  so tests can assert WHAT schedule a distributed entry point commits
  every device to.
- :func:`capacity_report` / :func:`assert_billion_safe` — the
  **capacity prover**, the runtime half of graftlint's capacity pass
  (GL11–GL15): traces a program at synthetic billion-scale shapes
  (``jax.ShapeDtypeStruct`` — ``jax.eval_shape`` semantics, zero bytes
  allocated, device-free) and walks the jaxpr for int32-dtyped
  intermediates that index axes ≥ 2³¹ (int32 iota over an oversized
  axis; gather/scatter/dynamic-slice indexing an oversized dim with
  int32 indices) plus peak intermediate bytes.
  ``assert_billion_safe`` raises :class:`CapacityError` with eqn
  provenance — the compile-time ``IdxT`` check the reference gets from
  64-bit index templating, here as a CI gate over the public search /
  build entries (``tools/capacity_prove.py``). x64 is enabled only
  inside a scoped save/restore (:func:`scoped_x64`): the prover never
  leaks ``jax_enable_x64`` into the test process.
- :func:`monitored_lock` / :func:`monitored_rlock` /
  :func:`monitored_condition` + :func:`assert_no_lock_cycles` /
  :func:`blocking_region` — the **lock-order tracker**, the runtime
  half of graftlint's concurrency pass (GL16–GL20): in the sanitize
  lane every registry/server/observability lock is an instrumented
  wrapper that records per-thread acquisition order into a
  process-wide graph with first-witness stacks, so an AB/BA inversion
  raises :class:`LockOrderViolation` even when this run's interleaving
  happened not to deadlock; :func:`blocking_region` brackets blocking
  calls (``queue.get``, ``Future.result``, ``join``, HTTP) and
  :func:`assert_no_held_lock_blocking` fails the lane when one ran
  while a monitored lock was held. Off the lane the factories return
  plain stdlib primitives — zero wrapper, zero overhead.

Everything here is import-cheap: jax is only imported when a guard is
actually used, and the monitoring listener is installed once on first
use (jax has no per-listener unregister across versions, so the
listener stays; it is a few instructions per compile event).
"""

from __future__ import annotations

import contextlib
import sys
import threading
import traceback
from typing import Dict, Iterator, List, Optional, Tuple

# jax.monitoring event recorded once per backend (XLA) compile — i.e.
# once per jit-cache MISS. Resolved lazily from jax's dispatch module so
# a rename fails loudly here rather than silently counting nothing.
_COMPILE_EVENT_FALLBACK = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_compiles = 0


def _compile_event_name() -> str:
    try:
        from jax._src import dispatch as _dispatch

        return getattr(_dispatch, "BACKEND_COMPILE_EVENT",
                       _COMPILE_EVENT_FALLBACK)
    except Exception:  # pragma: no cover - unknown jax layout
        return _COMPILE_EVENT_FALLBACK


def install_compile_counter() -> None:
    """Register the jit-cache-miss listener (idempotent, stays for the
    process lifetime — jax.monitoring has no stable unregister API)."""
    global _installed
    with _lock:
        if _installed:
            return
        import jax.monitoring

        event_name = _compile_event_name()

        def _on_duration(event: str, duration_secs: float, **kw) -> None:
            global _compiles
            if event == event_name:
                with _lock:
                    _compiles += 1

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True


def compile_count() -> int:
    """Backend compiles observed since :func:`install_compile_counter`."""
    with _lock:
        return _compiles


class RecompileBudgetExceeded(RuntimeError):
    """A scope compiled more programs than its declared budget."""


@contextlib.contextmanager
def recompile_budget(budget: int, what: str = "scope") -> Iterator[None]:
    """Fail if the wrapped scope triggers more than ``budget`` backend
    compiles. ``budget=0`` asserts a fully warm jit cache — the steady-
    state contract for serving hot paths. Install-on-first-use: the
    counter misses compiles that happened before the first budget scope
    in the process, which is fine — budgets measure deltas."""
    install_compile_counter()
    start = compile_count()
    yield
    spent = compile_count() - start
    if spent > budget:
        raise RecompileBudgetExceeded(
            f"{what}: {spent} backend compile(s), budget {budget} — an "
            "unexpected retrace (shape/dtype/static-arg churn or a "
            "non-hashable static) is recompiling the hot path")


@contextlib.contextmanager
def no_host_transfers() -> Iterator[None]:
    """Scope ``jax.transfer_guard("disallow")`` around a hot path:
    implicit device↔host transfers raise. Prepare all inputs on device
    before entering — eager ``jnp.asarray(host_data)`` and Python-scalar
    lifting inside the scope count as implicit and raise; explicit
    ``jax.device_get`` / ``jax.device_put`` stay allowed."""
    import jax

    with jax.transfer_guard("disallow"):
        yield


def apply_sanitize_config() -> None:
    """Apply the ``RAFT_TPU_SANITIZE=1`` jax.config set (rank-promotion
    raise + debug_nans) process-wide. Call before tests import the
    library under test; conftest does this when the env flag is set."""
    import jax

    jax.config.update("jax_numpy_rank_promotion", "raise")
    jax.config.update("jax_debug_nans", True)


def sanitize_enabled() -> bool:
    """True when the suite runs in ``RAFT_TPU_SANITIZE=1`` mode."""
    # deferred import: every threaded module (metrics included, which
    # spans itself imports) creates its locks through monitored_lock
    # below, so this module must be importable before obs.spans is
    from raft_tpu.obs.spans import env_flag

    return env_flag("RAFT_TPU_SANITIZE")


# ---------------------------------------------------------------------------
# collective-schedule checker — the runtime half of graftlint GL06–GL10
# ---------------------------------------------------------------------------

class CollectiveScheduleDivergence(RuntimeError):
    """A traced program's collective schedule can differ across devices
    (a collective appears in only some branches of conditional control
    flow) — the SPMD deadlock/corruption class on a real mesh."""


# Collective primitive base names; version-tolerant prefix matching
# (psum lowers as psum/psum2/psum_invariant depending on jax version).
# Longest-first so psum_scatter is not swallowed by psum. axis_index is
# deliberately absent: it carries no payload and cannot deadlock.
_COLLECTIVE_BASES = (
    "reduce_scatter", "psum_scatter", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "pgather", "pmax", "pmin", "pmean", "psum",
)


def _collective_base(prim_name: str):
    for base in _COLLECTIVE_BASES:
        if prim_name.startswith(base):
            return base
    return None


def _eqn_axes(params) -> tuple:
    axes = params.get("axes", params.get("axis_name"))
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _jaxpr_like(v):
    """Yield raw jaxprs found in an eqn-param value (Jaxpr, ClosedJaxpr,
    or containers of them)."""
    if hasattr(v, "eqns"):
        yield v
    elif hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for item in v:
            yield from _jaxpr_like(item)


def _render_schedule(sched) -> str:
    if not sched:
        return "(no collectives)"
    return ", ".join(
        f"{e[0]}@{','.join(e[1])}{list(e[2])}" if len(e) == 3
        else f"{e[0]}[{_render_schedule(e[1])}]" for e in sched)


def _jaxpr_schedule(jaxpr) -> tuple:
    """Depth-first collective schedule of one jaxpr. ``cond``/``switch``
    branches must commit to IDENTICAL schedules — a device-dependent
    predicate then cannot change what any device executes, which is the
    across-devices uniformity the checker asserts. Loop bodies
    (while/scan) are wrapped as nested entries: their schedule is
    uniform per iteration; trip counts driven by collective-reduced
    values are uniform by construction."""
    sched = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        base = _collective_base(name)
        if base is not None:
            shapes = tuple(str(getattr(v, "aval", v)) for v in eqn.invars)
            sched.append((base, _eqn_axes(eqn.params), shapes))
            continue
        branches = eqn.params.get("branches") if eqn.params else None
        if branches is not None:
            scheds = [_jaxpr_schedule(b) for bb in branches
                      for b in _jaxpr_like(bb)]
            if any(s != scheds[0] for s in scheds[1:]):
                detail = "\n".join(
                    f"  branch {i}: {_render_schedule(s)}"
                    for i, s in enumerate(scheds))
                raise CollectiveScheduleDivergence(
                    f"collective schedule diverges across {name} "
                    f"branches — devices taking different branches "
                    f"would disagree on which collectives run "
                    f"(deadlock/zero-fill on a real mesh):\n{detail}")
            if scheds:
                sched.extend(scheds[0])
            continue
        for sub in _jaxpr_like(list((eqn.params or {}).values())):
            inner = _jaxpr_schedule(sub)
            if not inner:
                continue
            if name in ("while", "scan"):
                sched.append((name, inner))
            else:
                sched.extend(inner)
    return tuple(sched)


def collective_schedule(fn, *args, **kwargs) -> tuple:
    """Trace ``fn(*args, **kwargs)`` (no execution) and return its
    device-uniform collective schedule as a tuple of
    ``(verb, axes, input_avals)`` entries (loops nest as
    ``("while"|"scan", inner)``). Raises
    :class:`CollectiveScheduleDivergence` when conditional branches
    commit different devices to different schedules."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return _jaxpr_schedule(closed.jaxpr)


def assert_uniform_collective_schedule(fn, *args, **kwargs) -> tuple:
    """Alias of :func:`collective_schedule` named for its assertion:
    use in tests to gate distributed entry points in the
    ``RAFT_TPU_SANITIZE=1`` lane."""
    return collective_schedule(fn, *args, **kwargs)


# -- comms-facade schedule recorder -----------------------------------------

_comms_schedule: Optional[list] = None


def comms_schedule_recording() -> bool:
    """True while a :func:`record_comms_schedule` scope is active (one
    module-global read — the facade's fast-path guard)."""
    return _comms_schedule is not None


def note_collective(verb: str, axis: str, nbytes: int) -> None:
    """Hook called by ``parallel.comms.Comms`` at trace time, once per
    collective per traced program (the same per-trace semantics as the
    ``comms.ops`` counters)."""
    rec = _comms_schedule
    if rec is not None:
        rec.append((verb, axis, int(nbytes)))


# ---------------------------------------------------------------------------
# capacity prover — the runtime half of graftlint's capacity pass
# (GL11–GL15): eval_shape-only billion-scale proofs, device-free
# ---------------------------------------------------------------------------

INT32_MAX_INDEX = 2**31 - 1  # largest axis position an int32 id can hold


class CapacityError(RuntimeError):
    """A traced program indexes a ≥ 2³¹ axis through int32-dtyped
    intermediates — the silent-overflow class 64-bit ``IdxT`` templating
    exists to prevent. Carries eqn provenance in the message."""


@contextlib.contextmanager
def scoped_x64(enable: bool = True) -> Iterator[None]:
    """Enable (or disable) ``jax_enable_x64`` for the scope ONLY —
    save/restore, exception-safe. The prover traces int64 id paths, but
    the flag is process-global and silently changes every test's
    dtypes, so it must never leak out of a proof."""
    import jax

    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)


def _is_i32(dtype) -> bool:
    import numpy as _np

    return _np.dtype(dtype) == _np.dtype("int32")


def _eqn_where(eqn) -> str:
    """Best-effort user-frame provenance of one eqn."""
    try:
        tb = eqn.source_info.traceback
        # jax eqn tracebacks are innermost-first: the FIRST non-jax
        # frame is the offending user line (the last would be the
        # prover's own call site)
        for fr in tb.frames:
            fn = getattr(fr, "file_name", "")
            if "site-packages" not in fn and "/jax/" not in fn:
                return (f"{fr.file_name}:{fr.line_num} "
                        f"({fr.function_name})")
    except Exception:
        pass
    return "<unknown site>"


def _aval_bytes(v) -> int:
    import math as _math
    import numpy as _np

    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = _np.dtype(dtype).itemsize
    except TypeError:  # extended dtypes (PRNG keys) have no numpy dtype
        itemsize = getattr(dtype, "itemsize", 0) or 0
    return _math.prod(shape) * itemsize if shape else itemsize


def _scan_eqn(eqn, hits: list) -> None:
    """Record int32-index-over-≥2³¹-axis violations for one eqn."""
    name = eqn.primitive.name
    params = eqn.params or {}

    def hit(msg: str) -> None:
        hits.append({"primitive": name, "where": _eqn_where(eqn),
                     "message": msg})

    if name == "iota":
        shape = params.get("shape") or getattr(
            eqn.outvars[0].aval, "shape", ())
        dim = params.get("dimension", 0)
        if _is_i32(params.get("dtype", "int32")) and shape \
                and shape[dim] - 1 > INT32_MAX_INDEX:
            hit(f"int32 iota over an axis of {shape[dim]} positions — "
                "ids past 2³¹ wrap negative (use core.ids.make_ids)")
    elif name in ("gather", "dynamic_gather"):
        dnums = params.get("dimension_numbers")
        if dnums is None or len(eqn.invars) < 2:
            return
        operand, indices = eqn.invars[0], eqn.invars[1]
        if not _is_i32(getattr(indices.aval, "dtype", None)):
            return
        oshape = getattr(operand.aval, "shape", ())
        for d in getattr(dnums, "start_index_map", ()):
            if d < len(oshape) and oshape[d] - 1 > INT32_MAX_INDEX:
                hit(f"gather indexes operand dim {d} of {oshape[d]} "
                    "rows with int32 indices — rows past 2³¹ are "
                    "unaddressable (thread core.ids.id_dtype through "
                    "the id path)")
    elif name.startswith("scatter"):
        dnums = params.get("dimension_numbers")
        if dnums is None or len(eqn.invars) < 2:
            return
        operand, indices = eqn.invars[0], eqn.invars[1]
        if not _is_i32(getattr(indices.aval, "dtype", None)):
            return
        oshape = getattr(operand.aval, "shape", ())
        for d in getattr(dnums, "scatter_dims_to_operand_dims", ()):
            if d < len(oshape) and oshape[d] - 1 > INT32_MAX_INDEX:
                hit(f"scatter addresses operand dim {d} of {oshape[d]} "
                    "rows with int32 indices")
    elif name in ("dynamic_slice", "dynamic_update_slice"):
        n_lead = 2 if name == "dynamic_update_slice" else 1
        operand = eqn.invars[0]
        oshape = getattr(operand.aval, "shape", ())
        starts = eqn.invars[n_lead:]
        for d, sv in enumerate(starts):
            if d < len(oshape) and oshape[d] - 1 > INT32_MAX_INDEX \
                    and _is_i32(getattr(sv.aval, "dtype", None)):
                hit(f"{name} starts into dim {d} of {oshape[d]} "
                    "positions with an int32 start index")
    elif name == "argmax" or name == "argmin":
        idx_dtype = params.get("index_dtype")
        axes = params.get("axes", ())
        ishape = getattr(eqn.invars[0].aval, "shape", ()) if eqn.invars \
            else ()
        if idx_dtype is not None and _is_i32(idx_dtype):
            for d in axes:
                if d < len(ishape) and ishape[d] - 1 > INT32_MAX_INDEX:
                    hit(f"{name} over an axis of {ishape[d]} positions "
                        "returns int32 positions")
    elif name == "top_k":
        ishape = getattr(eqn.invars[0].aval, "shape", ()) if eqn.invars \
            else ()
        out_i = eqn.outvars[1] if len(eqn.outvars) > 1 else None
        if ishape and ishape[-1] - 1 > INT32_MAX_INDEX and out_i is not None \
                and _is_i32(getattr(out_i.aval, "dtype", None)):
            hit(f"top_k over a {ishape[-1]}-wide axis returns int32 "
                "positions")


def _walk_capacity(jaxpr, hits: list, seen: set, stats: dict) -> None:
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        _scan_eqn(eqn, hits)
        out_bytes = sum(_aval_bytes(v) for v in eqn.outvars)
        in_bytes = sum(_aval_bytes(v) for v in eqn.invars
                       if hasattr(v, "aval"))
        stats["peak_intermediate_bytes"] = max(
            stats["peak_intermediate_bytes"], out_bytes + in_bytes)
        for sub in _jaxpr_like(list((eqn.params or {}).values())):
            _walk_capacity(sub, hits, seen, stats)


def capacity_report(fn, *abstract_args, **abstract_kwargs) -> dict:
    """Device-free capacity analysis of ``fn`` at synthetic shapes.

    ``abstract_args`` are ``jax.ShapeDtypeStruct`` pytrees (real arrays
    work too but defeat the point — the prover exists so SIFT-1B shapes
    cost zero bytes). Traces via ``jax.make_jaxpr`` (the same
    no-execution semantics as ``jax.eval_shape``) under a scoped-x64
    context and walks every sub-jaxpr (pjit/shard_map/scan/while/cond)
    for int32-dtyped intermediates indexing axes ≥ 2³¹.

    Returns ``{"violations": [{primitive, where, message}, ...],
    "peak_intermediate_bytes": int, "out_shapes": [...]}`` — use
    :func:`assert_billion_safe` as the raising gate.

    Two violation channels: (a) the jaxpr walk finds int32 iota /
    gather / scatter / dynamic-slice / arg-select eqns over oversized
    axes; (b) jax itself refuses to NORMALIZE an int32 index against a
    ≥ 2³¹ axis at trace time (``OverflowError: Python integer … out of
    bounds for int32`` from ``jnp``-level indexing) — the same overflow
    class surfacing earlier, reported with the offending user frame
    instead of propagating as a confusing trace crash."""
    import jax

    hits: list = []
    stats = {"peak_intermediate_bytes": 0}
    try:
        with scoped_x64(True):
            closed = jax.make_jaxpr(fn)(*abstract_args, **abstract_kwargs)
    except OverflowError as e:
        import traceback as _tb

        where = "<unknown site>"
        for fr in _tb.extract_tb(e.__traceback__):
            if "site-packages" not in fr.filename \
                    and "/jax/" not in fr.filename:
                where = f"{fr.filename}:{fr.lineno} ({fr.name})"
        hits.append({
            "primitive": "trace", "where": where,
            "message": f"int32 index cannot address the axis: {e} "
                       "(thread core.ids.id_dtype through the id path)"})
        return {"violations": hits, "peak_intermediate_bytes": 0,
                "out_shapes": []}
    _walk_capacity(closed.jaxpr, hits, set(), stats)
    return {
        "violations": hits,
        "peak_intermediate_bytes": stats["peak_intermediate_bytes"],
        "out_shapes": [str(getattr(v, "aval", v))
                       for v in closed.jaxpr.outvars],
    }


def assert_billion_safe(fn, *abstract_args, what: str = "program",
                        **abstract_kwargs) -> dict:
    """Trace ``fn`` at the given (billion-scale) abstract shapes and
    raise :class:`CapacityError` listing every int32-indexes-≥2³¹-axis
    eqn with provenance; returns the :func:`capacity_report` dict when
    clean. The CI gate (``tools/capacity_prove.py``) runs this over the
    four index search entries, the sharded merge tier, and
    ``build_chunked``'s assignment/encode pass."""
    report = capacity_report(fn, *abstract_args, **abstract_kwargs)
    if report["violations"]:
        detail = "\n".join(
            f"  [{v['primitive']}] {v['message']}\n      at {v['where']}"
            for v in report["violations"])
        raise CapacityError(
            f"{what}: {len(report['violations'])} int32 capacity "
            f"violation(s) at billion-scale shapes:\n{detail}")
    return report


@contextlib.contextmanager
def record_comms_schedule() -> Iterator[list]:
    """Record the trace-time sequence of comms-facade calls —
    ``(verb, axis, payload_bytes)`` per collective, in program order.
    Under SPMD every device executes the one traced program, so this IS
    each device's schedule; pair with
    :func:`assert_uniform_collective_schedule` to also rule out
    conditionally-divergent collectives the recorder (which sees both
    branches at trace time) cannot distinguish."""
    global _comms_schedule
    prev = _comms_schedule
    _comms_schedule = rec = []
    try:
        yield rec
    finally:
        _comms_schedule = prev


# ---------------------------------------------------------------------------
# lock-order tracker + held-lock-blocking detector — the runtime half of
# graftlint's concurrency pass (GL16–GL20)
# ---------------------------------------------------------------------------

class LockOrderViolation(RuntimeError):
    """The process-wide lock acquisition graph contains a cycle — two
    threads CAN deadlock (A→B here, B→A there), even if this run's
    interleaving happened not to. The message carries both witness
    stacks: where each direction of the inversion was first observed."""


class HeldLockBlockingCall(RuntimeError):
    """A blocking call (``queue.get`` / ``Future.result`` / ``join`` /
    HTTP) ran while a monitored registry/server lock was held — every
    other thread needing that lock stalls behind an unbounded wait."""


class _LockTrackerState:
    """One process-wide order graph + violation log. Swapped wholesale
    by :func:`force_lock_tracking` so tests never pollute the CI lane's
    graph."""

    def __init__(self, forced: bool = False):
        self.forced = forced
        # guards the maps below; internal-only and never reachable from
        # a signal handler, so a plain lock is correct here
        self.lock = threading.Lock()
        # (held_name, acquired_name) -> (held_stack, acquire_stack):
        # the FIRST witness of each ordered pair
        self.edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.blocking: List[dict] = []
        self.counts: Dict[str, int] = {}


_tracker = _LockTrackerState()
_held_tls = threading.local()  # .stack: [(lock_name, acquire_stack), ...]


def _lock_count(counter: str) -> None:
    state = _tracker
    with state.lock:
        state.counts[counter] = state.counts.get(counter, 0) + 1


def publish_lock_counters() -> None:
    """Mirror the tracker's counters into the metrics registry as
    ``sanitize.lock.*`` gauges. Deliberately NOT inline with
    acquisition: the registry's own locks are monitored, so publishing
    from inside ``_note_acquired`` would acquire registry locks while
    the just-acquired lock is held — injecting the very inversions the
    tracker exists to catch. The CI-lane assertions call this instead."""
    spans_mod = sys.modules.get("raft_tpu.obs.spans")
    if spans_mod is None or not spans_mod.enabled():
        return
    reg = spans_mod.registry()
    for name, value in lock_tracker_counts().items():
        reg.set(name, float(value))


def lock_tracking_enabled() -> bool:
    """True when monitored_lock() hands out instrumented wrappers —
    the ``RAFT_TPU_SANITIZE=1`` lane, or a :func:`force_lock_tracking`
    scope (tests)."""
    return _tracker.forced or sanitize_enabled()


def _held_stack() -> list:
    stack = getattr(_held_tls, "stack", None)
    if stack is None:
        stack = _held_tls.stack = []
    return stack


class _MonitoredLock:
    """Instrumented Lock/RLock: records this thread's acquisition order
    into the process-wide graph (with the first witness stack per
    edge). Supports the full lock protocol including the private
    ``Condition`` hooks, so ``threading.Condition(monitored_lock(...))``
    works — ``wait()`` strips the held-stack entries it releases and
    restores them on wakeup."""

    __slots__ = ("name", "reentrant", "_inner", "_owner", "_count")

    def __init__(self, name: str, reentrant: bool):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._owner: Optional[int] = None
        self._count = 0

    # -- lock protocol ------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def release(self) -> None:
        self._note_released()
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._count > 0

    def __repr__(self) -> str:
        kind = "RLock" if self.reentrant else "Lock"
        return f"<monitored {kind} {self.name!r} count={self._count}>"

    # -- Condition hooks ----------------------------------------------------
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        # cond.wait(): fully release (all recursion levels) and strip
        # our held-stack bookkeeping — the thread no longer holds it
        stripped = self._strip_held()
        if hasattr(self._inner, "_release_save"):
            return ("rlock", self._inner._release_save(), stripped)
        self._owner, self._count = None, 0
        self._inner.release()
        return ("lock", None, stripped)

    def _acquire_restore(self, saved) -> None:
        kind, inner_state, stripped = saved
        if kind == "rlock":
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        for _ in range(stripped):
            self._note_acquired()

    # -- bookkeeping --------------------------------------------------------
    def _note_acquired(self) -> None:
        self._owner = threading.get_ident()
        self._count += 1
        stack = _held_stack()
        here = "".join(traceback.format_stack(limit=10)[:-1])
        state = _tracker
        for held_name, held_at in stack:
            if held_name == self.name:
                continue  # reentrant re-acquire is not an ordering
            key = (held_name, self.name)
            with state.lock:
                if key not in state.edges:
                    state.edges[key] = (held_at, here)
        stack.append((self.name, here))
        _lock_count("sanitize.lock.acquire")

    def _note_released(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner, self._count = None, 0
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == self.name:
                del stack[i]
                break

    def _strip_held(self) -> int:
        stack = _held_stack()
        n = len([e for e in stack if e[0] == self.name])
        stack[:] = [e for e in stack if e[0] != self.name]
        self._owner, self._count = None, 0
        return n


def monitored_lock(name: str):
    """A ``threading.Lock`` for ``name`` — instrumented for lock-order
    tracking when the sanitize lane is on, a plain stdlib lock (zero
    overhead, no wrapper) otherwise. ``name`` is the node in the order
    graph: name the SITE (``"serve.registry"``), not the instance —
    every registry instance contends on the same ordering discipline."""
    if lock_tracking_enabled():
        return _MonitoredLock(name, reentrant=False)
    return threading.Lock()


def monitored_rlock(name: str):
    """Reentrant variant of :func:`monitored_lock` — the required kind
    on any path a signal handler can reach (graftlint GL19)."""
    if lock_tracking_enabled():
        return _MonitoredLock(name, reentrant=True)
    return threading.RLock()


def monitored_condition(name: str):
    """A ``threading.Condition`` whose underlying lock is monitored —
    waiters strip their held-stack entries while blocked in ``wait()``
    and restore them on wakeup, so a parked batcher thread never reads
    as 'holding' its lock."""
    if lock_tracking_enabled():
        return threading.Condition(_MonitoredLock(name, reentrant=True))
    return threading.Condition()


@contextlib.contextmanager
def blocking_region(kind: str) -> Iterator[None]:
    """Bracket a blocking call (``queue.get`` / ``Future.result`` /
    ``join`` / HTTP) so the held-lock-blocking detector can flag it
    when any monitored lock is held by this thread. No-op (one TLS
    read) outside the sanitize lane."""
    held = [name for name, _ in getattr(_held_tls, "stack", ())]
    if held:
        entry = {
            "call": kind,
            "held": held,
            "stack": "".join(traceback.format_stack(limit=10)[:-1]),
        }
        state = _tracker
        with state.lock:
            state.blocking.append(entry)
        _lock_count("sanitize.lock.blocked_while_held")
    yield


def lock_order_edges() -> Dict[Tuple[str, str], Tuple[str, str]]:
    """Snapshot of the observed order graph: ``(held, acquired) →
    (held_stack, acquire_stack)`` first witnesses."""
    state = _tracker
    with state.lock:
        return dict(state.edges)


def held_lock_blocking_violations() -> List[dict]:
    state = _tracker
    with state.lock:
        return list(state.blocking)


def lock_tracker_counts() -> Dict[str, int]:
    state = _tracker
    with state.lock:
        return dict(state.counts)


def reset_lock_tracker() -> None:
    """Clear the order graph, blocking log, and counters (call with no
    monitored locks held — between tests, not mid-flight)."""
    state = _tracker
    with state.lock:
        state.edges.clear()
        state.blocking.clear()
        state.counts.clear()


@contextlib.contextmanager
def force_lock_tracking() -> Iterator[None]:
    """Enable lock tracking inside the scope regardless of the env flag
    and swap in a FRESH tracker state — tests assert on exactly the
    edges their own locks produced, and a seeded-deadlock negative
    control never leaks its cycle into the CI lane's graph. Locks must
    be CREATED inside the scope to be instrumented."""
    global _tracker
    prev = _tracker
    _tracker = _LockTrackerState(forced=True)
    try:
        yield
    finally:
        _tracker = prev


def assert_no_lock_cycles() -> None:
    """Raise :class:`LockOrderViolation` when the observed acquisition
    graph has a cycle — the AB/BA (or longer) inversion that CAN
    deadlock under the right interleaving even if this run survived.
    The error carries one full witness pair per edge of the cycle."""
    publish_lock_counters()
    edges = lock_order_edges()
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    # iterative DFS, white/grey/black
    color: Dict[str, int] = {}
    parent: Dict[str, str] = {}

    def cycle_from(start: str) -> Optional[List[str]]:
        stack = [(start, iter(adj.get(start, ())))]
        color[start] = 1
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if color.get(nxt, 0) == 1:
                    path = [nxt, node]
                    cur = node
                    while cur != nxt:
                        cur = parent[cur]
                        path.append(cur)
                    path.reverse()
                    return path
                if color.get(nxt, 0) == 0:
                    color[nxt] = 1
                    parent[nxt] = node
                    stack.append((nxt, iter(adj.get(nxt, ()))))
                    advanced = True
                    break
            if not advanced:
                color[node] = 2
                stack.pop()
        return None

    for start in list(adj):
        if color.get(start, 0) == 0:
            path = cycle_from(start)
            if path is not None:
                _lock_count("sanitize.lock.cycle")
                lines = [
                    "lock-order cycle: " + " -> ".join(path),
                    "",
                ]
                for a, b in zip(path, path[1:]):
                    held_at, got_at = edges[(a, b)]
                    lines += [
                        f"edge {a} -> {b}:",
                        f"  {a} held at:",
                        *("    " + ln for ln in held_at.splitlines()),
                        f"  {b} acquired at:",
                        *("    " + ln for ln in got_at.splitlines()),
                        "",
                    ]
                raise LockOrderViolation("\n".join(lines))


def assert_no_held_lock_blocking() -> None:
    """Raise :class:`HeldLockBlockingCall` when any blocking call ran
    while a monitored lock was held (see :func:`blocking_region`)."""
    publish_lock_counters()
    violations = held_lock_blocking_violations()
    if violations:
        lines = [f"{len(violations)} blocking call(s) while holding a "
                 "monitored lock:", ""]
        for v in violations:
            lines += [
                f"{v['call']} while holding {v['held']}:",
                *("  " + ln for ln in v["stack"].splitlines()),
                "",
            ]
        raise HeldLockBlockingCall("\n".join(lines))
