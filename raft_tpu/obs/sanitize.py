"""Runtime sanitizer harness — jax-native guards for the test suite.

The static half of the correctness tooling (``tools/graftlint``) catches
what the AST shows; this module wires up what only shows at runtime —
the TPU-native analog of running the reference's tests under
compute-sanitizer (RAFT ci/test.sh) :

- :func:`apply_sanitize_config` — the ``RAFT_TPU_SANITIZE=1`` mode:
  ``jax_numpy_rank_promotion="raise"`` (implicit rank promotion is how
  a [n]-vs-[n,1] slip silently broadcasts into an O(n²) intermediate)
  and ``jax_debug_nans`` (NaNs surface at the op that made them, not
  three layers later in a recall number).
- :func:`no_host_transfers` — scopes
  ``jax.transfer_guard("disallow")`` around a search/build hot path:
  any implicit device↔host round-trip inside raises instead of
  silently serializing the dispatch pipeline. Prepare inputs on device
  BEFORE the scope: eager ``jnp.asarray(host_data)`` and Python-scalar
  lifting inside count as implicit and raise; ``jax.device_get`` /
  ``jax.device_put`` remain allowed.
- :func:`recompile_budget` / :func:`compile_count` — a jit-cache-miss
  counter fed by ``jax.monitoring``'s backend-compile event: a test
  wraps its steady-state calls in ``recompile_budget(0)`` and an
  unexpected retrace fails loudly with the count, instead of costing
  seconds per call in production three PRs later.

Everything here is import-cheap: jax is only imported when a guard is
actually used, and the monitoring listener is installed once on first
use (jax has no per-listener unregister across versions, so the
listener stays; it is a few instructions per compile event).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

from raft_tpu.obs.spans import env_flag

# jax.monitoring event recorded once per backend (XLA) compile — i.e.
# once per jit-cache MISS. Resolved lazily from jax's dispatch module so
# a rename fails loudly here rather than silently counting nothing.
_COMPILE_EVENT_FALLBACK = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_compiles = 0


def _compile_event_name() -> str:
    try:
        from jax._src import dispatch as _dispatch

        return getattr(_dispatch, "BACKEND_COMPILE_EVENT",
                       _COMPILE_EVENT_FALLBACK)
    except Exception:  # pragma: no cover - unknown jax layout
        return _COMPILE_EVENT_FALLBACK


def install_compile_counter() -> None:
    """Register the jit-cache-miss listener (idempotent, stays for the
    process lifetime — jax.monitoring has no stable unregister API)."""
    global _installed
    with _lock:
        if _installed:
            return
        import jax.monitoring

        event_name = _compile_event_name()

        def _on_duration(event: str, duration_secs: float, **kw) -> None:
            global _compiles
            if event == event_name:
                with _lock:
                    _compiles += 1

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True


def compile_count() -> int:
    """Backend compiles observed since :func:`install_compile_counter`."""
    with _lock:
        return _compiles


class RecompileBudgetExceeded(RuntimeError):
    """A scope compiled more programs than its declared budget."""


@contextlib.contextmanager
def recompile_budget(budget: int, what: str = "scope") -> Iterator[None]:
    """Fail if the wrapped scope triggers more than ``budget`` backend
    compiles. ``budget=0`` asserts a fully warm jit cache — the steady-
    state contract for serving hot paths. Install-on-first-use: the
    counter misses compiles that happened before the first budget scope
    in the process, which is fine — budgets measure deltas."""
    install_compile_counter()
    start = compile_count()
    yield
    spent = compile_count() - start
    if spent > budget:
        raise RecompileBudgetExceeded(
            f"{what}: {spent} backend compile(s), budget {budget} — an "
            "unexpected retrace (shape/dtype/static-arg churn or a "
            "non-hashable static) is recompiling the hot path")


@contextlib.contextmanager
def no_host_transfers() -> Iterator[None]:
    """Scope ``jax.transfer_guard("disallow")`` around a hot path:
    implicit device↔host transfers raise. Prepare all inputs on device
    before entering — eager ``jnp.asarray(host_data)`` and Python-scalar
    lifting inside the scope count as implicit and raise; explicit
    ``jax.device_get`` / ``jax.device_put`` stay allowed."""
    import jax

    with jax.transfer_guard("disallow"):
        yield


def apply_sanitize_config() -> None:
    """Apply the ``RAFT_TPU_SANITIZE=1`` jax.config set (rank-promotion
    raise + debug_nans) process-wide. Call before tests import the
    library under test; conftest does this when the env flag is set."""
    import jax

    jax.config.update("jax_numpy_rank_promotion", "raise")
    jax.config.update("jax_debug_nans", True)


def sanitize_enabled() -> bool:
    """True when the suite runs in ``RAFT_TPU_SANITIZE=1`` mode."""
    return env_flag("RAFT_TPU_SANITIZE")
