"""Runtime sanitizer harness — jax-native guards for the test suite.

The static half of the correctness tooling (``tools/graftlint``) catches
what the AST shows; this module wires up what only shows at runtime —
the TPU-native analog of running the reference's tests under
compute-sanitizer (RAFT ci/test.sh) :

- :func:`apply_sanitize_config` — the ``RAFT_TPU_SANITIZE=1`` mode:
  ``jax_numpy_rank_promotion="raise"`` (implicit rank promotion is how
  a [n]-vs-[n,1] slip silently broadcasts into an O(n²) intermediate)
  and ``jax_debug_nans`` (NaNs surface at the op that made them, not
  three layers later in a recall number).
- :func:`no_host_transfers` — scopes
  ``jax.transfer_guard("disallow")`` around a search/build hot path:
  any implicit device↔host round-trip inside raises instead of
  silently serializing the dispatch pipeline. Prepare inputs on device
  BEFORE the scope: eager ``jnp.asarray(host_data)`` and Python-scalar
  lifting inside count as implicit and raise; ``jax.device_get`` /
  ``jax.device_put`` remain allowed.
- :func:`recompile_budget` / :func:`compile_count` — a jit-cache-miss
  counter fed by ``jax.monitoring``'s backend-compile event: a test
  wraps its steady-state calls in ``recompile_budget(0)`` and an
  unexpected retrace fails loudly with the count, instead of costing
  seconds per call in production three PRs later.
- :func:`assert_uniform_collective_schedule` /
  :func:`collective_schedule` — the collective-schedule checker, the
  runtime complement of graftlint's SPMD pass (GL06–GL10): traces a
  program on the 8-device CPU mesh, derives each device's sequence of
  collectives, and raises :class:`CollectiveScheduleDivergence` when
  the schedules can differ across devices (a collective issued in only
  some branches of an ``axis_index``-gated ``lax.cond``/``switch`` —
  exactly the class the AST pass cannot prove absent, and the class
  that deadlocks a real v5e mesh while CPU tests stay green).
- :func:`record_comms_schedule` — records the trace-time sequence of
  comms-facade calls (verb, axis, payload bytes) per traced program,
  so tests can assert WHAT schedule a distributed entry point commits
  every device to.
- :func:`capacity_report` / :func:`assert_billion_safe` — the
  **capacity prover**, the runtime half of graftlint's capacity pass
  (GL11–GL15): traces a program at synthetic billion-scale shapes
  (``jax.ShapeDtypeStruct`` — ``jax.eval_shape`` semantics, zero bytes
  allocated, device-free) and walks the jaxpr for int32-dtyped
  intermediates that index axes ≥ 2³¹ (int32 iota over an oversized
  axis; gather/scatter/dynamic-slice indexing an oversized dim with
  int32 indices) plus peak intermediate bytes.
  ``assert_billion_safe`` raises :class:`CapacityError` with eqn
  provenance — the compile-time ``IdxT`` check the reference gets from
  64-bit index templating, here as a CI gate over the public search /
  build entries (``tools/capacity_prove.py``). x64 is enabled only
  inside a scoped save/restore (:func:`scoped_x64`): the prover never
  leaks ``jax_enable_x64`` into the test process.

Everything here is import-cheap: jax is only imported when a guard is
actually used, and the monitoring listener is installed once on first
use (jax has no per-listener unregister across versions, so the
listener stays; it is a few instructions per compile event).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

from raft_tpu.obs.spans import env_flag

# jax.monitoring event recorded once per backend (XLA) compile — i.e.
# once per jit-cache MISS. Resolved lazily from jax's dispatch module so
# a rename fails loudly here rather than silently counting nothing.
_COMPILE_EVENT_FALLBACK = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_compiles = 0


def _compile_event_name() -> str:
    try:
        from jax._src import dispatch as _dispatch

        return getattr(_dispatch, "BACKEND_COMPILE_EVENT",
                       _COMPILE_EVENT_FALLBACK)
    except Exception:  # pragma: no cover - unknown jax layout
        return _COMPILE_EVENT_FALLBACK


def install_compile_counter() -> None:
    """Register the jit-cache-miss listener (idempotent, stays for the
    process lifetime — jax.monitoring has no stable unregister API)."""
    global _installed
    with _lock:
        if _installed:
            return
        import jax.monitoring

        event_name = _compile_event_name()

        def _on_duration(event: str, duration_secs: float, **kw) -> None:
            global _compiles
            if event == event_name:
                with _lock:
                    _compiles += 1

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True


def compile_count() -> int:
    """Backend compiles observed since :func:`install_compile_counter`."""
    with _lock:
        return _compiles


class RecompileBudgetExceeded(RuntimeError):
    """A scope compiled more programs than its declared budget."""


@contextlib.contextmanager
def recompile_budget(budget: int, what: str = "scope") -> Iterator[None]:
    """Fail if the wrapped scope triggers more than ``budget`` backend
    compiles. ``budget=0`` asserts a fully warm jit cache — the steady-
    state contract for serving hot paths. Install-on-first-use: the
    counter misses compiles that happened before the first budget scope
    in the process, which is fine — budgets measure deltas."""
    install_compile_counter()
    start = compile_count()
    yield
    spent = compile_count() - start
    if spent > budget:
        raise RecompileBudgetExceeded(
            f"{what}: {spent} backend compile(s), budget {budget} — an "
            "unexpected retrace (shape/dtype/static-arg churn or a "
            "non-hashable static) is recompiling the hot path")


@contextlib.contextmanager
def no_host_transfers() -> Iterator[None]:
    """Scope ``jax.transfer_guard("disallow")`` around a hot path:
    implicit device↔host transfers raise. Prepare all inputs on device
    before entering — eager ``jnp.asarray(host_data)`` and Python-scalar
    lifting inside the scope count as implicit and raise; explicit
    ``jax.device_get`` / ``jax.device_put`` stay allowed."""
    import jax

    with jax.transfer_guard("disallow"):
        yield


def apply_sanitize_config() -> None:
    """Apply the ``RAFT_TPU_SANITIZE=1`` jax.config set (rank-promotion
    raise + debug_nans) process-wide. Call before tests import the
    library under test; conftest does this when the env flag is set."""
    import jax

    jax.config.update("jax_numpy_rank_promotion", "raise")
    jax.config.update("jax_debug_nans", True)


def sanitize_enabled() -> bool:
    """True when the suite runs in ``RAFT_TPU_SANITIZE=1`` mode."""
    return env_flag("RAFT_TPU_SANITIZE")


# ---------------------------------------------------------------------------
# collective-schedule checker — the runtime half of graftlint GL06–GL10
# ---------------------------------------------------------------------------

class CollectiveScheduleDivergence(RuntimeError):
    """A traced program's collective schedule can differ across devices
    (a collective appears in only some branches of conditional control
    flow) — the SPMD deadlock/corruption class on a real mesh."""


# Collective primitive base names; version-tolerant prefix matching
# (psum lowers as psum/psum2/psum_invariant depending on jax version).
# Longest-first so psum_scatter is not swallowed by psum. axis_index is
# deliberately absent: it carries no payload and cannot deadlock.
_COLLECTIVE_BASES = (
    "reduce_scatter", "psum_scatter", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "pgather", "pmax", "pmin", "pmean", "psum",
)


def _collective_base(prim_name: str):
    for base in _COLLECTIVE_BASES:
        if prim_name.startswith(base):
            return base
    return None


def _eqn_axes(params) -> tuple:
    axes = params.get("axes", params.get("axis_name"))
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _jaxpr_like(v):
    """Yield raw jaxprs found in an eqn-param value (Jaxpr, ClosedJaxpr,
    or containers of them)."""
    if hasattr(v, "eqns"):
        yield v
    elif hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for item in v:
            yield from _jaxpr_like(item)


def _render_schedule(sched) -> str:
    if not sched:
        return "(no collectives)"
    return ", ".join(
        f"{e[0]}@{','.join(e[1])}{list(e[2])}" if len(e) == 3
        else f"{e[0]}[{_render_schedule(e[1])}]" for e in sched)


def _jaxpr_schedule(jaxpr) -> tuple:
    """Depth-first collective schedule of one jaxpr. ``cond``/``switch``
    branches must commit to IDENTICAL schedules — a device-dependent
    predicate then cannot change what any device executes, which is the
    across-devices uniformity the checker asserts. Loop bodies
    (while/scan) are wrapped as nested entries: their schedule is
    uniform per iteration; trip counts driven by collective-reduced
    values are uniform by construction."""
    sched = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        base = _collective_base(name)
        if base is not None:
            shapes = tuple(str(getattr(v, "aval", v)) for v in eqn.invars)
            sched.append((base, _eqn_axes(eqn.params), shapes))
            continue
        branches = eqn.params.get("branches") if eqn.params else None
        if branches is not None:
            scheds = [_jaxpr_schedule(b) for bb in branches
                      for b in _jaxpr_like(bb)]
            if any(s != scheds[0] for s in scheds[1:]):
                detail = "\n".join(
                    f"  branch {i}: {_render_schedule(s)}"
                    for i, s in enumerate(scheds))
                raise CollectiveScheduleDivergence(
                    f"collective schedule diverges across {name} "
                    f"branches — devices taking different branches "
                    f"would disagree on which collectives run "
                    f"(deadlock/zero-fill on a real mesh):\n{detail}")
            if scheds:
                sched.extend(scheds[0])
            continue
        for sub in _jaxpr_like(list((eqn.params or {}).values())):
            inner = _jaxpr_schedule(sub)
            if not inner:
                continue
            if name in ("while", "scan"):
                sched.append((name, inner))
            else:
                sched.extend(inner)
    return tuple(sched)


def collective_schedule(fn, *args, **kwargs) -> tuple:
    """Trace ``fn(*args, **kwargs)`` (no execution) and return its
    device-uniform collective schedule as a tuple of
    ``(verb, axes, input_avals)`` entries (loops nest as
    ``("while"|"scan", inner)``). Raises
    :class:`CollectiveScheduleDivergence` when conditional branches
    commit different devices to different schedules."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return _jaxpr_schedule(closed.jaxpr)


def assert_uniform_collective_schedule(fn, *args, **kwargs) -> tuple:
    """Alias of :func:`collective_schedule` named for its assertion:
    use in tests to gate distributed entry points in the
    ``RAFT_TPU_SANITIZE=1`` lane."""
    return collective_schedule(fn, *args, **kwargs)


# -- comms-facade schedule recorder -----------------------------------------

_comms_schedule: Optional[list] = None


def comms_schedule_recording() -> bool:
    """True while a :func:`record_comms_schedule` scope is active (one
    module-global read — the facade's fast-path guard)."""
    return _comms_schedule is not None


def note_collective(verb: str, axis: str, nbytes: int) -> None:
    """Hook called by ``parallel.comms.Comms`` at trace time, once per
    collective per traced program (the same per-trace semantics as the
    ``comms.ops`` counters)."""
    rec = _comms_schedule
    if rec is not None:
        rec.append((verb, axis, int(nbytes)))


# ---------------------------------------------------------------------------
# capacity prover — the runtime half of graftlint's capacity pass
# (GL11–GL15): eval_shape-only billion-scale proofs, device-free
# ---------------------------------------------------------------------------

INT32_MAX_INDEX = 2**31 - 1  # largest axis position an int32 id can hold


class CapacityError(RuntimeError):
    """A traced program indexes a ≥ 2³¹ axis through int32-dtyped
    intermediates — the silent-overflow class 64-bit ``IdxT`` templating
    exists to prevent. Carries eqn provenance in the message."""


@contextlib.contextmanager
def scoped_x64(enable: bool = True) -> Iterator[None]:
    """Enable (or disable) ``jax_enable_x64`` for the scope ONLY —
    save/restore, exception-safe. The prover traces int64 id paths, but
    the flag is process-global and silently changes every test's
    dtypes, so it must never leak out of a proof."""
    import jax

    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)


def _is_i32(dtype) -> bool:
    import numpy as _np

    return _np.dtype(dtype) == _np.dtype("int32")


def _eqn_where(eqn) -> str:
    """Best-effort user-frame provenance of one eqn."""
    try:
        tb = eqn.source_info.traceback
        # jax eqn tracebacks are innermost-first: the FIRST non-jax
        # frame is the offending user line (the last would be the
        # prover's own call site)
        for fr in tb.frames:
            fn = getattr(fr, "file_name", "")
            if "site-packages" not in fn and "/jax/" not in fn:
                return (f"{fr.file_name}:{fr.line_num} "
                        f"({fr.function_name})")
    except Exception:
        pass
    return "<unknown site>"


def _aval_bytes(v) -> int:
    import math as _math
    import numpy as _np

    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = _np.dtype(dtype).itemsize
    except TypeError:  # extended dtypes (PRNG keys) have no numpy dtype
        itemsize = getattr(dtype, "itemsize", 0) or 0
    return _math.prod(shape) * itemsize if shape else itemsize


def _scan_eqn(eqn, hits: list) -> None:
    """Record int32-index-over-≥2³¹-axis violations for one eqn."""
    name = eqn.primitive.name
    params = eqn.params or {}

    def hit(msg: str) -> None:
        hits.append({"primitive": name, "where": _eqn_where(eqn),
                     "message": msg})

    if name == "iota":
        shape = params.get("shape") or getattr(
            eqn.outvars[0].aval, "shape", ())
        dim = params.get("dimension", 0)
        if _is_i32(params.get("dtype", "int32")) and shape \
                and shape[dim] - 1 > INT32_MAX_INDEX:
            hit(f"int32 iota over an axis of {shape[dim]} positions — "
                "ids past 2³¹ wrap negative (use core.ids.make_ids)")
    elif name in ("gather", "dynamic_gather"):
        dnums = params.get("dimension_numbers")
        if dnums is None or len(eqn.invars) < 2:
            return
        operand, indices = eqn.invars[0], eqn.invars[1]
        if not _is_i32(getattr(indices.aval, "dtype", None)):
            return
        oshape = getattr(operand.aval, "shape", ())
        for d in getattr(dnums, "start_index_map", ()):
            if d < len(oshape) and oshape[d] - 1 > INT32_MAX_INDEX:
                hit(f"gather indexes operand dim {d} of {oshape[d]} "
                    "rows with int32 indices — rows past 2³¹ are "
                    "unaddressable (thread core.ids.id_dtype through "
                    "the id path)")
    elif name.startswith("scatter"):
        dnums = params.get("dimension_numbers")
        if dnums is None or len(eqn.invars) < 2:
            return
        operand, indices = eqn.invars[0], eqn.invars[1]
        if not _is_i32(getattr(indices.aval, "dtype", None)):
            return
        oshape = getattr(operand.aval, "shape", ())
        for d in getattr(dnums, "scatter_dims_to_operand_dims", ()):
            if d < len(oshape) and oshape[d] - 1 > INT32_MAX_INDEX:
                hit(f"scatter addresses operand dim {d} of {oshape[d]} "
                    "rows with int32 indices")
    elif name in ("dynamic_slice", "dynamic_update_slice"):
        n_lead = 2 if name == "dynamic_update_slice" else 1
        operand = eqn.invars[0]
        oshape = getattr(operand.aval, "shape", ())
        starts = eqn.invars[n_lead:]
        for d, sv in enumerate(starts):
            if d < len(oshape) and oshape[d] - 1 > INT32_MAX_INDEX \
                    and _is_i32(getattr(sv.aval, "dtype", None)):
                hit(f"{name} starts into dim {d} of {oshape[d]} "
                    "positions with an int32 start index")
    elif name == "argmax" or name == "argmin":
        idx_dtype = params.get("index_dtype")
        axes = params.get("axes", ())
        ishape = getattr(eqn.invars[0].aval, "shape", ()) if eqn.invars \
            else ()
        if idx_dtype is not None and _is_i32(idx_dtype):
            for d in axes:
                if d < len(ishape) and ishape[d] - 1 > INT32_MAX_INDEX:
                    hit(f"{name} over an axis of {ishape[d]} positions "
                        "returns int32 positions")
    elif name == "top_k":
        ishape = getattr(eqn.invars[0].aval, "shape", ()) if eqn.invars \
            else ()
        out_i = eqn.outvars[1] if len(eqn.outvars) > 1 else None
        if ishape and ishape[-1] - 1 > INT32_MAX_INDEX and out_i is not None \
                and _is_i32(getattr(out_i.aval, "dtype", None)):
            hit(f"top_k over a {ishape[-1]}-wide axis returns int32 "
                "positions")


def _walk_capacity(jaxpr, hits: list, seen: set, stats: dict) -> None:
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        _scan_eqn(eqn, hits)
        out_bytes = sum(_aval_bytes(v) for v in eqn.outvars)
        in_bytes = sum(_aval_bytes(v) for v in eqn.invars
                       if hasattr(v, "aval"))
        stats["peak_intermediate_bytes"] = max(
            stats["peak_intermediate_bytes"], out_bytes + in_bytes)
        for sub in _jaxpr_like(list((eqn.params or {}).values())):
            _walk_capacity(sub, hits, seen, stats)


def capacity_report(fn, *abstract_args, **abstract_kwargs) -> dict:
    """Device-free capacity analysis of ``fn`` at synthetic shapes.

    ``abstract_args`` are ``jax.ShapeDtypeStruct`` pytrees (real arrays
    work too but defeat the point — the prover exists so SIFT-1B shapes
    cost zero bytes). Traces via ``jax.make_jaxpr`` (the same
    no-execution semantics as ``jax.eval_shape``) under a scoped-x64
    context and walks every sub-jaxpr (pjit/shard_map/scan/while/cond)
    for int32-dtyped intermediates indexing axes ≥ 2³¹.

    Returns ``{"violations": [{primitive, where, message}, ...],
    "peak_intermediate_bytes": int, "out_shapes": [...]}`` — use
    :func:`assert_billion_safe` as the raising gate.

    Two violation channels: (a) the jaxpr walk finds int32 iota /
    gather / scatter / dynamic-slice / arg-select eqns over oversized
    axes; (b) jax itself refuses to NORMALIZE an int32 index against a
    ≥ 2³¹ axis at trace time (``OverflowError: Python integer … out of
    bounds for int32`` from ``jnp``-level indexing) — the same overflow
    class surfacing earlier, reported with the offending user frame
    instead of propagating as a confusing trace crash."""
    import jax

    hits: list = []
    stats = {"peak_intermediate_bytes": 0}
    try:
        with scoped_x64(True):
            closed = jax.make_jaxpr(fn)(*abstract_args, **abstract_kwargs)
    except OverflowError as e:
        import traceback as _tb

        where = "<unknown site>"
        for fr in _tb.extract_tb(e.__traceback__):
            if "site-packages" not in fr.filename \
                    and "/jax/" not in fr.filename:
                where = f"{fr.filename}:{fr.lineno} ({fr.name})"
        hits.append({
            "primitive": "trace", "where": where,
            "message": f"int32 index cannot address the axis: {e} "
                       "(thread core.ids.id_dtype through the id path)"})
        return {"violations": hits, "peak_intermediate_bytes": 0,
                "out_shapes": []}
    _walk_capacity(closed.jaxpr, hits, set(), stats)
    return {
        "violations": hits,
        "peak_intermediate_bytes": stats["peak_intermediate_bytes"],
        "out_shapes": [str(getattr(v, "aval", v))
                       for v in closed.jaxpr.outvars],
    }


def assert_billion_safe(fn, *abstract_args, what: str = "program",
                        **abstract_kwargs) -> dict:
    """Trace ``fn`` at the given (billion-scale) abstract shapes and
    raise :class:`CapacityError` listing every int32-indexes-≥2³¹-axis
    eqn with provenance; returns the :func:`capacity_report` dict when
    clean. The CI gate (``tools/capacity_prove.py``) runs this over the
    four index search entries, the sharded merge tier, and
    ``build_chunked``'s assignment/encode pass."""
    report = capacity_report(fn, *abstract_args, **abstract_kwargs)
    if report["violations"]:
        detail = "\n".join(
            f"  [{v['primitive']}] {v['message']}\n      at {v['where']}"
            for v in report["violations"])
        raise CapacityError(
            f"{what}: {len(report['violations'])} int32 capacity "
            f"violation(s) at billion-scale shapes:\n{detail}")
    return report


@contextlib.contextmanager
def record_comms_schedule() -> Iterator[list]:
    """Record the trace-time sequence of comms-facade calls —
    ``(verb, axis, payload_bytes)`` per collective, in program order.
    Under SPMD every device executes the one traced program, so this IS
    each device's schedule; pair with
    :func:`assert_uniform_collective_schedule` to also rule out
    conditionally-divergent collectives the recorder (which sees both
    branches at trace time) cannot distinguish."""
    global _comms_schedule
    prev = _comms_schedule
    _comms_schedule = rec = []
    try:
        yield rec
    finally:
        _comms_schedule = prev
