"""Index-health introspection — structural quality stats for IVF indexes.

The quality plane's static half (ISSUE 16). An IVF index can be
*served* perfectly and still be *sick*: skewed lists turn n_probes into
a lottery (the probed mass varies per query), dead centroids waste
probe budget, centroid drift after many ``extend()`` rounds makes the
coarse quantizer lie about where points live, and a PQ codebook that
fits the build-time distribution poorly quantizes every residual badly.
All of these degrade recall *before* any latency symptom shows.

This module computes those stats host-side (numpy only — no jax import,
no chip work, safe to call from serving control paths):

- :func:`list_stats` — per-list size skew: CV (std/mean), max/mean
  ratio, dead-list count. The compaction trigger ROADMAP item 1 reads.
- :func:`centroid_drift` — ‖mean(assigned points) − centroid‖ per list
  (IVF-Flat: exact from packed rows; IVF-PQ: the decoded-residual mean,
  which equals the drift in rotated space since point = center +
  residual). Drift grows as ``extend()`` appends without re-training.
- :func:`pq_subspace_error` — per-subspace quantization MSE over a
  dataset sample re-encoded through the index's own rotation/codebooks.
  The distribution (not just the mean) matters: one bad subspace
  poisons every distance estimate that crosses it.
- :func:`tombstone_density` — deleted-slot fraction. Zero today (no
  delete path yet); this is the hook ROADMAP item 1's compactor will
  read, wired now so dashboards and ``/indexz`` have the series from
  day one.
- :func:`describe_index` — one JSON-ready dict of all of the above;
  what the registry caches at admission, ``/indexz`` renders, and
  ``obsdump`` tables.
- :func:`note_index_stats` — gauge emission (``index.*{index=}``) when
  obs recording is on; build/extend paths call the cheap subset.

Duck-typed over the index objects (``list_sizes`` + either
``packed_data`` or ``packed_codes``): no neighbors import, so the obs
layer stays below the algorithm layer.
"""

from __future__ import annotations

import math
import sys
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "list_stats", "centroid_drift", "pq_subspace_error",
    "tombstone_density", "describe_index", "note_index_stats",
    "note_tier_bytes",
]


def list_stats(list_sizes: Any) -> Dict[str, Any]:
    """Size-skew stats from a ``[n_lists]`` size vector: CV, max/mean,
    dead-list count. Cheap — one small host transfer — so build paths
    can afford it unconditionally when obs is on."""
    sizes = np.asarray(list_sizes, dtype=np.float64).reshape(-1)
    n_lists = int(sizes.size)
    total = float(sizes.sum())
    mean = total / n_lists if n_lists else 0.0
    mx = float(sizes.max()) if n_lists else 0.0
    std = float(sizes.std()) if n_lists else 0.0
    return {
        "n_lists": n_lists,
        "size": int(total),
        "mean": mean,
        "max": int(mx),
        "cv": (std / mean) if mean > 0 else 0.0,
        "max_mean": (mx / mean) if mean > 0 else 0.0,
        "dead": int((sizes == 0).sum()),
    }


def _sample_lists(sizes: np.ndarray, max_lists: int) -> np.ndarray:
    """Deterministic evenly-strided sample of the non-empty lists."""
    live = np.flatnonzero(sizes > 0)
    if live.size <= max_lists:
        return live
    stride = live.size / float(max_lists)
    return live[(np.arange(max_lists) * stride).astype(np.int64)]


def _unpack_codes_np(packed: np.ndarray, pq_dim: int,
                     pq_bits: int) -> np.ndarray:
    """Host unpack ``[..., nbytes] u8 → [..., pq_dim] u8`` — the numpy
    twin of ``ivf_pq.unpack_bits`` (same little-endian bit layout as
    ``pack_bits_np``), kept here so introspection never imports jax."""
    if pq_bits == 8:
        return packed[..., :pq_dim]
    nbytes = packed.shape[-1]
    s = np.arange(pq_dim)
    byte_idx = (s * pq_bits) // 8
    off = ((s * pq_bits) % 8).astype(np.uint16)
    p16 = packed.astype(np.uint16)
    lo = p16[..., byte_idx]
    hi_idx = np.minimum(byte_idx + 1, nbytes - 1)
    hi = np.where(byte_idx + 1 < nbytes, p16[..., hi_idx], 0)
    val = ((lo | (hi << np.uint16(8))) >> off) & ((1 << pq_bits) - 1)
    return val.astype(np.uint8)


def _host_codes(index: Any) -> np.ndarray:
    """Host copy of ``packed_codes`` as ``[n_lists, L, nbytes]``
    (unfolding the lane-folded storage layout). One transfer — per-list
    device indexing would pay a dispatch per list."""
    c = np.asarray(index.packed_codes)
    if getattr(index, "codes_folded", False):
        L = index.packed_ids.shape[1]
        c = c.reshape(c.shape[0], L, -1)
    return c


def centroid_drift(index: Any, max_lists: int = 256
                   ) -> Optional[Dict[str, Any]]:
    """Per-list ‖mean(assigned points) − centroid‖, summarized over an
    evenly-strided sample of ≤ ``max_lists`` non-empty lists.

    IVF-Flat: exact, in the original space. IVF-PQ: the decoded
    residual mean per list — since every point is stored as
    center + residual, the rotated-space drift IS the mean residual
    (quantization error biases it slightly; fine for a health gauge).
    Returns None for index types carrying neither packed rows nor
    packed codes. ``rel_mean`` normalizes by the RMS centroid norm so
    the gauge is comparable across datasets of different scale."""
    sizes = np.asarray(index.list_sizes, dtype=np.int64).reshape(-1)
    pick = _sample_lists(sizes, max_lists)
    if pick.size == 0:
        return {"lists_sampled": 0, "mean": 0.0, "max": 0.0,
                "rel_mean": 0.0}
    drifts = np.zeros(pick.size, np.float64)
    if hasattr(index, "packed_data"):
        centers = np.asarray(index.centers, np.float64)
        packed = np.asarray(index.packed_data)
        for j, li in enumerate(pick):
            rows = packed[int(li)][:sizes[li]].astype(np.float64)
            drifts[j] = float(np.linalg.norm(rows.mean(axis=0)
                                             - centers[int(li)]))
        scale = float(np.sqrt(np.mean(centers ** 2.0) * centers.shape[1]))
    elif hasattr(index, "packed_codes"):
        codebooks = np.asarray(index.codebooks, np.float64)
        per_subspace = getattr(index, "codebook_kind",
                               "per_subspace") == "per_subspace"
        S, P = index.pq_dim, index.pq_len
        packed = _host_codes(index)
        for j, li in enumerate(pick):
            codes = _unpack_codes_np(packed[int(li)], S,
                                     index.pq_bits)[:sizes[li]]
            cb = codebooks if per_subspace else codebooks[int(li)]
            if per_subspace:
                dec = cb[np.arange(S), codes.astype(np.int64)]
            else:
                dec = cb[codes.astype(np.int64)]
            drifts[j] = float(np.linalg.norm(
                dec.reshape(codes.shape[0], S * P).mean(axis=0)))
        centers_rot = np.asarray(index.centers_rot, np.float64)
        scale = float(np.sqrt(np.mean(centers_rot ** 2.0)
                              * centers_rot.shape[1]))
    else:
        return None
    mean = float(drifts.mean())
    return {"lists_sampled": int(pick.size), "mean": mean,
            "max": float(drifts.max()),
            "rel_mean": (mean / scale) if scale > 0 else 0.0}


def pq_subspace_error(index: Any, dataset: Any, sample_rows: int = 2048,
                      seed: int = 0) -> Optional[Dict[str, Any]]:
    """Per-subspace PQ quantization MSE over a dataset sample, re-encoded
    through the index's own rotation/assignment/codebooks (numpy mirror
    of the build's encode path; assignment is nearest-center in the
    metric's working space — vectors are unit-normalized first for the
    spherical metrics, matching the build). None for non-PQ indexes or
    when no dataset is at hand."""
    if not hasattr(index, "packed_codes") or dataset is None:
        return None
    x = np.asarray(dataset, np.float32)
    if x.ndim != 2 or x.shape[0] == 0:
        return None
    if x.shape[0] > sample_rows:
        rng = np.random.default_rng(seed)
        x = x[np.sort(rng.choice(x.shape[0], sample_rows, replace=False))]
    metric = str(getattr(index, "metric", "sqeuclidean"))
    if metric in ("inner_product", "cosine"):
        x = x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-12)
    centers = np.asarray(index.centers, np.float32)
    # nearest-center assignment (expanded L2 — the argmin matches
    # sqeuclidean; for spherical metrics the rows above are normalized,
    # where L2-nearest and cosine-nearest coincide up to center norms)
    d2 = (np.sum(x * x, axis=1, keepdims=True)
          - 2.0 * (x @ centers.T)
          + np.sum(centers * centers, axis=1)[None, :])
    labels = np.argmin(d2, axis=1)
    rot = np.asarray(index.rotation, np.float32)
    res = x @ rot.T - np.asarray(index.centers_rot, np.float32)[labels]
    S, P = index.pq_dim, index.pq_len
    m = res.shape[0]
    sub = res.reshape(m, S, P).astype(np.float64)
    codebooks = np.asarray(index.codebooks, np.float64)
    per_subspace = getattr(index, "codebook_kind",
                           "per_subspace") == "per_subspace"
    errs = np.zeros(S, np.float64)
    for s in range(S):
        if per_subspace:
            cb = codebooks[s][None]                   # [1, K, P]
        else:
            cb = codebooks[labels]                     # [m, K, P]
        diff = sub[:, s, None, :] - cb                 # [m, K, P]
        errs[s] = float(np.min(np.sum(diff * diff, axis=-1),
                               axis=-1).mean())
    total = float(np.sum(sub * sub) / max(m, 1))
    return {"rows_sampled": m, "pq_dim": S,
            "per_subspace_mse": [round(float(e), 8) for e in errs],
            "mean": float(errs.mean()), "max": float(errs.max()),
            # fraction of residual energy lost to quantization — the
            # scale-free number to alert on
            "rel_error": float(errs.sum() / total) if total > 0 else 0.0}


def tombstone_density(index: Any) -> float:
    """Deleted-slot fraction. There is no delete path yet, so this is
    identically 0.0 — the gauge exists NOW so ROADMAP item 1's
    compactor (and its dashboards) land on a series with history."""
    return 0.0


def describe_index(index: Any, dataset: Any = None, *,
                   sample_rows: int = 2048, max_lists: int = 256,
                   seed: int = 0) -> Dict[str, Any]:
    """One JSON-ready health snapshot: list skew + drift (+ PQ
    quantization error when a dataset sample is available). Never
    raises — a stats failure must not block admission or a scrape; the
    error rides the dict instead."""
    out: Dict[str, Any] = {"kind": type(index).__name__}
    try:
        out["lists"] = list_stats(index.list_sizes)
        out["dim"] = int(getattr(index, "dim", 0))
        out["tombstone_density"] = tombstone_density(index)
        out["drift"] = centroid_drift(index, max_lists=max_lists)
        out["pq"] = pq_subspace_error(index, dataset,
                                      sample_rows=sample_rows, seed=seed)
    except Exception as e:  # noqa: BLE001 — introspection is best-effort
        out["error"] = repr(e)
    return out


def _gauges_from(stats: Dict[str, Any]) -> Dict[str, float]:
    """Flatten a :func:`describe_index` dict into the ``index.*`` gauge
    values (only the numeric summaries — distributions stay in the
    dict/``/indexz``, never as unbounded label sets)."""
    g: Dict[str, float] = {}
    lists = stats.get("lists") or {}
    if lists:
        g["index.n_lists"] = float(lists.get("n_lists", 0))
        g["index.size"] = float(lists.get("size", 0))
        g["index.list_cv"] = float(lists.get("cv", 0.0))
        g["index.list_max_mean"] = float(lists.get("max_mean", 0.0))
        g["index.dead_lists"] = float(lists.get("dead", 0))
    g["index.tombstone_density"] = float(
        stats.get("tombstone_density", 0.0))
    drift = stats.get("drift")
    if drift:
        g["index.drift_mean"] = float(drift.get("mean", 0.0))
        g["index.drift_max"] = float(drift.get("max", 0.0))
        g["index.drift_rel"] = float(drift.get("rel_mean", 0.0))
    pq = stats.get("pq")
    if pq:
        g["index.pq_err_mean"] = float(pq.get("mean", 0.0))
        g["index.pq_err_max"] = float(pq.get("max", 0.0))
        g["index.pq_err_rel"] = float(pq.get("rel_error", 0.0))
    return g


def note_tier_bytes(name: str, *, hbm_bytes: int, host_bytes: int) -> None:
    """Publish one index's memory-tier byte split as
    ``index.bytes{index=name,tier=hbm|host}`` gauges (ISSUE 17) — the
    admission-math companion of the ``index.*`` health family: a
    tenant whose raw vectors were demoted to host shows its HBM gauge
    drop (and the host gauge rise) the moment the registry moves them,
    so "who is actually holding HBM?" is one query. Same emission
    contract as :func:`note_index_stats`: no-op when obs recording is
    off, failures swallowed."""
    spans = sys.modules.get("raft_tpu.obs.spans")
    if spans is None or not spans.enabled():
        return
    try:
        reg = spans.registry()
        for tier, value in (("hbm", hbm_bytes), ("host", host_bytes)):
            reg.gauge("index.bytes",
                      labels={"index": name, "tier": tier}
                      ).set(float(value))
    except Exception:  # noqa: BLE001 — gauges must never fail the mover
        pass


def note_index_stats(index: Any, *, name: str, dataset: Any = None,
                     cheap: bool = False,
                     stats: Optional[Dict[str, Any]] = None
                     ) -> Optional[Dict[str, Any]]:
    """Compute (or reuse ``stats``) and publish ``index.*{index=name}``
    gauges. ``cheap=True`` restricts to the O(n_lists) subset — what
    build/extend afford inline; admission/on-demand callers take the
    full describe. No-op (returns None) when obs recording is off and
    no precomputed ``stats`` were handed in — the build-path contract
    is one ``enabled()`` check when off. Emission failures are
    swallowed: stats must never fail the build that produced the index.
    Uses ``sys.modules`` for the spans lookup (same pattern as
    ``robust.faults``) so this module stays importable standalone."""
    spans = sys.modules.get("raft_tpu.obs.spans")
    recording = spans is not None and spans.enabled()
    if stats is None:
        if not recording:
            return None
        try:
            if cheap:
                stats = {"kind": type(index).__name__,
                         "lists": list_stats(index.list_sizes),
                         "tombstone_density": tombstone_density(index)}
            else:
                stats = describe_index(index, dataset)
        except Exception:  # noqa: BLE001 — never fail the producer
            return None
    if recording:
        try:
            reg = spans.registry()
            for gname, value in _gauges_from(stats).items():
                if math.isfinite(value):
                    reg.gauge(gname, labels={"index": name}).set(value)
        except Exception:  # noqa: BLE001
            pass
    return stats
