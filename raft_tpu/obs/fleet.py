"""Fleet aggregation — one view over a pod's per-host dumps (ISSUE 15).

Pod runs (the MULTICHIP build/search legs, a real v5e-64 job) emit one
flight dump per host process; until now nobody could correlate them —
"host 3's dump looks slow" was the whole analysis. This module is the
device-free aggregator:

- **identity**: every flight dump now carries a ``fleet`` stamp
  (:func:`identity`): a shared ``run_id`` (``RAFT_TPU_RUN_ID``, minted
  per process when unset), host name, pid, optional ``RAFT_TPU_RANK``,
  and a clock anchor pair.
- **clock alignment**: hosts' wall clocks disagree and monotonic
  epochs are per-boot. Each dump records ``(wall_s, mono_s)`` at dump
  time plus the shared wall anchor the launcher exported
  (``RAFT_TPU_RUN_ANCHOR`` — one ``time.time()`` stamped once, before
  the per-host processes fork). The aggregator re-expresses every
  event on one run-relative axis: ``ts − anchor`` when the anchor is
  present (cross-host alignment up to NTP discipline), else
  ``ts − min(wall)`` (same-host multi-process runs — the dryrun — are
  exact either way). The ``(wall − mono)`` residual per dump is
  reported as ``clock_skew_s`` so a stepped wall clock is visible
  instead of silently bending the timeline.
- **merging**: events fold into one timeline with host/pid attached to
  every event and colliding pids remapped — the same policy as
  :func:`raft_tpu.obs.trace.merge` (which still serves raw
  Chrome-trace files); metrics counters sum across hosts with a
  ``host=`` label preserved per series in the per-host section.
- **straggler attribution**: per-host collective timing comes from the
  ``comms.*`` span events (host-side timed dispatches of collective-
  bearing programs — e.g. the distributed build's ``comms.allgatherv``
  spans). For each collective family the table names the slowest host,
  its mean, the fleet mean, and the skew fraction
  ``(slowest − fleet_mean) / fleet_mean`` — the "which device is
  dragging the pod" answer the reference gets from nsys timelines.

``tools/obsdump.py --fleet dump1.json dump2.json …`` renders the
result; ``__graft_entry__``'s MULTICHIP fleet leg asserts it end-to-end
on the 8-dev dryrun. Stdlib-only; import-cheap (no jax).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from raft_tpu.obs import sanitize as _sanitize

SCHEMA = "raft_tpu.fleet/1"

RUN_ID_ENV = "RAFT_TPU_RUN_ID"
ANCHOR_ENV = "RAFT_TPU_RUN_ANCHOR"
RANK_ENV = "RAFT_TPU_RANK"

#: span-event name prefixes that count as collective timing for the
#: straggler table (``comms.allgatherv``, ``comms.ring_topk``, ...)
COLLECTIVE_PREFIXES = ("comms.",)

_minted_lock = _sanitize.monitored_lock("obs.fleet.minted")
_minted_run_id: Optional[str] = None


def run_id() -> str:
    """The process's run id: ``RAFT_TPU_RUN_ID`` when the launcher
    exported one (the pod case — every host shares it), else minted
    once per process."""
    rid = os.environ.get(RUN_ID_ENV, "").strip()  # id value, not a flag
    if rid:
        return rid
    global _minted_run_id
    with _minted_lock:
        if _minted_run_id is None:
            _minted_run_id = os.urandom(6).hex()
        return _minted_run_id


def rank() -> Optional[int]:
    """``RAFT_TPU_RANK`` (the launcher's per-host index), or None."""
    raw = os.environ.get(RANK_ENV, "").strip()  # numeric value
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


def anchor_wall_s() -> Optional[float]:
    """The shared wall anchor (``RAFT_TPU_RUN_ANCHOR`` — the launcher's
    ``time.time()`` exported to every host), or None."""
    raw = os.environ.get(ANCHOR_ENV, "").strip()  # numeric value
    try:
        return float(raw) if raw else None
    except ValueError:
        return None


def identity() -> Dict[str, Any]:
    """The fleet identity stamp :mod:`raft_tpu.obs.flight` folds into
    every dump (host/process identity + run id + clock anchor)."""
    return {
        "run_id": run_id(),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "rank": rank(),
        "anchor_wall_s": anchor_wall_s(),
        "wall_s": time.time(),
        "mono_s": time.monotonic(),
    }


def host_tag(fleet: Dict[str, Any]) -> str:
    """Stable display key for one dump's process: ``rank<r>`` when the
    launcher assigned ranks, else ``host:pid``."""
    r = fleet.get("rank")
    if r is not None:
        return f"rank{r}"
    return f"{fleet.get('host', '?')}:{fleet.get('pid', '?')}"


def _load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def clock_offset(fleet: Dict[str, Any], fallback_t0: float) -> float:
    """Seconds to subtract from this dump's wall timestamps to land on
    the run-relative axis: the shared anchor when present, else the
    fleet-wide fallback (min wall across dumps)."""
    anchor = fleet.get("anchor_wall_s")
    return float(anchor) if anchor is not None else float(fallback_t0)


def collective_family(name: str) -> Optional[str]:
    """The collective family of a span-event name, or None when the
    event is not collective timing. Spans dot-join under their caller's
    stack (``ivf_pq.build_distributed.comms.allgatherv``), so the
    family is the suffix from the first ``comms.`` segment — one family
    per collective verb regardless of which entry issued it."""
    for p in COLLECTIVE_PREFIXES:
        i = name.find(p)
        if i == 0 or (i > 0 and name[i - 1] == "."):
            return name[i:]
    return None


def straggler_table(events_by_host: Dict[str, List[Dict[str, Any]]]
                    ) -> List[Dict[str, Any]]:
    """Per-collective imbalance across hosts. Input: aligned span
    events per host tag. For every ``comms.*`` span family seen on ≥ 1
    host: per-host mean duration, the slowest host, and
    ``skew_frac = (slowest_mean − fleet_mean) / fleet_mean`` (0 when
    perfectly balanced). Sorted worst-skew first."""
    per: Dict[str, Dict[str, List[float]]] = {}
    for host, events in events_by_host.items():
        for e in events:
            if e.get("ph") != "X":
                continue
            fam = collective_family(e.get("name", ""))
            if fam is None:
                continue
            per.setdefault(fam, {}).setdefault(host, []).append(
                float(e.get("dur", 0.0)))
    rows: List[Dict[str, Any]] = []
    for name, by_host in sorted(per.items()):
        means = {h: sum(ds) / len(ds) for h, ds in by_host.items() if ds}
        if not means:
            continue
        fleet_mean = sum(means.values()) / len(means)
        slowest = max(means, key=lambda h: means[h])
        skew = ((means[slowest] - fleet_mean) / fleet_mean
                if fleet_mean > 0 else 0.0)
        rows.append({
            "collective": name,
            "hosts": len(means),
            "count": sum(len(ds) for ds in by_host.values()),
            "slowest": slowest,
            "slowest_mean_s": round(means[slowest], 6),
            "fleet_mean_s": round(fleet_mean, 6),
            "skew_frac": round(skew, 4),
            "per_host_mean_s": {h: round(m, 6)
                                for h, m in sorted(means.items())},
        })
    rows.sort(key=lambda r: -r["skew_frac"])
    return rows


def aggregate(paths: Iterable[str]) -> Dict[str, Any]:
    """Merge per-host flight dumps into one fleet view.

    Returns ``{"schema", "run_id", "run_ids", "hosts": [...],
    "events": [...], "counters": {...}, "stragglers": [...]}`` —
    events clock-aligned (run-relative ``ts``, each stamped with its
    ``host`` tag and a collision-free ``pid``), counters summed across
    hosts (per-host values preserved under ``hosts[i].counters``), and
    the straggler table computed from the ``comms.*`` span events.
    Dumps from different run_ids still merge (``run_ids`` lists them;
    callers that require one run assert on it) — a triage host should
    never refuse to read what it was handed. Several dumps from ONE
    process (periodic checkpoints + a final dump — all cumulative
    snapshots of the same registry and ring) dedupe: overlapping ring
    events count once, the process keeps one merged pid, and its
    latest dump's counters stand in for the process in the fleet
    totals (per-file raw counters stay under ``hosts[i].counters``)."""
    docs: List[Dict[str, Any]] = []
    for p in paths:
        doc = _load(p)
        doc["_path"] = p
        docs.append(doc)
    if not docs:
        return {"schema": SCHEMA, "run_id": None, "run_ids": [],
                "hosts": [], "events": [], "counters": {},
                "stragglers": []}
    fleets = [d.get("fleet") or {} for d in docs]
    run_ids = sorted({f.get("run_id") for f in fleets
                      if f.get("run_id")})
    # run-relative axis: shared anchor preferred, else the earliest
    # process start (wall − uptime), paired per dump — a dump without a
    # fleet stamp (pre-ISSUE-15) contributes nothing here but still
    # merges with zero offset against its siblings' origin
    origins = [f["wall_s"] - (d.get("uptime_s") or 0.0)
               for f, d in zip(fleets, docs) if f.get("wall_s")]
    fallback_t0 = min(origins, default=0.0)
    used_pids: set = set()
    hosts: List[Dict[str, Any]] = []
    merged_events: List[Dict[str, Any]] = []
    events_by_host: Dict[str, List[Dict[str, Any]]] = {}
    # cumulative-snapshot dedup: one PROCESS may contribute several
    # dumps (periodic checkpoints + a final/signal dump), and each is a
    # cumulative snapshot of the same registry and the same event ring.
    # Per (host, pid) process group: events dedupe on their identity
    # tuple (the ring contents overlap between dumps), the process
    # keeps ONE merged pid, and counters take the LATEST dump's values
    # (a cumulative snapshot supersedes every earlier one).
    merged_pid_by_proc: Dict[Any, int] = {}
    seen_events_by_proc: Dict[Any, set] = {}
    proc_counters: Dict[Any, tuple] = {}  # proc -> (wall, counters)
    first_skew_by_proc: Dict[Any, float] = {}
    for doc, fleet in zip(docs, fleets):
        tag = host_tag(fleet) if fleet else os.path.basename(
            doc.get("_path", "?"))
        offset = clock_offset(fleet, fallback_t0)
        pid = int(fleet.get("pid") or doc.get("pid") or 0)
        proc = (fleet.get("host", doc.get("host")), pid)
        new_pid = merged_pid_by_proc.get(proc)
        if new_pid is None:
            new_pid = pid
            while new_pid in used_pids:
                new_pid += 1  # the PR-5 merge() pid-collision policy
            used_pids.add(new_pid)
            merged_pid_by_proc[proc] = new_pid
        seen = seen_events_by_proc.setdefault(proc, set())
        aligned: List[Dict[str, Any]] = []
        for e in doc.get("events", []):
            # identity includes the args payload: two DISTINCT markers
            # can legitimately share (name, ts, dur, tid) — e.g. two
            # degrade.step events in the same rounded millisecond —
            # and must both survive; only true ring overlap dedupes
            ident = (e.get("ph"), e.get("name"), e.get("ts"),
                     e.get("dur"), e.get("tid"), e.get("value"),
                     json.dumps(e.get("args"), sort_keys=True,
                                default=str))
            if ident in seen:
                continue  # the same ring entry from an earlier dump
            seen.add(ident)
            e = dict(e)
            if "ts" in e:
                e["ts"] = float(e["ts"]) - offset
            e["host"] = tag
            e["pid"] = new_pid
            aligned.append(e)
        aligned.sort(key=lambda e: e.get("ts", 0.0))
        merged_events.extend(aligned)
        # extend, never assign: a process's every dump contributes its
        # (deduped) events to the straggler computation
        events_by_host.setdefault(tag, []).extend(aligned)
        host_counters = (doc.get("metrics") or {}).get("counters", {})
        wall = float(fleet.get("wall_s") or 0.0)
        prior = proc_counters.get(proc)
        if prior is None or wall >= prior[0]:
            proc_counters[proc] = (wall, host_counters)
        mono = fleet.get("mono_s")
        wall = fleet.get("wall_s")
        skew = (wall - mono if wall is not None and mono is not None
                else None)
        # wall − mono is constant per boot; a CHANGE between two dumps
        # of one process means the wall clock stepped mid-run — that
        # drift (not the boot-epoch-sized absolute) is the signal
        drift = None
        if skew is not None:
            first = first_skew_by_proc.setdefault(proc, skew)
            drift = skew - first
        hosts.append({
            "tag": tag,
            "path": doc.get("_path"),
            "host": fleet.get("host", doc.get("host")),
            "pid": pid,
            "merged_pid": new_pid,
            "rank": fleet.get("rank"),
            "run_id": fleet.get("run_id"),
            "offset_s": offset,
            "clock_skew_s": (round(skew, 6) if skew is not None
                             else None),
            "clock_drift_s": (round(drift, 6) if drift is not None
                              else None),
            "events": len(aligned),
            "dropped_events": doc.get("dropped_events", 0),
            "counters": dict(host_counters),
            "reason": doc.get("reason"),
        })
    merged_events.sort(key=lambda e: e.get("ts", 0.0))
    counters: Dict[str, float] = {}
    for _, host_counters in proc_counters.values():
        for key, v in host_counters.items():
            counters[key] = counters.get(key, 0.0) + float(v)
    return {
        "schema": SCHEMA,
        "run_id": run_ids[0] if len(run_ids) == 1 else None,
        "run_ids": run_ids,
        "hosts": hosts,
        "events": merged_events,
        "counters": counters,
        "stragglers": straggler_table(events_by_host),
    }


#: producer stamp for Chrome exports — the literal (not an import of
#: obs.trace) so a jax-less triage host can spec-load this file alone
PRODUCER = "raft_tpu.obs.trace"


def export_chrome(view: Dict[str, Any], path: str) -> int:
    """Render an :func:`aggregate` view as one Perfetto-loadable
    Chrome trace (µs timestamps, one process track per host tag)."""
    events: List[Dict[str, Any]] = []
    seen_pids: Dict[int, str] = {}
    for e in view.get("events", []):
        pid = int(e.get("pid", 0))
        seen_pids.setdefault(pid, e.get("host", str(pid)))
    for pid, name in sorted(seen_pids.items()):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": name}})
    for e in view.get("events", []):
        pid = int(e.get("pid", 0))
        if e.get("ph") == "X":
            ev = {"name": e.get("name", "?"), "ph": "X", "pid": pid,
                  "tid": e.get("tid", 0),
                  "ts": float(e.get("ts", 0.0)) * 1e6,
                  "dur": float(e.get("dur", 0.0)) * 1e6}
            if e.get("args"):
                ev["args"] = e["args"]
            events.append(ev)
        elif e.get("ph") == "C":
            events.append({"name": e.get("name", "?"), "ph": "C",
                           "pid": pid, "tid": 0,
                           "ts": float(e.get("ts", 0.0)) * 1e6,
                           "args": {"value": e.get("value", 0.0)}})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"producer": PRODUCER,
                         "schema": SCHEMA,
                         "run_id": view.get("run_id")}}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return len(events)
