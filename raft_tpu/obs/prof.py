"""Cost attribution — XLA compiled-program analysis + roofline classing.

The obs stack's first two legs (PRs 1 and 5) say *how long* a stage
took; this leg says whether that time is anywhere near the hardware
limit. The reference gets per-kernel attribution from nsys/NVTX; the
TPU-native equivalents are XLA's compiled cost model
(``Compiled.cost_analysis()`` / ``memory_analysis()``) and the
programmatic ``jax.profiler`` bracket — both wrapped here,
version-tolerant and CPU-degrading like :mod:`raft_tpu.obs.hbm` (every
helper returns ``{}``/``None`` instead of raising, so instrumented
code runs identically on the CPU test mesh).

Pieces:

- :func:`cost_analysis` / :func:`memory_analysis` — normalize the
  ``Compiled`` accessors across jax versions (dict vs list-of-dict vs
  absent) into plain dicts;
- :func:`device_peak` — a peak flops/HBM-bandwidth table per device
  kind (v5e/v5p/v4 + an explicit CPU placeholder) with the roofline
  ridge point ``peak_flops / peak_bw``;
- :func:`analyze_compiled` / :func:`analyze_jit` — derive per-program
  flops, bytes-accessed, and arithmetic intensity, classify memory- vs
  compute-bound against the peak table, and (given a measured elapsed
  time) the achieved-bandwidth / achieved-flops fractions;
- :func:`record` — emit ``prof.flops`` / ``prof.bytes`` /
  ``prof.arith_intensity`` / ``prof.achieved_bw_frac`` gauges (plus a
  labeled ``prof.bound`` marker) into a metrics registry — the series
  ``tools/obsdump.py`` renders and the bench detail rows are built
  from;
- :class:`capture` — a start/stop programmatic profiler bracket
  generalizing the one-shot ``RAFT_TPU_XPROF_DIR`` block that lived in
  ``bench/runner.py``.

The numbers are XLA's *static* cost model: flops are algorithmic
(fusion does not change them), bytes-accessed is the compiler's
estimate of HBM traffic for the fused program. They bound reality from
below — a program whose achieved bandwidth fraction is already near
1.0 has nothing left to fuse, which is exactly the question
("runs as fast as the hardware allows") the ROADMAP needs answered per
recorded row.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

__all__ = [
    "DevicePeak", "DEVICE_PEAKS", "device_peak", "peak_for_kind",
    "InterconnectPeak", "INTERCONNECT_PEAKS", "interconnect_peak",
    "axis_peak_bw",
    "cost_analysis", "memory_analysis", "ProgramCost",
    "analyze_compiled", "analyze_jit", "record", "capture",
]


# ---------------------------------------------------------------------------
# device peak table (roofline ceilings)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DevicePeak:
    """Peak dense compute (FLOP/s, bf16 MXU for TPUs) and HBM bandwidth
    (bytes/s) for one device kind. ``ridge`` is the roofline ridge
    point in flops/byte: programs whose arithmetic intensity sits below
    it are memory-bound on this part."""

    name: str
    flops: float
    hbm_bw: float
    placeholder: bool = False

    @property
    def ridge(self) -> float:
        return self.flops / self.hbm_bw


# Published per-chip peaks (dense bf16 matmul, HBM bandwidth). The CPU
# entry is an explicit PLACEHOLDER — the CI mesh only needs the
# classification machinery to run, not to be calibrated; rows it
# produces still carry real flops/bytes from the XLA cost model.
DEVICE_PEAKS: Dict[str, DevicePeak] = {
    "v4": DevicePeak("v4", 275e12, 1228e9),
    "v5e": DevicePeak("v5e", 197e12, 819e9),
    "v5p": DevicePeak("v5p", 459e12, 2765e9),
    "cpu": DevicePeak("cpu", 5e10, 2e10, placeholder=True),
}


def peak_for_kind(kind: str) -> DevicePeak:
    """Map a PJRT ``device_kind`` string to its peak entry. Matching is
    substring-based over the normalized kind ("TPU v5 lite" and
    "TPU v5e" both mean v5e); unknown kinds get the CPU placeholder —
    classification still runs, the ceiling is just not calibrated."""
    k = (kind or "").lower().replace(" ", "")
    if "v5p" in k or "v5pod" in k:
        return DEVICE_PEAKS["v5p"]
    if "v5e" in k or "v5lite" in k or "v5litepod" in k:
        return DEVICE_PEAKS["v5e"]
    if "v4" in k:
        return DEVICE_PEAKS["v4"]
    return DEVICE_PEAKS["cpu"]


def device_peak(device: Optional[Any] = None) -> DevicePeak:
    """Peak entry for ``device`` (default: device 0). Never raises —
    a backend that won't even report a device kind degrades to the CPU
    placeholder."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        return peak_for_kind(getattr(device, "device_kind", ""))
    except Exception:
        return DEVICE_PEAKS["cpu"]


@dataclasses.dataclass(frozen=True)
class InterconnectPeak:
    """Per-axis interconnect bandwidth ceiling for one device kind:
    ``ici_bw`` is per-link ICI bandwidth (bytes/s, one direction),
    ``dcn_bw`` the per-host DCN bandwidth share — the two denominators
    of the per-axis comm roofline (``comms.bytes{axis=...}`` / peak).
    Like :class:`DevicePeak` these are order-of-magnitude published
    figures, not calibrations; the CPU entry is a placeholder so the
    CI mesh can exercise the classification."""

    name: str
    ici_bw: float
    dcn_bw: float
    placeholder: bool = False


# Published per-link ICI and per-host DCN figures (one direction,
# order of magnitude — e.g. v4 ICI ≈ 300 GB/s per link; DCN shares
# ≈ 25 GB/s/host across generations). The asymmetry RATIO is what the
# per-axis roofline needs to be honest about: an axis=dcn byte is
# ~10× more expensive than an axis=ici byte.
INTERCONNECT_PEAKS: Dict[str, InterconnectPeak] = {
    "v4": InterconnectPeak("v4", 300e9, 25e9),
    "v5e": InterconnectPeak("v5e", 200e9, 25e9),
    "v5p": InterconnectPeak("v5p", 600e9, 25e9),
    "cpu": InterconnectPeak("cpu", 1e9, 1e8, placeholder=True),
}


def interconnect_peak(kind: Optional[str] = None) -> InterconnectPeak:
    """Interconnect peak entry for a PJRT ``device_kind`` string
    (default: device 0's kind). Same substring matching and
    CPU-placeholder degradation as :func:`peak_for_kind`."""
    if kind is None:
        try:
            import jax

            kind = getattr(jax.devices()[0], "device_kind", "")
        except Exception:
            kind = ""
    k = (kind or "").lower().replace(" ", "")
    if "v5p" in k or "v5pod" in k:
        return INTERCONNECT_PEAKS["v5p"]
    if "v5e" in k or "v5lite" in k or "v5litepod" in k:
        return INTERCONNECT_PEAKS["v5e"]
    if "v4" in k:
        return INTERCONNECT_PEAKS["v4"]
    return INTERCONNECT_PEAKS["cpu"]


def axis_peak_bw(axis: str, peak: Optional[InterconnectPeak] = None
                 ) -> float:
    """Bandwidth ceiling for one ``comms.bytes{axis=...}`` label: the
    DCN figure when the axis name is DCN-labeled
    (:func:`raft_tpu.parallel.mesh.is_dcn_axis` — imported lazily, obs
    must not import parallel at module scope), the ICI figure
    otherwise. On a jax-less triage host (obsdump reading a dump) the
    parallel package won't import; fall back to the same name-prefix
    rule ``is_dcn_axis`` applies (mesh.DCN_AXIS_PREFIXES)."""
    try:
        from raft_tpu.parallel.mesh import is_dcn_axis

        dcn = is_dcn_axis(axis)
    except Exception:
        dcn = str(axis).lower().startswith(("dcn", "pod", "slice"))
    p = peak if peak is not None else interconnect_peak()
    return p.dcn_bw if dcn else p.ici_bw


# ---------------------------------------------------------------------------
# version-tolerant Compiled accessors
# ---------------------------------------------------------------------------

def cost_analysis(compiled: Any) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` normalized to one plain dict.
    Handles every shape jax has shipped — a dict, a one-element list of
    dicts (0.4.x), or the method missing/raising (old jax, exotic
    backends) — by degrading to ``{}``."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return {}
    return {str(k): float(v) for k, v in ca.items()
            if isinstance(v, (int, float))}


def memory_analysis(compiled: Any) -> Dict[str, int]:
    """``Compiled.memory_analysis()`` (a ``CompiledMemoryStats``-like
    object or dict) flattened to ``{field: int}``; ``{}`` when the
    backend doesn't report."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    if isinstance(ma, dict):
        return {str(k): int(v) for k, v in ma.items()
                if isinstance(v, (int, float))}
    out: Dict[str, int] = {}
    for field in ("generated_code_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "alias_size_in_bytes",
                  "temp_size_in_bytes"):
        v = getattr(ma, field, None)
        if isinstance(v, (int, float)):
            out[field] = int(v)
    return out


# ---------------------------------------------------------------------------
# roofline derivation
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProgramCost:
    """Static cost + roofline classification of one compiled program.

    ``flops``/``bytes_accessed`` come from XLA's cost model;
    ``arithmetic_intensity = flops / bytes_accessed`` (flops/byte);
    ``bound`` is ``"memory"`` or ``"compute"`` against the device
    ridge. The achieved fractions are only set when a measured
    ``elapsed_s`` was supplied (see :meth:`attribute_elapsed`) — they
    compare realized bandwidth/compute against the peak table."""

    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    arithmetic_intensity: Optional[float] = None
    bound: Optional[str] = None
    device_kind: str = "cpu"
    peak_flops: float = 0.0
    peak_bw: float = 0.0
    ridge: float = 0.0
    peak_is_placeholder: bool = True
    memory: Dict[str, int] = dataclasses.field(default_factory=dict)
    elapsed_s: Optional[float] = None
    achieved_bw_frac: Optional[float] = None
    achieved_flops_frac: Optional[float] = None

    def attribute_elapsed(self, elapsed_s: Optional[float]) -> "ProgramCost":
        """Fold a measured wall time in: achieved bandwidth =
        ``bytes_accessed / elapsed_s`` as a fraction of peak (same for
        flops). No-op on None/zero elapsed."""
        if not elapsed_s or elapsed_s <= 0:
            return self
        self.elapsed_s = float(elapsed_s)
        if self.bytes_accessed and self.peak_bw:
            self.achieved_bw_frac = (
                self.bytes_accessed / elapsed_s) / self.peak_bw
        if self.flops and self.peak_flops:
            self.achieved_flops_frac = (
                self.flops / elapsed_s) / self.peak_flops
        return self

    def as_row(self) -> Dict[str, Any]:
        """The bench detail-row columns (rounded for record hygiene)."""
        out: Dict[str, Any] = {
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "bound": self.bound,
        }
        if self.arithmetic_intensity is not None:
            out["arith_intensity"] = round(self.arithmetic_intensity, 4)
        if self.achieved_bw_frac is not None:
            out["achieved_bw_frac"] = round(self.achieved_bw_frac, 6)
        if self.achieved_flops_frac is not None:
            out["achieved_flops_frac"] = round(self.achieved_flops_frac, 6)
        return out


def analyze_compiled(compiled: Any, device: Optional[Any] = None,
                     elapsed_s: Optional[float] = None) -> ProgramCost:
    """Derive a :class:`ProgramCost` from a ``jax.stages.Compiled``:
    flops/bytes from the cost model, memory stats, roofline bound
    against :func:`device_peak`, achieved fractions when ``elapsed_s``
    is given. Degrades field-by-field — a backend without a cost model
    still yields the peak/ridge context with None costs."""
    peak = device_peak(device)
    ca = cost_analysis(compiled)
    flops = ca.get("flops")
    bytes_accessed = ca.get("bytes accessed", ca.get("bytes_accessed"))
    ai = None
    bound = None
    if flops is not None and bytes_accessed:
        ai = flops / bytes_accessed
        bound = "memory" if ai < peak.ridge else "compute"
    cost = ProgramCost(
        flops=flops, bytes_accessed=bytes_accessed,
        arithmetic_intensity=ai, bound=bound,
        device_kind=peak.name, peak_flops=peak.flops, peak_bw=peak.hbm_bw,
        ridge=peak.ridge, peak_is_placeholder=peak.placeholder,
        memory=memory_analysis(compiled),
    )
    return cost.attribute_elapsed(elapsed_s)


def analyze_jit(fn, *args, device: Optional[Any] = None,
                elapsed_s: Optional[float] = None,
                **jit_kwargs) -> Optional[ProgramCost]:
    """Trace+compile ``fn(*args)`` under ``jax.jit`` and analyze the
    compiled program. The one-call wrapper for whole-API attribution:
    the bench runner points it at its search closure, so the cost of
    THE program the row measured (scan tier, refine tier, epilogue —
    whatever dispatch picked) is what lands in the record. Returns
    ``None`` when the callable cannot be traced end-to-end (host-side
    control flow on values, provider closures) — callers keep their
    row, just without cost columns."""
    try:
        import jax

        compiled = jax.jit(fn, **jit_kwargs).lower(*args).compile()
    except Exception:
        return None
    return analyze_compiled(compiled, device=device, elapsed_s=elapsed_s)


def record(cost: ProgramCost, registry=None,
           program: str = "default") -> None:
    """Write one program's cost into gauges: ``prof.flops`` /
    ``prof.bytes`` / ``prof.arith_intensity`` /
    ``prof.achieved_bw_frac`` / ``prof.achieved_flops_frac`` (labels
    ``{program=...}``) plus a ``prof.bound{program=,bound=}`` marker
    gauge — the series ``tools/obsdump.py``'s roofline table reads.
    Defaults to the live obs registry."""
    if registry is None:
        from raft_tpu.obs import spans as _spans

        registry = _spans.registry()
    # the registry renders labels as name{k=v,k2=v2} with no escaping:
    # a program label carrying , { } (the bench context embeds a search
    # -param dict repr) would corrupt every downstream key parse — map
    # them to lookalikes at this one chokepoint
    program = (str(program).replace(",", ";")
               .replace("{", "(").replace("}", ")"))
    labels = {"program": program}
    if cost.flops is not None:
        registry.gauge("prof.flops", labels).set(cost.flops)
    if cost.bytes_accessed is not None:
        registry.gauge("prof.bytes", labels).set(cost.bytes_accessed)
    if cost.arithmetic_intensity is not None:
        registry.gauge("prof.arith_intensity", labels).set(
            cost.arithmetic_intensity)
    if cost.achieved_bw_frac is not None:
        registry.gauge("prof.achieved_bw_frac", labels).set(
            cost.achieved_bw_frac)
    if cost.achieved_flops_frac is not None:
        registry.gauge("prof.achieved_flops_frac", labels).set(
            cost.achieved_flops_frac)
    if cost.bound is not None:
        registry.gauge("prof.bound",
                       {"program": program, "bound": cost.bound}).set(1.0)


# ---------------------------------------------------------------------------
# programmatic profiler capture
# ---------------------------------------------------------------------------

class capture:
    """Start/stop bracket around ``jax.profiler`` trace collection —
    the generalization of the one-shot ``RAFT_TPU_XPROF_DIR`` block
    that used to live inline in ``bench/runner.py``. Context manager
    or explicit ``start()``/``stop()``::

        cap = prof.capture("/tmp/xprof").start()
        run_workload()
        cap.stop()            # returns the log dir (None if never armed)

    Never raises: a backend without profiler support, a second
    concurrent capture (jax allows one trace at a time), or a broken
    logdir records the failure in ``.error`` and stays inactive —
    the measured workload must not pay for its own diagnostics."""

    def __init__(self, logdir: Optional[str] = None):
        if logdir is None:
            logdir = os.environ.get("RAFT_TPU_XPROF_DIR", "")  # path value
            if not logdir.strip():
                logdir = "/tmp/raft_tpu_xprof"
        self.logdir = logdir
        self.active = False
        self.error: Optional[BaseException] = None

    def start(self) -> "capture":
        if self.active:
            return self
        try:
            import jax

            jax.profiler.start_trace(self.logdir)
            self.active = True
        except Exception as e:
            self.error = e
        return self

    def stop(self) -> Optional[str]:
        if not self.active:
            return None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            self.error = e
        finally:
            self.active = False
        return self.logdir

    def __enter__(self) -> "capture":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False
