"""Capacity plane — utilization accounting and saturation forecasting
(ISSUE 20).

The cost ledger (:mod:`raft_tpu.obs.cost`) answers *who is consuming
what*; this module answers *when does this pod run out of headroom* —
and feeds the answer back into the knobs that can act on it
(``IndexRegistry.admit`` demotes raw tiers preemptively, the
``FleetRouter`` places new tenants by cost-share-weighted headroom).

:class:`DeltaRing` is the ISSUE-16 SLO monitor's multi-window
snapshot-delta machinery extracted for reuse: a bounded timestamped
ring of totals dicts with per-window base selection. The SLO monitor's
burn rates and this module's rate windows ride the same structure.

:class:`CapacityModel` keeps bounded per-pod rate windows and emits:

- ``capacity.utilization{resource=hbm|device}`` — HBM: resident bytes
  over the usable budget (instantaneous level); device: attributed
  device seconds over wall seconds, delta'd over the shortest window.
- ``capacity.headroom_frac`` — ``1 − max(utilization)``, the number
  the router's placement scoring wants.
- ``capacity.ttl_saturation_s`` — linear-trend time until resident
  bytes crosses the usable budget (least-squares slope over the
  longest window; ``inf`` while flat or shrinking).
- ``capacity.alert{resource=}`` — counted when a resource's
  utilization burns past ``CapacityPolicy.alert_utilization``, or when
  the HBM trend saturates inside ``horizon_s``.

The model is registered process-globally (:func:`set_model`, the
SLO-monitor install pattern) so the registry's admission path — which
cannot see the server object — can consult the forecast. Locks ride
``monitored_lock`` for the ISSUE-18 sanitize lane; all math is stdlib
(no numpy, no jax) so the module imports anywhere the obs layer does.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _spans
from raft_tpu.obs.metrics import counter_sum

__all__ = ["CapacityPolicy", "DeltaRing", "CapacityModel",
           "set_model", "get_model", "clear_model"]


class DeltaRing:
    """Bounded timestamped ring of totals snapshots with per-window
    base selection — the multi-window delta shape shared by the SLO
    monitor's burn rates and the capacity model's rate windows.

    Thread-safety is the *caller's*: both users already serialize
    appends under their own monitored lock, and a second lock here
    would only add an order edge for the sanitizer to track."""

    def __init__(self, keep_s: float):
        self.keep_s = float(keep_s)
        self._snaps: Deque[Tuple[float, Dict[str, float]]] = deque()

    def append(self, ts: float, totals: Dict[str, float]) -> None:
        """Append one snapshot and prune entries older than the keep
        window (relative to ``ts``)."""
        self._snaps.append((ts, totals))
        while self._snaps and ts - self._snaps[0][0] > self.keep_s:
            self._snaps.popleft()

    def snaps(self) -> List[Tuple[float, Dict[str, float]]]:
        return list(self._snaps)

    @staticmethod
    def window_base(snaps: List[Tuple[float, Dict[str, float]]],
                    now: float, window_s: float) -> Dict[str, float]:
        """The oldest snapshot inside ``window_s`` of ``now`` — the
        delta base. Falls back to the oldest snapshot held when the
        window predates the ring (short-uptime behavior: the window
        sees everything there is)."""
        for ts, totals in snaps:
            if now - ts <= window_s:
                return totals
        return snaps[0][1] if snaps else {}


@dataclasses.dataclass(frozen=True)
class CapacityPolicy:
    """Forecast knobs. ``windows_s``: rate lookbacks (shortest drives
    device utilization, longest drives the trend fit);
    ``horizon_s``: how far ahead admission looks — a projected HBM
    saturation inside it triggers preemptive demotion;
    ``alert_utilization``: the burn threshold past which
    ``capacity.alert`` counts; ``min_points``: snapshots a trend fit
    needs before it forecasts (two points make a line, not a trend)."""

    windows_s: Tuple[float, ...] = (30.0, 300.0)
    horizon_s: float = 600.0
    alert_utilization: float = 0.85
    min_points: int = 3


def _trend_slope(points: List[Tuple[float, float]]) -> float:
    """Least-squares slope of ``(t, y)`` points (units of y per
    second); 0.0 when degenerate."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    my = sum(y for _, y in points) / n
    num = sum((t - mt) * (y - my) for t, y in points)
    den = sum((t - mt) ** 2 for t, _ in points)
    return num / den if den > 0 else 0.0


class CapacityModel:
    """Rate windows + saturation forecast over one serving plane.

    ``resident_bytes`` / ``usable_bytes`` are callables (duck-typed
    over the registry) so the obs layer stays below serve; ``ledger``
    is the cost ledger supplying attributed device seconds. ``clock``
    is injectable — the CI ramp test drives synthetic time."""

    def __init__(self, resident_bytes: Callable[[], float],
                 usable_bytes: Callable[[], float],
                 ledger: Any = None,
                 policy: Optional[CapacityPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._resident = resident_bytes
        self._usable = usable_bytes
        self._ledger = ledger
        self.policy = policy or CapacityPolicy()
        self._clock = clock
        self._lock = _sanitize.monitored_lock("obs.capacity")
        keep = max(self.policy.windows_s) * 1.5 \
            if self.policy.windows_s else 450.0
        self._ring = DeltaRing(keep)

    # -- snapshots -----------------------------------------------------------
    def _totals(self) -> Dict[str, float]:
        try:
            resident = float(self._resident())
        except Exception:  # noqa: BLE001 — registry mid-teardown
            resident = 0.0
        device_s = 0.0
        if self._ledger is not None:
            device_s = sum(self._ledger.device_seconds().values())
        requests = 0.0
        if _spans.enabled():
            requests = counter_sum(_spans.registry().collect(),
                                   "serve.requests")
        return {"resident_bytes": resident, "device_s": device_s,
                "requests": requests}

    def tick(self) -> None:
        """Append one snapshot, refresh the ``capacity.*`` gauges, and
        count alerts. Driven from health scrapes, ``/costz``, and the
        admission path — no timer thread of its own (the SLO-monitor
        convention)."""
        now = self._clock()
        totals = self._totals()
        with self._lock:
            self._ring.append(now, totals)
        util = self.utilization()
        ttl = self.ttl_saturation_s()
        headroom = max(0.0, 1.0 - max(util.values(), default=0.0))
        if not _spans.enabled():
            return
        reg = _spans.registry()
        for resource, value in util.items():
            reg.gauge("capacity.utilization",
                      labels={"resource": resource}).set(value)
            if value > self.policy.alert_utilization:
                reg.inc("capacity.alert", labels={"resource": resource})
        reg.gauge("capacity.headroom_frac").set(headroom)
        reg.gauge("capacity.ttl_saturation_s").set(
            ttl if ttl != float("inf") else -1.0)
        if ttl < self.policy.horizon_s:
            reg.inc("capacity.alert", labels={"resource": "hbm"})

    # -- accounting ----------------------------------------------------------
    def utilization(self) -> Dict[str, float]:
        """Per-resource utilization: ``hbm`` is the instantaneous
        resident/usable level; ``device`` is attributed device seconds
        over wall seconds, delta'd over the shortest window."""
        try:
            usable = float(self._usable())
            resident = float(self._resident())
        except Exception:  # noqa: BLE001
            usable, resident = 0.0, 0.0
        out = {"hbm": (resident / usable) if usable > 0 else 0.0}
        with self._lock:
            snaps = self._ring.snaps()
        if snaps:
            now, newest = snaps[-1]
            w = min(self.policy.windows_s) if self.policy.windows_s \
                else 30.0
            base = DeltaRing.window_base(snaps, now, w)
            base_ts = next((ts for ts, t in snaps if t is base), now)
            d_wall = now - base_ts
            d_dev = newest.get("device_s", 0.0) - base.get("device_s", 0.0)
            out["device"] = (d_dev / d_wall) if d_wall > 0 else 0.0
        else:
            out["device"] = 0.0
        return out

    def headroom_frac(self) -> float:
        return max(0.0, 1.0 - max(self.utilization().values(),
                                  default=0.0))

    def arrival_rates(self) -> Dict[str, float]:
        """Per-tenant request arrival rate (req/s) from
        ``serve.requests{tenant=}`` deltas over the shortest window."""
        if not _spans.enabled():
            return {}
        rows = _spans.registry().collect()
        tenants = sorted({str((r.get("labels") or {}).get("tenant"))
                          for r in rows
                          if r.get("name") == "serve.requests"
                          and (r.get("labels") or {}).get("tenant")})
        if not tenants:
            return {}
        with self._lock:
            snaps = self._ring.snaps()
        if len(snaps) < 2:
            return {t: 0.0 for t in tenants}
        now = snaps[-1][0]
        w = min(self.policy.windows_s) if self.policy.windows_s else 30.0
        base = DeltaRing.window_base(snaps, now, w)
        base_ts = next((ts for ts, t in snaps if t is base), now)
        d_wall = max(now - base_ts, 1e-9)
        d_req = (snaps[-1][1].get("requests", 0.0)
                 - base.get("requests", 0.0))
        # totals ring carries the fleet aggregate; split it by the
        # current per-tenant counter proportions (bounded label sets
        # stay out of the ring — one dict per snapshot, not per tenant)
        per = {t: counter_sum(rows, "serve.requests", tenant=t)
               for t in tenants}
        total = sum(per.values())
        if total <= 0:
            return {t: 0.0 for t in tenants}
        return {t: (d_req / d_wall) * (v / total)
                for t, v in per.items()}

    # -- forecast ------------------------------------------------------------
    def _resident_slope(self) -> float:
        with self._lock:
            snaps = self._ring.snaps()
        if len(snaps) < self.policy.min_points:
            return 0.0
        return _trend_slope([(ts, t.get("resident_bytes", 0.0))
                             for ts, t in snaps])

    def ttl_saturation_s(self, extra_bytes: float = 0.0) -> float:
        """Linear-trend seconds until resident bytes (plus
        ``extra_bytes``, the admission candidate) crosses the usable
        budget. ``inf`` while the trend is flat/shrinking or already
        has no headroom to burn through; 0.0 when already over."""
        try:
            usable = float(self._usable())
            resident = float(self._resident()) + float(extra_bytes)
        except Exception:  # noqa: BLE001
            return float("inf")
        if usable <= 0:
            return float("inf")
        if resident >= usable:
            return 0.0
        slope = self._resident_slope()
        if slope <= 0.0:
            return float("inf")
        return (usable - resident) / slope

    def projected_growth_bytes(self,
                               horizon_s: Optional[float] = None) -> float:
        """Trend-projected resident-byte growth over the horizon —
        what the admission hook must free preemptively to outlive the
        forecast. 0.0 while flat/shrinking."""
        h = self.policy.horizon_s if horizon_s is None else horizon_s
        return max(0.0, self._resident_slope() * h)

    def would_saturate(self, extra_bytes: float = 0.0,
                       horizon_s: Optional[float] = None) -> bool:
        """The admission question: does the trend (plus the candidate's
        bytes) cross the usable budget inside the horizon?"""
        h = self.policy.horizon_s if horizon_s is None else horizon_s
        return self.ttl_saturation_s(extra_bytes=extra_bytes) < h

    def forecast(self) -> Dict[str, Any]:
        """JSON-ready forecast — the ``/costz`` ``"capacity"`` half."""
        ttl = self.ttl_saturation_s()
        return {
            "utilization": self.utilization(),
            "headroom_frac": self.headroom_frac(),
            "ttl_saturation_s": (ttl if ttl != float("inf") else None),
            "resident_slope_bytes_per_s": self._resident_slope(),
            "arrival_rates": self.arrival_rates(),
            "policy": dataclasses.asdict(self.policy),
        }


# -- process-global model (the slo-monitor install pattern) -----------------

_model: Optional[CapacityModel] = None
_model_lock = _sanitize.monitored_lock("obs.capacity.global")


def set_model(model: Optional[CapacityModel]) -> Optional[CapacityModel]:
    """Install the process-global capacity model (returns the previous
    one). The server installs at start and clears at stop so admission
    and placement can consult the forecast without plumbing."""
    global _model
    with _model_lock:
        prev = _model
        _model = model
        return prev


def get_model() -> Optional[CapacityModel]:
    return _model


def clear_model(model: Optional[CapacityModel] = None) -> None:
    """Remove the global model; with an argument, only when it is
    still the installed one."""
    global _model
    with _model_lock:
        if model is None or _model is model:
            _model = None
