"""Span timers — stage-level wall/device timing into the metrics registry.

The NVTX analog in :mod:`raft_tpu.core.tracing` labels profiler
timelines but *records* nothing; a :class:`span` additionally times the
covered region and writes a ``span.<dotted.name>`` histogram (seconds)
into the registry, so per-stage latency is readable in process.

Semantics:

- **Off by default, near-zero when off.** ``span.__enter__``/``__exit__``
  check one module flag and return — no clock read, no lock, no JAX
  import, and critically NO sync points, so production dispatch stays
  fully async (verified by tests/test_obs.py).
- **Nested spans dot-join**: a ``span("scan")`` inside ``span("search")``
  inside the traced ``ivf_pq`` entry records under
  ``span.ivf_pq.search.scan``. The stack is thread-local.
- **Sync mode** (``enable(sync=True)``): at span exit, arrays attached
  via :meth:`span.attach` are passed to ``jax.block_until_ready`` before
  the clock stops, so the span measures *device* time, not dispatch
  time. Off by default — syncing at stage boundaries serializes the
  pipeline and is strictly an observability trade.
- **Jit-safe**: under a JAX trace (inside ``jax.jit``), spans disable
  themselves — a host timer inside a traced function would measure
  trace time once and nothing on cached calls, and blocking on tracers
  would be an error.
- **Stage mode** (``enable(stages=True)``): hot paths that offer a
  stage-decomposed variant (``ivf_pq.search`` → ``search_staged``)
  route to it, trading fusion for per-stage attribution.

Env: ``RAFT_TPU_OBS=1`` enables at import; ``RAFT_TPU_OBS_SYNC=1``,
``RAFT_TPU_OBS_STAGES=1`` and ``RAFT_TPU_OBS_EVENTS=1`` (timeline event
recording into :mod:`raft_tpu.obs.trace`) add the respective modes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from raft_tpu.obs import metrics as _metrics

_enabled = False
_sync = False
_stages = False
_hbm_sample = True
_events = False
_registry: Optional[_metrics.MetricsRegistry] = None

_tls = threading.local()


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def enable(sync: bool = False, stages: bool = False,
           registry: Optional[_metrics.MetricsRegistry] = None,
           hbm: bool = True, events: bool = False) -> None:
    """Turn span recording on. ``sync`` blocks on attached arrays at span
    exit (device time); ``stages`` routes searches through their
    stage-decomposed variants; ``registry`` overrides the global sink;
    ``hbm`` samples HBM gauges at root-span exit; ``events``
    additionally appends one timeline event per span exit (plus HBM
    counter samples) into the :mod:`raft_tpu.obs.trace` ring buffer for
    Chrome-trace/Perfetto export."""
    global _enabled, _sync, _stages, _registry, _hbm_sample, _events
    _sync = bool(sync)
    _stages = bool(stages)
    _registry = registry
    _hbm_sample = bool(hbm)
    _events = bool(events)
    _enabled = True


def disable() -> None:
    global _enabled, _sync, _stages, _registry, _events
    _enabled = False
    _sync = False
    _stages = False
    _events = False
    _registry = None


def _state():
    """Snapshot the enable state (for save/restore around a temporary
    enable — e.g. the bench's diagnostic capture must not wipe a
    RAFT_TPU_OBS=1 enable the user installed at import)."""
    return (_enabled, _sync, _stages, _registry, _hbm_sample, _events)


def _restore(state) -> None:
    global _enabled, _sync, _stages, _registry, _hbm_sample, _events
    _enabled, _sync, _stages, _registry, _hbm_sample, _events = state


def enabled() -> bool:
    return _enabled


def sync_enabled() -> bool:
    return _enabled and _sync


def stages_enabled() -> bool:
    return _enabled and _stages


def events_enabled() -> bool:
    return _enabled and _events


def registry() -> _metrics.MetricsRegistry:
    """The registry spans currently record into."""
    return _registry if _registry is not None else _metrics.get_registry()


def current_name() -> str:
    """Dotted name of the innermost open span ('' outside any span)."""
    return ".".join(_stack())


def count_dispatch(name: str, impl: str, **labels: str) -> None:
    """Count one dispatch decision under ``<name>.dispatch{impl=...}`` —
    the #1 thing perf triage asks ("which engine actually ran?"). Free
    when recording is off. Counted per DISPATCH DECISION: once per jit
    trace for jitted callers (the choice is baked into the compiled
    program), once per call in eager dispatchers (``ivf_pq.search``'s
    scan-tier pick, ``select_k``'s engine pick). Extra keyword labels
    ride along (e.g. ``filtered="1"`` on a filtered fused-scan
    dispatch)."""
    if _enabled:
        registry().inc(name + ".dispatch", labels={"impl": impl, **labels})


def count_fallback(name: str, reason: str) -> None:
    """Count one *declined* preferred tier under
    ``<name>.fallback{reason=...}`` — the companion of
    :func:`count_dispatch`: the dispatch counter says which engine ran,
    this one says WHY the preferred tier did not (filter-blind kernel,
    memory guard, unsupported layout, ...). Free when recording is off;
    counted per dispatch decision, like count_dispatch."""
    if _enabled:
        registry().inc(name + ".fallback", labels={"reason": reason})


def env_flag(name: str) -> bool:
    """Parse a boolean env var: unset, '', '0', 'false', 'off', 'no' are
    False; anything else is True (plain string truthiness would read
    ``RAFT_TPU_OBS=0`` as enabled)."""
    # the canonical flag parser — the one raw read GL02 points everyone at
    return os.environ.get(name, "").strip().lower() not in (  # graftlint: disable=GL02
        "", "0", "false", "off", "no")


def env_tristate(name: str, default: str = "auto") -> str:
    """Parse a tri-state env var into ``"auto"`` / ``"on"`` / ``"off"``.

    The shared parser for the ``RAFT_TPU_PALLAS_*`` dispatch overrides:
    ``0/false/off/no/never`` → "off", ``1/true/on/yes/always`` → "on",
    unset/''/``auto`` → ``default``. The legacy ``always``/``never``
    spellings stay valid — they were the documented values before this
    helper existed. Unknown values fall back to ``default`` rather than
    silently enabling (same conservatism as :func:`env_flag`)."""
    raw = os.environ.get(name, "").strip().lower()  # graftlint: disable=GL02
    if raw in ("0", "false", "off", "no", "never"):
        return "off"
    if raw in ("1", "true", "on", "yes", "always"):
        return "on"
    return default


def _trace_clean() -> bool:
    """True outside any JAX trace (safe to time / block / reroute)."""
    try:
        import jax

        return jax.core.trace_state_clean()
    except Exception:
        pass
    try:  # newer jax drops it from the public namespace
        from jax._src import core as _jax_core

        return _jax_core.trace_state_clean()
    except Exception:
        # unknown jax: assume we ARE under a trace — spans go quiet, but
        # timing/blocking a tracer or baking the staged route into a
        # caller's jit cache would be worse than missing samples
        return False


class span:
    """Context manager timing one stage. Usage::

        with span("scan") as sp:
            out = scan_program(...)
            sp.attach(out)          # blocked on at exit in sync mode

    Arrays may also be passed at construction: ``span("scan", out)``.
    ``labels`` (and :meth:`annotate`) attach key/values that ride into
    the timeline event's ``args`` when event recording is on.
    """

    __slots__ = ("name", "_arrays", "_t0", "_live", "_labels")

    def __init__(self, name: str, *arrays: Any,
                 labels: Optional[dict] = None):
        self.name = name
        self._arrays = list(arrays)
        self._t0 = 0.0
        self._live = False
        self._labels = labels

    def attach(self, *arrays: Any) -> "span":
        """Register arrays (any pytrees) to block on at exit when sync
        mode is on. No-op (and free) when spans are disabled."""
        if self._live and _sync:
            self._arrays.extend(arrays)
        return self

    def annotate(self, **labels: Any) -> "span":
        """Attach labels to this span's timeline event (event recording
        only). No-op (and free) when spans/events are disabled."""
        if self._live and _events:
            if self._labels is None:
                self._labels = {}
            self._labels.update(labels)
        return self

    def __enter__(self) -> "span":
        if not _enabled or not _trace_clean():
            return self
        self._live = True
        _stack().append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if not self._live:
            return False
        stack = _stack()
        try:
            # a raising block yields a truncated duration (and in sync
            # mode one with no device time) — don't mix it into the
            # same series as successful samples
            if exc_type is None:
                if _sync and self._arrays:
                    import jax

                    jax.block_until_ready(self._arrays)
                dt = time.perf_counter() - self._t0
                reg = registry()
                dotted = ".".join(stack)
                reg.histogram("span." + dotted).observe(dt)
                events = None
                if _events:
                    from raft_tpu.obs import trace as _trace

                    events = _trace.get_buffer()
                    args = self._labels
                    # request-scoped propagation (ISSUE 15): a span
                    # recorded while a RequestContext is installed on
                    # this thread carries the request's trace id(s) —
                    # the stage emits its usual event, the identity
                    # rides along, and obsdump --slowest can reassemble
                    # one request's full timeline
                    ctx = _trace.current_request()
                    if ctx is not None:
                        args = {**(args or {}), **ctx.event_labels()}
                    # wall-clock begin reconstructed from the monotonic
                    # duration: one clock read per exit, none per enter
                    events.record_span(dotted, time.time() - dt, dt,
                                       args=args)
                # sample HBM only at ROOT-span exit: memory_stats() is a
                # transport round-trip on tunnel-attached devices, and
                # at a child-span exit every ancestor's clock is still
                # running — sampling there would inflate parent timings
                if _hbm_sample and len(stack) == 1:
                    from raft_tpu.obs import hbm as _hbm

                    _hbm.sample(reg, events=events)
        finally:
            stack.pop()
            self._live = False
            self._arrays = []
        return False


if env_flag("RAFT_TPU_OBS"):  # pragma: no cover - env-driven
    enable(sync=env_flag("RAFT_TPU_OBS_SYNC"),
           stages=env_flag("RAFT_TPU_OBS_STAGES"),
           events=env_flag("RAFT_TPU_OBS_EVENTS"))
