"""raft_tpu.obs — in-process observability: metrics, span timers, HBM.

The reference attributes time through NVTX ranges + external profilers;
this package makes the same attribution available *in process*:

- :mod:`raft_tpu.obs.metrics` — thread-safe counters/gauges/histograms
  with labels, ``snapshot()`` → dict, ``dump_jsonl`` sink;
- :mod:`raft_tpu.obs.spans`   — ``span(name)`` stage timers (dotted
  nesting, optional device-time sync), recorded into the registry;
- :mod:`raft_tpu.obs.hbm`     — ``device.memory_stats()`` telemetry,
  sampled per local device;
- :mod:`raft_tpu.obs.prof`    — compiled-program cost attribution
  (``Compiled.cost_analysis``), roofline memory-/compute-bound
  classing against a device peak table, and a programmatic
  ``jax.profiler`` start/stop bracket;
- :mod:`raft_tpu.obs.trace`   — span-event ring buffer +
  Chrome-trace/Perfetto export (``obs.enable(events=True)``);
- :mod:`raft_tpu.obs.flight`  — flight recorder: crash-surviving dumps
  of events + metrics + logs on signals/atexit/periodically;
- :mod:`raft_tpu.obs.expo`    — live telemetry exposition: stdlib HTTP
  endpoint serving Prometheus text-format ``/metrics``, ``/healthz``,
  on-demand ``/flightz`` dumps, and ``/indexz`` index health;
- :mod:`raft_tpu.obs.quality` — online recall estimation: a shadow
  verifier reservoir-samples live requests and replays them through
  exact brute force on host, publishing ``quality.recall`` gauges with
  Wilson confidence intervals;
- :mod:`raft_tpu.obs.index_stats` — index-health introspection:
  list-size skew, dead centroids, centroid drift, PQ quantization
  error, tombstone density, as ``index.*`` gauges + ``/indexz``;
- :mod:`raft_tpu.obs.fleet`   — pod-wide aggregation: merges per-host
  flight dumps (shared run_id, clock alignment) and attributes
  collective-timing stragglers;
- :mod:`raft_tpu.obs.sanitize` — runtime sanitizer harness
  (``RAFT_TPU_SANITIZE=1``): rank-promotion/NaN config, transfer-guard
  scopes, and a jit-cache-miss counter with budget assertions.

Everything is off by default and adds no sync points until
:func:`enable` is called (or ``RAFT_TPU_OBS=1`` is set). See
docs/observability.md.
"""

from raft_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    exemplars_for_quantile,
    get_registry,
    load_jsonl,
    quantile_from_state,
    set_registry,
)
from raft_tpu.obs.trace import (  # noqa: F401
    RequestContext,
    current_request,
    new_trace_id,
    use_request,
)
from raft_tpu.obs.spans import (  # noqa: F401
    count_dispatch,
    count_fallback,
    current_name,
    disable,
    enable,
    enabled,
    env_flag,
    env_tristate,
    events_enabled,
    registry,
    span,
    stages_enabled,
    sync_enabled,
)
from raft_tpu.obs import hbm  # noqa: F401
from raft_tpu.obs import prof  # noqa: F401
from raft_tpu.obs import trace  # noqa: F401
from raft_tpu.obs import flight  # noqa: F401
from raft_tpu.obs import expo  # noqa: F401
from raft_tpu.obs import quality  # noqa: F401
from raft_tpu.obs import index_stats  # noqa: F401
from raft_tpu.obs import fleet  # noqa: F401
from raft_tpu.obs import sanitize  # noqa: F401
