"""Event recording — span timelines you can open in Perfetto.

The aggregate half of the observability layer (histograms in
:mod:`raft_tpu.obs.metrics`) answers "how much time does stage X take
on average"; this module keeps the *event* half — which call ran when,
on which thread, for how long — the in-process counterpart of the
NVTX→nsys timeline the reference leans on (``core/nvtx.hpp``), minus
the externally-attached profiler.

- :class:`EventBuffer` — a bounded, thread-safe ring of span/counter
  events (default ~64k; oldest evicted, eviction counted). When event
  recording is on (``obs.enable(events=True)`` or
  ``RAFT_TPU_OBS_EVENTS=1``), every recording span appends one complete
  event at exit (dotted name, thread id, wall timestamp, duration,
  attached labels), and root-span HBM sampling appends counter events.
- :func:`export_chrome` — render the buffer as Chrome-trace JSON
  (``ph: "X"`` complete events, one track per thread, ``ph: "C"``
  counter tracks for the ``hbm.*`` gauges). The file loads directly in
  Perfetto / ``chrome://tracing``.
- :func:`merge` — merge per-process dumps (multichip/multihost runs) by
  remapping colliding pids, so an 8-process run renders as one timeline.

Everything here is import-cheap (no jax) and costs nothing until event
recording is enabled — the flight recorder (:mod:`raft_tpu.obs.flight`)
snapshots the same buffer into its crash dumps.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque

from raft_tpu.obs import sanitize as _sanitize
from typing import Any, Dict, Iterable, List, Optional

DEFAULT_CAPACITY = 65536

#: schema stamp written into exports so tools/obsdump.py can sniff files
PRODUCER = "raft_tpu.obs.trace"


# ---------------------------------------------------------------------------
# request-scoped trace propagation (ISSUE 15)
# ---------------------------------------------------------------------------

def new_trace_id() -> str:
    """A fresh 16-hex request trace id (64 random bits — collision-safe
    for any realistic retention window, short enough to grep)."""
    return os.urandom(8).hex()


class RequestContext:
    """One request's identity, carried through the serving pipeline.

    Minted where the request enters the system (``MicroBatchServer.
    submit()``) and installed — via :func:`use_request` — around every
    stage that works on the request's behalf (batcher, dispatch, retry,
    the degrade ladder, ``search_resilient``). While installed, every
    span event recorded on the thread is stamped with the context's
    labels, so ``obsdump --slowest`` can reassemble one request's full
    timeline from the shared event ring.

    ``trace_ids`` covers the coalesced case: a micro-batch dispatch
    works for MANY requests at once — its context carries every
    member's trace id, and a timeline query for any one of them matches
    the batch's spans too. ``tenant`` rides as a label; ``deadline``
    (a :class:`raft_tpu.robust.retry.Deadline`) rides as plain state
    for stages that draw down the budget. Stdlib-only, immutable after
    construction."""

    __slots__ = ("trace_id", "trace_ids", "tenant", "deadline")

    def __init__(self, tenant: Optional[str] = None,
                 deadline: Optional[Any] = None,
                 trace_id: Optional[str] = None,
                 trace_ids: Optional[List[str]] = None):
        self.trace_id = trace_id or new_trace_id()
        self.trace_ids = list(trace_ids) if trace_ids else None
        self.tenant = tenant
        self.deadline = deadline

    def event_labels(self) -> Dict[str, Any]:
        """The labels stamped into span events recorded under this
        context (the batch form carries the member list)."""
        out: Dict[str, Any] = {}
        if self.trace_ids is not None:
            out["trace_ids"] = list(self.trace_ids)
        else:
            out["trace_id"] = self.trace_id
        if self.tenant is not None:
            out["tenant"] = self.tenant
        return out

    def matches(self, trace_id: str) -> bool:
        """True when this context works (at least partly) for
        ``trace_id`` — the single id or any coalesced member."""
        return (trace_id == self.trace_id
                or (self.trace_ids is not None
                    and trace_id in self.trace_ids))

    def __repr__(self) -> str:
        n = f" +{len(self.trace_ids)} coalesced" if self.trace_ids else ""
        return f"<RequestContext {self.trace_id}{n} tenant={self.tenant}>"


_request_tls = threading.local()


def current_request() -> Optional[RequestContext]:
    """The request context installed on THIS thread (None outside any
    request scope). One TLS read — cheap enough for span-exit paths."""
    return getattr(_request_tls, "ctx", None)


def set_request(ctx: Optional[RequestContext]
                ) -> Optional[RequestContext]:
    """Install ``ctx`` as the thread's current request; returns the
    previous one (low-level — prefer :func:`use_request`)."""
    prev = getattr(_request_tls, "ctx", None)
    _request_tls.ctx = ctx
    return prev


class use_request:
    """Context manager installing a :class:`RequestContext` for the
    covered block (nesting restores the outer context on exit)::

        with use_request(RequestContext(tenant="acme", deadline=dl)):
            dispatch(...)   # spans recorded here carry the trace id
    """

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[RequestContext]):
        self.ctx = ctx
        self._prev = None

    def __enter__(self) -> Optional[RequestContext]:
        self._prev = set_request(self.ctx)
        return self.ctx

    def __exit__(self, *exc) -> bool:
        set_request(self._prev)
        return False


def event_matches_trace(event: Dict[str, Any], trace_id: str) -> bool:
    """True when a buffer/flight event belongs to ``trace_id``'s
    timeline: its args carry the id directly or in a coalesced
    ``trace_ids`` list (the shared filter obsdump's ``--slowest``
    drill-down and the tests use)."""
    args = event.get("args") or {}
    if args.get("trace_id") == trace_id:
        return True
    ids = args.get("trace_ids")
    return isinstance(ids, (list, tuple)) and trace_id in ids


class EventBuffer:
    """Bounded thread-safe ring buffer of span/counter events.

    Events are plain dicts (JSON-ready). Span events::

        {"ph": "X", "name": "ivf_pq.search.scan", "ts": <wall s>,
         "dur": <s>, "tid": <thread id>, "tname": "MainThread",
         "args": {...} | None}

    Counter events (HBM gauges at root-span exit)::

        {"ph": "C", "name": "hbm.bytes_in_use{device=0}", "ts": <wall s>,
         "value": <float>}

    The ring holds ``capacity`` events; older ones evict silently but
    are counted (``dropped``) so exports can say the timeline is
    truncated rather than pretending it is complete.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive (got {capacity})")
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._total = 0
        # RLock: the flight recorder snapshots the buffer from signal
        # handlers running on the interrupted main thread — a plain
        # Lock held by the interrupted record_span frame would deadlock
        self._lock = _sanitize.monitored_rlock("obs.trace.buffer")

    def record_span(self, name: str, ts: float, dur: float,
                    args: Optional[Dict[str, Any]] = None) -> None:
        """Append one complete span event (``ts``/``dur`` in seconds,
        ``ts`` = wall-clock begin)."""
        t = threading.current_thread()
        ev = {"ph": "X", "name": name, "ts": ts, "dur": dur,
              "tid": t.ident or 0, "tname": t.name}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            self._events.append(ev)
            self._total += 1

    def record_counter(self, name: str, value: float,
                       ts: Optional[float] = None) -> None:
        """Append one counter sample (a Perfetto counter-track point)."""
        ev = {"ph": "C", "name": name, "value": float(value),
              "ts": time.time() if ts is None else ts}
        with self._lock:
            self._events.append(ev)
            self._total += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        """Copy of the retained events, oldest first."""
        with self._lock:
            return [dict(e) for e in self._events]

    @property
    def dropped(self) -> int:
        """How many events were evicted by the ring bound."""
        with self._lock:
            return max(0, self._total - len(self._events))

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._total = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


_global_buffer = EventBuffer()
_global_lock = _sanitize.monitored_lock("obs.trace.global")


def get_buffer() -> EventBuffer:
    """The process-global event buffer (what spans record into)."""
    return _global_buffer


def set_buffer(buffer: EventBuffer) -> EventBuffer:
    """Swap the process-global buffer (returns the previous one)."""
    global _global_buffer
    with _global_lock:
        prev = _global_buffer
        _global_buffer = buffer
        return prev


def _chrome_events(events: Iterable[Dict[str, Any]], pid: int
                   ) -> List[Dict[str, Any]]:
    """Lower buffer events to Chrome-trace dicts (µs timestamps) plus
    one thread_name metadata event per track."""
    out: List[Dict[str, Any]] = []
    tnames: Dict[int, str] = {}
    for e in events:
        if e.get("ph") == "X":
            ev = {"name": e["name"], "ph": "X", "pid": pid,
                  "tid": e.get("tid", 0),
                  "ts": float(e["ts"]) * 1e6,
                  "dur": float(e["dur"]) * 1e6}
            if e.get("args"):
                ev["args"] = e["args"]
            out.append(ev)
            tnames.setdefault(e.get("tid", 0), e.get("tname", ""))
        elif e.get("ph") == "C":
            out.append({"name": e["name"], "ph": "C", "pid": pid, "tid": 0,
                        "ts": float(e["ts"]) * 1e6,
                        "args": {"value": e.get("value", 0.0)}})
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": name or f"thread-{tid}"}}
            for tid, name in sorted(tnames.items())]
    return meta + out


def export_chrome(path: str, buffer: Optional[EventBuffer] = None) -> int:
    """Write the buffer as Chrome-trace/Perfetto JSON; returns the
    number of (non-metadata) events exported.

    The output is the JSON-object form of the trace-event format
    (``{"traceEvents": [...]}``) with ``ph: "X"`` complete events, one
    named track per thread, and ``ph: "C"`` counter tracks — loadable
    in Perfetto and ``chrome://tracing`` as-is, mergeable across
    processes with :func:`merge`.
    """
    buf = buffer if buffer is not None else get_buffer()
    events = buf.snapshot()
    pid = os.getpid()
    doc = {
        "traceEvents": (
            [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
              "args": {"name": f"{socket.gethostname()}:{pid}"}}]
            + _chrome_events(events, pid)),
        "displayTimeUnit": "ms",
        "otherData": {"producer": PRODUCER, "pid": pid,
                      "host": socket.gethostname(),
                      "dropped_events": buf.dropped},
    }
    tmp = f"{path}.tmp.{pid}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return len(events)


def load(path: str) -> Dict[str, Any]:
    """Load a Chrome-trace JSON file (object or bare-array form) into
    the object form (``{"traceEvents": [...]}``)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # the bare-array spelling is also legal
        doc = {"traceEvents": doc}
    return doc


def merge(paths: Iterable[str], out_path: Optional[str] = None
          ) -> Dict[str, Any]:
    """Merge per-process Chrome-trace dumps into one timeline.

    Multichip/multihost runs export one file per process; pids can
    collide across hosts (and trivially do for the rank-0 convention),
    which would fold distinct processes onto one Perfetto track group.
    Colliding pids are remapped to fresh ids and every process track is
    named after its source file. Returns the merged document; writes it
    to ``out_path`` when given.
    """
    merged: List[Dict[str, Any]] = []
    used_pids: set = set()
    for p in paths:
        doc = load(p)
        events = doc.get("traceEvents", [])
        remap: Dict[int, int] = {}
        for e in events:
            pid = int(e.get("pid", 0))
            if pid not in remap:
                new = pid
                while new in used_pids:
                    new += 1
                remap[pid] = new
                used_pids.add(new)
        tag = os.path.basename(p)
        for pid, new in sorted(remap.items()):
            has_name = any(
                e.get("ph") == "M" and e.get("name") == "process_name"
                and int(e.get("pid", 0)) == pid for e in events)
            if not has_name or new != pid:
                merged.append({"name": "process_name", "ph": "M",
                               "pid": new, "tid": 0, "args": {"name": tag}})
        for e in events:
            e = dict(e)
            e["pid"] = remap.get(int(e.get("pid", 0)), e.get("pid", 0))
            merged.append(e)
    doc = {"traceEvents": merged, "displayTimeUnit": "ms",
           "otherData": {"producer": PRODUCER, "merged_from": len(used_pids)}}
    if out_path:
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, out_path)
    return doc
