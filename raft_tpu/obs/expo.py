"""Live telemetry exposition — a scrapable serving process (ISSUE 15).

Until now the metrics registry was only readable post-mortem (flight
dumps, JSONL sinks) or in-process (``snapshot()``); a production
serving loop needs its numbers *pullable while it runs*. This module
is the zero-dependency answer: a stdlib ``http.server`` endpoint
(daemon thread, bounded surface) exposing

- ``GET /metrics``  — the full registry in Prometheus text exposition
  format (version 0.0.4): ``# HELP``/``# TYPE`` per family, labeled
  counters and gauges, histograms as cumulative ``_bucket{le=...}`` +
  ``_sum`` + ``_count`` series. Dotted raft_tpu names sanitize to
  underscores (``serve.latency_s`` → ``raft_tpu_serve_latency_s``);
  the original dotted name rides in the HELP line.
- ``GET /healthz``  — JSON health: overall ``status`` plus the serving
  registry's per-tenant health states when a provider is wired
  (``200`` while at least one tenant is resident — or no registry is
  attached at all; ``503`` when a registry exists but nothing can
  serve).
- ``GET /flightz``  — triggers an on-demand flight dump
  (:func:`raft_tpu.obs.flight.dump_now`) and returns its path: the
  "dump the black box NOW" button, no signal required.
- ``GET /indexz``   — JSON index-health introspection (ISSUE 16):
  per-tenant list-size skew, dead centroids, centroid drift, PQ
  quantization error, and tombstone density, computed on demand by the
  serving layer and cached on the tenant.
- ``GET /costz``    — JSON cost & capacity plane (ISSUE 20): the
  per-tenant resource-attribution ledger
  (:class:`raft_tpu.obs.cost.CostLedger.describe`) plus the capacity
  model's saturation forecast.

``/metrics`` additionally exposes the standard ``process_*``
self-telemetry family (RSS, CPU seconds, open fds, uptime — stdlib
``resource``/``os``, :func:`process_rows`) so the endpoint is
scrapeable for its own footprint, not just the workload's. Those
families keep their conventional unprefixed names — dashboards and
scrape configs expect ``process_resident_memory_bytes``, not a
``raft_tpu_``-prefixed variant.

:class:`ExpoServer` is started/stopped by
:class:`raft_tpu.serve.server.MicroBatchServer` when
``ServerConfig.expo_port`` is set (0 = ephemeral port, the test/CI
spelling), and is usable standalone around any instrumented loop.
Import-cheap (stdlib only, no jax); the scrape path reads the registry
through its own locks — zero instrumentation-side cost.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from raft_tpu.obs import metrics as _metrics

__all__ = ["ExpoServer", "render_prometheus", "prom_name",
           "parse_prometheus", "process_rows", "process_text"]

#: metric-name prefix — one namespace for every raft_tpu family
PROM_PREFIX = "raft_tpu_"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")
# one exposition line: name{labels} value — the label body is matched
# lazily and validated pair-by-pair (label VALUES may contain commas
# and escaped quotes/braces; a comma-split would corrupt them)
_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def prom_name(name: str) -> str:
    """Sanitize a dotted raft_tpu series name into a legal Prometheus
    metric name (``serve.latency_s`` → ``raft_tpu_serve_latency_s``)."""
    return PROM_PREFIX + _NAME_BAD.sub("_", name)


def _esc(value: Any) -> str:
    """Escape a label value per the text-format rules (backslash,
    newline, and double-quote — the value sits inside quotes)."""
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _esc_help(value: Any) -> str:
    """Escape HELP text per the text-format spec: ONLY backslash and
    newline — unlike label values, HELP is unquoted, so a ``\\"``
    there would be a literal backslash-quote to a spec-compliant
    parser (promtool flags it)."""
    return str(value).replace("\\", r"\\").replace("\n", r"\n")


def _labels_str(labels: Dict[str, str],
                extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels or {})
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{_LABEL_BAD.sub("_", str(k))}="{_esc(v)}"'
        for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _num(v: Any) -> str:
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(f) if f != int(f) else str(int(f))


def render_prometheus(rows: List[Dict[str, Any]]) -> str:
    """Render ``MetricsRegistry.collect()`` rows as Prometheus text
    exposition (format 0.0.4). One ``# HELP``/``# TYPE`` pair per
    family (first occurrence wins), histograms as cumulative
    ``_bucket{le=...}``/``_sum``/``_count`` — the shape every scraper
    and ``promtool check metrics`` understands."""
    by_family: Dict[str, List[Dict[str, Any]]] = {}
    kinds: Dict[str, str] = {}
    for r in rows:
        fam = prom_name(r.get("name", "unnamed"))
        kind = r.get("kind", "gauge")
        if kinds.setdefault(fam, kind) != kind:
            # name collision across kinds after sanitization — keep the
            # first family's kind, expose the latecomer suffixed so no
            # series silently disappears from the scrape
            fam = fam + "_" + kind
            kinds.setdefault(fam, kind)
        by_family.setdefault(fam, []).append(r)
    out: List[str] = []
    for fam in sorted(by_family):
        rows_f = by_family[fam]
        kind = kinds[fam]
        first = rows_f[0]
        out.append(f"# HELP {fam} raft_tpu series "
                   f"{_esc_help(first.get('name', fam))}")
        if kind == "histogram":
            out.append(f"# TYPE {fam} histogram")
            for r in rows_f:
                labels = r.get("labels") or {}
                buckets = r.get("buckets") or {}
                entries = sorted(
                    ((float("inf") if k == "+inf" else float(k), cum)
                     for k, cum in buckets.items()))
                for ub, cum in entries:
                    out.append(
                        f"{fam}_bucket"
                        f"{_labels_str(labels, {'le': _num(ub)})}"
                        f" {_num(cum)}")
                out.append(f"{fam}_sum{_labels_str(labels)} "
                           f"{_num(r.get('sum', 0.0))}")
                out.append(f"{fam}_count{_labels_str(labels)} "
                           f"{_num(r.get('count', 0))}")
        else:
            out.append(f"# TYPE {fam} "
                       f"{'counter' if kind == 'counter' else 'gauge'}")
            for r in rows_f:
                out.append(f"{fam}{_labels_str(r.get('labels') or {})} "
                           f"{_num(r.get('value', 0.0))}")
    return "\n".join(out) + "\n"


def _unescape(value: str) -> str:
    return re.sub(r"\\(.)",
                  lambda m: "\n" if m.group(1) == "n" else m.group(1),
                  value)


def _parse_labels(body: str, lineno: int) -> Dict[str, str]:
    """Parse one ``k="v",k2="v2"`` label body. Values are matched as
    quoted strings with escapes (a value may legally contain commas,
    braces, and ``\\"``), so splitting on raw commas would corrupt
    them; anything the pair grammar doesn't fully consume raises."""
    labels: Dict[str, str] = {}
    pos = 0
    while pos < len(body):
        m = _LABEL_PAIR_RE.match(body, pos)
        if not m:
            raise ValueError(
                f"malformed label body at line {lineno}: {body!r}")
        labels[m.group(1)] = _unescape(m.group(2))
        pos = m.end()
        if pos < len(body):
            if body[pos] != ",":
                raise ValueError(
                    f"malformed label body at line {lineno}: {body!r}")
            pos += 1
    return labels


def parse_prometheus(text: str) -> Dict[str, List[Dict[str, Any]]]:
    """Minimal text-format parser (the CI smoke's validity check, and a
    convenience for tests): returns ``{family: [{"labels", "value"}]}``
    with ``_bucket``/``_sum``/``_count`` series folded under their
    histogram family name. Raises ``ValueError`` on a malformed line —
    "parses cleanly" is the assertion."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            raise ValueError(
                f"malformed exposition line {lineno}: {line!r}")
        name, body, value = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(body, lineno) if body else {}
        fam = re.sub(r"_(bucket|sum|count)$", "", name)
        out.setdefault(fam, []).append(
            {"series": name, "labels": labels,
             "value": float(value) if value not in ("+Inf", "-Inf")
             else float(value.replace("Inf", "inf"))})
    return out


#: process birth, for uptime (monotonic — wall-clock steps must not
#: make the process look younger/older than it is)
_PROC_START_MONO = time.monotonic()


def process_rows() -> List[Dict[str, Any]]:
    """The standard ``process_*`` self-telemetry family (ISSUE 20):
    RSS, CPU seconds, open fds, uptime — stdlib ``resource``/``os``
    only, best-effort (a metric whose source is unavailable on this
    platform is omitted, never a scrape failure)."""
    rows: List[Dict[str, Any]] = []
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        rows.append({"kind": "counter",
                     "name": "process_cpu_seconds_total",
                     "value": float(ru.ru_utime + ru.ru_stime)})
        rss = None
        try:
            with open("/proc/self/statm") as f:
                rss = (int(f.read().split()[1])
                       * os.sysconf("SC_PAGE_SIZE"))
        except (OSError, ValueError, IndexError):
            # ru_maxrss is the high-water mark in KB on Linux — a
            # coarser stand-in where /proc is absent
            rss = int(ru.ru_maxrss) * 1024
        rows.append({"kind": "gauge",
                     "name": "process_resident_memory_bytes",
                     "value": float(rss)})
    except (ImportError, OSError):
        pass
    try:
        rows.append({"kind": "gauge", "name": "process_open_fds",
                     "value": float(len(os.listdir("/proc/self/fd")))})
    except OSError:
        pass
    rows.append({"kind": "gauge", "name": "process_uptime_seconds",
                 "value": time.monotonic() - _PROC_START_MONO})
    return rows


def process_text() -> str:
    """:func:`process_rows` rendered as exposition text — appended to
    ``/metrics`` after the registry families. Names stay unprefixed
    (the Prometheus-conventional spellings scrape configs expect), so
    this renders directly instead of riding :func:`render_prometheus`
    and its ``raft_tpu_`` namespace."""
    out: List[str] = []
    for r in process_rows():
        name = r["name"]
        out.append(f"# HELP {name} process self-telemetry")
        out.append(f"# TYPE {name} {r['kind']}")
        out.append(f"{name} {_num(r['value'])}")
    return "\n".join(out) + "\n"


class ExpoServer:
    """The exposition endpoint: ``start()`` binds and serves on a
    daemon thread, ``stop()`` shuts down. ``port=0`` binds an
    ephemeral port (read it back from :attr:`port` after start).

    ``registry`` — a :class:`~raft_tpu.obs.metrics.MetricsRegistry` or
    a zero-arg callable returning one (default: whatever
    ``obs.spans.registry()`` resolves at scrape time, so a registry
    swap mid-run is reflected).
    ``health`` — optional zero-arg callable returning the serving
    registry's ``describe()`` dict; drives ``/healthz``.
    ``flight_dump`` — optional zero-arg callable returning a dump path;
    default :func:`raft_tpu.obs.flight.dump_now`.
    ``indexz`` — optional zero-arg callable returning the per-tenant
    index-health dict (ISSUE 16); drives ``GET /indexz``.
    ``costz`` — optional zero-arg callable returning the cost-plane
    dict (per-tenant ledger + capacity forecast, ISSUE 20); drives
    ``GET /costz``.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Any = None,
                 health: Optional[Callable[[], Dict[str, Any]]] = None,
                 flight_dump: Optional[Callable[[], Optional[str]]] = None,
                 indexz: Optional[Callable[[], Dict[str, Any]]] = None,
                 costz: Optional[Callable[[], Dict[str, Any]]] = None):
        self._port_req = int(port)
        self.host = host
        self._registry = registry
        self._health = health
        self._flight_dump = flight_dump
        self._indexz = indexz
        self._costz = costz
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- payload builders (shared with tests) -------------------------------
    def _resolve_registry(self) -> _metrics.MetricsRegistry:
        reg = self._registry
        if callable(reg):
            reg = reg()
        if reg is None:
            from raft_tpu.obs import spans as _spans

            reg = _spans.registry()
        return reg

    def metrics_text(self) -> str:
        return (render_prometheus(self._resolve_registry().collect())
                + process_text())

    def health_payload(self) -> (int, Dict[str, Any]):
        """(status_code, body): 200 while serving is possible — no
        health provider at all, or at least one tenant resident
        (warming/serving/degraded); 503 when a registry is wired and
        every tenant is terminal (the "scrape says page someone"
        state). Tenant states ride in the body either way."""
        if self._health is None:
            return 200, {"status": "ok", "tenants": {}}
        try:
            desc = self._health() or {}
        except Exception as e:  # a sick registry is itself a 503
            return 503, {"status": "error", "error": repr(e)}
        tenants = {t.get("name", "?"): t.get("state", "?")
                   for t in desc.get("tenants", [])}
        resident = [n for n, s in tenants.items()
                    if s in ("warming", "serving", "degraded")]
        ok = bool(resident) or not tenants
        # the quality plane (ISSUE 16): a recall-floor breach or a
        # degraded tenant keeps serving (HTTP 200 — results still flow)
        # but the status string flips to "degraded" so orchestration
        # that reads the body sees quality trouble before users do
        slo = desc.get("slo") or {}
        degraded = (bool(slo.get("recall_floor_breached"))
                    or any(s == "degraded" for s in tenants.values()))
        status = "ok" if ok else "unavailable"
        if ok and degraded:
            status = "degraded"
        body: Dict[str, Any] = {
            "status": status,
            "tenants": tenants,
            "resident": len(resident),
            "resident_bytes": desc.get("resident_bytes"),
            "budget_bytes": desc.get("budget_bytes"),
        }
        if slo:
            body["slo"] = slo
        return (200 if ok else 503), body

    def flight_payload(self) -> (int, Dict[str, Any]):
        try:
            if self._flight_dump is not None:
                path = self._flight_dump()
            else:
                from raft_tpu.obs import flight as _flight

                path = _flight.dump_now(reason="flightz")
        except Exception as e:
            return 500, {"status": "error", "error": repr(e)}
        if not path:
            return 500, {"status": "error",
                         "error": "flight dump unavailable"}
        return 200, {"status": "ok", "path": path}

    def indexz_payload(self) -> (int, Dict[str, Any]):
        """(status_code, body) for ``/indexz`` — the serving layer's
        per-tenant index-health dict. 404 when no provider is wired
        (standalone expo around a non-serving loop), 500 when the
        provider itself throws."""
        if self._indexz is None:
            return 404, {"status": "error",
                         "error": "no indexz provider wired"}
        try:
            return 200, (self._indexz() or {})
        except Exception as e:
            return 500, {"status": "error", "error": repr(e)}

    def costz_payload(self) -> (int, Dict[str, Any]):
        """(status_code, body) for ``/costz`` — the per-tenant cost
        ledger + capacity forecast (ISSUE 20). 404 when no provider is
        wired (standalone expo), 500 when the provider throws."""
        if self._costz is None:
            return 404, {"status": "error",
                         "error": "no costz provider wired"}
        try:
            return 200, (self._costz() or {})
        except Exception as e:
            return 500, {"status": "error", "error": repr(e)}

    # -- lifecycle ----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self.host}:{self.port}" if self._httpd else None

    def start(self) -> "ExpoServer":
        if self._httpd is not None:
            return self
        expo = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no stderr chatter per scrape
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        self._send(
                            200, expo.metrics_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/healthz":
                        code, doc = expo.health_payload()
                        self._send(code, json.dumps(doc).encode(),
                                   "application/json")
                    elif path == "/flightz":
                        code, doc = expo.flight_payload()
                        self._send(code, json.dumps(doc).encode(),
                                   "application/json")
                    elif path == "/indexz":
                        code, doc = expo.indexz_payload()
                        self._send(code, json.dumps(doc).encode(),
                                   "application/json")
                    elif path == "/costz":
                        code, doc = expo.costz_payload()
                        self._send(code, json.dumps(doc).encode(),
                                   "application/json")
                    else:
                        self._send(404, b'{"error": "not found"}',
                                   "application/json")
                except BrokenPipeError:  # scraper hung up mid-response
                    pass

        self._httpd = ThreadingHTTPServer((self.host, self._port_req),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="raft-tpu-expo", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "ExpoServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
