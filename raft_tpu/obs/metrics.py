"""Metrics registry — process-local counters, gauges, histograms.

The reference records per-stage timing/occupancy only through external
profilers (NVTX ranges consumed by nsys); production TPU serving needs
the numbers *in process* so the bench harness and a serving loop can
read them without attaching XProf. This registry is the sink the span
timers (:mod:`raft_tpu.obs.spans`) and HBM telemetry
(:mod:`raft_tpu.obs.hbm`) write into.

Design: deliberately tiny and dependency-free —

- three metric kinds (counter / gauge / histogram), each optionally
  labeled with a small ``dict`` of string labels (one time series per
  distinct label set, Prometheus-style);
- thread-safe: one registry lock for series creation, one lock per
  series for updates (hot-path updates never contend on the registry);
- ``snapshot()`` returns a plain nested dict (JSON-ready), and
  ``dump_jsonl(path)`` appends one self-describing JSON line per
  series — the format ``load_jsonl`` round-trips and the bench OBS
  smoke test parses.

A process-global default registry backs the module-level helpers;
:class:`~raft_tpu.core.resources.DeviceResources` hands it out as the
``"metrics"`` resource so handle-holding code needs no extra plumbing.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from raft_tpu.obs import sanitize as _sanitize

# Default histogram bucket upper bounds (seconds-oriented: spans are the
# main histogram producer; 10 µs .. 10 min covers a dispatch through a
# chunked 100M-row build stage).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0, 60.0, 600.0)

#: exemplar reservoir bound per histogram bucket: enough to name a few
#: concrete offenders per latency band, small enough that a long-lived
#: serving histogram stays O(buckets × this) no matter the traffic
EXEMPLARS_PER_BUCKET = 4


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render(name: str, lkey: Tuple[Tuple[str, str], ...]) -> str:
    """Stable display key: ``name`` or ``name{k=v,k2=v2}``."""
    if not lkey:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in lkey) + "}"


class Counter:
    """Monotonic counter (one labeled series)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        # RLock: the flight recorder's signal handler snapshots these
        # structures ON the interrupted main thread — a plain Lock the
        # interrupted frame already holds would deadlock the dying
        # process (same for every lock on the snapshot path below)
        self._lock = _sanitize.monitored_rlock("obs.metrics.counter")

    def inc(self, value: float = 1.0) -> None:
        if value < 0:
            raise ValueError("counters only go up (got %r)" % (value,))
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Set-to-current-value gauge (one labeled series)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = _sanitize.monitored_rlock("obs.metrics.gauge")  # signal-snapshot path, see Counter

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def max(self, value: float) -> None:
        """Keep the high-water mark (HBM peak sampling uses this)."""
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max (one labeled series).

    ``buckets`` are inclusive upper bounds; an implicit +inf bucket
    catches the tail (cumulative counts, Prometheus-style).
    """

    __slots__ = ("name", "labels", "buckets", "_bucket_counts", "_count",
                 "_sum", "_min", "_max", "_exemplars", "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 buckets: Optional[Iterable[float]] = None):
        self.name = name
        self.labels = dict(labels or {})
        self.buckets = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        self._bucket_counts = [0] * (len(self.buckets) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        # per-bucket exemplar reservoirs: {bucket_index: [(value, id)]},
        # lazily created — an exemplar-less histogram pays nothing
        self._exemplars: Optional[Dict[int, List[Tuple[float, str]]]] = None
        self._lock = _sanitize.monitored_rlock("obs.metrics.histogram")  # signal-snapshot path, see Counter

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        """Record one sample. ``exemplar`` (ISSUE 15) attaches an
        identity — a request trace id — to the sample: each bucket
        retains a bounded reservoir of its LARGEST exemplared values
        (:data:`EXEMPLARS_PER_BUCKET`), so a latency histogram's p99
        links directly to concrete slow requests instead of an
        anonymous bucket count."""
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value
            idx = len(self.buckets)
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    idx = i
                    break
            self._bucket_counts[idx] += 1
            if exemplar is None:
                return
            if self._exemplars is None:
                self._exemplars = {}
            res = self._exemplars.setdefault(idx, [])
            if len(res) < EXEMPLARS_PER_BUCKET:
                res.append((value, str(exemplar)))
            else:
                # keep the worst offenders: replace the reservoir's
                # smallest value when the new sample exceeds it —
                # within a bucket the largest values are the ones a
                # tail drill-down wants named
                j = min(range(len(res)), key=lambda jj: res[jj][0])
                if value > res[j][0]:
                    res[j] = (value, str(exemplar))

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (None when empty) —
        Prometheus ``histogram_quantile`` semantics: linear
        interpolation inside the bucket holding the q-th sample,
        clamped to the observed min/max so coarse buckets never report
        a value outside the data. p50/p99 of search latency in the
        bench OBS rows and ``tools/obsdump.py`` come from here."""
        return quantile_from_state(self.state(), q)

    def state(self) -> Dict[str, Any]:
        with self._lock:
            cum, counts = 0, []
            for c in self._bucket_counts:
                cum += c
                counts.append(cum)
            out = {
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
                "mean": (self._sum / self._count) if self._count else None,
                "buckets": {
                    **{repr(ub): counts[i]
                       for i, ub in enumerate(self.buckets)},
                    "+inf": counts[-1],
                },
            }
            if self._exemplars:
                # keyed like buckets (upper-bound repr / "+inf") so
                # JSONL rows and flight dumps round-trip alongside the
                # cumulative counts
                out["exemplars"] = {
                    ("+inf" if i >= len(self.buckets)
                     else repr(self.buckets[i])):
                    [{"value": v, "trace_id": t}
                     for v, t in sorted(res, reverse=True)]
                    for i, res in sorted(self._exemplars.items())}
            return out


class MetricsRegistry:
    """Thread-safe named-series registry (counters/gauges/histograms)."""

    def __init__(self) -> None:
        self._lock = _sanitize.monitored_rlock("obs.metrics.registry")  # signal-snapshot path, see Counter
        self._counters: Dict[Tuple[str, tuple], Counter] = {}
        self._gauges: Dict[Tuple[str, tuple], Gauge] = {}
        self._histograms: Dict[Tuple[str, tuple], Histogram] = {}

    # -- series accessors (get-or-create) ----------------------------------
    def counter(self, name: str,
                labels: Optional[Dict[str, str]] = None) -> Counter:
        key = (name, _label_key(labels))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter(name, labels)
            return c

    def gauge(self, name: str,
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        key = (name, _label_key(labels))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge(name, labels)
            return g

    def histogram(self, name: str,
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(name, labels, buckets)
            return h

    # -- shorthand update helpers ------------------------------------------
    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        self.counter(name, labels).inc(value)

    def set(self, name: str, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
        self.gauge(name, labels).set(value)

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None,
                exemplar: Optional[str] = None) -> None:
        self.histogram(name, labels).observe(value, exemplar=exemplar)

    # -- export -------------------------------------------------------------
    def collect(self) -> List[Dict[str, Any]]:
        """Structured series list — one self-describing dict per series
        (``{"kind", "name", "labels", ...value/state}``), the shape
        ``dump_jsonl`` writes and the exposition endpoint
        (:mod:`raft_tpu.obs.expo`) renders. Unlike :meth:`snapshot`,
        labels stay structured instead of rendered into the key."""
        with self._lock:
            rows: List[Dict[str, Any]] = []
            for (n, lk), c in self._counters.items():
                rows.append({"kind": "counter", "name": n,
                             "labels": dict(lk), "value": c.value})
            for (n, lk), g in self._gauges.items():
                rows.append({"kind": "gauge", "name": n,
                             "labels": dict(lk), "value": g.value})
            for (n, lk), h in self._histograms.items():
                rows.append({"kind": "histogram", "name": n,
                             "labels": dict(lk), **h.state()})
            return rows
    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view: ``{"counters": {key: v}, "gauges": {key: v},
        "histograms": {key: state}}`` with ``name{k=v}`` rendered keys."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        return {
            "counters": {_render(n, lk): c.value for (n, lk), c in counters},
            "gauges": {_render(n, lk): g.value for (n, lk), g in gauges},
            "histograms": {_render(n, lk): h.state() for (n, lk), h in hists},
        }

    def dump_jsonl(self, path: str, extra: Optional[Dict[str, Any]] = None,
                   max_mb: Optional[float] = None,
                   keep: Optional[int] = None) -> int:
        """Append one JSON line per series to ``path``; returns the number
        of lines written. ``extra`` keys are merged into every line
        (the bench runner stamps dataset/index/search_param context).

        **Rotation** (ISSUE 15): an always-on serving process dumping
        periodically would otherwise grow the sidecar file without
        bound. When the file already holds ≥ ``max_mb`` MB (default:
        ``RAFT_TPU_OBS_JSONL_MAX_MB``; unset/0 = unbounded — the
        one-shot bench behavior, unchanged), it is rotated
        ``path → path.1 → path.2 …`` keeping ``keep`` rotated files
        (default ``RAFT_TPU_OBS_JSONL_KEEP`` or 3, oldest dropped),
        each move an atomic ``os.replace`` so a reader never sees a
        torn file."""
        rows = self.collect()
        if extra:
            for r in rows:
                r.update(extra)
        if max_mb is None:
            max_mb = _env_float("RAFT_TPU_OBS_JSONL_MAX_MB", 0.0)
        if max_mb and max_mb > 0:
            _rotate_jsonl(path, max_mb,
                          keep if keep is not None
                          else int(_env_float("RAFT_TPU_OBS_JSONL_KEEP", 3)))
        with open(path, "a") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        return len(rows)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


def _env_float(name: str, default: float) -> float:
    """Numeric env knob (a value, not a boolean flag — GL02 covers flag
    parsing; unparseable values fall back to the default)."""
    raw = os.environ.get(name, "")  # numeric value, not a flag
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


def _rotate_jsonl(path: str, max_mb: float, keep: int) -> None:
    """Size-capped rotation ``path → path.1 → … → path.keep`` (atomic
    renames, oldest dropped). No-op while ``path`` is under the cap or
    absent; never raises — a rotation hiccup must not cost the dump."""
    try:
        if not os.path.exists(path) or \
                os.path.getsize(path) < max_mb * (1 << 20):
            return
        keep = max(int(keep), 1)
        oldest = f"{path}.{keep}"
        if os.path.exists(oldest):
            os.unlink(oldest)
        for i in range(keep - 1, 0, -1):
            src = f"{path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{path}.{i + 1}")
        os.replace(path, f"{path}.1")
    except OSError:
        pass


def exemplars_for_quantile(state: Dict[str, Any], q: float
                           ) -> List[Dict[str, Any]]:
    """The exemplars nearest quantile ``q`` of a ``Histogram.state()``
    dict: the reservoir of the bucket holding the q-th sample, falling
    back outward (higher buckets first — a tail query wants the worst
    offenders) when that bucket recorded none. Returns
    ``[{"value", "trace_id"}, ...]`` sorted worst-first; ``[]`` when
    the histogram holds no exemplars at all. This is how a reported
    p99 resolves to concrete slow-request trace ids (ISSUE 15)."""
    ex = state.get("exemplars") or {}
    if not ex or not state.get("count"):
        return []

    def _ub(key: str) -> float:
        return float("inf") if key == "+inf" else float(key)

    entries = sorted(((_ub(k), cum) for k, cum in
                      (state.get("buckets") or {}).items()))
    rank = min(max(float(q), 0.0), 1.0) * state["count"]
    target_keys = [k for k, _ in sorted(
        ((k, _ub(k)) for k in ex), key=lambda kv: kv[1])]
    # the bucket holding the q-th sample
    prev_cum, q_ub = 0, float("inf")
    for ub, cum in entries:
        if cum >= rank and cum - prev_cum > 0:
            q_ub = ub
            break
        prev_cum = cum
    # exact bucket first, then above (worse), then below
    above = [k for k in target_keys if _ub(k) >= q_ub]
    below = [k for k in reversed(target_keys) if _ub(k) < q_ub]
    for key in above + below:
        res = ex.get(key)
        if res:
            return sorted(res, key=lambda e: -float(e.get("value", 0.0)))
    return []


def quantile_from_state(state: Dict[str, Any], q: float
                        ) -> Optional[float]:
    """Bucket-interpolated quantile from a ``Histogram.state()`` dict
    (works on live states, JSONL rows, and flight-dump snapshots alike
    — the buckets are cumulative counts keyed by upper bound)."""
    count = state.get("count") or 0
    if not count:
        return None
    lo_clamp = state.get("min")
    hi_clamp = state.get("max")
    entries = []
    for key, cum in (state.get("buckets") or {}).items():
        ub = float("inf") if key == "+inf" else float(key)
        entries.append((ub, cum))
    entries.sort()
    if not entries:
        return hi_clamp
    rank = min(max(float(q), 0.0), 1.0) * count
    prev_cum, lower = 0, 0.0
    for ub, cum in entries:
        in_bucket = cum - prev_cum
        if cum >= rank and in_bucket > 0:
            if ub == float("inf"):
                est = hi_clamp if hi_clamp is not None else lower
            else:
                est = lower + (rank - prev_cum) / in_bucket * (ub - lower)
            if lo_clamp is not None:
                est = max(est, lo_clamp)
            if hi_clamp is not None:
                est = min(est, hi_clamp)
            return float(est)
        prev_cum = cum
        if ub != float("inf"):
            lower = ub
    return float(hi_clamp) if hi_clamp is not None else None


def counter_sum(rows: List[Dict[str, Any]], name: str,
                **match: str) -> float:
    """Sum every counter series named ``name`` whose labels carry all
    of ``match`` (subset match — unmatched extra labels are fine) over
    a :meth:`MetricsRegistry.collect` row list. The delta machinery in
    the SLO monitor, the cost ledger's counter folds, and the capacity
    model's arrival rates all aggregate through here."""
    total = 0.0
    for r in rows:
        if r.get("kind") == "counter" and r.get("name") == name:
            labels = r.get("labels") or {}
            if all(labels.get(k) == v for k, v in match.items()):
                total += float(r.get("value", 0.0))
    return total


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a ``dump_jsonl`` file back into a list of series dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


_global_registry = MetricsRegistry()
_global_lock = _sanitize.monitored_lock("obs.metrics.global")


def get_registry() -> MetricsRegistry:
    """The process-global default registry (what spans record into and
    ``DeviceResources.metrics`` hands out unless overridden)."""
    return _global_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (returns the previous one) — the
    bench runner installs a fresh one per measured row."""
    global _global_registry
    with _global_lock:
        prev = _global_registry
        _global_registry = registry
        return prev
