"""Flight recorder — telemetry that survives the process.

Round 5's lesson: the machinery worked but the *evidence* died with the
process — a SIGTERM'd bench leg left QPS numbers nobody could
decompose, and a killed deep-100m run left nothing at all. The flight
recorder makes process death leave a black box behind:
``install(dump_dir)`` registers atexit + signal-chained dumping of

- the event ring buffer (:mod:`raft_tpu.obs.trace` — the timeline),
- a full metrics-registry snapshot (spans, comm counters, HBM gauges),
- the last-N ``raft_tpu`` log lines (a ring-buffer logging handler),

into a timestamped ``flight_*.json``. Periodic checkpointing
(``every_s`` or ``RAFT_TPU_FLIGHT_EVERY_S``) additionally rewrites a
``flight_<pid>_latest.json`` on a daemon thread, so even a SIGKILL'd
run leaves a dump at most one period old — the round-5 outage failure
mode (``kill -9`` from the stall watchdog) becomes diagnosable.

Signal handling CHAINS: the previous handler (e.g. ``bench.py``'s
partial-record ``_die``) runs after the dump; an unhandled signal
re-raises its default disposition so exit codes stay honest. Import is
cheap (no jax); nothing is registered until :func:`install`.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from raft_tpu.obs import sanitize as _sanitize

from raft_tpu.obs import fleet as _fleet
from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import spans as _spans
from raft_tpu.obs import trace as _trace

SCHEMA = "raft_tpu.flight/1"
# SIGINT rides beside SIGTERM/SIGALRM (ISSUE 14): a Ctrl-C'd *serving*
# process previously lost its flight dump — the one run a human was
# watching closely enough to interrupt is exactly the one whose shed /
# deadline counters they wanted. Chaining preserves KeyboardInterrupt:
# the prior handler (Python's default_int_handler unless the app
# replaced it) still runs after the dump.
DEFAULT_SIGNALS = ("SIGTERM", "SIGALRM", "SIGINT")
DEFAULT_LOG_LINES = 200


class _LogTail(logging.Handler):
    """Keep the last N formatted ``raft_tpu`` log lines in a ring."""

    def __init__(self, maxlen: int):
        super().__init__()
        self.lines: deque = deque(maxlen=maxlen)
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.lines.append(self.format(record))
        except Exception:  # a broken record must never kill the app
            pass


def _watchdog_kill_info() -> Optional[Dict[str, Any]]:
    """Parse the stall-kill sidecar ``tools/run_watchdog.sh`` exports
    via the ``WATCHDOG_KILL_INFO`` env var (a JSON file path the
    watchdog writes just before SIGTERM). None when unset, absent, or
    unparseable — a broken sidecar must never cost the dump."""
    path = os.environ.get("WATCHDOG_KILL_INFO", "")  # path value
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            info = json.load(f)
        return info if isinstance(info, dict) else {"raw": info}
    except Exception:
        return None


def _robust_state() -> Dict[str, Any]:
    """Active fault plan (rules + live hit/fire counts) and the recent
    degrade-ladder moves, via ``sys.modules`` — the robust package is
    never imported FROM a dump path (``bench.py`` spec-loads
    ``faults``/``retry`` standalone before jax; importing the package
    route from a signal handler could re-enter a wedged import). {}
    when nothing robust is loaded or armed — and any failure stays
    silent: folding extras must never cost the dump."""
    out: Dict[str, Any] = {}
    try:
        faults_mod = sys.modules.get("raft_tpu.robust.faults")
        if faults_mod is not None:
            plan = faults_mod.active_plan()
            if plan is not None:
                out["fault_plan"] = plan.describe()
                out["fault_fires"] = plan.fires()
        degrade_mod = sys.modules.get("raft_tpu.robust.degrade")
        if degrade_mod is not None:
            steps = degrade_mod.recent_steps()
            if steps:
                out["degrade_recent"] = steps
    except Exception:
        return {}
    return out


# Pluggable dump sections (ISSUE 15): long-lived subsystems register a
# snapshot callable (the serving layer registers its IndexRegistry's
# describe() under "serve_registry") so every dump — crash, periodic,
# /flightz — carries their state without flight knowing their types.
_sections: Dict[str, Any] = {}
_sections_lock = _sanitize.monitored_rlock("obs.flight.sections")


def set_section(name: str, provider) -> None:
    """Register ``provider()`` (a zero-arg callable returning JSON-able
    data) to be folded into every dump under key ``name``. Re-setting a
    name replaces it. Providers run on the (possibly dying) dump path:
    they must be host-only and fast; any failure is swallowed."""
    with _sections_lock:
        _sections[name] = provider


def clear_section(name: str) -> None:
    """Remove a registered section (idempotent)."""
    with _sections_lock:
        _sections.pop(name, None)


def _section_snapshots() -> Dict[str, Any]:
    with _sections_lock:
        providers = dict(_sections)
    out: Dict[str, Any] = {}
    for name, fn in providers.items():
        try:
            out[name] = fn()
        except Exception:
            pass  # a sick provider must never cost the dump
    return out


def _resolve_signals(signals: Sequence) -> List[int]:
    out = []
    for s in signals:
        if isinstance(s, str):
            s = getattr(signal, s)
        out.append(int(s))
    return out


class FlightRecorder:
    """One per-process recorder; use :func:`install` for the singleton."""

    def __init__(self, dump_dir: str,
                 last_n_log_lines: int = DEFAULT_LOG_LINES):
        self.dump_dir = dump_dir
        self._t0 = time.time()
        self._prev_handlers: Dict[int, Any] = {}
        self._log_tail = _LogTail(last_n_log_lines)
        # RLock: a signal landing mid-dump re-enters dump() on the
        # same (main) thread — block the process' death on itself never
        self._dump_lock = _sanitize.monitored_rlock("obs.flight.dump")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._atexit_registered = False
        os.makedirs(dump_dir, exist_ok=True)
        from raft_tpu.core import logging as _log

        _log.get_logger().addHandler(self._log_tail)

    # -- payload ------------------------------------------------------------
    def payload(self, reason: str) -> Dict[str, Any]:
        """The dump body — everything is already-materialized host data
        (no jax, no device round-trips: safe from a signal handler)."""
        buf = _trace.get_buffer()
        try:
            metrics = _spans.registry().snapshot()
        except Exception:  # a half-swapped registry must not lose the dump
            metrics = _metrics.get_registry().snapshot()
        out = {
            "schema": SCHEMA,
            "reason": reason,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "argv": list(sys.argv),
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "uptime_s": round(time.time() - self._t0, 3),
            # fleet identity (ISSUE 15): run_id + host/pid/rank + the
            # clock anchor pair, so obs.fleet.aggregate can merge this
            # dump with its pod siblings on one aligned timeline
            "fleet": _fleet.identity(),
            "metrics": metrics,
            "events": buf.snapshot(),
            "dropped_events": buf.dropped,
            "logs": list(self._log_tail.lines),
        }
        for name, body in _section_snapshots().items():
            out.setdefault(name, body)  # core keys are not overridable
        watchdog = _watchdog_kill_info()
        if watchdog is not None:
            # why an external supervisor killed us (tools/run_watchdog.sh
            # writes its stall-kill reason + elapsed time to the file
            # named by WATCHDOG_KILL_INFO just before SIGTERM) — the
            # dump then says WHY it was killed, not just that it was
            out["watchdog"] = watchdog
        robust = _robust_state()
        if robust:
            # the robust↔obs cross-link: what the chaos lane had
            # injected (active fault plan + live fire counts) and how
            # far the run had degraded (recent ladder moves), so a
            # killed chaos-lane run's dump says what was IN FLIGHT,
            # not just what died
            out["robust"] = robust
        return out

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> str:
        """Write one dump; returns its path. Re-entrancy-safe (a dump
        triggered while another is mid-write waits its turn) and atomic
        (tmp + rename), so a signal landing mid-dump can't leave a
        truncated JSON behind."""
        if path is None:
            stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
            path = os.path.join(
                self.dump_dir, f"flight_{stamp}_{os.getpid()}.json")
        body = self.payload(reason)
        with self._dump_lock:
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(body, f)
                    # fsync BEFORE the rename: without it a power loss /
                    # SIGKILL after the (atomic) rename but before the
                    # data reaches disk can leave a zero-byte "latest"
                    # dump — the rename must never outrun its contents
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                # never leave tmp litter; the dump path either exposes a
                # complete file or nothing
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        return path

    # -- signal / atexit / periodic hooks -----------------------------------
    def install_signals(self, signals: Sequence = DEFAULT_SIGNALS) -> None:
        """Dump on the given signals, then CHAIN to the prior handler
        (or re-raise the default disposition) — the recorder observes
        the death, it does not change it."""
        for signum in _resolve_signals(signals):
            if signum in self._prev_handlers:
                continue

            def _handler(num, frame, _self=self):
                try:
                    _self.dump(reason=f"signal {num}")
                except Exception:
                    pass  # dying is the priority; a failed dump stays silent
                prev = _self._prev_handlers.get(num)
                if callable(prev):
                    prev(num, frame)
                elif prev != signal.SIG_IGN:
                    signal.signal(num, signal.SIG_DFL)
                    os.kill(os.getpid(), num)

            self._prev_handlers[signum] = signal.signal(signum, _handler)

    def install_atexit(self) -> None:
        if not self._atexit_registered:
            atexit.register(self._atexit_dump)
            self._atexit_registered = True

    def _atexit_dump(self) -> None:
        try:
            self.dump(reason="atexit")
        except Exception:
            pass

    def start_periodic(self, every_s: float) -> None:
        """Checkpoint ``flight_<pid>_latest.json`` every ``every_s``
        seconds on a daemon thread — the SIGKILL insurance."""
        if self._thread is not None or every_s <= 0:
            return
        latest = os.path.join(self.dump_dir,
                              f"flight_{os.getpid()}_latest.json")

        def loop():
            while not self._stop.wait(every_s):
                try:
                    self.dump(reason="periodic", path=latest)
                except Exception:
                    pass  # filesystem hiccups must not kill the thread

        self._thread = threading.Thread(
            target=loop, name="raft-tpu-flight", daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Stop the periodic thread and restore chained signal handlers
        (tests; production recorders live for the process)."""
        self._stop.set()
        if self._thread is not None:
            with _sanitize.blocking_region("join"):
                self._thread.join(timeout=5)
            self._thread = None
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):  # non-main thread / torn down
                pass
        self._prev_handlers.clear()
        from raft_tpu.core import logging as _log

        _log.get_logger().removeHandler(self._log_tail)


_recorder: Optional[FlightRecorder] = None
_recorder_lock = _sanitize.monitored_lock("obs.flight.recorder")


def install(dump_dir: str,
            signals: Sequence = DEFAULT_SIGNALS,
            every_s: Optional[float] = None,
            last_n_log_lines: int = DEFAULT_LOG_LINES,
            use_atexit: bool = True) -> FlightRecorder:
    """Install the process flight recorder (idempotent: a second call
    returns the existing one). ``every_s=None`` reads
    ``RAFT_TPU_FLIGHT_EVERY_S`` (unset/0 → no periodic checkpoints);
    ``signals=()`` skips signal hooks for callers with their own
    handlers (``bench.py`` dumps from ``_die`` itself)."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            return _recorder
        rec = FlightRecorder(dump_dir, last_n_log_lines=last_n_log_lines)
        if every_s is None:
            raw = os.environ.get("RAFT_TPU_FLIGHT_EVERY_S", "")
            try:
                every_s = float(raw) if raw.strip() else 0.0
            except ValueError:
                every_s = 0.0
        if signals:
            rec.install_signals(signals)
        if use_atexit:
            rec.install_atexit()
        if every_s and every_s > 0:
            rec.start_periodic(every_s)
        _recorder = rec
        return rec


def installed() -> Optional[FlightRecorder]:
    return _recorder


def uninstall() -> None:
    """Tear down the singleton (tests)."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            _recorder.close()
            _recorder = None


def dump_now(reason: str = "manual",
             dump_dir: Optional[str] = None) -> Optional[str]:
    """Dump immediately; auto-installs a default recorder (no signal
    hooks) when none exists — the one-liner for crash paths like
    ``bench.py``'s ``_die``. Returns the dump path, or None when even
    the dump directory can't be created."""
    rec = _recorder
    if rec is None:
        if dump_dir is None:
            dump_dir = os.environ.get(  # path value, not a flag
                "RAFT_TPU_FLIGHT_DIR", "/tmp/raft_tpu_flight")
        try:
            rec = install(dump_dir, signals=(), every_s=0.0)
        except Exception:
            return None
    try:
        return rec.dump(reason=reason)
    except Exception:
        return None
