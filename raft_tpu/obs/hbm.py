"""HBM telemetry — device memory stats as gauges.

One home for the ``device.memory_stats()`` calls that were previously
ad-hoc (the recon-cache sizing probe buried in ``neighbors/ivf_pq.py``
moved here). TPU/GPU PJRT clients report an allocator dict
(``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit``, ...); the CPU
client reports nothing — every helper degrades to ``None``/``{}``
instead of raising, so instrumented code runs identically on the CPU
test mesh.

:func:`sample` writes the readings into a metrics registry
(``hbm.bytes_in_use`` set-to-current, ``hbm.peak_bytes`` high-water) —
the span timers call it at root-span exit when observability is on
(nested-span exits skip it: the ``memory_stats()`` round-trip would
land inside every ancestor span's timed region).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def device_memory_stats(device: Optional[Any] = None) -> Dict[str, int]:
    """``device.memory_stats()`` with all failure modes collapsed to an
    empty dict (CPU backend, remote plugins mid-outage, very old jax)."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats()
        return dict(stats) if stats else {}
    except Exception:
        return {}


def bytes_in_use(device: Optional[Any] = None) -> Optional[int]:
    """Live allocated HBM bytes, or None when the backend doesn't report."""
    v = device_memory_stats(device).get("bytes_in_use")
    return int(v) if v is not None else None


def peak_bytes(device: Optional[Any] = None) -> Optional[int]:
    """Allocator high-water mark (process lifetime), or None."""
    v = device_memory_stats(device).get("peak_bytes_in_use")
    return int(v) if v is not None else None


def bytes_limit(device: Optional[Any] = None,
                default: Optional[int] = None) -> Optional[int]:
    """Total HBM the allocator may use (the capacity heuristics' input —
    e.g. the IVF-PQ recon-cache sizing), or ``default``."""
    v = device_memory_stats(device).get("bytes_limit")
    return int(v) if v else default


def sample(registry=None, device: Optional[Any] = None) -> Dict[str, int]:
    """Record current HBM gauges into ``registry`` (default: the global
    one) and return the raw stats dict ({} when unavailable)."""
    if registry is None:
        from raft_tpu.obs import metrics as _metrics

        registry = _metrics.get_registry()
    stats = device_memory_stats(device)
    if stats:
        if "bytes_in_use" in stats:
            registry.gauge("hbm.bytes_in_use").set(stats["bytes_in_use"])
        if "peak_bytes_in_use" in stats:
            registry.gauge("hbm.peak_bytes").max(stats["peak_bytes_in_use"])
        if "bytes_limit" in stats:
            registry.gauge("hbm.bytes_limit").set(stats["bytes_limit"])
    return stats
