"""HBM telemetry — device memory stats as gauges.

One home for the ``device.memory_stats()`` calls that were previously
ad-hoc (the recon-cache sizing probe buried in ``neighbors/ivf_pq.py``
moved here). TPU/GPU PJRT clients report an allocator dict
(``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit``, ...); the CPU
client reports nothing — every helper degrades to ``None``/``{}``
instead of raising, so instrumented code runs identically on the CPU
test mesh.

:func:`sample` writes the readings into a metrics registry
(``hbm.bytes_in_use`` set-to-current, ``hbm.peak_bytes`` high-water) —
the span timers call it at root-span exit when observability is on
(nested-span exits skip it: the ``memory_stats()`` round-trip would
land inside every ancestor span's timed region). By default it samples
EVERY local device into per-device-labeled gauges
(``hbm.bytes_in_use{device=0..n}``) so sharded runs see each chip, with
device 0 mirrored into the unlabeled series for single-chip readers.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


def device_memory_stats(device: Optional[Any] = None) -> Dict[str, int]:
    """``device.memory_stats()`` with all failure modes collapsed to an
    empty dict (CPU backend, remote plugins mid-outage, very old jax)."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats()
        return dict(stats) if stats else {}
    except Exception:
        return {}


def bytes_in_use(device: Optional[Any] = None) -> Optional[int]:
    """Live allocated HBM bytes, or None when the backend doesn't report."""
    v = device_memory_stats(device).get("bytes_in_use")
    return int(v) if v is not None else None


def peak_bytes(device: Optional[Any] = None) -> Optional[int]:
    """Allocator high-water mark (process lifetime), or None."""
    v = device_memory_stats(device).get("peak_bytes_in_use")
    return int(v) if v is not None else None


def bytes_limit(device: Optional[Any] = None,
                default: Optional[int] = None) -> Optional[int]:
    """Total HBM the allocator may use (the capacity heuristics' input —
    e.g. the IVF-PQ recon-cache sizing), or ``default``."""
    v = device_memory_stats(device).get("bytes_limit")
    return int(v) if v else default


def _local_devices() -> list:
    try:
        import jax

        return list(jax.local_devices())
    except Exception:
        return []


def _record(registry, stats: Dict[str, int], labels: Optional[Dict],
            events, suffix: str) -> None:
    if "bytes_in_use" in stats:
        registry.gauge("hbm.bytes_in_use", labels).set(stats["bytes_in_use"])
        if events is not None:
            events.record_counter("hbm.bytes_in_use" + suffix,
                                  stats["bytes_in_use"])
    if "peak_bytes_in_use" in stats:
        registry.gauge("hbm.peak_bytes", labels).max(
            stats["peak_bytes_in_use"])
        if events is not None:
            events.record_counter("hbm.peak_bytes" + suffix,
                                  stats["peak_bytes_in_use"])
    if "bytes_limit" in stats:
        registry.gauge("hbm.bytes_limit", labels).set(stats["bytes_limit"])


def note_budget(budget_bytes: int, registry=None) -> None:
    """Mirror an externally-resolved HBM budget into the
    ``hbm.bytes_limit`` gauge family, under its OWN labeled series
    ``{source=admission}``.

    Backends that report no allocator stats (the CPU test mesh) leave
    the ``hbm.*`` family empty — but a serving process still HAS an
    authoritative limit: the one its :class:`~raft_tpu.serve.registry.
    IndexRegistry` admits against. Recording it keeps the exposition
    endpoint's ``hbm_*`` families populated on every backend. The
    distinct label matters on real devices: the unlabeled and
    ``{device=i}`` series belong to :func:`sample`'s allocator
    readings, and a capacity-capped registry (``budget_bytes`` <
    the chip's limit) must not flip-flop those between two meanings."""
    if registry is None:
        from raft_tpu.obs import metrics as _metrics

        registry = _metrics.get_registry()
    registry.gauge("hbm.bytes_limit", {"source": "admission"}).set(
        int(budget_bytes))


def sample(registry=None, device: Optional[Any] = None,
           events=None) -> Dict[str, int]:
    """Record current HBM gauges into ``registry`` (default: the global
    one) and return device 0's raw stats dict ({} when unavailable).

    With ``device=None`` (the span-exit path) EVERY local device is
    sampled into per-device-labeled gauges (``hbm.bytes_in_use{device=i}``
    etc.) so sharded runs see each chip's HBM, and device 0 additionally
    feeds the unlabeled series the bench's peak-HBM column reads. An
    explicit ``device`` samples just that one into the unlabeled series.
    Backends that report nothing (the CPU test mesh) degrade to ``{}``.
    ``events`` (an :class:`raft_tpu.obs.trace.EventBuffer`) additionally
    records one counter-track sample per gauge.
    """
    if registry is None:
        from raft_tpu.obs import metrics as _metrics

        registry = _metrics.get_registry()
    if device is not None:
        stats = device_memory_stats(device)
        if stats:
            _record(registry, stats, None, events, "")
        return stats
    first: Dict[str, int] = {}
    for i, dev in enumerate(_local_devices()):
        stats = device_memory_stats(dev)
        if i == 0:
            first = stats
        if not stats:
            continue
        _record(registry, stats, {"device": str(i)}, events,
                "{device=%d}" % i)
        if i == 0:  # unlabeled back-compat series mirrors device 0
            _record(registry, stats, None, None, "")
    return first
