"""Online recall estimation — a shadow verifier for live ANN traffic.

The quality plane's dynamic half (ISSUE 16). Every recall number this
repo has published so far was measured offline against benchmark ground
truth; the knobs that *trade* recall at runtime — fp8 QLUTs, the
degrade ladder's bf16/fp8/decline-fused rungs, refine ratios — run
unmeasured. This module closes the loop: a :class:`RecallVerifier`
samples a small fraction of live requests, replays each one through an
exact host-side brute-force scan over the tenant's dataset, and turns
the verdict stream into per-tenant recall gauges with Wilson confidence
intervals.

Strictly off the hot path, by construction:

- the serving thread pays one fraction draw per completed request
  (deterministic per-tenant RNG, so tests replay the accept pattern),
  a token-bucket rate check, and a bounded-reservoir insert — numpy
  copies of one query row and one id row, no chip work;
- verification runs on a background thread with **no deadline** (a
  shadow request can never shed real traffic), on the **host** in
  numpy (no jit caches touched, ``recompile_budget(0)`` holds);
- each replay is **admission-checked** against the registry's HBM
  headroom first — a budget-full chip skips verification (counted
  ``quality.skipped{reason=admission}``) rather than competing with
  tenants for bytes;
- burst overflow displaces reservoir entries (algorithm-R style) and
  over-rate samples are dropped, both counted, so sustained overload
  costs a bounded, constant verification load.

Gauges/counters (per tenant, per served k):
``quality.recall{tenant=,k=}`` (windowed mean),
``quality.recall_ci_low/high{tenant=,k=}`` (Wilson bounds),
``quality.samples{tenant=,k=}``, ``quality.verified{tenant=}``,
``quality.skipped{tenant=,reason=}``. Worst-recall exemplars ride the
PR-15 machinery: every verdict lands in the
``quality.recall_loss{tenant=}`` histogram with the request's trace id
as exemplar — the buckets retain the LARGEST losses, so
``obsdump --worst-recall`` resolves the worst answers to concrete
request timelines (which ladder rungs / lut_dtype served them).

:meth:`RecallVerifier.state` feeds the flight recorder's ``"quality"``
section (current per-tenant estimates + the last ≤32 verdicts with
trace ids), so a SIGKILL'd serving run keeps its quality evidence.
"""

from __future__ import annotations

import dataclasses
import math
import random
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _spans

__all__ = ["VerifierConfig", "RecallVerifier", "wilson_interval",
           "exact_topk_ids", "recall_at_k", "LOSS_BUCKETS"]

#: ``quality.recall_loss`` histogram edges (loss = 1 − recall). Fine
#: near zero — healthy tenants live there — with the exemplar
#: reservoirs of the upper buckets naming the worst-served requests.
LOSS_BUCKETS = (0.0, 0.01, 0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0)


def wilson_interval(hits: float, total: float, z: float = 1.96
                    ) -> Tuple[float, float]:
    """Wilson score interval for a binomial proportion — the right CI
    for recall estimated from few samples near 1.0 (a normal
    approximation would poke above 1.0 and collapse at p̂=1)."""
    if total <= 0:
        return (0.0, 1.0)
    p = hits / total
    z2 = z * z
    denom = 1.0 + z2 / total
    center = (p + z2 / (2.0 * total)) / denom
    half = (z * math.sqrt(p * (1.0 - p) / total
                          + z2 / (4.0 * total * total))) / denom
    return (max(0.0, center - half), min(1.0, center + half))


def exact_topk_ids(dataset: np.ndarray, query: np.ndarray, k: int,
                   metric: str = "sqeuclidean") -> np.ndarray:
    """Exact top-k row ids for one query — host numpy, O(n·d), no jit.
    Ordering matches the index metrics: inner_product/cosine maximize,
    every L2 flavor minimizes (sqrt and expansion don't change order).
    Cosine normalizes the query only — dataset row norms rescale all
    scores per-row identically under cosine's row normalization."""
    x = np.asarray(dataset, np.float32)
    q = np.asarray(query, np.float32).reshape(-1)
    if metric in ("inner_product", "cosine"):
        scores = x @ q
        if metric == "cosine":
            scores = scores / np.maximum(
                np.linalg.norm(x, axis=1), 1e-12)
        order = -scores
    else:
        order = np.sum(x * x, axis=1) - 2.0 * (x @ q)
    k = min(int(k), x.shape[0])
    part = np.argpartition(order, k - 1)[:k]
    return part[np.argsort(order[part], kind="stable")]


def recall_at_k(served_ids: np.ndarray, true_ids: np.ndarray,
                k: int) -> float:
    """|served ∩ exact| / k. Pads (-1) in the served row count against
    recall — a half-filled answer IS a quality failure."""
    served = {int(i) for i in np.asarray(served_ids).reshape(-1)[:k]
              if int(i) >= 0}
    true = {int(i) for i in np.asarray(true_ids).reshape(-1)[:k]}
    if not true:
        return 1.0
    return len(served & true) / float(max(k, 1))


@dataclasses.dataclass(frozen=True)
class VerifierConfig:
    """Shadow-verifier knobs.

    ``sample_fraction`` is the per-request acceptance probability
    (deterministic per-tenant RNG seeded from ``seed`` — tests replay
    the pattern). ``rate_limit_per_s`` is a per-tenant token bucket on
    *accepted* samples — the fraction bounds relative load, the bucket
    bounds absolute load under a traffic spike. ``reservoir_depth``
    bounds the pending-replay queue; bursts displace uniformly
    (algorithm-R) instead of growing it. ``window`` is the per-(tenant,
    k) verdict window the CI is computed over; ``max_verdicts`` the
    flight-section verdict ring."""

    sample_fraction: float = 0.02
    rate_limit_per_s: float = 50.0
    reservoir_depth: int = 32
    window: int = 64
    max_verdicts: int = 32
    seed: int = 0
    z: float = 1.96
    #: host-bytes safety factor for the admission check: a replay's
    #: working set is the host dataset view + one score row; device-
    #: resident datasets transfer through a transient this multiplies
    admission_factor: float = 1.0


class _Window:
    """Per-(tenant, k) rolling verdict window."""

    __slots__ = ("recalls",)

    def __init__(self, cap: int):
        self.recalls: Deque[float] = deque(maxlen=cap)


class RecallVerifier:
    """Reservoir-sampling shadow verifier over an
    :class:`~raft_tpu.serve.registry.IndexRegistry` (duck-typed: only
    ``peek``/``usable_bytes``/``resident_bytes`` are used).

    The serving loop calls :meth:`maybe_sample` per completed request;
    a daemon worker drains the reservoir, replays each sample exactly,
    and publishes gauges. ``on_verdict`` (set by the server) lets the
    SLO monitor re-evaluate recall floors as evidence arrives."""

    def __init__(self, registry: Any,
                 config: Optional[VerifierConfig] = None):
        self.registry = registry
        self.config = config or VerifierConfig()
        self.on_verdict: Optional[Callable[[str], None]] = None
        self._lock = _sanitize.monitored_lock("obs.quality")
        self._cond = threading.Condition(self._lock)
        self._pending: List[Dict[str, Any]] = []
        self._seen: Dict[str, int] = {}           # accepted, per tenant
        self._rngs: Dict[str, random.Random] = {}
        self._bucket: Dict[str, Tuple[float, float]] = {}  # tokens, t
        self._windows: Dict[Tuple[str, int], _Window] = {}
        self._verdicts: Deque[Dict[str, Any]] = deque(
            maxlen=self.config.max_verdicts)
        self._host_ds: Dict[str, Tuple[int, np.ndarray]] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._verified_total = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "RecallVerifier":
        with self._lock:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._worker,
                                        name="raft-tpu-quality-verifier",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- hot-path sampling --------------------------------------------------
    def _rng(self, tenant: str) -> random.Random:
        rng = self._rngs.get(tenant)
        if rng is None:
            # crc32, not hash(): str hashing is salted per process and
            # would break the deterministic-seed replay contract
            rng = random.Random(self.config.seed * 1_000_003
                               + zlib.crc32(tenant.encode()))
            self._rngs[tenant] = rng
        return rng

    def _take_token(self, tenant: str, now: float) -> bool:
        rate = self.config.rate_limit_per_s
        if rate <= 0:
            return True
        burst = max(1.0, rate)
        tokens, last = self._bucket.get(tenant, (burst, now))
        tokens = min(burst, tokens + (now - last) * rate)
        if tokens < 1.0:
            self._bucket[tenant] = (tokens, now)
            return False
        self._bucket[tenant] = (tokens - 1.0, now)
        return True

    def maybe_sample(self, tenant: str, query: np.ndarray, k: int,
                     served_ids: np.ndarray, trace_id: str) -> bool:
        """Offer one completed request for shadow verification. Returns
        whether it was enqueued. Cheap when not sampled: one RNG draw
        under the verifier lock (never the server's)."""
        if self.config.sample_fraction <= 0.0:
            return False
        now = time.monotonic()
        with self._lock:
            rng = self._rng(tenant)
            if rng.random() >= self.config.sample_fraction:
                return False
            if not self._take_token(tenant, now):
                self._count_skip(tenant, "rate_limit")
                return False
            self._seen[tenant] = self._seen.get(tenant, 0) + 1
            item = {"tenant": tenant, "k": int(k),
                    "query": np.array(query, np.float32, copy=True),
                    "ids": np.array(served_ids, copy=True).reshape(-1),
                    "trace_id": str(trace_id)}
            if len(self._pending) < self.config.reservoir_depth:
                self._pending.append(item)
            else:
                # algorithm-R over this tenant's accepted stream: keep
                # each accepted sample with equal probability, bounded
                # memory — bursts displace, never grow
                j = rng.randrange(self._seen[tenant])
                if j < self.config.reservoir_depth:
                    self._pending[j % len(self._pending)] = item
                self._count_skip(tenant, "reservoir")
            self._cond.notify()
            return True

    # -- background replay --------------------------------------------------
    def _worker(self) -> None:
        while True:
            with self._lock:
                while self._running and not self._pending:
                    self._cond.wait(0.1)
                if not self._running and not self._pending:
                    return
                item = self._pending.pop(0)
            try:
                self._verify(item)
            except Exception:  # noqa: BLE001 — a shadow replay must
                self._count_skip(item["tenant"], "error")  # never kill
                continue                                   # the worker

    def _admission_ok(self, tenant_rec: Any, dataset: Any) -> bool:
        """Refuse replay when the registry's HBM headroom cannot cover
        the replay working set (host view of a device-resident dataset
        + one score row) — shadow traffic must not contend with tenant
        admissions for bytes."""
        try:
            nbytes = int(getattr(dataset, "nbytes", 0))
            need = int(nbytes * self.config.admission_factor)
            if isinstance(dataset, np.ndarray):
                need = 0  # already host-resident: no transfer transient
            headroom = (int(self.registry.usable_bytes)
                        - int(self.registry.resident_bytes()))
            return need <= max(headroom, 0)
        except Exception:  # noqa: BLE001 — no registry capacity API:
            return True    # nothing to check against

    def _host_dataset(self, tenant: str, dataset: Any) -> np.ndarray:
        """Host view of the tenant's dataset, cached per tenant and
        invalidated when the tenant re-admits a different array."""
        key = id(dataset)
        cached = self._host_ds.get(tenant)
        if cached is not None and cached[0] == key:
            return cached[1]
        host = np.asarray(dataset, np.float32)
        self._host_ds[tenant] = (key, host)
        return host

    def _verify(self, item: Dict[str, Any]) -> None:
        tenant_name, k = item["tenant"], item["k"]
        try:
            tenant_rec = self.registry.peek(tenant_name)
        except Exception:  # noqa: BLE001 — evicted since sampling
            self._count_skip(tenant_name, "tenant_gone")
            return
        dataset = getattr(tenant_rec, "dataset", None)
        if dataset is None:
            self._count_skip(tenant_name, "no_dataset")
            return
        if not self._admission_ok(tenant_rec, dataset):
            self._count_skip(tenant_name, "admission")
            return
        metric = str(getattr(tenant_rec.index, "metric", "sqeuclidean"))
        host = self._host_dataset(tenant_name, dataset)
        true_ids = exact_topk_ids(host, item["query"], k, metric)
        recall = recall_at_k(item["ids"], true_ids, k)
        self._publish(tenant_name, k, recall, item["trace_id"])
        cb = self.on_verdict
        if cb is not None:
            try:
                cb(tenant_name)
            except Exception:  # noqa: BLE001
                pass

    # -- aggregation / publication ------------------------------------------
    def _publish(self, tenant: str, k: int, recall: float,
                 trace_id: str) -> None:
        with self._lock:
            win = self._windows.get((tenant, k))
            if win is None:
                win = self._windows[(tenant, k)] = _Window(
                    self.config.window)
            win.recalls.append(recall)
            self._verified_total += 1
            self._verdicts.append({
                "ts": round(time.time(), 3), "tenant": tenant, "k": k,
                "recall": round(recall, 4), "trace_id": trace_id})
            n = len(win.recalls)
            hits = sum(win.recalls)
        lo, hi = wilson_interval(hits, n, self.config.z)
        if _spans.enabled():
            reg = _spans.registry()
            labels = {"tenant": tenant, "k": str(k)}
            reg.gauge("quality.recall", labels=labels).set(hits / n)
            reg.gauge("quality.recall_ci_low", labels=labels).set(lo)
            reg.gauge("quality.recall_ci_high", labels=labels).set(hi)
            reg.gauge("quality.samples", labels=labels).set(n)
            reg.inc("quality.verified", labels={"tenant": tenant})
            # the worst-recall exemplar ride (ISSUE 15 machinery): the
            # loss histogram's upper buckets retain the LARGEST losses
            # with their trace ids — obsdump --worst-recall resolves
            # them to full request timelines
            reg.histogram("quality.recall_loss",
                          labels={"tenant": tenant},
                          buckets=LOSS_BUCKETS).observe(
                              1.0 - recall, exemplar=trace_id)

    def _count_skip(self, tenant: str, reason: str) -> None:
        if _spans.enabled():
            _spans.registry().inc(
                "quality.skipped",
                labels={"tenant": tenant, "reason": reason})

    # -- read side ----------------------------------------------------------
    def recall_summary(self, tenant: str) -> Dict[int, Dict[str, float]]:
        """``{k: {"recall", "ci_low", "ci_high", "n"}}`` for a tenant —
        what the SLO monitor checks recall floors against."""
        with self._lock:
            wins = {kk: list(w.recalls)
                    for (t, kk), w in self._windows.items()
                    if t == tenant and w.recalls}
        out: Dict[int, Dict[str, float]] = {}
        for kk, recs in wins.items():
            n = len(recs)
            lo, hi = wilson_interval(sum(recs), n, self.config.z)
            out[kk] = {"recall": sum(recs) / n, "ci_low": lo,
                       "ci_high": hi, "n": float(n)}
        return out

    def state(self) -> Dict[str, Any]:
        """The flight recorder's ``"quality"`` section: current
        per-tenant/k estimates + the last ≤32 verdicts (trace ids
        included) — a killed serving run keeps its quality evidence."""
        with self._lock:
            verdicts = list(self._verdicts)
            keys = [(t, k) for (t, k), w in self._windows.items()
                    if w.recalls]
            verified = self._verified_total
        tenants: Dict[str, Any] = {}
        for t, k in keys:
            tenants.setdefault(t, {}).update(
                {str(k): self.recall_summary(t).get(k, {})})
        return {"config": {
                    "sample_fraction": self.config.sample_fraction,
                    "rate_limit_per_s": self.config.rate_limit_per_s,
                    "window": self.config.window},
                "verified_total": verified,
                "tenants": tenants,
                "verdicts": verdicts}
