"""Cooperative cancellation — the ``raft::interruptible`` analog.

The reference (core/interruptible.hpp:71) keeps a thread-local token;
``synchronize(stream)`` spin-yields on the GPU event and throws
``interrupted_exception`` when another thread calls ``cancel()`` — so
Ctrl-C aborts GPU work at the next sync point (pylibraft wires this into
Python via interruptible.pyx).

Under XLA there are no streams to spin on; the natural cancellation
points are the host-orchestration seams — between chunks of a streaming
build, between Lloyd iterations driven from the host, between bench
batches. :func:`cancellation_point` is called at those seams (e.g.
``ivf_pq.build_chunked``), and :func:`synchronize` is the
block-until-ready that doubles as a cancellation point, mirroring the
reference's sync-as-cancellation-point design.
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional, Union

import jax


class interrupted_exception(RuntimeError):
    """Raised at a cancellation point after :func:`cancel`
    (reference: raft::interruptible::interrupted_exception)."""


# Tokens are keyed by the Thread OBJECT in a weak dict, mirroring the
# reference's thread-local token store (interruptible.hpp:71 keeps a
# weak_ptr registry): entries die with their thread, so a cancel aimed
# at a thread that exits unconsumed can never leak onto a future thread
# whose OS ident happens to be recycled.
_tokens: "weakref.WeakKeyDictionary[threading.Thread, threading.Event]" = (
    weakref.WeakKeyDictionary())
_lock = threading.Lock()


def _resolve(thread: Optional[Union[int, threading.Thread]]
             ) -> Optional[threading.Thread]:
    if thread is None:
        return threading.current_thread()
    if isinstance(thread, threading.Thread):
        return thread
    for t in threading.enumerate():
        if t.ident == thread:
            return t
    return None  # already exited: nothing to cancel


def _token(thread: threading.Thread) -> threading.Event:
    with _lock:
        ev = _tokens.get(thread)
        if ev is None:
            ev = threading.Event()
            _tokens[thread] = ev
        return ev


def cancel(thread: Optional[Union[int, threading.Thread]] = None) -> None:
    """Request cancellation of a thread's raft_tpu work (default: the
    calling thread — useful from signal handlers). Accepts a Thread or an
    ident; an ident of an already-exited thread is a no-op. The target
    raises :class:`interrupted_exception` at its next cancellation point
    (reference: interruptible::cancel)."""
    t = _resolve(thread)
    if t is not None:
        _token(t).set()


def cancellation_point() -> None:
    """Raise if this thread was cancelled (reference: yield_no_throw /
    the check inside interruptible::synchronize). Clears the token so
    subsequent work can proceed, matching the reference's
    ``throw-and-reset`` semantics."""
    ev = _token(threading.current_thread())
    if ev.is_set():
        ev.clear()
        raise interrupted_exception("raft_tpu work cancelled")


def synchronize(*arrays) -> None:
    """Block on async results, then honor cancellation (reference:
    interruptible::synchronize — the sync that is also a cancellation
    point)."""
    for a in arrays:
        jax.block_until_ready(a)
    cancellation_point()
