"""Error handling — exception hierarchy + validation helpers.

TPU-native counterpart of the reference's error layer
(cpp/include/raft/core/error.hpp: ``raft::exception``, ``RAFT_EXPECTS``,
``RAFT_FAIL``). On TPU there is no CUDA error channel; the host-side
validation story (argument/shape checking with informative messages)
is what carries over.
"""

from __future__ import annotations


class RaftError(RuntimeError):
    """Base exception (reference: ``raft::exception``, core/error.hpp:63)."""


class LogicError(RaftError):
    """Invalid argument / precondition violation (``raft::logic_error``)."""


class InterruptedError_(RaftError):
    """Cooperative cancellation (``raft::interrupted_exception``,
    core/interruptible.hpp)."""


def expects(cond: bool, msg: str, *args) -> None:
    """Validate a precondition (reference: ``RAFT_EXPECTS``, core/error.hpp:152).

    Raises :class:`LogicError` with the formatted message when ``cond`` is
    falsy. Only call with host (trace-time) booleans — never with traced
    values inside jit.
    """
    if not cond:
        raise LogicError(msg % args if args else msg)


def fail(msg: str, *args) -> None:
    """Unconditional failure (reference: ``RAFT_FAIL``)."""
    raise LogicError(msg % args if args else msg)
