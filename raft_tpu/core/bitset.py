"""Bitset — packed device bitset for ANN sample pre-filtering.

TPU-native counterpart of ``raft::core::bitset`` (core/bitset.cuh: test :235,
flip :279). Bits pack little-endian into uint32 words; all ops are pure
functions on the packed array (value semantics — no in-place mutation),
which is the idiomatic JAX shape of the reference's device-mutable bitset.

The builder ops (:func:`create`, :func:`from_mask`, :func:`set_bits`,
:func:`to_mask`, :func:`count`) are jitted: each is ONE compiled program
instead of a chain of eager dispatches, and no implicit host↔device
scalar lifting happens at call time — verified by the sanitizer-mode
tests running them under ``jax.transfer_guard("disallow")``
(tests/test_sanitize.py). Broadcasts are explicit (``shifts[None, :]``):
the suite passes under ``jax_numpy_rank_promotion="raise"``.
:func:`test` is jitted with no static args — called inside the jitted
search paths (``sample_filter.passes`` inside ``_search_impl``) it
traces inline; called eagerly it is one program with the ``WORD_BITS``
constants baked in rather than lifted per call.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

WORD_BITS = 32


def n_words(bitset_len: int) -> int:
    return (bitset_len + WORD_BITS - 1) // WORD_BITS


@partial(jax.jit, static_argnames=("bitset_len", "default_value"))
def create(bitset_len: int, default_value: bool = True) -> jax.Array:
    """All-set (or all-clear) bitset of ``bitset_len`` bits."""
    fill = jnp.uint32(0xFFFFFFFF) if default_value else jnp.uint32(0)
    return jnp.full((n_words(bitset_len),), fill, dtype=jnp.uint32)


@jax.jit
def from_mask(mask: jax.Array) -> jax.Array:
    """Pack a boolean vector into a bitset."""
    n = mask.shape[0]
    pad = n_words(n) * WORD_BITS - n
    m = jnp.concatenate([mask.astype(jnp.uint32), jnp.zeros((pad,), jnp.uint32)])
    m = m.reshape(-1, WORD_BITS)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    return jnp.sum(m << shifts[None, :], axis=1, dtype=jnp.uint32)


@partial(jax.jit, static_argnames=("bitset_len",))
def to_mask(bits: jax.Array, bitset_len: int) -> jax.Array:
    """Unpack into a boolean vector of length ``bitset_len``."""
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)
    m = ((bits[:, None] >> shifts[None, :]) & 1).astype(jnp.bool_).reshape(-1)
    return m[:bitset_len]


@jax.jit
def word_at(bits: jax.Array, ids) -> jax.Array:
    """Gather the bitset word covering each id — the shared primitive
    behind :func:`test`, ``sample_filter.passes``, and the fused
    kernels' host-side filter-operand prep.

    Sentinel-preserving per the ``core/ids.py`` policy: negative ids
    (the ``-1`` invalid sentinel, in either id width) read word 0 —
    callers mask the result with ``ids >= 0``. The word-index divide
    runs in the INCOMING id dtype: an int64 id past 2³¹ must not narrow
    to int32 before ``// WORD_BITS`` (GL11; the filtered capacity proof
    traces this at n = 2.2e9)."""
    ids = jnp.asarray(ids)
    safe = jnp.where(ids >= 0, ids, 0)  # id-dtype preserved
    return bits[safe // WORD_BITS]


@jax.jit
def test(bits: jax.Array, idx) -> jax.Array:
    """Test bit(s) at ``idx`` (reference: bitset::test, core/bitset.cuh:235).

    Sentinel-preserving: negative ids (the ``-1`` pad sentinel) test
    False instead of wrapping to a live word."""
    idx = jnp.asarray(idx)
    word = word_at(bits, idx)
    off = jnp.where(idx >= 0, idx, 0) % WORD_BITS
    bit = ((word >> off.astype(jnp.uint32)) & 1).astype(jnp.bool_)
    return bit & (idx >= 0)


@partial(jax.jit, static_argnames=("value",))
def set_bits(bits: jax.Array, idx, value: bool = True) -> jax.Array:
    """Return a new bitset with bit(s) at ``idx`` set/cleared.

    Implemented as a segment-reduction over words (OR of the per-index
    one-hot patterns), not a scatter of read-modify-write words: with
    several indices landing in the same word a plain ``.at[word].set``
    keeps only one of the conflicting writes."""
    idx = jnp.atleast_1d(jnp.asarray(idx))
    word_idx = (idx // WORD_BITS).astype(jnp.int32)
    # scatter True into a [n_words, 32] boolean grid (duplicate targets
    # all write the same value, so collisions are harmless), then pack
    # each word's row into the OR-pattern
    updates = jnp.zeros((bits.shape[0], WORD_BITS), jnp.bool_)
    updates = updates.at[word_idx, (idx % WORD_BITS)].set(True)
    pattern = from_mask(updates.reshape(-1))
    if value:
        return bits | pattern
    return bits & ~pattern


def flip(bits: jax.Array) -> jax.Array:
    """Flip all bits (reference: bitset::flip, core/bitset.cuh:279)."""
    return ~bits


@partial(jax.jit, static_argnames=("bitset_len",))
def count(bits: jax.Array, bitset_len: int) -> jax.Array:
    """Population count over the valid prefix."""
    return jnp.sum(to_mask(bits, bitset_len).astype(jnp.int32))


@jax.jit
def density(bits: jax.Array) -> jax.Array:
    """Set-bit fraction over the WHOLE word array — the cheap
    selectivity estimate feeding the fp8-LUT dispatch slack
    (``ivf_pq.resolve_lut_dtype``). Trailing pad bits inside the last
    word (at most 31) are counted as-is: a rounding error of
    ``< 32/n``, irrelevant to a dispatch heuristic."""
    pc = jax.lax.population_count(bits).astype(jnp.float32)
    return jnp.mean(pc) / WORD_BITS
