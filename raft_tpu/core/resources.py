"""Resources / handle — the context object passed to every raft_tpu API.

TPU-native re-design of the reference's handle stack:

- ``raft::resources`` (core/resources.hpp:47-136): a type-indexed registry of
  lazily-created resources via registered factories. Reproduced here as a
  string-keyed factory registry on :class:`Resources`.
- ``raft::device_resources`` (core/device_resources.hpp:61): the concrete
  handle carrying stream/BLAS handles/comms. On TPU, streams and vendor-library
  handles do not exist (XLA owns scheduling), so :class:`DeviceResources`
  carries what *does* matter on TPU: the target :class:`jax.Device`, the
  device :class:`~jax.sharding.Mesh` (for distributed work), a counter-based
  PRNG key source, the matmul precision policy, and an optional comms facade.
- ``device_resources_manager`` (core/device_resources_manager.hpp:79):
  process-wide per-device handle pool → :func:`get_device_resources`.

There is deliberately no stream-sync machinery: XLA dispatch is async and
value-semantic; :meth:`Resources.sync` maps to ``block_until_ready`` on
user-held arrays and exists for API parity.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Any, Callable, Dict, Optional

import jax
import numpy as np

from raft_tpu.core import logging as _log
from raft_tpu.core.errors import expects

if TYPE_CHECKING:
    from raft_tpu.obs import metrics as _obs_metrics


class Resources:
    """Type-indexed lazy resource registry (reference: core/resources.hpp:47).

    Factories are registered under a string key; the resource is created on
    first :meth:`get_resource` and cached. This mirrors the reference's
    ``add_resource_factory``/``get_resource`` design without C++ type tokens.
    """

    def __init__(self) -> None:
        self._factories: Dict[str, Callable[[], Any]] = {}
        self._resources: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def add_resource_factory(self, key: str, factory: Callable[[], Any]) -> None:
        with self._lock:
            self._factories[key] = factory
            self._resources.pop(key, None)

    def has_resource_factory(self, key: str) -> bool:
        with self._lock:
            return key in self._factories or key in self._resources

    def get_resource(self, key: str) -> Any:
        with self._lock:
            if key not in self._resources:
                expects(key in self._factories, "no resource factory for %r", key)
                self._resources[key] = self._factories[key]()
            return self._resources[key]

    def set_resource(self, key: str, value: Any) -> None:
        with self._lock:
            self._resources[key] = value


class RngKeySource:
    """Stateful wrapper over JAX's counter-based (threefry) PRNG.

    The reference's ``rng_state`` (random/rng_state.hpp:29) carries
    seed+subsequence so kernels are reproducible-stateless; JAX's key-splitting
    is the native version of the same idea. This source hands out fresh
    subkeys for APIs that take a handle instead of an explicit key.
    """

    def __init__(self, seed: int = 0):
        self._key = jax.random.PRNGKey(seed)
        self._lock = threading.Lock()

    def next_key(self) -> jax.Array:
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def reseed(self, seed: int) -> None:
        with self._lock:
            self._key = jax.random.PRNGKey(seed)


class DeviceResources(Resources):
    """The raft_tpu handle (reference: core/device_resources.hpp:61).

    Parameters
    ----------
    device : jax.Device, optional
        Target device; defaults to ``jax.devices()[0]``.
    mesh : jax.sharding.Mesh, optional
        Device mesh for distributed algorithms (replaces the reference's
        comms-in-handle; see raft_tpu.parallel).
    seed : int
        Seed for the handle's PRNG key source.
    precision : str
        Default matmul precision ("default" | "high" | "highest"); the TPU
        analog of cuBLAS math-mode selection.
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        mesh: Optional["jax.sharding.Mesh"] = None,
        seed: int = 0,
        precision: str = "highest",
    ) -> None:
        super().__init__()
        self._device = device
        self.precision = precision
        self.add_resource_factory("rng", lambda: RngKeySource(seed))
        if mesh is not None:
            self.set_resource("mesh", mesh)

    # -- accessors mirroring core/resource/*.hpp ---------------------------
    @property
    def device(self) -> jax.Device:
        if self._device is None:
            self._device = jax.devices()[0]
        return self._device

    @property
    def mesh(self) -> Optional["jax.sharding.Mesh"]:
        return self._resources.get("mesh")

    def set_mesh(self, mesh: "jax.sharding.Mesh") -> None:
        self.set_resource("mesh", mesh)

    @property
    def comms(self):
        """Injected communicator facade (reference: core/resource/comms.hpp)."""
        return self._resources.get("comms")

    def set_comms(self, comms) -> None:
        self.set_resource("comms", comms)

    @property
    def metrics(self) -> "_obs_metrics.MetricsRegistry":
        """The handle's metrics registry (see raft_tpu.obs.metrics): the
        one installed via :meth:`set_metrics`, else whatever registry
        spans currently record into — resolved per access, not cached,
        so a handle follows both ``obs.set_registry`` swaps and a
        temporary ``obs.enable(registry=...)`` override (the bench's
        per-row capture), and handle-recorded metrics land in the same
        sink as the spans'."""
        reg = self._resources.get("metrics")
        if reg is not None:
            return reg
        from raft_tpu.obs import spans as _obs_spans

        return _obs_spans.registry()

    def set_metrics(self, registry: "_obs_metrics.MetricsRegistry") -> None:
        self.set_resource("metrics", registry)

    def memory_stats(self) -> dict:
        """HBM telemetry for the handle's device (see raft_tpu.obs.hbm);
        empty dict on backends that don't report (CPU)."""
        from raft_tpu.obs import hbm as _hbm

        return _hbm.device_memory_stats(self.device)

    def next_rng_key(self) -> jax.Array:
        return self.get_resource("rng").next_key()

    def sync(self, *arrays) -> None:
        """Wait for async dispatch (reference: ``sync_stream``). Value-
        semantics means there is nothing global to sync; block on the given
        arrays if provided."""
        for a in arrays:
            jax.block_until_ready(a)

    def logger(self):
        return _log.get_logger()


class DeviceResourcesManager:
    """Process-wide pool of per-device handles for multi-threaded servers
    (reference: ``device_resources_manager``,
    core/device_resources_manager.hpp:79).

    The reference pools N handles per device, each with its own stream
    pool, and freezes configuration at first ``get_device_resources``.
    The TPU analog: N handles per device, each with an independent PRNG
    stream (the handle-local state that matters under XLA), options
    (pool size, seed, precision, mesh) settable only before first use —
    later setters log a warning and are ignored, matching the
    reference's behavior."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pools: Dict[int, list] = {}
        self._rr: Dict[int, int] = {}
        self._pool_size = 1
        self._seed = 0
        self._precision = "highest"
        self._mesh = None
        self._initialized = False

    def _warn_if_initialized(self, what: str) -> bool:
        if self._initialized:
            _log.warn("DeviceResourcesManager.%s ignored: pool already "
                      "initialized (set options before the first "
                      "get_device_resources, as the reference requires)", what)
            return True
        return False

    def set_pool_size(self, n: int) -> None:
        """Handles pooled per device (reference: set_streams_per_device)."""
        with self._lock:
            if not self._warn_if_initialized("set_pool_size"):
                expects(n >= 1, "pool size must be >= 1")
                self._pool_size = int(n)

    def set_seed(self, seed: int) -> None:
        with self._lock:
            if not self._warn_if_initialized("set_seed"):
                self._seed = int(seed)

    def set_precision(self, precision: str) -> None:
        with self._lock:
            if not self._warn_if_initialized("set_precision"):
                self._precision = precision

    def set_mesh(self, mesh) -> None:
        with self._lock:
            if not self._warn_if_initialized("set_mesh"):
                self._mesh = mesh

    def get_resources(self, device: Optional[jax.Device] = None
                      ) -> DeviceResources:
        """Round-robin a pooled handle for ``device`` (first call freezes
        the options, builds the pool lazily per device)."""
        if device is None:
            device = jax.devices()[0]
        with self._lock:
            self._initialized = True
            pool = self._pools.get(device.id)
            if pool is None:
                pool = [
                    DeviceResources(
                        device=device, mesh=self._mesh,
                        seed=int(np.uint32(self._seed + device.id * 7919 + i)),
                        precision=self._precision)
                    for i in range(self._pool_size)
                ]
                self._pools[device.id] = pool
                self._rr[device.id] = 0
            i = self._rr[device.id]
            self._rr[device.id] = (i + 1) % len(pool)
            return pool[i]


manager = DeviceResourcesManager()


def get_device_resources(device: Optional[jax.Device] = None) -> DeviceResources:
    """Process-wide per-device handle pool
    (reference: core/device_resources_manager.hpp:79)."""
    return manager.get_resources(device)
