"""Tracing annotations — the nvtx analog.

The reference wraps every major API in a scoped NVTX range
(``raft::common::nvtx::range``, core/nvtx.hpp:96-144 — e.g.
select_k-inl.cuh:289, ivf_pq_build.cuh:130), zero-cost unless profiling.
The TPU equivalents are:

- :func:`jax.named_scope` — labels the XLA ops traced inside the scope,
  so kernels show up under the API name in XProf/Perfetto op profiles;
- :class:`jax.profiler.TraceAnnotation` — a host-side span on the
  profiler timeline covering dispatch + host orchestration.

:func:`traced` applies both. Like NVTX, the cost when no profiler is
attached is negligible (a context-manager enter/exit per call), and the
XLA metadata is baked in at trace time only.

When the observability layer is enabled (:func:`raft_tpu.obs.enable`),
``traced`` additionally opens a recording :func:`span` named after the
API (``raft_tpu.`` prefix stripped), so every traced entry point's wall
time lands in the metrics registry and nested stage spans report under
dotted names like ``ivf_pq.search.scan``. In sync mode the function's
outputs are attached, so the span measures device time. With
observability off this adds one flag check per call — no clock reads,
no sync points.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

from raft_tpu.obs import spans as _spans
from raft_tpu.obs.spans import span  # noqa: F401  (re-export: the stage timer)


def annotate(name: str):
    """Named-scope annotation for code that is ALREADY inside a trace
    (shard_map/jit bodies — the collectives in ``parallel/comms.py``):
    labels the lowered XLA ops so they group under ``name`` in
    XProf/Perfetto op profiles. The host-side halves of :func:`traced`
    (TraceAnnotation, recording spans) are meaningless there — a traced
    body runs once at trace time — so this is just the metadata half."""
    return jax.named_scope(name)


def traced(name: Optional[str] = None) -> Callable:
    """Decorator: run the function under a named profiler scope
    (reference: RAFT_USING_NVTX / nvtx::range at API entry), plus a
    recording span when observability is enabled.

    Works with and without parentheses:

    >>> @traced("raft_tpu.select_k")
    ... def select_k(...): ...
    >>> @traced
    ... def helper(...): ...
    """
    if callable(name):  # bare @traced form
        return traced(None)(name)

    def deco(fn):
        label = name or f"raft_tpu.{fn.__qualname__}"
        span_name = label[len("raft_tpu."):] if label.startswith("raft_tpu.") \
            else label

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
                if not _spans.enabled():
                    return fn(*args, **kwargs)
                with span(span_name) as sp:
                    out = fn(*args, **kwargs)
                    sp.attach(out)
                    return out

        return wrapper

    return deco
