"""Tracing annotations — the nvtx analog.

The reference wraps every major API in a scoped NVTX range
(``raft::common::nvtx::range``, core/nvtx.hpp:96-144 — e.g.
select_k-inl.cuh:289, ivf_pq_build.cuh:130), zero-cost unless profiling.
The TPU equivalents are:

- :func:`jax.named_scope` — labels the XLA ops traced inside the scope,
  so kernels show up under the API name in XProf/Perfetto op profiles;
- :class:`jax.profiler.TraceAnnotation` — a host-side span on the
  profiler timeline covering dispatch + host orchestration.

:func:`traced` applies both. Like NVTX, the cost when no profiler is
attached is negligible (a context-manager enter/exit per call), and the
XLA metadata is baked in at trace time only.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax


def traced(name: Optional[str] = None) -> Callable:
    """Decorator: run the function under a named profiler scope
    (reference: RAFT_USING_NVTX / nvtx::range at API entry).

    >>> @traced("raft_tpu.select_k")
    ... def select_k(...): ...
    """

    def deco(fn):
        label = name or f"raft_tpu.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco
