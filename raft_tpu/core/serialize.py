"""Serialization — .npy-based array + header streaming for index save/load.

TPU-native counterpart of the reference's mdspan serializer
(core/serialize.hpp:35 ``serialize_mdspan``,
core/detail/mdspan_numpy_serializer.hpp): arrays stream as standard NumPy
``.npy`` records, scalars/POD headers as little-endian fixed-width fields.
Index checkpoint files produced here are self-describing and versioned
(cf. ``serialization_version`` in ivf_pq_types.hpp).
"""

from __future__ import annotations

import functools
import io
import json
import struct
from typing import Any, BinaryIO, Dict

import jax
import numpy as np

MAGIC = b"RAFTTPU\x00"


def serialize_scalar(f: BinaryIO, value) -> None:
    """Write one little-endian scalar (int64/float64/bool) with a type tag."""
    if isinstance(value, (bool, np.bool_)):
        f.write(b"b" + struct.pack("<?", bool(value)))
    elif isinstance(value, (int, np.integer)):
        f.write(b"i" + struct.pack("<q", int(value)))
    elif isinstance(value, (float, np.floating)):
        f.write(b"f" + struct.pack("<d", float(value)))
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        f.write(b"s" + struct.pack("<q", len(raw)) + raw)
    else:
        raise TypeError(f"unsupported scalar type: {type(value)}")


def deserialize_scalar(f: BinaryIO):
    tag = f.read(1)
    if tag == b"b":
        return struct.unpack("<?", f.read(1))[0]
    if tag == b"i":
        return struct.unpack("<q", f.read(8))[0]
    if tag == b"f":
        return struct.unpack("<d", f.read(8))[0]
    if tag == b"s":
        (n,) = struct.unpack("<q", f.read(8))
        return f.read(n).decode("utf-8")
    raise ValueError(f"bad scalar tag: {tag!r}")


# Device↔host transfer granularity for big arrays: a single multi-GB
# RPC degrades badly on tunnelled backends (a 9.7 GB fetch measured far
# below the ~25 MB/s a 512 MB fetch sustains, and has crashed workers);
# row slices keep the steady rate AND bound peak memory.
_FETCH_BYTES = 256 << 20


def _rows_per_chunk(arr, chunk_bytes: int = _FETCH_BYTES) -> int:
    return max(1, int(chunk_bytes
                      // max(arr.nbytes // max(arr.shape[0], 1), 1)))


def serialize_array(f: BinaryIO, arr) -> None:
    """Stream one array as a standard .npy record
    (reference: serialize_mdspan, core/serialize.hpp:35)."""
    if getattr(arr, "nbytes", 0) > _FETCH_BYTES and hasattr(arr, "shape") \
            and arr.ndim >= 1 and not isinstance(arr, np.ndarray):
        rows = _rows_per_chunk(arr)
        header = np.lib.format.header_data_from_array_1_0(
            np.empty((0,) + tuple(arr.shape[1:]),
                     np.dtype(str(arr.dtype))))
        header["shape"] = tuple(arr.shape)
        np.lib.format.write_array_header_1_0(f, header)
        for a in range(0, arr.shape[0], rows):
            block = np.asarray(jax.device_get(arr[a:a + rows]))
            f.write(np.ascontiguousarray(block).tobytes())
        return
    np.save(f, np.asarray(jax.device_get(arr)), allow_pickle=False)


def deserialize_array(f: BinaryIO) -> np.ndarray:
    return np.load(f, allow_pickle=False)


@functools.lru_cache(maxsize=None)
def _chunk_writer(ndim: int):
    import jax
    import jax.numpy as jnp

    def upd(b, blk, i):
        idx = (i,) + (jnp.int32(0),) * (ndim - 1)
        return jax.lax.dynamic_update_slice(b, blk, idx)

    return jax.jit(upd, donate_argnums=0)


def to_device_chunked(a: np.ndarray, chunk_bytes: int = _FETCH_BYTES):
    """Host→device transfer in row slices into a donated buffer — the
    upload mirror of serialize_array's sliced fetches (one multi-GB
    ``jnp.asarray`` RPC has stalled and even crashed tunnelled
    workers; ~256 MB slices sustain the steady rate and bound peak
    device allocation at buffer + one slice)."""
    import jax.numpy as jnp

    if a.nbytes <= chunk_bytes:
        return jnp.asarray(a)
    rows = _rows_per_chunk(a, chunk_bytes)
    buf = jnp.zeros(a.shape, a.dtype)
    upd = _chunk_writer(a.ndim)
    for i in range(0, a.shape[0], rows):
        if i + rows > a.shape[0] and i > 0:
            # ragged tail: overlap-write the LAST full-width slice so
            # every chunk compiles to one shape
            i = a.shape[0] - rows
        blk = np.ascontiguousarray(a[i:i + rows])
        buf = upd(buf, jnp.asarray(blk), jnp.int32(i))
    return buf


def serialize_header(f: BinaryIO, kind: str, version: int, meta: Dict[str, Any]) -> None:
    """Write the container header: magic, kind, version, JSON metadata."""
    f.write(MAGIC)
    serialize_scalar(f, kind)
    serialize_scalar(f, version)
    serialize_scalar(f, json.dumps(meta, sort_keys=True))


def deserialize_header(f: BinaryIO, expected_kind: str):
    magic = f.read(len(MAGIC))
    if magic != MAGIC:
        raise ValueError("not a raft_tpu serialized file (bad magic)")
    kind = deserialize_scalar(f)
    if kind != expected_kind:
        raise ValueError(f"expected {expected_kind!r} file, got {kind!r}")
    version = deserialize_scalar(f)
    meta = json.loads(deserialize_scalar(f))
    return version, meta


def save_arrays(path: str, kind: str, version: int, meta: Dict[str, Any], arrays: Dict[str, Any]) -> None:
    """Save a named-array container (one file per index)."""
    with open(path, "wb") as f:
        serialize_header(f, kind, version, meta)
        serialize_scalar(f, len(arrays))
        for name, arr in arrays.items():
            serialize_scalar(f, name)
            serialize_array(f, arr)


def load_arrays(path: str, kind: str):
    """Load a named-array container → (version, meta, {name: np.ndarray})."""
    with open(path, "rb") as f:
        version, meta = deserialize_header(f, kind)
        n = deserialize_scalar(f)
        arrays = {}
        for _ in range(n):
            name = deserialize_scalar(f)
            arrays[name] = deserialize_array(f)
    return version, meta, arrays
