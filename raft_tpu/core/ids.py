"""Row-id dtype policy — the TPU-native analog of the reference's
64-bit ``IdxT`` templating.

The reference templates every index on ``IdxT`` (``int64_t`` for the
billion-scale paths) so a dataset with n ≥ 2³¹ rows can be addressed at
all; raft_tpu instead carries ONE policy function and threads it through
every id-producing site. The contract:

- **int32 when provably safe, int64 when the row count demands it** —
  decided by :func:`id_dtype` from the addressed row count, never by
  per-site casts. int32 ids halve id-table HBM and are what the Pallas
  kernels (int32-only by construction) consume; they are kept exactly
  while ``n_rows ≤ 2³¹ − 1`` (ids span ``0 … n−1``; ``-1`` stays the
  invalid sentinel in both widths).
- **global-id arithmetic goes through** :func:`global_ids` /
  :func:`local_ids`: ``shard · shard_rows + local`` overflows int32 the
  moment the POD holds ≥ 2³¹ rows even though every per-shard id fits,
  so the offset math must run in the policy dtype of the *total* row
  count, not the shard's.
- **never narrow an id array blindly**: downstream code preserves the
  dtype an index/search produced (:func:`id_dtype_like`), so an int64
  index built for SIFT-1B flows through merge tiers and refine remaps
  without a silent ``astype(int32)`` truncation.

Enforced twice over: graftlint GL11 flags hard-coded int32 id
arithmetic at lint time, and ``obs.sanitize.assert_billion_safe``
(the eval_shape capacity prover) fails any entry whose traced program
still indexes a ≥ 2³¹ axis with int32 — see
docs/developer_guide.md ("id & accumulator dtype policy").

Note on x64: jax canonicalizes int64 → int32 unless ``jax_enable_x64``
is set. :func:`id_dtype` only ever *returns* int64 when the row count
actually needs it (> 2³¹ − 1 rows), and real billion-row runs require
x64 anyway; the capacity prover enables x64 inside a scoped
save/restore so proofs never leak the flag into the process.
"""

from __future__ import annotations

import numpy as np

# Largest row count whose ids (0 … n−1) all fit int32. The -1 invalid
# sentinel is representable in both widths, so it does not shrink the
# bound.
INT32_MAX_ROWS = 2**31 - 1


def id_dtype(n_rows: int):
    """The id dtype addressing ``n_rows`` dataset rows: ``jnp.int32``
    while every id fits (n_rows ≤ 2³¹ − 1), ``jnp.int64`` beyond — ONE
    policy decision instead of per-site casts."""
    import jax.numpy as jnp

    return jnp.int32 if int(n_rows) <= INT32_MAX_ROWS else jnp.int64


def np_id_dtype(n_rows: int):
    """Host (numpy) twin of :func:`id_dtype` — the chunked builders
    stamp global ids into host-side id tables."""
    return np.int32 if int(n_rows) <= INT32_MAX_ROWS else np.int64


def np_id_dtype_like(*id_arrays):
    """Host twin of :func:`id_dtype_like` over one or more numpy id
    arrays: int64 if ANY input is 64-bit (widths never narrow through a
    repack), int32 otherwise."""
    wide = any(np.dtype(a.dtype).itemsize >= 8
               and np.issubdtype(np.dtype(a.dtype), np.signedinteger)
               for a in id_arrays)
    return np.int64 if wide else np.int32


def id_dtype_like(ids):
    """Preserve an existing id array's width: int64 stays int64 (never
    silently truncate a billion-scale id), anything narrower or
    non-integer normalizes to int32."""
    import jax.numpy as jnp

    if np.issubdtype(np.dtype(ids.dtype), np.signedinteger) \
            and np.dtype(ids.dtype).itemsize >= 8:
        return jnp.int64
    return jnp.int32


def make_ids(n: int, start: int = 0, n_total: int = 0):
    """``jnp.arange(start, start + n)`` in the policy dtype — the
    replacement for default-dtype (or hard-int32) id iotas. The dtype is
    sized by the largest id produced (``start + n``) or by ``n_total``
    (the full dataset row count) when the caller knows it is larger."""
    import jax.numpy as jnp

    dt = id_dtype(max(int(start) + int(n), int(n_total)))
    return jnp.arange(start, start + n, dtype=dt)


def global_ids(rank, shard_rows: int, local_ids, n_total: int):
    """Shard-local ids → global ids: ``local + rank · shard_rows`` in
    ``id_dtype(n_total)`` (the POD-wide row count — the product
    overflows int32 even when every operand fits it). ``rank`` may be a
    traced per-device scalar (``Comms.get_rank()``). Invalid (< 0) local
    ids stay ``-1``."""
    import jax.numpy as jnp

    dt = id_dtype(n_total)
    loc = local_ids.astype(dt)
    off = jnp.asarray(rank).astype(dt) * jnp.asarray(shard_rows, dt)
    return jnp.where(loc >= 0, loc + off, jnp.asarray(-1, dt))


def local_ids(gids, rank, shard_rows: int):
    """Global ids → shard-local ids (the refine remap): ``gid − rank ·
    shard_rows`` computed in the incoming id width (never narrowed);
    invalid (< 0) global ids stay ``-1``. The caller masks ids outside
    ``[0, shard_rows)`` — they belong to other shards."""
    import jax.numpy as jnp

    dt = id_dtype_like(gids)
    g = gids.astype(dt)
    off = jnp.asarray(rank).astype(dt) * jnp.asarray(shard_rows, dt)
    return jnp.where(g >= 0, g - off, jnp.asarray(-1, dt))
