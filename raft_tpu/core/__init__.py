"""raft_tpu.core — handle/resources, errors, logging, bitset, serialization.

TPU-native counterpart of the reference's core layer
(cpp/include/raft/core): the mdspan/mdarray machinery collapses into JAX
arrays (value-semantic, device-placed), streams/vendor handles into XLA's
async dispatch, and the comms *interface* into raft_tpu.parallel.
"""

from raft_tpu.core.resources import (  # noqa: F401
    DeviceResources,
    DeviceResourcesManager,
    Resources,
    RngKeySource,
    get_device_resources,
    manager,
)
from raft_tpu.core.errors import RaftError, LogicError, expects, fail  # noqa: F401
from raft_tpu.core.tracing import traced  # noqa: F401
from raft_tpu.core.interruptible import (  # noqa: F401
    cancel,
    cancellation_point,
    interrupted_exception,
    synchronize,
)
from raft_tpu.core import logging, serialize, bitset, ids  # noqa: F401
