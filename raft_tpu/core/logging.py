"""Logging — leveled singleton logger with callback sink.

TPU-native counterpart of the reference's spdlog-backed logger
(core/logger-inl.hpp:103 ``logger::set_level``, core/logger-macros.hpp
``RAFT_LOG_*``, core/detail/callback_sink.hpp). Built on :mod:`logging`;
the callback-sink feature (reference uses it to redirect C++ logs into
Python) maps to a plain handler hook here.
"""

from __future__ import annotations

import logging as _pylogging
from typing import Callable, Optional

TRACE = 5
_pylogging.addLevelName(TRACE, "TRACE")

_logger = _pylogging.getLogger("raft_tpu")
_logger.addHandler(_pylogging.NullHandler())


def get_logger() -> _pylogging.Logger:
    return _logger


def set_level(level: int) -> None:
    """Set the global log level (reference: logger::set_level)."""
    _logger.setLevel(level)


class _CallbackHandler(_pylogging.Handler):
    def __init__(self, fn: Callable[[int, str], None]):
        super().__init__()
        self._fn = fn

    def emit(self, record: _pylogging.LogRecord) -> None:
        self._fn(record.levelno, self.format(record))


_callback_handler: Optional[_CallbackHandler] = None


def set_callback(fn: Optional[Callable[[int, str], None]]) -> None:
    """Install a callback sink (reference: core/detail/callback_sink.hpp)."""
    global _callback_handler
    if _callback_handler is not None:
        _logger.removeHandler(_callback_handler)
        _callback_handler = None
    if fn is not None:
        _callback_handler = _CallbackHandler(fn)
        _logger.addHandler(_callback_handler)


def trace(msg, *a):
    _logger.log(TRACE, msg, *a)


def debug(msg, *a):
    _logger.debug(msg, *a)


def info(msg, *a):
    _logger.info(msg, *a)


def warn(msg, *a):
    _logger.warning(msg, *a)


def error(msg, *a):
    _logger.error(msg, *a)
