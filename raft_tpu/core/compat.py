"""JAX version compatibility shims.

The codebase targets the modern surface (top-level ``jax.shard_map``
with ``check_vma=``); older jax (<0.6) ships ``shard_map`` under
``jax.experimental`` and spells the replication check ``check_rep=``.
Import :func:`shard_map` from here instead of from jax so both work.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # jax<0.6 ships it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)


try:
    from jax.lax import axis_size
except ImportError:
    def axis_size(axis_name):  # graftlint: disable-fn=GL10
        # psum of a Python literal over a named axis constant-folds to
        # the axis size (a concrete int) at trace time. GL10 exception:
        # zero wire traffic (folded before lowering), and Comms itself
        # calls this shim — routing it through the facade would be
        # circular.
        from jax import lax

        return lax.psum(1, axis_name)
