"""Dense fixed-radius neighborhood (ε-neighborhood).

TPU-native counterpart of the reference's
``raft::neighbors::epsilon_neighborhood::eps_neighbors_l2sq``
(neighbors/epsilon_neighborhood.cuh): boolean adjacency of all pairs
within squared-L2 radius, plus per-query vertex degrees — one tiled
pairwise-distance pass with a fused threshold epilogue.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.tracing import traced
from ..distance.pairwise import pairwise_distance


@traced("raft_tpu.eps_neighbors_l2sq")
def eps_neighbors_l2sq(
    x: jax.Array, y: jax.Array, eps_sq: float
) -> Tuple[jax.Array, jax.Array]:
    """adj[i, j] = ||x_i − y_j||² < eps_sq, and vd[i] = deg(x_i).

    Returns (adj [m, n] bool, vd [m] int32) — matching the reference's
    (adj, vd) output pair."""
    d = pairwise_distance(jnp.asarray(x), jnp.asarray(y), metric="sqeuclidean")
    adj = d < eps_sq
    return adj, jnp.sum(adj, axis=1, dtype=jnp.int32)
