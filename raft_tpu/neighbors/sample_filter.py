"""Sample filtering for ANN search — bitset-based pre-filtering.

TPU-native counterpart of the reference's sample filters
(neighbors/sample_filter_types.hpp ``bitset_filter`` /
``none_ivf_sample_filter``, core/bitset.cuh): a packed uint32 bitset
over dataset row ids where a **set bit means the vector may be
returned**.  Every search path accepts ``filter_bitset``; filtered
candidates are scored +inf (or −inf for similarities) before top-k, the
same exclusion point the reference's filters hook
(ivf_flat_interleaved_scan / ivf_pq_compute_similarity / cagra).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import bitset


def make_filter(
    n: int,
    remove=None,
    keep=None,
) -> jax.Array:
    """Build a filter bitset over ``n`` dataset rows.

    ``remove``: indices to exclude (all others kept) — the common
    "deleted vectors" case; ``keep``: indices to allow (all others
    excluded).  Exactly one may be given; neither → allow-all."""
    if remove is not None and keep is not None:
        raise ValueError("pass either remove or keep, not both")
    if keep is not None:
        bits = bitset.create(n, default_value=False)
        return bitset.set_bits(bits, jnp.asarray(keep), True)
    bits = bitset.create(n, default_value=True)
    if remove is not None:
        bits = bitset.set_bits(bits, jnp.asarray(remove), False)
    return bits


@jax.jit
def passes(filter_bits: Optional[jax.Array], ids: jax.Array) -> jax.Array:
    """Vectorized filter test for candidate id arrays (negative ids —
    padding — always fail). Jitted: inside the jitted search paths it
    traces inline (a ``None`` filter is pytree structure, so the branch
    is trace-static); called eagerly it is one program with no implicit
    scalar lifting — the sanitizer-mode transfer guard stays quiet
    (tests/test_sanitize.py)."""
    if filter_bits is None:
        return jnp.ones(ids.shape, jnp.bool_)
    ok = bitset.test(filter_bits, jnp.clip(ids, 0))
    return ok & (ids >= 0)
