"""Sample filtering for ANN search — bitset-based pre-filtering.

TPU-native counterpart of the reference's sample filters
(neighbors/sample_filter_types.hpp ``bitset_filter`` /
``none_ivf_sample_filter``, core/bitset.cuh): a packed uint32 bitset
over dataset row ids where a **set bit means the vector may be
returned**.  Every search path accepts ``filter_bitset``; filtered
candidates are scored +inf (or −inf for similarities) before top-k, the
same exclusion point the reference's filters hook
(ivf_flat_interleaved_scan / ivf_pq_compute_similarity / cagra).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import bitset


def make_filter(
    n: int,
    remove=None,
    keep=None,
) -> jax.Array:
    """Build a filter bitset over ``n`` dataset rows.

    ``remove``: indices to exclude (all others kept) — the common
    "deleted vectors" case; ``keep``: indices to allow (all others
    excluded).  Exactly one may be given; neither → allow-all."""
    if remove is not None and keep is not None:
        raise ValueError("pass either remove or keep, not both")
    if keep is not None:
        bits = bitset.create(n, default_value=False)
        return bitset.set_bits(bits, jnp.asarray(keep), True)
    bits = bitset.create(n, default_value=True)
    if remove is not None:
        bits = bitset.set_bits(bits, jnp.asarray(remove), False)
    return bits


@jax.jit
def passes(filter_bits: Optional[jax.Array], ids: jax.Array) -> jax.Array:
    """Vectorized filter test for candidate id arrays (negative ids —
    padding — always fail). Jitted: inside the jitted search paths it
    traces inline (a ``None`` filter is pytree structure, so the branch
    is trace-static); called eagerly it is one program with no implicit
    scalar lifting — the sanitizer-mode transfer guard stays quiet
    (tests/test_sanitize.py). Routed through ``bitset.word_at`` (via
    ``bitset.test``) so the word-index math runs in the incoming id
    width — the shared primitive the fused kernels' operand prep uses
    (:func:`list_filter_bytes`)."""
    if filter_bits is None:
        return jnp.ones(ids.shape, jnp.bool_)
    return bitset.test(filter_bits, ids)


def pack_mask_bytes(keep: jax.Array) -> jax.Array:
    """Pack a boolean keep-mask along its LAST axis into little-endian
    bytes (bit ``j`` of byte ``b`` = position ``8·b + j``) — the storage
    layout the fused Pallas scan tiers stream and unpack in-kernel with
    the same shift/mask machinery as the n-bit code unpack
    (``ops.pallas_kernels._lut_unpack_filter``). Row-major bits are
    identical to the uint32 bitset words' (both little-endian), so the
    byte view and the word view of one filter agree bit-for-bit."""
    L = keep.shape[-1]
    pad = (-L) % 8
    if pad:
        widths = [(0, 0)] * (keep.ndim - 1) + [(0, pad)]
        keep = jnp.pad(keep, widths, constant_values=False)
    m = keep.reshape(*keep.shape[:-1], -1, 8).astype(jnp.int32)
    # explicit rank-matched shift row (sanitizer mode raises on
    # implicit rank promotion)
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(
        (1,) * (m.ndim - 1) + (8,))
    return jnp.sum(m << shifts, axis=-1, dtype=jnp.int32).astype(jnp.uint8)


@jax.jit
def list_filter_bytes(filter_bits: jax.Array,
                      packed_ids: jax.Array) -> jax.Array:
    """Per-list packed filter mask ``[n_lists, ceil(L/8)]`` u8 — the
    host-side operand prep for the fused scan kernels: bit ``j`` of
    byte ``b`` in list ``l``'s row is 1 iff candidate
    ``packed_ids[l, 8·b + j]`` passes the filter (pad slots, id -1,
    pack as 0). One :func:`passes` gather over the id table plus a
    byte re-pack — O(n) work and n/8 output bytes per search, 32×
    smaller than streaming a per-candidate f32 bias and the reason the
    fused tiers stay admissible at billion scale
    (``ivf_common.filtered_scan_mem_ok`` budgets the transients)."""
    return pack_mask_bytes(passes(filter_bits, packed_ids))
