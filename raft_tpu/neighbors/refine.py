"""Refine — exact re-ranking of ANN candidate lists.

TPU-native counterpart of ``raft::neighbors::refine`` (refine-inl.cuh;
device kernel detail/refine_device.cuh, host/OpenMP variant
detail/refine_host-inl.hpp). Gathers each query's candidate rows and
recomputes exact distances (one batched MXU contraction), then selects the
top-k. Used after IVF-PQ search to recover recall lost to quantization
(the reference's refinement_rate pattern: search k·rate candidates,
refine down to k).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.utils.precision import get_precision


@partial(jax.jit, static_argnames=("k", "metric"))
def _refine_impl(dataset, queries, candidates, k: int, metric: str):
    safe_cand = jnp.maximum(candidates, 0)
    cand_rows = dataset[safe_cand].astype(jnp.float32)    # [m, C, d]
    return _refine_rows(cand_rows, queries, candidates, k, metric)


@partial(jax.jit, static_argnames=("k", "metric"))
def _refine_rows(cand_rows, queries, candidates, k: int, metric: str):
    mt = resolve_metric(metric)
    q = jnp.asarray(queries, jnp.float32)
    scores = jnp.einsum("md,mcd->mc", q, cand_rows,
                        precision=get_precision(),
                        preferred_element_type=jnp.float32)
    if mt == DistanceType.InnerProduct:
        dists = scores
        invalid = -jnp.inf
        select_min = False
    elif mt == DistanceType.CosineExpanded:
        qn = jnp.sqrt(jnp.maximum(jnp.sum(q * q, 1), 1e-30))
        cn = jnp.sqrt(jnp.maximum(jnp.sum(cand_rows**2, -1), 1e-30))
        dists = 1.0 - scores / (qn[:, None] * cn)
        invalid = jnp.inf
        select_min = True
    else:
        q_sq = jnp.sum(q * q, axis=1)
        c_sq = jnp.sum(cand_rows**2, axis=-1)
        dists = jnp.maximum(q_sq[:, None] + c_sq - 2.0 * scores, 0.0)
        if mt == DistanceType.L2SqrtExpanded:
            dists = jnp.sqrt(dists)
        invalid = jnp.inf
        select_min = True
    dists = jnp.where(candidates >= 0, dists, invalid)
    vals, pos = _select_k(dists, k, select_min=select_min)
    ids = jnp.take_along_axis(candidates, pos, axis=1)
    return vals, ids


@traced("raft_tpu.refine")
def refine(
    dataset: jax.Array,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` [m, n_cand] (row ids into ``dataset``, -1 =
    invalid) down to the exact top-k (reference: refine-inl.cuh).

    Returns (distances [m, k], ids [m, k]).
    """
    expects(candidates.ndim == 2, "candidates must be [m, n_candidates]")
    expects(queries.shape[0] == candidates.shape[0],
            "queries/candidates row mismatch")
    expects(k <= candidates.shape[1], "k=%d > n_candidates=%d",
            k, candidates.shape[1])
    mt = resolve_metric(metric)
    return _refine_impl(dataset, queries, candidates, k, mt.value)


@partial(jax.jit, donate_argnums=(0,))
def _fill_rows(buf, blk, lidx, pos):
    """Scatter gathered block rows into the candidate-row buffer
    (module-level so the jit cache hits across refine_provider calls;
    the last ``pos`` slot is the dump row for padding)."""
    return buf.at[pos].set(blk[lidx].astype(jnp.float32))


@traced("raft_tpu.refine_provider")
# the provider path exists to gather candidate rows on the HOST (memmap
# bases) — its device_get round-trip is the point, not a leak
def refine_provider(  # graftlint: disable-fn=GL01
    provider,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank against a device-chunk provider (bench.dataset.
    DeviceSyntheticChunks): regenerate each fixed-size generation block
    ON DEVICE and gather the candidate rows out of it — an EXACT f32
    re-rank with zero host traffic and no quantization error (the SQ8
    refine file loses ~1e-2 per squared distance, which on dense
    synthetic data exceeds neighbor gaps and caps recall; reference:
    the full-precision refinement_rate path, refine-inl.cuh).

    Cost is one generation pass over the provider's blocks (pipelined
    device programs; the gathered-row buffer is O(m·C·d) in HBM).
    """
    import numpy as np

    expects(candidates.ndim == 2, "candidates must be [m, n_candidates]")
    expects(queries.shape[0] == candidates.shape[0],
            "queries/candidates row mismatch")
    expects(k <= candidates.shape[1], "k=%d > n_candidates=%d",
            k, candidates.shape[1])
    mt = resolve_metric(metric)
    cand = np.asarray(candidates)
    m, C = cand.shape
    n, d = provider.shape
    c = provider.chunk_rows
    n_blocks = -(-n // c)
    flat = cand.reshape(-1)
    safe = np.clip(flat, 0, n - 1)
    block_of = safe // c
    counts = np.bincount(block_of, minlength=n_blocks)
    P = max(8, int(counts.max()))  # one compiled shape for every block

    buf = jnp.zeros((m * C + 1, d), jnp.float32)
    order = np.argsort(block_of, kind="stable")
    starts = np.searchsorted(block_of[order], np.arange(n_blocks + 1))
    for bi in range(n_blocks):
        sel = order[starts[bi]:starts[bi + 1]]
        if sel.size == 0:
            continue
        lidx = np.zeros((P,), np.int32)
        lidx[:sel.size] = safe[sel] - bi * c
        pos = np.full((P,), m * C, np.int32)
        pos[:sel.size] = sel
        buf = _fill_rows(buf, provider._block(bi), jnp.asarray(lidx),
                         jnp.asarray(pos))
    rows = buf[:m * C].reshape(m, C, d)
    return _refine_rows(rows, queries, jnp.asarray(cand), k, mt.value)


@traced("raft_tpu.refine_gathered")
# host-side candidate-row gather by design (memmap bases — jitted refine
# would materialize the whole base in HBM)
def refine_gathered(  # graftlint: disable-fn=GL01
    host_base,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    metric="sqeuclidean",
    dequant=None,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank against a HOST-resident (possibly memmapped) dataset:
    gather only each query's candidate rows on the host — O(m·C·d) pages
    touched, never the whole base — then re-rank on device (reference:
    the host refine path, detail/refine_host-inl.hpp, used by CAGRA
    builds and billion-scale benches where the base doesn't fit).

    ``dequant=(scale, zero)``: ``host_base`` holds int8 scalar-quantized
    rows (x ≈ zero + scale·code, per-dim) — the billion-scale refine
    file is 4× smaller and re-ranking ~20 candidates to top-k tolerates
    SQ8 precision easily."""
    import numpy as np

    expects(candidates.ndim == 2, "candidates must be [m, n_candidates]")
    expects(queries.shape[0] == candidates.shape[0],
            "queries/candidates row mismatch")
    expects(k <= candidates.shape[1], "k=%d > n_candidates=%d",
            k, candidates.shape[1])
    mt = resolve_metric(metric)
    cand = np.asarray(candidates)
    safe = np.clip(cand, 0, host_base.shape[0] - 1)
    rows = np.asarray(host_base[safe.reshape(-1)], np.float32).reshape(
        cand.shape[0], cand.shape[1], host_base.shape[1])
    if dequant is not None:
        scale, zero = dequant
        rows = rows * np.asarray(scale)[None, None, :] \
            + np.asarray(zero)[None, None, :]
    return _refine_rows(jnp.asarray(rows), queries, jnp.asarray(cand),
                        k, mt.value)
