"""Refine — exact re-ranking of ANN candidate lists.

TPU-native counterpart of ``raft::neighbors::refine`` (refine-inl.cuh;
device kernel detail/refine_device.cuh, host/OpenMP variant
detail/refine_host-inl.hpp). Used after IVF-PQ search to recover recall
lost to quantization (the reference's refinement_rate pattern: search
k·rate candidates, refine down to k).

Tier dispatch (``refine.dispatch{impl=...}`` obs counter; decision
table in docs/api_reference.md):

- ``pallas_gather`` — the fused gather-refine kernel
  (ops.pallas_kernels.gather_refine_topk): candidate rows stream
  HBM→VMEM per tile and the exact epilogue + top-k run on-chip, so the
  ``[m, C, d]`` gather buffer never exists (7.7 GB at batch 10000 ×
  k_cand 2000 × d 96 — the accumulator-OOM shape of the oversampled
  DEEP-100M configs). Auto-on for TPU oversampled shapes; env override
  ``RAFT_TPU_PALLAS_REFINE`` (tri-state).
- ``xla_gather`` — gather each query's candidate rows and recompute
  exact distances with one batched MXU contraction, then select.
- ``host_gather`` / ``provider_regen`` — the host-resident-base tiers
  (:func:`refine_gathered`, :func:`refine_provider`): the gather runs
  on the host / regenerates device blocks BY DESIGN (memmap bases that
  do not fit HBM), so the fused device tier does not apply.
- ``tiered_prefetch`` — the memory-tier pipeline (ISSUE 17,
  :mod:`raft_tpu.neighbors.tiered`): host-resident bases whose
  candidate rows are fetched host→HBM by a background reader
  overlapped under the next sub-batch's scan; :func:`refine_landed` is
  its re-rank entry (rows already on device — same exact epilogue,
  zero extra gather).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced, span
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.obs import spans as _obs_spans
from raft_tpu.robust import degrade as _degrade
from raft_tpu.robust import faults as _faults
from raft_tpu.utils.precision import get_precision


def _check_candidates(queries, candidates, k: int) -> None:
    """Shared argument validation for every refine entry point — an
    oversized k or an empty candidate axis otherwise surfaces deep in
    the jitted program as an opaque take_along_axis/einsum error."""
    expects(candidates.ndim == 2, "candidates must be [m, n_candidates]")
    expects(candidates.shape[1] > 0,
            "candidates must have a non-empty candidate axis "
            "(got shape %s)", tuple(candidates.shape))
    expects(queries.shape[0] == candidates.shape[0],
            "queries/candidates row mismatch: %d queries vs %d candidate "
            "rows", queries.shape[0], candidates.shape[0])
    expects(k <= candidates.shape[1],
            "k=%d > n_candidates=%d — refine can only re-rank the "
            "candidates it is given (search more candidates or lower k)",
            k, candidates.shape[1])


def _check_base_dim(base, queries) -> None:
    """Feature-dim agreement between the re-rank base and the queries —
    a mismatch otherwise dies in the einsum (or the Pallas block spec)
    with an opaque shape error. Row-count agreement stays the caller's
    contract: candidate ids past the base clamp to its last row (the
    historical XLA-gather semantics), and checking it here would cost a
    device sync per call on indexed structures."""
    shape = getattr(base, "shape", None)
    expects(shape is not None and len(shape) == 2
            and shape[1] == queries.shape[1],
            "dataset/queries feature-dim mismatch: dataset shape %s vs "
            "%d-dim queries", tuple(shape) if shape else None,
            queries.shape[1])


@partial(jax.jit, static_argnames=("k", "metric"))
def _refine_impl(dataset, queries, candidates, k: int, metric: str):
    safe_cand = jnp.maximum(candidates, 0)
    cand_rows = dataset[safe_cand].astype(jnp.float32)    # [m, C, d]
    return _refine_rows(cand_rows, queries, candidates, k, metric)


@partial(jax.jit, static_argnames=("k", "metric"))
def _refine_rows(cand_rows, queries, candidates, k: int, metric: str):
    mt = resolve_metric(metric)
    q = jnp.asarray(queries, jnp.float32)
    scores = jnp.einsum("md,mcd->mc", q, cand_rows,
                        precision=get_precision(),
                        preferred_element_type=jnp.float32)
    if mt == DistanceType.InnerProduct:
        dists = scores
        invalid = -jnp.inf
        select_min = False
    elif mt == DistanceType.CosineExpanded:
        qn = jnp.sqrt(jnp.maximum(jnp.sum(q * q, 1), 1e-30))
        cn = jnp.sqrt(jnp.maximum(jnp.sum(cand_rows**2, -1), 1e-30))
        dists = 1.0 - scores / (qn[:, None] * cn)
        invalid = jnp.inf
        select_min = True
    else:
        q_sq = jnp.sum(q * q, axis=1)
        c_sq = jnp.sum(cand_rows**2, axis=-1)
        dists = jnp.maximum(q_sq[:, None] + c_sq - 2.0 * scores, 0.0)
        if mt == DistanceType.L2SqrtExpanded:
            dists = jnp.sqrt(dists)
        invalid = jnp.inf
        select_min = True
    dists = jnp.where(candidates >= 0, dists, invalid)
    vals, pos = _select_k(dists, k, select_min=select_min)
    ids = jnp.take_along_axis(candidates, pos, axis=1)
    return vals, ids


@partial(jax.jit, static_argnames=("metric",))
def _gather_keys_to_dists(keys, ids, metric: str):
    """Kernel keys → reported distances: the gather-refine kernel emits
    minimized sort keys (l2: squared distance, ip: −score, cos: cosine
    distance); recover :func:`_refine_rows`' reporting convention."""
    mt = resolve_metric(metric)
    if mt == DistanceType.InnerProduct:
        return -keys, ids  # +inf invalid keys flip to -inf, as the XLA path
    if mt == DistanceType.L2SqrtExpanded:
        return jnp.sqrt(keys), ids
    return keys, ids


def _fused_refine_wanted(dataset, queries, candidates, k: int,
                         filtered: bool = False) -> bool:
    """True when the fused gather-refine tier serves this call: a
    device-resident 2-D dataset whose dtype the row DMAs stream (f32 or
    the bf16 recon cache) and a shape :func:`pallas_gather_refine_wanted`
    accepts (``filtered`` adds the per-candidate bitset-word scratch to
    its VMEM model)."""
    from raft_tpu.neighbors import ivf_common as ic
    from raft_tpu.ops import pallas_kernels as _pk

    if not isinstance(dataset, jax.Array) or dataset.ndim != 2:
        return False
    if dataset.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    mem_ok = ic.gather_refine_mem_ok(dataset.shape[0], dataset.shape[1],
                                     dataset.dtype.itemsize,
                                     m=candidates.shape[0],
                                     C=candidates.shape[1])
    if _faults.forced("refine.mem_guard"):  # CI-testable decline path
        mem_ok = False
    if not mem_ok:
        # the static half of the degradation policy (robust.degrade):
        # the guard's pre-emptive tier decline counts the same
        # degrade.steps move a reactive OOM walk would
        _degrade.note_step("refine", "pallas_gather", "xla_gather",
                           "mem_guard")
        return False
    return _pk.pallas_gather_refine_wanted(
        candidates.shape[0], candidates.shape[1], dataset.shape[1], k,
        itemsize=dataset.dtype.itemsize, filtered=filtered)


def _refine_fused(dataset, queries, candidates, k: int, mt: DistanceType,
                  filter_bits=None):
    from raft_tpu.ops import pallas_kernels as _pk

    met = ("ip" if mt == DistanceType.InnerProduct
           else "cos" if mt == DistanceType.CosineExpanded else "l2")
    with span("fused_scan") as _sp:
        keys, ids = _pk.gather_refine_topk(
            dataset, queries, jnp.asarray(candidates), k, met,
            filter_bits=filter_bits, interpret=not _pk._on_tpu())
        out = _gather_keys_to_dists(keys, ids, mt.value)
        _sp.attach(out)
    return out


@traced("raft_tpu.refine")
def refine(
    dataset: jax.Array,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    metric="sqeuclidean",
    filter_bits=None,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` [m, n_cand] (row ids into ``dataset``, -1 =
    invalid) down to the exact top-k (reference: refine-inl.cuh).

    Dispatches between the fused Pallas gather-refine kernel (streamed
    candidate rows, no ``[m, C, d]`` buffer — auto on TPU for
    oversampled shapes, override ``RAFT_TPU_PALLAS_REFINE``) and the
    XLA gather+einsum path; both share exact semantics (module
    docstring has the tier table). Returns (distances [m, k],
    ids [m, k]).

    ``filter_bits``: optional packed uint32 bitset over dataset rows
    (``core.bitset`` layout) — candidates whose bit is clear are
    excluded like invalid ids. The fused tier tests each candidate
    in-kernel against its bitset word (fetched by the row-DMA queue);
    the XLA tier sentinel-masks the candidate table first. Oversampled
    searches hand refine pre-filtered candidates already — the filter
    here is the enforcement site for DIRECT callers re-ranking an
    unfiltered candidate list.
    """
    _check_candidates(queries, candidates, k)
    _check_base_dim(dataset, queries)
    mt = resolve_metric(metric)
    filtered = filter_bits is not None
    if _fused_refine_wanted(dataset, queries, candidates, k,
                            filtered=filtered):
        if filtered:
            _obs_spans.count_dispatch("refine", "pallas_gather",
                                      filtered="1")
        else:
            _obs_spans.count_dispatch("refine", "pallas_gather")
        return _refine_fused(dataset, queries, candidates, k, mt,
                             filter_bits=filter_bits)
    if filtered:
        from raft_tpu.neighbors.sample_filter import passes

        # sentinel-mask before the gather: a filtered candidate becomes
        # the -1 invalid id _refine_rows already poisons to ±inf
        candidates = jnp.where(passes(filter_bits, candidates),
                               candidates, -1)
        _obs_spans.count_dispatch("refine", "xla_gather", filtered="1")
    else:
        _obs_spans.count_dispatch("refine", "xla_gather")
    return _refine_impl(dataset, queries, candidates, k, mt.value)


@traced("raft_tpu.refine_landed")
def refine_landed(
    cand_rows: jax.Array,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank against candidate rows ALREADY LANDED on device — the
    tiered prefetch pipeline's re-rank entry (ISSUE 17,
    :mod:`raft_tpu.neighbors.tiered`): the ``[m, C, d]`` f32 rows were
    gathered host-side by the background reader (bit-identical to
    :func:`refine_gathered`'s gather) and device_put ahead of time, so
    this entry runs only the exact epilogue (same jitted
    ``_refine_rows`` program as every other tier — same results)."""
    _check_candidates(queries, candidates, k)
    shape = getattr(cand_rows, "shape", None)
    expects(shape is not None and len(shape) == 3
            and tuple(shape[:2]) == tuple(candidates.shape)
            and shape[2] == queries.shape[1],
            "cand_rows shape %s does not match candidates %s × dim %d",
            tuple(shape) if shape else None, tuple(candidates.shape),
            queries.shape[1])
    _obs_spans.count_dispatch("refine", "tiered_prefetch")
    mt = resolve_metric(metric)
    return _refine_rows(cand_rows, queries, jnp.asarray(candidates), k,
                        mt.value)


@partial(jax.jit, donate_argnums=(0,))
def _fill_rows(buf, blk, lidx, pos):
    """Scatter gathered block rows into the candidate-row buffer
    (module-level so the jit cache hits across refine_provider calls;
    the last ``pos`` slot is the dump row for padding)."""
    return buf.at[pos].set(blk[lidx].astype(jnp.float32))


@traced("raft_tpu.refine_provider")
# the provider path exists to gather candidate rows on the HOST (memmap
# bases) — its device_get round-trip is the point, not a leak
def refine_provider(  # graftlint: disable-fn=GL01
    provider,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank against a device-chunk provider (bench.dataset.
    DeviceSyntheticChunks): regenerate each fixed-size generation block
    ON DEVICE and gather the candidate rows out of it — an EXACT f32
    re-rank with zero host traffic and no quantization error (the SQ8
    refine file loses ~1e-2 per squared distance, which on dense
    synthetic data exceeds neighbor gaps and caps recall; reference:
    the full-precision refinement_rate path, refine-inl.cuh).

    Cost is one generation pass over the provider's blocks (pipelined
    device programs; the gathered-row buffer is O(m·C·d) in HBM).
    """
    import numpy as np

    _check_candidates(queries, candidates, k)
    _check_base_dim(provider, queries)
    _obs_spans.count_dispatch("refine", "provider_regen")
    mt = resolve_metric(metric)
    cand = np.asarray(candidates)
    m, C = cand.shape
    n, d = provider.shape
    c = provider.chunk_rows
    n_blocks = -(-n // c)
    flat = cand.reshape(-1)
    safe = np.clip(flat, 0, n - 1)
    block_of = safe // c
    counts = np.bincount(block_of, minlength=n_blocks)
    P = max(8, int(counts.max()))  # one compiled shape for every block

    buf = jnp.zeros((m * C + 1, d), jnp.float32)
    order = np.argsort(block_of, kind="stable")
    starts = np.searchsorted(block_of[order], np.arange(n_blocks + 1))
    for bi in range(n_blocks):
        sel = order[starts[bi]:starts[bi + 1]]
        if sel.size == 0:
            continue
        lidx = np.zeros((P,), np.int32)
        lidx[:sel.size] = safe[sel] - bi * c
        pos = np.full((P,), m * C, np.int32)
        pos[:sel.size] = sel
        buf = _fill_rows(buf, provider._block(bi), jnp.asarray(lidx),
                         jnp.asarray(pos))
    rows = buf[:m * C].reshape(m, C, d)
    return _refine_rows(rows, queries, jnp.asarray(cand), k, mt.value)


@traced("raft_tpu.refine_gathered")
# host-side candidate-row gather by design (memmap bases — jitted refine
# would materialize the whole base in HBM)
def refine_gathered(  # graftlint: disable-fn=GL01
    host_base,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    metric="sqeuclidean",
    dequant=None,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank against a HOST-resident (possibly memmapped) dataset:
    gather only each query's candidate rows on the host — O(m·C·d) pages
    touched, never the whole base — then re-rank on device (reference:
    the host refine path, detail/refine_host-inl.hpp, used by CAGRA
    builds and billion-scale benches where the base doesn't fit).

    ``dequant=(scale, zero)``: ``host_base`` holds int8 scalar-quantized
    rows (x ≈ zero + scale·code, per-dim) — the billion-scale refine
    file is 4× smaller and re-ranking ~20 candidates to top-k tolerates
    SQ8 precision easily."""
    import numpy as np

    _check_candidates(queries, candidates, k)
    _check_base_dim(host_base, queries)
    _obs_spans.count_dispatch("refine", "host_gather")
    mt = resolve_metric(metric)
    cand = np.asarray(candidates)
    safe = np.clip(cand, 0, host_base.shape[0] - 1)
    rows = np.asarray(host_base[safe.reshape(-1)], np.float32).reshape(
        cand.shape[0], cand.shape[1], host_base.shape[1])
    if dequant is not None:
        scale, zero = dequant
        rows = rows * np.asarray(scale)[None, None, :] \
            + np.asarray(zero)[None, None, :]
    return _refine_rows(jnp.asarray(rows), queries, jnp.asarray(cand),
                        k, mt.value)
