"""raft_tpu.neighbors — ANN vector search indexes.

Counterpart of the reference neighbors layer (cpp/include/raft/neighbors):
brute-force, IVF-Flat, IVF-PQ, CAGRA, NN-Descent, refine, ball-cover,
epsilon-neighborhood, sample filtering.
"""

from raft_tpu.neighbors import (  # noqa: F401
    ball_cover,
    brute_force,
    cagra,
    epsilon_neighborhood,
    ivf_flat,
    ivf_pq,
    nn_descent,
    refine,
    sample_filter,
)
