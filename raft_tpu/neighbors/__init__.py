"""raft_tpu.neighbors — ANN vector search indexes.

Counterpart of the reference neighbors layer (cpp/include/raft/neighbors):
brute-force, IVF-Flat, IVF-PQ, CAGRA, NN-Descent, refine, filtering.
"""

from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, refine  # noqa: F401
