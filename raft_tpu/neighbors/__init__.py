"""raft_tpu.neighbors — ANN vector search indexes.

Counterpart of the reference neighbors layer (cpp/include/raft/neighbors):
brute-force, IVF-Flat, IVF-PQ, CAGRA, NN-Descent, refine, filtering.
"""

from raft_tpu.neighbors import (  # noqa: F401
    brute_force,
    cagra,
    ivf_flat,
    ivf_pq,
    nn_descent,
    refine,
)
