"""Random Ball Cover — exact kNN/radius search for low-dim metrics.

TPU-native counterpart of the reference's RBC
(neighbors/ball_cover-inl.cuh, spatial/knn/detail/ball_cover/,
ball_cover_types.hpp; cites the Cayton Random Ball Cover paper).  Used
for true-metric spaces (euclidean, haversine) where the triangle
inequality prunes.

Design (TPU re-think of the reference's 3-pass kernel):
- build: ~√n landmarks sampled, every point assigned to its nearest
  landmark (fused argmin), members packed into padded per-landmark
  lists with each landmark's covering radius.
- search: landmarks are ranked per query by true distance; probing
  proceeds in fixed-size rounds of the next-closest lists (static
  shapes, gather + batched distance + select_k).  After each round the
  triangle-inequality bound  d(q, c) − r(c) ≥ kth_best  decides — via
  one scalar host read — whether any query still needs more rounds.
  This replaces the reference's per-thread dynamic pruning with
  data-parallel rounds + a host convergence check, and remains exact.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import flax.struct

from ..core.errors import expects
from ..core.tracing import traced
from ..distance.pairwise import pairwise_distance
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import select_k as _select_k

_SUPPORTED = {
    DistanceType.L2SqrtExpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.Haversine,
}


class BallCoverIndex(flax.struct.PyTreeNode):
    """Reference: ``BallCoverIndex`` (neighbors/ball_cover_types.hpp)."""

    landmarks: jax.Array     # [L, d] f32
    packed_data: jax.Array   # [L, max_list, d] f32
    packed_ids: jax.Array    # [L, max_list] i32 (-1 pad)
    radii: jax.Array         # [L] f32 covering radius per landmark
    list_sizes: jax.Array    # [L] i32
    metric: str = flax.struct.field(pytree_node=False, default="euclidean")

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]

    @property
    def dim(self) -> int:
        return self.landmarks.shape[1]


def _metric_dist(a: jax.Array, b: jax.Array, mt: DistanceType) -> jax.Array:
    """True-metric pairwise distances [m, n] (must satisfy the triangle
    inequality — sqrt'd L2 or haversine)."""
    return pairwise_distance(a, b, metric=mt)


@traced("raft_tpu.ball_cover.build")
# host-side list pack (bincount + np scatter) by design — build is eager
def build(  # graftlint: disable-fn=GL01
    dataset: jax.Array,
    metric: str = "euclidean",
    n_landmarks: Optional[int] = None,
    seed: int = 0,
) -> BallCoverIndex:
    """Build the ball cover (reference: ball_cover-inl.cuh:56
    ``rbc_build_index``)."""
    mt = resolve_metric(metric)
    if mt == DistanceType.L2Expanded:  # accept plain "euclidean" family
        mt = DistanceType.L2SqrtExpanded
    expects(mt in _SUPPORTED, "ball_cover needs a true metric (euclidean/haversine)")
    x = jnp.asarray(dataset, jnp.float32)
    n, d = x.shape
    L = n_landmarks or max(1, int(np.sqrt(n)))
    L = min(L, n)
    rng = np.random.default_rng(seed)
    picks = rng.choice(n, size=L, replace=False)
    landmarks = x[jnp.asarray(np.sort(picks))]

    dists = _metric_dist(x, landmarks, mt)  # [n, L]
    labels = np.asarray(jax.device_get(jnp.argmin(dists, axis=1)))
    dmin = np.asarray(jax.device_get(jnp.min(dists, axis=1)))

    counts = np.bincount(labels, minlength=L)
    max_list = max(1, int(counts.max()))
    x_h = np.asarray(jax.device_get(x))
    packed = np.zeros((L, max_list, d), np.float32)
    ids = np.full((L, max_list), -1, np.int32)
    radii = np.zeros((L,), np.float32)
    order = np.argsort(labels, kind="stable")
    starts = np.searchsorted(labels[order], np.arange(L))
    ends = np.searchsorted(labels[order], np.arange(L), side="right")
    for l in range(L):
        rows = order[starts[l] : ends[l]]
        packed[l, : len(rows)] = x_h[rows]
        ids[l, : len(rows)] = rows
        if len(rows):
            radii[l] = dmin[rows].max()
    return BallCoverIndex(
        landmarks=landmarks,
        packed_data=jnp.asarray(packed),
        packed_ids=jnp.asarray(ids),
        radii=jnp.asarray(radii),
        list_sizes=jnp.asarray(counts.astype(np.int32)),
        metric=str(
            {
                DistanceType.L2SqrtExpanded: "euclidean",
                DistanceType.L2SqrtUnexpanded: "euclidean",
                DistanceType.Haversine: "haversine",
            }[mt]
        ),
    )


def _cand_dists(q: jax.Array, cand: jax.Array, mt: DistanceType) -> jax.Array:
    """Distances between q [t, d] and per-query candidates [t, C, d]."""
    if mt == DistanceType.Haversine:
        lat1, lon1 = q[:, None, 0], q[:, None, 1]
        lat2, lon2 = cand[..., 0], cand[..., 1]
        sdlat = jnp.sin((lat2 - lat1) * 0.5)
        sdlon = jnp.sin((lon2 - lon1) * 0.5)
        h = sdlat * sdlat + jnp.cos(lat1) * jnp.cos(lat2) * sdlon * sdlon
        return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))
    # euclidean
    diff = cand - q[:, None, :]
    return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=-1), 0.0))


@partial(jax.jit, static_argnames=("k", "round_lists"))
def _probe_round(index: BallCoverIndex, q, ranked_lists, start, best_d, best_i,
                 k: int, round_lists: int):
    """Scan the next ``round_lists`` closest unprobed lists per query and
    merge into the running top-k."""
    m = q.shape[0]
    Lsz = index.packed_data.shape[1]
    probe = lax.dynamic_slice_in_dim(ranked_lists, start, round_lists, axis=1)
    cand = index.packed_data[probe].reshape(m, round_lists * Lsz, index.dim)
    cand_ids = index.packed_ids[probe].reshape(m, round_lists * Lsz)
    mt = resolve_metric(index.metric)
    if mt == DistanceType.L2Expanded:
        mt = DistanceType.L2SqrtExpanded
    d = _cand_dists(q, cand, mt)
    d = jnp.where(cand_ids >= 0, d, jnp.inf)
    # merge with carried best
    all_d = jnp.concatenate([best_d, d], axis=1)
    all_i = jnp.concatenate([best_i, cand_ids], axis=1)
    vals, pos = _select_k(all_d, k, select_min=True)
    return vals, jnp.take_along_axis(all_i, pos, axis=1)


@traced("raft_tpu.ball_cover.knn")
def knn(
    index: BallCoverIndex,
    queries: jax.Array,
    k: int,
    round_lists: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN via ball-cover pruning (reference: ball_cover-inl.cuh:266
    ``rbc_knn_query``).  Returns (distances [m, k], ids [m, k])."""
    q = jnp.asarray(queries, jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim, "queries must be [m, %d]", index.dim)
    mt = resolve_metric(index.metric)
    if mt == DistanceType.L2Expanded:
        mt = DistanceType.L2SqrtExpanded
    m = q.shape[0]
    L = index.n_landmarks
    expects(k <= int(jnp.sum(index.list_sizes)), "k larger than index size")

    d_ql = _metric_dist(q, index.landmarks, mt)  # [m, L]
    order = jnp.argsort(d_ql, axis=1).astype(jnp.int32)  # ranked lists
    d_sorted = jnp.take_along_axis(d_ql, order, axis=1)
    r_sorted = index.radii[order]
    # lower bound of any list at rank j: d(q,c_j) - r_j; suffix-min gives
    # the best possible distance among lists ranked >= j
    lb = jnp.maximum(d_sorted - r_sorted, 0.0)
    suffix_lb = lax.cummin(lb[:, ::-1], axis=1)[:, ::-1]

    if round_lists <= 0:
        round_lists = max(1, int(np.ceil(np.sqrt(L))))
    best_d = jnp.full((m, k), jnp.inf, jnp.float32)
    best_i = jnp.full((m, k), -1, jnp.int32)
    probed = 0
    while probed < L:
        nxt = min(round_lists, L - probed)
        best_d, best_i = _probe_round(
            index, q, order, probed, best_d, best_i, k, nxt
        )
        probed += nxt
        if probed >= L:
            break
        # exact-stop test: does any query's kth distance still exceed the
        # best possible bound among unprobed lists?  one host scalar read
        kth = best_d[:, -1]
        need_more = bool(jnp.any(kth > suffix_lb[:, probed]))
        if not need_more:
            break
    return best_d, best_i


@traced("raft_tpu.ball_cover.eps_nn")
def eps_nn(
    index: BallCoverIndex, queries: jax.Array, eps: float
) -> Tuple[jax.Array, jax.Array]:
    """Fixed-radius neighbors via ball-cover pruning (reference:
    ball_cover eps_nn, neighbors/ball_cover-inl.cuh:393).  Returns a
    boolean adjacency [m, n_index_rows... ] in *packed candidate* form:
    (mask [m, total_slots], ids [total_slots]) where mask[i, j] marks
    packed vector j within eps of query i.  Lists whose lower bound
    exceeds eps are pruned wholesale before the scan."""
    q = jnp.asarray(queries, jnp.float32)
    mt = resolve_metric(index.metric)
    if mt == DistanceType.L2Expanded:
        mt = DistanceType.L2SqrtExpanded
    m = q.shape[0]
    L, Lsz, d = index.packed_data.shape
    cand = index.packed_data.reshape(1, L * Lsz, d)
    dists = _cand_dists(q, jnp.broadcast_to(cand, (m, L * Lsz, d)), mt)
    valid = (index.packed_ids.reshape(-1) >= 0)[None, :]
    # (the landmark-level triangle bound d(q,c)−r > eps is implied by the
    # exact distances computed above, so no separate prune conjunct —
    # it could only disagree at the boundary through float rounding)
    keep = valid & (dists <= eps)
    return keep, index.packed_ids.reshape(-1)
