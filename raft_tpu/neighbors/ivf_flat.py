"""IVF-Flat — inverted-file index over balanced-kmeans clusters.

TPU-native re-design of ``raft::neighbors::ivf_flat``
(ivf_flat-inl.cuh:65 build, :452 search; detail/ivf_flat_build.cuh;
detail/ivf_flat_search.cuh; interleaved scan kernel
detail/ivf_flat_interleaved_scan-inl.cuh). Design mapping:

- the reference stores raw vectors *interleaved in groups of 32*
  (kIndexGroupSize, ivf_flat_types.hpp:47) for coalesced warp scans. The
  TPU layout is **padded per-list blocks**: one dense ``[n_lists,
  max_list_size, dim]`` array (+ id array, -1 padded). Static shapes are
  what XLA needs, and balanced kmeans keeps the padding waste bounded —
  list-size balance is a first-class TPU concern (SURVEY.md §7 hard part c);
- the fused interleaved-scan + per-warp top-k kernel → coarse probe
  selection (Gram + select_k on the MXU), a batched gather of the probed
  list blocks, one batched matmul over candidates (``einsum`` on the MXU),
  and a fused select_k — XLA fuses the mask/epilogue into the contraction;
- query batching replaces the reference's stream-pool chunking: a
  ``lax.map`` over query tiles bounds the [tile, n_probes·list_size]
  intermediate.

Supported metrics: sqeuclidean / euclidean / inner_product / cosine
(float32 and int8/uint8 data — integers are scanned in int8 and
accumulated in int32 on the MXU, mirroring the reference's dp4a path).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced, span
from raft_tpu.core import ids as _ids
from raft_tpu.core import serialize as ser
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.obs import index_stats as _istats
from raft_tpu.robust import faults as _faults
from raft_tpu.utils.precision import get_precision

_SERIAL_VERSION = 1


@dataclasses.dataclass
class IndexParams:
    """reference: ``ivf_flat::index_params`` (ivf_flat_types.hpp)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    add_data_on_build: bool = True
    list_size_cap_factor: float = 4.0  # max_list_size = factor * n/n_lists
    # TPU-specific: cap padded capacity at the factor above and SPILL
    # overflow rows to their second-nearest list instead of dropping
    # them (ivf_common.spill_assignments) — every probe DMAs the padded
    # block, so skew-driven padding is wasted bandwidth on every scan;
    # spill with cap_factor ~1.5 shrinks the working set 2-3×
    spill: bool = False
    seed: int = 0


@dataclasses.dataclass
class SearchParams:
    """reference: ``ivf_flat::search_params`` (ivf_flat_types.hpp:157).

    ``scan_mode`` selects the TPU scan structure: "grouped" is the
    list-centric batch scan (see neighbors/ivf_common.py — each list block
    streams through the MXU once per query batch), "per_query" gathers
    each query's probed lists (lower latency for small batches), "auto"
    picks by batch size."""

    n_probes: int = 20
    query_tile: int = 256  # per_query path: bounds the per-step intermediate
    scan_mode: str = "auto"  # "auto" | "grouped" | "per_query"
    list_chunk: int = 64     # grouped path: segments scanned per step
    # per-segment candidate selection on the grouped path: "exact"
    # (lax.top_k / Pallas — the reference's semantics) or "approx"
    # (lax.approx_min_k, the TPU-hardware top-k: measured 30×+ cheaper
    # at scan shapes, making the scan matmul-bound; per-op recall is
    # targeted by scan_recall and end recall stays within ~1e-3 on
    # clustered data)
    scan_select: str = "exact"  # | "approx"
    scan_recall: float = 0.95   # approx select per-op recall target
    # refinement_rate pattern shared with ivf_pq (reference:
    # refine-inl.cuh): "f32_regen" scans k·refine_ratio candidates and
    # re-ranks exactly against search()'s ``dataset`` argument through
    # neighbors.refine's dispatch tier — recovers the recall the approx
    # hardware top-k trades away on oversampled configs
    refine: str = "none"  # | "f32_regen"
    refine_ratio: float = 2.0
    # host-resident re-rank bases (ISSUE 17): same knob as ivf_pq —
    # "auto" takes the tiered candidate-row prefetch pipeline when
    # eligible, "tiered" forces it, "serial" pins the serialized host
    # gather (the ladder's last-resort host_gather rung)
    refine_transfer: str = "auto"  # | "tiered" | "serial"


class IvfFlatIndex(flax.struct.PyTreeNode):
    """Padded-list IVF-Flat index (reference: ``ivf_flat::index``,
    ivf_flat_types.hpp:157-159 — TPU layout, see module docstring)."""

    centers: jax.Array       # [n_lists, dim] f32
    packed_data: jax.Array   # [n_lists, max_list_size, dim]
    packed_ids: jax.Array    # [n_lists, max_list_size] i32, -1 = pad
    packed_norms: jax.Array  # [n_lists, max_list_size] f32 squared norms
    list_sizes: jax.Array    # [n_lists] i32
    metric: str = flax.struct.field(pytree_node=False, default="sqeuclidean")

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.packed_data.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))


def _normalize_rows(x):
    n = jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=-1, keepdims=True), 1e-12))
    return x / n


def _pack_lists(dataset: np.ndarray, labels: np.ndarray, n_lists: int,
                max_list_size: int, dtype) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side list packing (reference: detail/ivf_flat_build.cuh pack;
    build is host-orchestrated, like the reference's build pipeline).
    Fully vectorized: one argsort + fancy-indexed fill, no per-list loop."""
    n, d = dataset.shape
    order = np.argsort(labels, kind="stable")
    sorted_labels = labels[order]
    starts = np.searchsorted(sorted_labels, np.arange(n_lists))
    rank = np.arange(n) - starts[sorted_labels]   # slot within each list
    keep = rank < max_list_size
    dropped = int(n - keep.sum())
    packed = np.zeros((n_lists, max_list_size, d), dtype=dtype)
    # row ids are 0 … n−1: the table width follows the policy dtype of n
    # (core.ids) — int64 past 2³¹ rows
    ids = np.full((n_lists, max_list_size), -1, _ids.np_id_dtype(n))
    rows = order[keep]
    packed[sorted_labels[keep], rank[keep]] = dataset[rows]
    ids[sorted_labels[keep], rank[keep]] = rows
    sizes = np.minimum(np.bincount(labels, minlength=n_lists),
                       max_list_size).astype(np.int32)
    if dropped:
        from raft_tpu.core import logging as _log
        _log.warn("ivf_flat: dropped %d overflow vectors (raise "
                  "list_size_cap_factor)", dropped)
    return packed, ids, sizes


def _lane_round(size: int) -> int:
    """Round a list capacity up to a lane-friendly multiple — 128 for
    MXU-shaped scans once lists are that big, but only a multiple of 8
    below that so tiny-list indexes (actual max 15 → 16, not 128)
    aren't padded 8×."""
    size = max(8, size)
    if size >= 128:
        return -(-size // 128) * 128
    return -(-size // 8) * 8


def _fit_list_size(counts: np.ndarray, avg: int, cap_factor: float) -> int:
    """Padded list capacity: the actual max list size, clamped by the cap
    factor, rounded up lane-friendly (see _lane_round). Sizing to the
    real histogram instead of the worst-case cap is a large scan-FLOP
    saver — padding is wasted work on every probe."""
    cap = max(8, int(avg * cap_factor))
    actual = int(counts.max()) if counts.size else 8
    return _lane_round(min(cap, actual))


@traced("raft_tpu.ivf_flat.build")
def build(dataset: jax.Array, params: Optional[IndexParams] = None) -> IvfFlatIndex:  # graftlint: disable-fn=GL01 (host-side histogram/pack by design)
    """Build the index (reference: ivf_flat::build, ivf_flat-inl.cuh:65):
    balanced-kmeans coarse fit on a trainset subsample, assign all rows,
    pack padded lists."""
    if params is None:
        params = IndexParams()
    mt = resolve_metric(params.metric)
    x = jnp.asarray(dataset)
    n, d = x.shape
    expects(params.n_lists <= n, "n_lists=%d > n=%d", params.n_lists, n)

    spherical = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    km_params = KMeansBalancedParams(
        n_iters=params.kmeans_n_iters,
        metric="cosine" if spherical else "l2",
        seed=params.seed)

    # trainset subsample (reference: ivf_flat_build trainset_fraction)
    n_train = max(params.n_lists * 4, int(n * params.kmeans_trainset_fraction))
    n_train = min(n, n_train)
    if n_train < n:
        rng = np.random.default_rng(params.seed)
        train_rows = np.sort(rng.choice(n, n_train, replace=False))
        trainset = x[jnp.asarray(train_rows)]
    else:
        trainset = x
    with span("train") as _sp:
        centers = kmeans_balanced.fit(trainset.astype(jnp.float32),
                                      params.n_lists, km_params)
        _sp.attach(centers)
    del trainset  # wide datasets: the subsample copy is GBs

    avg = max(1, n // params.n_lists)

    if not params.add_data_on_build:
        max_list_size = max(8, int(avg * params.list_size_cap_factor))
        packed = jnp.zeros((params.n_lists, max_list_size, d), x.dtype)
        ids = jnp.full((params.n_lists, max_list_size), -1, jnp.int32)
        sizes = jnp.zeros((params.n_lists,), jnp.int32)
        norms = jnp.zeros((params.n_lists, max_list_size), jnp.float32)
        return IvfFlatIndex(centers=centers, packed_data=packed,
                            packed_ids=ids, packed_norms=norms,
                            list_sizes=sizes, metric=mt.value)

    # assign + pack ON DEVICE (ivf_common.pack_lists, the same sort+
    # scatter the distributed build uses): the data never round-trips the
    # host, only the [n_lists] histogram does (it sizes the static padded
    # list capacity). The host packer remains for memmapped/chunked flows.
    from raft_tpu.neighbors import ivf_common as ic

    with span("assign") as _sp:
        if params.spill:
            # cap capacity at factor × mean and cascade overflow rows to
            # their next-nearest lists (see IndexParams.spill)
            lk = kmeans_balanced.predict_topk(centers,
                                              x.astype(jnp.float32),
                                              ic.SPILL_DEPTH, km_params)
            max_list_size = _lane_round(
                int(avg * params.list_size_cap_factor))
            labels = ic.spill_assignments(lk[:, 0], lk[:, 1],
                                          params.n_lists, max_list_size,
                                          *[lk[:, c] for c in
                                            range(2, lk.shape[1])])
            n_marker = int(jnp.sum(labels >= params.n_lists))
            if n_marker:
                # pack_lists' drop counter excludes out-of-range labels,
                # so double-overflow rows must be surfaced here
                from raft_tpu.core import logging as _log
                _log.warn("ivf_flat: %d rows overflowed every spill choice "
                          "at cap %d (raise list_size_cap_factor)",
                          n_marker, max_list_size)
        else:
            labels = kmeans_balanced.predict(centers, x.astype(jnp.float32),
                                             km_params)
            # histogram on host: the [n] labels transfer is small, and a
            # device scatter-add histogram serializes on TPU
            counts = np.bincount(np.asarray(labels),
                                 minlength=params.n_lists)
            max_list_size = _fit_list_size(counts, avg,
                                           params.list_size_cap_factor)
        _sp.attach(labels)
    with span("pack") as _sp:
        if (n + params.n_lists * max_list_size) * d * x.dtype.itemsize \
                > (8 << 30):
            # wide datasets: the one-shot pack's gather copy OOMs (see
            # pack_rows_chunked)
            packed, ids, sizes, dropped = ic.pack_rows_chunked(
                x, labels, params.n_lists, max_list_size,
                chunk_rows=1 << 16)
        else:
            (packed,), ids, sizes, dropped, _ = ic.pack_lists_jit(
                [x], labels, _ids.make_ids(n),
                n_lists=params.n_lists, L=max_list_size,
                fill_values=[jnp.zeros((), x.dtype)])
        _sp.attach(packed, ids)
    n_drop = int(dropped)
    if n_drop:
        from raft_tpu.core import logging as _log
        _log.warn("ivf_flat: dropped %d overflow vectors (raise "
                  "list_size_cap_factor%s)", n_drop,
                  "" if params.spill else " or set spill=True")
    norms = jnp.sum(packed.astype(jnp.float32) ** 2, axis=-1)
    index = IvfFlatIndex(centers=centers, packed_data=packed,
                         packed_ids=ids, packed_norms=norms,
                         list_sizes=sizes, metric=mt.value)
    _istats.note_index_stats(index, name="ivf_flat.build", cheap=True)
    return index


@traced("raft_tpu.ivf_flat.build_distributed")
def build_distributed(dataset, params: Optional[IndexParams] = None, *,
                      mesh, axis: str = "shard",
                      chunk_rows: int = 1 << 18,
                      max_train_rows: int = 1 << 21,
                      prefetch: bool = True,
                      coarse: str = "replicated",
                      progress: bool = False):
    """Distributed chunked build from a host array/memmap — the
    IVF-Flat twin of :func:`raft_tpu.neighbors.ivf_pq.build_distributed`
    (see it and :mod:`raft_tpu.parallel.build` for the shard/prefetch/
    comms structure). Returns a ``parallel.ivf.ShardedIvfFlat`` the
    sharded searcher consumes directly;
    ``parallel.build.assemble_ivf_flat`` of the default
    (``coarse="replicated"``) result is bit-identical to
    :func:`build` over the same dataset/params while the trainset stays
    under ``max_train_rows``."""
    if params is None:
        params = IndexParams()
    from raft_tpu.parallel import build as _dbuild

    return _dbuild.build_ivf_flat_distributed(
        dataset, params, mesh, axis=axis, chunk_rows=chunk_rows,
        max_train_rows=max_train_rows, prefetch=prefetch, coarse=coarse,
        progress=progress)


@traced("raft_tpu.ivf_flat.extend")
def extend(index: IvfFlatIndex, new_vectors: jax.Array,  # graftlint: disable-fn=GL01 (host re-pack by design)
           new_ids: Optional[jax.Array] = None) -> IvfFlatIndex:
    """Append vectors (reference: ivf_flat::extend). Host-side re-pack with
    capacity growth; centers unchanged."""
    mt = resolve_metric(index.metric)
    spherical = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    km_params = KMeansBalancedParams(metric="cosine" if spherical else "l2")

    old_n = index.size
    new_vectors = jnp.asarray(new_vectors)
    if new_ids is None:
        new_ids = _ids.make_ids(new_vectors.shape[0], start=old_n)
    labels = np.asarray(kmeans_balanced.predict(
        index.centers, new_vectors.astype(jnp.float32), km_params))

    # host re-pack: merge existing rows with new ones
    n_lists, L, d = index.packed_data.shape
    old_sizes = np.asarray(index.list_sizes)
    new_counts = np.bincount(labels, minlength=n_lists)
    need = old_sizes + new_counts
    new_L = max(L, int(need.max()))
    new_L = max(8, -(-new_L // 8) * 8)

    old_ids = np.asarray(index.packed_ids)
    ni = np.asarray(new_ids)
    packed = np.zeros((n_lists, new_L, d), np.asarray(index.packed_data).dtype)
    ids = np.full((n_lists, new_L), -1, _ids.np_id_dtype_like(old_ids, ni))
    packed[:, :L] = np.asarray(index.packed_data)
    ids[:, :L] = old_ids
    nv = np.asarray(new_vectors)
    # vectorized append: slot = old_size[list] + rank within the new rows
    order = np.argsort(labels, kind="stable")
    sorted_l = labels[order]
    starts = np.searchsorted(sorted_l, np.arange(n_lists))
    rank = np.arange(len(labels)) - starts[sorted_l]
    slot = old_sizes[sorted_l] + rank
    keep = slot < new_L
    packed[sorted_l[keep], slot[keep]] = nv[order[keep]]
    ids[sorted_l[keep], slot[keep]] = ni[order[keep]]
    fill = np.minimum(need, new_L)
    packed_j = jnp.asarray(packed)
    out = IvfFlatIndex(
        centers=index.centers, packed_data=packed_j, packed_ids=jnp.asarray(ids),
        packed_norms=jnp.sum(packed_j.astype(jnp.float32) ** 2, axis=-1),
        list_sizes=jnp.asarray(fill.astype(np.int32)), metric=index.metric)
    _istats.note_index_stats(out, name="ivf_flat.extend", cheap=True)
    return out


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

def _coarse_distances(q, centers, mt):
    """Query→center scores for probe selection (reference:
    detail/ivf_flat_search.cuh select_clusters gemm)."""
    g = lax.dot_general(q, centers, (((1,), (1,)), ((), ())),
                        precision=get_precision(),
                        preferred_element_type=jnp.float32)
    if mt == DistanceType.InnerProduct:
        return g, False
    if mt == DistanceType.CosineExpanded:
        qn = jnp.sqrt(jnp.maximum(jnp.sum(q * q, 1), 1e-30))
        cn = jnp.sqrt(jnp.maximum(jnp.sum(centers * centers, 1), 1e-30))
        return 1.0 - g / (qn[:, None] * cn[None, :]), True
    c_sq = jnp.sum(centers * centers, axis=1)
    q_sq = jnp.sum(q * q, axis=1)
    return jnp.maximum(q_sq[:, None] + c_sq[None, :] - 2.0 * g, 0.0), True


@partial(jax.jit, static_argnames=("k", "n_probes", "query_tile"))
def _search_impl(index: IvfFlatIndex, queries: jax.Array, k: int,
                 n_probes: int, query_tile: int, filter_bits=None):
    mt = resolve_metric(index.metric)
    q_all = queries.astype(jnp.float32)
    m = q_all.shape[0]
    L = index.max_list_size
    sqrt_out = mt == DistanceType.L2SqrtExpanded
    select_min = mt != DistanceType.InnerProduct

    coarse, coarse_min = _coarse_distances(q_all, index.centers, mt)
    _, probes = _select_k(coarse, n_probes, select_min=coarse_min)

    def search_tile(args):
        q, probe = args  # [t, dim], [t, P]
        t = q.shape[0]
        cand_data = index.packed_data[probe].astype(jnp.float32)  # [t,P,L,dim]
        cand_ids = index.packed_ids[probe].reshape(t, n_probes * L)
        cand = cand_data.reshape(t, n_probes * L, index.dim)
        scores = jnp.einsum("td,tcd->tc", q, cand,
                            precision=get_precision(),
                            preferred_element_type=jnp.float32)
        if mt == DistanceType.InnerProduct:
            dists = scores
            invalid_val = -jnp.inf
        elif mt == DistanceType.CosineExpanded:
            qn = jnp.sqrt(jnp.maximum(jnp.sum(q * q, 1), 1e-30))
            cn = jnp.sqrt(jnp.maximum(
                index.packed_norms[probe].reshape(t, n_probes * L), 1e-30))
            dists = 1.0 - scores / (qn[:, None] * cn)
            invalid_val = jnp.inf
        else:
            c_sq = index.packed_norms[probe].reshape(t, n_probes * L)
            q_sq = jnp.sum(q * q, axis=1)
            dists = jnp.maximum(q_sq[:, None] + c_sq - 2.0 * scores, 0.0)
            if sqrt_out:
                dists = jnp.sqrt(dists)
            invalid_val = jnp.inf
        valid = cand_ids >= 0
        if filter_bits is not None:
            from raft_tpu.neighbors.sample_filter import passes

            valid = passes(filter_bits, cand_ids)
        dists = jnp.where(valid, dists, invalid_val)
        vals, pos = _select_k(dists, k, select_min=select_min)
        ids = jnp.take_along_axis(cand_ids, pos, axis=1)
        return vals, ids

    if m <= query_tile:
        return search_tile((q_all, probes))

    n_tiles = -(-m // query_tile)
    pad = n_tiles * query_tile - m
    qp = jnp.pad(q_all, ((0, pad), (0, 0)))
    pp = jnp.pad(probes, ((0, pad), (0, 0)))
    vals, ids = lax.map(
        search_tile,
        (qp.reshape(n_tiles, query_tile, -1), pp.reshape(n_tiles, query_tile, -1)))
    return (vals.reshape(n_tiles * query_tile, k)[:m],
            ids.reshape(n_tiles * query_tile, k)[:m])


@partial(jax.jit, static_argnames=("k", "n_probes", "seg", "n_seg",
                                   "seg_chunk", "use_pallas", "select_impl",
                                   "select_recall", "use_segk"))
def _search_grouped(index: IvfFlatIndex, queries: jax.Array, k: int,
                    n_probes: int, seg: int, n_seg: int, seg_chunk: int,
                    use_pallas: bool = False, filter_bits=None,
                    select_impl: str = "exact",
                    select_recall: float = 0.95,
                    use_segk: bool = False):
    """Segmented list-centric batch scan (see ivf_common module
    docstring): probe selection, probe segmenting, the MXU scan over
    segment chunks, and the final merge — ONE jitted program, statically
    shaped by (B, n_probes, n_lists, seg). TPU counterpart of the
    reference's interleaved scan (ivf_flat_interleaved_scan-inl.cuh)
    with the loop order inverted. ``use_pallas`` (static) routes the
    per-chunk scan to the fused Pallas kernel."""
    from raft_tpu.neighbors import ivf_common as ic

    mt = resolve_metric(index.metric)
    q_all = queries.astype(jnp.float32)
    B = q_all.shape[0]
    n_lists, L, d = index.packed_data.shape
    sqrt_out = mt == DistanceType.L2SqrtExpanded
    ip = mt == DistanceType.InnerProduct
    cos = mt == DistanceType.CosineExpanded
    select_min = not ip
    invalid = -jnp.inf if ip else jnp.inf

    coarse, coarse_min = _coarse_distances(q_all, index.centers, mt)
    _, probes = _select_k(coarse, n_probes, select_min=coarse_min)
    seg_list, seg_q, pair_seg, pair_slot = ic.segment_probes(
        probes, n_lists, seg, n_seg)

    q_sq = jnp.sum(q_all * q_all, axis=1)                 # [B]
    qn = jnp.sqrt(jnp.maximum(q_sq, 1e-30))

    kk_ = min(k, L)
    if use_segk:
        # scalar-prefetch kernel: list blocks DMA'd from the full packed
        # array at copy bandwidth (the XLA gather of the same blocks
        # measured ~20 GB/s and dominated the scan); per-tile-min
        # selection merged with one tiny top-k
        from raft_tpu.ops import pallas_kernels as _pk

        met = "ip" if ip else ("cos" if cos else "l2")
        qv_all = q_all[jnp.clip(seg_q, 0, B - 1)]         # [n_seg, S, d]
        keys, kids = _pk.segmented_scan_topk(
            seg_list, qv_all, index.packed_data, index.packed_ids, met,
            interpret=not _pk._on_tpu())
        out_vals, out_ids = ic.merge_bin_results(
            keys, kids, pair_seg, pair_slot, k, select_min, invalid,
            select_recall)
        if sqrt_out:
            out_vals = jnp.sqrt(out_vals)
        return out_vals, out_ids

    C = seg_chunk
    n_chunks = -(-n_seg // C)
    nsp = n_chunks * C
    seg_list = jnp.pad(seg_list, (0, nsp - n_seg))
    seg_q = jnp.pad(seg_q, ((0, nsp - n_seg), (0, 0)), constant_values=-1)

    from raft_tpu.ops import pallas_kernels as _pk

    kk = min(k, L)  # a single list holds at most L candidates

    def scan_chunk(args):
        sl, qt = args                                     # [C], [C, seg]
        data = index.packed_data[sl].astype(jnp.float32)  # [C, L, d]
        lids = index.packed_ids[sl]
        # the scan is HBM-gather-bound (XLA TPU gathers run ~20 GB/s vs
        # 800+ streaming, measured): derive validity from the gathered
        # ids and recompute norms from the gathered data instead of
        # gathering two more [C, L] arrays
        valid = lids >= 0
        if filter_bits is not None:
            from raft_tpu.neighbors.sample_filter import passes

            valid &= passes(filter_bits, lids)
        norms = jnp.sum(data * data, axis=-1)             # [C, L]
        qi = jnp.clip(qt, 0, B - 1)                       # [C, seg]
        qv = q_all[qi]                                    # [C, seg, d]
        # pad slots (qt == -1) compute against query 0 and are simply
        # never gathered back — masking them would cost more than the
        # wasted lanes
        if use_pallas:
            # fused contraction + epilogue + local top-k in VMEM — the
            # [C·seg, L] distance block never reaches HBM (reference:
            # the fused scan kernels, ivf_flat_interleaved_scan-inl.cuh)
            met = "ip" if ip else ("cos" if cos else "l2")
            mask_add = jnp.where(valid, 0.0, jnp.inf)
            keys, pos = _pk.grouped_scan_topk(
                qv, data, mask_add, kk, met, bq=seg,
                interpret=not _pk._on_tpu())
            vals = -keys if ip else keys
            vals = jnp.where(pos < 0, invalid, vals)
            cids = jax.vmap(lambda l, p: l[jnp.clip(p, 0, L - 1)])(lids, pos)
            cids = jnp.where(pos < 0, -1, cids)
            return vals, cids
        scores = jnp.einsum("gqd,gld->gql", qv, data,
                            precision=get_precision(),
                            preferred_element_type=jnp.float32)
        if ip:
            dists = scores
        elif cos:
            cn = jnp.sqrt(jnp.maximum(norms, 1e-30))
            dists = 1.0 - scores / (qn[qi][:, :, None] * cn[:, None, :])
        else:
            dists = jnp.maximum(
                q_sq[qi][:, :, None] + norms[:, None, :] - 2.0 * scores, 0.0)
        dists = jnp.where(valid[:, None, :], dists, invalid)
        if select_impl == "approx":
            # hardware top-k (TPU approx reduction): per-op recall
            # targeted, 30×+ cheaper than the sort-based exact select
            if select_min:
                vals, pos = lax.approx_min_k(
                    dists.reshape(C * seg, L), kk,
                    recall_target=select_recall)
            else:
                vals, pos = lax.approx_max_k(
                    dists.reshape(C * seg, L), kk,
                    recall_target=select_recall)
        else:
            vals, pos = _select_k(dists.reshape(C * seg, L), kk,
                                  select_min=select_min)
        vals = vals.reshape(C, seg, kk)
        pos = pos.reshape(C, seg, kk)
        cids = jax.vmap(lambda l, p: l[p])(lids, pos)     # [C, seg, kk]
        cids = jnp.where(vals == invalid, -1, cids)       # filtered/padded
        return vals, cids

    vals, cids = lax.map(
        scan_chunk, (seg_list.reshape(n_chunks, C),
                     seg_q.reshape(n_chunks, C, seg)))
    vals = vals.reshape(nsp, seg, kk)
    cids = cids.reshape(nsp, seg, kk)

    pv, pi = ic.gather_segment_results(vals, cids, pair_seg, pair_slot)
    out_vals, out_ids = _select_k(pv.reshape(B, n_probes * kk),
                                  min(k, n_probes * kk),
                                  select_min=select_min,
                                  input_indices=pi.reshape(B, n_probes * kk))
    if k > n_probes * kk:  # fewer candidates than asked: pad with invalid
        pad = k - n_probes * kk
        out_vals = jnp.pad(out_vals, ((0, 0), (0, pad)),
                           constant_values=invalid)
        out_ids = jnp.pad(out_ids, ((0, 0), (0, pad)), constant_values=-1)
    if sqrt_out:
        out_vals = jnp.sqrt(out_vals)
    return out_vals, out_ids


def _route_refined(index: IvfFlatIndex, queries: jax.Array, k: int,
                   params: SearchParams, filter_bitset, dataset
                   ) -> Tuple[jax.Array, jax.Array]:
    """``refine="f32_regen"``: oversampled scan + exact re-rank through
    neighbors.refine's dispatch tier (fused Pallas gather-refine on TPU
    oversampled shapes / XLA einsum / host gather) — same routing as
    ivf_pq's refined path."""
    import dataclasses

    from raft_tpu.neighbors import refine as _refine

    expects(params.refine == "f32_regen",
            "unknown refine mode %r (supported: 'none', 'f32_regen')",
            params.refine)
    expects(dataset is not None,
            "refine='f32_regen' needs search(..., dataset=...): the "
            "exact rows to re-rank against")
    dshape = getattr(dataset, "shape", None)
    expects(dshape is not None and len(dshape) == 2
            and dshape[1] == index.dim,
            "refine dataset shape %s does not match the index dim %d",
            tuple(dshape) if dshape else None, index.dim)
    expects(params.refine_ratio >= 1.0,
            "refine_ratio must be >= 1 (got %s)", params.refine_ratio)
    k_cand = max(k, int(round(k * params.refine_ratio)))
    scan_params = dataclasses.replace(params, refine="none")
    # host-resident base → the memory tier (ISSUE 17): decided BEFORE
    # the scan, same routing as ivf_pq's refined path
    if (not isinstance(dataset, jax.Array)
            and not hasattr(dataset, "_block")):
        from raft_tpu.neighbors import tiered as _tiered

        if _tiered.tiered_refine_wanted(dataset, queries.shape[0],
                                        k_cand, index.dim, params):
            return _tiered.search_refined_tiered(
                search, index, queries, k, k_cand, scan_params,
                filter_bitset, dataset, index.metric)
    _, i0 = search(index, queries, k_cand, scan_params, filter_bitset)
    if hasattr(dataset, "_block") and hasattr(dataset, "chunk_rows"):
        return _refine.refine_provider(dataset, queries, i0, k,
                                       metric=index.metric)
    if isinstance(dataset, jax.Array):
        # i0 is already filter-clean; the refine-tier filter is defense
        # in depth (the fused kernel's in-DMA bit test costs nothing)
        return _refine.refine(dataset, queries, i0, k, metric=index.metric,
                              filter_bits=filter_bitset)
    return _refine.refine_gathered(dataset, queries, i0, k,
                                   metric=index.metric)


@traced("raft_tpu.ivf_flat.search")
def search(index, queries: jax.Array, k: int,
           params: Optional[SearchParams] = None,
           filter_bitset: Optional[jax.Array] = None,
           dataset=None, *, mesh=None,
           mesh_axis: str = "shard",
           merge: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """Search the index (reference: ivf_flat::search, ivf_flat-inl.cuh:452;
    filtered overload ivf_flat-inl.cuh search_with_filtering).

    Returns (distances [m, k], ids [m, k]); ids are dataset row numbers,
    -1 marks slots beyond the number of valid candidates.
    ``filter_bitset``: optional packed bitset over dataset rows (see
    neighbors.sample_filter) — cleared bits are excluded.
    ``params.refine="f32_regen"`` + ``dataset`` re-ranks an oversampled
    scan exactly (see SearchParams.refine).

    **Pod-scale dispatch**: handed a ``parallel.ShardedIvfFlat`` (plus
    its ``mesh``), routes to the sharded search tier with the
    cross-shard merge picked by ``merge`` (auto | allgather | ring, see
    ``parallel.merge``)."""
    if params is None:
        params = SearchParams()
    from raft_tpu.neighbors import ivf_common as ic

    _divf = ic.sharded_dispatch(index, mesh, "ShardedIvfFlat")
    if _divf is not None:
        expects(params.refine == "none",
                "sharded IVF-Flat search does not support refine yet")
        return _divf.search_ivf_flat(params, index, queries, k, mesh,
                                     axis=mesh_axis, merge=merge,
                                     filter_bitset=filter_bitset)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "queries must be [m, %d]", index.dim)
    _faults.faultpoint("ivf_flat.search")
    if params.refine != "none":
        return _route_refined(index, queries, k, params, filter_bitset,
                              dataset)
    n_probes = min(params.n_probes, index.n_lists)
    B = queries.shape[0]
    mode = params.scan_mode
    if mode == "auto":
        # grouped wins once the batch populates the per-list queues
        mode = ("grouped" if B * n_probes >= 2 * index.n_lists
                else "per_query")
    if mode == "grouped":
        from raft_tpu.neighbors import ivf_common as ic

        # segmented scan: the table shape is a function of (B, n_probes,
        # n_lists, seg) alone — no probe histogram, no host sync, one
        # jitted program per static config (see ivf_common docstring)
        seg = ic.SEGMENT_SIZE
        pairs = B * n_probes
        n_seg = ic.n_segments(pairs, index.n_lists, seg)
        L = index.max_list_size
        kk = min(k, L)
        if params.scan_mode == "grouped" or ic.grouped_mem_ok(
                n_seg, seg, kk, pairs):
            chunk = ic.fit_seg_chunk(seg, L, index.dim, params.list_chunk)
            from raft_tpu.ops import pallas_kernels as _pk

            approx = params.scan_select == "approx"
            segk = (approx and filter_bitset is None
                    and _pk.pallas_segmented_wanted(kk, L, index.dim,
                                                    S=seg))
            wants = (not approx) and _pk.pallas_grouped_wanted(
                kk, L, index.dim, bq=seg)
            return _search_grouped(index, queries, k, n_probes, seg,
                                   n_seg, chunk, use_pallas=wants,
                                   filter_bits=filter_bitset,
                                   select_impl=params.scan_select,
                                   select_recall=params.scan_recall,
                                   use_segk=segk)
    return _search_impl(index, queries, k, n_probes,
                        _fit_query_tile(params.query_tile, n_probes, index),
                        filter_bits=filter_bitset)


@traced("raft_tpu.ivf_flat.search_resilient")
def search_resilient(index: IvfFlatIndex, queries: jax.Array, k: int,
                     params: Optional[SearchParams] = None,
                     filter_bitset: Optional[jax.Array] = None,
                     dataset=None,
                     deadline=None) -> Tuple[jax.Array, jax.Array]:
    """:func:`search` behind the standard degradation ladder
    (:mod:`raft_tpu.robust.degrade`, same wiring as
    ``ivf_pq.search_resilient`` minus the LUT rung — IVF-Flat has no
    LUT to quantize): RESOURCE_EXHAUSTED walks halve-batch → decline
    fused tier → host gather (then keeps halving), counted in
    ``degrade.steps{site=ivf_flat.search,...}``. ``deadline`` (a
    :class:`raft_tpu.robust.retry.Deadline`) is the request's shared
    wall-clock budget — the ladder aborts with ``DeadlineExceeded``
    instead of retrying past it (same contract as
    ``ivf_pq.search_resilient``)."""
    from raft_tpu.robust import degrade as _dg

    if params is None:
        params = SearchParams()
    queries = jnp.asarray(queries)
    return _dg.run_with_degradation(
        _dg.batched_search_call(search, index, queries, k, filter_bitset,
                                deadline=deadline, site="ivf_flat.search"),
        {"params": params, "dataset": dataset},
        _dg.standard_search_ladder(queries.shape[0], has_lut=False),
        site="ivf_flat.search", deadline=deadline)


def _fit_query_tile(want: int, n_probes: int, index: IvfFlatIndex) -> int:
    """Largest per_query tile ≤ ``want`` whose [t, n_probes, L, d] f32
    candidate gather stays under ~1 GB — at 1M rows (L≈4k) the default
    256-query tile would gather 17 GB and OOM the chip."""
    L, d = index.max_list_size, index.dim
    return max(1, min(want, (1 << 30) // max(1, n_probes * L * d * 4)))


# ---------------------------------------------------------------------------
# serialization (reference: neighbors/ivf_flat_serialize.cuh)
# ---------------------------------------------------------------------------

def save(index: IvfFlatIndex, path: str) -> None:
    ser.save_arrays(path, "ivf_flat", _SERIAL_VERSION,
                    {"metric": index.metric},
                    {"centers": index.centers,
                     "packed_data": index.packed_data,
                     "packed_ids": index.packed_ids,
                     "packed_norms": index.packed_norms,
                     "list_sizes": index.list_sizes})


def load(path: str) -> IvfFlatIndex:
    version, meta, arrays = ser.load_arrays(path, "ivf_flat")
    expects(version == _SERIAL_VERSION, "unsupported ivf_flat version %d", version)
    return IvfFlatIndex(
        centers=jnp.asarray(arrays["centers"]),
        packed_data=jnp.asarray(arrays["packed_data"]),
        packed_ids=jnp.asarray(arrays["packed_ids"]),
        packed_norms=jnp.asarray(arrays["packed_norms"]),
        list_sizes=jnp.asarray(arrays["list_sizes"]),
        metric=meta["metric"])
