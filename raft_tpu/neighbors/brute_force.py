"""Brute-force exact kNN — tiled over query×index to bound memory.

TPU-native counterpart of ``raft::neighbors::brute_force``
(neighbors/brute_force-inl.cuh:156 ``knn``; detail/knn_brute_force.cuh:58
``tiled_brute_force_knn``, :320 ``brute_force_knn_impl``; index type with
cached norms brute_force_types.hpp). Design mapping:

- the reference's stream-pool parallelism over index chunks → one fused XLA
  program: per-tile Gram matmul (MXU) + per-tile ``select_k`` + cross-tile
  merge ``select_k``, scheduled by XLA;
- the fused-L2 small-D fast path (fused_l2_knn-inl.cuh) → same scan-fused
  shape, since XLA fuses distance epilogue into the matmul tile;
- distributed (sharded-index) search lives in raft_tpu.parallel and merges
  per-shard results with :func:`raft_tpu.matrix.merge_parts`.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced
from raft_tpu.core import ids as _ids
from raft_tpu.distance import pairwise_distance, resolve_metric, DistanceType, SELECT_MIN
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.matrix.select_k import merge_parts
from raft_tpu.utils.precision import get_precision

# Max elements of one [query_tile, index_tile] distance block (~256 MB f32).
_TILE_BUDGET_ELEMS = 1 << 26

# Strided-bin width of the per-tile candidate cut (lane-shaped).
_BIN_LANES = 128


def _two_best_per_bin(dists: jax.Array, select_min: bool):
    """Per-tile candidate cut: the two best entries of each of 128
    STRIDED bins (position mod 128) with their in-tile positions —
    [m, it] → ([m, 256], [m, 256]) in two vectorized min/argmin passes.
    The same reduction the segmented IVF kernel applies in VMEM
    (ops/pallas_kernels._segmented_scan_kernel), here in XLA for the
    brute-force tile scan: it replaces a k-round extraction select with
    work the VPU does in one sweep, and positions come from arithmetic
    (argmin·128 + lane), never a gather."""
    m, it = dists.shape
    s = dists if select_min else -dists
    T = it // _BIN_LANES
    d3 = s.reshape(m, T, _BIN_LANES)
    lane = jnp.arange(_BIN_LANES, dtype=jnp.int32)[None, :]
    mn1 = jnp.min(d3, axis=1)
    a1 = jnp.argmin(d3, axis=1).astype(jnp.int32)
    t_iota = jax.lax.broadcasted_iota(jnp.int32, d3.shape, 1)
    d3b = jnp.where(t_iota == a1[:, None, :], jnp.inf, d3)
    mn2 = jnp.min(d3b, axis=1)
    a2 = jnp.argmin(d3b, axis=1).astype(jnp.int32)
    vals = jnp.concatenate([mn1, mn2], axis=1)
    pos = jnp.concatenate([a1 * _BIN_LANES + lane,
                           a2 * _BIN_LANES + lane], axis=1)
    if not select_min:
        vals = jnp.where(jnp.isinf(vals), -jnp.inf, -vals)
    return vals, pos


def _top_k_merge(cat_v: jax.Array, k: int, select_min: bool):
    """Small exact top-k over the [m, k+256] merge row (lax.top_k —
    narrow rows, where the sort-based select is already optimal)."""
    if select_min:
        nv, pos = lax.top_k(-cat_v, k)
        return -nv, pos
    return lax.top_k(cat_v, k)


class BruteForceIndex(flax.struct.PyTreeNode):
    """Brute-force index: the dataset plus cached norms
    (reference: brute_force_types.hpp ``brute_force::index``).

    A pytree (arrays are leaves, metric config is static) so whole
    searches jit over it — the search path must be ONE compiled program:
    op-by-op dispatch costs ~50 ms/op through a remote-device tunnel."""

    dataset: jax.Array          # [n, d]
    norms: Optional[jax.Array]  # [n] cached squared L2 norms (L2/cosine only)
    metric: DistanceType = flax.struct.field(pytree_node=False,
                                             default=DistanceType.L2Expanded)
    metric_arg: float = flax.struct.field(pytree_node=False, default=2.0)

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


@traced("raft_tpu.brute_force.build")
def build(dataset: jax.Array, metric="euclidean", metric_arg: float = 2.0) -> BruteForceIndex:
    """Build a brute-force index (reference: brute_force::build).

    Caches squared norms for expanded metrics so repeated searches skip
    recomputing them (brute_force_types.hpp norms caching).
    """
    mt = resolve_metric(metric)
    norms = None
    if mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.CosineExpanded):
        ds = dataset.astype(jnp.float32)
        norms = jnp.sum(ds * ds, axis=1)
    return BruteForceIndex(dataset=dataset, norms=norms, metric=mt, metric_arg=metric_arg)


def _choose_tiles(m: int, n: int, d: int) -> Tuple[int, int]:
    """Tile-size heuristic (reference: knn_brute_force.cuh:80): bound the
    [qt, it] distance block; favor wide index tiles (longer MXU contractions
    per select)."""
    if m * n <= _TILE_BUDGET_ELEMS:
        return m, n
    it = min(n, max(1 << 14, _TILE_BUDGET_ELEMS // max(m, 1)))
    qt = max(1, _TILE_BUDGET_ELEMS // it)
    return min(m, qt), it


def _expanded_block(q, db, q_sq, db_sq, metric):
    g = lax.dot_general(q, db, (((1,), (1,)), ((), ())),
                        precision=get_precision(),
                        preferred_element_type=jnp.float32)
    if metric == DistanceType.InnerProduct:
        return g
    if metric == DistanceType.CosineExpanded:
        nq = jnp.sqrt(jnp.maximum(q_sq, 1e-30))
        nd = jnp.sqrt(jnp.maximum(db_sq, 1e-30))
        return 1.0 - g / (nq[:, None] * nd[None, :])
    d2 = jnp.maximum(q_sq[:, None] + db_sq[None, :] - 2.0 * g, 0.0)
    if metric == DistanceType.L2SqrtExpanded:
        return jnp.sqrt(d2)
    return d2


# traced OUTSIDE jit: the named_scope still labels ops (the first call
# traces inside the wrapper's context), and the wrapper now runs per
# call — so the obs span records every search, not just the trace
@traced("raft_tpu.brute_force.knn")
@partial(jax.jit, static_argnames=("k", "impl"))
def knn(
    index: BruteForceIndex,
    queries: jax.Array,
    k: int,
    filter_bitset: Optional[jax.Array] = None,
    impl: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Exact k nearest neighbors (reference: brute_force::knn,
    brute_force-inl.cuh:156). Returns (distances [m,k], indices [m,k]).
    The whole search is one jitted program (index is a pytree).

    ``filter_bitset``: optional packed bitset over index rows (see
    neighbors.sample_filter) — cleared bits are excluded from results.
    ``impl``: "auto" uses the strided-bin tile cut (exact up to a
    ~2e-6/query bin-collision chance, see _two_best_per_bin); "sort"
    forces the guaranteed-exact per-tile selection."""
    expects(queries.ndim == 2, "queries must be [m, d]")
    expects(queries.shape[1] == index.dim, "query dim %d != index dim %d",
            queries.shape[1], index.dim)
    m, d = queries.shape
    n = index.size
    expects(k <= n, "k=%d > index size %d", k, n)
    mt = index.metric
    select_min = SELECT_MIN[mt]

    fast = mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                  DistanceType.CosineExpanded, DistanceType.InnerProduct)

    qt, it = _choose_tiles(m, n, d)

    # optional pre-filter mask over index rows (cleared bit → excluded)
    fmask = None
    if filter_bitset is not None:
        from raft_tpu.core import bitset as _bitset

        fmask = _bitset.to_mask(filter_bitset, n)

    def _finalize(vals, ids):
        """With a filter, fewer than k candidates may survive: the inf
        slots would otherwise carry arbitrary ids — mark them -1 (the
        same pad convention the IVF searches use)."""
        if fmask is None:
            return vals, ids
        bad = jnp.isinf(vals)
        return vals, jnp.where(bad, -1, ids)

    if fast:
        q = queries.astype(jnp.float32)
        q_sq = jnp.sum(q * q, axis=1)
        db = index.dataset.astype(jnp.float32)
        db_sq = index.norms if index.norms is not None else jnp.sum(db * db, axis=1)

        if it >= n:
            dists = _expanded_block(q, db, q_sq, db_sq, mt)
            if fmask is not None:
                dists = jnp.where(fmask[None, :], dists,
                                  jnp.inf if select_min else -jnp.inf)
            return _finalize(*_select_k(dists, k, select_min=select_min))

        # scan over index tiles with a running top-k merge — never holds the
        # full [m, n] matrix (tiled_brute_force_knn:234-276).
        n_tiles = -(-n // it)
        pad = n_tiles * it - n
        pad_val = jnp.inf if select_min else -jnp.inf
        dbp = jnp.pad(db, ((0, pad), (0, 0)))
        dbp_sq = jnp.pad(db_sq, (0, pad), constant_values=pad_val)
        db_blocks = dbp.reshape(n_tiles, it, d)
        sq_blocks = dbp_sq.reshape(n_tiles, it)
        kk = min(k, it)
        # the depth-2 strided-bin cut needs k ≤ 2·bins per tile and a
        # lane-aligned tile; it replaces a per-tile k-extraction select
        # whose running-buffer loop measured ~11 ms per [10K, 16K] tile
        # (select dominated the whole scan: 13.7K q/s end to end)
        use_bins = (impl != "sort" and it % _BIN_LANES == 0
                    and kk <= 2 * _BIN_LANES)

        if fmask is not None:
            fmask_blocks = jnp.pad(fmask, (0, pad)).reshape(n_tiles, it)
        else:
            fmask_blocks = jnp.ones((n_tiles, it), jnp.bool_)

        def step(carry, inp):
            best_v, best_i = carry
            db_blk, sq_blk, base, mask_blk = inp
            dists = _expanded_block(q, db_blk, q_sq, sq_blk, mt)
            dists = jnp.where(mask_blk[None, :], dists, pad_val)
            if use_bins:
                # EXACT unless ≥3 of a query's true top-k collide in one
                # of the 128 stride bins of one tile (p ≈ 2e-6 per query
                # at k=10; impl="sort" forces the guaranteed path). Bin
                # positions resolve arithmetically — no gathers.
                tv, ti = _two_best_per_bin(dists, select_min)
            else:
                tv, ti = _select_k(dists, kk, select_min=select_min)
            ti = ti.astype(idt) + base
            cat_v = jnp.concatenate([best_v, tv], axis=1)
            cat_i = jnp.concatenate([best_i, ti], axis=1)
            nv, pos = _top_k_merge(cat_v, k, select_min)
            ni = jnp.take_along_axis(cat_i, pos, axis=1)
            return (nv, ni), None

        # global ids = tile base + in-tile position: the bases (and the
        # add) run in the policy dtype of the FULL row count (core.ids) —
        # base values reach n, which overflows int32 past 2³¹ rows even
        # though every in-tile position fits it
        idt = _ids.id_dtype(n)
        init_v = jnp.full((m, k), pad_val, jnp.float32)
        init_i = jnp.zeros((m, k), idt)
        bases = jnp.arange(n_tiles, dtype=idt) * it
        (vals, idx), _ = lax.scan(
            step, (init_v, init_i), (db_blocks, sq_blocks, bases, fmask_blocks))
        return _finalize(vals, idx)

    # general metrics: full pairwise (row-tiled internally) + select
    dists = pairwise_distance(queries, index.dataset, metric=mt,
                              metric_arg=index.metric_arg)
    if fmask is not None:
        dists = jnp.where(fmask[None, :], dists,
                          jnp.inf if select_min else -jnp.inf)
    return _finalize(*_select_k(dists, k, select_min=select_min))


def knn_arrays(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    metric="euclidean",
    metric_arg: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot build+search convenience (mirrors pylibraft's functional
    ``brute_force.knn``)."""
    return knn(build(dataset, metric=metric, metric_arg=metric_arg), queries, k)
