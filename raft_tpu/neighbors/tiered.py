"""Tiered IVF search — host-resident raw vectors, prefetched under the scan.

The serving-path memory tier (ISSUE 17). An IVF-PQ tenant's scan
structures (packed codes, centroids, norms) are small and touched by
every query — they stay HBM-resident. Its raw vectors are ~10-30×
bigger and touched only at the exact re-rank, for ``k_cand`` rows per
query — they can live in HOST memory (numpy array or memmap) with only
the candidate rows crossing host→HBM per batch. This module makes that
hop free at steady state by running it UNDER the scan:

- the query batch is split into pipeline sub-batches
  (:func:`pipeline_batch`);
- sub-batch *i*'s oversampled scan is dispatched, its candidate ids
  submitted to a :class:`RowPrefetcher` — a background reader thread
  resolves the ids (the only device sync, off the main thread), gathers
  the rows from the host base under the PR-7 ``IO_POLICY`` retry
  (fault point ``serve.row_read``), and lands them on device;
- while the reader fetches batch *i*'s rows, the main thread dispatches
  batch *i+1*'s scan and re-ranks batch *i−1*'s already-landed rows
  (``refine.refine_landed`` — the exact epilogue), so the host transfer
  hides under scan + refine compute exactly like the distributed
  build's chunk reads hide under encode (PR-13 ``ChunkPrefetcher``,
  whose counter/error/close contract this mirrors).

Accounting: ``serve.prefetch.hit{tenant=}`` (rows were already landed
when the consumer asked — the transfer fully hid) vs
``serve.prefetch.stall{tenant=}`` (the consumer waited; the un-hidden
wait runs under a ``span("h2d")``). ``prefetch=False`` degenerates to a
serialized inline fetch per get — the bench's comparison leg.

Results are BIT-EQUAL to the HBM-resident path: the row gather
reproduces ``refine.refine_gathered``'s host-side semantics (clip +
f32 gather) and the re-rank is the same jitted ``_refine_rows``
program; each query's math is independent, so the sub-batch split is
exact (the ``halve_batch`` precedent).

Dispatch: ``SearchParams.refine_transfer`` ("auto" | "tiered" |
"serial") and the ``RAFT_TPU_TIERED_REFINE`` tri-state env override;
:func:`tiered_refine_wanted` is the guard (``ivf_common.
tiered_refine_mem_ok`` bounds the in-flight landed-row buffers; a
decline is a counted ``degrade.steps`` move to the serialized host
gather, per the GL15 convention).
"""

from __future__ import annotations

import os
import queue
import threading
from collections import deque
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.tracing import span
from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _obs_spans
from raft_tpu.robust import degrade as _degrade
from raft_tpu.robust import faults as _faults
from raft_tpu.robust import retry as _retry

__all__ = [
    "RowPrefetcher", "host_row_reader", "pipeline_batch",
    "tiered_refine_wanted", "search_refined_tiered",
    "serving_tenant", "current_tenant", "PREFETCH_DEPTH",
]

#: in-flight landed-row buffers the prefetch pipeline may hold: the
#: done-queue depth. One being consumed + ``PREFETCH_DEPTH`` parked is
#: the HBM bound ``ivf_common.tiered_refine_mem_ok`` sizes against.
PREFETCH_DEPTH = 2

# Per-thread serving-tenant attribution for the prefetch counters:
# dispatch_batch brackets its search with serving_tenant(name), so the
# serve.prefetch.{hit,stall} series carry tenant= labels without
# plumbing a name through SearchParams. Thread-local like the degrade
# quality gate — one tenant's dispatch can never label another's.
_tenant_tls = threading.local()


class serving_tenant:
    """Context manager naming the tenant whose dispatch brackets this
    thread's tiered searches (``None``/missing → ``"-"``)."""

    __slots__ = ("_name", "_prev")

    def __init__(self, name: Optional[str]):
        self._name = name
        self._prev = None

    def __enter__(self) -> "serving_tenant":
        self._prev = getattr(_tenant_tls, "name", None)
        _tenant_tls.name = self._name
        return self

    def __exit__(self, *exc) -> None:
        _tenant_tls.name = self._prev


def current_tenant() -> str:
    """The tenant label for this thread's prefetch counters."""
    return getattr(_tenant_tls, "name", None) or "-"


def pipeline_batch(m: int) -> int:
    """Pipeline sub-batch size for an ``m``-query search: the explicit
    ``RAFT_TPU_TIERED_BATCH`` when set, else ``max(32, ceil(m/4))`` —
    at least 4 sub-batches on real serving batches (enough stages for
    the overlap to bite) without shrinking below a scan-efficient
    width. Deterministic in ``m`` alone, so the serving path's jitted
    sub-batch shapes are a closed set the AOT warmup covers."""
    raw = os.environ.get("RAFT_TPU_TIERED_BATCH", "")  # int value
    if raw.strip():
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return max(32, -(-int(m) // 4))


def host_row_reader(host_base, tenant: str = "-"
                    ) -> Callable[[Any], jax.Array]:
    """Build the prefetcher's ``fetch_fn`` over a host-resident base:
    ``fetch(candidates [m_b, C] device/host ids) -> [m_b, C, d] f32
    device rows``.

    Runs on the reader thread: the ``np.asarray(candidates)`` is the
    pipeline's only device sync (it blocks until that sub-batch's scan
    delivers — ON the worker, while the main thread dispatches the next
    scan). The gather reproduces ``refine.refine_gathered`` bit-for-bit
    (clip to [0, n−1], f32 gather) so the tiered path's results match
    the serialized host path exactly. The host read + H2D retries under
    ``retry.IO_POLICY`` (fault point ``serve.row_read``; a recovery
    counts ``retry.recovered{site=serve.row_read}``)."""
    n, d = host_base.shape

    def fetch(candidates) -> jax.Array:
        cand = np.asarray(candidates)  # device sync — worker-side only
        m_b, C = cand.shape

        def attempt():
            _faults.faultpoint("serve.row_read")
            safe = np.clip(cand, 0, n - 1)
            rows = np.asarray(host_base[safe.reshape(-1)],
                              np.float32).reshape(m_b, C, d)
            # cost attribution (ISSUE 20): host-tier IO bytes, charged
            # at the single fetch chokepoint so both the direct-read
            # and prefetched paths count. Attempt-side: a retried read
            # re-moves the bytes, and re-moved bytes are the cost.
            if _obs_spans.enabled():
                _obs_spans.registry().inc(
                    "cost.io_bytes", float(rows.nbytes),
                    labels={"tenant": tenant})
            return jax.device_put(rows)

        return _retry.retry_call(attempt, site="serve.row_read",
                                 policy=_retry.IO_POLICY)

    return fetch


class RowPrefetcher:
    """Submission-driven host→HBM candidate-row pipeline.

    The serving twin of the build's :class:`~raft_tpu.parallel.build.
    ChunkPrefetcher` — same thread/queue/counter/error contract, but
    fed by :meth:`submit` as the scan produces candidate ids instead of
    walking a precomputed range list (serving cannot know the ids ahead
    of the scan). A background reader resolves each submitted candidate
    block through ``fetch_fn`` and parks up to ``depth`` landed device
    row blocks; :meth:`get` returns them in submit order.

    Accounting (only when obs recording is on):

    - ``serve.prefetch.hit{tenant=}`` — the rows were already landed
      when requested (the host fetch fully hid under compute);
    - ``serve.prefetch.stall{tenant=}`` — the consumer had to wait; the
      wait runs under a ``span("h2d")`` so un-hidden transfer time
      lands beside the scan/refine stage spans.

    ``prefetch=False`` degenerates to a serialized inline fetch at each
    :meth:`get` (same counter/span names, every get a stall) — the
    bench's serialized-gather comparison leg.

    Error contract: an exception on the reader thread (IO failure past
    the retry budget, an injected fault) is re-raised at the consumer's
    next :meth:`get`; the reader exits after queueing it. :meth:`close`
    is idempotent, drains both queues and joins the thread — safe to
    call mid-stream (the ``finally`` of an interrupted search)."""

    def __init__(self, fetch_fn: Callable[[Any], jax.Array],
                 depth: int = PREFETCH_DEPTH, tenant: str = "-",
                 prefetch: bool = True):
        self._fetch = fetch_fn
        self._tenant = tenant
        self._prefetch = bool(prefetch)
        self._submitted = 0
        self._taken = 0
        self._pending: deque = deque()  # serialized mode: parked ids
        self._work: "queue.Queue" = queue.Queue()
        self._done: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self._prefetch:
            self._thread = threading.Thread(
                target=self._run, name="raft_tpu-row-prefetch",
                daemon=True)
            self._thread.start()

    def _count(self, name: str) -> None:
        if _obs_spans.enabled():
            _obs_spans.registry().inc(name,
                                      labels={"tenant": self._tenant})

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                cand = self._work.get(timeout=0.05)
            except queue.Empty:
                continue
            if cand is None:  # close() sentinel
                return
            try:
                item = (self._fetch(cand), None)
            except BaseException as e:  # propagated at the next get()
                item = (None, e)
            while not self._stop.is_set():
                try:
                    self._done.put(item, timeout=0.05)
                    break
                except queue.Full:
                    continue
            if item[1] is not None:
                return

    def submit(self, candidates) -> None:
        """Queue one sub-batch's candidate ids for fetching. Never
        blocks and never syncs — the ids may still be an in-flight
        device computation; the reader thread resolves them."""
        self._submitted += 1
        if not self._prefetch:
            self._pending.append(candidates)
        else:
            self._work.put(candidates)

    def get(self) -> jax.Array:
        """Next landed ``[m_b, C, d]`` f32 device row block (submit
        order). Raises the reader's exception if its fetch failed;
        ``IndexError`` when every submitted block was already taken."""
        if self._taken >= self._submitted:
            raise IndexError("RowPrefetcher: get() past the last submit")
        if not self._prefetch:
            cand = self._pending.popleft()
            self._count("serve.prefetch.stall")
            with span("h2d"):
                x = self._fetch(cand)
            self._taken += 1
            return x
        # benign race on empty(): a reader mid-put counts as a stall
        # with a ~zero-length wait — the conservative side
        if self._done.empty():
            self._count("serve.prefetch.stall")
            with span("h2d"), _sanitize.blocking_region("queue.get"):
                x, exc = self._done.get()
        else:
            self._count("serve.prefetch.hit")
            with _sanitize.blocking_region("queue.get"):
                x, exc = self._done.get()
        if exc is not None:
            self.close()
            raise exc
        self._taken += 1
        return x

    def close(self) -> None:
        """Stop the reader and release queue slots (idempotent). A
        reader stuck inside a slow retried fetch can outlive the join
        timeout — keep the handle (and say so) instead of dropping the
        reference, so the still-running thread stays visible rather
        than silently gathering rows for a search that moved on."""
        self._stop.set()
        for q in (self._work, self._done):
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        if self._thread is not None:
            with _sanitize.blocking_region("join"):
                self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                from raft_tpu.core import logging as _log

                _log.warn("RowPrefetcher.close: reader thread still "
                          "inside a fetch after 5s (slow IO/retry "
                          "backoff) — it will exit at its next "
                          "stop-flag check")
            else:
                self._thread = None


def tiered_refine_wanted(dataset, m: int, k_cand: int, d: int,
                         params) -> bool:
    """True when the prefetch-overlapped tier serves this refined
    search: a host-resident 2-D base (jax.Array bases take the device
    refine tiers; providers regenerate), ``refine_transfer`` not
    pinned ``"serial"``, the ``RAFT_TPU_TIERED_REFINE`` tri-state not
    off, and — unless forced on — at least two pipeline sub-batches
    (one batch has nothing to overlap under). The
    ``tiered_refine_mem_ok`` guard bounds the in-flight landed-row
    buffers; its decline is a counted ``degrade.steps`` move to the
    serialized host gather (``refine.mem_guard`` fault point forces
    the decline branch for CI)."""
    from raft_tpu.neighbors import ivf_common as ic

    shape = getattr(dataset, "shape", None)
    if (dataset is None or isinstance(dataset, jax.Array)
            or hasattr(dataset, "_block") or shape is None
            or len(shape) != 2):
        return False
    transfer = getattr(params, "refine_transfer", "auto")
    if transfer == "serial":
        return False
    env = _obs_spans.env_tristate("RAFT_TPU_TIERED_REFINE")
    if env == "off":
        return False
    forced_on = transfer == "tiered" or env == "on"
    mb = pipeline_batch(m)
    if not forced_on and m <= mb:
        return False  # a single sub-batch cannot overlap anything
    mem_ok = ic.tiered_refine_mem_ok(min(mb, m), k_cand, d)
    if _faults.forced("tiered.mem_guard"):  # CI-testable decline path
        mem_ok = False
    if not mem_ok:
        # the static half of the degradation policy: the guard's
        # pre-emptive decline counts the same degrade.steps move a
        # reactive walk would (GL15 convention)
        _degrade.note_step("refine", "tiered_prefetch", "host_gather",
                           "mem_guard")
        return False
    return True


def search_refined_tiered(search_fn, index, queries: jax.Array, k: int,
                          k_cand: int, scan_params, filter_bitset,
                          host_base, metric: str,
                          prefetch: bool = True
                          ) -> Tuple[jax.Array, jax.Array]:
    """The tiered refined search: pipeline sub-batches through
    oversampled scan → candidate-row prefetch → exact re-rank, with the
    host fetch of batch *i* overlapped under batch *i+1*'s scan and
    batch *i−1*'s refine. Returns ``(distances [m, k], ids [m, k])``,
    bit-equal to the serialized host-gather path (module docstring).

    ``search_fn`` is the owning module's plain ``search`` (ivf_pq /
    ivf_flat), called per sub-batch with ``scan_params`` (refine
    already stripped); ``prefetch=False`` serializes every fetch — the
    bench's comparison leg, same results."""
    from raft_tpu.neighbors import refine as _refine

    m = queries.shape[0]
    mb = pipeline_batch(m)
    tenant = current_tenant()
    pf = RowPrefetcher(host_row_reader(host_base, tenant=tenant),
                       depth=PREFETCH_DEPTH, tenant=tenant,
                       prefetch=prefetch)
    in_flight: deque = deque()  # (queries slice, candidate ids)
    outs = []

    def consume():
        q_i, ids_i = in_flight.popleft()
        rows = pf.get()
        outs.append(_refine.refine_landed(rows, q_i, ids_i, k,
                                          metric=metric))

    try:
        for a in range(0, m, mb):
            q_i = queries[a:a + mb]
            _, ids_i = search_fn(index, q_i, k_cand, scan_params,
                                 filter_bitset)
            pf.submit(ids_i)
            in_flight.append((q_i, ids_i))
            # keep one sub-batch's fetch in the air behind the scan we
            # just dispatched; consume the one BEFORE it, whose rows
            # landed while that scan ran
            if len(in_flight) > 1:
                consume()
        while in_flight:
            consume()
    finally:
        pf.close()
    if len(outs) == 1:
        return outs[0]
    return (jnp.concatenate([o[0] for o in outs], axis=0),
            jnp.concatenate([o[1] for o in outs], axis=0))
