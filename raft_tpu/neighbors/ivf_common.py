"""List-centric IVF scan machinery — the TPU-native inversion of the
reference's per-query list scan.

The reference's search kernels (ivf_flat_interleaved_scan-inl.cuh,
ivf_pq_compute_similarity-inl.cuh) are per-query: one CTA walks the
query's probed lists through shared memory. On a TPU that structure is
wrong twice over: per-query work is too small for the MXU, and each
query re-reads its lists from HBM.

The TPU-native structure inverts the loop — **group the query batch by
probed list**, then stream each probed list through the MXU in
fixed-size *segments* of its query queue:

1. probe selection gives ``probes [B, n_probes]`` (queries → lists);
2. :func:`segment_probes` buckets the (query, probe) pairs into
   segments of ``seg`` pairs, each segment owned by ONE list, via one
   stable sort — the same trick the index build uses to pack rows;
3. the scan loops over *segment chunks*: gather each segment's list
   block and its ``seg`` queries, run one batched ``[seg, d] × [d, L]``
   contraction per segment on the MXU, take a per-(slot, list) top-k;
4. results are gathered back to ``[B, n_probes, k]`` pair order (a
   gather, not a scatter — TPUs gather much faster than they scatter)
   and a final select_k merges each query's n_probes·k candidates.

Segments are the load-balancing device: a skew-hot list simply owns
more segments, a cold list at most one — total padded work is bounded
by ``pairs + n_lists·seg`` slots regardless of skew. (The earlier
design padded every list's queue to the batch's max per-list load,
which both wasted up to ~70× FLOPs under skew and needed a host sync
to read that load; the segmented table is **statically shaped** from
``(B, n_probes, n_lists)`` alone, so a whole search — probe selection,
segmenting, scan, merge — compiles into ONE jitted program with no
host round-trip.)

HBM traffic: each list block is read once per *owned segment* per
batch instead of once per *probing query* — the amortization that
makes IVF beat brute force on TPU at large batch sizes.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


# Default segment size: one MXU-friendly block of queries per segment
# (matches the Pallas grouped kernel's bq block).
SEGMENT_SIZE = 128


def sharded_dispatch(index, mesh, cls_name: str):
    """The pod-scale dispatch gate shared by the IVF search entries:
    returns the ``raft_tpu.parallel.ivf`` module when ``(index, mesh)``
    route to the sharded tier, ``None`` for the single-chip path.

    A sharded index can only exist if ``parallel.ivf`` is already
    imported, so the gate checks ``sys.modules`` first — plain
    single-chip searches never pay the parallel-subtree import.
    Validates the pairing (a sharded index without its mesh, or a
    ``mesh=`` with a single-chip index, is a caller error); per-entry
    capability checks (filters, refine) stay with the caller."""
    import sys

    from raft_tpu.core.errors import expects as _expects

    if mesh is None and "raft_tpu.parallel.ivf" not in sys.modules:
        return None
    from raft_tpu.parallel import ivf as _divf

    cls = getattr(_divf, cls_name)
    if mesh is None and not isinstance(index, cls):
        return None
    _expects(isinstance(index, cls),
             "mesh= dispatch needs a parallel.%s index (got %s)",
             cls_name, type(index).__name__)
    _expects(mesh is not None,
             "a %s index needs search(..., mesh=...)", cls_name)
    return _divf


def n_segments(pairs: int, n_lists: int, seg: int) -> int:
    """Static upper bound on the segment count: every list owns
    ``ceil(load/seg)`` segments, and ``sum ceil(load/seg) <=
    floor(pairs/seg) + n_lists`` for any load histogram — so the table
    shape depends only on (B, n_probes, n_lists, seg), never on the
    data. That is what keeps the whole search one jitted program."""
    return pairs // seg + n_lists


def segment_probes(probes: jax.Array, n_lists: int, seg: int, n_seg: int):
    """Bucket (query, probe) pairs into per-list segments (trace-time;
    called inside the search jit).

    One stable sort of the flattened probe table gives each pair its
    within-list rank; segment ids follow from a cumsum of per-list
    segment counts. TPU note: everything here is sorts + GATHERS — the
    segment table is filled by computing, per (segment, slot), which
    sorted pair occupies it (``i = starts[list] + local_seg·seg +
    slot``), and pair-order addresses come from the sort's inverse
    permutation (a second argsort). XLA scatters serialize on TPU
    (~100 ms at 10⁵ elements, measured), so the scatter formulation of
    the same table costs more than the whole rest of the scan.

    Parameters
    ----------
    probes : [B, P] int32 list ids per query.
    n_lists : number of inverted lists.
    seg : segment capacity (pairs per segment, static).
    n_seg : static segment-table height (:func:`n_segments`).

    Returns
    -------
    seg_list : [n_seg] int32 — which list each segment scans (unused
        segments point at an arbitrary list; their slots are all -1).
    seg_q : [n_seg, seg] int32 — query ids, -1 pad.
    pair_seg, pair_slot : [B, P] int32 — each pair's (segment, slot)
        address, for gathering results back to pair order.
    """
    B, P = probes.shape
    BP = B * P
    l_flat = probes.reshape(-1).astype(jnp.int32)
    # sort_key_val, not argsort+gather: values ride the sort for free
    # (an argsort plus the sorted_l re-gather measured ~3× the cost)
    iota = jnp.arange(BP, dtype=jnp.int32)
    sorted_l, order = jax.lax.sort_key_val(l_flat, iota)
    starts = jnp.searchsorted(sorted_l, jnp.arange(n_lists, dtype=jnp.int32))
    counts = jnp.diff(jnp.append(starts, BP)).astype(jnp.int32)
    segs_per_list = (counts + seg - 1) // seg
    seg_base = jnp.cumsum(segs_per_list) - segs_per_list  # exclusive
    # segment → owning list: rightmost list whose base is <= s (right-
    # side search steps over zero-segment lists, whose base repeats)
    seg_ids = jnp.arange(n_seg, dtype=jnp.int32)
    seg_list = jnp.clip(
        jnp.searchsorted(seg_base, seg_ids, side="right") - 1,
        0, n_lists - 1).astype(jnp.int32)
    # seg_q by gather: slot (s, j) holds sorted pair i = starts[l] +
    # local_seg·seg + j, valid while that rank is inside l's load
    # (covers both partial tail segments and unused segments, whose
    # local rank lands beyond the owning list's count)
    rank0 = (seg_ids - seg_base[seg_list]) * seg           # [n_seg]
    i0 = starts[seg_list] + rank0
    j = jnp.arange(seg, dtype=jnp.int32)
    rank = rank0[:, None] + j[None, :]
    valid = rank < counts[seg_list][:, None]
    q_of = (order // P).astype(jnp.int32)
    seg_q = jnp.where(
        valid, q_of[jnp.clip(i0[:, None] + j[None, :], 0, BP - 1)], -1)
    # pair-order addresses via the sort's inverse permutation
    rank_sorted = iota - starts[sorted_l].astype(jnp.int32)
    seg_sorted = seg_base[sorted_l] + rank_sorted // seg
    slot_sorted = rank_sorted % seg
    # inverse permutation by sorting the (seg, slot) addresses back to
    # pair order keyed on `order` — one sort carries both payloads, no
    # argsort + two pointwise gathers
    addr = seg_sorted * seg + slot_sorted
    _, addr_pair = jax.lax.sort_key_val(order, addr)
    return (seg_list, seg_q,
            (addr_pair // seg).reshape(B, P),
            (addr_pair % seg).reshape(B, P))


def gather_segment_results(seg_vals: jax.Array, seg_ids: jax.Array,
                           pair_seg: jax.Array, pair_slot: jax.Array
                           ) -> Tuple[jax.Array, jax.Array]:
    """Collect per-(segment, slot) top-k back into (query, probe) order:
    ``[n_seg, seg, kk] → [B, P, kk]``. Pure gather — every pair owns
    exactly one slot (the segmented table is drop-free by construction)."""
    return seg_vals[pair_seg, pair_slot], seg_ids[pair_seg, pair_slot]


def merge_bin_results(keys: jax.Array, kids: jax.Array,
                      pair_seg: jax.Array, pair_slot: jax.Array,
                      k: int, select_min: bool, invalid, recall: float):
    """Merge the scalar-prefetch kernel's per-bin output into final
    (distances [B, k], ids [B, k]) — shared by IVF-Flat and IVF-PQ.

    ``keys/kids [n_seg, S, nbins]`` are minimized sort keys + global
    candidate ids (-1 invalid, key +inf) from ops.pallas_kernels.
    segmented_scan_topk. Structure (each step sized by measurement on a
    1M×128 B=10000 search): gather each pair's WHOLE bin row to query
    order (a [B·P]-row block gather — row gathers are cheap; the former
    per-slot cut needed a [n_seg·S, nbins]→kk ``take_along_axis``
    whose ~3M pointwise picks measured 50–137 ms and dominated the
    whole search), one hardware top-k per query over its P·nbins
    candidates, then resolve the k winning ids with a [B, k]-pick
    gather (~100K picks ≈ 2 ms). Metric epilogues (sqrt, 1−cos) stay
    with the callers."""
    n_seg, seg, nbins = keys.shape
    B, P = pair_seg.shape
    kk = min(k, nbins)
    kq = min(k, P * kk)
    # per-slot cut on KEYS ONLY — the hardware top-k over 256-wide bin
    # rows is near-exact (measured end recall 0.999+; one cut over the
    # concatenated [B, P·nbins] row instead loses clustered winners to
    # reduction-tile collisions, measured 0.97)
    mk, sel = jax.lax.approx_min_k(keys.reshape(-1, nbins), kk,
                                   recall_target=recall)
    # gather the kk-wide cut (values + BIN POSITIONS) to query order —
    # c-class row gathers, ~1-4 ms. The former formulation gathered the
    # winning IDS here via a [n_seg·S, nbins]→kk take_along_axis whose
    # ~3M pointwise picks measured 50–137 ms and dominated the search.
    pv = mk.reshape(n_seg, seg, kk)[pair_seg, pair_slot].reshape(B, P * kk)
    pb = sel.reshape(n_seg, seg, kk)[pair_seg, pair_slot].reshape(B, P * kk)
    # exact final per-query cut over the P·kk survivors
    nv, pos2 = jax.lax.top_k(-pv, kq)
    # compose winners back to (seg, slot, bin) and resolve global ids —
    # [B, kq] picks only (~100K picks ≈ 2 ms)
    p_of = pos2 // kk
    bin_of = jnp.take_along_axis(pb, pos2, axis=1)
    seg_of = jnp.take_along_axis(pair_seg, p_of, axis=1)
    slot_of = jnp.take_along_axis(pair_slot, p_of, axis=1)
    out_ids = kids[seg_of, slot_of, bin_of]                 # [B, kq]
    out_vals = -nv if select_min else nv  # keys minimized; ip flips back
    out_vals = jnp.where(out_ids < 0, invalid, out_vals)
    if k > kq:
        pad = k - kq
        out_vals = jnp.pad(out_vals, ((0, 0), (0, pad)),
                           constant_values=invalid)
        out_ids = jnp.pad(out_ids, ((0, 0), (0, pad)), constant_values=-1)
    return out_vals, out_ids


# Auto-dispatch guard: fall back from grouped to per_query only when the
# segmented scan's allocations would be memory-hostile. Measured
# on-chip, grouped beats the gather-bound per_query path, so this is a
# memory bound, not a cost model. The accumulators are transient (freed
# after the pair gather), so the cap is sized against total HBM.
GROUPED_BYTES_CAP = 4 << 30
# Per-chunk budget for the scan's transients (the [chunk·seg, L]
# distance block and the gathered [chunk, L, d] list blocks); search()
# shrinks the segment chunk (down to 1) to honor it.
CHUNK_BYTES_TARGET = 256 << 20


def grouped_mem_ok(n_seg: int, seg: int, kk: int, pairs: int) -> bool:
    """True when the segmented scan's buffers fit the budget: the
    [n_seg, seg] int32 query table, the [n_seg, seg, kk] f32+i32
    per-slot top-k accumulators, and the [pairs, kk] gathered results
    live at the same time during gather_segment_results."""
    return (n_seg * seg * (4 + 8 * kk) + pairs * kk * 8) <= GROUPED_BYTES_CAP


def lut_scan_mem_ok(n_seg: int, seg: int, rot: int, pairs: int,
                    nbins: int = 256) -> bool:
    """HBM budget for the Pallas LUT-scan tier: the gathered per-segment
    queries [n_seg, seg, rot] f32, the kernel's [n_seg, seg, nbins]
    key+id bin tables, and the pair-order gather [pairs, nbins] f32+i32
    all live at once (everything else stays in VMEM — that is the tier's
    point). Shares GROUPED_BYTES_CAP with the XLA grouped scan."""
    qv = n_seg * seg * rot * 4
    bins = n_seg * seg * nbins * 8
    gathered = pairs * nbins * 8
    return qv + bins + gathered <= GROUPED_BYTES_CAP


def filtered_scan_mem_ok(n_lists: int, L: int,
                         slot_bytes: int = 1) -> bool:
    """HBM budget for a FILTERED fused-scan dispatch (the admission
    guard GL15 expects beside every streaming-kernel call site that
    hands a kernel filter operands). ``slot_bytes`` is the per-slot
    transient width of the filter operand the dispatching tier builds:
    1 for the LUT/ring tiers — a ``[n_lists, L]`` bool keep mask
    re-packed to ``[n_lists, ceil(L/8)]`` u8 byte rows
    (``sample_filter.list_filter_bytes``; ~2.5 GB at n = 2.2e9, inside
    the cap, so billion-scale filtered searches stay on the fused
    tier) — and 5 for segk's sentinel-masked i32 id table (mask +
    i32; segk's recon-cache precondition keeps its n small anyway).
    The packed byte rows are counted in both cases."""
    slots = n_lists * L
    return slots * slot_bytes + slots // 8 <= GROUPED_BYTES_CAP


def gather_refine_mem_ok(n: int, d: int, itemsize: int = 4,
                         m: int = 0, C: int = 0) -> bool:
    """HBM guard for the fused gather-refine tier (ops.pallas_kernels.
    gather_refine_topk): everything per-candidate stays in VMEM — the
    tier's point — but a dataset whose minor dim is not lane-aligned
    pays a PER-CALL padded ``[n, ceil(d/128)·128]`` HBM copy before the
    kernel (row DMAs address lane-tiled rows; the pad lives inside the
    jitted wrapper, so every refined search re-materializes it). Two
    checks: the copy must fit the shared transient cap, and — when the
    workload shape ``(m, C)`` is known — it must be smaller than the
    ``[m, C, d]`` f32 gather buffer the tier exists to avoid (a small
    re-rank against a huge unaligned dataset would otherwise pay MORE
    HBM than the einsum path it replaces). The XLA path pads per
    candidate row instead, so declining here is always serviceable."""
    if d % 128 == 0:
        return True
    dpad = -(-d // 128) * 128
    pad_copy = n * dpad * itemsize
    if pad_copy > GROUPED_BYTES_CAP:
        return False
    if m and C:
        return pad_copy <= m * C * d * 4
    return True


def tiered_refine_mem_ok(m_b: int, C: int, d: int,
                         depth: int = 2) -> bool:
    """HBM guard for the tiered prefetch-refine pipeline
    (neighbors.tiered): up to ``depth`` landed ``[m_b, C, d]`` f32
    candidate-row blocks parked in the prefetch queue plus the one
    being re-ranked live at once — the tier's whole HBM footprint (the
    base itself stays on the host, that is the point). Shares
    GROUPED_BYTES_CAP with the scan transients. Declining here is
    always serviceable: the serialized host gather (refine_gathered)
    holds exactly one block."""
    return (depth + 1) * m_b * C * d * 4 <= GROUPED_BYTES_CAP


def fit_seg_chunk(seg: int, L: int, d: int, want: int) -> int:
    """Largest segment chunk ≤ ``want`` whose per-step transients — the
    [chunk·seg, L] f32 distance block and the gathered [chunk, L, d]
    f32 list blocks — stay under CHUNK_BYTES_TARGET."""
    per_seg = L * 4 * (seg + d)
    return max(1, min(want, CHUNK_BYTES_TARGET // max(1, per_seg)))


# spill-cascade depth shared by every spilling builder (ivf_flat.build,
# ivf_pq.build, ivf_pq.build_chunked): a dense natural blob can fill
# its whole ~5-list neighborhood, so top-4 choices still drop rows a
# 6th keeps (measured on a 40%-mass Gaussian over 16 lists: depth 4
# dropped 158 rows, depth 6 dropped 0)
SPILL_DEPTH = 6


@partial(jax.jit, static_argnames=("n_lists", "cap"))
def spill_assignments(l1: jax.Array, l2: jax.Array, n_lists: int,
                      cap: int, *more) -> jax.Array:
    """Cap list loads by spilling overflow rows to their next-nearest
    lists — the TPU-native answer to padded-block waste.

    The padded [n_lists, L, ...] layout sizes L to the FATTEST list, so
    skewed assignments pay padding on every scan DMA (and at 100M rows
    can overflow HBM outright). Instead of dropping rows past the cap
    (the packers' old behavior) or padding to the skew, rows ranked
    ≥ cap in their first-choice list CASCADE to their next choice
    (``l2``, then each array in ``more``); rows that overflow every
    choice get the drop marker ``n_lists`` (callers warn). Deeper
    choice lists matter under natural-blob skew: one dense Gaussian
    holding ~40% of the rows fills its whole neighborhood of lists, so
    top-2 spilling still drops rows that a 3rd/4th choice keeps. A
    probe set covering a query's nearest lists almost always includes
    those next-nearest centers too, so the recall cost is marginal
    while L shrinks from ~(max load) to cap.

    All sorts + gathers (one stable sort pass per choice), jit-safe on
    host-sized inputs: [n] i32 argsorts are cheap even at 10⁸ rows.
    Settled rows never move again: ranks sort by (list, arrival
    generation) lexicographically, so later arrivals are the ones past
    the cap.
    """
    choices = (l2,) + more
    n = l1.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    g = len(choices) + 1                       # generations stride
    kmax = g * n_lists + g

    def ranks(keys, base):
        """Stable rank of each row within its group: ``keys`` orders
        rows inside and across groups, ``base`` is each row's group's
        smallest key (rank = sorted position − group start)."""
        sk, order = jax.lax.sort_key_val(keys, iota)
        base_sorted = base[order]
        starts = jnp.searchsorted(sk, jnp.arange(kmax, dtype=jnp.int32))
        rk_sorted = iota - starts[jnp.clip(base_sorted, 0, kmax - 1)]
        _, rk = jax.lax.sort_key_val(order, rk_sorted)
        return rk

    lab = l1.astype(jnp.int32)
    gen = jnp.zeros((n,), jnp.int32)
    for c, lc in enumerate(choices, start=1):
        rank = ranks(lab * g + gen, lab * g)
        over = rank >= cap
        lab = jnp.where(over, lc.astype(jnp.int32), lab)
        gen = jnp.where(over, c, gen)
    rank = ranks(lab * g + gen, lab * g)
    return jnp.where(rank >= cap, jnp.int32(n_lists), lab)


def pack_lists(row_arrays, labels: jax.Array, row_ids: jax.Array,
               n_lists: int, L: int, fill_values):
    """Device-side list packing (jit-safe) — the device twin of the host
    numpy packers in ivf_flat/ivf_pq (reference: encode+pack,
    ivf_pq_build.cuh:1411-1432), used by the distributed SPMD build where
    a host round-trip is impossible.

    One stable sort of ``labels`` gives each row its (list, slot) address;
    rows with ``labels >= n_lists`` (pad markers) or slot ``>= L``
    (overflow) are dropped by the scatter's ``mode="drop"``.

    Parameters
    ----------
    row_arrays : sequence of [n, ...] arrays to pack per-list.
    labels : [n] int — destination list per row.
    row_ids : [n] int32 — ids stored alongside (global ids for shards).
    n_lists, L : static list count / padded capacity.
    fill_values : pad value per row_array.

    Returns (packed_arrays [n_lists, L, ...], ids [n_lists, L] (-1 pad),
    sizes [n_lists] int32, n_dropped () int32 — rows lost to list
    overflow; callers should surface it, the host packers warn —
    row_addr = (row_list [n], row_slot [n]) int32: each input row's
    packed (list, slot) address; slot >= L marks an overflow-dropped
    row. Returning the addresses here keeps consumers (e.g. CAGRA's
    cluster-blocked graph) from re-deriving the packing order.)

    The stored id table preserves ``row_ids``' policy width
    (``core.ids.id_dtype_like``): an int64 global-id array from a
    ≥ 2³¹-row sharded build packs without a silent int32 truncation.
    """
    from raft_tpu.core import ids as _ids

    n = labels.shape[0]
    labels = labels.astype(jnp.int32)
    order = jnp.argsort(labels, stable=True)
    sorted_l = labels[order]
    starts = jnp.searchsorted(sorted_l, jnp.arange(n_lists, dtype=jnp.int32))
    rank = (jnp.arange(n, dtype=jnp.int32)
            - starts[jnp.clip(sorted_l, 0, n_lists - 1)].astype(jnp.int32))
    packed = []
    for arr, fill in zip(row_arrays, fill_values):
        out = jnp.full((n_lists, L) + arr.shape[1:], fill, arr.dtype)
        packed.append(out.at[sorted_l, rank].set(arr[order], mode="drop"))
    idt = _ids.id_dtype_like(row_ids)
    ids = jnp.full((n_lists, L), -1, idt).at[sorted_l, rank].set(
        row_ids[order].astype(idt), mode="drop")
    counts = jnp.zeros((n_lists,), jnp.int32).at[labels].add(1, mode="drop")
    sizes = jnp.minimum(counts, L)
    n_dropped = jnp.sum(counts - sizes)
    # row-order addresses via the sort's inverse permutation (gathers,
    # not scatters — see segment_probes)
    inv = jnp.argsort(order)
    return packed, ids, sizes, n_dropped, (sorted_l[inv], rank[inv])


pack_lists_jit = partial(jax.jit, static_argnames=("n_lists", "L"))(
    lambda row_arrays, labels, row_ids, n_lists, L, fill_values: pack_lists(
        row_arrays, labels, row_ids, n_lists, L, fill_values))
"""Jitted :func:`pack_lists` — single-program builds on remote devices
(eager packing costs a dispatch round-trip per op through a tunnel)."""


def pack_rows_chunked(x: jax.Array, labels: jax.Array, n_lists: int,
                      L: int, chunk_rows: int = 1 << 17):
    """Row-chunked device packing of ``x [n, d]`` into ``[n_lists, L,
    d]`` for WIDE datasets — the one-shot :func:`pack_lists` peaks at
    input + full gather copy + padded output (≈ 13.6 GB at 1M×960,
    an OOM on a 16 GB chip). One sort derives every row's flattened
    destination; chunks of rows then gather + scatter into a DONATED
    output buffer, bounding the peak at input + output + one chunk.

    Returns (packed [n_lists, L, d], ids [n_lists, L] (-1 pad),
    sizes [n_lists], n_dropped)."""
    n, d = x.shape
    labels = labels.astype(jnp.int32)

    @partial(jax.jit, static_argnames=("n_lists", "L"))
    def prep(labels, n_lists, L):
        order = jnp.argsort(labels, stable=True)
        sorted_l = labels[order]
        starts = jnp.searchsorted(sorted_l,
                                  jnp.arange(n_lists, dtype=jnp.int32))
        rank = (jnp.arange(n, dtype=jnp.int32)
                - starts[jnp.clip(sorted_l, 0, n_lists - 1)].astype(jnp.int32))
        valid = (sorted_l >= 0) & (sorted_l < n_lists) & (rank < L)
        dest = jnp.where(valid, sorted_l * L + rank, n_lists * L)
        counts = jnp.zeros((n_lists,), jnp.int32).at[
            jnp.clip(labels, 0, n_lists - 1)].add(
                (labels >= 0) & (labels < n_lists), mode="drop")
        return order, dest, jnp.minimum(counts, L), counts

    order, dest, sizes, counts = prep(labels, n_lists, L)

    @partial(jax.jit, donate_argnums=(0, 1))
    def write_chunk(out, ids_out, rows, ridx, dst):
        out = out.at[dst].set(rows, mode="drop")
        ids_out = ids_out.at[dst].set(ridx, mode="drop")
        return out, ids_out

    out = jnp.zeros((n_lists * L, d), x.dtype)
    ids_out = jnp.full((n_lists * L,), -1, jnp.int32)
    for a in range(0, n, chunk_rows):
        b = min(n, a + chunk_rows)
        oc = order[a:b]
        out, ids_out = write_chunk(out, ids_out, x[oc],
                                   oc.astype(jnp.int32), dest[a:b])
    n_dropped = jnp.sum(counts - sizes)
    return (out.reshape(n_lists, L, d), ids_out.reshape(n_lists, L),
            sizes, n_dropped)


def choose_list_chunk(n_lists: int, target: int) -> int:
    """Largest divisor of ``n_lists`` that is ≤ target (chunked scans
    reshape [n_lists, …] to [n_chunks, chunk, …], so the chunk must
    divide n_lists)."""
    c = max(1, min(target, n_lists))
    while n_lists % c:
        c -= 1
    return c
