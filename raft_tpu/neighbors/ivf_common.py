"""List-centric IVF scan machinery — the TPU-native inversion of the
reference's per-query list scan.

The reference's search kernels (ivf_flat_interleaved_scan-inl.cuh,
ivf_pq_compute_similarity-inl.cuh) are per-query: one CTA walks the
query's probed lists through shared memory. On a TPU that structure is
wrong twice over: per-query work is too small for the MXU, and each
query re-reads its lists from HBM.

The TPU-native structure inverts the loop — **group the query batch by
probed list**, then stream each list block through the MXU exactly once
per batch:

1. probe selection gives ``probes [B, n_probes]`` (queries → lists);
2. :func:`invert_probes` builds the transposed table
   ``qtable [n_lists, qmax]`` (lists → queries) via one sort — the same
   trick the index build uses to pack rows into lists;
3. the scan loops over *list chunks*: for chunk lists, gather their
   (few, small) queries, run one batched ``[qmax, d] × [d, L]``
   contraction per list on the MXU, and take a per-(query,list) top-k;
4. results are gathered back to ``[B, n_probes, k]`` pair order (a
   gather, not a scatter — TPUs gather much faster than they scatter)
   and a final select_k merges each query's n_probes·k candidates.

HBM traffic: each list block is read once per *batch* instead of once
per *probing query* — the amortization that makes IVF beat brute force
on TPU at large batch sizes. ``qmax`` is sized from the actual probe
histogram (``max_probe_load`` + ``exact_qmax``), so the scan is
drop-free; the machinery still tolerates ``rank >= qmax`` defensively
(those pairs come back masked invalid).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_lists",))
def probe_sort(probes: jax.Array, n_lists: int):
    """One stable sort of the flattened probe table, shared by everything
    downstream: the per-list load histogram (max_load → qmax), the
    pair-order ranks, and the qtable scatter. Splitting this qmax-
    independent work out means the host sync that picks the static qmax
    costs one cheap ``max`` instead of a separate scatter-add histogram
    (TPU scatters are serial — the bincount approach measured ~100 ms at
    B=10k on a v5e chip, the sort pipeline amortizes it to ~0).

    Returns (max_load [], sorted_l [B·P], rank_sorted [B·P], q_of [B·P],
    rank [B, P]).
    """
    B, P = probes.shape
    l_flat = probes.reshape(-1).astype(jnp.int32)
    order = jnp.argsort(l_flat, stable=True)
    sorted_l = l_flat[order]
    starts = jnp.searchsorted(sorted_l, jnp.arange(n_lists, dtype=jnp.int32))
    rank_sorted = (jnp.arange(B * P, dtype=jnp.int32)
                   - starts[sorted_l].astype(jnp.int32))
    counts = jnp.diff(jnp.append(starts, B * P))
    max_load = jnp.max(counts)
    # back to pair order (small scatter: B·P elements)
    rank = jnp.zeros((B * P,), jnp.int32).at[order].set(rank_sorted)
    q_of = (order // P).astype(jnp.int32)
    return max_load, sorted_l, rank_sorted, q_of, rank.reshape(B, P)


@partial(jax.jit, static_argnames=("n_lists", "qmax"))
def qtable_from_sort(sorted_l: jax.Array, rank_sorted: jax.Array,
                     q_of: jax.Array, n_lists: int, qmax: int) -> jax.Array:
    """Scatter the sorted probe pairs into the [n_lists, qmax] queue table
    (the only qmax-dependent step; see probe_sort)."""
    qtable = jnp.full((n_lists, qmax), -1, jnp.int32)
    return qtable.at[sorted_l, rank_sorted].set(q_of, mode="drop")


def invert_probes(probes: jax.Array, n_lists: int, qmax: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """Invert queries→lists probes into per-list query queues.

    Parameters
    ----------
    probes : [B, P] int32 list ids per query.
    n_lists : number of inverted lists.
    qmax : queue capacity per list (static).

    Returns
    -------
    qtable : [n_lists, qmax] int32 — query ids probing each list, -1 pad.
    rank : [B, P] int32 — each (query, probe) pair's slot in its list's
        queue; ``rank >= qmax`` marks a dropped pair.
    """
    _, sorted_l, rank_sorted, q_of, rank = probe_sort(probes, n_lists)
    return qtable_from_sort(sorted_l, rank_sorted, q_of, n_lists, qmax), rank


def gather_pair_results(list_vals: jax.Array, list_ids: jax.Array,
                        probes: jax.Array, rank: jax.Array,
                        invalid_val) -> Tuple[jax.Array, jax.Array]:
    """Collect per-(list, queue-slot) top-k back into (query, probe) order.

    ``list_vals/list_ids [n_lists, qmax, k]`` hold each queue slot's local
    top-k; pair (q, p) owns slot ``(probes[q,p], rank[q,p])``. Dropped
    pairs (rank >= qmax) come back masked to ``invalid_val`` / -1.
    Returns ``[B, P, k]`` values and ids.
    """
    qmax = list_vals.shape[1]
    ok = rank < qmax
    r = jnp.minimum(rank, qmax - 1)
    vals = list_vals[probes, r]
    ids = list_ids[probes, r]
    vals = jnp.where(ok[..., None], vals, invalid_val)
    ids = jnp.where(ok[..., None], ids, -1)
    return vals, ids


# Auto-dispatch guard: fall back from grouped to per_query only when the
# grouped scan's qmax-shaped allocations would be memory-hostile.
# Measured on-chip, grouped beats the gather-bound per_query path even at
# full skew (qmax = B), so this is a memory bound, not a cost model. The
# accumulators are transient (freed after the pair gather), so the cap
# is sized against total HBM, not a per-op budget.
GROUPED_BYTES_CAP = 4 << 30
# Per-chunk budget for the [chunk·qmax, L] distance block — the scan's
# transient; search() shrinks the list chunk (down to 1) to honor it.
CHUNK_BYTES_TARGET = 256 << 20


def grouped_mem_ok(n_lists: int, qmax: int, kk: int, pairs: int) -> bool:
    """True when the grouped scan's qmax-shaped buffers fit the budget:
    the [n_lists, qmax] int32 queue table, the [n_lists, qmax, kk]
    f32+i32 per-slot top-k accumulators, and the [pairs, kk] gathered
    results live at the same time during gather_pair_results
    (``pairs`` = B·n_probes; the per-chunk distance block is bounded
    separately via fit_list_chunk)."""
    return (n_lists * qmax * (4 + 8 * kk)
            + pairs * kk * 8) <= GROUPED_BYTES_CAP


def fit_list_chunk(n_lists: int, qmax: int, L: int, want: int) -> int:
    """Largest list chunk ≤ ``want`` (and dividing n_lists) whose
    [chunk·qmax, L] f32 distance block stays under CHUNK_BYTES_TARGET —
    skew-hot batches (large qmax) scan fewer lists per step instead of
    blowing HBM."""
    cap = max(1, CHUNK_BYTES_TARGET // max(1, qmax * L * 4))
    return choose_list_chunk(n_lists, min(want, cap))


def max_probe_load(probes: jax.Array, n_lists: int) -> jax.Array:
    """Largest per-list queue load of a probe table [B, P] — the exact
    qmax needed for a drop-free grouped scan (sort-based; see probe_sort)."""
    return probe_sort(probes, n_lists)[0]


def exact_qmax(max_load: int) -> int:
    """Static queue capacity covering the observed max load, rounded up
    to a power of two (≥8) so repeated searches with similar batches hit
    the jit cache instead of recompiling per batch."""
    m = max(8, int(max_load))
    return 1 << (m - 1).bit_length()


def pack_lists(row_arrays, labels: jax.Array, row_ids: jax.Array,
               n_lists: int, L: int, fill_values):
    """Device-side list packing (jit-safe) — the device twin of the host
    numpy packers in ivf_flat/ivf_pq (reference: encode+pack,
    ivf_pq_build.cuh:1411-1432), used by the distributed SPMD build where
    a host round-trip is impossible.

    One stable sort of ``labels`` gives each row its (list, slot) address;
    rows with ``labels >= n_lists`` (pad markers) or slot ``>= L``
    (overflow) are dropped by the scatter's ``mode="drop"``.

    Parameters
    ----------
    row_arrays : sequence of [n, ...] arrays to pack per-list.
    labels : [n] int — destination list per row.
    row_ids : [n] int32 — ids stored alongside (global ids for shards).
    n_lists, L : static list count / padded capacity.
    fill_values : pad value per row_array.

    Returns (packed_arrays [n_lists, L, ...], ids [n_lists, L] (-1 pad),
    sizes [n_lists] int32, n_dropped () int32 — rows lost to list
    overflow; callers should surface it, the host packers warn).
    """
    n = labels.shape[0]
    labels = labels.astype(jnp.int32)
    order = jnp.argsort(labels, stable=True)
    sorted_l = labels[order]
    starts = jnp.searchsorted(sorted_l, jnp.arange(n_lists, dtype=jnp.int32))
    rank = (jnp.arange(n, dtype=jnp.int32)
            - starts[jnp.clip(sorted_l, 0, n_lists - 1)].astype(jnp.int32))
    packed = []
    for arr, fill in zip(row_arrays, fill_values):
        out = jnp.full((n_lists, L) + arr.shape[1:], fill, arr.dtype)
        packed.append(out.at[sorted_l, rank].set(arr[order], mode="drop"))
    ids = jnp.full((n_lists, L), -1, jnp.int32).at[sorted_l, rank].set(
        row_ids[order].astype(jnp.int32), mode="drop")
    counts = jnp.zeros((n_lists,), jnp.int32).at[labels].add(1, mode="drop")
    sizes = jnp.minimum(counts, L)
    n_dropped = jnp.sum(counts - sizes)
    return packed, ids, sizes, n_dropped


def choose_list_chunk(n_lists: int, target: int) -> int:
    """Largest divisor of ``n_lists`` that is ≤ target (chunked scans
    reshape [n_lists, …] to [n_chunks, chunk, …], so the chunk must
    divide n_lists)."""
    c = max(1, min(target, n_lists))
    while n_lists % c:
        c -= 1
    return c
