"""List-centric IVF scan machinery — the TPU-native inversion of the
reference's per-query list scan.

The reference's search kernels (ivf_flat_interleaved_scan-inl.cuh,
ivf_pq_compute_similarity-inl.cuh) are per-query: one CTA walks the
query's probed lists through shared memory. On a TPU that structure is
wrong twice over: per-query work is too small for the MXU, and each
query re-reads its lists from HBM.

The TPU-native structure inverts the loop — **group the query batch by
probed list**, then stream each list block through the MXU exactly once
per batch:

1. probe selection gives ``probes [B, n_probes]`` (queries → lists);
2. :func:`invert_probes` builds the transposed table
   ``qtable [n_lists, qmax]`` (lists → queries) via one sort — the same
   trick the index build uses to pack rows into lists;
3. the scan loops over *list chunks*: for chunk lists, gather their
   (few, small) queries, run one batched ``[qmax, d] × [d, L]``
   contraction per list on the MXU, and take a per-(query,list) top-k;
4. results are gathered back to ``[B, n_probes, k]`` pair order (a
   gather, not a scatter — TPUs gather much faster than they scatter)
   and a final select_k merges each query's n_probes·k candidates.

HBM traffic: each list block is read once per *batch* instead of once
per *probing query* — the amortization that makes IVF beat brute force
on TPU at large batch sizes. Queries overflowing a list's ``qmax`` queue
slots are dropped from that one probe (bounded recall loss; sized by
``qmax_factor`` with generous default headroom).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def invert_probes(probes: jax.Array, n_lists: int, qmax: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """Invert queries→lists probes into per-list query queues.

    Parameters
    ----------
    probes : [B, P] int32 list ids per query.
    n_lists : number of inverted lists.
    qmax : queue capacity per list (static).

    Returns
    -------
    qtable : [n_lists, qmax] int32 — query ids probing each list, -1 pad.
    rank : [B, P] int32 — each (query, probe) pair's slot in its list's
        queue; ``rank >= qmax`` marks a dropped pair.
    """
    B, P = probes.shape
    l_flat = probes.reshape(-1).astype(jnp.int32)
    order = jnp.argsort(l_flat, stable=True)
    sorted_l = l_flat[order]
    starts = jnp.searchsorted(sorted_l, jnp.arange(n_lists, dtype=jnp.int32))
    rank_sorted = (jnp.arange(B * P, dtype=jnp.int32)
                   - starts[sorted_l].astype(jnp.int32))
    # back to pair order (small scatter: B·P elements)
    rank = jnp.zeros((B * P,), jnp.int32).at[order].set(rank_sorted)
    q_of = (order // P).astype(jnp.int32)
    qtable = jnp.full((n_lists, qmax), -1, jnp.int32)
    qtable = qtable.at[sorted_l, rank_sorted].set(q_of, mode="drop")
    return qtable, rank.reshape(B, P)


def gather_pair_results(list_vals: jax.Array, list_ids: jax.Array,
                        probes: jax.Array, rank: jax.Array,
                        invalid_val) -> Tuple[jax.Array, jax.Array]:
    """Collect per-(list, queue-slot) top-k back into (query, probe) order.

    ``list_vals/list_ids [n_lists, qmax, k]`` hold each queue slot's local
    top-k; pair (q, p) owns slot ``(probes[q,p], rank[q,p])``. Dropped
    pairs (rank >= qmax) come back masked to ``invalid_val`` / -1.
    Returns ``[B, P, k]`` values and ids.
    """
    qmax = list_vals.shape[1]
    ok = rank < qmax
    r = jnp.minimum(rank, qmax - 1)
    vals = list_vals[probes, r]
    ids = list_ids[probes, r]
    vals = jnp.where(ok[..., None], vals, invalid_val)
    ids = jnp.where(ok[..., None], ids, -1)
    return vals, ids


def default_qmax(batch: int, n_probes: int, n_lists: int,
                 factor: float = 4.0) -> int:
    """Queue capacity: ``factor ×`` the average queue load, padded to a
    multiple of 8, at least 8. The default 4× headroom makes drops rare
    even on clustered query sets (probe loads are data-dependent)."""
    avg = batch * n_probes / max(n_lists, 1)
    return max(8, int(-(-factor * avg // 8)) * 8)


def choose_list_chunk(n_lists: int, target: int) -> int:
    """Largest divisor of ``n_lists`` that is ≤ target (chunked scans
    reshape [n_lists, …] to [n_chunks, chunk, …], so the chunk must
    divide n_lists)."""
    c = max(1, min(target, n_lists))
    while n_lists % c:
        c -= 1
    return c
