"""IVF-PQ — inverted-file index with product-quantized residuals.

TPU-native re-design of ``raft::neighbors::ivf_pq``
(ivf_pq-inl.cuh:272 build, :478 search; detail/ivf_pq_build.cuh:1511;
detail/ivf_pq_search.cuh; the compute_similarity scan kernel
detail/ivf_pq_compute_similarity-inl.cuh). Design mapping and the one
deliberate algorithmic change:

- coarse quantizer: balanced kmeans (as the reference, ivf_pq_build.cuh:1618);
- random rotation: QR of a Gaussian (ivf_pq_build.cuh:122) giving an
  orthonormal embedding dim → rot_dim = pq_dim·pq_len;
- codebooks: PER_SUBSPACE kmeans over residual sub-vectors — all pq_dim
  subspace kmeans runs execute as ONE vmapped Lloyd (the reference loops
  subspaces, ivf_pq_build.cuh:404-407);
- storage: padded per-list blocks of uint8 codes (the TPU analog of the
  reference's packed interleaved n-bit lists) + ids;
- **search restructure**: the reference builds a LUT per (query, probe)
  over *residual* distances, then scans packed codes in shared memory.
  A per-(query,probe) LUT is hostile to XLA (dynamic, smem-sized). We
  decompose the asymmetric distance instead:
      ‖q − (c + d)‖² = ‖q‖² − 2⟨q,c⟩ − 2⟨q,d⟩ + ‖c + d‖²
  where d = decoded PQ residual. ‖c+d‖² is a per-candidate scalar
  **precomputed at build**; ⟨q,c⟩ falls out of coarse probing; and
  ⟨q,d⟩ = Σ_s QLUT[s, code_s] needs only a *query-only* LUT
  [pq_dim, 2^bits] built by one batched MXU contraction. The list scan
  is then a pure gather+sum — the Pallas kernel target — with identical
  math to the reference's fused scan.

Supported metrics: sqeuclidean / euclidean / inner_product / cosine
(cosine = inner product over L2-normalized vectors, as the reference).
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced, span
from raft_tpu.core import ids as _ids
from raft_tpu.core import serialize as ser
from raft_tpu.obs import index_stats as _istats
from raft_tpu.obs import spans as _obs_spans
from raft_tpu.robust import degrade as _degrade
from raft_tpu.robust import faults as _faults
from raft_tpu.robust import retry as _retry
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.utils.precision import get_precision

# Code arrays above this size scan via dynamic_slice (see the
# billion-scale guard in _search_grouped).
_SLICE_SCAN_BYTES = 2 << 30

_SERIAL_VERSION = 2


@dataclasses.dataclass
class IndexParams:
    """reference: ``ivf_pq::index_params`` (ivf_pq_types.hpp:48-148)."""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    pq_dim: int = 0           # 0 → dim/2 rounded to a multiple of 8 (reference default heuristic)
    pq_bits: int = 8          # 4..8 (codebook size 2^bits)
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    codebook_kind: str = "per_subspace"  # | "per_cluster"
    add_data_on_build: bool = True
    list_size_cap_factor: float = 4.0
    # TPU-specific: cap padded list capacity at list_size_cap_factor ×
    # mean and SPILL overflow rows to their second-nearest list instead
    # of dropping them (ivf_common.spill_assignments). The padded
    # [n_lists, L, ...] layout pays the fattest list's padding on every
    # scan DMA — and at 10⁸ rows overflows HBM outright — so spill with
    # cap_factor ~1.5 trades a marginal assignment-quality loss for a
    # 2-3× smaller scan working set.
    spill: bool = False
    seed: int = 0
    # TPU-specific: keep a bf16 reconstruction (c + decoded residual) of
    # every list alongside the codes. Trades HBM (2 bytes/dim) for scan
    # speed — the grouped scan then skips the per-chunk one-hot decode
    # (expensive at pq_bits=8: the MXU decode runs at K× the lookup
    # FLOPs). "auto" enables it when the cache stays under ~1 GB.
    cache_reconstruction: str = "auto"  # "auto" | "always" | "never"


@dataclasses.dataclass
class SearchParams:
    """reference: ``ivf_pq::search_params``.

    ``scan_mode``: "grouped" is the list-centric batch scan (see
    neighbors/ivf_common.py), "per_query" the gather path for small
    batches, "auto" picks by batch size.

    ``lut_dtype``: dtype the query LUT is quantized to before the scan
    contraction — the reference's ``search_params::lut_dtype`` fp8 option
    (detail/ivf_pq_fp_8bit.cuh) trading LUT precision for on-chip
    footprint. One of "auto" | "float32" | "bfloat16" | "float8_e4m3".
    The Pallas LUT-scan tier applies the same knob to its codebook
    operand (see ops.pallas_kernels.ivfpq_lut_scan_topk). The default
    "auto" resolves per dispatch (:func:`resolve_lut_dtype`): fp8 for
    oversampled scans on TPU — the measured-default trade, recall
    deltas recorded per dataset by the bench lut_dtype legs and held by
    the benchdiff gate — declining to bf16 when the candidate slack is
    too thin to absorb fp8's ranking noise, and exact f32 everywhere
    else.

    ``scan_select`` picks the grouped path's selection engine:
    "exact" (reference semantics), "approx" (TPU hardware top-k,
    recall-targeted; see ivf_flat), or "pallas" — the fused Pallas
    LUT-scan kernel over packed codes (no recon cache needed, candidate
    tables never hit HBM; docs/api_reference.md has the decision
    table). "approx" auto-upgrades to the pallas tier on TPU for
    oversampled shapes (n_probes ≥ 64 or k ≥ 400) when no recon cache
    exists — the configs where the XLA scan's HBM transients are
    hostile. The tier needs n_probes·256 ≥ k; a ``filter_bitset`` rides
    along as a streamed per-candidate mask (packed keep bits beside the
    codes, sentinel-masked before bin selection — filtered searches no
    longer leave the fast path); ineligible explicit requests warn once
    (with the concrete reason) and run the approx tier instead."""

    n_probes: int = 20
    query_tile: int = 64
    scan_mode: str = "auto"  # "auto" | "grouped" | "per_query"
    list_chunk: int = 64
    lut_dtype: str = "auto"  # | "float32" | "bfloat16" | "float8_e4m3"
    # grouped-path per-segment selection: "exact" (reference semantics),
    # "approx" (TPU hardware top-k, recall-targeted; see ivf_flat), or
    # "pallas" (fused LUT-scan kernel over packed codes)
    scan_select: str = "exact"  # | "approx" | "pallas"
    scan_recall: float = 0.95
    # the reference's refinement_rate pattern (refine-inl.cuh) folded
    # into search(): "f32_regen" scans k·refine_ratio candidates, then
    # re-ranks them against exact f32 rows through neighbors.refine's
    # dispatch tier (the fused Pallas gather-refine kernel on TPU
    # oversampled shapes, XLA einsum otherwise). Needs search()'s
    # ``dataset`` argument: a device array (fused-eligible), a host
    # array/memmap (host-gather tier), or a device-chunk provider with
    # ``_block``/``chunk_rows`` (provider-regen tier).
    refine: str = "none"  # | "f32_regen"
    refine_ratio: float = 2.0
    # host-resident re-rank bases only (ISSUE 17): "auto" routes
    # through the tiered candidate-row prefetch pipeline
    # (neighbors.tiered — the host fetch overlapped under the scan)
    # when eligible, falling back to the serialized host gather;
    # "tiered" forces the pipeline (mem guard still applies);
    # "serial" pins refine_gathered — the degrade ladder's last-resort
    # host_gather rung and the bench's comparison leg. Device-resident
    # bases ignore this knob (the fused/XLA tiers need no transfer).
    refine_transfer: str = "auto"  # | "tiered" | "serial"


_LUT_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
               "float8_e4m3": jnp.float8_e4m3fn}

#: Documented recall floor for the fp8-QLUT dispatch default: the
#: recorded per-dataset recall@10 delta of the fp8 legs (bench conf
#: ``lut_dtype`` sweeps, held by the benchdiff gate) must stay within
#: this of the f32 leg at fixed search params. A dataset measured past
#: the floor runs with ``lut_dtype="bfloat16"`` (or f32) explicitly —
#: dispatch cannot see recall at runtime, so the floor's static proxy
#: is candidate slack (:data:`FP8_LUT_MIN_SLACK`): with ≥4× more
#: scanned candidates than requested neighbors, fp8's LUT rounding
#: reorders within the oversample margin, not across the cut.
FP8_LUT_RECALL_FLOOR = 0.01
#: Minimum candidate slack (n_probes·LUT_SCAN_BINS / k) before "auto"
#: picks fp8 over bf16 for an oversampled scan.
FP8_LUT_MIN_SLACK = 4


def resolve_lut_dtype(lut_dtype: str, n_probes: int, k: int,
                      selectivity: float = 1.0) -> str:
    """Resolve ``SearchParams.lut_dtype="auto"`` for one dispatch.

    fp8 QLUTs are the measured default for OVERSAMPLED scans (the
    LUT-tier auto-upgrade shape: n_probes ≥ 64 or k ≥ 400) on TPU —
    the reference's fp8 trade (ivf_pq_fp_8bit.cuh) promoted from
    opt-in to default where the recall cost is bounded (see
    :data:`FP8_LUT_RECALL_FLOOR`). When the candidate slack is under
    :data:`FP8_LUT_MIN_SLACK`, dispatch declines to bf16 instead; every
    other shape keeps exact f32.

    ``selectivity`` (set-bit fraction of a ``filter_bitset``, 1.0
    unfiltered — :func:`_filter_selectivity`) discounts the slack: a
    filtered scan's bins hold only SURVIVING candidates, so the
    effective oversample margin fp8's ranking noise must stay inside is
    ``selectivity · n_probes · LUT_SCAN_BINS`` — at 1% selectivity a
    nominally 25× slack is really 0.25× and fp8 reordering would cross
    the cut, so dispatch declines to bf16.

    ``RAFT_TPU_FP8_LUT`` = auto | on | off
    (tri-state): "on" applies the policy off-TPU too (interpret-mode
    tests), "off" pins auto to f32. Explicit dtypes pass through
    untouched; each auto resolution lands in
    ``ivf_pq.lut.dispatch{dtype=...}``."""
    if lut_dtype != "auto":
        return lut_dtype
    from raft_tpu.ops import pallas_kernels as _pk

    force = _obs_spans.env_tristate("RAFT_TPU_FP8_LUT")
    oversampled = n_probes >= 64 or k >= 400
    chosen = "float32"
    if (force != "off" and oversampled
            and (force == "on" or _pk._on_tpu())):
        surviving = selectivity * n_probes * _pk.LUT_SCAN_BINS
        slack_ok = surviving >= FP8_LUT_MIN_SLACK * k
        chosen = "float8_e4m3" if slack_ok else "bfloat16"
    if _obs_spans.enabled():
        _obs_spans.registry().inc("ivf_pq.lut.dispatch",
                                  labels={"dtype": chosen})
    return chosen


def _filter_selectivity(filter_bits) -> float:
    """Eager set-bit-fraction estimate of a filter bitset feeding the
    fp8-LUT slack discount (one tiny popcount reduction + host sync per
    filtered dispatch with ``lut_dtype="auto"``). Returns 1.0 for no
    filter. Under an abstract trace (a jitted ``search`` call, the
    eval_shape capacity prover) the popcount cannot concretize — the
    filter IS present but its density is unknowable, so return 0.0:
    the slack check then declines fp8 to bf16, the conservative side
    of the precision policy (a 1.0 fallback would silently disable the
    discount exactly when a selective filter needs it)."""
    if filter_bits is None:
        return 1.0
    from raft_tpu.core import bitset as _bitset

    try:
        return float(_bitset.density(filter_bits))
    except (jax.errors.ConcretizationTypeError, TypeError):
        return 0.0


def _quantize_lut(lut: jax.Array, lut_dtype: str) -> jax.Array:
    """Round the query LUT to the requested storage dtype, returning it in
    a compute-friendly dtype (fp8 simulates the reference's fp8 LUT: the
    values are quantized, the contraction runs in bf16)."""
    expects(lut_dtype in _LUT_DTYPES, "unknown lut_dtype %s", lut_dtype)
    dt = _LUT_DTYPES[lut_dtype]
    if dt == jnp.float32:
        return lut
    q = lut.astype(dt)
    return q.astype(jnp.bfloat16) if dt == jnp.float8_e4m3fn else q


class IvfPqIndex(flax.struct.PyTreeNode):
    """IVF-PQ index (reference: ``ivf_pq::index``, ivf_pq_types.hpp).

    ``codebooks`` is [pq_dim, K, pq_len] for per_subspace codebooks and
    [n_lists, K, pq_len] for per_cluster (ivf_pq_types.hpp:43,83).
    ``packed_codes`` stores n-bit codes bit-packed into bytes — pq_bits=4
    costs half the bytes of pq_bits=8, matching the reference's packed
    list layout (ivf_pq_types.hpp:68)."""

    centers: jax.Array        # [n_lists, dim] f32 (original space)
    centers_rot: jax.Array    # [n_lists, rot_dim] f32
    rotation: jax.Array       # [rot_dim, dim] f32, orthonormal rows' columns
    codebooks: jax.Array      # [S|n_lists, 2^bits, pq_len] f32
    packed_codes: jax.Array   # [n_lists, L, ceil(pq_dim·pq_bits/8)] u8
    packed_ids: jax.Array     # [n_lists, L] i32, -1 pad
    packed_norms: jax.Array   # [n_lists, L] f32: ‖c + decoded‖²
    list_sizes: jax.Array     # [n_lists] i32
    packed_recon: Optional[jax.Array] = None  # [n_lists, L, rot_dim] bf16 cache
    metric: str = flax.struct.field(pytree_node=False, default="sqeuclidean")
    codebook_kind: str = flax.struct.field(pytree_node=False,
                                           default="per_subspace")
    pq_bits: int = flax.struct.field(pytree_node=False, default=8)
    # 0 → derive from packed_codes (legacy byte-per-subspace layout)
    pq_dim_static: int = flax.struct.field(pytree_node=False, default=0)
    # folded code storage: [n_lists, L·nb/128, 128] instead of
    # [n_lists, L, nb]. A u8 array's trailing dim pads to 128 lanes in
    # TPU tile layouts, so nb=64-byte code rows would occupy 2× their
    # bytes in HBM — at 100M rows the difference between a 9.7 GB and a
    # 19 GB resident index. Row-major bytes are identical either way;
    # codes_chunk() unfolds per scanned chunk.
    codes_folded: bool = flax.struct.field(pytree_node=False, default=False)

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.pq_dim_static or self.packed_codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.codebooks.shape[2]

    @property
    def pq_book_size(self) -> int:
        return self.codebooks.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.packed_ids.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))

    def codes_chunk(self, sl) -> jax.Array:
        """[C, L, nb] code rows for the list chunk ``sl`` — unfolds the
        lane-folded storage (see ``codes_folded``)."""
        c = self.packed_codes[sl]
        if self.codes_folded:
            return c.reshape(c.shape[0], self.packed_ids.shape[1], -1)
        return c

    def unpack_codes(self, packed: jax.Array) -> jax.Array:
        """[..., nbytes] u8 → [..., pq_dim] u8 code values."""
        return unpack_bits(packed, self.pq_dim, self.pq_bits)


# ---------------------------------------------------------------------------
# n-bit code packing (reference: packed n-bit lists, ivf_pq_types.hpp:68)
# ---------------------------------------------------------------------------

def packed_nbytes(pq_dim: int, pq_bits: int) -> int:
    return (pq_dim * pq_bits + 7) // 8


def pack_bits_np(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """Host bit-pack [n, S] u8 code values (< 2^pq_bits) → [n, nbytes] u8."""
    if pq_bits == 8:
        return np.ascontiguousarray(codes, dtype=np.uint8)
    n, S = codes.shape
    nbytes = packed_nbytes(S, pq_bits)
    out = np.zeros((n, nbytes), np.uint8)
    for s in range(S):
        byte_idx, off = divmod(s * pq_bits, 8)
        v = codes[:, s].astype(np.uint16) << off
        out[:, byte_idx] |= (v & 0xFF).astype(np.uint8)
        if byte_idx + 1 < nbytes:
            out[:, byte_idx + 1] |= (v >> 8).astype(np.uint8)
    return out


def pack_bits(codes: jax.Array, pq_bits: int) -> jax.Array:
    """Device bit-pack [..., S] u8 → [..., nbytes] u8 (jit-safe; the SPMD
    build packs on device where a host round-trip is impossible)."""
    if pq_bits == 8:
        return codes.astype(jnp.uint8)
    S = codes.shape[-1]
    nbytes = packed_nbytes(S, pq_bits)
    acc = jnp.zeros(codes.shape[:-1] + (nbytes,), jnp.uint16)
    for s in range(S):  # static unroll: S is a trace-time constant
        byte_idx, off = divmod(s * pq_bits, 8)
        v = codes[..., s].astype(jnp.uint16) << off
        acc = acc.at[..., byte_idx].set(acc[..., byte_idx] | (v & 0xFF))
        if byte_idx + 1 < nbytes:
            acc = acc.at[..., byte_idx + 1].set(
                acc[..., byte_idx + 1] | (v >> 8))
    return acc.astype(jnp.uint8)


def unpack_bits(packed: jax.Array, pq_dim: int, pq_bits: int) -> jax.Array:
    """Device unpack [..., nbytes] u8 → [..., pq_dim] u8 code values.
    Pure shift/mask VPU ops — fuses into whatever consumes the codes."""
    if pq_bits == 8:
        return packed
    nbytes = packed.shape[-1]
    s = np.arange(pq_dim)
    byte_idx = (s * pq_bits) // 8
    # full-rank (1, ..., pq_dim) operands: the sanitize lane runs with
    # jax_numpy_rank_promotion="raise", so 1-D-vs-N-D broadcasts are
    # spelled out instead of implied
    lead = (1,) * (packed.ndim - 1)
    bit_off = jnp.asarray(((s * pq_bits) % 8).reshape(lead + (-1,)),
                          jnp.uint16)
    p16 = packed.astype(jnp.uint16)
    lo = jnp.take(p16, jnp.asarray(byte_idx), axis=-1)
    hi_idx = np.minimum(byte_idx + 1, nbytes - 1)
    hi = jnp.take(p16, jnp.asarray(hi_idx), axis=-1)
    hi = jnp.where(
        jnp.asarray((byte_idx + 1 < nbytes).reshape(lead + (-1,))), hi, 0)
    val = ((lo | (hi << 8)) >> bit_off) & ((1 << pq_bits) - 1)
    return val.astype(jnp.uint8)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

def _default_pq_dim(dim: int) -> int:
    """Reference heuristic (ivf_pq_types.hpp pq_dim=0 doc): ~dim/2, rounded
    to a multiple of 8, at least 8."""
    return max(8, (dim // 2 + 7) // 8 * 8 if dim >= 16 else dim)


def make_rotation_matrix(key: jax.Array, rot_dim: int, dim: int) -> jax.Array:
    """Random orthonormal embedding R [rot_dim, dim], RᵀR = I_dim
    (reference: make_rotation_matrix, ivf_pq_build.cuh:122 — QR of a
    Gaussian). Rotation preserves inner products and L2 distances."""
    g = jax.random.normal(key, (rot_dim, dim), jnp.float32)
    q, _ = jnp.linalg.qr(g, mode="reduced")  # [rot_dim, dim] for rot_dim>=dim
    return q


@partial(jax.jit, static_argnames=("k", "n_iters"))
def _vmapped_lloyd(data, k: int, n_iters: int, key):
    """Independent kmeans per subspace, one vmapped program
    (reference loops kmeans_balanced per subspace, ivf_pq_build.cuh:404)."""
    S, n, d = data.shape

    def one(sub_data, subkey):
        idx = jax.random.choice(subkey, n, (k,), replace=False)
        c0 = sub_data[idx]

        def body(i, c):
            d2 = (jnp.sum(sub_data**2, 1)[:, None] + jnp.sum(c**2, 1)[None, :]
                  - 2.0 * sub_data @ c.T)
            labels = jnp.argmin(d2, axis=1)
            sums = jax.ops.segment_sum(sub_data, labels, num_segments=k)
            counts = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), labels,
                                         num_segments=k)
            return jnp.where(counts[:, None] > 0,
                             sums / jnp.maximum(counts[:, None], 1e-12), c)

        return lax.fori_loop(0, n_iters, body, c0)

    keys = jax.random.split(key, S)
    return jax.vmap(one)(data, keys)


@partial(jax.jit, static_argnames=("k", "n_iters"))
def _vmapped_lloyd_masked(data, mask, k: int, n_iters: int, key):
    """Independent kmeans per cluster over PADDED row blocks — the
    per_cluster codebook trainer (reference: train_per_cluster,
    ivf_pq_build.cuh:448-492). ``mask`` zero-weights pad rows; clusters
    with fewer than k valid rows keep their init centroids for the
    surplus entries."""
    C, cap, d = data.shape

    def one(sub, m, subkey):
        w = m.astype(jnp.float32)
        p = w / jnp.maximum(jnp.sum(w), 1.0)
        idx = jax.random.choice(subkey, cap, (k,), replace=False, p=p)
        c0 = sub[idx]

        def body(i, c):
            d2 = (jnp.sum(sub**2, 1)[:, None] + jnp.sum(c**2, 1)[None, :]
                  - 2.0 * sub @ c.T)
            labels = jnp.argmin(d2, axis=1)
            sums = jax.ops.segment_sum(sub * w[:, None], labels,
                                       num_segments=k)
            counts = jax.ops.segment_sum(w, labels, num_segments=k)
            return jnp.where(counts[:, None] > 0,
                             sums / jnp.maximum(counts[:, None], 1e-12), c)

        return lax.fori_loop(0, n_iters, body, c0)

    keys = jax.random.split(key, C)
    return jax.vmap(one)(data, mask, keys)


def _train_per_cluster(tr_res: jax.Array, tr_labels: jax.Array,
                       n_lists: int, pq_dim: int, pq_len: int, K: int,
                       n_iters: int, key) -> jax.Array:
    """Per-cluster codebooks [n_lists, K, pq_len]: each cluster trains one
    codebook over its residual sub-vectors pooled across ALL subspaces
    (ivf_pq_types.hpp:83 PER_CLUSTER). Rows are grouped per cluster with
    the same sort+scatter the list packers use; clusters hotter than the
    per-cluster cap are subsampled by truncation (the trainset is already
    a random subsample, so truncation is unbiased)."""
    from raft_tpu.neighbors import ivf_common as ic

    n_train = tr_res.shape[0]
    flat_sub = tr_res.reshape(n_train * pq_dim, pq_len)
    flat_lbl = jnp.repeat(tr_labels.astype(jnp.int32), pq_dim)
    avg = max(1, (n_train * pq_dim) // max(n_lists, 1))
    # clamp: the padded block is [n_lists, cap, pq_len] whose tiny minor
    # dim lane-pads to 128 — an unbounded cap at large n_lists would
    # blow HBM for no statistical gain
    cap = min(max(2 * K, -(-4 * avg // 8) * 8), max(2 * K, 8192))
    (packed,), _, sizes, _, _ = ic.pack_lists(
        (flat_sub,), flat_lbl,
        jnp.arange(n_train * pq_dim, dtype=jnp.int32),
        n_lists, cap, (jnp.float32(0),))
    mask = jnp.arange(cap)[None, :] < sizes[:, None]
    return _vmapped_lloyd_masked(packed, mask, K, n_iters, key)


def _encode_rows(rot_rows: jax.Array, centers_rot: jax.Array,
                 labels: jax.Array, codebooks: jax.Array,
                 block: int = 4096) -> jax.Array:
    """PQ-encode rotated rows against their cluster's residual
    (reference: encode+pack, ivf_pq_build.cuh:1411-1432).
    Returns codes [n, pq_dim] uint8."""
    S, K, P = codebooks.shape
    n = rot_rows.shape[0]

    def encode_block(args):
        rows, lbls = args
        res = rows - centers_rot[lbls]                    # [b, rot_dim]
        sub = res.reshape(res.shape[0], S, P)             # [b, S, P]
        # ‖sub − cb‖² argmin over K: [b, S, K]
        d2 = (jnp.sum(sub**2, -1)[..., None]
              + jnp.sum(codebooks**2, -1)[None]
              - 2.0 * jnp.einsum("bsp,skp->bsk", sub, codebooks,
                                 precision=get_precision()))
        return jnp.argmin(d2, axis=-1).astype(jnp.uint8)  # [b, S]

    if n <= block:
        return encode_block((rot_rows, labels))
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    rows_p = jnp.pad(rot_rows, ((0, pad), (0, 0)))
    lbls_p = jnp.pad(labels, (0, pad))
    out = lax.map(encode_block, (rows_p.reshape(n_blocks, block, -1),
                                 lbls_p.reshape(n_blocks, block)))
    return out.reshape(n_blocks * block, S)[:n]


def _encode_rows_cluster(rot_rows: jax.Array, centers_rot: jax.Array,
                         labels: jax.Array, codebooks: jax.Array,
                         block: int = 4096) -> jax.Array:
    """Per-cluster encode: row i's subspaces all quantize against its
    cluster's codebook ``codebooks[labels[i]]`` (reference: PER_CLUSTER
    encode, ivf_pq_build.cuh). Returns codes [n, pq_dim] uint8."""
    C, K, P = codebooks.shape
    n = rot_rows.shape[0]
    S = rot_rows.shape[1] // P

    def encode_block(args):
        rows, lbls = args
        res = rows - centers_rot[lbls]
        sub = res.reshape(res.shape[0], S, P)
        cb = codebooks[lbls]                              # [b, K, P]
        d2 = (jnp.sum(sub**2, -1)[..., None]
              + jnp.sum(cb**2, -1)[:, None, :]
              - 2.0 * jnp.einsum("bsp,bkp->bsk", sub, cb,
                                 precision=get_precision()))
        return jnp.argmin(d2, axis=-1).astype(jnp.uint8)

    if n <= block:
        return encode_block((rot_rows, labels))
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    rows_p = jnp.pad(rot_rows, ((0, pad), (0, 0)))
    lbls_p = jnp.pad(labels, (0, pad))
    out = lax.map(encode_block, (rows_p.reshape(n_blocks, block, -1),
                                 lbls_p.reshape(n_blocks, block)))
    return out.reshape(n_blocks * block, S)[:n]


def _decode_dtype():
    """One-hot decode compute dtype: bf16 feeds the MXU on TPU; CPU XLA
    doesn't fuse the one-hot, so keep exact f32 there."""
    return jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16


def _decode_codes_cluster(codes: jax.Array, cb_rows: jax.Array) -> jax.Array:
    """Per-cluster decode: codes [..., S] u8 with a MATCHING per-row
    codebook ``cb_rows [..., K, P]`` → decoded residuals [..., S·P] f32."""
    K, P = cb_rows.shape[-2:]
    S = codes.shape[-1]
    dt = _decode_dtype()
    oh = jax.nn.one_hot(codes.astype(jnp.int32), K, dtype=dt)
    dec = jnp.einsum("...sk,...kp->...sp", oh, cb_rows.astype(dt),
                     preferred_element_type=jnp.float32)
    return dec.reshape(*codes.shape[:-1], S * P)


def _decode_lists_cluster(codes: jax.Array, cb: jax.Array) -> jax.Array:
    """Per-cluster decode of a chunk of packed LISTS: codes [C, L, S] u8
    with one codebook per list ``cb [C, K, P]`` → [C, L, S·P] f32 (the
    recon cache and the grouped scan both decode in this shape)."""
    C, L, S = codes.shape
    dt = _decode_dtype()
    oh = jax.nn.one_hot(codes.astype(jnp.int32), cb.shape[1], dtype=dt)
    dec = jnp.einsum("clsk,ckp->clsp", oh, cb.astype(dt),
                     preferred_element_type=jnp.float32)
    return dec.reshape(C, L, -1)


def _decode_codes(codes: jax.Array, codebooks: jax.Array) -> jax.Array:
    """codes [..., S] u8 → decoded residuals [..., S*P] f32.

    On TPU the lookup is a one-hot MXU contraction: arbitrary-axis
    gathers do not lower on the TPU backend (and would be VPU-serial
    anyway), while the iota-compare one-hot feeds the MXU directly.
    CPU keeps the natural gather."""
    S, K, P = codebooks.shape
    if jax.default_backend() == "cpu":
        gathered = codebooks[jnp.arange(S), codes.astype(jnp.int32)]
        return gathered.reshape(*codes.shape[:-1], S * P)
    oh = jax.nn.one_hot(codes.astype(jnp.int32), K, dtype=jnp.bfloat16)
    dec = jnp.einsum("...sk,skp->...sp", oh, codebooks.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)
    return dec.reshape(*codes.shape[:-1], S * P)


def _stable_slots(labels: np.ndarray, n_lists: int,
                  base: Optional[np.ndarray] = None):
    """Each row's (list, slot) address from ONE stable sort — the shared
    core of every host packer (reference: encode+pack,
    ivf_pq_build.cuh:1411-1432). ``base`` offsets slots by current list
    fill (extend / chunked append). Returns (order, sorted_l, slot):
    row ``order[i]`` goes to ``(sorted_l[i], slot[i])``."""
    n = len(labels)
    order = np.argsort(labels, kind="stable")
    sorted_l = labels[order]
    # tolerate the spill drop marker (label == n_lists): those rows
    # rank within their own group and every caller's slot/keep mask
    # rejects them via ``sorted_l < n_lists``
    starts = np.searchsorted(sorted_l, np.arange(n_lists + 1))
    rank = np.arange(n) - starts[sorted_l]
    slot = rank if base is None else base[np.clip(sorted_l, 0,
                                                  n_lists - 1)] + rank
    return order, sorted_l, slot


def _pack_codes(codes: np.ndarray, labels: np.ndarray, norms: np.ndarray,
                n_lists: int, max_list_size: int, row_ids: np.ndarray):
    """Vectorized list packing: one argsort + fancy-indexed fill
    (reference: encode+pack, ivf_pq_build.cuh:1411-1432)."""
    n, S = codes.shape
    order, sorted_labels, rank = _stable_slots(labels, n_lists)
    keep = rank < max_list_size
    dropped = int(n - keep.sum())
    packed = np.zeros((n_lists, max_list_size, S), np.uint8)
    # id-table width follows the incoming global ids (core.ids policy:
    # int32 until the row count demands int64, never narrowed here)
    ids = np.full((n_lists, max_list_size), -1,
                  _ids.np_id_dtype_like(row_ids))
    pnorm = np.zeros((n_lists, max_list_size), np.float32)
    rows = order[keep]
    ls, rk = sorted_labels[keep], rank[keep]
    packed[ls, rk] = codes[rows]
    ids[ls, rk] = row_ids[rows]
    pnorm[ls, rk] = norms[rows]
    sizes = np.minimum(np.bincount(labels, minlength=n_lists),
                       max_list_size).astype(np.int32)
    if dropped:
        from raft_tpu.core import logging as _log
        _log.warn("ivf_pq: dropped %d overflow vectors", dropped)
    return packed, ids, pnorm, sizes


def _train_quantizers(trainset: jax.Array, params: IndexParams, dim: int,
                      pq_dim: int, pq_len: int, K: int, key,
                      km: KMeansBalancedParams,
                      max_codebook_rows: int = 1 << 16,
                      centers: Optional[jax.Array] = None):
    """Coarse centers + rotation + codebooks from a (sub)trainset — the
    quantizer-training block shared by build() and build_chunked()
    (reference: detail/ivf_pq_build.cuh:1511-1621 + :385-492).

    Codebook training sees at most ``max_codebook_rows`` rows (a strided
    subset of the already-random trainset; the coarse kmeans keeps the
    full trainset). Beyond the statistics (≥256 samples/centroid at
    K=256), this bounds a TPU-specific blowup: the per-subspace sample
    [pq_dim, n, pq_len] lane-pads its tiny minor dim to 128, so an
    uncapped 2M-row trainset at pq_len=2 would demand 64× its logical
    size in HBM (measured: a 51 GB allocation on a 16 GB chip).

    ``centers`` (optional) skips the coarse fit and trains the
    rotation/codebooks against the GIVEN coarse centers — the
    distributed build's ``coarse="distributed"`` mode fits its centers
    with the psum-Lloyd MNMG trainer first, and the codebooks must see
    residuals to the centers the index will actually encode against."""
    n_train = trainset.shape[0]
    rot_dim = pq_dim * pq_len
    if centers is None:
        centers = kmeans_balanced.fit(trainset, params.n_lists, km)
    rotation = make_rotation_matrix(jax.random.fold_in(key, 1), rot_dim, dim)
    centers_rot = centers @ rotation.T
    stride = max(1, -(-n_train // max_codebook_rows))
    tr_cb = trainset[::stride]
    n_cb = tr_cb.shape[0]
    cb_labels = kmeans_balanced.predict(centers, tr_cb, km)
    tr_res = tr_cb @ rotation.T - centers_rot[cb_labels]
    if params.codebook_kind == "per_subspace":
        sub = jnp.transpose(tr_res.reshape(n_cb, pq_dim, pq_len), (1, 0, 2))
        codebooks = _vmapped_lloyd(sub, K, params.kmeans_n_iters,
                                   jax.random.fold_in(key, 2))
    else:
        codebooks = _train_per_cluster(
            tr_res, cb_labels, params.n_lists, pq_dim, pq_len, K,
            params.kmeans_n_iters, jax.random.fold_in(key, 2))
    return centers, rotation, centers_rot, codebooks


def _encode_with_norms(x_rot: jax.Array, centers_rot: jax.Array,
                       labels: jax.Array, codebooks: jax.Array,
                       codebook_kind: str, block: int = 4096):
    """(codes [n, S] u8, ‖c + decoded‖² [n]) for either codebook kind —
    the encode block shared by build/build_chunked/extend. Both the
    encode and the norms decode are blocked with ``lax.map``: an
    unblocked decode's one-hot is K× the code volume (measured OOM at
    n=1M, pq_dim=64, K=256 on a 16 GB chip)."""
    per_subspace = codebook_kind == "per_subspace"
    if per_subspace:
        codes = _encode_rows(x_rot, centers_rot, labels, codebooks)
    else:
        codes = _encode_rows_cluster(x_rot, centers_rot, labels, codebooks)

    def norms_block(args):
        cds, lbls = args
        if per_subspace:
            dec = _decode_codes(cds, codebooks)
        else:
            dec = _decode_codes_cluster(cds, codebooks[lbls])
        rec = centers_rot[lbls] + dec
        return jnp.sum(rec * rec, axis=1)

    n = codes.shape[0]
    if n <= block:
        return codes, norms_block((codes, labels))
    n_blocks = -(-n // block)
    pad = n_blocks * block - n
    codes_p = jnp.pad(codes, ((0, pad), (0, 0)))
    lbls_p = jnp.pad(labels, (0, pad))
    norms = lax.map(norms_block,
                    (codes_p.reshape(n_blocks, block, -1),
                     lbls_p.reshape(n_blocks, block)))
    return codes, norms.reshape(-1)[:n]


@traced("raft_tpu.ivf_pq.build")
def build(dataset: jax.Array, params: Optional[IndexParams] = None) -> IvfPqIndex:  # graftlint: disable-fn=GL01 (host-side histogram/pack by design)
    """Build the index (reference: ivf_pq::build, detail/ivf_pq_build.cuh:1511)."""
    if params is None:
        params = IndexParams()
    mt = resolve_metric(params.metric)
    expects(params.codebook_kind in ("per_subspace", "per_cluster"),
            "codebook_kind must be per_subspace or per_cluster")
    expects(4 <= params.pq_bits <= 8, "pq_bits must be in [4, 8]")

    x = jnp.asarray(dataset, jnp.float32)
    n, dim = x.shape
    spherical = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    if mt == DistanceType.CosineExpanded:
        x = x / jnp.sqrt(jnp.maximum(jnp.sum(x * x, -1, keepdims=True), 1e-12))

    pq_dim = params.pq_dim or _default_pq_dim(dim)
    pq_len = -(-dim // pq_dim)
    rot_dim = pq_dim * pq_len
    K = 1 << params.pq_bits
    key = jax.random.PRNGKey(params.seed)

    # 1. coarse centers (balanced kmeans on a trainset subsample)
    n_train = min(n, max(params.n_lists * 4,
                         int(n * params.kmeans_trainset_fraction)))
    if n_train < n:
        rng = np.random.default_rng(params.seed)
        tr = jnp.asarray(np.sort(rng.choice(n, n_train, replace=False)))
        trainset = x[tr]
    else:
        trainset = x
    km = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                              metric="cosine" if spherical else "l2",
                              seed=params.seed)
    # 2.-3. coarse centers + rotation + codebooks (shared trainer)
    with span("train") as _sp:
        centers, rotation, centers_rot, codebooks = _train_quantizers(
            trainset, params, dim, pq_dim, pq_len, K, key, km)
        _sp.attach(centers_rot, codebooks)

    avg = max(1, n // params.n_lists)
    nbytes = packed_nbytes(pq_dim, params.pq_bits)

    if not params.add_data_on_build:
        max_list_size = max(8, int(avg * params.list_size_cap_factor))
        return IvfPqIndex(
            centers=centers, centers_rot=centers_rot, rotation=rotation,
            codebooks=codebooks,
            packed_codes=jnp.zeros((params.n_lists, max_list_size, nbytes), jnp.uint8),
            packed_ids=jnp.full((params.n_lists, max_list_size), -1, jnp.int32),
            packed_norms=jnp.zeros((params.n_lists, max_list_size), jnp.float32),
            list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
            metric=mt.value, codebook_kind=params.codebook_kind,
            pq_bits=params.pq_bits, pq_dim_static=pq_dim)

    # 4. encode + bit-pack + pack all rows into lists — ON DEVICE (same
    # pack the distributed build uses); only the [n_lists] histogram
    # round-trips the host to size the static padded capacity
    from raft_tpu.neighbors.ivf_flat import _fit_list_size, _lane_round
    from raft_tpu.neighbors import ivf_common as ic

    with span("assign") as _sp:
        if params.spill:
            # cap capacity + cascade overflow to next-nearest lists (see
            # IndexParams.spill); encode AFTER spilling so residuals use
            # the assigned list's center
            lk = kmeans_balanced.predict_topk(centers, x, ic.SPILL_DEPTH, km)
            max_list_size = _lane_round(
                int(avg * params.list_size_cap_factor))
            labels = ic.spill_assignments(lk[:, 0], lk[:, 1],
                                          params.n_lists, max_list_size,
                                          *[lk[:, c] for c in
                                            range(2, lk.shape[1])])
            n_marker = int(jnp.sum(labels >= params.n_lists))
            if n_marker:
                # pack_lists' drop counter excludes out-of-range labels
                from raft_tpu.core import logging as _log
                _log.warn("ivf_pq: %d rows overflowed every spill choice at "
                          "cap %d (raise list_size_cap_factor)",
                          n_marker, max_list_size)
        else:
            labels = kmeans_balanced.predict(centers, x, km)
            # histogram on host: the [n] labels transfer is small, and a
            # device scatter-add histogram serializes on TPU
            counts = np.bincount(np.asarray(labels),
                                 minlength=params.n_lists)
            max_list_size = _fit_list_size(counts, avg,
                                           params.list_size_cap_factor)
        _sp.attach(labels)
    with span("encode") as _sp:
        codes, norms = _encode_with_norms(
            x @ rotation.T, centers_rot,
            jnp.clip(labels, 0, params.n_lists - 1), codebooks,
            params.codebook_kind)
        codes_p = pack_bits(codes, params.pq_bits)
        _sp.attach(codes_p, norms)
    with span("pack") as _sp:
        (packed, pnorm), ids, sizes, dropped, _ = ic.pack_lists_jit(
            [codes_p, norms], labels, _ids.make_ids(n),
            n_lists=params.n_lists, L=max_list_size,
            fill_values=[jnp.zeros((), jnp.uint8),
                         jnp.zeros((), jnp.float32)])
        _sp.attach(packed, ids)
    n_drop = int(dropped)
    if n_drop:
        from raft_tpu.core import logging as _log
        _log.warn("ivf_pq: dropped %d overflow vectors (raise "
                  "list_size_cap_factor)", n_drop)
    index = IvfPqIndex(
        centers=centers, centers_rot=centers_rot, rotation=rotation,
        codebooks=codebooks, packed_codes=packed,
        packed_ids=ids, packed_norms=pnorm,
        list_sizes=sizes, metric=mt.value,
        codebook_kind=params.codebook_kind, pq_bits=params.pq_bits,
        pq_dim_static=pq_dim)
    if _want_recon_cache(params, params.n_lists, max_list_size, rot_dim):
        with span("recon_cache") as _sp:
            recon = _build_recon_cache(index)
            _sp.attach(recon)
            index = index.replace(packed_recon=recon)
    _istats.note_index_stats(index, name="ivf_pq.build", cheap=True)
    return index


def _count_resume(name: str, value: float = 1.0) -> None:
    """``resume.*{site=ivf_pq.build_chunked}`` counters — recorded only
    when obs is on (the count_dispatch convention)."""
    if _obs_spans.enabled():
        _obs_spans.registry().inc(name, value,
                                  labels={"site": "ivf_pq.build_chunked"})


@traced("raft_tpu.ivf_pq.build_chunked")
def build_chunked(dataset, params: Optional[IndexParams] = None,  # graftlint: disable-fn=GL01 (streaming memmap build syncs per chunk by design)
                  chunk_rows: int = 1 << 18,
                  max_train_rows: int = 1 << 21,
                  progress: bool = False,
                  checkpoint_dir: Optional[str] = None,
                  resume=False) -> IvfPqIndex:
    """Build from a host array/memmap in O(chunk) device + host working
    memory — the billion-scale path (reference: the bench harness's
    memmapped BinFile + subset datasets, cpp/bench/ann/src/common/
    dataset.hpp, and ivf_pq::build's trainset subsampling).

    ``dataset`` may be a ``np.memmap`` (see bench.dataset.bin_memmap):
    rows are touched once per pass (train-sample, label, encode), so host
    RSS stays bounded by ``chunk_rows`` plus the packed index itself.
    ``progress`` prints phase/chunk timings (hour-scale 10⁸-row builds
    are opaque without them).

    **Checkpointed resumable builds** (docs/developer_guide.md
    "Robustness"): with ``checkpoint_dir=`` the build writes a durable
    manifest (atomic tmp+fsync+rename), the trained quantizer state,
    the label pass, and one encoded-list shard per completed chunk.
    ``resume=True`` verifies the manifest's dataset/params fingerprints
    (a mismatch, truncated manifest, or missing shard refuses with a
    clear error) and continues from the last complete chunk — quantizers
    and labels are *loaded*, completed chunks replay from their shards,
    so the resumed index is bit-identical to an uninterrupted build.
    ``resume="auto"`` resumes when a manifest exists and starts fresh
    otherwise. Host reads / device transfers retry under
    :data:`raft_tpu.robust.retry.IO_POLICY`; an encode chunk that hits
    RESOURCE_EXHAUSTED is halved (``degrade.steps`` counts the walk).
    """
    import time as _time

    _t0 = _time.time()

    def _say(msg):
        if progress:
            print(f"[build_chunked +{_time.time()-_t0:7.0f}s] {msg}",
                  flush=True)
    if params is None:
        params = IndexParams()
    mt = resolve_metric(params.metric)
    expects(params.codebook_kind in ("per_subspace", "per_cluster"),
            "codebook_kind must be per_subspace or per_cluster")
    expects(4 <= params.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expects(resume in (False, True, "auto"),
            "resume must be False, True, or 'auto' (got %r)", resume)
    expects(not resume or checkpoint_dir is not None,
            "resume=%r needs checkpoint_dir= (there is no manifest to "
            "resume from without one)", resume)
    n, dim = dataset.shape

    # checkpoint bootstrap: fingerprint the inputs, load + validate the
    # manifest when resuming (robust.checkpoint owns the refusal cases)
    ck = manifest = None
    base_manifest = {}
    if checkpoint_dir is not None:
        from raft_tpu.robust import checkpoint as _ckpt

        ck = _ckpt.BuildCheckpoint(checkpoint_dir)
        # fingerprint ONCE (timed) and thread the pair through every
        # manifest write below — a memmap fingerprint samples real
        # content, so re-fingerprinting per state change would pay the
        # head/tail reads over and over; the elapsed seconds are
        # stamped so long builds can see the identity check's cost
        ds_sha, p_sha, fp_s = _ckpt.fingerprints_once(
            dataset, {**dataclasses.asdict(params),
                      "chunk_rows": chunk_rows,
                      "max_train_rows": max_train_rows})
        base_manifest = {"dataset_sha": ds_sha, "params_sha": p_sha,
                         "fingerprint_s": round(fp_s, 6),
                         "n": int(n), "dim": int(dim),
                         "chunk_rows": int(chunk_rows),
                         "n_chunks": -(-n // chunk_rows)}
        if resume is True or (resume == "auto"
                              and os.path.exists(ck.manifest_path)):
            manifest = ck.load_manifest()
            ck.validate_manifest(manifest, ds_sha, p_sha)
            _count_resume("resume.attempts")
            _say(f"resuming from {ck.manifest_path} "
                 f"(phase {manifest.get('phase')}, "
                 f"{manifest.get('chunks_done', 0)} chunks done)")
    spherical = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    normalize = mt == DistanceType.CosineExpanded

    def to_device(rows):
        # device-chunk providers (bench.dataset.DeviceSyntheticChunks)
        # hand back arrays already on device — don't round-trip them
        if isinstance(rows, jax.Array):
            x = rows.astype(jnp.float32)
        else:
            x = jnp.asarray(np.asarray(rows, np.float32))
        if normalize:
            x = x / jnp.sqrt(jnp.maximum(
                jnp.sum(x * x, -1, keepdims=True), 1e-12))
        return x

    def read_chunk(a, b):
        """One host read + device transfer under the shared IO retry
        policy (tunnel hiccups and flaky memmap reads recover instead of
        killing an hour-scale build)."""
        def _do():
            _faults.faultpoint("build.chunk_read")
            return to_device(dataset[a:b])
        return _retry.retry_call(_do, site="build.chunk_read",
                                 policy=_retry.IO_POLICY)

    pq_dim = params.pq_dim or _default_pq_dim(dim)
    pq_len = -(-dim // pq_dim)
    rot_dim = pq_dim * pq_len
    K = 1 << params.pq_bits
    key = jax.random.PRNGKey(params.seed)

    km = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                              metric="cosine" if spherical else "l2",
                              seed=params.seed)
    if manifest is not None:
        # any manifest phase implies trained quantizers on disk (the
        # first manifest write happens after the train checkpoint);
        # loading raises a clear error when the state file is missing
        _say("resume: loading quantizer state from checkpoint")
        q = ck.load_arrays("quantizers")
        centers = jnp.asarray(q["centers"])
        rotation = jnp.asarray(q["rotation"])
        centers_rot = jnp.asarray(q["centers_rot"])
        codebooks = jnp.asarray(q["codebooks"])
    else:
        # 1. quantizers on a bounded random subsample (sorted: memmap
        # locality)
        n_train = min(n, max_train_rows,
                      max(params.n_lists * 4, 4 * K,
                          int(n * params.kmeans_trainset_fraction)))
        rng = np.random.default_rng(params.seed)
        tr_idx = np.sort(rng.choice(n, n_train, replace=False))
        _say(f"sampling {n_train} train rows")
        if hasattr(dataset, "sample_rows"):  # device-chunk provider
            trainset = to_device(dataset.sample_rows(tr_idx))
        else:
            trainset = _retry.retry_call(
                lambda: to_device(dataset[tr_idx]),
                site="build.train_sample", policy=_retry.IO_POLICY)
        _say("training quantizers (coarse kmeans + rotation + codebooks)")
        with span("train"):
            centers, rotation, centers_rot, codebooks = _train_quantizers(
                trainset, params, dim, pq_dim, pq_len, K, key, km)
            jax.block_until_ready(codebooks)
        del trainset
        if ck is not None:
            # kmeans centroid state + rotation + codebooks: the state a
            # resume must NOT retrain (f32 round-trips bit-exact, so a
            # resumed encode is identical to an uninterrupted one)
            ck.save_arrays("quantizers",
                           centers=np.asarray(centers),
                           rotation=np.asarray(rotation),
                           centers_rot=np.asarray(centers_rot),
                           codebooks=np.asarray(codebooks))
            ck.write_manifest({**base_manifest, "phase": "label"})
    _say("quantizers trained; label pass")

    # 2. streaming label pass → histogram → list capacity (loaded from
    # the checkpoint when the resume manifest says the pass completed)
    from raft_tpu.neighbors.ivf_flat import _fit_list_size

    from raft_tpu.core.interruptible import cancellation_point

    avg = max(1, n // params.n_lists)
    have_labels = (manifest is not None
                   and manifest.get("phase") in ("encode", "done"))
    if have_labels:
        _say("resume: loading label pass from checkpoint")
        labels = np.asarray(ck.load_arrays("labels")["labels"], np.int32)
        expects(labels.shape[0] == n,
                "resume label checkpoint holds %d rows, dataset has %d",
                labels.shape[0], n)
        L = int(manifest["L"])
        counts = np.bincount(labels[labels < params.n_lists],
                             minlength=params.n_lists)
    else:
        with span("label"):
            if params.spill:
                # top-2 labels, then cap+spill (see IndexParams.spill):
                # L is the cap itself, not the skewed max load
                from raft_tpu.neighbors import ivf_common as ic
                from raft_tpu.neighbors.ivf_flat import _lane_round

                NC = min(ic.SPILL_DEPTH, params.n_lists)
                lk = np.empty((n, NC), np.int32)
                for a in range(0, n, chunk_rows):
                    cancellation_point()
                    b = min(n, a + chunk_rows)
                    lk[a:b] = np.asarray(
                        kmeans_balanced.predict_topk(centers,
                                                     read_chunk(a, b),
                                                     NC, km))
                    if a % (8 * chunk_rows) == 0:
                        _say(f"labeled {b}/{n}")
                L = _lane_round(int(avg * params.list_size_cap_factor))
                _say("spilling assignments")
                labels = np.asarray(ic.spill_assignments(
                    jnp.asarray(lk[:, 0]), jnp.asarray(lk[:, 1]),
                    params.n_lists, L,
                    *[jnp.asarray(lk[:, c]) for c in range(2, lk.shape[1])]))
                del lk
                _say("spill done; encode pass")
                n_spill_drop = int((labels >= params.n_lists).sum())
                if n_spill_drop:
                    from raft_tpu.core import logging as _log
                    _log.warn("ivf_pq chunked build: %d rows overflowed both "
                              "choices at cap %d", n_spill_drop, L)
                counts = np.bincount(labels[labels < params.n_lists],
                                     minlength=params.n_lists)
            else:
                labels = np.empty(n, np.int32)
                for a in range(0, n, chunk_rows):
                    cancellation_point()  # chunk seams are cancellation points
                    b = min(n, a + chunk_rows)
                    labels[a:b] = np.asarray(
                        kmeans_balanced.predict(centers,
                                                read_chunk(a, b), km))
                counts = np.bincount(labels, minlength=params.n_lists)
                L = _fit_list_size(counts, avg, params.list_size_cap_factor)
        if ck is not None:
            ck.save_arrays("labels", labels=labels)
            ck.write_manifest({**base_manifest, "phase": "encode",
                               "L": int(L), "chunks_done": 0})
    nbytes = packed_nbytes(pq_dim, params.pq_bits)

    # 3. streaming encode + pack into the preallocated index
    def encode_range(lo, hi):
        """Encode dataset[lo:hi) → host (packed codes, norms). A chunk
        that hits RESOURCE_EXHAUSTED is halved and retried (each row's
        encode is independent, so splitting changes nothing but the
        peak working set) — the build entry point's degradation rung."""
        try:
            xb = read_chunk(lo, hi)
            lb = jnp.asarray(labels[lo:hi])
            codes, norms = _encode_with_norms(xb @ rotation.T, centers_rot,
                                              lb, codebooks,
                                              params.codebook_kind)
            return (pack_bits_np(np.asarray(codes), params.pq_bits),
                    np.asarray(norms))
        except Exception as e:
            if not _degrade.is_resource_exhausted(e) or hi - lo <= 1024:
                raise
            _degrade.note_step("ivf_pq.build_chunked", "chunk",
                               "half_chunk", "resource_exhausted")
            from raft_tpu.core import logging as _log

            _log.warn("ivf_pq chunked build: RESOURCE_EXHAUSTED encoding "
                      "rows [%d, %d) — halving the chunk", lo, hi)
            mid = (lo + hi) // 2
            c1, n1 = encode_range(lo, mid)
            c2, n2 = encode_range(mid, hi)
            return np.concatenate([c1, c2]), np.concatenate([n1, n2])

    chunks_done = int(manifest.get("chunks_done", 0)) if have_labels else 0
    packed = np.zeros((params.n_lists, L, nbytes), np.uint8)
    # global ids stamped below are a + row ∈ [0, n): the table width
    # follows the POLICY dtype of n (core.ids) — int64 past 2³¹ rows,
    # where the old hard np.int32 silently wrapped
    ids = np.full((params.n_lists, L), -1, _ids.np_id_dtype(n))
    pnorm = np.zeros((params.n_lists, L), np.float32)
    cursor = np.zeros(params.n_lists, np.int64)  # next free slot per list
    dropped = 0
    with span("encode_pack"):
        for ci, a in enumerate(range(0, n, chunk_rows)):
            b = min(n, a + chunk_rows)
            if ci < chunks_done:
                # completed before the interruption: replay the encoded
                # shard (no device work) so the pack below is identical
                shard = ck.load_shard(ci)
                codes_h = np.asarray(shard["codes"], np.uint8)
                norms_h = np.asarray(shard["norms"], np.float32)
                expects(codes_h.shape[0] == b - a,
                        "resume shard %d holds %d rows, expected %d — "
                        "corrupt checkpoint; refusing to resume",
                        ci, codes_h.shape[0], b - a)
                _count_resume("resume.chunks_replayed")
            else:
                cancellation_point()
                _faults.faultpoint("build.chunk_encode")
                codes_h, norms_h = encode_range(a, b)
                if ck is not None:
                    # shard first, then the manifest that records it —
                    # a death between the two re-encodes one chunk, it
                    # never trusts a missing shard
                    ck.save_shard(ci, codes=codes_h, norms=norms_h)
                    ck.write_manifest({**base_manifest, "phase": "encode",
                                       "L": int(L), "chunks_done": ci + 1})
            lb_h = labels[a:b]
            order, sorted_l, slot = _stable_slots(lb_h, params.n_lists,
                                                  cursor)
            keep = (slot < L) & (sorted_l < params.n_lists)
            dropped += int((~keep).sum())
            rows = order[keep]
            ls, sl = sorted_l[keep], slot[keep].astype(np.int64)
            packed[ls, sl] = codes_h[rows]
            ids[ls, sl] = (a + rows).astype(ids.dtype)
            pnorm[ls, sl] = norms_h[rows]
            cursor = np.minimum(
                cursor + np.bincount(lb_h, minlength=params.n_lists)[
                    :params.n_lists], L)
            if a % (8 * chunk_rows) == 0:
                _say(f"encoded {b}/{n}")
    if ck is not None:
        ck.write_manifest({**base_manifest, "phase": "done", "L": int(L),
                           "chunks_done": -(-n // chunk_rows)})
    if dropped:
        from raft_tpu.core import logging as _log
        _log.warn("ivf_pq chunked build: dropped %d overflow vectors", dropped)

    fold = (nbytes < 128 and packed.nbytes > (1 << 30)
            and (L * nbytes) % 128 == 0)
    if fold:  # lane-fold big code arrays (see IvfPqIndex.codes_folded)
        packed = packed.reshape(params.n_lists, -1, 128)
    index = IvfPqIndex(
        centers=centers, centers_rot=centers_rot, rotation=rotation,
        codebooks=codebooks, packed_codes=ser.to_device_chunked(packed),
        packed_ids=jnp.asarray(ids), packed_norms=jnp.asarray(pnorm),
        list_sizes=jnp.asarray(np.minimum(counts, L).astype(np.int32)),
        metric=mt.value, codebook_kind=params.codebook_kind,
        pq_bits=params.pq_bits, pq_dim_static=pq_dim, codes_folded=fold)
    if _want_recon_cache(params, params.n_lists, L, rot_dim):
        index = index.replace(packed_recon=_build_recon_cache(index))
    _istats.note_index_stats(index, name="ivf_pq.build_chunked",
                             cheap=True)
    return index


@traced("raft_tpu.ivf_pq.build_distributed")
def build_distributed(dataset, params: Optional[IndexParams] = None, *,
                      mesh, axis: str = "shard",
                      chunk_rows: int = 1 << 18,
                      max_train_rows: int = 1 << 21,
                      prefetch: bool = True,
                      coarse: str = "replicated",
                      checkpoint_dir: Optional[str] = None,
                      resume=False, progress: bool = False):
    """Distributed billion-scale build from a host array/memmap — the
    pod twin of :func:`build_chunked` (reference: the raft-dask MNMG
    build lane, SURVEY §2.15; ROADMAP item 2's SIFT-1B path). Returns a
    :class:`raft_tpu.parallel.ivf.ShardedIvfPq` that the PR-8 sharded
    searcher (``search``'s ``mesh=`` dispatch, ring merge and fused
    scan-in-ring included) consumes directly.

    Structure (details: :mod:`raft_tpu.parallel.build`):

    - quantizers trained ONCE from a cross-shard trainset gathered with
      one ``allgatherv`` — by default (``coarse="replicated"``) the
      exact single-host trainer over the exact single-host sample, so
      ``parallel.build.assemble_ivf_pq`` of the result is
      **bit-identical** to ``build_chunked`` over the same
      dataset/params; ``coarse="distributed"`` swaps in the psum-Lloyd
      MNMG trainer (:func:`raft_tpu.cluster.distributed.fit`) when the
      trainset itself is too big to replicate (parity waived);
    - each shard walks only its contiguous slice of ``dataset`` in
      ``chunk_rows`` chunks through a double-buffered host→HBM
      prefetcher (chunk N+1's read + ``device_put`` hide under chunk
      N's encode; ``build.prefetch.{hit,stall}`` counters and the
      ``span.*.encode`` / ``span.*.h2d`` rows prove the overlap;
      ``prefetch=False`` keeps the serialized copy-then-encode walk for
      comparison). Reads retry under the PR-7 IO policy;
    - the only post-train collective is one ``allgatherv`` of per-list
      counts — encoded codes/ids/norms never cross the interconnect;
    - ``checkpoint_dir=`` makes the pod build preemption-safe per
      shard: per-(shard, chunk) encoded shards + a shard-axis manifest,
      resume replays to a sha-identical sharded index (fingerprints
      computed once, validated on resume — same refusal matrix as
      ``build_chunked``)."""
    if params is None:
        params = IndexParams()
    from raft_tpu.parallel import build as _dbuild

    return _dbuild.build_ivf_pq_distributed(
        dataset, params, mesh, axis=axis, chunk_rows=chunk_rows,
        max_train_rows=max_train_rows, prefetch=prefetch, coarse=coarse,
        checkpoint_dir=checkpoint_dir, resume=resume, progress=progress)


def _want_recon_cache(params: IndexParams, n_lists: int, L: int,
                      rot_dim: int) -> bool:
    if params.cache_reconstruction == "never":
        return False
    if params.cache_reconstruction == "always":
        return True
    # "auto": cap at ~1/5 of the local device's memory (3 GB on a 16 GB
    # chip — covers 1M×128 f32-equivalent datasets with room for codes,
    # queries and accumulators). The scan reads the cache instead of
    # decoding codes per probe, and the fast scalar-prefetch kernel
    # requires it; devices that don't report memory get the 16 GB-class
    # default.
    from raft_tpu.obs import hbm as _hbm

    cap = 3 << 30
    limit = _hbm.bytes_limit()
    if limit:
        cap = min(cap, limit // 5)
    return n_lists * L * rot_dim * 2 <= cap


@jax.jit
def _build_recon_cache(index: IvfPqIndex) -> jax.Array:
    """bf16 reconstruction (c + decoded residual) of every packed slot.

    The decode is blocked over list chunks with ``lax.map`` (mirroring
    _encode_rows' 4096-row blocking): a single unblocked decode would
    materialize a one-hot K× the code volume if XLA fails to fuse it —
    near the 1 GB "auto" cache cap that is a multi-GB peak."""
    from raft_tpu.neighbors import ivf_common as ic

    n_lists, L = index.packed_ids.shape
    nb = packed_nbytes(index.pq_dim, index.pq_bits)
    S = index.pq_dim
    chunk = ic.choose_list_chunk(n_lists, max(1, -(-4096 // max(L, 1))))
    n_chunks = n_lists // chunk
    per_cluster = index.codebook_kind == "per_cluster"

    def decode_chunk(args):
        if per_cluster:
            codes_p, crot, cb = args
            dec = _decode_lists_cluster(index.unpack_codes(codes_p), cb)
        else:
            codes_p, crot = args
            codes = index.unpack_codes(codes_p)
            dec = _decode_codes(codes.reshape(chunk * L, S),
                                index.codebooks).reshape(chunk, L, -1)
        return (dec + crot[:, None, :]).astype(jnp.bfloat16)

    # row-major reshape is layout-agnostic: folded storage unfolds here
    ins = (index.packed_codes.reshape(n_chunks, chunk, L, nb),
           index.centers_rot.reshape(n_chunks, chunk, -1))
    if per_cluster:
        K, P = index.codebooks.shape[1:]
        ins = ins + (index.codebooks.reshape(n_chunks, chunk, K, P),)
    out = lax.map(decode_chunk, ins)
    return out.reshape(n_lists, L, -1)


@traced("raft_tpu.ivf_pq.extend")
def extend(index: IvfPqIndex, new_vectors: jax.Array,  # graftlint: disable-fn=GL01 (host re-pack by design)
           new_ids: Optional[jax.Array] = None) -> IvfPqIndex:
    """Append vectors (reference: ivf_pq::extend): encode against existing
    centers/codebooks, host re-pack with capacity growth."""
    mt = resolve_metric(index.metric)
    spherical = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    km = KMeansBalancedParams(metric="cosine" if spherical else "l2")
    x = jnp.asarray(new_vectors, jnp.float32)
    if mt == DistanceType.CosineExpanded:
        x = x / jnp.sqrt(jnp.maximum(jnp.sum(x * x, -1, keepdims=True), 1e-12))
    old_n = index.size
    if new_ids is None:
        new_ids = _ids.make_ids(x.shape[0], start=old_n)

    labels = kmeans_balanced.predict(index.centers, x, km)
    codes, norms = _encode_with_norms(x @ index.rotation.T, index.centers_rot,
                                      labels, index.codebooks,
                                      index.codebook_kind)

    n_lists, L = index.packed_ids.shape
    S = packed_nbytes(index.pq_dim, index.pq_bits)  # bytes per code row
    old_sizes = np.asarray(index.list_sizes)
    labels_h = np.asarray(labels)
    need = old_sizes + np.bincount(labels_h, minlength=n_lists)
    new_L = max(L, max(8, -(-int(need.max()) // 8) * 8))

    old_ids = np.asarray(index.packed_ids)
    nid_h0 = np.asarray(new_ids)
    packed = np.zeros((n_lists, new_L, S), np.uint8)
    ids = np.full((n_lists, new_L), -1,
                  _ids.np_id_dtype_like(old_ids, nid_h0))
    pnorm = np.zeros((n_lists, new_L), np.float32)
    packed[:, :L] = np.asarray(index.packed_codes).reshape(n_lists, L, -1)
    ids[:, :L] = old_ids
    pnorm[:, :L] = np.asarray(index.packed_norms)
    codes_h = pack_bits_np(np.asarray(codes), index.pq_bits)
    norms_h, nid_h = np.asarray(norms), nid_h0
    # vectorized append: slot = old_size[list] + rank within the new rows
    order, sorted_l, slot = _stable_slots(labels_h, n_lists, old_sizes)
    keep = slot < new_L
    rows = order[keep]
    ls, sl = sorted_l[keep], slot[keep]
    packed[ls, sl] = codes_h[rows]
    ids[ls, sl] = nid_h[rows]
    pnorm[ls, sl] = norms_h[rows]
    fill = np.minimum(need, new_L)
    out = IvfPqIndex(
        centers=index.centers, centers_rot=index.centers_rot,
        rotation=index.rotation, codebooks=index.codebooks,
        packed_codes=jnp.asarray(
            packed.reshape(n_lists, -1, 128)
            if index.codes_folded and (new_L * S) % 128 == 0 else packed),
        packed_ids=jnp.asarray(ids),
        packed_norms=jnp.asarray(pnorm),
        list_sizes=jnp.asarray(fill.astype(np.int32)), metric=index.metric,
        codebook_kind=index.codebook_kind, pq_bits=index.pq_bits,
        pq_dim_static=index.pq_dim,
        codes_folded=index.codes_folded and (new_L * S) % 128 == 0)
    if index.packed_recon is not None:
        out = out.replace(packed_recon=_build_recon_cache(out))
    _istats.note_index_stats(out, name="ivf_pq.extend", cheap=True)
    return out


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

def _qd_from_qlut(idx: jax.Array, qlut: jax.Array) -> jax.Array:
    """⟨q,d⟩ per candidate from the query-only LUT: ``idx`` [t, C, S]
    i32 code values, ``qlut`` [t, S, K] → [t, C] f32. One-hot MXU
    contraction on TPU (per-lane gathers are the slowest op there; the
    iota-compare one-hot fuses into the matmul's operand feed — the TPU
    counterpart of the reference's fused LUT scan,
    ivf_pq_compute_similarity-inl.cuh); CPU keeps the natural gather
    (its XLA doesn't fuse the one-hot and would materialize it)."""
    if jax.default_backend() != "cpu":
        onehot = jax.nn.one_hot(idx, qlut.shape[-1], dtype=jnp.float32)
        return jnp.einsum("tcsk,tsk->tc", onehot, qlut,
                          precision=get_precision(),
                          preferred_element_type=jnp.float32)
    idx_t = jnp.transpose(idx, (0, 2, 1))                       # [t, S, C]
    gath = jnp.take_along_axis(qlut.astype(jnp.float32), idx_t, axis=2)
    return jnp.sum(gath, axis=1)                                # [t, C]


def _finish_candidates(dots, cand_ids, cand_norms, q_sq, mt, k,
                       filter_bits=None):
    """Shared candidate epilogue: ``dots`` = ⟨q, c+d⟩ per candidate (from
    the LUT decomposition or the recon gather) → metric distances, mask,
    select, id gather, cosine flip. Used by both the fused per_query
    path and the stage-decomposed scan, so their results cannot drift."""
    ip_like = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    if ip_like:
        dists = dots
        invalid = -jnp.inf
        final_min = False
    else:
        dists = jnp.maximum(q_sq[:, None] - 2.0 * dots + cand_norms, 0.0)
        if mt == DistanceType.L2SqrtExpanded:
            dists = jnp.sqrt(dists)
        invalid = jnp.inf
        final_min = True
    valid = cand_ids >= 0
    if filter_bits is not None:
        from raft_tpu.neighbors.sample_filter import passes

        valid = passes(filter_bits, cand_ids)
    dists = jnp.where(valid, dists, invalid)
    vals, pos = _select_k(dists, k, select_min=final_min)
    ids = jnp.take_along_axis(cand_ids, pos, axis=1)
    if ip_like and mt == DistanceType.CosineExpanded:
        vals = 1.0 - vals  # report cosine distance
    return vals, ids


def _coarse_probes(index: IvfPqIndex, q_all: jax.Array, n_probes: int,
                   ip_like: bool):
    """Coarse probe selection on q·c (reference: select_clusters,
    ivf_pq_search.cuh:70-156) — plain helper traced inside both jitted
    search paths (per_query and grouped), so the metric-dependent
    expansion lives in ONE place. Returns (qc [m, n_lists], probes
    [m, n_probes])."""
    qc = lax.dot_general(q_all, index.centers, (((1,), (1,)), ((), ())),
                         precision=get_precision(),
                         preferred_element_type=jnp.float32)
    if ip_like:
        _, probes = _select_k(qc, n_probes, select_min=False)
    else:
        c_sq = jnp.sum(index.centers**2, axis=1)
        _, probes = _select_k(c_sq[None, :] - 2.0 * qc, n_probes,
                              select_min=True)
    return qc, probes


@partial(jax.jit, static_argnames=("k", "n_probes", "query_tile",
                                   "lut_dtype"))
def _search_impl(index: IvfPqIndex, queries: jax.Array, k: int,
                 n_probes: int, query_tile: int, filter_bits=None,
                 lut_dtype: str = "float32"):
    mt = resolve_metric(index.metric)
    q_all = jnp.asarray(queries, jnp.float32)
    if mt == DistanceType.CosineExpanded:
        q_all = q_all / jnp.sqrt(jnp.maximum(
            jnp.sum(q_all * q_all, -1, keepdims=True), 1e-12))
    m = q_all.shape[0]
    S, K, P = index.pq_dim, index.pq_book_size, index.pq_len
    per_cluster = index.codebook_kind == "per_cluster"
    L = index.max_list_size
    ip_like = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)

    # qc itself is needed regardless — the ⟨q,c⟩ term of the decomposition
    qc, probes = _coarse_probes(index, q_all, n_probes, ip_like)

    q_rot_all = q_all @ index.rotation.T
    q_sq_all = jnp.sum(q_rot_all * q_rot_all, axis=1)
    qc_probed_all = jnp.take_along_axis(qc, probes, axis=1)  # [m, P] ⟨q,c⟩

    # recon-dot preempts the LUT scan only when (a) the cache exists,
    # (b) the user didn't ask for LUT quantization, and (c) the one-hot
    # operand feed [C, S, K] is large enough to be dangerous — observed
    # device fault at C≈254k, S=64, K=256 (n=1M, L≈4k); small indexes
    # keep the exact f32-LUT ADC so per_query results are unchanged
    use_recon_dot = (index.packed_recon is not None
                     and lut_dtype == "float32"
                     and n_probes * L * S * K >= (1 << 28))

    def search_tile(args):
        q_rot, probe, qc_probed, q_sq = args
        t = q_rot.shape[0]
        q_sub = q_rot.reshape(t, S, P)
        cand_ids = index.packed_ids[probe].reshape(t, n_probes * L)
        cand_norms = index.packed_norms[probe].reshape(t, n_probes * L)
        if use_recon_dot:
            # one contraction against the gathered bf16 reconstructions:
            # ⟨q_rot, c+d⟩ = ⟨q,c⟩ + ⟨q,d⟩, so the LUT decomposition
            # collapses and no one-hot is formed
            rows = index.packed_recon[probe].reshape(t, n_probes * L, -1)
            dots = jnp.einsum("td,tcd->tc", q_rot,
                              rows.astype(jnp.float32),
                              precision=get_precision(),
                              preferred_element_type=jnp.float32)
            return finish_tile(dots, cand_ids, cand_norms, q_sq)
        codes_p = index.codes_chunk(probe.reshape(-1)).reshape(
            t, n_probes, L, -1)                           # [t, Pr, L, nb]
        codes = index.unpack_codes(codes_p)               # [t, Pr, L, S]
        # ⟨q, d⟩: qd[t,c] = Σ_s qlut[t, s, codes[t,c,s]].  On TPU this is
        # formulated as a one-hot contraction: per-lane dynamic gathers
        # are the slowest op on a TPU, while the iota-compare one-hot
        # fuses into the MXU matmul's operand feed (never hits HBM) —
        # the TPU counterpart of the reference's fused LUT scan
        # (ivf_pq_compute_similarity-inl.cuh).  CPU keeps the gather
        # (its XLA doesn't fuse the one-hot and would materialize it).
        if per_cluster:
            # LUT is per (query, probed cluster): ⟨q_s, cb[probe][k]⟩
            cb_probed = index.codebooks[probe]            # [t, Pr, K, P]
            lut = jnp.einsum("tsp,tjkp->tjsk", q_sub, cb_probed,
                             precision=get_precision())   # [t, Pr, S, K]
            lut = _quantize_lut(lut, lut_dtype)
            if jax.default_backend() == "cpu":
                # CPU XLA won't fuse the 5-D one-hot — gather instead
                codes_t = jnp.transpose(codes, (0, 1, 3, 2))  # [t, Pr, S, L]
                gath = jnp.take_along_axis(
                    lut.astype(jnp.float32), codes_t.astype(jnp.int32),
                    axis=3)                               # [t, Pr, S, L]
                qd = jnp.sum(gath, axis=2).reshape(t, n_probes * L)
            else:
                oh = jax.nn.one_hot(codes.astype(jnp.int32), K,
                                    dtype=jnp.float32)    # [t, Pr, L, S, K]
                qd = jnp.einsum("tjlsk,tjsk->tjl", oh, lut,
                                precision=get_precision(),
                                preferred_element_type=jnp.float32
                                ).reshape(t, n_probes * L)
        else:
            # query-only LUT: ⟨q_s, cb[s,k]⟩ — one batched MXU contraction
            qlut = jnp.einsum("tsp,skp->tsk", q_sub, index.codebooks,
                              precision=get_precision())  # [t, S, K]
            qlut = _quantize_lut(qlut, lut_dtype)
            idx = codes.reshape(t, n_probes * L, S).astype(jnp.int32)
            qd = _qd_from_qlut(idx, qlut)
        qcand = jnp.broadcast_to(qc_probed[:, :, None],
                                 (t, n_probes, L)).reshape(t, n_probes * L)
        return finish_tile(qcand + qd, cand_ids, cand_norms, q_sq)

    def finish_tile(dots, cand_ids, cand_norms, q_sq):
        return _finish_candidates(dots, cand_ids, cand_norms, q_sq, mt, k,
                                  filter_bits=filter_bits)

    if m <= query_tile:
        return search_tile((q_rot_all, probes, qc_probed_all, q_sq_all))

    n_tiles = -(-m // query_tile)
    pad = n_tiles * query_tile - m
    qr = jnp.pad(q_rot_all, ((0, pad), (0, 0)))
    pr = jnp.pad(probes, ((0, pad), (0, 0)))
    qp = jnp.pad(qc_probed_all, ((0, pad), (0, 0)))
    qs = jnp.pad(q_sq_all, (0, pad))
    vals, ids = lax.map(search_tile, (
        qr.reshape(n_tiles, query_tile, -1),
        pr.reshape(n_tiles, query_tile, -1),
        qp.reshape(n_tiles, query_tile, -1),
        qs.reshape(n_tiles, query_tile)))
    return (vals.reshape(-1, k)[:m], ids.reshape(-1, k)[:m])


@partial(jax.jit, static_argnames=("k", "n_probes", "seg", "n_seg",
                                   "seg_chunk", "use_pallas", "select_impl",
                                   "select_recall", "use_segk"))
def _search_grouped(index: IvfPqIndex, queries: jax.Array, k: int,
                    n_probes: int, seg: int, n_seg: int, seg_chunk: int,
                    use_pallas: bool = False, filter_bits=None,
                    select_impl: str = "exact",
                    select_recall: float = 0.95,
                    use_segk: bool = False):
    """Segmented list-centric batch scan (see ivf_common): each probed
    list's codes are decoded once per owned segment (one-hot MXU
    contraction — or skipped entirely when the bf16 reconstruction cache
    is present) and scanned against that segment's queries with one
    batched MXU contraction. Probe selection, segmenting, scan and merge
    are ONE jitted program, statically shaped by (B, n_probes, n_lists,
    seg). Counterpart of the reference's compute_similarity kernel
    (ivf_pq_compute_similarity-inl.cuh) with the loop order inverted:
    the reference re-reads packed codes per query, this reads them per
    query *segment*."""
    from raft_tpu.neighbors import ivf_common as ic

    mt = resolve_metric(index.metric)
    q_all = jnp.asarray(queries, jnp.float32)
    if mt == DistanceType.CosineExpanded:
        q_all = q_all / jnp.sqrt(jnp.maximum(
            jnp.sum(q_all * q_all, -1, keepdims=True), 1e-12))
    B = q_all.shape[0]
    n_lists, L = index.packed_ids.shape
    per_cluster = index.codebook_kind == "per_cluster"
    ip_like = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    sqrt_out = mt == DistanceType.L2SqrtExpanded
    select_min = not ip_like
    invalid = -jnp.inf if ip_like else jnp.inf

    from raft_tpu.ops import pallas_kernels as _pk

    use_pallas = use_pallas and index.packed_recon is not None

    _, probes = _coarse_probes(index, q_all, n_probes, ip_like)
    seg_list, seg_q, pair_seg, pair_slot = ic.segment_probes(
        probes, n_lists, seg, n_seg)

    q_rot = q_all @ index.rotation.T                      # [B, rot_dim]
    q_sq = jnp.sum(q_rot * q_rot, axis=1)
    valid_full = index.packed_ids >= 0
    if filter_bits is not None:
        from raft_tpu.neighbors.sample_filter import passes

        valid_full &= passes(filter_bits, index.packed_ids)

    kk_ = min(k, L)
    if use_segk:
        # scalar-prefetch kernel over the bf16 recon cache (see ivf_flat:
        # the XLA gather of list blocks runs ~20 GB/s and dominates).
        # A filter rides as a SENTINEL-MASKED id table: filtered slots
        # become the -1 invalid id the kernel already poisons to +inf
        # before its bin pre-selection (the GL13 pattern), so the bins
        # hold only kept candidates — dispatch admits the [n_lists, L]
        # mask+i32 transient via filtered_scan_mem_ok(slot_bytes=5)
        met = "ip" if ip_like else "l2"
        qv_all = q_rot[jnp.clip(seg_q, 0, B - 1)]         # [n_seg, S, rot]
        seg_ids = (index.packed_ids if filter_bits is None
                   else jnp.where(valid_full, index.packed_ids, -1))
        keys, kids = _pk.segmented_scan_topk(
            seg_list, qv_all, index.packed_recon, seg_ids, met,
            interpret=not _pk._on_tpu())
        out_vals, out_ids = ic.merge_bin_results(
            keys, kids, pair_seg, pair_slot, k, select_min, invalid,
            select_recall)
        if sqrt_out:
            out_vals = jnp.sqrt(out_vals)
        if mt == DistanceType.CosineExpanded:
            out_vals = 1.0 - out_vals
        return out_vals, out_ids

    C = seg_chunk
    n_chunks = -(-n_seg // C)
    nsp = n_chunks * C
    seg_list = jnp.pad(seg_list, (0, nsp - n_seg))
    seg_q = jnp.pad(seg_q, ((0, nsp - n_seg), (0, 0)), constant_values=-1)
    has_recon = index.packed_recon is not None

    # billion-scale guard: a GATHER of list chunks from a multi-GB code
    # array inside the scan loop provokes XLA into rematerializing
    # pipelined SLAB COPIES of the whole array (measured: 3× 1.88 GB
    # temps at 100M — an instant compile OOM next to the resident
    # index). dynamic_slice at C=1 keeps the loop slab-free.
    slice_scan = index.packed_codes.nbytes > _SLICE_SCAN_BYTES
    if slice_scan:
        C = 1
        n_chunks = n_seg
        nsp = n_seg
        seg_list = seg_list[:n_seg]
        seg_q = seg_q[:n_seg]

    def _chunk(arr, sl):
        if slice_scan:
            return lax.dynamic_slice(
                arr, (sl[0],) + (0,) * (arr.ndim - 1),
                (1,) + arr.shape[1:])
        return arr[sl]

    def scan_chunk(args):
        sl, qt = args                                     # [C], [C, seg]
        norms = _chunk(index.packed_norms, sl)
        lids = _chunk(index.packed_ids, sl)
        valid = lids >= 0 if slice_scan else valid_full[sl]
        if slice_scan and filter_bits is not None:
            from raft_tpu.neighbors.sample_filter import passes

            valid &= passes(filter_bits, lids)
        if has_recon:
            recon = _chunk(index.packed_recon, sl)        # [C, L, rot]
        else:
            cp = _chunk(index.packed_codes, sl)
            if index.codes_folded:
                cp = cp.reshape(cp.shape[0], L, -1)
            codes = index.unpack_codes(cp)
            if per_cluster:
                decoded = _decode_lists_cluster(codes,
                                                _chunk(index.codebooks, sl))
            else:
                decoded = _decode_codes(codes, index.codebooks)
            recon = decoded + _chunk(index.centers_rot, sl)[:, None, :]
        qi = jnp.clip(qt, 0, B - 1)
        qv = q_rot[qi]                                    # [C, seg, rot]
        # pad slots (qt == -1) compute against query 0 and are simply
        # never gathered back
        if use_pallas:
            # fused contraction + epilogue + local top-k in VMEM over the
            # bf16 reconstructions (reference: compute_similarity's fused
            # block-sort top-k, ivf_pq_compute_similarity-inl.cuh:439);
            # the l2 epilogue recomputes ‖c+d‖² from the bf16 recon —
            # ~1e-3 relative drift vs the stored f32 norms
            met = "ip" if ip_like else "l2"
            mask_add = jnp.where(valid, 0.0, jnp.inf)
            keys, pos = _pk.grouped_scan_topk(
                qv, recon, mask_add, kk, met, bq=seg,
                interpret=not _pk._on_tpu())
            vals = -keys if ip_like else keys
            vals = jnp.where(pos < 0, invalid, vals)
            cids = jax.vmap(lambda l, p: l[jnp.clip(p, 0, L - 1)])(lids, pos)
            cids = jnp.where(pos < 0, -1, cids)
            return vals, cids
        scores = jnp.einsum("gqd,gld->gql", qv,
                            recon.astype(jnp.float32),
                            precision=get_precision(),
                            preferred_element_type=jnp.float32)
        if ip_like:
            dists = scores
        else:
            dists = jnp.maximum(
                q_sq[qi][:, :, None] + norms[:, None, :] - 2.0 * scores, 0.0)
        dists = jnp.where(valid[:, None, :], dists, invalid)
        if select_impl == "approx":
            # hardware top-k (TPU approx reduction) — see ivf_flat
            if select_min:
                vals, pos = lax.approx_min_k(
                    dists.reshape(C * seg, L), kk,
                    recall_target=select_recall)
            else:
                vals, pos = lax.approx_max_k(
                    dists.reshape(C * seg, L), kk,
                    recall_target=select_recall)
        else:
            vals, pos = _select_k(dists.reshape(C * seg, L), kk,
                                  select_min=select_min)
        vals = vals.reshape(C, seg, kk)
        pos = pos.reshape(C, seg, kk)
        cids = jax.vmap(lambda l, p: l[p])(lids, pos)
        cids = jnp.where(vals == invalid, -1, cids)
        return vals, cids

    kk = min(k, L)  # a single list holds at most L candidates
    vals, cids = lax.map(
        scan_chunk, (seg_list.reshape(n_chunks, C),
                     seg_q.reshape(n_chunks, C, seg)))
    vals = vals.reshape(nsp, seg, kk)
    cids = cids.reshape(nsp, seg, kk)

    pv, pi = ic.gather_segment_results(vals, cids, pair_seg, pair_slot)
    out_vals, out_ids = _select_k(pv.reshape(B, n_probes * kk),
                                  min(k, n_probes * kk),
                                  select_min=select_min,
                                  input_indices=pi.reshape(B, n_probes * kk))
    if k > n_probes * kk:
        pad = k - n_probes * kk
        out_vals = jnp.pad(out_vals, ((0, 0), (0, pad)),
                           constant_values=invalid)
        out_ids = jnp.pad(out_ids, ((0, 0), (0, pad)), constant_values=-1)
    if sqrt_out:
        out_vals = jnp.sqrt(out_vals)
    if mt == DistanceType.CosineExpanded:
        out_vals = 1.0 - out_vals
    return out_vals, out_ids


@partial(jax.jit, static_argnames=("k", "n_probes", "seg", "n_seg",
                                   "lut_dtype"))
def _search_lut_pallas(index: IvfPqIndex, queries: jax.Array, k: int,
                       n_probes: int, seg: int, n_seg: int,
                       filter_bits=None, lut_dtype: str = "float32"):
    """The ``scan_select="pallas"`` tier: segmented scan through the fused
    Pallas LUT kernel (ops.pallas_kernels.ivfpq_lut_scan_topk). Packed
    codes stream HBM→VMEM per segment, unpack/decode/accumulate/select
    happen on-chip, and only the [n_seg, seg, 256] bin tables come back —
    neither the decoded-f32 lists, the one-hot operands, nor the
    [B, n_probes·L] candidate tables ever exist in HBM. The merged bins
    run through the shared :func:`_finish_candidates` epilogue, so
    results cannot drift from the fused/staged paths' semantics.

    ``filter_bits`` streams INTO the kernel as a per-candidate packed
    mask (``sample_filter.list_filter_bytes`` over the same id table
    the kernel scans, 1 bit/candidate): filtered candidates take the
    +inf/-1 sentinel BEFORE the 2×128-bin pre-selection, so the emitted
    bins hold only kept candidates and a selective filter no longer
    makes kept neighbors unreachable. The shared
    :func:`_finish_candidates` epilogue re-applies the same filter over
    the merged candidates — a no-op on the kernel's output, kept so the
    fused and unfused paths share one exclusion site."""
    from raft_tpu.neighbors import ivf_common as ic
    from raft_tpu.ops import pallas_kernels as _pk

    mt = resolve_metric(index.metric)
    q_all = jnp.asarray(queries, jnp.float32)
    if mt == DistanceType.CosineExpanded:
        q_all = q_all / jnp.sqrt(jnp.maximum(
            jnp.sum(q_all * q_all, -1, keepdims=True), 1e-12))
    B = q_all.shape[0]
    ip_like = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)

    _, probes = _coarse_probes(index, q_all, n_probes, ip_like)
    seg_list, seg_q, pair_seg, pair_slot = ic.segment_probes(
        probes, index.n_lists, seg, n_seg)
    q_rot = q_all @ index.rotation.T
    q_sq = jnp.sum(q_rot * q_rot, axis=1)
    qv_all = q_rot[jnp.clip(seg_q, 0, B - 1)]         # [n_seg, seg, rot]

    filter_bytes = None
    if filter_bits is not None:
        from raft_tpu.neighbors import sample_filter as _sf

        # per-list packed keep bits over the SAME [n_lists, L] id table
        # the kernel streams — one gather + byte re-pack, n/8 bytes
        filter_bytes = _sf.list_filter_bytes(filter_bits,
                                             index.packed_ids)
    keys, kids = _pk.ivfpq_lut_scan_topk(
        seg_list, qv_all, index.packed_codes, index.packed_ids,
        index.packed_norms, index.centers_rot, index.codebooks,
        "ip" if ip_like else "l2", pq_bits=index.pq_bits,
        pq_dim=index.pq_dim, L=index.max_list_size, lut_dtype=lut_dtype,
        filter_bytes=filter_bytes, interpret=not _pk._on_tpu())
    pv, pi = ic.gather_segment_results(keys, kids, pair_seg, pair_slot)
    C = n_probes * keys.shape[-1]
    pv = pv.reshape(B, C)
    pi = pi.reshape(B, C)
    # the kernel emits minimized keys (l2: ‖c+d‖² − 2⟨q,c+d⟩; ip:
    # −⟨q,c+d⟩); recover the shared epilogue's ⟨q,c+d⟩ convention with
    # zero cand_norms so _finish_candidates reconstructs the metric
    dots = -pv if ip_like else -0.5 * pv
    kq = min(k, C)
    out_vals, out_ids = _finish_candidates(
        dots, pi, jnp.zeros_like(pv), q_sq, mt, kq,
        filter_bits=filter_bits)
    if k > kq:
        invalid = -jnp.inf if ip_like else jnp.inf
        if mt == DistanceType.CosineExpanded:
            invalid = jnp.inf  # reported as cosine distance
        out_vals = jnp.pad(out_vals, ((0, 0), (0, k - kq)),
                           constant_values=invalid)
        out_ids = jnp.pad(out_ids, ((0, 0), (0, k - kq)),
                          constant_values=-1)
    return out_vals, out_ids


def _count_scan_dispatch(impl: str, filtered: bool = False) -> None:
    """Record which scan engine ``search`` dispatched to (the obs
    ``ivf_pq.scan.dispatch{impl=...}`` counter) — eager, so it counts
    dispatch decisions, not device executions. Filtered searches carry
    a ``filtered=1`` label so "did the filtered workload stay on the
    fast tier?" is one counter query (the CI obs-smoke step asserts
    exactly this)."""
    if filtered:
        _obs_spans.count_dispatch("ivf_pq.scan", impl, filtered="1")
    else:
        _obs_spans.count_dispatch("ivf_pq.scan", impl)


def _count_lut_fallback(reason: str) -> None:
    """Record WHY a search eligible for (or explicitly requesting) the
    fused Pallas LUT tier ran elsewhere — the obs
    ``ivf_pq.scan.fallback{reason=...}`` counter. The dispatch counter
    alone shows only the engine that won; triage of "why isn't the
    oversampled config on the fast tier?" needs the losing reason:
    ``bin_capacity`` (n_probes·256 < k), ``per_cluster`` codebooks,
    ``mem_guard`` (lut_scan_mem_ok / filtered_scan_mem_ok declined), or
    ``kernel_ineligible`` (packed layout / VMEM / not on TPU). The
    ``filter_bitset`` reason is RETIRED: the kernels stream the bitset
    as a per-candidate mask, so a filter no longer disqualifies the
    tier (CI asserts the retired reason stays at zero)."""
    _obs_spans.count_fallback("ivf_pq.scan", reason)


def _route_refined(index: IvfPqIndex, queries: jax.Array, k: int,
                   params: "SearchParams", filter_bitset, dataset
                   ) -> Tuple[jax.Array, jax.Array]:
    """The ``refine="f32_regen"`` path: oversampled scan (k·refine_ratio
    candidates through whatever scan tier ``search`` picks), then an
    exact re-rank routed by what ``dataset`` is — the device refine
    dispatch tier (fused gather-refine kernel / XLA einsum), the
    device-chunk provider regen, or the host gather (reference:
    refine-inl.cuh's refinement_rate; deep-100m's headline rows)."""
    from raft_tpu.neighbors import refine as _refine

    expects(params.refine == "f32_regen",
            "unknown refine mode %r (supported: 'none', 'f32_regen')",
            params.refine)
    expects(dataset is not None,
            "refine='f32_regen' needs search(..., dataset=...): the "
            "exact rows to re-rank against")
    dshape = getattr(dataset, "shape", None)
    expects(dshape is not None and len(dshape) == 2
            and dshape[1] == index.dim,
            "refine dataset shape %s does not match the index dim %d",
            tuple(dshape) if dshape else None, index.dim)
    expects(params.refine_ratio >= 1.0,
            "refine_ratio must be >= 1 (got %s)", params.refine_ratio)
    k_cand = max(k, int(round(k * params.refine_ratio)))
    scan_params = dataclasses.replace(params, refine="none")
    # host-resident base → the memory tier (ISSUE 17): decide BEFORE
    # the scan — the tiered pipeline runs its own sub-batch scans so
    # each batch's candidate-row fetch can overlap the next scan
    if (not isinstance(dataset, jax.Array)
            and not hasattr(dataset, "_block")):
        from raft_tpu.neighbors import tiered as _tiered

        if _tiered.tiered_refine_wanted(dataset, queries.shape[0],
                                        k_cand, index.dim, params):
            return _tiered.search_refined_tiered(
                search, index, queries, k, k_cand, scan_params,
                filter_bitset, dataset, index.metric)
    _, i0 = search(index, queries, k_cand, scan_params, filter_bitset)
    if hasattr(dataset, "_block") and hasattr(dataset, "chunk_rows"):
        return _refine.refine_provider(dataset, queries, i0, k,
                                       metric=index.metric)
    if isinstance(dataset, jax.Array):
        # the scan already excluded filtered candidates from i0; the
        # refine-tier filter is defense in depth at zero extra traffic
        # (the fused kernel folds the bit test into its row-DMA queue)
        return _refine.refine(dataset, queries, i0, k, metric=index.metric,
                              filter_bits=filter_bitset)
    # host array / memmap, tiered declined or pinned "serial": the
    # serialized candidate-row gather
    return _refine.refine_gathered(dataset, queries, i0, k,
                                   metric=index.metric)


_lut_fallback_warned = False

# human-readable detail per fallback-counter reason. filter_bitset is
# NOT here: the fused tiers stream the bitset as a per-candidate mask
# now, so a filter no longer disqualifies the tier and warning for it
# would point at a cause that cannot occur.
_LUT_FALLBACK_DETAIL = {
    "bin_capacity": "too few probes for the requested k "
                    "(needs n_probes·256 ≥ k)",
    "per_cluster": "per_cluster codebooks (the kernel decodes "
                   "per_subspace only)",
    "mem_guard": "the lut_scan_mem_ok/filtered_scan_mem_ok HBM guard "
                 "declined the shape",
    "kernel_ineligible": "unsupported packed layout, VMEM budget, or "
                         "not on TPU",
}


def _warn_lut_fallback(reason: str) -> None:
    """Once-per-process notice that an explicit scan_select="pallas" was
    downgraded, carrying the CONCRETE reason the tier lost (the same
    label the ``ivf_pq.scan.fallback{reason=...}`` counter records) and
    the env override that forces the tier off-TPU."""
    global _lut_fallback_warned
    if _lut_fallback_warned:
        return
    _lut_fallback_warned = True
    from raft_tpu.core import logging as _log
    detail = _LUT_FALLBACK_DETAIL.get(reason, reason)
    _log.warn("ivf_pq: scan_select='pallas' requested but the fused LUT "
              "kernel cannot serve this search — reason=%s: %s "
              "(RAFT_TPU_PALLAS_LUTSCAN=always forces the tier off-TPU; "
              "the obs counter ivf_pq.scan.fallback{reason=%s} records "
              "every decline) — falling back to scan_select='approx'",
              reason, detail, reason)


@traced("raft_tpu.ivf_pq.search")
def search(index, queries: jax.Array, k: int,
           params: Optional[SearchParams] = None,
           filter_bitset: Optional[jax.Array] = None,
           dataset=None, *, mesh=None,
           mesh_axis: str = "shard",
           merge: str = "auto") -> Tuple[jax.Array, jax.Array]:
    """Search (reference: ivf_pq::search, ivf_pq-inl.cuh:478; filtered
    overload search_with_filtering). Distances are PQ-approximate (as the
    reference's) unless ``params.refine="f32_regen"``, which scans
    ``k·refine_ratio`` candidates and re-ranks them exactly against
    ``dataset`` (device array → the fused gather-refine tier on TPU
    oversampled shapes; host array/memmap → host gather; device-chunk
    provider → on-device regen). Standalone re-ranking stays available
    as neighbors.refine.
    ``filter_bitset``: optional packed bitset over dataset rows (see
    neighbors.sample_filter) — cleared bits are excluded.

    **Pod-scale dispatch**: handed a ``parallel.ShardedIvfPq`` (plus its
    ``mesh``), the same entry routes to the sharded search tier —
    per-shard scan (+ per-shard fused refine when
    ``params.refine="f32_regen"`` and ``dataset`` is given) and the
    cross-shard merge tier picked by ``merge`` (auto | allgather |
    ring, see ``parallel.merge``). Filter bitsets are single-chip-only
    for now."""
    if params is None:
        params = SearchParams()
    if params.lut_dtype == "auto" and params.refine == "none":
        # one resolution point for the fp8-default policy: every scan
        # tier below (LUT kernel, staged, grouped, per-query) and the
        # sharded dispatch receive a concrete dtype. Refined searches
        # resolve at the _route_refined RE-ENTRY instead, where k is
        # the oversampled k_cand = k·refine_ratio — the selection
        # width the fp8 slack floor (FP8_LUT_MIN_SLACK) is defined
        # over; resolving here with the final k would overstate the
        # slack by refine_ratio×. A filter's selectivity discounts the
        # slack the same way: only surviving candidates fill the bins.
        params = dataclasses.replace(params, lut_dtype=resolve_lut_dtype(
            "auto", min(params.n_probes, index.n_lists), k,
            selectivity=_filter_selectivity(filter_bitset)))
    from raft_tpu.neighbors import ivf_common as ic

    _divf = ic.sharded_dispatch(index, mesh, "ShardedIvfPq")
    if _divf is not None:
        return _divf.search_ivf_pq(params, index, queries, k, mesh,
                                   axis=mesh_axis, dataset=dataset,
                                   merge=merge, filter_bitset=filter_bitset)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "queries must be [m, %d]", index.dim)
    _faults.faultpoint("ivf_pq.search")
    if params.refine != "none":
        return _route_refined(index, queries, k, params, filter_bitset,
                              dataset)
    if (_obs_spans.stages_enabled() and _obs_spans._trace_clean()
            and filter_bitset is None
            and index.codebook_kind == "per_subspace"):
        # observability stage mode: dispatch coarse_quantize / lut / scan
        # as separate programs, each under a recording span. Never under
        # an outer jax trace — the routing would be baked into the
        # caller's jit cache and outlive obs.disable()
        _count_scan_dispatch("staged")
        return search_staged(index, queries, k, params)
    n_probes = min(params.n_probes, index.n_lists)
    B = queries.shape[0]
    mode = params.scan_mode
    if mode == "auto":
        # an explicit pallas tier request is a grouped-scan request: the
        # LUT kernel is segment-structured, batch size notwithstanding
        mode = ("grouped" if (B * n_probes >= 2 * index.n_lists
                              or params.scan_select == "pallas")
                else "per_query")
    if mode == "grouped":
        from raft_tpu.neighbors import ivf_common as ic

        # segmented scan: the table shape is a function of (B, n_probes,
        # n_lists, seg) alone — no probe histogram, no host sync, one
        # jitted program per static config (see ivf_common docstring)
        seg = ic.SEGMENT_SIZE
        pairs = B * n_probes
        n_seg = ic.n_segments(pairs, index.n_lists, seg)
        L = index.max_list_size
        kk = min(k, L)
        from raft_tpu.ops import pallas_kernels as _pk

        # fused Pallas LUT-scan tier: explicit scan_select="pallas", or
        # the approx tier auto-upgraded for oversampled shapes where the
        # XLA scan's HBM transients are hostile and no recon cache
        # exists to shortcut the decode (the DEEP-100M regime)
        # the LUT tier emits at most LUT_SCAN_BINS candidates per probed
        # list — with too few probes for the requested k it would pad
        # the tail with -1s where the XLA tiers return real neighbors.
        # Filtered searches RIDE the tier: the kernel streams the packed
        # per-candidate filter bytes beside the codes and masks filtered
        # candidates to the sentinel BEFORE bin selection, so the bins
        # hold only kept candidates (the retired filter_bitset fallback)
        filtered = filter_bitset is not None
        lut_desired = (params.scan_select == "pallas"
                       or (params.scan_select == "approx"
                           and index.packed_recon is None
                           and (n_probes >= 64 or k >= 400)))
        lut_serviceable = n_probes * _pk.LUT_SCAN_BINS >= k
        want_lut = lut_desired and lut_serviceable
        select_impl = params.scan_select
        if lut_desired and not lut_serviceable:
            # the fallback counter records WHY the tier lost (satellite:
            # the dispatch counter alone shows only the winner)
            _count_lut_fallback("bin_capacity")
            if params.scan_select == "pallas":
                _warn_lut_fallback("bin_capacity")
                select_impl = "approx"
        if want_lut:
            mem_ok = (ic.lut_scan_mem_ok(n_seg, seg, index.rot_dim,
                                         pairs, _pk.LUT_SCAN_BINS)
                      and (not filtered
                           or ic.filtered_scan_mem_ok(index.n_lists, L))
                      and not _faults.forced("ivf_pq.scan.mem_guard"))
            kernel_ok = mem_ok and _pk.pallas_lut_scan_wanted(
                index.pq_dim, index.pq_book_size, index.pq_len,
                packed_nbytes(index.pq_dim, index.pq_bits),
                index.packed_codes.shape[-1], L, index.rot_dim,
                seg=seg, lut_dtype=params.lut_dtype, filtered=filtered)
            if index.codebook_kind == "per_subspace" and kernel_ok:
                _count_scan_dispatch("pallas_lut", filtered=filtered)
                with span("scan") as _sp:
                    out = _search_lut_pallas(
                        index, queries, k, n_probes, seg, n_seg,
                        filter_bits=filter_bitset,
                        lut_dtype=params.lut_dtype)
                    _sp.attach(out)
                return out
            reason = ("per_cluster" if index.codebook_kind != "per_subspace"
                      else "mem_guard" if not mem_ok else "kernel_ineligible")
            _count_lut_fallback(reason)
            if reason == "mem_guard":
                # the static half of the degradation policy: a guard
                # declining the fused tier before it OOMs records the
                # same degrade.steps move the reactive ladder would
                # (explicit pallas requests land on approx, see below)
                to_impl = ("approx" if params.scan_select == "pallas"
                           else select_impl)
                _degrade.note_step("ivf_pq.search", "pallas_lut",
                                   f"grouped_{to_impl}", "mem_guard")
            if params.scan_select == "pallas":
                # an EXPLICIT pallas request that the kernel can't serve
                # (per_cluster codebooks, unsupported layout, off-TPU, or
                # a memory guard) must not silently land on the exact
                # grouped scan — the most HBM-hostile engine at exactly
                # the oversampled shapes this tier exists for. Fall back
                # to the recall-targeted approx tier (which re-enables
                # segk when a recon cache exists) and say so.
                _warn_lut_fallback(reason)
                select_impl = "approx"
        if params.scan_mode == "grouped" or ic.grouped_mem_ok(
                n_seg, seg, kk, pairs):
            chunk = ic.fit_seg_chunk(seg, L, index.rot_dim,
                                     params.list_chunk)
            approx = select_impl == "approx"
            # segk rides filtered searches through a SENTINEL-MASKED id
            # table (filtered slots become the -1 invalid id before the
            # kernel's bin pre-selection — _search_grouped builds it);
            # the [n_lists, L] bool+i32 transient is the 5-byte/slot
            # admission filtered_scan_mem_ok budgets
            segk = (approx and index.packed_recon is not None
                    and (filter_bitset is None
                         or ic.filtered_scan_mem_ok(index.n_lists, L,
                                                    slot_bytes=5))
                    and _pk.pallas_segmented_wanted(kk, L, index.rot_dim,
                                                    S=seg))
            wants = (not approx) and _pk.pallas_grouped_wanted(
                kk, L, index.rot_dim, bq=seg)
            _count_scan_dispatch("segk" if segk else
                                 ("grouped_pallas" if wants
                                  else "grouped_xla"), filtered=filtered)
            return _search_grouped(index, queries, k, n_probes, seg,
                                   n_seg, chunk, use_pallas=wants,
                                   filter_bits=filter_bitset,
                                   select_impl=select_impl,
                                   select_recall=params.scan_recall,
                                   use_segk=segk)
    _count_scan_dispatch("per_query", filtered=filter_bitset is not None)
    return _search_impl(index, queries, k, n_probes,
                        _fit_query_tile(params.query_tile, n_probes, index),
                        filter_bits=filter_bitset, lut_dtype=params.lut_dtype)


@traced("raft_tpu.ivf_pq.search_resilient")
def search_resilient(index: IvfPqIndex, queries: jax.Array, k: int,
                     params: Optional[SearchParams] = None,
                     filter_bitset: Optional[jax.Array] = None,
                     dataset=None,
                     deadline=None) -> Tuple[jax.Array, jax.Array]:
    """:func:`search` behind the standard degradation ladder
    (:mod:`raft_tpu.robust.degrade`): a ``RESOURCE_EXHAUSTED`` walks
    halve-batch → bf16 LUT → fp8 LUT → decline fused tier → host
    gather (then
    keeps halving) instead of crashing the request, recording every
    move in ``degrade.steps{site=ivf_pq.search,from=,to=,reason=}``.
    Results are the degraded configuration's results — batch splitting
    is exact (each query's math is independent); the bf16-LUT and
    declined-tier rungs trade the documented precision/speed margins.
    Serving loops should call this; offline sweeps that prefer a crash
    to a silently degraded number keep calling :func:`search`.

    ``deadline`` (a :class:`raft_tpu.robust.retry.Deadline` — ISSUE 14)
    is the request's ONE shared wall-clock budget: the ladder checks it
    before every re-attempt and between split sub-batches, so degraded
    retries can no longer stack past the SLO the caller promised
    (:class:`~raft_tpu.robust.retry.DeadlineExceeded` on exhaustion,
    counted ``degrade.deadline_abort{site=ivf_pq.search}``)."""
    if params is None:
        params = SearchParams()
    if params.lut_dtype == "auto":
        # resolve BEFORE the ladder, exactly as the wrapped search
        # would (refined searches select over k_cand = k·refine_ratio):
        # the LUT rungs must see the concrete dtype dispatch runs with
        # — on a TPU oversampled shape "auto" is already fp8, and
        # pinning bf16 over that would ENLARGE the operand under the
        # very memory pressure the ladder exists to relieve (both LUT
        # rungs correctly skip instead)
        kr = k if params.refine == "none" else max(
            k, int(round(k * params.refine_ratio)))
        params = dataclasses.replace(params, lut_dtype=resolve_lut_dtype(
            "auto", min(params.n_probes, index.n_lists), kr,
            selectivity=_filter_selectivity(filter_bitset)))
    queries = jnp.asarray(queries)
    return _degrade.run_with_degradation(
        _degrade.batched_search_call(search, index, queries, k,
                                     filter_bitset, deadline=deadline,
                                     site="ivf_pq.search"),
        {"params": params, "dataset": dataset},
        _degrade.standard_search_ladder(queries.shape[0], has_lut=True),
        site="ivf_pq.search", deadline=deadline)


# ---------------------------------------------------------------------------
# stage-decomposed search (observability mode — see raft_tpu.obs)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_probes", "ip_like"))
def _stage_coarse(index: IvfPqIndex, q_all: jax.Array, n_probes: int,
                  ip_like: bool):
    return _coarse_probes(index, q_all, n_probes, ip_like)


@jax.jit
def _stage_lut(index: IvfPqIndex, q_all: jax.Array):
    """Staged stage 2 (per_subspace): rotate queries + build the
    query-only LUT [m, S, K] in one batched MXU contraction."""
    q_rot = q_all @ index.rotation.T
    q_sub = q_rot.reshape(q_rot.shape[0], index.pq_dim, index.pq_len)
    qlut = jnp.einsum("msp,skp->msk", q_sub, index.codebooks,
                      precision=get_precision())
    return q_rot, qlut


@partial(jax.jit, static_argnames=("k", "n_probes", "query_tile"))
def _stage_scan(index: IvfPqIndex, q_rot_all: jax.Array, qlut_all: jax.Array,
                qc: jax.Array, probes: jax.Array, k: int, n_probes: int,
                query_tile: int):
    """Staged stage 3: gather candidates, LUT-sum ⟨q,d⟩, metric epilogue,
    select — the per_query scan with the LUT precomputed by _stage_lut."""
    mt = resolve_metric(index.metric)
    m = q_rot_all.shape[0]
    S, K, L = index.pq_dim, index.pq_book_size, index.max_list_size
    q_sq_all = jnp.sum(q_rot_all * q_rot_all, axis=1)
    qc_probed_all = jnp.take_along_axis(qc, probes, axis=1)
    # same preemption as the fused path: an oversized one-hot operand
    # feed faults the device (observed at C≈254k, S=64, K=256) — the
    # diagnostic mode must not crash exactly the big runs it exists to
    # diagnose, so scan via the recon cache when it exists and the
    # one-hot would be dangerous
    use_recon_dot = (index.packed_recon is not None
                     and n_probes * L * S * K >= (1 << 28))

    def scan_tile(args):
        q_rot, qlut, qc_probed, probe, q_sq = args
        t = q_rot.shape[0]
        cand_ids = index.packed_ids[probe].reshape(t, n_probes * L)
        cand_norms = index.packed_norms[probe].reshape(t, n_probes * L)
        if use_recon_dot:
            rows = index.packed_recon[probe].reshape(t, n_probes * L, -1)
            dots = jnp.einsum("td,tcd->tc", q_rot,
                              rows.astype(jnp.float32),
                              precision=get_precision(),
                              preferred_element_type=jnp.float32)
        else:
            codes_p = index.codes_chunk(probe.reshape(-1)).reshape(
                t, n_probes, L, -1)
            codes = index.unpack_codes(codes_p)
            idx = codes.reshape(t, n_probes * L, S).astype(jnp.int32)
            qd = _qd_from_qlut(idx, qlut)
            dots = jnp.broadcast_to(
                qc_probed[:, :, None],
                (t, n_probes, L)).reshape(t, n_probes * L) + qd
        return _finish_candidates(dots, cand_ids, cand_norms, q_sq, mt, k)

    if m <= query_tile:
        return scan_tile((q_rot_all, qlut_all, qc_probed_all, probes,
                          q_sq_all))
    n_tiles = -(-m // query_tile)
    pad = n_tiles * query_tile - m
    padded = tuple(
        jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
        for a in (q_rot_all, qlut_all, qc_probed_all, probes, q_sq_all))
    vals, ids = lax.map(scan_tile, tuple(
        a.reshape((n_tiles, query_tile) + a.shape[1:]) for a in padded))
    return vals.reshape(-1, k)[:m], ids.reshape(-1, k)[:m]


def search_staged(index: IvfPqIndex, queries: jax.Array, k: int,
                  params: Optional[SearchParams] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Stage-decomposed search for observability: coarse_quantize / lut /
    scan dispatch as separate programs, each under a recording
    :func:`raft_tpu.obs.span` — with sync mode on, spans attribute
    *device* time per stage (the fused :func:`search` cannot be timed
    stage-wise from the host). Exact f32-LUT per_query semantics,
    per_subspace codebooks only; results match ``search()``'s per_query
    path. ``search()`` routes here when obs stage mode is enabled;
    production paths never pay for the lost fusion."""
    if params is None:
        params = SearchParams()
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "queries must be [m, %d]", index.dim)
    expects(index.codebook_kind == "per_subspace",
            "search_staged supports per_subspace codebooks only")
    mt = resolve_metric(index.metric)
    ip_like = mt in (DistanceType.InnerProduct, DistanceType.CosineExpanded)
    n_probes = min(params.n_probes, index.n_lists)
    q_all = jnp.asarray(queries, jnp.float32)
    if mt == DistanceType.CosineExpanded:
        q_all = q_all / jnp.sqrt(jnp.maximum(
            jnp.sum(q_all * q_all, -1, keepdims=True), 1e-12))
    with span("coarse_quantize") as sp:
        qc, probes = _stage_coarse(index, q_all, n_probes, ip_like)
        sp.attach(qc, probes)
    with span("lut") as sp:
        q_rot, qlut = _stage_lut(index, q_all)
        sp.attach(q_rot, qlut)
    with span("scan") as sp:
        out = _stage_scan(index, q_rot, qlut, qc, probes, k, n_probes,
                          _fit_query_tile(params.query_tile, n_probes,
                                          index))
        sp.attach(out)
    return out


def _fit_query_tile(want: int, n_probes: int, index: IvfPqIndex) -> int:
    """Largest per_query tile ≤ ``want`` whose per-tile candidate tensors
    stay bounded: the f32 [t, n_probes, L, rot_dim] recon gather on the
    recon-dot path, or the unpacked codes + one-hot operand feed on the
    LUT path — sized on the wider of the two at 4 bytes."""
    L = index.max_list_size
    width = max(index.pq_dim,
                index.rot_dim if index.packed_recon is not None else 0)
    return max(1, min(want, (1 << 30) // max(1, n_probes * L * width * 4)))


# ---------------------------------------------------------------------------
# serialization (reference: neighbors/ivf_pq_serialize.cuh)
# ---------------------------------------------------------------------------

def save(index: IvfPqIndex, path: str) -> None:
    arrays = {"centers": index.centers,
              "centers_rot": index.centers_rot,
              "rotation": index.rotation,
              "codebooks": index.codebooks,
              "packed_codes": index.packed_codes,
              "packed_ids": index.packed_ids,
              "packed_norms": index.packed_norms,
              "list_sizes": index.list_sizes}
    # the bf16 cache is derived data — rebuilt on load, never serialized
    ser.save_arrays(path, "ivf_pq", _SERIAL_VERSION,
                    {"metric": index.metric,
                     "has_recon": index.packed_recon is not None,
                     "codebook_kind": index.codebook_kind,
                     "pq_bits": index.pq_bits,
                     "pq_dim": index.pq_dim}, arrays)


def load(path: str) -> IvfPqIndex:
    version, meta, a = ser.load_arrays(path, "ivf_pq")
    expects(version in (1, _SERIAL_VERSION),
            "unsupported ivf_pq version %d", version)
    # v1 files predate codebook_kind/pq_bits/packed codes: byte-per-
    # subspace per_subspace layout, recoverable from the defaults.
    # Billion-scale arrays upload in row slices (see to_device_chunked).
    pc = a["packed_codes"]
    pq_dim_meta = int(meta.get("pq_dim", 0)) or pc.shape[-1]
    nb = packed_nbytes(pq_dim_meta, int(meta.get("pq_bits", 8)))
    folded = pc.ndim == 3 and pc.shape[-1] != nb
    if (not folded and nb < 128 and pc.nbytes > (1 << 30)
            and (pc.shape[1] * nb) % 128 == 0):
        # lane-fold big code arrays (free row-major host view): a u8
        # trailing dim < 128 pads to 128 lanes on TPU — 2× the HBM
        pc = pc.reshape(pc.shape[0], -1, 128)
        folded = True
    packed_codes = ser.to_device_chunked(pc)
    index = IvfPqIndex(
        centers=jnp.asarray(a["centers"]),
        centers_rot=jnp.asarray(a["centers_rot"]),
        rotation=jnp.asarray(a["rotation"]),
        codebooks=jnp.asarray(a["codebooks"]),
        packed_codes=packed_codes,
        packed_ids=ser.to_device_chunked(a["packed_ids"]),
        packed_norms=ser.to_device_chunked(a["packed_norms"]),
        list_sizes=jnp.asarray(a["list_sizes"]),
        metric=meta["metric"],
        codebook_kind=meta.get("codebook_kind", "per_subspace"),
        pq_bits=int(meta.get("pq_bits", 8)),
        pq_dim_static=pq_dim_meta,
        codes_folded=folded)
    if meta.get("has_recon"):
        index = index.replace(packed_recon=_build_recon_cache(index))
    return index
