"""NN-Descent (GNND) — iterative knn-graph construction.

TPU-native counterpart of ``raft::neighbors::nn_descent``
(detail/nn_descent.cuh, 1452 LoC; GNND = GPU-parallel variant of Dong et
al.'s NN-Descent). Used as CAGRA's alternate graph-build backend
(cagra_types.hpp:47). Design mapping:

- the reference's per-node sampled local join (new/old neighbor lists,
  reverse-neighbor sampling, lock-free list updates) becomes a batched
  fixed-shape iteration: sample ``n_samples`` current neighbors per node,
  gather *their* neighbor lists (neighbor-of-neighbor candidates) plus a
  sampled set of reverse neighbors, compute all candidate distances with
  one MXU contraction, and merge into the running top-k with ``top_k`` —
  value-semantic instead of lock-free mutation;
- convergence: fixed ``n_iters`` sweeps (the reference's update-counter
  early exit maps to choosing n_iters; each sweep is cheap and fully
  fused).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.tracing import traced
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.utils.precision import get_precision


@partial(jax.jit, static_argnames=("k", "n_iters", "n_samples", "metric"))
def _nn_descent_impl(x: jax.Array, k: int, n_iters: int, n_samples: int,
                     metric: str, key: jax.Array):
    mt = resolve_metric(metric)
    ip = mt == DistanceType.InnerProduct
    n, d = x.shape
    xf = x.astype(jnp.float32)
    x_sq = jnp.sum(xf * xf, axis=1)
    BIG = jnp.float32(jnp.inf)

    def dists_to(ids):
        """ids [n, C] → distance(u, ids[u]) [n, C] (lower = better)."""
        rows = xf[ids]                                   # [n, C, d]
        s = jnp.einsum("nd,ncd->nc", xf, rows,
                       precision=get_precision(),
                       preferred_element_type=jnp.float32)
        if ip:
            return -s
        return jnp.maximum(x_sq[:, None] + x_sq[ids] - 2.0 * s, 0.0)

    def merge(ids_a, d_a, ids_b, d_b):
        """Merge candidate lists, dropping duplicates and self-edges."""
        ids = jnp.concatenate([ids_a, ids_b], axis=1)
        dd = jnp.concatenate([d_a, d_b], axis=1)
        dd = jnp.where(ids == jnp.arange(n)[:, None], BIG, dd)
        # first-occurrence dedupe
        eq = ids[:, :, None] == ids[:, None, :]
        C = ids.shape[1]
        earlier = jnp.tril(jnp.ones((C, C), jnp.bool_), -1)
        dd = jnp.where(jnp.any(eq & earlier[None], axis=2), BIG, dd)
        nd, pos = lax.top_k(-dd, k)
        return jnp.take_along_axis(ids, pos, axis=1), -nd

    # init: random graph
    k0, key = jax.random.split(key)
    init_ids = jax.random.randint(k0, (n, k), 0, n, jnp.int32)
    graph_ids, graph_d = merge(init_ids, dists_to(init_ids),
                               init_ids, jnp.full((n, k), BIG))

    def body(i, carry):
        graph_ids, graph_d = carry
        ki = jax.random.fold_in(key, i)
        # sample n_samples current neighbors per node
        sample_pos = jax.random.randint(ki, (n, n_samples), 0, k)
        sampled = jnp.take_along_axis(graph_ids, sample_pos, axis=1)  # [n, S]
        # neighbor-of-neighbor candidates
        non = graph_ids[sampled].reshape(n, n_samples * k)
        # TRUE reverse-neighbor candidates: nodes v whose sampled forward
        # edges point at u (the reference builds reverse lists from the
        # forward lists the same way, detail/nn_descent.cuh). One stable
        # sort inverts the [n·S] edge list; each node keeps up to S
        # reverse sources, overflow dropped, empty slots masked via self.
        # The edge list is shuffled first so a hub's kept sources are a
        # RANDOM subsample — a stable sort of the raw list would keep the
        # lowest source ids every iteration (systematic bias; the
        # reference subsamples reverse lists randomly too)
        kr = jax.random.fold_in(ki, 1)
        shuf = jax.random.permutation(kr, n * n_samples)
        targets = sampled.reshape(-1)[shuf]
        srcs = jnp.repeat(jnp.arange(n, dtype=jnp.int32), n_samples)[shuf]
        order = jnp.argsort(targets, stable=True)
        st = targets[order]
        starts = jnp.searchsorted(st, jnp.arange(n, dtype=jnp.int32))
        rank = (jnp.arange(n * n_samples, dtype=jnp.int32)
                - starts[st].astype(jnp.int32))
        rev = jnp.full((n, n_samples), -1, jnp.int32).at[st, rank].set(
            srcs[order], mode="drop")
        rev = jnp.where(rev < 0, jnp.arange(n, dtype=jnp.int32)[:, None], rev)
        cand = jnp.concatenate([non, rev], axis=1)
        cd = dists_to(cand)
        return merge(graph_ids, graph_d, cand, cd)

    graph_ids, graph_d = lax.fori_loop(0, n_iters, body, (graph_ids, graph_d))
    return graph_ids, graph_d


@traced("raft_tpu.nn_descent.build_knn_graph")
def build_knn_graph(
    dataset: jax.Array,
    k: int,
    metric: str = "sqeuclidean",
    n_iters: int = 20,
    n_samples: int = 8,
    seed: int = 0,
) -> jax.Array:
    """Build an approximate knn graph [n, k]
    (reference: nn_descent::build → index.graph())."""
    x = jnp.asarray(dataset, jnp.float32)
    ids, _ = _nn_descent_impl(x, k, n_iters, n_samples,
                              resolve_metric(metric).value,
                              jax.random.PRNGKey(seed))
    return ids


@traced("raft_tpu.nn_descent.build_knn_graph_with_distances")
def build_knn_graph_with_distances(
    dataset: jax.Array,
    k: int,
    metric: str = "sqeuclidean",
    n_iters: int = 20,
    n_samples: int = 8,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """As :func:`build_knn_graph` but also returns distances [n, k]."""
    x = jnp.asarray(dataset, jnp.float32)
    ids, dists = _nn_descent_impl(x, k, n_iters, n_samples,
                                  resolve_metric(metric).value,
                                  jax.random.PRNGKey(seed))
    return ids, dists
