"""CAGRA — graph-based ANN: knn-graph build + greedy traversal search.

TPU-native re-design of ``raft::neighbors::cagra`` (cagra.cuh:354;
build detail/cagra/cagra_build.cuh:47-89; optimize graph_core.cuh;
search cagra_search.cuh:105 + search_single_cta_kernel-inl.cuh). Paper:
arXiv:2308.15136 (cited in reference README.md:348). Design mapping:

- **build**: knn-graph from IVF-PQ search over the dataset itself + exact
  refine (the reference's default path, cagra_build.cuh:89-173), then
  ``optimize``: rank-based detourable-edge pruning + reverse-edge
  augmentation (graph_core.cuh) — expressed as batched gather/compare
  tensor ops instead of per-edge CUDA kernels;
- **search**: the reference runs one CTA per query doing a data-dependent
  greedy walk with a visited hashmap and a bitonic itopk buffer. A
  lockstep-SIMD machine wants *fixed-shape* iterations: we batch all
  queries and run a ``lax.while_loop`` whose body expands
  ``search_width`` parents per query (gather graph rows → gather vectors
  → one batched MXU contraction → mask-dedupe against the itopk buffer →
  ``top_k`` merge), with per-entry visited bits replacing the hashmap.
  Iterations stop when every query's top-k is settled (all-parents-
  visited), bounded by ``max_iterations``.

The itopk buffer doubles as the visited-dedup set: a candidate already in
the buffer is marked +inf before the merge. Entries are (dist, id,
visited-bit); parents are the best unvisited entries.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced
from raft_tpu.core import serialize as ser
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.neighbors import ivf_pq as _ivf_pq
from raft_tpu.neighbors.refine import refine as _refine
from raft_tpu.utils.precision import get_precision

_SERIAL_VERSION = 1


@dataclasses.dataclass
class IndexParams:
    """reference: ``cagra::index_params`` (cagra_types.hpp:47-60)."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    metric: str = "sqeuclidean"
    build_algo: str = "ivf_pq"  # | "nn_descent"
    nn_descent_niter: int = 20
    seed: int = 0


@dataclasses.dataclass
class SearchParams:
    """reference: ``cagra::search_params`` (cagra_types.hpp:54-112).

    ``num_seeds``: random entry points sampled per query (the
    ``num_random_samplings``/rand_xor_mask analog). 0 → auto, scaled
    with index size: a graph over strongly clustered data is near-
    disconnected across clusters, so greedy traversal only finds a
    query's cluster if some entry lands in it — entry count is the
    recall floor, and it must grow with n (measured: recall 0.35 at
    n=100k with 128 seeds on 316-cluster data; the miss probability
    (1 - c/n_clusters)^seeds matches exactly)."""

    itopk_size: int = 64
    search_width: int = 4
    max_iterations: int = 0   # 0 → auto: ceil(itopk/search_width) * 2
    query_tile: int = 256
    seed: int = 0             # entry-point sampling (rand_xor_mask analog)
    num_seeds: int = 0        # 0 → auto: max(2·itopk, min(2048, n/64))


class CagraIndex(flax.struct.PyTreeNode):
    """reference: ``cagra::index`` (cagra_types.hpp)."""

    dataset: jax.Array   # [n, dim]
    graph: jax.Array     # [n, graph_degree] i32
    metric: str = flax.struct.field(pytree_node=False, default="sqeuclidean")

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

def build_knn_graph(dataset: jax.Array, k: int, metric: str = "sqeuclidean",
                    seed: int = 0, search_batch: int = 16384) -> jax.Array:
    """k-NN graph via IVF-PQ self-search + exact refine
    (reference: cagra_build.cuh:89 build_knn_graph — ivf_pq::build, batched
    search with gpu_top_k = k·refine_rate :102, refine :173).

    The self-search runs in ``search_batch`` query chunks, as the
    reference does: one all-rows batch would give the grouped scan an
    O(n·n_probes/n_lists) per-list queue and blow HBM at 100k+ rows."""
    x = jnp.asarray(dataset, jnp.float32)
    n, d = x.shape
    n_lists = max(8, min(1024, int(np.sqrt(n) / 2) or 8))
    pq_dim = max(8, min(d, -(-d // 2 // 8) * 8))
    idx = _ivf_pq.build(x, _ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=pq_dim, pq_bits=8, metric=metric,
        kmeans_trainset_fraction=min(1.0, 10.0 * n_lists / n + 0.1),
        seed=seed))
    gpu_top_k = min(n, 2 * (k + 1))  # refine_rate 2
    n_probes = max(2, n_lists // 8)
    sp = _ivf_pq.SearchParams(n_probes=n_probes)
    b = min(search_batch, n)
    knn_parts = []
    for start in range(0, n, b):
        q = x[start:start + b]
        if q.shape[0] < b:  # pad the tail chunk: one compiled shape
            q = jnp.pad(q, ((0, b - q.shape[0]), (0, 0)))
        _, cand = _ivf_pq.search(idx, q, gpu_top_k, sp)
        _, ids = _refine(x, q, cand, k + 1, metric=metric)
        knn_parts.append(ids)
    knn_ids = jnp.concatenate(knn_parts, axis=0)[:n]
    # drop self-edges: if a row's first hit is itself, skip it, else drop last
    self_col = knn_ids == jnp.arange(n, dtype=knn_ids.dtype)[:, None]
    # stable partition: non-self entries first, keep k of them
    order = jnp.argsort(self_col, axis=1, stable=True)  # False (non-self) first
    cleaned = jnp.take_along_axis(knn_ids, order, axis=1)[:, :k]
    return cleaned.astype(jnp.int32)


@partial(jax.jit, static_argnames=("out_degree",))
def optimize_graph(knn_graph: jax.Array, out_degree: int) -> jax.Array:
    """Detourable-edge pruning + reverse-edge augmentation
    (reference: graph_core.cuh optimize, 572 LoC; CAGRA paper §4.1).

    Edge u→v (rank i in u's list) is *detourable* through w (rank j<i) if
    v also appears in w's own neighbor list at a rank < i — i.e. the
    two-hop path u→w→v uses strictly closer edges. Edges with the fewest
    detour paths are kept; half the output degree is then filled with
    reverse edges (incoming links), which CAGRA shows is what makes the
    graph navigable.
    """
    n, D = knn_graph.shape
    d_half = out_degree // 2

    def detour_counts(u_list):
        # u_list: [D] neighbor ids sorted by distance rank
        nbr_lists = knn_graph[u_list]                     # [D, D] lists of w=u_list[j]
        # pos[j, i] = rank of u_list[i] in w_j's list (D if absent)
        eq = nbr_lists[:, :, None] == u_list[None, None, :]  # [D(j), D(pos), D(i)]
        pos = jnp.min(jnp.where(eq, jnp.arange(D)[None, :, None], D), axis=1)  # [D(j), D(i)]
        ranks = jnp.arange(D)
        # detour via w_j for edge i: j < i  AND  pos[j, i] < i
        detour = (ranks[:, None] < ranks[None, :]) & (pos < ranks[None, :])
        return jnp.sum(detour, axis=0)                    # [D] counts per edge i

    counts = lax.map(detour_counts, knn_graph, batch_size=256)  # [n, D]
    # keep lowest-detour-count edges, tie-broken by distance rank
    score = counts.astype(jnp.int32) * D + jnp.arange(D, dtype=jnp.int32)[None, :]
    keep = jnp.argsort(score, axis=1)[:, :out_degree]
    pruned = jnp.take_along_axis(knn_graph, keep, axis=1)  # [n, out_degree]

    # reverse-edge augmentation: for each node, gather up to d_half incoming
    # edges (from the pruned forward graph) and splice them after the
    # d_half best forward edges (graph_core.cuh rev_graph).
    fwd = pruned[:, :d_half]
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32)[:, None], d_half, 1).reshape(-1)
    dst = fwd.reshape(-1)
    # count and slot reverse edges per destination node
    order = jnp.argsort(dst, stable=True)
    dst_s, src_s = dst[order], src[order]
    # position of each edge within its destination group
    first_idx = jnp.searchsorted(dst_s, jnp.arange(n))
    slot = jnp.arange(dst_s.shape[0]) - first_idx[dst_s]
    rev = jnp.full((n, d_half), -1, jnp.int32)
    valid = slot < d_half
    # out-of-quota reverse edges write to row n → dropped
    rev = rev.at[jnp.where(valid, dst_s, n),
                 jnp.clip(slot, 0, d_half - 1)].set(src_s, mode="drop")
    # final graph: best forward half + reverse half (fall back to forward
    # edges where no reverse edge exists)
    fallback = pruned[:, d_half:out_degree]
    merged = jnp.where(rev >= 0, rev, fallback)
    return jnp.concatenate([fwd, merged], axis=1)


@traced("raft_tpu.cagra.build")
def build(dataset: jax.Array, params: Optional[IndexParams] = None) -> CagraIndex:
    """Build (reference: cagra::build, cagra.cuh — knn-graph + optimize)."""
    if params is None:
        params = IndexParams()
    mt = resolve_metric(params.metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct),
            "cagra supports sqeuclidean/euclidean/inner_product")
    x = jnp.asarray(dataset, jnp.float32)
    n = x.shape[0]
    inter_d = min(params.intermediate_graph_degree, n - 1)
    out_d = min(params.graph_degree, inter_d)
    if params.build_algo == "nn_descent":
        from raft_tpu.neighbors.nn_descent import build_knn_graph as _nnd
        knn = _nnd(x, inter_d, metric=mt.value, n_iters=params.nn_descent_niter,
                   seed=params.seed)
    else:
        knn = build_knn_graph(x, inter_d, metric=mt.value, seed=params.seed)
    graph = optimize_graph(knn, out_d)
    return CagraIndex(dataset=x, graph=graph, metric=mt.value)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k", "itopk_size", "search_width",
                                   "max_iterations", "query_tile", "seed",
                                   "num_seeds"))
def _search_impl(index: CagraIndex, queries: jax.Array, k: int,
                 itopk_size: int, search_width: int, max_iterations: int,
                 query_tile: int, seed: int = 0, num_seeds: int = 0,
                 filter_bits=None):
    mt = resolve_metric(index.metric)
    ip = mt == DistanceType.InnerProduct
    sqrt_out = mt == DistanceType.L2SqrtExpanded
    x = index.dataset
    n, d = x.shape
    deg = index.graph_degree
    m = queries.shape[0]
    q_all = jnp.asarray(queries, jnp.float32)
    BIG = jnp.float32(jnp.inf)
    x_sq = jnp.sum(x * x, axis=1)

    def dists_to(q, ids):
        """q [t, d], ids [t, C] → metric scores [t, C] (lower = better)."""
        rows = x[ids]                                     # [t, C, d]
        s = jnp.einsum("td,tcd->tc", q, rows,
                       precision=get_precision(),
                       preferred_element_type=jnp.float32)
        if ip:
            return -s
        return jnp.maximum(jnp.sum(q * q, 1)[:, None] + x_sq[ids] - 2.0 * s, 0.0)

    base_key = jax.random.PRNGKey(seed)

    def search_tile(q, qstart):
        t = q.shape[0]
        # entry points are a per-QUERY pseudo-random function of (seed,
        # global query index) — the reference hashes query id through
        # rand_xor_mask the same way — so results are independent of query
        # tiling and entry sets are decorrelated across queries
        qidx = qstart + jnp.arange(t, dtype=jnp.uint32)
        keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(qidx)
        # oversample candidates and keep the best itopk — the reference's
        # random_sampling makes multiple hashed draws per itopk slot the
        # same way (compute_random_samples / num_random_samplings). The
        # count scales with n (see SearchParams.num_seeds): entry
        # coverage is the recall floor on clustered data
        # clamp: the buffer init takes top itopk of the seeds, so fewer
        # seeds than itopk slots would break lax.top_k; round to a
        # multiple of 128 so the seed phase can chunk evenly
        n_seed = max(num_seeds or max(2 * itopk_size, min(2048, n // 64)),
                     itopk_size)
        n_seed = -(-n_seed // 128) * 128
        init_ids = jax.vmap(
            lambda kk: jax.random.randint(kk, (n_seed,), 0, n))(keys)
        # sampled with replacement: demote duplicate entry slots so an id
        # can never surface twice in the buffer. Sort-based dedup — the
        # quadratic pairwise mask would be O(n_seed²) per query
        order = jnp.argsort(init_ids, axis=1)
        sorted_ids = jnp.take_along_axis(init_ids, order, axis=1)
        dup_sorted = jnp.concatenate(
            [jnp.zeros((t, 1), jnp.bool_),
             sorted_ids[:, 1:] == sorted_ids[:, :-1]], axis=1)
        inv = jnp.argsort(order, axis=1)
        dup0 = jnp.take_along_axis(dup_sorted, inv, axis=1)
        # chunk the seed-distance gather: at n_seed=2048 an unchunked
        # x[init_ids] would materialize [t, n_seed, d] (GBs at large d);
        # lax.map bounds the intermediate to one chunk
        if n_seed > 512:
            c = 512
            while n_seed % c:
                c -= 128  # n_seed is a multiple of 128
            ids_r = jnp.transpose(
                init_ids.reshape(t, n_seed // c, c), (1, 0, 2))
            seed_d = jnp.transpose(
                lax.map(lambda ii: dists_to(q, ii), ids_r),
                (1, 0, 2)).reshape(t, n_seed)
        else:
            seed_d = dists_to(q, init_ids)
        seed_d = jnp.where(dup0, BIG, seed_d)
        _, best = lax.top_k(-seed_d, itopk_size)
        init_ids = jnp.take_along_axis(init_ids, best, axis=1)
        buf_d = jnp.take_along_axis(seed_d, best, axis=1)
        if filter_bits is not None:
            from raft_tpu.neighbors.sample_filter import passes

            # filtered vectors score +inf so they never rank in the itopk
            # nor get expanded — the exclusion point the reference's
            # cagra sample_filter hooks
            buf_d = jnp.where(passes(filter_bits, init_ids), buf_d, BIG)
        buf_i = init_ids.astype(jnp.int32)
        order = jnp.argsort(buf_d, axis=1)
        buf_d = jnp.take_along_axis(buf_d, order, 1)
        buf_i = jnp.take_along_axis(buf_i, order, 1)
        buf_v = jnp.zeros((t, itopk_size), jnp.bool_)     # visited bits

        def cond(state):
            _, _, buf_v, it = state
            # stop when every query's whole itopk buffer is visited
            # (the reference iterates until the itopk converges)
            return (it < max_iterations) & ~jnp.all(buf_v)

        def body(state):
            buf_d, buf_i, buf_v, it = state
            # freeze settled queries (whole buffer visited): their updates
            # are discarded, so results don't depend on query tiling
            frozen = jnp.all(buf_v, axis=1)
            old = (buf_d, buf_i, buf_v)
            # 1. pick search_width best unvisited parents
            cand_score = jnp.where(buf_v, BIG, buf_d)
            _, parent_pos = lax.top_k(-cand_score, search_width)   # [t, W]
            parent_ids = jnp.take_along_axis(buf_i, parent_pos, 1)
            parent_valid = jnp.take_along_axis(cand_score, parent_pos, 1) < BIG
            # mark visited
            buf_v = buf_v.at[jnp.arange(t)[:, None], parent_pos].set(True)
            # 2. expand: gather graph rows of parents → [t, W·deg]
            nbrs = index.graph[jnp.clip(parent_ids, 0, n - 1)]     # [t, W, deg]
            nbrs = nbrs.reshape(t, search_width * deg)
            nbrs = jnp.where(jnp.repeat(parent_valid, deg, axis=1), nbrs, 0)
            # 3. distances on the MXU
            nd = dists_to(q, nbrs)
            nd = jnp.where(jnp.repeat(parent_valid, deg, axis=1), nd, BIG)
            if filter_bits is not None:
                from raft_tpu.neighbors.sample_filter import passes

                nd = jnp.where(passes(filter_bits, nbrs), nd, BIG)
            # 4. dedupe against the buffer (the visited-hashmap stand-in)
            dup = jnp.any(nbrs[:, :, None] == buf_i[:, None, :], axis=2)
            nd = jnp.where(dup, BIG, nd)
            # dedupe within the candidate set (first occurrence wins)
            eq = nbrs[:, :, None] == nbrs[:, None, :]
            earlier = jnp.tril(jnp.ones((search_width * deg,) * 2, jnp.bool_), -1)
            nd = jnp.where(jnp.any(eq & earlier[None], axis=2), BIG, nd)
            # 5. merge into itopk: concat + select
            all_d = jnp.concatenate([buf_d, nd], axis=1)
            all_i = jnp.concatenate([buf_i, nbrs.astype(jnp.int32)], axis=1)
            all_v = jnp.concatenate(
                [buf_v, jnp.zeros_like(nd, dtype=jnp.bool_)], axis=1)
            _, pos = lax.top_k(-all_d, itopk_size)
            buf_d = jnp.take_along_axis(all_d, pos, 1)
            buf_i = jnp.take_along_axis(all_i, pos, 1)
            buf_v = jnp.take_along_axis(all_v, pos, 1)
            buf_d = jnp.where(frozen[:, None], old[0], buf_d)
            buf_i = jnp.where(frozen[:, None], old[1], buf_i)
            buf_v = jnp.where(frozen[:, None], old[2], buf_v)
            return buf_d, buf_i, buf_v, it + 1

        buf_d, buf_i, _, _ = lax.while_loop(
            cond, body, (buf_d, buf_i, buf_v, jnp.array(0, jnp.int32)))
        out_d, out_i = buf_d[:, :k], buf_i[:, :k]
        if filter_bits is not None:
            # inf-score slots are filtered/unreached: mark their ids -1
            # (same pad convention as brute-force/IVF)
            out_i = jnp.where(jnp.isinf(out_d), -1, out_i)
        if ip:
            out_d = -out_d
        elif sqrt_out:
            out_d = jnp.sqrt(out_d)
        return out_d, out_i

    if m <= query_tile:
        return search_tile(q_all, jnp.uint32(0))
    n_tiles = -(-m // query_tile)
    pad = n_tiles * query_tile - m
    qp = jnp.pad(q_all, ((0, pad), (0, 0)))
    starts = (jnp.arange(n_tiles, dtype=jnp.uint32) * query_tile)
    vals, ids = lax.map(lambda args: search_tile(*args),
                        (qp.reshape(n_tiles, query_tile, d), starts))
    return vals.reshape(-1, k)[:m], ids.reshape(-1, k)[:m]


@traced("raft_tpu.cagra.search")
def search(index: CagraIndex, queries: jax.Array, k: int,
           params: Optional[SearchParams] = None,
           filter_bitset: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Search (reference: cagra::search → search_main, cagra_search.cuh:105;
    filtered overload via CagraSampleFilterT).
    ``filter_bitset``: optional packed bitset over dataset rows (see
    neighbors.sample_filter) — cleared bits are excluded."""
    if params is None:
        params = SearchParams()
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "queries must be [m, %d]", index.dim)
    itopk = max(params.itopk_size, k)
    max_it = params.max_iterations or 2 * (-(-itopk // params.search_width))
    return _search_impl(index, queries, k, itopk, params.search_width,
                        max_it, params.query_tile, seed=params.seed,
                        num_seeds=params.num_seeds,
                        filter_bits=filter_bitset)


# ---------------------------------------------------------------------------
# serialization (reference: neighbors/cagra_serialize.cuh)
# ---------------------------------------------------------------------------

def save(index: CagraIndex, path: str, include_dataset: bool = True) -> None:
    arrays = {"graph": index.graph}
    if include_dataset:
        arrays["dataset"] = index.dataset
    ser.save_arrays(path, "cagra", _SERIAL_VERSION,
                    {"metric": index.metric}, arrays)


def load(path: str, dataset: Optional[jax.Array] = None) -> CagraIndex:
    version, meta, a = ser.load_arrays(path, "cagra")
    expects(version == _SERIAL_VERSION, "unsupported cagra version %d", version)
    ds = jnp.asarray(a["dataset"]) if "dataset" in a else jnp.asarray(dataset)
    return CagraIndex(dataset=ds, graph=jnp.asarray(a["graph"]),
                      metric=meta["metric"])


def serialize_to_hnswlib(index: CagraIndex, path: str,
                         ef_construction: int = 200) -> None:
    """Export the CAGRA graph as an hnswlib-loadable index file
    (reference capability: cagra_serialize serialize_to_hnswlib — a
    flat level-0-only HNSW whose neighbor lists are the CAGRA graph).

    Binary layout follows hnswlib's ``HierarchicalNSW::saveIndex``
    (hnswalg.h): header of size_t/int fields, then per-element level-0
    blocks ``[link_count u16 + pad u16][maxM0 x u32 links][f32 data]
    [u64 label]``, then a zero u32 per element (no upper levels).
    Loadable with ``hnswlib.Index(space, dim).load_index(path)`` where
    space is "l2" for (sq)euclidean and "ip" for inner_product.
    """
    import struct

    expects(resolve_metric(index.metric) in
            (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
             DistanceType.InnerProduct),
            "hnswlib export supports l2/inner_product metrics, not %s",
            index.metric)
    data = np.ascontiguousarray(np.asarray(index.dataset), np.float32)
    graph = np.asarray(index.graph, np.int64)
    n, dim = data.shape
    degree = graph.shape[1]

    max_m0 = degree               # level-0 out-degree = graph degree
    m = max(1, degree // 2)
    data_size = dim * 4
    size_links0 = max_m0 * 4 + 4  # u32 count-word + maxM0 u32 links
    size_per_elem = size_links0 + data_size + 8  # + u64 label
    offset_data = size_links0
    label_offset = size_links0 + data_size
    mult = 1.0 / np.log(max(m, 2))

    # hnswlib reads the first `count` links, so valid ids must be
    # compacted to the front (graph rows can carry interior -1 entries
    # when the knn stage returned fewer than degree candidates)
    valid = graph >= 0
    counts = np.sum(valid, axis=1).astype(np.uint16)
    front = np.argsort(~valid, axis=1, kind="stable")  # valid-first, ordered
    links = np.take_along_axis(np.where(valid, graph, 0), front,
                               axis=1).astype(np.uint32)

    with open(path, "wb") as f:
        f.write(struct.pack("<QQQQQQiIQQQdQ",
                            0,              # offsetLevel0_
                            n,              # max_elements_
                            n,              # cur_element_count
                            size_per_elem,  # size_data_per_element_
                            label_offset,   # label_offset_
                            offset_data,    # offsetData_
                            0,              # maxlevel_
                            0,              # enterpoint_node_
                            m,              # maxM_
                            max_m0,         # maxM0_
                            m,              # M_
                            float(mult),    # mult_
                            ef_construction))
        # level-0 blocks, assembled vectorized then written once
        block = np.zeros((n, size_per_elem), np.uint8)
        block[:, 0:2] = counts[:, None].view(np.uint8).reshape(n, 2)
        block[:, 4:4 + max_m0 * 4] = links.view(np.uint8).reshape(n, -1)
        block[:, offset_data:offset_data + data_size] = data.view(
            np.uint8).reshape(n, -1)
        block[:, label_offset:] = np.arange(n, dtype=np.uint64).view(
            np.uint8).reshape(n, 8)
        f.write(block.tobytes())
        # one u32 per element: no higher-level link lists
        f.write(np.zeros(n, np.uint32).tobytes())
