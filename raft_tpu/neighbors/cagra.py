"""CAGRA — graph-based ANN: knn-graph build + greedy traversal search.

TPU-native re-design of ``raft::neighbors::cagra`` (cagra.cuh:354;
build detail/cagra/cagra_build.cuh:47-89; optimize graph_core.cuh;
search cagra_search.cuh:105 + search_single_cta_kernel-inl.cuh). Paper:
arXiv:2308.15136 (cited in reference README.md:348). Design mapping:

- **build**: knn-graph from IVF-PQ search over the dataset itself + exact
  refine (the reference's default path, cagra_build.cuh:89-173), then
  ``optimize``: rank-based detourable-edge pruning + reverse-edge
  augmentation (graph_core.cuh) — expressed as batched gather/compare
  tensor ops instead of per-edge CUDA kernels;
- **search**: the reference runs one CTA per query doing a data-dependent
  greedy walk with a visited hashmap and a bitonic itopk buffer. A
  lockstep-SIMD machine wants *fixed-shape* iterations: we batch all
  queries and run a ``lax.while_loop`` whose body expands
  ``search_width`` parents per query (gather graph rows → gather vectors
  → one batched MXU contraction → mask-dedupe against the itopk buffer →
  ``top_k`` merge), with per-entry visited bits replacing the hashmap.
  Iterations stop when every query's top-k is settled (all-parents-
  visited), bounded by ``max_iterations``.

The itopk buffer doubles as the visited-dedup set: a candidate already in
the buffer is marked +inf before the merge. Entries are (dist, id,
visited-bit); parents are the best unvisited entries.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced, span
from raft_tpu.core import ids as _ids
from raft_tpu.core import serialize as ser
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.neighbors import ivf_pq as _ivf_pq
from raft_tpu.neighbors.refine import refine as _refine
from raft_tpu.utils.precision import get_precision

_SERIAL_VERSION = 3  # v3: + int8 scalar-quantized traversal rows


@dataclasses.dataclass
class IndexParams:
    """reference: ``cagra::index_params`` (cagra_types.hpp:47-60)."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    metric: str = "sqeuclidean"
    # "auto" → "cluster": the TPU-native cluster-blocked exact self-kNN
    # (see cluster_knn_graph). The reference's two build algos remain
    # selectable: "ivf_pq" (ANN self-search + refine, cagra_build.cuh:89)
    # and "nn_descent" (GNND).
    build_algo: str = "auto"  # | "cluster" | "ivf_pq" | "nn_descent"
    nn_descent_niter: int = 20
    # cluster build algo tuning (cluster_knn_graph): each row's exact
    # candidate scan covers the members of its list's `knn_neighborhood`
    # nearest lists of ~`knn_rows_per_list` rows each. On data whose
    # true neighborhoods span many kmeans cells (e.g. thousands of tiny
    # natural clusters), 16 lists cover only ~0.89 of true edges —
    # raising the neighborhood (or shrinking lists) trades build FLOPs
    # for graph recall exactly like IVF n_probes at search time
    knn_rows_per_list: int = 1024
    knn_neighborhood: int = 16
    # graph-BUILD dimensionality: 0 = full-d; "auto" (-1) projects
    # wide datasets (d > 256) onto a random orthonormal 128-d basis
    # for the candidate scans only — the cluster-blocked build's block
    # gathers scale with d (≈96 GB of HBM traffic at 1M×960), while
    # 128-d projections preserve neighbor RANKS well enough for graph
    # candidates; the searched dataset stays full precision
    build_projection_dim: int = -1  # -1 auto | 0 off | explicit dim
    # store int8 scalar-quantized rows beside the f32 dataset (the
    # CAGRA-Q compression analog). OPT-IN like the reference's
    # compression param: it costs +n·d bytes of HBM and, via
    # SearchParams.traverse="auto", changes default search results
    # (int8 traversal trades ~3e-3 recall for ~1.8×/hop bandwidth)
    quantize_dataset: bool = False
    seed: int = 0


@dataclasses.dataclass
class SearchParams:
    """reference: ``cagra::search_params`` (cagra_types.hpp:54-112).

    ``num_seeds``: random entry points sampled per query (the
    ``num_random_samplings``/rand_xor_mask analog). 0 → auto. On an
    index with cluster-seeded entries (default build; see CagraIndex)
    the auto count is max(itopk, 512) random pads on top of the
    nearest-cluster entry points — coverage comes from the entries, not
    the randoms. Without entries (reference build algos) the auto count
    scales with n (max(2·itopk, min(2048, n/64))): a graph over
    strongly clustered data is near-disconnected across clusters, so
    greedy traversal only finds a query's cluster if some random entry
    lands in it — the miss probability (1 - c/n_clusters)^seeds is the
    recall floor (measured: recall 0.35 at n=100k with 128 seeds on
    316-cluster data)."""

    itopk_size: int = 64
    search_width: int = 4
    max_iterations: int = 0   # 0 → auto: ceil(itopk/search_width) * 2
    query_tile: int = 1024
    seed: int = 0             # entry-point sampling (rand_xor_mask analog)
    num_seeds: int = 0        # 0 → auto (see class docstring)
    # cluster-seeded entries: how many nearest clusters contribute
    # entry points (indexes built by the cluster algo). On many-tiny-
    # cluster data the query's true neighborhood spans more kmeans
    # cells than 4 — raising this widens initial coverage the same way
    # n_probes does for IVF (cost: entry_clusters·E seed distances)
    entry_clusters: int = 4
    # traversal dataset precision: "auto" uses the index's int8
    # scalar-quantized rows when present (the CAGRA-Q direction —
    # traversal is HBM-gather-bound, int8 rows move 4× fewer bytes,
    # measured ~1.8× faster per hop) with an exact f32 re-rank of the
    # final buffer; "f32" forces full-precision traversal
    traverse: str = "auto"    # | "f32" | "int8"
    # within-candidate dedup strategy: "pairwise" materializes the
    # [t, C, C] equality mask, "sort" uses two C-wide argsorts
    dedup: str = "pairwise"   # | "sort"


class CagraIndex(flax.struct.PyTreeNode):
    """reference: ``cagra::index`` (cagra_types.hpp).

    ``centers``/``entry_ids`` are a TPU-native extension the cluster
    build algo provides for free: greedy graph traversal over strongly
    clustered data only reaches a query's cluster if an entry point
    lands in it, so random entries put a coverage floor on recall
    (≈ 1 − e^{−seeds/n_clusters}). Seeding from the nearest clusters'
    members removes that floor AND needs far fewer seed distances.
    ``None`` (reference build algos) falls back to random entries."""

    dataset: jax.Array   # [n, dim]
    graph: jax.Array     # [n, graph_degree] i32
    metric: str = flax.struct.field(pytree_node=False, default="sqeuclidean")
    centers: Optional[jax.Array] = None    # [n_lists, dim] f32
    entry_ids: Optional[jax.Array] = None  # [n_lists, E] i32, -1 pad
    # int8 scalar-quantized rows for gather-bound traversal (CAGRA-Q
    # analog): x ≈ q_zero + q_scale · code, per-dimension affine
    dataset_q: Optional[jax.Array] = None  # [n, dim] int8
    q_scale: Optional[jax.Array] = None    # [dim] f32
    q_zero: Optional[jax.Array] = None     # [dim] f32

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

@traced("raft_tpu.cagra.build_knn_graph")
def build_knn_graph(dataset: jax.Array, k: int, metric: str = "sqeuclidean",
                    seed: int = 0, search_batch: int = 16384) -> jax.Array:
    """k-NN graph via IVF-PQ self-search + exact refine
    (reference: cagra_build.cuh:89 build_knn_graph — ivf_pq::build, batched
    search with gpu_top_k = k·refine_rate :102, refine :173).

    The self-search runs in ``search_batch`` query chunks, as the
    reference does: one all-rows batch would give the grouped scan an
    O(n·n_probes/n_lists) per-list queue and blow HBM at 100k+ rows."""
    x = jnp.asarray(dataset, jnp.float32)
    n, d = x.shape
    n_lists = max(8, min(1024, int(np.sqrt(n) / 2) or 8))
    pq_dim = max(8, min(d, -(-d // 2 // 8) * 8))
    idx = _ivf_pq.build(x, _ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=pq_dim, pq_bits=8, metric=metric,
        kmeans_trainset_fraction=min(1.0, 10.0 * n_lists / n + 0.1),
        seed=seed))
    gpu_top_k = min(n, 2 * (k + 1))  # refine_rate 2
    n_probes = max(2, n_lists // 8)
    sp = _ivf_pq.SearchParams(n_probes=n_probes)
    b = min(search_batch, n)
    knn_parts = []
    for start in range(0, n, b):
        q = x[start:start + b]
        if q.shape[0] < b:  # pad the tail chunk: one compiled shape
            q = jnp.pad(q, ((0, b - q.shape[0]), (0, 0)))
        _, cand = _ivf_pq.search(idx, q, gpu_top_k, sp)
        # the exact re-rank rides neighbors.refine's dispatch tier (the
        # fused gather-refine kernel once gpu_top_k reaches the
        # oversampled regime; XLA einsum below it)
        _, ids = _refine(x, q, cand, k + 1, metric=metric)
        knn_parts.append(ids)
    knn_ids = jnp.concatenate(knn_parts, axis=0)[:n]
    return _drop_self_edges(knn_ids, n, k)


@partial(jax.jit, static_argnames=("k", "n_lists", "T", "chunk", "ip"))
def _cluster_blocked_knn(packed, pids, centers, row_list, row_slot,
                         k: int, n_lists: int, T: int, chunk: int,
                         ip: bool):
    """Exact kNN of every row against its cluster neighborhood — one
    jitted program. ``packed [n_lists, L, d]`` / ``pids [n_lists, L]``
    are the balanced-kmeans-packed rows; each list's members scan the
    members of its ``T`` nearest lists with one batched MXU contraction
    per list chunk, and ``approx_min_k`` (the TPU-native top-k) selects
    ``k`` candidates per row. Results return in row order via the
    (list, slot) addresses."""
    nbc = lax.dot_general(centers, centers, (((1,), (1,)), ((), ())),
                          precision=get_precision(),
                          preferred_element_type=jnp.float32)
    c_sq = jnp.sum(centers * centers, axis=1)
    cd = c_sq[:, None] + c_sq[None, :] - 2.0 * nbc
    _, nbrs = lax.top_k(-cd, T)                            # [n_lists, T]

    L = packed.shape[1]
    n_chunks = -(-n_lists // chunk)
    nsp = n_chunks * chunk
    lists_pad = jnp.pad(jnp.arange(n_lists, dtype=jnp.int32),
                        (0, nsp - n_lists))

    def scan_chunk(ls):
        mem = packed[ls].astype(jnp.float32)               # [C, L, d]
        mids = pids[ls]                                    # [C, L]
        nb = nbrs[ls]                                      # [C, T]
        cand = packed[nb].astype(jnp.float32).reshape(
            ls.shape[0], T * L, -1)                        # [C, T·L, d]
        cids = pids[nb].reshape(ls.shape[0], T * L)        # [C, T·L]
        s = jnp.einsum("cld,cmd->clm", mem, cand,
                       precision=get_precision(),
                       preferred_element_type=jnp.float32)
        if ip:
            score = s                                      # maximize
        else:
            m_sq = jnp.sum(mem * mem, axis=-1)
            q_sq = jnp.sum(cand * cand, axis=-1)
            score = -(m_sq[:, :, None] + q_sq[:, None, :] - 2.0 * s)
        bad = (cids[:, None, :] < 0) | (cids[:, None, :] == mids[:, :, None])
        score = jnp.where(bad, -jnp.inf, score)
        _, pos = jax.lax.approx_max_k(
            score.reshape(-1, T * L), k, recall_target=0.95)
        ids = jnp.take_along_axis(
            jnp.repeat(cids, L, axis=0), pos, axis=1)      # [C·L, k]
        return ids.reshape(ls.shape[0], L, k)

    res = lax.map(scan_chunk, lists_pad.reshape(n_chunks, chunk))
    res = res.reshape(nsp, L, k)
    return res[row_list, row_slot]                         # [n, k]


@partial(jax.jit, static_argnames=("k", "ip", "chunk"))
def _overflow_knn(x, packed, pids, rows, lists, k: int, ip: bool,
                  chunk: int):
    """Exact kNN of overflow rows against their own cluster blocks:
    q [o, d] vs packed[lists] [o, L, d] → ids [o, k]. Chunked over rows
    so the [chunk, L, d] block gather stays memory-bounded — heavy skew
    (the only trigger of this path) can overflow many rows at once."""
    L = packed.shape[1]

    def one_chunk(args):
        rows_c, lists_c = args
        q = x[rows_c].astype(jnp.float32)                 # [c, d]
        blk = packed[lists_c].astype(jnp.float32)         # [c, L, d]
        bids = pids[lists_c]                              # [c, L]
        s = jnp.einsum("od,old->ol", q, blk,
                       precision=get_precision(),
                       preferred_element_type=jnp.float32)
        if ip:
            score = s
        else:
            score = -(jnp.sum(blk * blk, -1) - 2.0 * s)   # rank-equivalent
        bad = (bids < 0) | (bids == rows_c[:, None])
        score = jnp.where(bad, -jnp.inf, score)
        _, pos = lax.top_k(score, k)
        return jnp.take_along_axis(bids, pos, axis=1).astype(jnp.int32)

    o = rows.shape[0]
    if o <= chunk:
        return one_chunk((rows, lists))
    n_chunks = -(-o // chunk)
    pad = n_chunks * chunk - o
    rows_p = jnp.pad(rows, (0, pad), mode="edge")
    lists_p = jnp.pad(lists, (0, pad), mode="edge")
    out = lax.map(one_chunk, (rows_p.reshape(n_chunks, chunk),
                              lists_p.reshape(n_chunks, chunk)))
    return out.reshape(n_chunks * chunk, k)[:o]


def cluster_knn_graph(dataset: jax.Array, k: int, metric: str = "sqeuclidean",
                      seed: int = 0, rows_per_list: int = 1024,
                      neighborhood: int = 16, return_entries: bool = False,
                      centers_from: Optional[jax.Array] = None):
    """TPU-native k-NN graph: cluster-blocked exact self-kNN.

    The reference builds CAGRA's knn graph by ANN self-search (IVF-PQ +
    refine, cagra_build.cuh:89) — a per-query gather/scan structure. On
    TPU the natural shape is block-dense: balanced-kmeans the rows into
    ~n/1024 lists, then give each list's members EXACT brute-force
    distances against the members of its ``neighborhood`` nearest lists
    — large square MXU contractions, no codes, no refine pass. Candidate
    coverage matches an IVF search probing ``neighborhood`` lists; the
    distances are exact f32 (better rank quality than PQ+refine), and
    graph build time at 1M×128 drops from tens of minutes to ~1 minute
    on a v5e.
    """
    x = jnp.asarray(dataset, jnp.float32)
    n, d = x.shape
    mt = resolve_metric(metric)
    ip = mt == DistanceType.InnerProduct
    if n <= (1 << 14) or n // rows_per_list < 4:
        # small corpus: plain exact kNN (one tiled program)
        from raft_tpu.neighbors import brute_force as _bf

        idx = _bf.build(x, metric="inner_product" if ip else "sqeuclidean")
        _, knn_ids = _bf.knn(idx, x, min(n, k + 1))
        g = _drop_self_edges(knn_ids, n, k)
        return (g, None, None) if return_entries else g

    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
    from raft_tpu.neighbors import ivf_common as ic
    from raft_tpu.neighbors.ivf_flat import _fit_list_size

    n_lists = max(8, n // rows_per_list)
    km = KMeansBalancedParams(n_iters=10, metric="l2", seed=seed)
    n_train = min(n, max(n_lists * 4, n // 4))
    if n_train < n:
        rng = np.random.default_rng(seed)
        trainset = x[jnp.asarray(np.sort(rng.choice(n, n_train, replace=False)))]
    else:
        trainset = x
    centers = kmeans_balanced.fit(trainset, n_lists, km)
    labels = kmeans_balanced.predict(centers, x, km)
    counts = np.bincount(np.asarray(labels), minlength=n_lists)
    L = _fit_list_size(counts, max(1, n // n_lists), 4.0)
    (packed,), pids, _, dropped, (row_list, row_slot) = ic.pack_lists_jit(
        [x], labels, jnp.arange(n, dtype=jnp.int32),
        n_lists=n_lists, L=L, fill_values=[jnp.zeros((), x.dtype)])
    n_over = int(dropped)
    overflow_rows = np.nonzero(np.asarray(row_slot) >= L)[0] if n_over else None
    row_slot = jnp.clip(row_slot, 0, L - 1)  # overflow rows borrow slot L-1

    T = min(neighborhood, n_lists)
    kk = min(k, T * L - 1)
    # chunk bound: [C, L, T·L] f32 distance block under ~192 MB
    chunk = max(1, (192 << 20) // max(1, L * T * L * 4))
    graph = _cluster_blocked_knn(packed, pids, centers, row_list, row_slot,
                                 kk, n_lists, T, min(chunk, n_lists), ip)
    if n_over:
        # overflow rows never entered a packed list: the blocked scan
        # would hand them slot L-1's neighbor list (a different vector's
        # edges) and they receive no incoming edges either. Patch them
        # with an exact scan of their own cluster block — rare (the
        # packer already warned), so one padded side pass is cheap.
        from raft_tpu.core import logging as _log
        _log.warn("cluster_knn_graph: %d rows overflowed their list; "
                  "patching their graph rows via an in-cluster scan",
                  n_over)
        o_pad = max(8, 1 << (n_over - 1).bit_length())
        o_idx = np.pad(overflow_rows, (0, o_pad - n_over), mode="edge")
        o_rows = jnp.asarray(o_idx)
        o_chunk = max(8, (192 << 20) // max(1, L * d * 4))
        ov = _overflow_knn(x, packed, pids, o_rows,
                           row_list[o_rows], min(kk, L - 1), ip,
                           min(o_pad, -(-o_chunk // 8) * 8))
        if ov.shape[1] < kk:
            ov = jnp.pad(ov, ((0, 0), (0, kk - ov.shape[1])), mode="edge")
        graph = graph.at[o_rows[:n_over]].set(ov[:n_over])
    if kk < k:
        graph = jnp.pad(graph, ((0, 0), (0, k - kk)), mode="edge")
    graph = graph.astype(jnp.int32)
    if return_entries:
        if centers_from is not None:
            # projected build (see IndexParams.build_projection_dim):
            # search seeds score queries against centers in FULL space,
            # so recompute them as per-list means of the original rows
            from raft_tpu.cluster.kmeans import _update_centroids

            centers, _ = _update_centroids(
                centers_from.astype(jnp.float32),
                jnp.ones((n,), jnp.float32), labels, n_lists,
                jnp.zeros((n_lists, centers_from.shape[1]), jnp.float32))
        return graph, centers, pids[:, :min(32, L)]
    return graph


def _drop_self_edges(knn_ids: jax.Array, n: int, k: int) -> jax.Array:
    """Stable-partition self hits out of a [n, >=k+1] id table → [n, k]."""
    self_col = knn_ids == jnp.arange(n, dtype=knn_ids.dtype)[:, None]
    order = jnp.argsort(self_col, axis=1, stable=True)
    cleaned = jnp.take_along_axis(knn_ids, order, axis=1)[:, :k]
    return cleaned.astype(jnp.int32)


@partial(jax.jit, static_argnames=("out_degree",))
def optimize_graph(knn_graph: jax.Array, out_degree: int) -> jax.Array:
    """Detourable-edge pruning + reverse-edge augmentation
    (reference: graph_core.cuh optimize, 572 LoC; CAGRA paper §4.1).

    Edge u→v (rank i in u's list) is *detourable* through w (rank j<i) if
    v also appears in w's own neighbor list at a rank < i — i.e. the
    two-hop path u→w→v uses strictly closer edges. Edges with the fewest
    detour paths are kept; half the output degree is then filled with
    reverse edges (incoming links), which CAGRA shows is what makes the
    graph navigable.
    """
    n, D = knn_graph.shape
    d_half = out_degree // 2

    def detour_counts(u_list):
        # u_list: [D] neighbor ids sorted by distance rank
        nbr_lists = knn_graph[u_list]                     # [D, D] lists of w=u_list[j]
        # pos[j, i] = rank of u_list[i] in w_j's list (D if absent)
        eq = nbr_lists[:, :, None] == u_list[None, None, :]  # [D(j), D(pos), D(i)]
        pos = jnp.min(jnp.where(eq, jnp.arange(D)[None, :, None], D), axis=1)  # [D(j), D(i)]
        ranks = jnp.arange(D)
        # detour via w_j for edge i: j < i  AND  pos[j, i] < i
        detour = (ranks[:, None] < ranks[None, :]) & (pos < ranks[None, :])
        return jnp.sum(detour, axis=0)                    # [D] counts per edge i

    counts = lax.map(detour_counts, knn_graph, batch_size=256)  # [n, D]
    # keep lowest-detour-count edges, tie-broken by distance rank
    score = counts.astype(jnp.int32) * D + jnp.arange(D, dtype=jnp.int32)[None, :]
    keep = jnp.argsort(score, axis=1)[:, :out_degree]
    pruned = jnp.take_along_axis(knn_graph, keep, axis=1)  # [n, out_degree]

    # reverse-edge augmentation: for each node, gather up to d_half incoming
    # edges (from the pruned forward graph) and splice them after the
    # d_half best forward edges (graph_core.cuh rev_graph).
    fwd = pruned[:, :d_half]
    src = jnp.repeat(_ids.make_ids(n)[:, None], d_half, 1).reshape(-1)
    dst = fwd.reshape(-1)
    # count and slot reverse edges per destination node
    order = jnp.argsort(dst, stable=True)
    dst_s, src_s = dst[order], src[order]
    # position of each edge within its destination group
    first_idx = jnp.searchsorted(dst_s, _ids.make_ids(n))
    slot = jnp.arange(dst_s.shape[0]) - first_idx[dst_s]
    # table dtype follows the source ids' policy width (core.ids) — a
    # hard int32 table would silently truncate int64 node ids through
    # the scatter at n ≥ 2³¹ (jnp .at[].set casts, it doesn't error)
    rev = jnp.full((n, d_half), -1, src.dtype)
    valid = slot < d_half
    # out-of-quota reverse edges write to row n → dropped
    rev = rev.at[jnp.where(valid, dst_s, n),
                 jnp.clip(slot, 0, d_half - 1)].set(src_s, mode="drop")
    # final graph: best forward half + reverse half (fall back to forward
    # edges where no reverse edge exists)
    fallback = pruned[:, d_half:out_degree]
    merged = jnp.where(rev >= 0, rev, fallback)
    return jnp.concatenate([fwd, merged], axis=1)


@jax.jit
def _quantize_rows(x: jax.Array):
    """Per-dimension affine int8 scalar quantization of the dataset —
    the traversal-side compression of the reference's CAGRA-Q
    direction (vpq_dataset / cagra compression): x ≈ zero + scale·code,
    codes in [-127, 127]. Costs n·d bytes; search gathers these rows
    instead of f32 (4× fewer bytes on the gather-bound hop) and
    re-ranks the final buffer exactly."""
    mn = jnp.min(x, axis=0)
    mx = jnp.max(x, axis=0)
    zero = 0.5 * (mn + mx)
    scale = jnp.maximum((mx - mn) / 254.0, 1e-12)
    codes = jnp.clip(jnp.round((x - zero) / scale), -127, 127)
    return codes.astype(jnp.int8), scale.astype(jnp.float32), zero.astype(jnp.float32)


@traced("raft_tpu.cagra.build")
def build(dataset: jax.Array, params: Optional[IndexParams] = None) -> CagraIndex:
    """Build (reference: cagra::build, cagra.cuh — knn-graph + optimize)."""
    if params is None:
        params = IndexParams()
    mt = resolve_metric(params.metric)
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct),
            "cagra supports sqeuclidean/euclidean/inner_product")
    x = jnp.asarray(dataset, jnp.float32)
    n = x.shape[0]
    inter_d = min(params.intermediate_graph_degree, n - 1)
    out_d = min(params.graph_degree, inter_d)
    algo = params.build_algo
    if algo == "auto":
        algo = "cluster"
    centers = entry_ids = None
    proj_d = params.build_projection_dim
    if proj_d == -1:
        proj_d = 128 if x.shape[1] > 256 else 0
    if proj_d and proj_d < x.shape[1] and mt != DistanceType.InnerProduct:
        # random orthonormal projection for the BUILD scans only (see
        # IndexParams.build_projection_dim); L2 ranks are approximately
        # preserved, and optimize_graph's detour pruning only consumes
        # ranks. ip metric skips it (projection distorts raw dot
        # products more than distances).
        g = jax.random.normal(jax.random.PRNGKey(params.seed ^ 0x5EED),
                              (x.shape[1], proj_d), jnp.float32)
        r, _ = jnp.linalg.qr(g)
        x_build = x @ r
    else:
        x_build = x
    with span("knn_graph") as _sp:
        if algo == "nn_descent":
            from raft_tpu.neighbors.nn_descent import build_knn_graph as _nnd
            knn = _nnd(x_build, inter_d, metric=mt.value,
                       n_iters=params.nn_descent_niter, seed=params.seed)
        elif algo == "cluster":
            knn, centers, entry_ids = cluster_knn_graph(
                x_build, inter_d, metric=mt.value, seed=params.seed,
                rows_per_list=params.knn_rows_per_list,
                neighborhood=params.knn_neighborhood,
                return_entries=True,
                centers_from=x if x_build is not x else None)
        else:
            knn = build_knn_graph(x, inter_d, metric=mt.value,
                                  seed=params.seed)
        _sp.attach(knn)
    with span("optimize") as _sp:
        graph = optimize_graph(knn, out_d)
        _sp.attach(graph)
    codes = scale = zero = None
    if params.quantize_dataset:
        with span("quantize") as _sp:
            codes, scale, zero = _quantize_rows(x)
            _sp.attach(codes)
    return CagraIndex(dataset=x, graph=graph, metric=mt.value,
                      centers=centers, entry_ids=entry_ids,
                      dataset_q=codes, q_scale=scale, q_zero=zero)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k", "itopk_size", "search_width",
                                   "max_iterations", "query_tile", "seed",
                                   "num_seeds", "use_q", "dedup",
                                   "entry_clusters"))
def _search_impl(index: CagraIndex, queries: jax.Array, k: int,
                 itopk_size: int, search_width: int, max_iterations: int,
                 query_tile: int, seed: int = 0, num_seeds: int = 0,
                 use_q: bool = False, dedup: str = "pairwise",
                 filter_bits=None, entry_clusters: int = 4):
    mt = resolve_metric(index.metric)
    ip = mt == DistanceType.InnerProduct
    sqrt_out = mt == DistanceType.L2SqrtExpanded
    x = index.dataset
    n, d = x.shape
    deg = index.graph_degree
    m = queries.shape[0]
    q_all = jnp.asarray(queries, jnp.float32)
    BIG = jnp.float32(jnp.inf)
    # node ids (traversal buffer, seeds, neighbor lists) ride the policy
    # dtype of the dataset row count (core.ids): int32 until n ≥ 2³¹
    idt = _ids.id_dtype(n)

    def dists_to(q, ids):
        """q [t, d], ids [t, C] → metric scores [t, C] (lower = better).

        Traversal is HBM-gather-bound (512 B random rows measured
        ~32 GB/s); ``use_q`` gathers the int8 scalar-quantized rows
        instead (4× fewer bytes, ~1.8× faster per hop — the CAGRA-Q
        direction, search epilogue re-ranks exactly). Candidate norms
        come from the gathered rows: a separate ``x_sq[ids]`` POINTWISE
        gather costs more than the whole row gather."""
        if use_q:
            rows = (index.q_zero[None, None, :]
                    + index.dataset_q[ids].astype(jnp.float32)
                    * index.q_scale[None, None, :])       # [t, C, d]
        else:
            rows = x[ids]                                 # [t, C, d]
        s = jnp.einsum("td,tcd->tc", q, rows,
                       precision=get_precision(),
                       preferred_element_type=jnp.float32)
        if ip:
            return -s
        nsq = jnp.sum(rows * rows, axis=-1)
        return jnp.maximum(jnp.sum(q * q, 1)[:, None] + nsq - 2.0 * s, 0.0)

    base_key = jax.random.PRNGKey(seed)

    def search_tile(q, qstart):
        t = q.shape[0]
        # entry points are a per-QUERY pseudo-random function of (seed,
        # global query index) — the reference hashes query id through
        # rand_xor_mask the same way — so results are independent of query
        # tiling and entry sets are decorrelated across queries
        qidx = qstart + jnp.arange(t, dtype=jnp.uint32)
        keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(qidx)
        if index.centers is not None:
            # cluster-seeded entries (see CagraIndex): members of the
            # query's nearest clusters, padded with random draws — the
            # random-only floor (1 − e^{−seeds/n_clusters}) disappears
            # and far fewer seed distances are needed
            cts = index.centers
            qc = jnp.einsum("td,ld->tl", q, cts,
                            precision=get_precision(),
                            preferred_element_type=jnp.float32)
            c_score = qc if ip else 2.0 * qc - jnp.sum(cts * cts, 1)[None, :]
            c_sel = min(entry_clusters, cts.shape[0])
            _, top_l = lax.top_k(c_score, c_sel)           # [t, c_sel]
            ent = index.entry_ids[top_l].reshape(t, -1)    # [t, c_sel·E]
            n_rand = max(num_seeds or max(itopk_size, 512), itopk_size)
            # total seeds rounded UP to a multiple of 128 so the seed-
            # distance chunking below always finds a divisor (c_sel·E is
            # not 128 for narrow entry tables)
            n_seed = -(-(ent.shape[1] + n_rand) // 128) * 128
            ent = jnp.concatenate(
                [ent, jnp.full((t, n_seed - ent.shape[1]), -1, ent.dtype)],
                axis=1)
            rnd = jax.vmap(
                lambda kk: jax.random.randint(kk, (n_seed,), 0, n,
                                              dtype=idt))(keys)
            init_ids = jnp.where(ent >= 0, ent.astype(idt), rnd)
        else:
            # oversample candidates and keep the best itopk — the
            # reference's random_sampling makes multiple hashed draws per
            # itopk slot the same way (compute_random_samples /
            # num_random_samplings). The count scales with n (see
            # SearchParams.num_seeds): entry coverage is the recall floor
            # on clustered data. Clamp: the buffer init takes top itopk
            # of the seeds, so fewer seeds than itopk slots would break
            # lax.top_k; round to a multiple of 128 so the seed phase can
            # chunk evenly
            n_seed = max(num_seeds or max(2 * itopk_size, min(2048, n // 64)),
                         itopk_size)
            n_seed = -(-n_seed // 128) * 128
            init_ids = jax.vmap(
                lambda kk: jax.random.randint(kk, (n_seed,), 0, n,
                                              dtype=idt))(keys)
        # sampled with replacement: demote duplicate entry slots so an id
        # can never surface twice in the buffer. Sort-based dedup — the
        # quadratic pairwise mask would be O(n_seed²) per query
        order = jnp.argsort(init_ids, axis=1)
        sorted_ids = jnp.take_along_axis(init_ids, order, axis=1)
        dup_sorted = jnp.concatenate(
            [jnp.zeros((t, 1), jnp.bool_),
             sorted_ids[:, 1:] == sorted_ids[:, :-1]], axis=1)
        inv = jnp.argsort(order, axis=1)
        dup0 = jnp.take_along_axis(dup_sorted, inv, axis=1)
        # chunk the seed-distance gather: at n_seed=2048 an unchunked
        # x[init_ids] would materialize [t, n_seed, d] (GBs at large d);
        # lax.map bounds the intermediate to one chunk
        if n_seed > 512:
            c = 512
            while n_seed % c:
                c -= 128  # n_seed is a multiple of 128
            ids_r = jnp.transpose(
                init_ids.reshape(t, n_seed // c, c), (1, 0, 2))
            seed_d = jnp.transpose(
                lax.map(lambda ii: dists_to(q, ii), ids_r),
                (1, 0, 2)).reshape(t, n_seed)
        else:
            seed_d = dists_to(q, init_ids)
        seed_d = jnp.where(dup0, BIG, seed_d)
        _, best = lax.top_k(-seed_d, itopk_size)
        init_ids = jnp.take_along_axis(init_ids, best, axis=1)
        buf_d = jnp.take_along_axis(seed_d, best, axis=1)
        if filter_bits is not None:
            from raft_tpu.neighbors.sample_filter import passes

            # filtered vectors score +inf so they never rank in the itopk
            # nor get expanded — the exclusion point the reference's
            # cagra sample_filter hooks
            buf_d = jnp.where(passes(filter_bits, init_ids), buf_d, BIG)
        buf_i = init_ids.astype(idt)
        order = jnp.argsort(buf_d, axis=1)
        buf_d = jnp.take_along_axis(buf_d, order, 1)
        buf_i = jnp.take_along_axis(buf_i, order, 1)
        buf_v = jnp.zeros((t, itopk_size), jnp.bool_)     # visited bits

        def cond(state):
            _, _, buf_v, it = state
            # stop when every query's whole itopk buffer is visited
            # (the reference iterates until the itopk converges)
            return (it < max_iterations) & ~jnp.all(buf_v)

        def body(state):
            buf_d, buf_i, buf_v, it = state
            # freeze settled queries (whole buffer visited): their updates
            # are discarded, so results don't depend on query tiling
            frozen = jnp.all(buf_v, axis=1)
            old = (buf_d, buf_i, buf_v)
            # 1. pick search_width best unvisited parents
            cand_score = jnp.where(buf_v, BIG, buf_d)
            _, parent_pos = lax.top_k(-cand_score, search_width)   # [t, W]
            parent_ids = jnp.take_along_axis(buf_i, parent_pos, 1)
            parent_valid = jnp.take_along_axis(cand_score, parent_pos, 1) < BIG
            # mark visited
            buf_v = buf_v.at[jnp.arange(t)[:, None], parent_pos].set(True)
            # 2. expand: gather graph rows of parents → [t, W·deg]
            nbrs = index.graph[jnp.clip(parent_ids, 0, n - 1)]     # [t, W, deg]
            nbrs = nbrs.reshape(t, search_width * deg)
            nbrs = jnp.where(jnp.repeat(parent_valid, deg, axis=1), nbrs, 0)
            # 3. distances on the MXU
            nd = dists_to(q, nbrs)
            nd = jnp.where(jnp.repeat(parent_valid, deg, axis=1), nd, BIG)
            if filter_bits is not None:
                from raft_tpu.neighbors.sample_filter import passes

                nd = jnp.where(passes(filter_bits, nbrs), nd, BIG)
            # 4. dedupe against the buffer (the visited-hashmap stand-in)
            dup = jnp.any(nbrs[:, :, None] == buf_i[:, None, :], axis=2)
            nd = jnp.where(dup, BIG, nd)
            # dedupe within the candidate set (first occurrence wins):
            # "sort" marks equal-adjacent ids through two C-wide
            # argsorts; "pairwise" lets XLA fuse the [t, C, C] equality
            # mask (cheaper at small C, never materialized)
            if dedup == "sort":
                c_order = jnp.argsort(nbrs, axis=1)
                sorted_ids = jnp.take_along_axis(nbrs, c_order, axis=1)
                dup_s = jnp.concatenate(
                    [jnp.zeros((t, 1), jnp.bool_),
                     sorted_ids[:, 1:] == sorted_ids[:, :-1]], axis=1)
                c_inv = jnp.argsort(c_order, axis=1)
                nd = jnp.where(jnp.take_along_axis(dup_s, c_inv, axis=1),
                               BIG, nd)
            else:
                eq = nbrs[:, :, None] == nbrs[:, None, :]
                earlier = jnp.tril(
                    jnp.ones((search_width * deg,) * 2, jnp.bool_), -1)
                nd = jnp.where(jnp.any(eq & earlier[None], axis=2), BIG, nd)
            # 5. merge into itopk: concat + select
            all_d = jnp.concatenate([buf_d, nd], axis=1)
            all_i = jnp.concatenate([buf_i, nbrs.astype(idt)], axis=1)
            all_v = jnp.concatenate(
                [buf_v, jnp.zeros_like(nd, dtype=jnp.bool_)], axis=1)
            _, pos = lax.top_k(-all_d, itopk_size)
            buf_d = jnp.take_along_axis(all_d, pos, 1)
            buf_i = jnp.take_along_axis(all_i, pos, 1)
            buf_v = jnp.take_along_axis(all_v, pos, 1)
            buf_d = jnp.where(frozen[:, None], old[0], buf_d)
            buf_i = jnp.where(frozen[:, None], old[1], buf_i)
            buf_v = jnp.where(frozen[:, None], old[2], buf_v)
            return buf_d, buf_i, buf_v, it + 1

        buf_d, buf_i, _, _ = lax.while_loop(
            cond, body, (buf_d, buf_i, buf_v, jnp.array(0, jnp.int32)))
        if use_q:
            # exact f32 re-rank of the final buffer: quantization error
            # only ever shuffled candidates WITHIN the buffer; one cheap
            # [t, itopk] row gather restores exact distances and order
            rows = x[jnp.clip(buf_i, 0, n - 1)]           # [t, itopk, d]
            s = jnp.einsum("td,tcd->tc", q, rows,
                           precision=get_precision(),
                           preferred_element_type=jnp.float32)
            if ip:
                exact = -s
            else:
                exact = jnp.maximum(
                    jnp.sum(q * q, 1)[:, None]
                    + jnp.sum(rows * rows, -1) - 2.0 * s, 0.0)
            exact = jnp.where(jnp.isinf(buf_d), BIG, exact)
            _, pos = lax.top_k(-exact, k)
            buf_d = jnp.take_along_axis(exact, pos, axis=1)
            buf_i = jnp.take_along_axis(buf_i, pos, axis=1)
        out_d, out_i = buf_d[:, :k], buf_i[:, :k]
        if filter_bits is not None:
            # inf-score slots are filtered/unreached: mark their ids -1
            # (same pad convention as brute-force/IVF)
            out_i = jnp.where(jnp.isinf(out_d), -1, out_i)
        if ip:
            out_d = -out_d
        elif sqrt_out:
            out_d = jnp.sqrt(out_d)
        return out_d, out_i

    if m <= query_tile:
        return search_tile(q_all, jnp.uint32(0))
    n_tiles = -(-m // query_tile)
    pad = n_tiles * query_tile - m
    qp = jnp.pad(q_all, ((0, pad), (0, 0)))
    starts = (jnp.arange(n_tiles, dtype=jnp.uint32) * query_tile)
    vals, ids = lax.map(lambda args: search_tile(*args),
                        (qp.reshape(n_tiles, query_tile, d), starts))
    return vals.reshape(-1, k)[:m], ids.reshape(-1, k)[:m]


@traced("raft_tpu.cagra.search")
def search(index: CagraIndex, queries: jax.Array, k: int,
           params: Optional[SearchParams] = None,
           filter_bitset: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Search (reference: cagra::search → search_main, cagra_search.cuh:105;
    filtered overload via CagraSampleFilterT).
    ``filter_bitset``: optional packed bitset over dataset rows (see
    neighbors.sample_filter) — cleared bits are excluded."""
    if params is None:
        params = SearchParams()
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "queries must be [m, %d]", index.dim)
    expects(params.traverse in ("auto", "f32", "int8"),
            "traverse must be auto/f32/int8, not %s", params.traverse)
    use_q = (params.traverse == "int8"
             or (params.traverse == "auto" and index.dataset_q is not None))
    if use_q:
        expects(index.dataset_q is not None,
                "traverse='int8' needs an index with quantized rows")
    itopk = max(params.itopk_size, k)
    max_it = params.max_iterations or 2 * (-(-itopk // params.search_width))
    return _search_impl(index, queries, k, itopk, params.search_width,
                        max_it, params.query_tile, seed=params.seed,
                        num_seeds=params.num_seeds, use_q=use_q,
                        dedup=params.dedup, filter_bits=filter_bitset,
                        entry_clusters=params.entry_clusters)


# ---------------------------------------------------------------------------
# serialization (reference: neighbors/cagra_serialize.cuh)
# ---------------------------------------------------------------------------

def save(index: CagraIndex, path: str, include_dataset: bool = True) -> None:
    arrays = {"graph": index.graph}
    if include_dataset:
        arrays["dataset"] = index.dataset
    if index.centers is not None:
        arrays["centers"] = index.centers
        arrays["entry_ids"] = index.entry_ids
    if index.dataset_q is not None:
        arrays["dataset_q"] = index.dataset_q
        arrays["q_scale"] = index.q_scale
        arrays["q_zero"] = index.q_zero
    ser.save_arrays(path, "cagra", _SERIAL_VERSION,
                    {"metric": index.metric}, arrays)


def load(path: str, dataset: Optional[jax.Array] = None) -> CagraIndex:
    version, meta, a = ser.load_arrays(path, "cagra")
    # v1/v2 files lack centers/entry_ids resp. quantized rows (search
    # falls back to random entries / f32 traversal)
    expects(version in (1, 2, _SERIAL_VERSION),
            "unsupported cagra version %d", version)
    ds = jnp.asarray(a["dataset"]) if "dataset" in a else jnp.asarray(dataset)

    def get(name):
        return jnp.asarray(a[name]) if name in a else None

    return CagraIndex(
        dataset=ds, graph=jnp.asarray(a["graph"]), metric=meta["metric"],
        centers=get("centers"), entry_ids=get("entry_ids"),
        dataset_q=get("dataset_q"), q_scale=get("q_scale"),
        q_zero=get("q_zero"))


def serialize_to_hnswlib(index: CagraIndex, path: str,
                         ef_construction: int = 200) -> None:
    """Export the CAGRA graph as an hnswlib-loadable index file
    (reference capability: cagra_serialize serialize_to_hnswlib — a
    flat level-0-only HNSW whose neighbor lists are the CAGRA graph).

    Binary layout follows hnswlib's ``HierarchicalNSW::saveIndex``
    (hnswalg.h): header of size_t/int fields, then per-element level-0
    blocks ``[link_count u16 + pad u16][maxM0 x u32 links][f32 data]
    [u64 label]``, then a zero u32 per element (no upper levels).
    Loadable with ``hnswlib.Index(space, dim).load_index(path)`` where
    space is "l2" for (sq)euclidean and "ip" for inner_product.
    """
    import struct

    expects(resolve_metric(index.metric) in
            (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
             DistanceType.InnerProduct),
            "hnswlib export supports l2/inner_product metrics, not %s",
            index.metric)
    data = np.ascontiguousarray(np.asarray(index.dataset), np.float32)
    graph = np.asarray(index.graph, np.int64)
    n, dim = data.shape
    degree = graph.shape[1]

    max_m0 = degree               # level-0 out-degree = graph degree
    m = max(1, degree // 2)
    data_size = dim * 4
    size_links0 = max_m0 * 4 + 4  # u32 count-word + maxM0 u32 links
    size_per_elem = size_links0 + data_size + 8  # + u64 label
    offset_data = size_links0
    label_offset = size_links0 + data_size
    mult = 1.0 / np.log(max(m, 2))

    # hnswlib reads the first `count` links, so valid ids must be
    # compacted to the front (graph rows can carry interior -1 entries
    # when the knn stage returned fewer than degree candidates)
    valid = graph >= 0
    counts = np.sum(valid, axis=1).astype(np.uint16)
    front = np.argsort(~valid, axis=1, kind="stable")  # valid-first, ordered
    links = np.take_along_axis(np.where(valid, graph, 0), front,
                               axis=1).astype(np.uint32)

    with open(path, "wb") as f:
        f.write(struct.pack("<QQQQQQiIQQQdQ",
                            0,              # offsetLevel0_
                            n,              # max_elements_
                            n,              # cur_element_count
                            size_per_elem,  # size_data_per_element_
                            label_offset,   # label_offset_
                            offset_data,    # offsetData_
                            0,              # maxlevel_
                            0,              # enterpoint_node_
                            m,              # maxM_
                            max_m0,         # maxM0_
                            m,              # M_
                            float(mult),    # mult_
                            ef_construction))
        # level-0 blocks, assembled vectorized then written once
        block = np.zeros((n, size_per_elem), np.uint8)
        block[:, 0:2] = counts[:, None].view(np.uint8).reshape(n, 2)
        block[:, 4:4 + max_m0 * 4] = links.view(np.uint8).reshape(n, -1)
        block[:, offset_data:offset_data + data_size] = data.view(
            np.uint8).reshape(n, -1)
        block[:, label_offset:] = np.arange(n, dtype=np.uint64).view(
            np.uint8).reshape(n, 8)
        f.write(block.tobytes())
        # one u32 per element: no higher-level link lists
        f.write(np.zeros(n, np.uint32).tobytes())
