"""Open-loop load generator — real latency-vs-throughput curves.

A closed-loop client (send → wait → send) can never overload a server:
its arrival rate collapses to the service rate and the latency curve
flat-lines exactly where production pain begins (coordinated
omission). This generator is **open-loop**: arrivals follow a Poisson
process at the OFFERED rate regardless of completions, so queueing
delay, shedding, and deadline misses show up at the rates they would
in production.

:func:`run_step` drives one offered-load step and returns a
bench-shaped row: achieved qps, p50/p99 latency (from the PR-5
histogram-quantile machinery — the same interpolation the bench's
latency columns use), shed/miss/error counts. :func:`sweep` walks a
ladder of offered loads into the latency-vs-throughput curve, and
:func:`record` wraps rows with environment provenance
(``runner.environment_stamp()``) so the committed
``baselines/serve_cpu_smoke.json`` passes the benchdiff gate's
env-refusal check like every other perf claim in the tree.
"""

from __future__ import annotations

import random
import subprocess
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.obs import cost as _cost
from raft_tpu.obs.metrics import Histogram, exemplars_for_quantile
from raft_tpu.robust.retry import DeadlineExceeded
from raft_tpu.serve.errors import ShedError
from raft_tpu.serve.server import MicroBatchServer, _LATENCY_BUCKETS

__all__ = ["run_step", "sweep", "record"]


def _git_commit() -> Optional[str]:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=__file__.rsplit("/", 3)[0]).stdout.strip() or None
    except Exception:
        return None


def run_step(server: MicroBatchServer, tenant: str,
             queries: np.ndarray, k: int,
             offered_qps: float, duration_s: float,
             seed: int = 0,
             slo_s: Optional[float] = -1.0,
             ground_truth: Optional[np.ndarray] = None) -> Dict[str, Any]:
    """One offered-load step: submit single-query requests at Poisson
    arrivals of rate ``offered_qps`` for ``duration_s`` seconds (query
    vectors cycled from ``queries``), then wait for every future and
    tally. The arrival clock never waits on completions — that is the
    point.

    ``ground_truth`` (optional, ``[n_queries, ≥k]`` exact neighbor ids
    per query row, ISSUE 16) turns the step's quality column on: every
    completed request's served ids are scored against the truth row and
    the step reports mean ``recall`` — so a latency-vs-throughput curve
    that cheats (sheds into a degraded rung trading recall for speed)
    can no longer look like a win."""
    rng = random.Random(seed)
    n = queries.shape[0]
    lat = Histogram("loadgen.latency_s", buckets=_LATENCY_BUCKETS)
    sent = shed = missed = errors = 0
    shed_reasons: Dict[str, int] = {}
    inflight: List[Tuple[float, Future, int]] = []
    # completion times captured by done-callbacks (fired by the
    # batcher thread the moment the future resolves): the drain loop
    # below must not masquerade its own pace as request latency
    done_at: Dict[int, float] = {}

    def _mark_done(fut: Future) -> None:
        done_at[id(fut)] = time.monotonic()

    # cost attribution (ISSUE 20): bracket the step with ledger reads
    # so the row reports the device time THIS step's traffic consumed
    # (per-tenant delta) and the tenant's fleet share at step end —
    # None when no ledger is installed (old records join unchanged)
    ledger = _cost.get_ledger()
    device_s0 = (ledger.device_seconds().get(tenant, 0.0)
                 if ledger is not None else 0.0)

    t_start = time.monotonic()
    next_arrival = t_start
    deadline_end = t_start + duration_s
    i = 0
    while True:
        now = time.monotonic()
        if now >= deadline_end:
            break
        if now < next_arrival:
            time.sleep(min(next_arrival - now, deadline_end - now))
            continue
        # schedule the NEXT arrival off the schedule, not off "now":
        # submit() overhead must not thin the offered rate
        next_arrival += rng.expovariate(offered_qps)
        sent += 1
        t_submit = time.monotonic()
        try:
            fut = server.submit(tenant, queries[i % n], k, slo_s=slo_s)
        except ShedError as e:
            shed += 1
            shed_reasons[e.reason] = shed_reasons.get(e.reason, 0) + 1
        else:
            fut.add_done_callback(_mark_done)
            inflight.append((t_submit, fut, i % n))
        i += 1
    ok = 0
    recall_sum = 0.0
    recall_n = 0
    t_last_done = t_start
    for t_submit, fut, qi in inflight:
        try:
            _, served_ids = fut.result(timeout=30.0)
        except DeadlineExceeded:
            missed += 1
        except ShedError as e:
            shed += 1
            shed_reasons[e.reason] = shed_reasons.get(e.reason, 0) + 1
        except Exception:
            errors += 1
        else:
            ok += 1
            if ground_truth is not None:
                from raft_tpu.obs.quality import recall_at_k

                recall_sum += recall_at_k(np.asarray(served_ids),
                                          ground_truth[qi], k)
                recall_n += 1
            t_done = done_at.get(id(fut), time.monotonic())
            t_last_done = max(t_last_done, t_done)
            # the future knows its request's trace id (stamped by
            # submit): the step's latency histogram retains the slowest
            # requests' ids as exemplars, so a regressed baseline names
            # reproducible offender requests (ISSUE 15)
            lat.observe(t_done - t_submit,
                        exemplar=getattr(fut, "trace_id", None))
    # achieved rate over the window that actually served: arrivals
    # stopped at duration_s but queued work drains past it
    wall = max(t_last_done, deadline_end) - t_start
    slow = exemplars_for_quantile(lat.state(), 0.99)
    device_s = cost_share = None
    if ledger is not None:
        device_s = round(ledger.device_seconds().get(tenant, 0.0)
                         - device_s0, 6)
        cost_share = round(ledger.shares().get(tenant, 0.0), 6)
    return {
        "offered_qps": offered_qps,
        "duration_s": round(wall, 4),
        "sent": sent,
        "completed": ok,
        "shed": shed,
        "shed_reasons": shed_reasons,
        "deadline_missed": missed,
        "errors": errors,
        "qps": round(ok / wall, 2) if wall > 0 else 0.0,
        "latency_p50_s": lat.quantile(0.5),
        "latency_p99_s": lat.quantile(0.99),
        "latency_mean_s": (lat.sum / lat.count) if lat.count else None,
        # measured quality (None without ground truth): mean recall@k
        # over the completed requests of this step
        "recall": (round(recall_sum / recall_n, 6)
                   if recall_n else None),
        # per-step cost columns (ISSUE 20, None without a ledger):
        # device seconds this step's traffic consumed, and the
        # tenant's normalized fleet share at step end
        "device_s": device_s,
        "cost_share": cost_share,
        # the p99 bucket's worst offenders, worst first — joinable back
        # to their timelines via obsdump --slowest on the server's dump
        "slow_trace_ids": [e["trace_id"] for e in slow],
    }


def sweep(server: MicroBatchServer, tenant: str, queries: np.ndarray,
          k: int, offered_steps: Sequence[float],
          duration_s: float = 2.0, seed: int = 0,
          slo_s: Optional[float] = -1.0,
          ground_truth: Optional[np.ndarray] = None
          ) -> List[Dict[str, Any]]:
    """The latency-vs-throughput curve: one :func:`run_step` per
    offered load, in order (each step inherits the previous step's
    thermal/queue state the way a ramping production load would)."""
    return [run_step(server, tenant, queries, k, q, duration_s,
                     seed=seed + j, slo_s=slo_s,
                     ground_truth=ground_truth)
            for j, q in enumerate(offered_steps)]


def record(rows: List[Dict[str, Any]], dataset: str, tenant: str,
           k: int, note: str = "") -> Dict[str, Any]:
    """Wrap sweep rows as a benchdiff-joinable record: each row keyed
    by (dataset, algo="serve", index=tenant, search_param={offered_qps,
    k}, batch_size=1) and stamped with ``measured_at`` / ``git_commit``
    / environment provenance — the same self-stamping protocol every
    recorded perf row in the tree follows."""
    from raft_tpu.bench import runner as _runner

    env = _runner.environment_stamp()
    measured_at = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    commit = _git_commit()
    detail = []
    for r in rows:
        detail.append({
            "dataset": dataset, "algo": "serve", "index": tenant,
            "search_param": {"offered_qps": r["offered_qps"], "k": k},
            "batch_size": 1,
            "qps": r["qps"], "recall": r.get("recall"),
            "latency_p50_s": r["latency_p50_s"],
            "latency_p99_s": r["latency_p99_s"],
            "sent": r["sent"], "completed": r["completed"],
            "shed": r["shed"], "shed_reasons": r["shed_reasons"],
            "deadline_missed": r["deadline_missed"],
            "errors": r["errors"],
            # optional cost columns (ISSUE 20): absent-tolerant on the
            # benchdiff join so pre-ledger records stay comparable
            "device_s": r.get("device_s"),
            "cost_share": r.get("cost_share"),
            "slow_trace_ids": r.get("slow_trace_ids", []),
            "measured_at": measured_at, "git_commit": commit,
            "env": env,
        })
    best = max((d["qps"] for d in detail), default=0.0)
    # name the offenders (ISSUE 15): the worst-p99 step's exemplar
    # trace ids ride the record's notes, so a benchdiff regression on
    # this baseline points at reproducible requests, not just a number
    worst = max((d for d in detail if d["latency_p99_s"] is not None),
                key=lambda d: d["latency_p99_s"], default=None)
    notes = note
    if worst is not None and worst.get("slow_trace_ids"):
        tail = (f"worst p99 step offered_qps="
                f"{worst['search_param']['offered_qps']}: "
                f"p99={worst['latency_p99_s']:.4f}s, slow traces "
                + ",".join(worst["slow_trace_ids"]))
        notes = f"{note}; {tail}" if note else tail
    return {
        "metric": "serve_qps_cpu",
        "value": best,
        "unit": "completed requests/s",
        "total_rows": len(detail),
        "baseline_note": notes,
        "detail": detail,
    }
