"""Per-component memory placement — where an index's pieces live.

The memory-tier half of the serving story (ISSUE 17): a tenant's
scan structures (PQ codes, centroids, norms — the small, every-query
operands) stay HBM-resident, while its raw vectors — the big, touched-
only-at-re-rank component — may live in host memory (a numpy array or
memmap) and reach the chip as candidate rows through the tiered
prefetch pipeline (:mod:`raft_tpu.neighbors.tiered`). Capacity is then
bought with the memory hierarchy instead of with chips: demoting a
tenant's raw vectors reclaims their HBM without evicting the tenant,
and results stay EXACT (the re-rank still runs against full-precision
rows — only where they are fetched from changes).

:class:`Placement` is the registry's first-class record of that choice:

- ``codes="hbm"`` — the scan structures. Always HBM today: every query
  touches them, so host residency would put the host hop on the
  latency path of every scan.
- ``raw="hbm" | "host" | "none"`` — the re-rank base. ``"hbm"`` routes
  refine through the fused/XLA device tiers; ``"host"`` through the
  tiered candidate-row prefetch; ``"none"`` means the tenant carries
  no dataset (PQ-approximate distances only, no exact re-rank and no
  shadow recall verification).

``registry.admit(placement=...)`` validates the declared placement
against the dataset actually handed in; ``registry.demote_raw`` /
``promote_when_clear`` move ``raw`` between the tiers under pressure.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

__all__ = ["Placement", "dataset_tier", "placement_for",
           "to_host", "to_device"]

_CODE_TIERS = ("hbm",)
_RAW_TIERS = ("hbm", "host", "none")


@dataclasses.dataclass(frozen=True)
class Placement:
    """Where each index component lives. Frozen — a tier move creates a
    new record (``dataclasses.replace``), so a snapshot handed to
    ``/indexz`` can never mutate under the renderer."""

    codes: str = "hbm"
    raw: str = "hbm"

    def __post_init__(self):
        if self.codes not in _CODE_TIERS:
            raise ValueError(
                f"Placement.codes={self.codes!r} unsupported (scan "
                f"structures are HBM-resident: {_CODE_TIERS})")
        if self.raw not in _RAW_TIERS:
            raise ValueError(
                f"Placement.raw={self.raw!r} not one of {_RAW_TIERS}")

    def describe(self) -> Dict[str, str]:
        """JSON-ready dict for /indexz and registry snapshots."""
        return {"codes": self.codes, "raw": self.raw}


def dataset_tier(dataset: Any) -> str:
    """Observed residency of a re-rank base: ``"none"`` (no dataset),
    ``"hbm"`` (a jax.Array), or ``"host"`` (numpy array, memmap, or a
    device-chunk provider — anything the refine tiers fetch or
    regenerate rather than index in place on device)."""
    if dataset is None:
        return "none"
    import jax

    return "hbm" if isinstance(dataset, jax.Array) else "host"


def placement_for(dataset: Any) -> Placement:
    """The placement a plain ``admit(dataset=...)`` implies: codes on
    HBM, raw wherever the dataset already lives."""
    return Placement(codes="hbm", raw=dataset_tier(dataset))


def to_host(dataset: Any):
    """Demote a re-rank base to host memory (device → one D2H copy;
    already-host bases pass through untouched, so the call is
    idempotent)."""
    import jax
    import numpy as np

    if isinstance(dataset, jax.Array):
        return np.asarray(dataset)
    return dataset


def to_device(dataset: Any):
    """Promote a re-rank base to HBM (one H2D copy; device-resident
    bases pass through). Memmap sources land as a plain device array —
    re-promotion materializes the rows, that is the point."""
    import jax
    import numpy as np

    if dataset is None or isinstance(dataset, jax.Array):
        return dataset
    return jax.device_put(np.asarray(dataset, np.float32))
