"""SLO-aware dispatch — one deadline budget from queue to result.

The execution half of the serving layer (ISSUE 14): the server hands a
coalesced micro-batch to :func:`dispatch_batch`, which runs it through
the tenant's **resilient** search entry — the PR-7 degrade ladder is
the overload path (halve batch → bf16/fp8 LUT → decline fused → shed)
— under the request group's shared
:class:`~raft_tpu.robust.retry.Deadline`:

- an expired deadline is refused BEFORE any chip work
  (:class:`~raft_tpu.robust.retry.DeadlineExceeded` — the server turns
  it into a counted shed);
- transient faults retry via :func:`raft_tpu.robust.retry.retry_call`
  drawing down the SAME budget (retries can no longer stack past the
  SLO);
- a ladder walk that fires marks the tenant ``degraded`` (the
  registry's health state) so the fleet sees which tenants are serving
  on the slow path.

Fault point ``serve.dispatch`` lets the chaos lane OOM, stall, or kill
the dispatch itself.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Tuple

from raft_tpu.obs import cost as _cost
from raft_tpu.obs import spans as _spans
from raft_tpu.obs import trace as _trace
from raft_tpu.robust import degrade as _degrade
from raft_tpu.robust import faults as _faults
from raft_tpu.robust import retry as _retry
from raft_tpu.robust.retry import Deadline, DeadlineExceeded
from raft_tpu.serve import slo as _slo
from raft_tpu.serve.errors import ShedError
from raft_tpu.serve.registry import Tenant

__all__ = ["dispatch_batch", "resilient_entry", "DISPATCH_RETRY_POLICY"]

# Transient-fault absorption on the dispatch path: short and fast —
# serving latency budgets are milliseconds, so backoff starts at 10 ms
# and the shared Deadline (not the per-site cap) is the real ceiling.
DISPATCH_RETRY_POLICY = _retry.RetryPolicy(
    max_attempts=3, base_delay_s=0.01, max_delay_s=0.25, jitter=0.25)


def resilient_entry(index: Any):
    """Resolve the degrade-ladder search entry for an index object by
    type (IvfPqIndex → ``ivf_pq.search_resilient``, IvfFlatIndex →
    ``ivf_flat.search_resilient``). Imports lazily so a registry of
    flat-only tenants never pays the PQ module import."""
    kind = type(index).__name__
    if kind == "IvfPqIndex":
        from raft_tpu.neighbors import ivf_pq

        return ivf_pq.search_resilient
    if kind == "IvfFlatIndex":
        from raft_tpu.neighbors import ivf_flat

        return ivf_flat.search_resilient
    raise TypeError(
        f"no resilient search entry for index type {kind!r} — the "
        "serving layer dispatches IvfPqIndex / IvfFlatIndex tenants")


def dispatch_batch(tenant: Tenant, queries, k: int,
                   deadline: Optional[Deadline] = None,
                   registry: Any = None) -> Tuple[Any, Any]:
    """Run one micro-batch for ``tenant`` under the shared ``deadline``.

    Returns device arrays ``(distances, ids)`` blocked-until-ready (the
    server's latency histogram must measure delivered results, not
    dispatch enqueue). Raises :class:`DeadlineExceeded` when the budget
    is already gone before any chip work, :class:`ShedError`
    (``overload``) when even the fully-degraded ladder cannot complete,
    and propagates anything else as the tenant's failure.

    ``registry`` (the tenant's :class:`~raft_tpu.serve.registry.
    IndexRegistry`, optional) receives the degraded-health demotion
    through its lock (``note_degraded``) when the ladder moves — an
    unlocked write from here could race a concurrent eviction."""
    import jax

    if deadline is not None and deadline.expired:
        # refuse doomed work before it costs chip time — queue wait
        # already spent this request's budget
        raise DeadlineExceeded("serve.dispatch", deadline)
    _faults.faultpoint("serve.dispatch")
    # snapshot the index ONCE: a concurrent pressure eviction sets
    # tenant.index = None at any time; holding our own reference keeps
    # the arrays alive for this batch (in-flight work completes) and an
    # already-gone index is the typed refusal, not a NoneType crash
    index = tenant.index
    if index is None:
        from raft_tpu.serve.errors import TenantUnknown

        raise TenantUnknown(tenant.name, state=tenant.state)
    search = resilient_entry(index)
    # per-thread monotonic, NOT len(recent_steps()): the recent ring
    # saturates at its capacity (which would silently stop
    # degraded-health marking exactly in the sustained-overload runs it
    # exists for), and the global ring also collects OTHER threads'
    # ladder moves — this dispatch's walk runs in THIS stack
    degrade_mark = _degrade.steps_seen()
    def attempt():
        # the deadline reaches BOTH layers: retry_call's backoff clamps
        # to it, and the ladder inside search_resilient draws from it —
        # one request, one budget, no per-site stacking. The tenant's
        # dataset rides along as the refined search's re-rank base
        # (ISSUE 17): a host-resident dataset routes the exact re-rank
        # through the tiered candidate-row prefetch, labeled per
        # tenant by the serving_tenant bracket; refine="none" tenants
        # ignore it
        from raft_tpu.neighbors import tiered as _tiered

        with _tiered.serving_tenant(tenant.name):
            return search(index, queries, k, tenant.params,
                          dataset=tenant.dataset, deadline=deadline)

    retry_stats: dict = {}
    # the quality gate (ISSUE 16): a tenant the SLO monitor holds
    # recall-floor-breached must not walk recall-trading rungs — the
    # gate brackets the whole retry+ladder region, thread-locally. The
    # un-breached common case gets gate=None (a no-op bracket).
    monitor = _slo.get_monitor()
    gate = (monitor.quality_gate_for(tenant.name)
            if monitor is not None else None)
    # cost attribution (ISSUE 20): obs off costs exactly this flag
    # check — no clock read, no ledger lookup (the PR-1 contract)
    costing = _spans.enabled()
    t0 = time.perf_counter() if costing else 0.0
    with _spans.span("serve.dispatch") as sp:
        try:
            with _degrade.quality_gate(gate):
                dist, ids = _retry.retry_call(
                    attempt, site="serve.dispatch",
                    policy=DISPATCH_RETRY_POLICY, deadline=deadline,
                    stats=retry_stats)
            jax.block_until_ready((dist, ids))
        except _degrade.DegradationExhausted as e:
            # the ladder walked every rung and the batch still cannot
            # run — the request group is shed, the server backs off
            raise ShedError("overload", str(e)) from e
        # the request context installed by the batcher stamps this
        # span's event with the batch's trace ids; attempts rides too
        # so a drill-down sees retry pressure without counting markers
        sp.annotate(tenant=tenant.name, batch=int(queries.shape[0]), k=k,
                    attempts=retry_stats.get("attempts", 1))
    if costing:
        ledger = _cost.get_ledger()
        if ledger is not None:
            # the batch's device-inclusive wall time (the block above
            # waited on the result), prorated across the coalesced
            # context's live members — shed members never reached this
            # batch, padding waste rides the members that filled it
            ctx = _trace.current_request()
            n = (len(ctx.trace_ids) if ctx is not None and ctx.trace_ids
                 else int(queries.shape[0]))
            ledger.note_batch(time.perf_counter() - t0,
                              [tenant.name] * n)
    if _degrade.steps_seen() > degrade_mark and registry is not None:
        # the ladder moved during this dispatch: the tenant is serving,
        # but on a degraded configuration — surface it as health,
        # through the registry's lock so a concurrent eviction/failure
        # is never resurrected into residency
        registry.note_degraded(tenant.name)
    # a deadline that expired DURING the work is the server's call, not
    # ours: results are correct (just late), so the front end delivers
    # them and counts the miss per request (serve.deadline_missed)
    return dist, ids
