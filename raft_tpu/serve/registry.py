"""Multi-tenant index registry — N resident indexes sharing one chip's HBM.

The serving story's capacity half (ISSUE 14): "millions of users" means
many *indexes*, not one — per-customer collections, per-language
shards, staging-vs-prod twins — and one chip's HBM is the scarce thing
they share. The registry makes residency an explicit, observable
policy instead of an allocator surprise:

- **admission** — :meth:`IndexRegistry.admit` sizes the candidate
  (every device-resident pytree leaf) against the HBM budget. The
  budget comes from the PR-1 HBM gauges (``obs.hbm.bytes_limit``) when
  the backend reports one, minus a configurable headroom fraction for
  scan transients; backends that report nothing (the CPU test mesh)
  take an explicit ``budget_bytes``.
- **demotion before eviction** — the memory tier (ISSUE 17): when a
  new tenant doesn't fit, the registry first *demotes* resident
  tenants' raw vectors to host memory (coldest first; the refined
  search keeps serving EXACT answers through the tiered candidate-row
  prefetch, :mod:`raft_tpu.neighbors.tiered`), and only then sheds the
  least-recently-used *cold* resident (never pinned tenants), or
  refuses with a typed :class:`~raft_tpu.serve.errors.AdmissionError`.
  :class:`~raft_tpu.serve.placement.Placement` records where each
  tenant's components live; ``index.bytes{index=,tier=hbm|host}``
  gauges the split. Every move is counted:
  ``serve.registry.admit{tenant=}`` / ``serve.registry.evict{tenant=,
  reason=}`` / ``serve.registry.demote{tenant=}`` /
  ``serve.registry.promote{tenant=}`` (demote/promote also land as
  ``degrade.steps{to=demote_raw}`` moves — one observable degradation
  policy), with ``serve.registry.resident_bytes`` gauging the fleet.
- **health** — each tenant carries an explicit state machine
  (``warming → serving → degraded``, terminal ``evicted`` / ``failed``)
  so dispatch can refuse, a dashboard can page, and the chaos lane can
  assert on the transition instead of inferring it from crashes.

Fault point ``serve.registry.admit`` lets the chaos lane force
admission-time failures (an OOM while warming a tenant must mark it
``failed``, not wedge the registry lock).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

from raft_tpu.core import logging as _log
from raft_tpu.obs import capacity as _capacity
from raft_tpu.obs import hbm as _hbm
from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _spans
from raft_tpu.robust import faults as _faults
from raft_tpu.serve import placement as _placement
from raft_tpu.serve.errors import AdmissionError, TenantUnknown
from raft_tpu.serve.placement import Placement

__all__ = ["Tenant", "IndexRegistry", "index_device_bytes",
           "index_bytes_by_tier", "Placement", "HEALTH_STATES"]

# The tenant state machine. RESIDENT states hold HBM; terminal states
# keep the Tenant record (for "why is my tenant gone" forensics) but
# not the index.
HEALTH_STATES = ("warming", "serving", "degraded", "evicted", "failed")
_RESIDENT = ("warming", "serving", "degraded")

# CPU/test-mesh fallback budget when the backend reports no bytes_limit
# and the caller pins none: generous enough for test tenants, small
# enough that a runaway admission loop still trips AdmissionError.
DEFAULT_BUDGET_BYTES = 8 << 30


def index_device_bytes(index: Any) -> int:
    """HBM residency estimate for an index: the sum of every
    DEVICE-RESIDENT (``jax.Array``) leaf's ``nbytes`` in the pytree.
    Host-resident leaves — numpy arrays, memmaps — are the memory
    tier's point (ISSUE 17): they cost ZERO HBM and must not be charged
    against the admission budget, or a tenant whose raw vectors live on
    the host would be billed for capacity it never uses. (Indexes are
    device pytrees at admission — build/load put every component on
    device — so nothing here "lands on device at first dispatch".)"""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(index):
        if isinstance(leaf, jax.Array):
            total += int(leaf.nbytes)
    return total


def index_bytes_by_tier(index: Any, dataset: Any = None) -> Dict[str, int]:
    """``{"hbm": ..., "host": ...}`` byte split of an index pytree plus
    an optional re-rank ``dataset`` — the honest-accounting twin of
    :func:`index_device_bytes` for the ``index.bytes{tier=}`` gauges
    and ``/indexz``: jax.Array leaves are HBM, every other
    nbytes-bearing leaf (numpy, memmap) is host."""
    import jax

    out = {"hbm": 0, "host": 0}
    leaves = list(jax.tree_util.tree_leaves(index))
    if dataset is not None:
        leaves.append(dataset)
    for leaf in leaves:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            continue
        tier = "hbm" if isinstance(leaf, jax.Array) else "host"
        out[tier] += int(nbytes)
    return out


@dataclasses.dataclass
class Tenant:
    """One resident index + its serving policy and health."""

    name: str
    index: Any
    params: Any = None            # default SearchParams for dispatch
    default_k: int = 10
    # the tenant's CLOSED k surface: the server AOT-warms every
    # (bucket × k) in this set and submit() rejects anything outside it
    # — an un-warmed k would recompile on the serving path, a
    # head-of-line latency spike the recompile_budget(0) contract bans
    serve_ks: tuple = ()
    size_bytes: int = 0
    pinned: bool = False          # never auto-evicted
    state: str = "warming"
    admitted_at: float = 0.0
    last_used: float = 0.0        # monotonic; the LRU eviction key
    requests: int = 0
    # the quality plane (ISSUE 16): ``dataset`` is the exact ground the
    # shadow verifier replays against (no dataset → no verification for
    # this tenant — counted, never an error); ``recall_floor`` arms the
    # SLO monitor's closed loop (CI lower bound below it → degraded
    # health + quality-rung gate); ``index_stats`` caches the
    # admission-time health introspection for /indexz
    dataset: Any = None
    recall_floor: Optional[float] = None
    index_stats: Optional[Dict[str, Any]] = None
    # the memory tier (ISSUE 17): where this tenant's components live.
    # ``raw_hbm_bytes`` remembers the dataset's device footprint so a
    # demotion knows how much HBM it returns and a re-promotion how
    # much it must find; ``demoted`` marks raw=host as PRESSURE-driven
    # (promote_when_clear re-promotes only these — a tenant admitted
    # host-resident by choice stays host-resident)
    placement: Optional[Placement] = None
    raw_hbm_bytes: int = 0
    demoted: bool = False

    def describe(self) -> Dict[str, Any]:
        """Registry snapshot row (flight dumps / debugging)."""
        out = {"name": self.name, "state": self.state,
               "size_bytes": self.size_bytes, "pinned": self.pinned,
               "requests": self.requests}
        if self.recall_floor is not None:
            out["recall_floor"] = self.recall_floor
        if self.placement is not None:
            out["placement"] = self.placement.describe()
            if self.demoted:
                out["demoted"] = True
        return out


def _count(name: str, labels: Dict[str, str]) -> None:
    if _spans.enabled():
        _spans.registry().inc(name, labels=labels)


def _gauge(name: str, value: float) -> None:
    if _spans.enabled():
        _spans.registry().gauge(name).set(value)


class IndexRegistry:
    """Thread-safe registry of resident tenants under one HBM budget.

    ``budget_bytes=None`` reads the device's ``bytes_limit`` HBM gauge
    (PR 1), falling back to :data:`DEFAULT_BUDGET_BYTES` on backends
    that report nothing; ``headroom_frac`` of the budget is reserved
    for scan/refine transients (the working set a search needs beyond
    the index itself)."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 headroom_frac: float = 0.10):
        if budget_bytes is None:
            budget_bytes = _hbm.bytes_limit(default=DEFAULT_BUDGET_BYTES)
        if not 0.0 <= headroom_frac < 1.0:
            raise ValueError(f"headroom_frac {headroom_frac} not in [0, 1)")
        self.budget_bytes = int(budget_bytes)
        self.headroom_frac = float(headroom_frac)
        self._tenants: Dict[str, Tenant] = {}
        self._lock = _sanitize.monitored_rlock("serve.registry")
        if _spans.enabled():
            # mirror the admission budget into the hbm.bytes_limit
            # family (its own {source=admission} series — never the
            # allocator's readings) so the exposition endpoint's hbm_*
            # families stay populated even on allocator-less backends
            _hbm.note_budget(self.budget_bytes, _spans.registry())

    # -- capacity -----------------------------------------------------------
    @property
    def usable_bytes(self) -> int:
        """The admission ceiling: budget minus transient headroom."""
        return int(self.budget_bytes * (1.0 - self.headroom_frac))

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(t.size_bytes for t in self._tenants.values()
                       if t.state in _RESIDENT)

    def _evict_candidates(self) -> List[Tenant]:
        """Evictable residents, coldest first (LRU by last dispatch)."""
        return sorted((t for t in self._tenants.values()
                       if t.state in _RESIDENT and not t.pinned),
                      key=lambda t: t.last_used)

    def _demote_candidates(self) -> List[Tenant]:
        """Residents whose raw vectors could move to host (device
        dataset, not pinned), coldest first — the demote-before-evict
        plan walks these."""
        import jax

        return [t for t in self._evict_candidates()
                if isinstance(t.dataset, jax.Array)]

    # -- lifecycle ----------------------------------------------------------
    def admit(self, name: str, index: Any, *, params: Any = None,
              default_k: int = 10, ks: Optional[Any] = None,
              pinned: bool = False,
              size_bytes: Optional[int] = None,
              dataset: Any = None,
              recall_floor: Optional[float] = None,
              placement: Optional[Placement] = None) -> Tenant:
        """Admit ``index`` as tenant ``name``, demoting resident
        tenants' raw vectors to host and then evicting LRU cold
        tenants as needed to fit under :attr:`usable_bytes`. Raises
        :class:`AdmissionError` when the index cannot fit even after
        shedding every evictable resident (or is alone too big for the
        budget). ``ks`` enumerates the tenant's served k values
        (default: just ``default_k``) — the server warms exactly this
        set and refuses others. ``dataset`` (optional) is the tenant's
        source rows — the shadow verifier's exact ground truth AND the
        refined search's re-rank base — and ``recall_floor`` its
        quality SLO (ISSUE 16): a tenant whose live recall CI falls
        below the floor is demoted and its recall-trading ladder rungs
        gated. ``placement`` (ISSUE 17) declares where components
        live; the default is inferred from the dataset's residency
        (``Placement(codes="hbm", raw="hbm"|"host"|"none")``). A
        declared ``raw="host"`` with a device dataset demotes it at
        admission (one D2H copy); ``raw="hbm"`` with a host dataset is
        a contradiction and raises. HBM sizing is honest: only
        device-resident components count (a host-resident raw base
        costs zero budget). Re-admitting a live name replaces it.
        Admission is
        all-or-nothing: the demotion + eviction set (including a
        replaced prior) is PLANNED before anything is released, so a
        refused admission leaves every resident tenant — the prior
        under this name included — exactly as it was (a failed
        hot-swap must not destroy the serving tenant)."""
        import jax

        _faults.faultpoint("serve.registry.admit")
        if placement is None:
            placement = _placement.placement_for(dataset)
        elif placement.raw == "hbm" and not isinstance(dataset,
                                                       jax.Array):
            raise AdmissionError(
                f"tenant {name!r} declares Placement(raw='hbm') but "
                f"its dataset is {'missing' if dataset is None else 'host-resident'} "
                "— hand a device array or declare raw='host'")
        elif placement.raw == "host" and isinstance(dataset, jax.Array):
            # declared host residency wins: demote at admission (one
            # D2H copy) so the budget math below sees the real tiers
            dataset = _placement.to_host(dataset)
        elif placement.raw != "none" and dataset is None:
            raise AdmissionError(
                f"tenant {name!r} declares Placement(raw="
                f"{placement.raw!r}) without a dataset")
        raw_hbm = int(dataset.nbytes) if isinstance(dataset, jax.Array) \
            else 0
        size = (index_device_bytes(index) + raw_hbm) \
            if size_bytes is None else int(size_bytes)
        with self._lock:
            if size > self.usable_bytes:
                raise AdmissionError(
                    f"tenant {name!r} needs {size:,} B but the usable "
                    f"budget is {self.usable_bytes:,} B "
                    f"({self.budget_bytes:,} B minus "
                    f"{self.headroom_frac:.0%} headroom)")
            prior = self._tenants.get(name)
            replacing = prior is not None and prior.state in _RESIDENT
            # simulate first: the prior's bytes come back for free,
            # then raw-vector demotions (coldest first — HBM reclaimed,
            # tenants keep serving exact answers via the tiered
            # prefetch), then LRU victims until the candidate fits — or
            # nobody moves
            projected = self.resident_bytes()
            if replacing:
                projected -= prior.size_bytes
            demotions: List[Tenant] = []
            for cand in self._demote_candidates():
                if projected + size <= self.usable_bytes:
                    break
                if cand.name == name:
                    continue  # the prior is accounted above
                demotions.append(cand)
                projected -= int(cand.dataset.nbytes)
            victims: List[Tenant] = []
            for cand in self._evict_candidates():
                if projected + size <= self.usable_bytes:
                    break
                if cand.name == name:
                    continue  # the prior is accounted above
                victims.append(cand)
                projected -= cand.size_bytes
                if cand in demotions:
                    # evicting it releases the whole tenant — do not
                    # double-count the planned raw demotion
                    demotions.remove(cand)
                    projected += int(cand.dataset.nbytes)
            if projected + size > self.usable_bytes:
                raise AdmissionError(
                    f"tenant {name!r} ({size:,} B) does not fit: "
                    f"{self.resident_bytes():,} B resident are pinned "
                    f"or un-evictable under the {self.usable_bytes:,} B "
                    "usable budget")
            # capacity-forecast hook (ISSUE 20): the plan above handles
            # the pressure cliff; the installed capacity model looks
            # AHEAD. When the resident-bytes trend (plus this
            # candidate) saturates HBM inside the policy horizon,
            # demote additional raw tiers NOW — coldest first, enough
            # to cover the projected growth — so the admission that
            # WOULD have hit the cliff mid-horizon demotes calmly
            # today instead. Counted apart from pressure demotions
            # (``serve.registry.preemptive_demote{tenant=}``).
            preemptive: List[Tenant] = []
            model = _capacity.get_model()
            if model is not None and model.would_saturate(
                    extra_bytes=float(size)):
                need = (float(projected + size)
                        + model.projected_growth_bytes()
                        - float(self.usable_bytes))
                for cand in self._demote_candidates():
                    if need <= 0.0:
                        break
                    if cand.name == name or cand in demotions \
                            or cand in victims:
                        continue
                    preemptive.append(cand)
                    need -= float(cand.dataset.nbytes)
            # commit: the admission is now guaranteed to succeed
            for demo in demotions:
                self._demote_locked(demo, reason="pressure")
            for demo in preemptive:
                self._demote_locked(demo, reason="preemptive")
                _count("serve.registry.preemptive_demote",
                       {"tenant": demo.name})
            for victim in victims:
                self._evict_locked(victim, reason="pressure")
            if replacing:
                self._evict_locked(prior, reason="replaced")
            now = time.monotonic()
            serve_ks = tuple(sorted({int(k) for k in (ks or [default_k])}
                                    | {int(default_k)}))
            tenant = Tenant(name=name, index=index, params=params,
                            default_k=default_k, serve_ks=serve_ks,
                            size_bytes=size,
                            pinned=pinned, state="warming",
                            admitted_at=now, last_used=now,
                            dataset=dataset,
                            recall_floor=(None if recall_floor is None
                                          else float(recall_floor)),
                            placement=placement, raw_hbm_bytes=raw_hbm)
            self._tenants[name] = tenant
            self._note_tier_bytes(tenant)
            # admission-time health introspection (ISSUE 16): list skew
            # always (one [n_lists] transfer); drift + PQ quantization
            # error only when the caller handed a dataset (the quality-
            # plane serving path) — kept off the plain admit so tests
            # and verification-less serving pay nothing new. Cached on
            # the tenant for /indexz; gauges land as index.*{index=}.
            from raft_tpu.obs import index_stats as _istats

            if dataset is not None:
                stats = _istats.describe_index(index, dataset)
                _istats.note_index_stats(index, name=name, stats=stats)
                tenant.index_stats = stats
            elif _spans.enabled():
                tenant.index_stats = _istats.note_index_stats(
                    index, name=name, cheap=True)
            _count("serve.registry.admit", {"tenant": name})
            _gauge("serve.registry.resident_bytes", self.resident_bytes())
            _log.info("registry: admitted %r (%s B, pinned=%s, "
                      "%d resident)", name, f"{size:,}", pinned,
                      len(self.resident()))
            return tenant

    def _note_tier_bytes(self, tenant: Tenant) -> None:
        """Publish the tenant's HBM-vs-host byte split as
        ``index.bytes{index=,tier=}`` gauges (obs.index_stats owns the
        family) — a demoted tenant is visible at a glance."""
        if not _spans.enabled():
            return
        from raft_tpu.obs import index_stats as _istats

        index = tenant.index
        if index is None:  # terminal: both tiers read zero
            _istats.note_tier_bytes(tenant.name, hbm_bytes=0,
                                    host_bytes=0)
            return
        split = index_bytes_by_tier(index, tenant.dataset)
        _istats.note_tier_bytes(tenant.name, hbm_bytes=split["hbm"],
                                host_bytes=split["host"])

    def _evict_locked(self, tenant: Tenant, reason: str) -> None:
        tenant.state = "evicted"
        tenant.index = None  # drop the reference; GC frees the HBM
        _count("serve.registry.evict",
               {"tenant": tenant.name, "reason": reason})
        _gauge("serve.registry.resident_bytes", self.resident_bytes())
        self._note_tier_bytes(tenant)
        _log.warn("registry: evicted %r (%s)", tenant.name, reason)

    def _demote_locked(self, tenant: Tenant, reason: str) -> None:
        """Move a resident tenant's raw vectors HBM → host (ISSUE 17):
        one D2H copy, ``size_bytes`` gives back the dataset's device
        footprint, and the refined search keeps serving EXACT answers
        through the tiered prefetch (the dataset reference swap is
        atomic under the GIL; an in-flight dispatch holding the device
        array finishes on it). Counted both as the registry's own move
        (``serve.registry.demote{tenant=}``) and as the fleet-wide
        degradation policy's (``degrade.steps{to=demote_raw}``) — the
        chaos lane asserts demotion fires BEFORE any eviction on that
        one family."""
        from raft_tpu.robust import degrade as _degrade

        raw_bytes = int(tenant.dataset.nbytes)
        tenant.dataset = _placement.to_host(tenant.dataset)
        tenant.raw_hbm_bytes = raw_bytes
        tenant.demoted = True
        tenant.size_bytes = max(0, tenant.size_bytes - raw_bytes)
        if tenant.placement is not None:
            tenant.placement = dataclasses.replace(tenant.placement,
                                                   raw="host")
        _count("serve.registry.demote", {"tenant": tenant.name})
        _degrade.note_step("serve.registry", "raw_hbm", "demote_raw",
                           reason)
        _gauge("serve.registry.resident_bytes", self.resident_bytes())
        self._note_tier_bytes(tenant)
        _log.warn("registry: demoted %r raw vectors to host "
                  "(%s B reclaimed, %s)", tenant.name,
                  f"{raw_bytes:,}", reason)

    def demote_raw(self, name: str, reason: str = "manual") -> None:
        """Explicitly demote a tenant's raw vectors to host memory
        (idempotent on already-host or dataset-less tenants; unknown
        or terminal tenants raise)."""
        import jax

        with self._lock:
            tenant = self.peek(name)
            if isinstance(tenant.dataset, jax.Array):
                self._demote_locked(tenant, reason=reason)

    def promote_when_clear(self) -> List[str]:
        """Re-promote pressure-demoted raw vectors while headroom
        allows (hottest first — the tenant paying the host hop most
        often gets its HBM back first). Called after explicit
        evictions free budget; returns the promoted tenant names.
        Only PRESSURE demotions promote: a tenant admitted with
        ``Placement(raw="host")`` chose the tier and keeps it."""
        promoted: List[str] = []
        with self._lock:
            cands = sorted(
                (t for t in self._tenants.values()
                 if t.state in _RESIDENT and t.demoted
                 and t.dataset is not None),
                key=lambda t: -t.last_used)
            for tenant in cands:
                need = tenant.raw_hbm_bytes or int(tenant.dataset.nbytes)
                if self.resident_bytes() + need > self.usable_bytes:
                    continue
                tenant.dataset = _placement.to_device(tenant.dataset)
                tenant.size_bytes += int(tenant.dataset.nbytes)
                tenant.demoted = False
                if tenant.placement is not None:
                    tenant.placement = dataclasses.replace(
                        tenant.placement, raw="hbm")
                _count("serve.registry.promote", {"tenant": tenant.name})
                _gauge("serve.registry.resident_bytes",
                       self.resident_bytes())
                self._note_tier_bytes(tenant)
                _log.info("registry: re-promoted %r raw vectors to HBM "
                          "(%s B)", tenant.name,
                          f"{int(tenant.dataset.nbytes):,}")
                promoted.append(tenant.name)
        return promoted

    def evict(self, name: str, reason: str = "manual") -> None:
        """Explicitly release a tenant's residency (idempotent on
        already-terminal tenants; unknown names raise). Freed budget
        re-promotes pressure-demoted raw vectors
        (:meth:`promote_when_clear`)."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise TenantUnknown(name)
            if tenant.state in _RESIDENT:
                self._evict_locked(tenant, reason=reason)
                self.promote_when_clear()

    def mark(self, name: str, state: str) -> None:
        """Health transition (``warming``/``serving``/``degraded``/
        ``failed``/``evicted``). Terminal states release the index:
        ``evicted`` routes through the same path as :meth:`evict`
        (counted, gauge updated) and ``failed`` drops the reference —
        either way a terminal tenant can never pin HBM that
        ``resident_bytes()`` no longer counts."""
        assert state in HEALTH_STATES, state
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise TenantUnknown(name)
            if tenant.state not in _RESIDENT:
                # terminal states are FINAL: a slow lock-free warmup
                # finishing with mark("serving") after a concurrent
                # pressure eviction must not resurrect an index-less
                # record into residency (phantom resident_bytes + an
                # untyped NoneType crash at the next dispatch)
                return
            if state == "evicted":
                self._evict_locked(tenant, reason="manual")
                return
            tenant.state = state
            if state == "failed":
                tenant.index = None
                _gauge("serve.registry.resident_bytes",
                       self.resident_bytes())

    def note_degraded(self, name: str) -> None:
        """Lock-protected health demotion from dispatch: a live tenant
        whose ladder moved becomes ``degraded``; anything else —
        terminal states above all — is left alone (an unlocked
        check-then-set from the batcher could otherwise resurrect a
        concurrently-evicted record into residency). Unknown names are
        a no-op: the tenant may have been dropped mid-dispatch."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is not None and tenant.state in ("warming",
                                                       "serving"):
                tenant.state = "degraded"

    def note_recovered(self, name: str) -> None:
        """Lock-protected promotion back to ``serving`` — the closed
        half of the quality loop (ISSUE 16): the SLO monitor calls this
        when a tenant it demoted for a recall-floor breach shows fresh
        evidence above the floor. Only ``degraded`` promotes — terminal
        states stay final (same resurrection hazard as
        :meth:`note_degraded`) and ``warming`` stays the server's to
        finish. Unknown names are a no-op."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is not None and tenant.state == "degraded":
                tenant.state = "serving"

    # -- lookup -------------------------------------------------------------
    def peek(self, name: str) -> Tenant:
        """Side-effect-free lookup: resolves a RESIDENT tenant WITHOUT
        touching its LRU clock. The validation lookup — submit-time
        checks (and warmup) must not heat a tenant's eviction recency:
        a flood of shed/invalid traffic would otherwise keep a tenant
        LRU-hot while quieter tenants actually serving requests get
        evicted. Unknown or terminal tenants raise
        :class:`TenantUnknown`."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise TenantUnknown(name)
            if tenant.state not in _RESIDENT:
                raise TenantUnknown(name, state=tenant.state)
            return tenant

    def get(self, name: str) -> Tenant:
        """The dispatch lookup: :meth:`peek` + touch the LRU clock
        (``last_used`` = last *dispatched*, the eviction recency key).
        ``Tenant.requests`` is accounted by the server per accepted
        request, not here."""
        with self._lock:
            tenant = self.peek(name)
            tenant.last_used = time.monotonic()
            return tenant

    def resident(self) -> List[Tenant]:
        """Resident tenants (any health), admission order."""
        with self._lock:
            return [t for t in self._tenants.values()
                    if t.state in _RESIDENT]

    def tenants(self) -> List[Tenant]:
        """All tenants including terminal ones (forensics)."""
        with self._lock:
            return list(self._tenants.values())

    def describe(self) -> Dict[str, Any]:
        """Snapshot for flight dumps / logs."""
        with self._lock:
            return {"budget_bytes": self.budget_bytes,
                    "usable_bytes": self.usable_bytes,
                    "resident_bytes": self.resident_bytes(),
                    "tenants": [t.describe()
                                for t in self._tenants.values()]}
