"""raft_tpu.serve — resilient online serving (ISSUE 14).

The request-shaped half of the system: everything upstream measures
offline batch sweeps; this package serves single-query traffic with
production manners — the reference's runtime/pylibraft deployment
story that nothing upstream provides on TPU.

- :mod:`raft_tpu.serve.server`   — micro-batching front end:
  shape-bucketed coalescing, AOT-warmed buckets (provably zero
  steady-state recompiles under ``recompile_budget(0)``), bounded
  queue with typed load shedding;
- :mod:`raft_tpu.serve.registry` — multi-tenant index registry: N
  resident indexes under one HBM budget (PR-1 gauges), LRU eviction
  under pressure, per-tenant health states;
- :mod:`raft_tpu.serve.dispatch` — SLO-aware dispatch: one
  :class:`~raft_tpu.robust.retry.Deadline` per request drawn down by
  queue wait + batching + search + retries + the PR-7 degrade ladder
  (the overload path);
- :mod:`raft_tpu.serve.loadgen`  — open-loop (Poisson) load generator
  recording latency-vs-throughput curves with p50/p99 from the PR-5
  histogram quantiles;
- :mod:`raft_tpu.serve.errors`   — the typed refusal surface
  (``ShedError{reason=}``, ``TenantUnknown``, ``AdmissionError``) —
  every failure is a type, never a hang;
- :mod:`raft_tpu.serve.placement` — memory-tier placement (ISSUE 17):
  where a tenant's pieces live (scan structures HBM-resident, raw
  re-rank vectors HBM or host), the registry's ``demote_raw`` pressure
  valve riding on it;
- :mod:`raft_tpu.serve.slo`      — SLO guardrails (ISSUE 16):
  multi-window burn rates over the latency/shed series, and per-tenant
  recall floors closing the loop from the shadow verifier's confidence
  intervals to health state and the degrade-ladder quality gate;
- :mod:`raft_tpu.serve.router`   — fleet router (ISSUE 19): tenant
  placement across pods (replicate hot, keep sharded builds on their
  pod), the one request Deadline carried across the pod hop, and the
  PR-15 straggler table consumed as a steering control loop with typed
  failover/shed accounting (``serve.router.*`` counters).

Counters: ``serve.requests``, ``serve.shed{reason=}``,
``serve.batch_fill``, ``serve.latency_s``, ``serve.deadline_missed``,
``serve.registry.{admit,evict,demote,promote}``,
``serve.prefetch.{hit,stall}`` — see docs/observability.md; chaos
coverage in tests/test_serve.py and the CI serve smoke.
"""

from raft_tpu.serve.dispatch import dispatch_batch  # noqa: F401
from raft_tpu.serve.errors import (  # noqa: F401
    AdmissionError,
    Deadline,
    DeadlineExceeded,
    ServeError,
    ShedError,
    TenantUnknown,
)
from raft_tpu.serve.loadgen import record, run_step, sweep  # noqa: F401
from raft_tpu.serve.placement import Placement  # noqa: F401
from raft_tpu.serve.router import (  # noqa: F401
    FleetRouter,
    Pod,
    RouterPolicy,
    clear_router,
    get_router,
    set_router,
)
from raft_tpu.serve.registry import (  # noqa: F401
    IndexRegistry,
    Tenant,
    index_bytes_by_tier,
    index_device_bytes,
)
from raft_tpu.serve.server import (  # noqa: F401
    MicroBatchServer,
    ServerConfig,
    bucket_for,
    bucket_sizes,
)
from raft_tpu.serve.slo import (  # noqa: F401
    SLOMonitor,
    SLOPolicy,
    get_monitor,
    set_monitor,
)
