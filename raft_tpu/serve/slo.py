"""SLO guardrails — multi-window burn rates and closed-loop recall floors.

The quality plane's policy half (ISSUE 16), the SRE multi-window
burn-rate shape (Beyer et al., *The Site Reliability Workbook*) applied
to the serving metrics this repo already has plus the recall evidence
the shadow verifier (:mod:`raft_tpu.obs.quality`) produces:

- **burn rates** — per configured window, the fraction of requests
  gone bad (sheds + deadline misses + latency over the SLO threshold)
  divided by the error budget (1 − availability target), from deltas
  over a timestamped ring of metric snapshots. Exposed as
  ``slo.burn_rate{window=}`` gauges; a window burning over
  ``burn_threshold`` counts ``slo.burn_alert{window=}``.
- **recall floors, closed-loop** — a tenant admitted with
  ``recall_floor=r`` is *breached* when any served k's Wilson CI lower
  bound sits below ``r`` with enough evidence (``min_samples``). A
  breach (1) demotes the tenant to ``degraded`` (``/healthz`` flips),
  and (2) arms the degrade ladder's **quality gate**: rungs that trade
  recall (``bf16_lut`` / ``fp8_lut`` / ``decline_fused``) are refused
  for that tenant — counted ``degrade.refused{reason=recall_floor}`` —
  so overload *sheds* instead of silently serving bad answers. When
  fresh verdicts lift the CI back above the floor, the tenant is
  promoted back to ``serving`` and the gate disarms — no operator in
  the loop.

The monitor is registered process-globally (:func:`set_monitor`) so
``serve.dispatch`` — which cannot see the server object — can fetch the
quality gate for the tenant it is about to run.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _spans
from raft_tpu.obs.capacity import DeltaRing
from raft_tpu.obs.metrics import counter_sum as _counter_sum

__all__ = ["SLOPolicy", "SLOMonitor", "set_monitor", "get_monitor",
           "clear_monitor"]


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Guardrail knobs. ``windows_s`` are the burn-rate lookbacks
    (short = fast detection, long = low noise — alert shape pairs
    them); ``availability_target`` sets the error budget;
    ``latency_slo_s`` counts completions over it as bad (None = only
    sheds/misses burn budget); ``min_samples`` is the evidence bar a
    recall verdict window must clear before a floor can trip or
    recover (a floor must not flap on two unlucky samples)."""

    windows_s: Tuple[float, ...] = (30.0, 300.0)
    availability_target: float = 0.999
    burn_threshold: float = 2.0
    latency_slo_s: Optional[float] = None
    min_samples: int = 8


def _latency_totals(rows: List[Dict[str, Any]],
                    slo_s: Optional[float]) -> Tuple[float, float]:
    """(completions, completions within ``slo_s``) from the
    ``serve.latency_s`` histogram rows (cumulative buckets: the count
    at the smallest upper bound ≥ the threshold — standard
    histogram-quantile resolution)."""
    count = good = 0.0
    for r in rows:
        if r.get("kind") != "histogram" or r.get("name") != "serve.latency_s":
            continue
        count += float(r.get("count", 0))
        if slo_s is None:
            continue
        best_ub, best_cum = None, 0.0
        for key, cum in (r.get("buckets") or {}).items():
            ub = float("inf") if key == "+inf" else float(key)
            if ub >= slo_s and (best_ub is None or ub < best_ub):
                best_ub, best_cum = ub, float(cum)
        good += best_cum
    if slo_s is None:
        good = count
    return count, good


class SLOMonitor:
    """Burn-rate + recall-floor evaluation over a registry and (when
    sampling is on) a :class:`~raft_tpu.obs.quality.RecallVerifier`.

    :meth:`evaluate` is cheap (one metrics collect + dict walks) and is
    driven from verdict callbacks and health scrapes — no timer thread
    of its own."""

    def __init__(self, registry: Any, verifier: Any = None,
                 policy: Optional[SLOPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.verifier = verifier
        self.policy = policy or SLOPolicy()
        self._clock = clock
        self._lock = _sanitize.monitored_lock("serve.slo")
        keep = max(self.policy.windows_s) * 1.5 if self.policy.windows_s \
            else 300.0
        # the multi-window delta ring, shared shape with the capacity
        # model (ISSUE 20 extracted it to obs.capacity.DeltaRing)
        self._ring = DeltaRing(keep)
        self._floor_breached: set = set()

    # -- burn rates ---------------------------------------------------------
    def _totals(self) -> Dict[str, float]:
        if not _spans.enabled():
            return {"requests": 0.0, "bad": 0.0, "completed": 0.0,
                    "good": 0.0}
        rows = _spans.registry().collect()
        shed = _counter_sum(rows, "serve.shed")
        missed = _counter_sum(rows, "serve.deadline_missed")
        requests = _counter_sum(rows, "serve.requests")
        completed, good = _latency_totals(rows, self.policy.latency_slo_s)
        slow = max(completed - good, 0.0)
        return {"requests": requests, "bad": shed + missed + slow,
                "completed": completed, "good": good}

    def tick(self) -> None:
        """Append one timestamped totals snapshot and prune the ring."""
        now = self._clock()
        totals = self._totals()
        with self._lock:
            self._ring.append(now, totals)

    def burn_rates(self) -> Dict[float, float]:
        """Per-window burn rate: (bad/total within the window) over the
        error budget. 0.0 while a window holds no traffic."""
        self.tick()
        budget = max(1.0 - self.policy.availability_target, 1e-9)
        with self._lock:
            snaps = self._ring.snaps()
        if not snaps:
            return {w: 0.0 for w in self.policy.windows_s}
        now, newest = snaps[-1]
        out: Dict[float, float] = {}
        for w in self.policy.windows_s:
            base = DeltaRing.window_base(snaps, now, w)
            d_total = newest["requests"] - base["requests"]
            d_bad = newest["bad"] - base["bad"]
            burn = ((d_bad / d_total) / budget) if d_total > 0 else 0.0
            out[w] = burn
            if _spans.enabled():
                labels = {"window": f"{int(w)}s"}
                _spans.registry().gauge("slo.burn_rate",
                                        labels=labels).set(burn)
                if burn > self.policy.burn_threshold:
                    _spans.registry().inc("slo.burn_alert", labels=labels)
        return out

    # -- recall floors ------------------------------------------------------
    def _floor_state(self, tenant: Any) -> Optional[bool]:
        """True = breached, False = provably fine, None = not enough
        evidence either way (state holds)."""
        floor = getattr(tenant, "recall_floor", None)
        if floor is None or self.verifier is None:
            return False
        summary = self.verifier.recall_summary(tenant.name)
        seen = False
        for stats in summary.values():
            if stats.get("n", 0.0) < self.policy.min_samples:
                continue
            seen = True
            if stats.get("ci_low", 1.0) < float(floor):
                return True
        return False if seen else None

    def evaluate(self, tenant_name: Optional[str] = None) -> None:
        """Re-check burn rates and every tenant's recall floor, driving
        the closed loop: breach → demote + gate; recovery → promote +
        disarm. ``tenant_name`` narrows the floor check (the verdict
        callback path); burn gauges always refresh."""
        self.burn_rates()
        try:
            tenants = self.registry.resident()
        except Exception:  # noqa: BLE001 — registry mid-teardown
            return
        for tenant in tenants:
            if tenant_name is not None and tenant.name != tenant_name:
                continue
            breached = self._floor_state(tenant)
            if breached is None:
                continue
            with self._lock:
                was = tenant.name in self._floor_breached
                if breached and not was:
                    self._floor_breached.add(tenant.name)
                elif not breached and was:
                    self._floor_breached.discard(tenant.name)
                else:
                    continue
            if breached:
                if _spans.enabled():
                    _spans.registry().inc(
                        "slo.recall_floor_breach",
                        labels={"tenant": tenant.name})
                try:
                    self.registry.note_degraded(tenant.name)
                except Exception:  # noqa: BLE001
                    pass
                from raft_tpu.core import logging as _log

                _log.warn("slo: tenant %r recall CI fell below floor "
                          "%.3f — degraded, quality rungs gated",
                          tenant.name, float(tenant.recall_floor))
            else:
                if _spans.enabled():
                    _spans.registry().inc(
                        "slo.recall_floor_recovered",
                        labels={"tenant": tenant.name})
                try:
                    self.registry.note_recovered(tenant.name)
                except Exception:  # noqa: BLE001
                    pass
                from raft_tpu.core import logging as _log

                _log.info("slo: tenant %r recall recovered above its "
                          "floor — serving restored", tenant.name)
        if _spans.enabled():
            with self._lock:
                breached = set(self._floor_breached)
            for tenant in tenants:
                if getattr(tenant, "recall_floor", None) is not None:
                    ok = tenant.name not in breached
                    _spans.registry().gauge(
                        "slo.recall_floor_ok",
                        labels={"tenant": tenant.name}).set(
                            1.0 if ok else 0.0)

    # -- the degrade ladder's quality gate -----------------------------------
    def refuse_quality_rung(self, tenant_name: str, rung: str) -> bool:
        """True when ``tenant_name`` is floor-breached: the ladder must
        not take a recall-trading rung for a tenant already serving
        below its recall floor."""
        with self._lock:
            return tenant_name in self._floor_breached

    def quality_gate_for(self, tenant_name: str
                         ) -> Optional[Callable[[str], bool]]:
        """The per-dispatch gate callable for
        :func:`raft_tpu.robust.degrade.quality_gate` — None when the
        tenant is un-breached (the common case costs dispatch one set
        lookup, no closure)."""
        with self._lock:
            if tenant_name not in self._floor_breached:
                return None
        return lambda rung: self.refuse_quality_rung(tenant_name, rung)

    def breached(self) -> List[str]:
        with self._lock:
            return sorted(self._floor_breached)

    # -- health payload ------------------------------------------------------
    def healthz(self) -> Dict[str, Any]:
        """The ``/healthz`` slo section: evaluated-on-scrape burn rates
        + floor-breached tenants (the degraded flip rides the
        registry's tenant states, which :meth:`evaluate` demotes)."""
        self.evaluate()
        burns = self.burn_rates()
        return {"burn_rates": {f"{int(w)}s": round(b, 4)
                               for w, b in burns.items()},
                "burn_threshold": self.policy.burn_threshold,
                "recall_floor_breached": self.breached()}


_monitor: Optional[SLOMonitor] = None
_monitor_lock = _sanitize.monitored_lock("serve.slo.monitor")


def set_monitor(monitor: Optional[SLOMonitor]) -> Optional[SLOMonitor]:
    """Install the process-global monitor (returns the previous one).
    The server installs its monitor at start and clears it at stop so
    dispatch can consult the quality gate without plumbing."""
    global _monitor
    with _monitor_lock:
        prev = _monitor
        _monitor = monitor
        return prev


def get_monitor() -> Optional[SLOMonitor]:
    return _monitor


def clear_monitor(monitor: Optional[SLOMonitor] = None) -> None:
    """Remove the global monitor; with an argument, only when it is
    still the installed one (a stop() racing a newer start() must not
    clear the newer server's monitor)."""
    global _monitor
    with _monitor_lock:
        if monitor is None or _monitor is monitor:
            _monitor = None
