"""Micro-batching front end — request-shaped traffic onto batch-shaped chips.

The serving tentpole (ISSUE 14). TPUs amortize dispatch over batches;
users send one query at a time. :class:`MicroBatchServer` closes that
gap with the standard production recipe, robustness first:

- **shape-bucketed coalescing** — single-query submits land in a
  bounded queue keyed by ``(tenant, k)``; the batcher drains up to
  ``max_batch`` of them within a ``linger_s`` window and pads the
  group to the next power-of-two **bucket** so the whole serving
  surface compiles to a small closed set of shapes.
- **AOT warmup, provably-zero steady-state recompiles** — ``start()``
  runs every (tenant × bucket × k) shape through the REAL dispatch
  path once, so the jit caches are warm before the first user request;
  with ``compile_cache_dir`` set the XLA compilation cache persists
  across process restarts (bounded cold-start). The PR-3
  ``recompile_budget(0)`` sanitizer wraps steady-state traffic in
  tests/CI — an unexpected retrace is a FAILURE, not a latency blip.
- **bounded queue + explicit shedding** — a full queue rejects with a
  typed :class:`~raft_tpu.serve.errors.ShedError` immediately (counted
  ``serve.shed{reason=queue_full}``); nothing ever blocks a client
  indefinitely and no future is left unresolved, under any fault the
  chaos lane injects.
- **deadline propagation** — every request carries one
  :class:`~raft_tpu.robust.retry.Deadline` from enqueue: queue wait,
  batching, dispatch, retries, and the degrade ladder all draw down
  the same budget (see :mod:`raft_tpu.serve.dispatch`). Requests whose
  budget died in the queue are shed without touching the chip.
- **overload = the degrade ladder** — a RESOURCE_EXHAUSTED under load
  walks PR-7's ``standard_search_ladder`` (halve batch → bf16/fp8 LUT
  → decline fused → host gather); only a fully-exhausted ladder sheds
  (``serve.shed{reason=overload}``).

Counters: ``serve.requests{tenant=}``, ``serve.shed{reason=}``,
``serve.deadline_missed``, ``serve.batch_fill`` (histogram, fill
fraction), ``serve.latency_s`` (histogram — the p50/p99 source),
``serve.queue_depth`` (gauge). Fault points: ``serve.enqueue``,
``serve.dispatch``, ``serve.registry.admit``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.core import logging as _log
from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _spans
from raft_tpu.obs import trace as _trace
from raft_tpu.robust import faults as _faults
from raft_tpu.robust.retry import Deadline, DeadlineExceeded
from raft_tpu.serve import dispatch as _dispatch
from raft_tpu.serve.errors import ServeError, ShedError, TenantUnknown
from raft_tpu.serve.registry import IndexRegistry

__all__ = ["ServerConfig", "MicroBatchServer", "bucket_sizes",
           "bucket_for"]

# serve.latency_s histogram edges: request latencies from 100 µs to
# seconds — same shape as the bench's search-latency buckets so
# quantile interpolation stays fine-grained where serving lives.
_LATENCY_BUCKETS = [1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                    2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0]
_FILL_BUCKETS = [0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0]


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """The bucket set: powers of two up to ``max_batch`` (rounded up) —
    every batch compiles to one of ``log2(max_batch)+1`` shapes."""
    if max_batch < 1:
        raise ValueError(f"max_batch {max_batch} < 1")
    out = [1]
    while out[-1] < max_batch:
        out.append(out[-1] * 2)
    return tuple(out)


def bucket_for(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket ≥ ``n`` (``n`` capped to the largest bucket by
    the batcher's take size, so this never falls off the end)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Serving knobs (defaults sized for the CPU smoke; production pods
    raise ``max_batch``/``queue_depth`` and tighten ``default_slo_s``).

    ``linger_s`` is the micro-batch window: the batcher waits at most
    this long past the oldest queued request for the bucket to fill —
    the latency the front end spends buying batch efficiency.
    ``default_slo_s`` seeds each request's :class:`Deadline`
    (``None`` → unbounded, the offline default). ``compile_cache_dir``
    points jax's persistent compilation cache somewhere durable so a
    restarted server's cold-start is bounded by cache loads, not
    recompiles."""

    max_batch: int = 32
    queue_depth: int = 256
    linger_s: float = 0.002
    default_slo_s: Optional[float] = 1.0
    compile_cache_dir: Optional[str] = None
    drain_s: float = 5.0
    # live telemetry exposition (ISSUE 15): a port arms an
    # obs.expo.ExpoServer for the server's lifetime (/metrics /healthz
    # /flightz). None = off (the offline default); 0 = ephemeral port
    # (tests/CI read it back from server.expo.port)
    expo_port: Optional[int] = None
    expo_host: str = "127.0.0.1"
    # the quality plane (ISSUE 16): ``verify_sample`` > 0 arms the
    # shadow recall verifier — that fraction of completed requests is
    # replayed exactly (host-side, off the hot path, rate-limited to
    # ``verify_rate_per_s`` per tenant) against each tenant's admitted
    # dataset, feeding quality.recall{tenant=,k=} gauges with Wilson
    # CIs and the SLO monitor's recall floors. 0.0 = off (the default:
    # verification-less serving pays nothing new).
    verify_sample: float = 0.0
    verify_rate_per_s: float = 50.0
    verify_seed: int = 0
    #: an :class:`raft_tpu.serve.slo.SLOPolicy` (None → defaults) —
    #: burn-rate windows/targets and the recall-floor evidence bar
    slo: Optional[Any] = None


class _Request:
    __slots__ = ("tenant", "query", "k", "deadline", "future", "enqueued",
                 "ctx")

    def __init__(self, tenant: str, query: np.ndarray, k: int,
                 deadline: Optional[Deadline]):
        self.tenant = tenant
        self.query = query
        self.k = k
        self.deadline = deadline
        self.future: Future = Future()
        self.enqueued = time.monotonic()
        # request-scoped trace identity (ISSUE 15): minted at submit,
        # carried through queue → batcher → dispatch → retry/degrade →
        # search_resilient; stamped on every span event those stages
        # emit and retained as the latency histogram's exemplar
        self.ctx = _trace.RequestContext(tenant=tenant, deadline=deadline)


def _count(name: str, **labels: str) -> None:
    if _spans.enabled():
        _spans.registry().inc(name, labels=labels or None)


def _observe(name: str, value: float, buckets,
             exemplar: Optional[str] = None) -> None:
    if _spans.enabled():
        _spans.registry().histogram(name, buckets=buckets).observe(
            value, exemplar=exemplar)


class MicroBatchServer:
    """The async front end: ``submit()`` returns a
    :class:`concurrent.futures.Future` immediately; a background
    batcher coalesces, buckets, and dispatches. ``search()`` is the
    blocking convenience wrapper. Use as a context manager or call
    :meth:`start`/:meth:`stop`."""

    def __init__(self, registry: IndexRegistry,
                 config: Optional[ServerConfig] = None):
        self.registry = registry
        self.config = config or ServerConfig()
        self.buckets = bucket_sizes(self.config.max_batch)
        self._queues: Dict[Tuple[str, int], Deque[_Request]] = {}
        self._total = 0
        self._cond = _sanitize.monitored_condition("serve.server")
        self._running = False
        self._thread: Optional[threading.Thread] = None
        #: the live exposition endpoint (obs.expo.ExpoServer) while
        #: running with ``config.expo_port`` set, else None
        self.expo = None
        #: the shadow recall verifier (obs.quality.RecallVerifier)
        #: while running with ``config.verify_sample`` > 0, else None
        self.verifier = None
        #: the SLO monitor (serve.slo.SLOMonitor) while running
        self.slo = None
        #: the cost ledger (obs.cost.CostLedger) while running
        self.ledger = None
        #: the capacity model (obs.capacity.CapacityModel) while running
        self.capacity = None

    # -- lifecycle ----------------------------------------------------------
    def start(self, warmup: bool = True) -> "MicroBatchServer":
        """Arm the server: point jax at the persistent compilation
        cache (when configured), AOT-warm every resident tenant's
        bucket set through the real dispatch path, then start the
        batcher. After ``start(warmup=True)`` returns, steady-state
        serving holds ``recompile_budget(0)``."""
        with self._cond:
            if self._running:
                return self
        if self.config.compile_cache_dir:
            self._persist_compile_cache(self.config.compile_cache_dir)
        if warmup:
            for tenant in self.registry.resident():
                try:
                    self.warm_tenant(tenant.name)
                except Exception as e:
                    # one tenant that cannot even warm must not keep
                    # the whole server (and every healthy tenant) down:
                    # mark it failed — its residency is released, its
                    # submits become typed TenantUnknown — and serve on
                    _log.warn("serve: warmup failed for %r: %r — "
                              "marking failed", tenant.name, e)
                    self.registry.mark(tenant.name, "failed")
        with self._cond:
            self._running = True
        self._thread = threading.Thread(target=self._batch_loop,
                                        name="raft-tpu-serve-batcher",
                                        daemon=True)
        self._thread.start()
        # live exposition (ISSUE 15): scrapable /metrics + /healthz +
        # /flightz for the server's lifetime; the registry's tenant
        # health also rides every flight dump as "serve_registry"
        from raft_tpu.obs import flight as _flight

        _flight.set_section("serve_registry", self.registry.describe)
        # the quality plane (ISSUE 16): shadow verifier (when sampling
        # is on) + SLO monitor (always — burn rates need no verifier).
        # The monitor registers process-globally so dispatch can fetch
        # the quality gate; verdicts drive its floor evaluation.
        from raft_tpu.serve import slo as _slo

        if self.config.verify_sample > 0.0:
            from raft_tpu.obs import quality as _quality

            self.verifier = _quality.RecallVerifier(
                self.registry,
                _quality.VerifierConfig(
                    sample_fraction=self.config.verify_sample,
                    rate_limit_per_s=self.config.verify_rate_per_s,
                    seed=self.config.verify_seed)).start()
            _flight.set_section("quality", self.verifier.state)
        self.slo = _slo.SLOMonitor(self.registry, verifier=self.verifier,
                                   policy=self.config.slo)
        if self.verifier is not None:
            self.verifier.on_verdict = self.slo.evaluate
        _slo.set_monitor(self.slo)
        # the cost & capacity plane (ISSUE 20): the ledger attributes
        # per-tenant resources (dispatch reaches it through the
        # process-global install, same pattern as the SLO monitor); the
        # capacity model forecasts saturation for admission/placement.
        # Both live regardless of the obs flag — attribution itself is
        # gated at the dispatch tap, so obs-off serving stays at one
        # flag check per batch.
        from raft_tpu.obs import capacity as _capacity
        from raft_tpu.obs import cost as _cost

        self.ledger = _cost.CostLedger()
        _cost.set_ledger(self.ledger)
        self.capacity = _capacity.CapacityModel(
            resident_bytes=self.registry.resident_bytes,
            usable_bytes=lambda: self.registry.usable_bytes,
            ledger=self.ledger)
        _capacity.set_model(self.capacity)
        _flight.set_section("cost", self._costz_payload)
        if _spans.enabled():
            # re-mirror the admission budget into hbm.bytes_limit at
            # START (the registry's __init__ mirror only fires when obs
            # was already enabled at construction — callers that enable
            # obs or swap registries afterwards would otherwise serve
            # an hbm-less /metrics on allocator-less backends)
            from raft_tpu.obs import hbm as _hbm

            _hbm.note_budget(self.registry.budget_bytes,
                             _spans.registry())
        if self.config.expo_port is not None:
            from raft_tpu.obs import expo as _expo

            try:
                self.expo = _expo.ExpoServer(
                    port=self.config.expo_port,
                    host=self.config.expo_host,
                    health=self._health_payload,
                    indexz=self._indexz_payload,
                    costz=self._costz_payload).start()
            except Exception:
                # a failed bind (port taken, privileged port) must not
                # leave a half-started server: the batcher thread is
                # already live and a second start() would early-return
                # on _running forever — tear back down to "stopped" so
                # the caller can fix the port and start() again
                self.stop(drain=False)
                raise
            _log.info("serve: exposition endpoint at %s", self.expo.url)
        return self

    @staticmethod
    def _persist_compile_cache(cache_dir: str) -> None:
        """Best-effort persistent XLA compilation cache: a cold-started
        server reloads compiled buckets from disk instead of
        recompiling them (bounded cold-start). Failure degrades to
        in-memory caching — never blocks serving."""
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # compile times on serving buckets are small; cache every
            # program rather than only the slow ones
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception as e:  # unknown jax: in-memory cache only
            _log.warn("serve: persistent compile cache unavailable (%s)", e)

    def warm_tenant(self, name: str) -> int:
        """AOT-precompile tenant ``name``'s bucket set: run every
        (bucket × served-k) shape — the tenant's ``serve_ks``, its
        whole admissible surface — through the REAL dispatch path
        (same entry, same params — the same jit caches steady state
        hits), then mark the tenant ``serving``. Returns the number of
        shapes warmed; counted ``serve.warmup{tenant=}``."""
        import jax.numpy as jnp

        # peek: warmup must not heat the tenant's LRU eviction clock
        tenant = self.registry.peek(name)
        dim = tenant.index.dim
        ks = tenant.serve_ks or (tenant.default_k,)
        for b in self.buckets:
            zeros = jnp.zeros((b, dim), jnp.float32)
            for k in ks:
                _dispatch.dispatch_batch(tenant, zeros, k,
                                         deadline=None)
                _count("serve.warmup", tenant=name)
        self.registry.mark(name, "serving")
        _log.info("serve: warmed %r over buckets %s x ks %s", name,
                  list(self.buckets), list(ks))
        return len(self.buckets) * len(ks)

    def stop(self, drain: bool = True) -> None:
        """Stop the batcher. ``drain=True`` gives queued work up to
        ``config.drain_s`` to complete; whatever remains (and anything
        submitted after stop) is shed as ``draining`` — a shutdown
        leaves zero unresolved futures."""
        if drain:
            end = time.monotonic() + self.config.drain_s
            with self._cond:
                while self._total and time.monotonic() < end:
                    self._cond.wait(0.01)
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            with _sanitize.blocking_region("join"):
                self._thread.join(timeout=self.config.drain_s + 5)
            self._thread = None
        shed: List[_Request] = []
        with self._cond:
            for q in self._queues.values():
                shed.extend(q)
                q.clear()
            self._total = 0
        for r in shed:
            _count("serve.shed", reason="draining")
            self._request_event(r, outcome="shed_draining")
            r.future.set_exception(ShedError("draining"))
        if self.expo is not None:
            self.expo.stop()
            self.expo = None
        from raft_tpu.obs import flight as _flight

        _flight.clear_section("serve_registry")
        if self.verifier is not None:
            self.verifier.stop()
            self.verifier = None
            _flight.clear_section("quality")
        if self.slo is not None:
            from raft_tpu.serve import slo as _slo

            # clear only OUR monitor: a stop() racing a newer server's
            # start() must not strip that server's gate
            _slo.clear_monitor(self.slo)
            self.slo = None
        if self.ledger is not None:
            from raft_tpu.obs import capacity as _capacity
            from raft_tpu.obs import cost as _cost

            _flight.clear_section("cost")
            _cost.clear_ledger(self.ledger)  # ours only, same as slo
            _capacity.clear_model(self.capacity)
            self.ledger = None
            self.capacity = None

    # -- exposition payloads (ISSUE 16) -------------------------------------
    def _health_payload(self) -> Dict[str, Any]:
        """/healthz body: the registry describe + the SLO section
        (burn rates, floor-breached tenants). The scrape itself drives
        an SLO evaluation, so health is current even on an idle
        verifier."""
        desc = self.registry.describe()
        if self.slo is not None:
            try:
                desc["slo"] = self.slo.healthz()
            except Exception:  # noqa: BLE001 — health must render
                pass
        return desc

    def _costz_payload(self) -> Dict[str, Any]:
        """/costz body (and the ``"cost"`` flight-dump section): the
        per-tenant attribution ledger plus the capacity forecast. The
        scrape itself advances the HBM byte-second integrals and the
        capacity rate windows (the healthz-drives-evaluation
        convention), so an idle scrape still moves the clock."""
        out: Dict[str, Any] = {}
        if self.ledger is not None:
            out["ledger"] = self.ledger.describe()
        if self.capacity is not None:
            try:
                self.capacity.tick()
                out["capacity"] = self.capacity.forecast()
            except Exception as e:  # noqa: BLE001 — scrape must render
                out["capacity"] = {"error": repr(e)}
        return out

    def _indexz_payload(self) -> Dict[str, Any]:
        """/indexz body: per-tenant index-health introspection
        (admission-time stats, computed on first demand for tenants
        admitted before the quality plane or without a dataset)."""
        from raft_tpu.obs import index_stats as _istats

        from raft_tpu.serve.registry import index_bytes_by_tier

        out: Dict[str, Any] = {}
        for t in self.registry.tenants():
            entry: Dict[str, Any] = {"state": t.state,
                                     "requests": t.requests}
            if t.recall_floor is not None:
                entry["recall_floor"] = t.recall_floor
            # the memory tier (ISSUE 17): where this tenant's pieces
            # live and what each tier costs — a demoted tenant shows
            # raw=host (plus demoted=true) at a glance
            if t.placement is not None:
                entry["placement"] = t.placement.describe()
                if t.demoted:
                    entry["demoted"] = True
            if t.index is not None:
                entry["bytes"] = index_bytes_by_tier(t.index, t.dataset)
            stats = t.index_stats
            if stats is None and t.index is not None:
                stats = _istats.describe_index(t.index, t.dataset)
                t.index_stats = stats
            if stats:
                entry["stats"] = stats
            out[t.name] = entry
        return {"tenants": out}

    def __enter__(self) -> "MicroBatchServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the client surface -------------------------------------------------
    def submit(self, tenant: str, query, k: Optional[int] = None,
               slo_s: Optional[float] = -1.0) -> Future:
        """Enqueue one single-query request; returns a Future resolving
        to ``(distances, ids)`` numpy vectors of length ``k``.

        The request's :class:`Deadline` starts NOW — queue wait counts
        against the SLO. ``slo_s`` overrides the config default
        (``None`` = unbounded; the ``-1.0`` sentinel means "use
        ``config.default_slo_s``"). Refusals are immediate and typed:
        :class:`ShedError` (queue full / not running),
        :class:`TenantUnknown`."""
        _faults.faultpoint("serve.enqueue")
        # peek, not get: submit-time validation must not heat the LRU
        # clock — shed/invalid floods would keep a tenant eviction-hot
        # while quieter tenants actually serving get evicted; recency
        # is touched at DISPATCH (the batcher's registry.get)
        tenant_rec = self.registry.peek(tenant)  # TenantUnknown raises
        # counted AFTER the tenant resolves: the label set must stay
        # the enumerable set of real tenants — client-supplied bogus
        # names minting unbounded labeled series would leak registry
        # memory and make every per-tenant dump table unreadable
        _count("serve.requests", tenant=tenant)
        q = np.asarray(query, dtype=np.float32)
        if q.ndim != 1:
            raise ValueError(
                f"submit() takes one query vector [dim], got {q.shape} — "
                "the front end owns batching")
        if q.shape[0] != tenant_rec.index.dim:
            raise ValueError(
                f"query dim {q.shape[0]} != tenant {tenant!r} index dim "
                f"{tenant_rec.index.dim}")
        kk = tenant_rec.default_k if k is None else int(k)
        allowed = tenant_rec.serve_ks or (tenant_rec.default_k,)
        if kk not in allowed:
            # an un-warmed k would COMPILE on the serving path — a
            # head-of-line latency spike for every queued request and a
            # recompile_budget(0) violation; the k surface is closed at
            # admission (registry.admit(ks=...))
            raise ValueError(
                f"k={kk} not in tenant {tenant!r}'s warmed surface "
                f"{list(allowed)} — declare it at admit(ks=...)")
        budget = self.config.default_slo_s if slo_s == -1.0 else slo_s
        req = _Request(tenant, q, kk,
                       None if budget is None else Deadline(budget))
        # the client's handle to the trace: a returned future knows its
        # request's trace id, so load generators / clients can join a
        # slow result back to its timeline (loadgen stamps these into
        # its benchdiff rows)
        req.future.trace_id = req.ctx.trace_id
        with self._cond:
            if not self._running:
                _count("serve.shed", reason="not_running")
                # same anchor-event contract as every other shed path:
                # a drill-down for this trace id must find the request
                # marked shed, not simply missing
                self._request_event(req, outcome="shed_not_running")
                raise ShedError("not_running", "server not started")
            if self._total >= self.config.queue_depth:
                # the explicit load-shed: a bounded queue full of work
                # the chip hasn't absorbed means more arrivals than
                # capacity — reject NOW so the client can back off,
                # instead of queueing into certain deadline misses
                _count("serve.shed", reason="queue_full")
                self._request_event(req, outcome="shed_queue_full",
                                    depth=self._total)
                raise ShedError(
                    "queue_full",
                    f"{self._total} queued >= depth "
                    f"{self.config.queue_depth}")
            self._queues.setdefault((tenant, kk), deque()).append(req)
            self._total += 1
            if _spans.enabled():
                _spans.registry().gauge("serve.queue_depth").set(
                    self._total)
            self._cond.notify_all()
        return req.future

    def search(self, tenant: str, query, k: Optional[int] = None,
               slo_s: Optional[float] = -1.0,
               timeout_s: float = 30.0):
        """Blocking convenience wrapper: ``submit().result()``."""
        fut = self.submit(tenant, query, k, slo_s)
        with _sanitize.blocking_region("Future.result"):
            return fut.result(timeout=timeout_s)

    # -- the batcher --------------------------------------------------------
    def _batch_loop(self) -> None:
        cfg = self.config
        while True:
            with self._cond:
                while self._running and self._total == 0:
                    self._cond.wait(0.05)
                if not self._running:
                    return
                # serve the key whose HEAD request has waited longest
                key = min(
                    (k for k, q in self._queues.items() if q),
                    key=lambda k: self._queues[k][0].enqueued)
                q = self._queues[key]
                age = time.monotonic() - q[0].enqueued
                if len(q) < cfg.max_batch and age < cfg.linger_s:
                    # the micro-batch window: wait (briefly) for the
                    # bucket to fill — re-evaluate on every arrival
                    self._cond.wait(cfg.linger_s - age)
                    continue
                take = [q.popleft()
                        for _ in range(min(cfg.max_batch, len(q)))]
                self._total -= len(take)
                if _spans.enabled():
                    _spans.registry().gauge("serve.queue_depth").set(
                        self._total)
                self._cond.notify_all()
            try:
                self._run_batch(key, take)
            except BaseException as e:  # noqa: B036 — resolve futures first
                # belt-and-braces: _run_batch already routes failures to
                # futures; anything escaping (a bug, an injected
                # SIGTERM's re-raise path) must not strand the batch
                for r in take:
                    if not r.future.done():
                        r.future.set_exception(
                            e if isinstance(e, Exception)
                            else ServeError(f"batcher died: {e!r}"))
                if not isinstance(e, Exception):
                    raise

    def _request_event(self, r: _Request, outcome: str,
                       **extra: Any) -> None:
        """One ``serve.request`` timeline event spanning the request's
        whole life (enqueue → now), stamped with its trace id — the
        anchor row ``obsdump --slowest`` renders a drill-down around.
        Free when event recording is off."""
        if not _spans.events_enabled():
            return
        dur = time.monotonic() - r.enqueued
        args = {"trace_id": r.ctx.trace_id, "tenant": r.tenant,
                "k": r.k, "outcome": outcome, **extra}
        _trace.get_buffer().record_span("serve.request",
                                        time.time() - dur, dur,
                                        args=args)

    def _run_batch(self, key: Tuple[str, int], reqs: List[_Request]
                   ) -> None:
        tenant_name, k = key
        t_take = time.monotonic()  # queue wait ends here
        try:
            tenant = self.registry.get(tenant_name)  # touches LRU
            tenant.requests += len(reqs)  # accepted-request forensics
        except TenantUnknown as e:
            # evicted/failed between enqueue and dispatch: typed error,
            # never a crash into a dropped index reference
            for r in reqs:
                self._request_event(r, outcome="tenant_unknown")
                r.future.set_exception(e)
            return
        live: List[_Request] = []
        for r in reqs:
            if r.deadline is not None and r.deadline.expired:
                # budget burned in the queue — shed without chip work
                _count("serve.shed", reason="deadline")
                _count("serve.deadline_missed")
                self._request_event(r, outcome="shed_deadline",
                                    queue_s=round(t_take - r.enqueued, 6))
                r.future.set_exception(
                    DeadlineExceeded("serve.queue", r.deadline))
            else:
                live.append(r)
        if not live:
            return
        bucket = bucket_for(len(live), self.buckets)
        _observe("serve.batch_fill", len(live) / bucket, _FILL_BUCKETS)
        batch = np.zeros((bucket, live[0].query.shape[0]), np.float32)
        for j, r in enumerate(live):
            batch[j] = r.query
        # the group deadline is the most patient member's: one member's
        # nearly-dead budget must not abort a batch others can still
        # use; individual misses are counted per request at completion
        deadlines = [r.deadline for r in live if r.deadline is not None]
        group = None
        if deadlines and len(deadlines) == len(live):
            group = max(deadlines, key=lambda d: d.remaining())
        # the batch's RequestContext carries EVERY member's trace id:
        # the dispatch/search/retry spans (and any ladder move) below
        # are work done for all of them at once, and a drill-down for
        # any one member must find those shared stages
        batch_ctx = _trace.RequestContext(
            tenant=tenant_name, deadline=group,
            trace_ids=[r.ctx.trace_id for r in live])
        fill = len(live) / bucket
        import jax.numpy as jnp

        try:
            with _trace.use_request(batch_ctx):
                dist, ids = _dispatch.dispatch_batch(
                    tenant, jnp.asarray(batch), k, deadline=group,
                    registry=self.registry)
        except TenantUnknown as e:
            # evicted between our registry.get and the dispatch's index
            # snapshot: the same typed refusal as the lookup path —
            # routine evictions must not read as tenant errors
            for r in live:
                self._request_event(r, outcome="tenant_unknown")
                r.future.set_exception(e)
            return
        except DeadlineExceeded as e:
            for r in live:
                _count("serve.shed", reason="deadline")
                _count("serve.deadline_missed")
                self._request_event(r, outcome="shed_deadline",
                                    bucket=bucket)
                r.future.set_exception(e)
            return
        except ShedError as e:
            for r in live:
                _count("serve.shed", reason=e.reason)
                self._request_event(r, outcome=f"shed_{e.reason}",
                                    bucket=bucket)
                r.future.set_exception(e)
            return
        except Exception as e:
            # a non-shed failure is the tenant's problem, not the
            # queue's: resolve the batch with the error and keep serving
            # other tenants
            _log.warn("serve: batch failed for %r: %r", tenant_name, e)
            for r in live:
                _count("serve.errors", tenant=tenant_name)
                self._request_event(r, outcome="error", bucket=bucket)
                r.future.set_exception(e)
            return
        d_np = np.asarray(dist)[:len(live)]
        i_np = np.asarray(ids)[:len(live)]
        now = time.monotonic()
        for j, r in enumerate(live):
            latency = now - r.enqueued
            # the exemplar (ISSUE 15): the latency histogram's buckets
            # retain concrete (value, trace_id) pairs, so a reported
            # p99 resolves to real requests whose timelines render in
            # obsdump --slowest
            _observe("serve.latency_s", latency, _LATENCY_BUCKETS,
                     exemplar=r.ctx.trace_id)
            missed = r.deadline is not None and r.deadline.expired
            if missed:
                # completed, but late: deliver the (correct) result and
                # count the SLO miss — the curve's p99 tells the story
                _count("serve.deadline_missed")
            self._request_event(
                r, outcome="late" if missed else "ok",
                queue_s=round(t_take - r.enqueued, 6),
                bucket=bucket, fill=round(fill, 4))
            r.future.set_result((d_np[j], i_np[j]))
            if self.verifier is not None:
                # the shadow-verifier tap (ISSUE 16): AFTER the future
                # resolves, so the client's latency never includes the
                # sample offer (an RNG draw + bounded copy when taken)
                self.verifier.maybe_sample(tenant_name, r.query, k,
                                           i_np[j], r.ctx.trace_id)
