"""Fleet router — tenant placement and straggler-steered dispatch
across pods (ISSUE 19).

One pod is one serving plane (registry + dispatch over one mesh); the
router is the layer above: it spreads tenants across pods (replicate
hot tenants, keep sharded ones on the pod whose mesh their Sharded*
build spans), carries the ONE request :class:`Deadline` across the pod
hop, and turns the PR-15 straggler table (``obs.fleet.straggler_table``)
from a diagnostic into a control loop — dispatch steers load away from
pods whose hosts recently straggled, and a pod that dies mid-request is
failed over with typed accounting instead of a hang.

Counters (all under ``serve.router.*``):

- ``serve.router.requests{tenant=}`` — one per routed dispatch
- ``serve.router.place{tenant=,mode=}`` — placement decisions
  (``replicate`` | ``shard`` | ``single``)
- ``serve.router.straggler{host=}`` — straggler-table rows above the
  skew threshold, as consumed by :meth:`FleetRouter.note_stragglers`
- ``serve.router.steer{away_from=,reason=straggler}`` — a dispatch
  that avoided its preferred pod because of a recent straggler
- ``serve.router.steer{away_from=,reason=capacity}`` — a placement
  that overrode the fewest-tenants heuristic because the cost
  ledger's share-weighted headroom ranked another pod better
  (ISSUE 20)
- ``serve.router.pod_down{pod=}`` — a pod marked unhealthy after a
  failed hop
- ``serve.router.degraded{reason=pod_lost}`` — a request answered by
  surviving pods after losing one (degraded-but-correct for
  replicated tenants)
- ``serve.router.shed{reason=pod_unhealthy}`` — no healthy pod left
  (the typed refusal; reason registered in
  :data:`raft_tpu.serve.errors.SHED_REASONS`)

The fault point ``serve.router.hop.<pod>`` brackets the cross-pod hop,
so the chaos lane can kill one simulated pod mid-query-storm and
assert the failover accounting exactly.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from raft_tpu.obs import cost as _cost
from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _spans
from raft_tpu.robust import faults as _faults
from raft_tpu.robust.retry import Deadline, DeadlineExceeded
from raft_tpu.serve.errors import ShedError, TenantUnknown

__all__ = ["RouterPolicy", "Pod", "FleetRouter",
           "set_router", "get_router", "clear_router"]


def _count(name: str, **labels: str) -> None:
    if _spans.enabled():
        _spans.registry().inc(name, labels=labels or None)


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """Steering knobs.

    ``skew_threshold``: a straggler-table row's ``skew_frac`` (slowest
    host's mean collective lag over the fleet mean) above which the
    slowest host counts as straggling — 0.25 = 25% above fleet mean,
    well past the jitter the PR-15 table shows on healthy fleets.
    ``lag_window_s``: how long one sighting keeps steering traffic away
    — stale sightings expire so a recovered host wins its load back
    without an operator touch."""

    skew_threshold: float = 0.25
    lag_window_s: float = 60.0


class Pod:
    """One serving pod: a registry (its resident tenants) plus the
    callable that runs a batch on the pod's own mesh.

    ``dispatch_fn(tenant_name, queries, k, deadline)`` defaults to the
    in-process serving plane — registry lookup +
    :func:`raft_tpu.serve.dispatch.dispatch_batch` — and is injectable
    so tests (and the chaos leg) can pin a pod to a CPU submesh.
    ``hosts`` are the host tags this pod's devices live on, the join
    key against the straggler table's ``slowest`` column."""

    def __init__(self, name: str, registry: Any = None,
                 hosts: Sequence[str] = (),
                 dispatch_fn: Optional[Callable[..., Tuple[Any, Any]]]
                 = None):
        self.name = name
        self.registry = registry
        self.hosts = tuple(hosts)
        self.healthy = True
        self._dispatch_fn = dispatch_fn

    def dispatch(self, tenant: str, queries, k: int,
                 deadline: Optional[Deadline] = None) -> Tuple[Any, Any]:
        if self._dispatch_fn is not None:
            return self._dispatch_fn(tenant, queries, k, deadline)
        from raft_tpu.serve.dispatch import dispatch_batch

        t = self.registry.get(tenant)
        return dispatch_batch(t, queries, k, deadline=deadline,
                              registry=self.registry)

    def has_tenant(self, tenant: str) -> bool:
        if self.registry is None:
            return True  # dispatch_fn-only pods serve everything
        try:
            self.registry.peek(tenant)
            return True
        except Exception:
            return False


class FleetRouter:
    """Routes requests to pods; consumes the straggler feed; fails
    over with typed accounting. Thread-safe (dispatch runs on serving
    threads, ``note_stragglers`` on the observability poller)."""

    def __init__(self, pods: Sequence[Pod],
                 policy: Optional[RouterPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        if not pods:
            raise ValueError("FleetRouter needs at least one pod")
        names = [p.name for p in pods]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate pod names: {names}")
        self.pods = list(pods)
        self.policy = policy or RouterPolicy()
        self._clock = clock
        self._lock = _sanitize.monitored_lock("serve.router")
        # host tag -> monotonic time of last above-threshold sighting
        self._lag_seen: Dict[str, float] = {}
        # round-robin cursor per tenant (fair spread over replicas)
        self._rr: Dict[str, int] = {}

    # -- straggler control loop -------------------------------------------
    def note_stragglers(self, rows: List[Dict[str, Any]]) -> int:
        """Feed straggler-table rows (``obs.fleet.straggler_table``
        shape) into the steering state. Rows whose ``skew_frac``
        exceeds the policy threshold record a sighting against the
        ``slowest`` host. Returns how many sightings were recorded."""
        now = self._clock()
        hit = 0
        with self._lock:
            for row in rows:
                if float(row.get("skew_frac", 0.0)) \
                        <= self.policy.skew_threshold:
                    continue
                host = str(row.get("slowest", ""))
                if not host:
                    continue
                self._lag_seen[host] = now
                hit += 1
                _count("serve.router.straggler", host=host)
        return hit

    def straggling_hosts(self) -> List[str]:
        """Hosts with a live (unexpired) straggler sighting."""
        now = self._clock()
        with self._lock:
            return [h for h, t in self._lag_seen.items()
                    if now - t <= self.policy.lag_window_s]

    def _pod_straggler(self, pod: Pod) -> Optional[str]:
        lagging = set(self.straggling_hosts())
        for h in pod.hosts:
            if h in lagging:
                return h
        return None

    # -- placement ---------------------------------------------------------
    def _place_single(self, healthy: List[Pod]) -> Pod:
        """Single-pod placement scoring (ISSUE 20): prefer the pod
        with the best **cost-share-weighted headroom** — HBM headroom
        fraction minus the fleet-normalized ``cost.share`` of the
        tenants the pod already holds — so a pod whose few tenants
        burn most of the fleet's device time stops looking "empty" to
        the old fewest-tenants heuristic. Falls back to fewest-tenants
        while no ledger is installed (or nothing has been attributed
        yet). A capacity-steered choice that overrides the tenant-count
        heuristic counts ``serve.router.steer{reason=capacity}``."""
        by_count = min(healthy,
                       key=lambda p: len(p.registry.resident()))
        ledger = _cost.get_ledger()
        shares = ledger.shares() if ledger is not None else {}
        if not shares:
            return by_count

        def weighted_headroom(pod: Pod) -> float:
            usable = float(getattr(pod.registry, "usable_bytes", 0) or 0)
            resident = float(pod.registry.resident_bytes())
            headroom = (1.0 - resident / usable) if usable > 0 else 0.0
            load = sum(shares.get(t.name, 0.0)
                       for t in pod.registry.resident())
            return headroom - load

        best = max(healthy, key=weighted_headroom)
        if best is not by_count:
            _count("serve.router.steer", away_from=by_count.name,
                   reason="capacity")
        return best

    def place(self, name: str, index: Any, *, hot: bool = False,
              sharded: bool = False, params: Any = None,
              **admit_kw: Any) -> List[str]:
        """Admit a tenant to the fleet. ``hot`` replicates it to every
        healthy pod (query fan-out beats one saturated pod);
        ``sharded`` marks an index whose Sharded* build already spans
        its pod's mesh (stays on one pod — the sharding IS the spread);
        default is single-pod placement by cost-share-weighted
        headroom (:meth:`_place_single`). Returns the pod names that
        admitted it."""
        healthy = [p for p in self.pods if p.healthy
                   and p.registry is not None]
        if not healthy:
            raise ShedError("pod_unhealthy", "no healthy pod to place on")
        if hot:
            mode, targets = "replicate", healthy
        elif sharded:
            mode, targets = "shard", [healthy[0]]
        else:
            mode = "single"
            targets = [self._place_single(healthy)]
        for pod in targets:
            pod.registry.admit(name, index, params=params, **admit_kw)
        _count("serve.router.place", tenant=name, mode=mode)
        return [p.name for p in targets]

    # -- dispatch ----------------------------------------------------------
    def candidates(self, tenant: str) -> List[Pod]:
        """Healthy pods holding ``tenant``, steering-ordered: pods with
        no straggling host first (round-robin among them), straggling
        pods kept as last-resort fallbacks. Counts one
        ``serve.router.steer`` per demoted pod when a clean alternative
        exists."""
        holding = [p for p in self.pods if p.healthy
                   and p.has_tenant(tenant)]
        clean = [p for p in holding if self._pod_straggler(p) is None]
        lagging = [p for p in holding if p not in clean]
        if clean and lagging:
            for pod in lagging:
                _count("serve.router.steer",
                       away_from=str(self._pod_straggler(pod)),
                       reason="straggler")
        with self._lock:
            start = self._rr.get(tenant, 0)
            self._rr[tenant] = start + 1
        if clean:
            clean = clean[start % len(clean):] + clean[:start % len(clean)]
        return clean + lagging

    def dispatch(self, tenant: str, queries, k: int,
                 deadline: Optional[Deadline] = None) -> Tuple[Any, Any]:
        """Route one batch. The ONE ``deadline`` object crosses the pod
        hop untouched — queue wait, the hop, and the pod's own ladder
        all draw down the same budget. A pod that fails the hop (or
        dies under it) is marked unhealthy and the request fails over
        to the next candidate; typed request-scoped refusals
        (:class:`DeadlineExceeded`, :class:`TenantUnknown`,
        :class:`ShedError`) propagate — they are the REQUEST's problem,
        not the pod's."""
        _count("serve.router.requests", tenant=tenant)
        cands = self.candidates(tenant)
        if not cands:
            _count("serve.router.shed", reason="pod_unhealthy")
            raise ShedError("pod_unhealthy",
                            f"no healthy pod holds {tenant!r}")
        lost = False
        for pod in cands:
            try:
                _faults.faultpoint(f"serve.router.hop.{pod.name}")
                out = pod.dispatch(tenant, queries, k, deadline=deadline)
            except (DeadlineExceeded, TenantUnknown, ShedError):
                raise
            except Exception:
                # infrastructure failure: the pod is gone, not the
                # request — fail over to the survivors
                pod.healthy = False
                lost = True
                _count("serve.router.pod_down", pod=pod.name)
                continue
            if lost:
                _count("serve.router.degraded", reason="pod_lost")
            return out
        _count("serve.router.shed", reason="pod_unhealthy")
        raise ShedError("pod_unhealthy",
                        f"all pods holding {tenant!r} failed the hop")

    # -- introspection -----------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        lagging = set(self.straggling_hosts())
        return {
            "policy": dataclasses.asdict(self.policy),
            "straggling_hosts": sorted(lagging),
            "pods": [{
                "name": p.name,
                "healthy": p.healthy,
                "hosts": list(p.hosts),
                "straggling": any(h in lagging for h in p.hosts),
                "tenants": ([t.name for t in p.registry.resident()]
                            if p.registry is not None else None),
            } for p in self.pods],
        }


# -- process-global router (the slo-monitor install pattern) ---------------

_router: Optional[FleetRouter] = None
_router_lock = _sanitize.monitored_lock("serve.router.global")


def set_router(router: Optional[FleetRouter]) -> Optional[FleetRouter]:
    """Install the process-global router (returns the previous one)."""
    global _router
    with _router_lock:
        prev = _router
        _router = router
        return prev


def get_router() -> Optional[FleetRouter]:
    return _router


def clear_router(router: Optional[FleetRouter] = None) -> None:
    """Remove the global router; with an argument, only when it is
    still the installed one (a teardown racing a newer install must
    not clear the newer router)."""
    global _router
    with _router_lock:
        if router is None or _router is router:
            _router = None
