"""Typed serving errors — every refusal is a type, never a hang.

The serving contract (ISSUE 14): a request that cannot be served is
REJECTED with a typed, reasoned error the client can act on — back off
(:class:`ShedError`), fix the request (:class:`TenantUnknown`), or give
up (:class:`~raft_tpu.robust.retry.DeadlineExceeded`). No code path may
leave a future unresolved: the chaos lane kills, OOMs, and stalls the
server and asserts every submitted request terminates in a result or
one of these types.

All serve errors carry ``transient = False`` so the in-process retry
policy (:mod:`raft_tpu.robust.retry`) never blind-retries them — a shed
under overload retried in-process IS the overload; backoff belongs to
the *client* side of the queue.
"""

from __future__ import annotations

from typing import Optional

# the deadline type is defined with the retry policy (stdlib-only) so
# nested retry sites and the serving layer share one budget object
from raft_tpu.robust.retry import Deadline, DeadlineExceeded  # noqa: F401

__all__ = ["ServeError", "ShedError", "TenantUnknown", "AdmissionError",
           "Deadline", "DeadlineExceeded", "SHED_REASONS"]

# The closed set of shed reasons — ``serve.shed{reason=}`` label values
# (docs/observability.md). A new shed path must add its reason here so
# the counter family stays enumerable for dashboards and the chaos lane.
SHED_REASONS = ("queue_full", "deadline", "overload", "draining",
                "not_running", "pod_unhealthy")


class ServeError(RuntimeError):
    """Base of all typed serving refusals (never retried in-process)."""

    transient = False


class ShedError(ServeError):
    """The server declined the request to protect the ones it already
    holds — the explicit load-shedding rejection. ``reason`` is one of
    :data:`SHED_REASONS`; clients treat it as a backpressure signal
    (back off + retry elsewhere/later), never as a server bug."""

    def __init__(self, reason: str, detail: str = ""):
        assert reason in SHED_REASONS, reason
        msg = f"request shed ({reason})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.reason = reason


class TenantUnknown(ServeError):
    """No resident index under that tenant name (never admitted,
    evicted, or failed) — the client addressed the wrong registry or
    the tenant lost its residency; ``state`` says which."""

    def __init__(self, name: str, state: Optional[str] = None):
        extra = f" (state={state})" if state else ""
        super().__init__(f"unknown tenant {name!r}{extra}")
        self.name = name
        self.state = state


class AdmissionError(ServeError):
    """The registry could not make room for a new index: the HBM budget
    is exhausted and every resident tenant is pinned or hotter than the
    candidate. The caller retries after evicting explicitly or admits
    to a different chip."""
