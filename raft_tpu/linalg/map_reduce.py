"""Elementwise map + reduction surfaces (reference: linalg/map.cuh,
unary_op.cuh, binary_op.cuh, ternary_op.cuh, matrix_vector_op.cuh,
normalize.cuh, reduce.cuh, coalesced_reduction.cuh, strided_reduction.cuh,
map_reduce.cuh, reduce_rows_by_key.cuh, reduce_cols_by_key.cuh,
mean_squared_error.cuh). All are thin named XLA surfaces — XLA fuses them;
the names keep ported algorithm code readable against the reference."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def map_op(fn: Callable, *arrays) -> jax.Array:
    """Elementwise map over arrays (reference: linalg/map.cuh ``map``)."""
    return fn(*arrays)


def map_offset(fn: Callable[[jax.Array], jax.Array], shape) -> jax.Array:
    """Map over flat element offsets (reference: map.cuh ``map_offset``)."""
    n = 1
    for s in shape:
        n *= s
    return fn(jnp.arange(n)).reshape(shape)


def unary_op(fn: Callable, x: jax.Array) -> jax.Array:
    """reference: linalg/unary_op.cuh."""
    return fn(x)


def binary_op(fn: Callable, x: jax.Array, y: jax.Array) -> jax.Array:
    """reference: linalg/binary_op.cuh."""
    return fn(x, y)


def ternary_op(fn: Callable, x, y, z) -> jax.Array:
    """reference: linalg/ternary_op.cuh."""
    return fn(x, y, z)


def matrix_vector_op(m: jax.Array, v: jax.Array, op: Callable,
                     along_rows: bool = True) -> jax.Array:
    """Broadcast a vector op over matrix lines
    (reference: linalg/matrix_vector_op.cuh)."""
    return op(m, v[None, :] if along_rows else v[:, None])


def normalize_rows(m: jax.Array, norm: str = "l2", eps: float = 1e-12) -> jax.Array:
    """Row normalization (reference: linalg/normalize.cuh row_normalize)."""
    if norm == "l2":
        d = jnp.sqrt(jnp.maximum(jnp.sum(m * m, axis=1, keepdims=True), eps))
    elif norm == "l1":
        d = jnp.maximum(jnp.sum(jnp.abs(m), axis=1, keepdims=True), eps)
    elif norm == "linf":
        d = jnp.maximum(jnp.max(jnp.abs(m), axis=1, keepdims=True), eps)
    else:
        raise ValueError(f"unknown norm {norm!r}")
    return m / d


def reduce_op(m: jax.Array, axis: int = 1, op: str = "sum",
              main_op: Optional[Callable] = None) -> jax.Array:
    """Row/col reduce with optional pre-map (reference: linalg/reduce.cuh:
    ``reduce(..., main_op, reduce_op)``)."""
    x = main_op(m) if main_op is not None else m
    if op == "sum":
        return jnp.sum(x, axis=axis)
    if op == "max":
        return jnp.max(x, axis=axis)
    if op == "min":
        return jnp.min(x, axis=axis)
    raise ValueError(f"unknown reduce op {op!r}")


def coalesced_reduction(m: jax.Array, op: str = "sum",
                        main_op: Optional[Callable] = None) -> jax.Array:
    """Reduce along the contiguous (last) axis
    (reference: linalg/coalesced_reduction.cuh). Layout is an XLA concern;
    semantically a row reduce."""
    return reduce_op(m, axis=-1, op=op, main_op=main_op)


def strided_reduction(m: jax.Array, op: str = "sum",
                      main_op: Optional[Callable] = None) -> jax.Array:
    """Reduce along the strided (first) axis
    (reference: linalg/strided_reduction.cuh)."""
    return reduce_op(m, axis=0, op=op, main_op=main_op)


def map_then_reduce(fn: Callable, *arrays, axis=None) -> jax.Array:
    """reference: linalg/map_reduce.cuh ``map_reduce``."""
    return jnp.sum(fn(*arrays), axis=axis)


def reduce_rows_by_key(m: jax.Array, keys: jax.Array, n_keys: int,
                       weights: Optional[jax.Array] = None) -> jax.Array:
    """Sum rows grouped by key (reference: linalg/reduce_rows_by_key.cuh —
    kmeans' centroid accumulation)."""
    x = m if weights is None else m * weights[:, None]
    return jax.ops.segment_sum(x, keys, num_segments=n_keys)


def reduce_cols_by_key(m: jax.Array, keys: jax.Array, n_keys: int) -> jax.Array:
    """Sum columns grouped by key (reference: linalg/reduce_cols_by_key.cuh)."""
    return jax.ops.segment_sum(m.T, keys, num_segments=n_keys).T


def mean_squared_error(a: jax.Array, b: jax.Array) -> jax.Array:
    """reference: linalg/mean_squared_error.cuh."""
    d = a - b
    return jnp.mean(d * d)
