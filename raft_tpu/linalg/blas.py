"""BLAS-level ops (reference: linalg/gemm.cuh, gemv.cuh, axpy.cuh, dot.cuh —
cuBLAS wrappers, detail/cublas_wrappers.hpp). On TPU these lower straight
to MXU ``dot_general``; the named wrappers keep ported code source-
compatible and pin fp32 accumulation."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.utils.precision import get_precision


def gemm(a: jax.Array, b: jax.Array, alpha: float = 1.0, beta: float = 0.0,
         c: jax.Array | None = None, trans_a: bool = False,
         trans_b: bool = False) -> jax.Array:
    """C = α·op(A)·op(B) + β·C (reference: linalg/gemm.cuh)."""
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = alpha * jnp.matmul(a, b, precision=get_precision())
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out


def gemv(a: jax.Array, x: jax.Array, alpha: float = 1.0, beta: float = 0.0,
         y: jax.Array | None = None, trans: bool = False) -> jax.Array:
    """y = α·op(A)·x + β·y (reference: linalg/gemv.cuh)."""
    m = a.T if trans else a
    out = alpha * (m @ x)
    if y is not None and beta != 0.0:
        out = out + beta * y
    return out


def axpy(alpha: float, x: jax.Array, y: jax.Array) -> jax.Array:
    """y ← α·x + y (reference: linalg/axpy.cuh)."""
    return alpha * x + y


def dot(x: jax.Array, y: jax.Array) -> jax.Array:
    """⟨x, y⟩ (reference: linalg/dot.cuh)."""
    return jnp.dot(x, y, precision=get_precision())
