"""raft_tpu.linalg — dense linear algebra API surface.

Counterpart of the reference linalg layer (cpp/include/raft/linalg,
15.9k LoC). Per SURVEY.md §2.3, ~80% of that layer exists to re-implement
what XLA provides natively; here each reference API is a named, tested
surface over the XLA op so ported algorithm code reads the same — the MXU
tiling the reference hand-builds (contractions.cuh) is XLA ``dot_general``.
"""

from raft_tpu.linalg.blas import axpy, dot, gemm, gemv  # noqa: F401
from raft_tpu.linalg.solvers import (  # noqa: F401
    cholesky_r1_update,
    eig_dc,
    eig_jacobi,
    lstsq,
    qr,
    rsvd,
    svd,
)
from raft_tpu.linalg.map_reduce import (  # noqa: F401
    binary_op,
    coalesced_reduction,
    map_offset,
    map_op,
    map_then_reduce,
    matrix_vector_op,
    mean_squared_error,
    normalize_rows,
    reduce_cols_by_key,
    reduce_op,
    reduce_rows_by_key,
    strided_reduction,
    ternary_op,
    unary_op,
)
from raft_tpu.linalg.eltwise import (  # noqa: F401
    add,
    divide,
    eltwise_multiply,
    power,
    sqrt,
    subtract,
    transpose,
)
