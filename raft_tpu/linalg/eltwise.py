"""Elementwise arithmetic surfaces (reference: linalg/add.cuh,
subtract.cuh, multiply.cuh, divide.cuh, power.cuh, sqrt.cuh,
transpose.cuh)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def add(x, y):
    """reference: linalg/add.cuh."""
    return jnp.add(x, y)


def subtract(x, y):
    """reference: linalg/subtract.cuh."""
    return jnp.subtract(x, y)


def eltwise_multiply(x, y):
    """reference: linalg/multiply.cuh (eltwiseMultiply)."""
    return jnp.multiply(x, y)


def divide(x, y):
    """reference: linalg/divide.cuh."""
    return jnp.divide(x, y)


def power(x, y):
    """reference: linalg/power.cuh."""
    return jnp.power(x, y)


def sqrt(x):
    """reference: linalg/sqrt.cuh."""
    return jnp.sqrt(x)


def transpose(m: jax.Array) -> jax.Array:
    """reference: linalg/transpose.cuh."""
    return m.T
