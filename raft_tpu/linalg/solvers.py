"""Dense solvers (reference: linalg/eig.cuh, svd.cuh, rsvd.cuh, qr.cuh,
lstsq.cuh, cholesky_r1_update.cuh — cuSOLVER-backed). On TPU these lower
to XLA's LAPACK-equivalent decompositions; rsvd is implemented as the
standard randomized range-finder (Halko et al.), matching the reference's
randomized SVD semantics."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.utils.precision import get_precision


def eig_dc(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition, divide & conquer
    (reference: linalg/eig.cuh eig_dc). Returns (eigenvalues asc,
    eigenvectors as columns)."""
    w, v = jnp.linalg.eigh(a)
    return w, v


def eig_jacobi(a: jax.Array, tol: float = 1e-7) -> Tuple[jax.Array, jax.Array]:
    """Jacobi-method symmetric eig (reference: linalg/eig.cuh eig_jacobi).
    XLA's eigh is already iterative-stable; the tol parameter is accepted
    for API parity."""
    return eig_dc(a)


def svd(a: jax.Array, full_matrices: bool = False
        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """SVD → (U, S, Vᵀ) (reference: linalg/svd.cuh svd_qr)."""
    u, s, vt = jnp.linalg.svd(a, full_matrices=full_matrices)
    return u, s, vt


def rsvd(a: jax.Array, k: int, p: int = 10, n_iter: int = 2,
         key: Optional[jax.Array] = None
         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Randomized SVD (reference: linalg/rsvd.cuh): range-finder with
    ``p`` oversampling columns and ``n_iter`` power iterations."""
    if key is None:
        key = jax.random.PRNGKey(0)
    m, n = a.shape
    l = min(n, k + p)
    omega = jax.random.normal(key, (n, l), a.dtype)
    y = a @ omega
    # re-orthonormalize between power iterations: in fp32 the subspace
    # otherwise collapses onto the dominant direction
    for _ in range(n_iter):
        q, _ = jnp.linalg.qr(y)
        y = a @ (a.T @ q)
    q, _ = jnp.linalg.qr(y)
    b = q.T @ a
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return u[:, :k], s[:k], vt[:k]


def qr(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """QR decomposition (reference: linalg/qr.cuh)."""
    return jnp.linalg.qr(a)


def lstsq(a: jax.Array, b: jax.Array) -> jax.Array:
    """Least-squares solve min‖Ax − b‖ (reference: linalg/lstsq.cuh)."""
    sol, _, _, _ = jnp.linalg.lstsq(a, b)
    return sol


def cholesky_r1_update(l: jax.Array, v: jax.Array) -> jax.Array:
    """Rank-1 Cholesky update: chol(LLᵀ + vvᵀ)
    (reference: linalg/cholesky_r1_update.cuh). Classic hyperbolic-rotation
    update, expressed as a scan over columns."""
    n = l.shape[0]

    def body(carry, j):
        l, v = carry
        ljj = l[j, j]
        r = jnp.sqrt(ljj * ljj + v[j] * v[j])
        c, s = r / ljj, v[j] / ljj
        col = l[:, j]
        new_col = (col + s * v) / c
        new_v = c * v - s * new_col
        mask = jnp.arange(n) >= j
        l = l.at[:, j].set(jnp.where(mask, new_col, col))
        v = jnp.where(mask, new_v, v)
        return (l, v), None

    (l_out, _), _ = jax.lax.scan(body, (l, v), jnp.arange(n))
    return l_out
