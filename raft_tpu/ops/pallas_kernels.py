"""Pallas TPU kernels for the hot ANN primitives.

TPU-native re-implementation of the reference's load-bearing CUDA
kernels (SURVEY.md §7 "hard parts"):

- :func:`fused_l2_argmin` — fused distance + argmin over column tiles,
  the counterpart of ``fused_l2_nn`` (distance/detail/fused_l2_nn.cuh):
  one VMEM-resident pass per y-tile, MXU Gram + VPU epilogue + running
  (min, argmin) accumulated in the output block across the sequential
  grid axis — the [m, n] matrix never touches HBM.
- :func:`select_k_pallas` — batched top-k, counterpart of
  ``matrix::select_k``'s warp-sort path
  (matrix/detail/select_warpsort.cuh): a running k-buffer in VMEM is
  merged with each score tile by iterative extraction (k min+mask
  rounds per tile, all VPU work on VMEM-resident data — the TPU-shaped
  replacement for warp bitonic queues).

Both kernels run compiled on TPU and in interpreter mode elsewhere
(tests force ``interpret=True`` on CPU; dispatchers in matrix/distance
pick the XLA path off-TPU).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane width constraint: last dim multiples of 128, sublanes of 8 (f32).
_LANES = 128
_SUBLANES = 8


def _pad_to(x, mult, axis, value):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


# ---------------------------------------------------------------------------
# fused L2 argmin
# ---------------------------------------------------------------------------

def _fused_l2_argmin_kernel(x_ref, y_ref, nvalid_ref, dist_ref, idx_ref):
    """Grid = (m_tiles, n_tiles); n is the minor (sequential) axis, so the
    output block for a given m-tile is revisited across n-tiles and acts
    as the running (min, argmin) accumulator.

    Per-row scalars live as lane-broadcast [bm, 128] blocks — Mosaic's
    layout for 1-D f32 operands doesn't match XLA's, so 2-D it is; the
    host-side wrapper slices lane 0.  Row norms are computed in-kernel
    (cheap VPU work) to avoid extra 1-D operands."""
    nt = pl.program_id(1)
    bn = y_ref.shape[0]

    @pl.when(nt == 0)
    def _init():
        dist_ref[:] = jnp.full_like(dist_ref, jnp.inf)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    x = x_ref[:]                       # [bm, d]
    y = y_ref[:]                       # [bn, d]
    xsq = jnp.sum(x * x, axis=1)       # [bm]
    ysq = jnp.sum(y * y, axis=1)       # [bn]
    d2 = (
        xsq[:, None]
        + ysq[None, :]
        - 2.0 * jax.lax.dot_general(
            x, y, (((1,), (1,)), ((), ())),
            # f32-exact MXU passes: bf16 default loses ~1e-3 relative,
            # enough to flip argmins (the reference kernel is fp32)
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
    )
    d2 = jnp.maximum(d2, 0.0)
    # mask padded columns of the final tile
    col = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1) + nt * bn
    d2 = jnp.where(col < nvalid_ref[0], d2, jnp.inf)

    blk_min = jnp.min(d2, axis=1)                                  # [bm]
    blk_arg = jnp.argmin(d2, axis=1).astype(jnp.int32) + nt * bn   # [bm]
    lanes = dist_ref.shape[1]
    take = blk_min < dist_ref[:, 0]
    dist_ref[:] = jnp.where(
        take[:, None], jnp.broadcast_to(blk_min[:, None], (blk_min.shape[0], lanes)),
        dist_ref[:])
    idx_ref[:] = jnp.where(
        take[:, None], jnp.broadcast_to(blk_arg[:, None], (blk_arg.shape[0], lanes)),
        idx_ref[:])


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def fused_l2_argmin(x: jax.Array, y: jax.Array, bm: int = 256, bn: int = 512,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """(min squared-L2 distance, argmin) of each x row against all y rows.

    Pallas counterpart of ``fused_l2_nn`` (distance/fused_l2_nn.cuh).
    """
    m, d = x.shape
    n = y.shape[0]
    xf = _pad_to(x.astype(jnp.float32), bm, 0, 0.0)
    yf = _pad_to(y.astype(jnp.float32), bn, 0, 0.0)
    dpad = (-d) % _LANES
    if dpad:
        xf = jnp.pad(xf, ((0, 0), (0, dpad)))
        yf = jnp.pad(yf, ((0, 0), (0, dpad)))
    mp, np_ = xf.shape[0], yf.shape[0]
    nvalid = jnp.full((1,), n, jnp.int32)

    grid = (mp // bm, np_ // bn)
    dist, idx = pl.pallas_call(
        _fused_l2_argmin_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, xf.shape[1]), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, yf.shape[1]), lambda i, j: (j, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((mp, _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(xf, yf, nvalid)
    return dist[:m, 0], idx[:m, 0]


# ---------------------------------------------------------------------------
# select_k (running top-k buffer, iterative extraction per tile)
# ---------------------------------------------------------------------------

def _select_k_kernel(scores_ref, nvalid_ref, vals_ref, idx_ref, *, k: int,
                     select_min: bool):
    """Grid = (m_tiles, len_tiles); len is the sequential minor axis.  The
    output [bm, kpad] block doubles as the running top-k buffer."""
    lt = pl.program_id(1)
    bm, bl = scores_ref.shape
    kpad = vals_ref.shape[1]
    big = jnp.inf if select_min else -jnp.inf

    @pl.when(lt == 0)
    def _init():
        vals_ref[:] = jnp.full_like(vals_ref, big)
        idx_ref[:] = jnp.full_like(idx_ref, -1)

    s = scores_ref[:]
    if not select_min:
        s = -s  # uniform ascending selection
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + lt * bl
    s = jnp.where(col < nvalid_ref[0], s, jnp.inf)

    buf_v = vals_ref[:] if select_min else jnp.where(
        jnp.isinf(vals_ref[:]), jnp.inf, -vals_ref[:])
    # combined candidate set: running buffer ++ this tile
    comb_v = jnp.concatenate([buf_v, s], axis=1)          # [bm, kpad+bl]
    comb_i = jnp.concatenate([idx_ref[:], col], axis=1)

    out_v = jnp.full((bm, kpad), jnp.inf, jnp.float32)
    out_i = jnp.full((bm, kpad), -1, jnp.int32)
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (bm, kpad), 1)
    # k is static → unrolled extraction (scatter at a traced column is
    # unsupported in Mosaic; a where against the static column is)
    for j in range(k):
        mn = jnp.min(comb_v, axis=1)
        am = jnp.argmin(comb_v, axis=1)
        onehot = jax.lax.broadcasted_iota(jnp.int32, comb_v.shape, 1) == am[:, None]
        # gather-free pick: masked min over the argmin one-hot (Mosaic
        # has no general gather)
        picked_i = jnp.min(
            jnp.where(onehot, comb_i, jnp.iinfo(jnp.int32).max), axis=1)
        out_v = jnp.where(out_cols == j, mn[:, None], out_v)
        out_i = jnp.where(out_cols == j, picked_i[:, None], out_i)
        # knock out the extracted entry
        comb_v = jnp.where(onehot, jnp.inf, comb_v)
    vals_ref[:] = out_v if select_min else jnp.where(
        jnp.isinf(out_v), -jnp.inf, -out_v)
    idx_ref[:] = out_i


@functools.partial(jax.jit,
                   static_argnames=("k", "select_min", "bm", "bl", "interpret"))
def select_k_pallas(scores: jax.Array, k: int, select_min: bool = True,
                    bm: int = 64, bl: int = 2048,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Batched top-k over rows of ``scores`` [m, len] — Pallas counterpart
    of ``matrix::select_k`` (matrix/select_k.cuh:81).  Returns sorted
    (values [m, k], indices [m, k])."""
    m, n = scores.shape
    if k > n:
        raise ValueError(f"k={k} > len={n}")
    kpad = max(_LANES, ((k + _LANES - 1) // _LANES) * _LANES)
    s = _pad_to(scores.astype(jnp.float32), bm, 0, 0.0)
    s = _pad_to(s, bl, 1, jnp.inf if select_min else -jnp.inf)
    mp, npad = s.shape
    nvalid = jnp.full((1,), n, jnp.int32)

    grid = (mp // bm, npad // bl)
    vals, idx = pl.pallas_call(
        functools.partial(_select_k_kernel, k=k, select_min=select_min),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bl), lambda i, j: (i, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mp, kpad), jnp.int32),
        ],
        interpret=interpret,
    )(s, nvalid)
    return vals[:m, :k], idx[:m, :k]
