"""Pallas TPU kernels for the hot ANN primitives.

TPU-native re-implementation of the reference's load-bearing CUDA
kernels (SURVEY.md §7 "hard parts"):

- :func:`fused_l2_argmin` — fused distance + argmin over column tiles,
  the counterpart of ``fused_l2_nn`` (distance/detail/fused_l2_nn.cuh):
  one VMEM-resident pass per y-tile, MXU Gram + VPU epilogue + running
  (min, argmin) accumulated in the output block across the sequential
  grid axis — the [m, n] matrix never touches HBM.
- :func:`select_k_pallas` — batched top-k, counterpart of
  ``matrix::select_k``'s warp-sort path
  (matrix/detail/select_warpsort.cuh): a running k-buffer in VMEM is
  merged with each score tile by iterative extraction (k min+mask
  rounds per tile, all VPU work on VMEM-resident data — the TPU-shaped
  replacement for warp bitonic queues).

Both kernels run compiled on TPU and in interpreter mode elsewhere
(tests force ``interpret=True`` on CPU; dispatchers in matrix/distance
pick the XLA path off-TPU).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane width constraint: last dim multiples of 128, sublanes of 8 (f32).
_LANES = 128
_SUBLANES = 8


def _pad_to(x, mult, axis, value):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


# ---------------------------------------------------------------------------
# fused L2 argmin
# ---------------------------------------------------------------------------

def _fused_l2_argmin_kernel(x_ref, y_ref, nvalid_ref, dist_ref, idx_ref):
    """Grid = (m_tiles, n_tiles); n is the minor (sequential) axis, so the
    output block for a given m-tile is revisited across n-tiles and acts
    as the running (min, argmin) accumulator.

    Per-row scalars live as lane-broadcast [bm, 128] blocks — Mosaic's
    layout for 1-D f32 operands doesn't match XLA's, so 2-D it is; the
    host-side wrapper slices lane 0.  Row norms are computed in-kernel
    (cheap VPU work) to avoid extra 1-D operands."""
    nt = pl.program_id(1)
    bn = y_ref.shape[0]

    @pl.when(nt == 0)
    def _init():
        dist_ref[:] = jnp.full_like(dist_ref, jnp.inf)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    x = x_ref[:]                       # [bm, d]
    y = y_ref[:]                       # [bn, d]
    xsq = jnp.sum(x * x, axis=1)       # [bm]
    ysq = jnp.sum(y * y, axis=1)       # [bn]
    d2 = (
        xsq[:, None]
        + ysq[None, :]
        - 2.0 * jax.lax.dot_general(
            x, y, (((1,), (1,)), ((), ())),
            # f32-exact MXU passes: bf16 default loses ~1e-3 relative,
            # enough to flip argmins (the reference kernel is fp32)
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
    )
    d2 = jnp.maximum(d2, 0.0)
    # mask padded columns of the final tile
    col = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1) + nt * bn
    d2 = jnp.where(col < nvalid_ref[0], d2, jnp.inf)

    blk_min = jnp.min(d2, axis=1)                                  # [bm]
    blk_arg = jnp.argmin(d2, axis=1).astype(jnp.int32) + nt * bn   # [bm]
    lanes = dist_ref.shape[1]
    take = blk_min < dist_ref[:, 0]
    dist_ref[:] = jnp.where(
        take[:, None], jnp.broadcast_to(blk_min[:, None], (blk_min.shape[0], lanes)),
        dist_ref[:])
    idx_ref[:] = jnp.where(
        take[:, None], jnp.broadcast_to(blk_arg[:, None], (blk_arg.shape[0], lanes)),
        idx_ref[:])


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def fused_l2_argmin(x: jax.Array, y: jax.Array, bm: int = 256, bn: int = 512,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """(min squared-L2 distance, argmin) of each x row against all y rows.

    Pallas counterpart of ``fused_l2_nn`` (distance/fused_l2_nn.cuh).
    """
    m, d = x.shape
    n = y.shape[0]
    xf = _pad_to(x.astype(jnp.float32), bm, 0, 0.0)
    yf = _pad_to(y.astype(jnp.float32), bn, 0, 0.0)
    dpad = (-d) % _LANES
    if dpad:
        xf = jnp.pad(xf, ((0, 0), (0, dpad)))
        yf = jnp.pad(yf, ((0, 0), (0, dpad)))
    mp, np_ = xf.shape[0], yf.shape[0]
    nvalid = jnp.full((1,), n, jnp.int32)

    grid = (mp // bm, np_ // bn)
    dist, idx = pl.pallas_call(
        _fused_l2_argmin_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, xf.shape[1]), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, yf.shape[1]), lambda i, j: (j, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((mp, _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(xf, yf, nvalid)
    return dist[:m, 0], idx[:m, 0]


# ---------------------------------------------------------------------------
# select_k (running top-k buffer, iterative extraction per tile)
# ---------------------------------------------------------------------------

def _select_k_kernel(scores_ref, nvalid_ref, vals_ref, idx_ref, *, k: int,
                     select_min: bool):
    """Grid = (m_tiles, len_tiles); len is the sequential minor axis.  The
    output [bm, kpad] block doubles as the running top-k buffer."""
    lt = pl.program_id(1)
    bm, bl = scores_ref.shape
    kpad = vals_ref.shape[1]
    big = jnp.inf if select_min else -jnp.inf

    @pl.when(lt == 0)
    def _init():
        vals_ref[:] = jnp.full_like(vals_ref, big)
        idx_ref[:] = jnp.full_like(idx_ref, -1)

    s = scores_ref[:]
    if not select_min:
        s = -s  # uniform ascending selection
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + lt * bl
    s = jnp.where(col < nvalid_ref[0], s, jnp.inf)

    buf_v = vals_ref[:] if select_min else jnp.where(
        jnp.isinf(vals_ref[:]), jnp.inf, -vals_ref[:])
    # combined candidate set: running buffer ++ this tile
    comb_v = jnp.concatenate([buf_v, s], axis=1)          # [bm, kpad+bl]
    comb_i = jnp.concatenate([idx_ref[:], col], axis=1)

    out_v = jnp.full((bm, kpad), jnp.inf, jnp.float32)
    out_i = jnp.full((bm, kpad), -1, jnp.int32)
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (bm, kpad), 1)
    # k is static → unrolled extraction (scatter at a traced column is
    # unsupported in Mosaic; a where against the static column is)
    for j in range(k):
        mn = jnp.min(comb_v, axis=1)
        am = jnp.argmin(comb_v, axis=1)
        onehot = jax.lax.broadcasted_iota(jnp.int32, comb_v.shape, 1) == am[:, None]
        # gather-free pick: masked min over the argmin one-hot (Mosaic
        # has no general gather)
        picked_i = jnp.min(
            jnp.where(onehot, comb_i, jnp.iinfo(jnp.int32).max), axis=1)
        out_v = jnp.where(out_cols == j, mn[:, None], out_v)
        out_i = jnp.where(out_cols == j, picked_i[:, None], out_i)
        # knock out the extracted entry
        comb_v = jnp.where(onehot, jnp.inf, comb_v)
    vals_ref[:] = out_v if select_min else jnp.where(
        jnp.isinf(out_v), -jnp.inf, -out_v)
    idx_ref[:] = out_i


# ---------------------------------------------------------------------------
# grouped IVF list scan: contraction + metric epilogue + local top-k, fused
# ---------------------------------------------------------------------------

def _grouped_scan_kernel(qv_ref, data_ref, mask_ref, vals_ref, pos_ref, *,
                         kk: int, metric: str):
    """One (list, query-tile) program: [bq, d] × [d, Lp] on the MXU, the
    metric epilogue on the VPU, and a kk-round running extraction — the
    [bq, Lp] distance block lives and dies in VMEM.  Counterpart of the
    reference's fused scan+top-k kernels
    (ivf_flat_interleaved_scan-inl.cuh; ivf_pq_compute_similarity-inl.cuh
    manage_local_topk :439).  All metrics are minimized: ip keys are
    negated scores (caller restores sign)."""
    qv = qv_ref[0]                                  # [bq, dpad] f32
    data = data_ref[0].astype(jnp.float32)          # [Lp, dpad]
    mask = mask_ref[0]                              # [1, Lp]
    s = jax.lax.dot_general(
        qv, data, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)         # [bq, Lp]
    if metric == "ip":
        dist = -s
    else:
        qsq = jnp.sum(qv * qv, axis=1)              # [bq]
        nsq = jnp.sum(data * data, axis=1)          # [Lp]
        if metric == "cos":
            qn = jax.lax.rsqrt(jnp.maximum(qsq, 1e-30))
            cn = jax.lax.rsqrt(jnp.maximum(nsq, 1e-30))
            dist = 1.0 - s * qn[:, None] * cn[None, :]
        else:  # l2
            dist = jnp.maximum(qsq[:, None] + nsq[None, :] - 2.0 * s, 0.0)
    dist = dist + mask                              # [1, Lp] broadcast: +inf invalid

    bq = dist.shape[0]
    kpad = vals_ref.shape[2]
    out_v = jnp.full((bq, kpad), jnp.inf, jnp.float32)
    out_i = jnp.full((bq, kpad), -1, jnp.int32)
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (bq, kpad), 1)
    col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1)
    for j in range(kk):  # static unroll (see _select_k_kernel)
        mn = jnp.min(dist, axis=1)
        am = jnp.argmin(dist, axis=1)
        out_v = jnp.where(out_cols == j, mn[:, None], out_v)
        out_i = jnp.where(out_cols == j, am[:, None], out_i)
        # knock out the extracted entry for the next round
        dist = jnp.where(col == am[:, None], jnp.inf, dist)
    vals_ref[0] = out_v
    pos_ref[0] = out_i


# VMEM working-set budget for one grouped-scan program (of ~16 MB/core):
# list block [Lp, dpad] f32 + distance block [bq, Lp] f32 + small operands.
_GROUPED_VMEM_BUDGET = 12 * 1024 * 1024


def pallas_grouped_wanted(kk: int, L: int = 0, d: int = 0,
                          bq: int = 128) -> bool:
    """Dispatch: use the fused grouped-scan kernel on TPU for small kk
    (the extraction loop is kk VPU rounds) when one program's VMEM
    working set — padded list block + distance block — fits the budget;
    otherwise the XLA grouped path (which tiles freely) handles it.
    ``RAFT_TPU_PALLAS_GROUPED`` = always | never | auto — "always" runs
    interpreted off-TPU (tests)."""
    import os

    force = os.environ.get("RAFT_TPU_PALLAS_GROUPED", "auto")
    if force == "never" or kk > 64:
        return False
    if L and d:
        Lp = -(-L // _LANES) * _LANES
        dpad = -(-d // _LANES) * _LANES
        vmem = 4 * (Lp * dpad + bq * Lp + bq * dpad)
        if vmem > _GROUPED_VMEM_BUDGET:
            return False
    return True if force == "always" else _on_tpu()


@functools.partial(jax.jit,
                   static_argnames=("kk", "metric", "bq", "interpret"))
def grouped_scan_topk(q_gathered: jax.Array, list_data: jax.Array,
                      mask_add: jax.Array, kk: int, metric: str = "l2",
                      bq: int = 128, interpret: bool = False
                      ) -> Tuple[jax.Array, jax.Array]:
    """Fused grouped IVF scan over one segment chunk.

    q_gathered [G, S, d] — each segment's queued queries (gathered by
    the caller from the segment tables, see ivf_common.segment_probes);
    list_data [G, L, d] — each segment's list block: raw vectors
    (ivf_flat) or bf16 reconstructions (ivf_pq recon cache); mask_add
    [G, L] — 0 for valid slots, +inf for padding/filtered.  Returns
    (keys [G, S, kk], pos [G, S, kk]): minimized sort keys (ip keys are
    negated scores) and in-list column positions (-1 when the slot saw
    fewer than kk valid candidates).  ``bq`` tiles the S axis; callers
    pass the segment size."""
    G, qmax, d = q_gathered.shape
    L = list_data.shape[1]
    assert metric in ("l2", "ip", "cos")
    bq = min(bq, max(_SUBLANES, qmax))
    q = _pad_to(q_gathered.astype(jnp.float32), bq, 1, 0.0)
    q = _pad_to(q, _LANES, 2, 0.0)
    data = _pad_to(list_data, _LANES, 2, 0.0)
    data = _pad_to(data, 16, 1, 0.0)  # 16 sublanes covers bf16 list data
    mask = _pad_to(mask_add.astype(jnp.float32), data.shape[1], 1, jnp.inf)
    mask = mask[:, None, :]  # [G, 1, Lp]: trailing dims match the array
    qp, Lp, dpad = q.shape[1], data.shape[1], data.shape[2]
    kpad = max(_LANES, -(-kk // _LANES) * _LANES)

    grid = (G, qp // bq)
    vals, pos = pl.pallas_call(
        functools.partial(_grouped_scan_kernel, kk=kk, metric=metric),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dpad), lambda g, j: (g, j, 0)),
            pl.BlockSpec((1, Lp, dpad), lambda g, j: (g, 0, 0)),
            pl.BlockSpec((1, 1, Lp), lambda g, j: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, kpad), lambda g, j: (g, j, 0)),
            pl.BlockSpec((1, bq, kpad), lambda g, j: (g, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G, qp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((G, qp, kpad), jnp.int32),
        ],
        interpret=interpret,
    )(q, data, mask)
    keys = vals[:, :qmax, :kk]
    posk = pos[:, :qmax, :kk]
    # positions beyond the valid candidates come back as inf keys
    posk = jnp.where(jnp.isinf(keys), -1, posk)
    return keys, posk


def _segmented_scan_kernel(seg_list_ref, qv_ref, data_ref, ids_ref,
                           keys_ref, pos_ref, *, metric: str, L: int):
    """One program per segment: the segment's [S, d] queries against its
    list's [Lp, d] block — which the pipeline DMAs straight out of the
    FULL packed array using the scalar-prefetched ``seg_list`` index
    (hot lists occupy consecutive segments, so repeated indices skip
    the copy entirely). Selection reduces the [S, Lp] distance row into
    128 STRIDED bins (bin = position mod 128, min across the L/128
    tiles): consecutive list slots land in distinct bins, so clustered
    datasets — where a query's true top-k sits in a run of consecutive
    rows — don't collapse into one bin (a per-consecutive-tile min
    measured recall 0.63 vs 0.97 for strided bins on 1M clustered
    data). The caller top-ks the [S, 128] bin table."""
    qv = qv_ref[0].astype(jnp.float32)              # [S, dpad]
    data = data_ref[0].astype(jnp.float32)          # [Lp, dpad]
    ids = ids_ref[0]                                # [1, Lp] i32
    s = jax.lax.dot_general(
        qv, data, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)         # [S, Lp]
    if metric == "ip":
        dist = -s
    else:
        qsq = jnp.sum(qv * qv, axis=1)
        nsq = jnp.sum(data * data, axis=1)
        if metric == "cos":
            qn = jax.lax.rsqrt(jnp.maximum(qsq, 1e-30))
            cn = jax.lax.rsqrt(jnp.maximum(nsq, 1e-30))
            dist = 1.0 - s * qn[:, None] * cn[None, :]
        else:  # l2
            dist = jnp.maximum(qsq[:, None] + nsq[None, :] - 2.0 * s, 0.0)
    col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1)
    bad = (ids < 0) | (col >= L)                    # [1→S, Lp] broadcast
    dist = jnp.where(bad, jnp.inf, dist)

    S, Lp = dist.shape
    T = Lp // _LANES
    d3 = dist.reshape(S, T, _LANES)
    t_iota = jax.lax.broadcasted_iota(jnp.int32, (S, T, _LANES), 1)
    ids3 = jnp.broadcast_to(ids.reshape(1, T, _LANES), (S, T, _LANES))
    imax = jnp.iinfo(jnp.int32).max

    def pick(dd):
        # (min, winner's GLOBAL id) per strided bin. Emitting ids here —
        # a one-hot masked min, Mosaic has no gather — is what lets the
        # caller skip the [n_seg·S, kk] pointwise id gather that
        # measured ~1 s at kk=40 on a 771K-slot scan
        mnx = jnp.min(dd, axis=1)                   # [S, 128]
        amx = jnp.argmin(dd, axis=1).astype(jnp.int32)
        win = t_iota == amx[:, None, :]
        idx = jnp.min(jnp.where(win, ids3, imax), axis=1)
        return mnx, jnp.where(jnp.isinf(mnx), -1, idx), win

    # two best per bin: one collision (two of a query's true top-k in
    # the same stride-128 bin) no longer loses a candidate
    mn1, id1, win1 = pick(d3)
    mn2, id2, _ = pick(jnp.where(win1, jnp.inf, d3))
    keys_ref[0] = jnp.concatenate([mn1, mn2], axis=1)   # [S, 256]
    pos_ref[0] = jnp.concatenate([id1, id2], axis=1)


@functools.partial(jax.jit, static_argnames=("metric", "interpret"))
def segmented_scan_topk(seg_list: jax.Array, qv: jax.Array,
                        packed: jax.Array, ids: jax.Array,
                        metric: str = "l2", interpret: bool = False
                        ) -> Tuple[jax.Array, jax.Array]:
    """Segmented grouped IVF scan with in-kernel list-block DMA.

    The XLA formulation gathers each probed list block out of HBM at
    ~20 GB/s (measured — TPU gathers don't stream); here the Pallas
    pipeline DMAs ``packed[seg_list[s]]`` per grid step at copy
    bandwidth, double-buffered against the MXU contraction.

    seg_list [n_seg] i32 — owning list per segment (scalar-prefetched);
    qv [n_seg, S, d] — per-segment queries (pad slots may repeat rows);
    packed [n_lists, L, d] — FULL padded list data; ids [n_lists, L].
    Returns (keys [n_seg, S, 256], ids [n_seg, S, 256]) — the two best
    (minimized sort key, GLOBAL candidate id) per strided bin, id -1
    invalid; callers merge with a top-k over the 256 candidates. Ids
    are resolved in-kernel from the VMEM ids row — an XLA-side
    pointwise id gather measured ~1 s at kk=40 on a 771K-slot scan.
    """
    n_seg, S, d = qv.shape
    n_lists, L = ids.shape
    assert metric in ("l2", "ip", "cos")
    qvp = _pad_to(qv.astype(jnp.float32), _LANES, 2, 0.0)
    data = _pad_to(packed, _LANES, 2, 0.0)
    # the kernel splits the list axis into (L/128, 128) strided bins, so
    # pad L to a full lane multiple (tiny-list indexes have L as small
    # as 8); padded slots carry id -1 → masked invalid
    data = _pad_to(data, _LANES, 1, 0.0)
    idsp = _pad_to(ids, data.shape[1], 1, -1)[:, None, :]  # [n_lists, 1, Lp]
    Lp, dpad = data.shape[1], data.shape[2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_seg,),
        in_specs=[
            pl.BlockSpec((1, S, dpad), lambda s, sl: (s, 0, 0)),
            pl.BlockSpec((1, Lp, dpad), lambda s, sl: (sl[s], 0, 0)),
            pl.BlockSpec((1, 1, Lp), lambda s, sl: (sl[s], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, S, 2 * _LANES), lambda s, sl: (s, 0, 0)),
            pl.BlockSpec((1, S, 2 * _LANES), lambda s, sl: (s, 0, 0)),
        ],
    )
    keys, pos = pl.pallas_call(
        functools.partial(_segmented_scan_kernel, metric=metric, L=L),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_seg, S, 2 * _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_seg, S, 2 * _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(seg_list.astype(jnp.int32), qvp, data, idsp)
    return keys, pos


def pallas_segmented_wanted(kk: int, L: int, d: int, S: int = 128) -> bool:
    """Dispatch for :func:`segmented_scan_topk`: needs kk ≤ 128 (two
    candidates per strided bin) and a VMEM-sized list block. Same env override
    as pallas_grouped_wanted."""
    import os

    force = os.environ.get("RAFT_TPU_PALLAS_GROUPED", "auto")
    if force == "never" or kk > _LANES:
        return False
    Lp = -(-L // _LANES) * _LANES
    dpad = -(-d // _LANES) * _LANES
    vmem = 4 * (Lp * dpad + S * Lp + S * dpad)
    if vmem > _GROUPED_VMEM_BUDGET:
        return False
    return True if force == "always" else _on_tpu()


@functools.partial(jax.jit,
                   static_argnames=("k", "select_min", "bm", "bl", "interpret"))
def select_k_pallas(scores: jax.Array, k: int, select_min: bool = True,
                    bm: int = 64, bl: int = 2048,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Batched top-k over rows of ``scores`` [m, len] — Pallas counterpart
    of ``matrix::select_k`` (matrix/select_k.cuh:81).  Returns sorted
    (values [m, k], indices [m, k])."""
    m, n = scores.shape
    if k > n:
        raise ValueError(f"k={k} > len={n}")
    kpad = max(_LANES, ((k + _LANES - 1) // _LANES) * _LANES)
    s = _pad_to(scores.astype(jnp.float32), bm, 0, 0.0)
    s = _pad_to(s, bl, 1, jnp.inf if select_min else -jnp.inf)
    mp, npad = s.shape
    nvalid = jnp.full((1,), n, jnp.int32)

    grid = (mp // bm, npad // bl)
    vals, idx = pl.pallas_call(
        functools.partial(_select_k_kernel, k=k, select_min=select_min),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bl), lambda i, j: (i, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mp, kpad), jnp.int32),
        ],
        interpret=interpret,
    )(s, nvalid)
    return vals[:m, :k], idx[:m, :k]
