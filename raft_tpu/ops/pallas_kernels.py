"""Pallas TPU kernels for the hot ANN primitives.

TPU-native re-implementation of the reference's load-bearing CUDA
kernels (SURVEY.md §7 "hard parts"):

- :func:`fused_l2_argmin` — fused distance + argmin over column tiles,
  the counterpart of ``fused_l2_nn`` (distance/detail/fused_l2_nn.cuh):
  one VMEM-resident pass per y-tile, MXU Gram + VPU epilogue + running
  (min, argmin) accumulated in the output block across the sequential
  grid axis — the [m, n] matrix never touches HBM.
- :func:`select_k_pallas` — batched top-k, counterpart of
  ``matrix::select_k``'s warp-sort path
  (matrix/detail/select_warpsort.cuh): a running k-buffer in VMEM is
  merged with each score tile by iterative extraction (k min+mask
  rounds per tile, all VPU work on VMEM-resident data — the TPU-shaped
  replacement for warp bitonic queues).

Both kernels run compiled on TPU and in interpreter mode elsewhere
(tests force ``interpret=True`` on CPU; dispatchers in matrix/distance
pick the XLA path off-TPU).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.obs.spans import env_tristate as _env_tristate

# Lane width constraint: last dim multiples of 128, sublanes of 8 (f32).
_LANES = 128
_SUBLANES = 8


def _pad_to(x, mult, axis, value):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


# ---------------------------------------------------------------------------
# fused L2 argmin
# ---------------------------------------------------------------------------

def _fused_l2_argmin_kernel(x_ref, y_ref, nvalid_ref, dist_ref, idx_ref):
    """Grid = (m_tiles, n_tiles); n is the minor (sequential) axis, so the
    output block for a given m-tile is revisited across n-tiles and acts
    as the running (min, argmin) accumulator.

    Per-row scalars live as lane-broadcast [bm, 128] blocks — Mosaic's
    layout for 1-D f32 operands doesn't match XLA's, so 2-D it is; the
    host-side wrapper slices lane 0.  Row norms are computed in-kernel
    (cheap VPU work) to avoid extra 1-D operands."""
    nt = pl.program_id(1)
    bn = y_ref.shape[0]

    @pl.when(nt == 0)
    def _init():
        dist_ref[:] = jnp.full_like(dist_ref, jnp.inf)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    x = x_ref[:]                       # [bm, d]
    y = y_ref[:]                       # [bn, d]
    xsq = jnp.sum(x * x, axis=1)       # [bm]
    ysq = jnp.sum(y * y, axis=1)       # [bn]
    d2 = (
        xsq[:, None]
        + ysq[None, :]
        - 2.0 * jax.lax.dot_general(
            x, y, (((1,), (1,)), ((), ())),
            # f32-exact MXU passes: bf16 default loses ~1e-3 relative,
            # enough to flip argmins (the reference kernel is fp32)
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
    )
    d2 = jnp.maximum(d2, 0.0)
    # mask padded columns of the final tile
    col = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1) + nt * bn
    d2 = jnp.where(col < nvalid_ref[0], d2, jnp.inf)

    blk_min = jnp.min(d2, axis=1)                                  # [bm]
    blk_arg = jnp.argmin(d2, axis=1).astype(jnp.int32) + nt * bn   # [bm]
    lanes = dist_ref.shape[1]
    take = blk_min < dist_ref[:, 0]
    dist_ref[:] = jnp.where(
        take[:, None], jnp.broadcast_to(blk_min[:, None], (blk_min.shape[0], lanes)),
        dist_ref[:])
    idx_ref[:] = jnp.where(
        take[:, None], jnp.broadcast_to(blk_arg[:, None], (blk_arg.shape[0], lanes)),
        idx_ref[:])


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def fused_l2_argmin(x: jax.Array, y: jax.Array, bm: int = 256, bn: int = 512,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """(min squared-L2 distance, argmin) of each x row against all y rows.

    Pallas counterpart of ``fused_l2_nn`` (distance/fused_l2_nn.cuh).
    """
    m, d = x.shape
    n = y.shape[0]
    xf = _pad_to(x.astype(jnp.float32), bm, 0, 0.0)
    yf = _pad_to(y.astype(jnp.float32), bn, 0, 0.0)
    dpad = (-d) % _LANES
    if dpad:
        xf = jnp.pad(xf, ((0, 0), (0, dpad)))
        yf = jnp.pad(yf, ((0, 0), (0, dpad)))
    mp, np_ = xf.shape[0], yf.shape[0]
    nvalid = jnp.full((1,), n, jnp.int32)

    grid = (mp // bm, np_ // bn)
    dist, idx = pl.pallas_call(
        _fused_l2_argmin_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, xf.shape[1]), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, yf.shape[1]), lambda i, j: (j, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((mp, _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(xf, yf, nvalid)
    return dist[:m, 0], idx[:m, 0]


# ---------------------------------------------------------------------------
# select_k (running top-k buffer, iterative extraction per tile)
# ---------------------------------------------------------------------------

def _select_k_kernel(scores_ref, nvalid_ref, vals_ref, idx_ref, *, k: int,
                     select_min: bool):
    """Grid = (m_tiles, len_tiles); len is the sequential minor axis.  The
    output [bm, kpad] block doubles as the running top-k buffer."""
    lt = pl.program_id(1)
    bm, bl = scores_ref.shape
    kpad = vals_ref.shape[1]
    big = jnp.inf if select_min else -jnp.inf

    @pl.when(lt == 0)
    def _init():
        vals_ref[:] = jnp.full_like(vals_ref, big)
        idx_ref[:] = jnp.full_like(idx_ref, -1)

    s = scores_ref[:]
    if not select_min:
        s = -s  # uniform ascending selection
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + lt * bl
    s = jnp.where(col < nvalid_ref[0], s, jnp.inf)

    buf_v = vals_ref[:] if select_min else jnp.where(
        jnp.isinf(vals_ref[:]), jnp.inf, -vals_ref[:])
    # combined candidate set: running buffer ++ this tile
    comb_v = jnp.concatenate([buf_v, s], axis=1)          # [bm, kpad+bl]
    comb_i = jnp.concatenate([idx_ref[:], col], axis=1)

    out_v = jnp.full((bm, kpad), jnp.inf, jnp.float32)
    out_i = jnp.full((bm, kpad), -1, jnp.int32)
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (bm, kpad), 1)
    # k is static → unrolled extraction (scatter at a traced column is
    # unsupported in Mosaic; a where against the static column is)
    for j in range(k):
        mn = jnp.min(comb_v, axis=1)
        am = jnp.argmin(comb_v, axis=1)
        onehot = jax.lax.broadcasted_iota(jnp.int32, comb_v.shape, 1) == am[:, None]
        # gather-free pick: masked min over the argmin one-hot (Mosaic
        # has no general gather)
        picked_i = jnp.min(
            jnp.where(onehot, comb_i, jnp.iinfo(jnp.int32).max), axis=1)
        out_v = jnp.where(out_cols == j, mn[:, None], out_v)
        out_i = jnp.where(out_cols == j, picked_i[:, None], out_i)
        # knock out the extracted entry
        comb_v = jnp.where(onehot, jnp.inf, comb_v)
    vals_ref[:] = out_v if select_min else jnp.where(
        jnp.isinf(out_v), -jnp.inf, -out_v)
    idx_ref[:] = out_i


# ---------------------------------------------------------------------------
# grouped IVF list scan: contraction + metric epilogue + local top-k, fused
# ---------------------------------------------------------------------------

def _grouped_scan_kernel(qv_ref, data_ref, mask_ref, vals_ref, pos_ref, *,
                         kk: int, metric: str):
    """One (list, query-tile) program: [bq, d] × [d, Lp] on the MXU, the
    metric epilogue on the VPU, and a kk-round running extraction — the
    [bq, Lp] distance block lives and dies in VMEM.  Counterpart of the
    reference's fused scan+top-k kernels
    (ivf_flat_interleaved_scan-inl.cuh; ivf_pq_compute_similarity-inl.cuh
    manage_local_topk :439).  All metrics are minimized: ip keys are
    negated scores (caller restores sign)."""
    qv = qv_ref[0]                                  # [bq, dpad] f32
    data = data_ref[0].astype(jnp.float32)          # [Lp, dpad]
    mask = mask_ref[0]                              # [1, Lp]
    s = jax.lax.dot_general(
        qv, data, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)         # [bq, Lp]
    if metric == "ip":
        dist = -s
    else:
        qsq = jnp.sum(qv * qv, axis=1)              # [bq]
        nsq = jnp.sum(data * data, axis=1)          # [Lp]
        if metric == "cos":
            qn = jax.lax.rsqrt(jnp.maximum(qsq, 1e-30))
            cn = jax.lax.rsqrt(jnp.maximum(nsq, 1e-30))
            dist = 1.0 - s * qn[:, None] * cn[None, :]
        else:  # l2
            dist = jnp.maximum(qsq[:, None] + nsq[None, :] - 2.0 * s, 0.0)
    dist = dist + mask                              # [1, Lp] broadcast: +inf invalid

    bq = dist.shape[0]
    kpad = vals_ref.shape[2]
    out_v = jnp.full((bq, kpad), jnp.inf, jnp.float32)
    out_i = jnp.full((bq, kpad), -1, jnp.int32)
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (bq, kpad), 1)
    col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1)
    for j in range(kk):  # static unroll (see _select_k_kernel)
        mn = jnp.min(dist, axis=1)
        am = jnp.argmin(dist, axis=1)
        out_v = jnp.where(out_cols == j, mn[:, None], out_v)
        out_i = jnp.where(out_cols == j, am[:, None], out_i)
        # knock out the extracted entry for the next round
        dist = jnp.where(col == am[:, None], jnp.inf, dist)
    vals_ref[0] = out_v
    pos_ref[0] = out_i


# VMEM working-set budget for one grouped-scan program (of ~16 MB/core):
# list block [Lp, dpad] f32 + distance block [bq, Lp] f32 + small operands.
_GROUPED_VMEM_BUDGET = 12 * 1024 * 1024


def pallas_grouped_wanted(kk: int, L: int = 0, d: int = 0,
                          bq: int = 128) -> bool:
    """Dispatch: use the fused grouped-scan kernel on TPU for small kk
    (the extraction loop is kk VPU rounds) when one program's VMEM
    working set — padded list block + distance block — fits the budget;
    otherwise the XLA grouped path (which tiles freely) handles it.
    ``RAFT_TPU_PALLAS_GROUPED`` = always | never | auto (tri-state, see
    :func:`raft_tpu.obs.env_tristate`) — "on"/"always" runs interpreted
    off-TPU (tests)."""
    force = _env_tristate("RAFT_TPU_PALLAS_GROUPED")
    if force == "off" or kk > 64:
        return False
    if L and d:
        Lp = -(-L // _LANES) * _LANES
        dpad = -(-d // _LANES) * _LANES
        vmem = 4 * (Lp * dpad + bq * Lp + bq * dpad)
        if vmem > _GROUPED_VMEM_BUDGET:
            return False
    return True if force == "on" else _on_tpu()


@functools.partial(jax.jit,
                   static_argnames=("kk", "metric", "bq", "interpret"))
def grouped_scan_topk(q_gathered: jax.Array, list_data: jax.Array,
                      mask_add: jax.Array, kk: int, metric: str = "l2",
                      bq: int = 128, interpret: bool = False
                      ) -> Tuple[jax.Array, jax.Array]:
    """Fused grouped IVF scan over one segment chunk.

    q_gathered [G, S, d] — each segment's queued queries (gathered by
    the caller from the segment tables, see ivf_common.segment_probes);
    list_data [G, L, d] — each segment's list block: raw vectors
    (ivf_flat) or bf16 reconstructions (ivf_pq recon cache); mask_add
    [G, L] — 0 for valid slots, +inf for padding/filtered.  Returns
    (keys [G, S, kk], pos [G, S, kk]): minimized sort keys (ip keys are
    negated scores) and in-list column positions (-1 when the slot saw
    fewer than kk valid candidates).  ``bq`` tiles the S axis; callers
    pass the segment size."""
    G, qmax, d = q_gathered.shape
    L = list_data.shape[1]
    assert metric in ("l2", "ip", "cos")
    bq = min(bq, max(_SUBLANES, qmax))
    q = _pad_to(q_gathered.astype(jnp.float32), bq, 1, 0.0)
    q = _pad_to(q, _LANES, 2, 0.0)
    data = _pad_to(list_data, _LANES, 2, 0.0)
    data = _pad_to(data, 16, 1, 0.0)  # 16 sublanes covers bf16 list data
    mask = _pad_to(mask_add.astype(jnp.float32), data.shape[1], 1, jnp.inf)
    mask = mask[:, None, :]  # [G, 1, Lp]: trailing dims match the array
    qp, Lp, dpad = q.shape[1], data.shape[1], data.shape[2]
    kpad = max(_LANES, -(-kk // _LANES) * _LANES)

    grid = (G, qp // bq)
    vals, pos = pl.pallas_call(
        functools.partial(_grouped_scan_kernel, kk=kk, metric=metric),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, dpad), lambda g, j: (g, j, 0)),
            pl.BlockSpec((1, Lp, dpad), lambda g, j: (g, 0, 0)),
            pl.BlockSpec((1, 1, Lp), lambda g, j: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, kpad), lambda g, j: (g, j, 0)),
            pl.BlockSpec((1, bq, kpad), lambda g, j: (g, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G, qp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((G, qp, kpad), jnp.int32),
        ],
        interpret=interpret,
    )(q, data, mask)
    keys = vals[:, :qmax, :kk]
    posk = pos[:, :qmax, :kk]
    # positions beyond the valid candidates come back as inf keys
    posk = jnp.where(jnp.isinf(keys), -1, posk)
    return keys, posk


def _segmented_scan_kernel(seg_list_ref, qv_ref, data_ref, ids_ref,
                           keys_ref, pos_ref, *, metric: str, L: int):
    """One program per segment: the segment's [S, d] queries against its
    list's [Lp, d] block — which the pipeline DMAs straight out of the
    FULL packed array using the scalar-prefetched ``seg_list`` index
    (hot lists occupy consecutive segments, so repeated indices skip
    the copy entirely). Selection reduces the [S, Lp] distance row into
    128 STRIDED bins (bin = position mod 128, min across the L/128
    tiles): consecutive list slots land in distinct bins, so clustered
    datasets — where a query's true top-k sits in a run of consecutive
    rows — don't collapse into one bin (a per-consecutive-tile min
    measured recall 0.63 vs 0.97 for strided bins on 1M clustered
    data). The caller top-ks the [S, 128] bin table."""
    qv = qv_ref[0].astype(jnp.float32)              # [S, dpad]
    data = data_ref[0].astype(jnp.float32)          # [Lp, dpad]
    ids = ids_ref[0]                                # [1, Lp] i32
    s = jax.lax.dot_general(
        qv, data, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)         # [S, Lp]
    if metric == "ip":
        dist = -s
    else:
        qsq = jnp.sum(qv * qv, axis=1)
        nsq = jnp.sum(data * data, axis=1)
        if metric == "cos":
            qn = jax.lax.rsqrt(jnp.maximum(qsq, 1e-30))
            cn = jax.lax.rsqrt(jnp.maximum(nsq, 1e-30))
            dist = 1.0 - s * qn[:, None] * cn[None, :]
        else:  # l2
            dist = jnp.maximum(qsq[:, None] + nsq[None, :] - 2.0 * s, 0.0)
    col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1)
    bad = (ids < 0) | (col >= L)                    # [1→S, Lp] broadcast
    dist = jnp.where(bad, jnp.inf, dist)

    S, Lp = dist.shape
    T = Lp // _LANES
    d3 = dist.reshape(S, T, _LANES)
    t_iota = jax.lax.broadcasted_iota(jnp.int32, (S, T, _LANES), 1)
    ids3 = jnp.broadcast_to(ids.reshape(1, T, _LANES), (S, T, _LANES))
    imax = jnp.iinfo(jnp.int32).max

    def pick(dd):
        # (min, winner's GLOBAL id) per strided bin. Emitting ids here —
        # a one-hot masked min, Mosaic has no gather — is what lets the
        # caller skip the [n_seg·S, kk] pointwise id gather that
        # measured ~1 s at kk=40 on a 771K-slot scan
        mnx = jnp.min(dd, axis=1)                   # [S, 128]
        amx = jnp.argmin(dd, axis=1).astype(jnp.int32)
        win = t_iota == amx[:, None, :]
        idx = jnp.min(jnp.where(win, ids3, imax), axis=1)
        return mnx, jnp.where(jnp.isinf(mnx), -1, idx), win

    # two best per bin: one collision (two of a query's true top-k in
    # the same stride-128 bin) no longer loses a candidate
    mn1, id1, win1 = pick(d3)
    mn2, id2, _ = pick(jnp.where(win1, jnp.inf, d3))
    keys_ref[0] = jnp.concatenate([mn1, mn2], axis=1)   # [S, 256]
    pos_ref[0] = jnp.concatenate([id1, id2], axis=1)


@functools.partial(jax.jit, static_argnames=("metric", "interpret"))
def segmented_scan_topk(seg_list: jax.Array, qv: jax.Array,
                        packed: jax.Array, ids: jax.Array,
                        metric: str = "l2", interpret: bool = False
                        ) -> Tuple[jax.Array, jax.Array]:
    """Segmented grouped IVF scan with in-kernel list-block DMA.

    The XLA formulation gathers each probed list block out of HBM at
    ~20 GB/s (measured — TPU gathers don't stream); here the Pallas
    pipeline DMAs ``packed[seg_list[s]]`` per grid step at copy
    bandwidth, double-buffered against the MXU contraction.

    seg_list [n_seg] i32 — owning list per segment (scalar-prefetched);
    qv [n_seg, S, d] — per-segment queries (pad slots may repeat rows);
    packed [n_lists, L, d] — FULL padded list data; ids [n_lists, L].
    Returns (keys [n_seg, S, 256], ids [n_seg, S, 256]) — the two best
    (minimized sort key, GLOBAL candidate id) per strided bin, id -1
    invalid; callers merge with a top-k over the 256 candidates. Ids
    are resolved in-kernel from the VMEM ids row — an XLA-side
    pointwise id gather measured ~1 s at kk=40 on a 771K-slot scan.
    """
    n_seg, S, d = qv.shape
    n_lists, L = ids.shape
    assert metric in ("l2", "ip", "cos")
    qvp = _pad_to(qv.astype(jnp.float32), _LANES, 2, 0.0)
    data = _pad_to(packed, _LANES, 2, 0.0)
    # the kernel splits the list axis into (L/128, 128) strided bins, so
    # pad L to a full lane multiple (tiny-list indexes have L as small
    # as 8); padded slots carry id -1 → masked invalid
    data = _pad_to(data, _LANES, 1, 0.0)
    idsp = _pad_to(ids, data.shape[1], 1, -1)[:, None, :]  # [n_lists, 1, Lp]
    Lp, dpad = data.shape[1], data.shape[2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_seg,),
        in_specs=[
            pl.BlockSpec((1, S, dpad), lambda s, sl: (s, 0, 0)),
            pl.BlockSpec((1, Lp, dpad), lambda s, sl: (sl[s], 0, 0)),
            pl.BlockSpec((1, 1, Lp), lambda s, sl: (sl[s], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, S, 2 * _LANES), lambda s, sl: (s, 0, 0)),
            pl.BlockSpec((1, S, 2 * _LANES), lambda s, sl: (s, 0, 0)),
        ],
    )
    keys, pos = pl.pallas_call(
        functools.partial(_segmented_scan_kernel, metric=metric, L=L),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_seg, S, 2 * _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_seg, S, 2 * _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(seg_list.astype(jnp.int32), qvp, data, idsp)
    return keys, pos


def pallas_segmented_wanted(kk: int, L: int, d: int, S: int = 128) -> bool:
    """Dispatch for :func:`segmented_scan_topk`: needs kk ≤ 128 (two
    candidates per strided bin) and a VMEM-sized list block. Same env override
    as pallas_grouped_wanted."""
    force = _env_tristate("RAFT_TPU_PALLAS_GROUPED")
    if force == "off" or kk > _LANES:
        return False
    Lp = -(-L // _LANES) * _LANES
    dpad = -(-d // _LANES) * _LANES
    vmem = 4 * (Lp * dpad + S * Lp + S * dpad)
    if vmem > _GROUPED_VMEM_BUDGET:
        return False
    return True if force == "on" else _on_tpu()


# ---------------------------------------------------------------------------
# fused IVF-PQ LUT scan: packed codes streamed from HBM, n-bit unpack +
# ADC accumulation + 2-deep strided-bin top-k all in VMEM
# ---------------------------------------------------------------------------

# Candidates emitted per (segment, query) slot: two best per strided bin.
LUT_SCAN_BINS = 2 * _LANES


def _lut_scan_config(S: int, K: int, P: int, nb: int, Wb: int,
                     lut_dtype: str):
    """Static tiling for :func:`ivfpq_lut_scan_topk`, or ``None`` when the
    layout is unsupported.

    ``G`` — code rows per stored byte row (1 unfolded; ``128/nb`` for the
    lane-folded layout, see ``IvfPqIndex.codes_folded``). ``Sg`` —
    subspaces decoded per MXU call: the grouped block-diagonal codebook
    operand is ``[K·Sg, Sg·P]``, so ``Sg·P ≤ 128`` keeps the output
    inside one lane tile and the operand's VMEM cost (``S·K·P·Sg``
    entries total) stays bounded. ``Kc`` — codebook entries compared per
    one-hot pass (bounds the ``[rows, Kc·Sg]`` transient)."""
    if nb <= 0 or Wb % nb:
        return None
    G = Wb // nb
    # bin spreading rotates lanes by 128/G per fold group; G must divide
    # the lane count, and deep folds mean tiny pq_dim — not this kernel's
    # territory
    if G > 8 or (G & (G - 1)):
        return None
    op_bytes = 4 if lut_dtype == "float32" else 2
    cap = min(_LANES // max(P, 1),
              (4 << 20) // max(1, S * K * P * op_bytes))
    if cap < 1:
        return None
    Sg = max(d for d in range(1, min(S, cap) + 1) if S % d == 0)
    # largest power of two ≤ min(K, 2048/Sg): divides K (K = 2^pq_bits)
    Kc = 1 << (min(K, max(1, 2048 // Sg)).bit_length() - 1)
    return G, Sg, Kc


def _lane_pick(a: jax.Array, start: int, stride: int, n: int) -> jax.Array:
    """Static strided lane slice of ``a [1, W]`` → ``[1, n]``."""
    if stride == 1:
        return jax.lax.slice(a, (0, start), (1, start + n))
    return jax.lax.slice(a, (0, start),
                         (1, start + (n - 1) * stride + 1), (1, stride))


def _roll_lanes(x: jax.Array, sh: int) -> jax.Array:
    """Static lane rotate (lane i ← lane (i − sh) mod W) via two slices —
    unambiguous in both Mosaic and interpret mode (``pltpu.roll``'s
    interpret path is ``jnp.roll``; its Mosaic path is tpu.dynamic_rotate,
    and relying on both agreeing is exactly the kind of bet this kernel
    avoids)."""
    sh %= x.shape[1]
    if sh == 0:
        return x
    return jnp.concatenate([x[:, -sh:], x[:, :-sh]], axis=1)


def _lut_unpack_codes(bytes_f, sel_lo, sel_hi, off_row, pq_bits: int,
                      K: int):
    """In-kernel unpack_bits: stored byte rows → integer code values via
    the exact f32 selection matmuls (Mosaic has no lane gather) plus
    integer shift/mask. ``bytes_f`` [Rt, Wb] f32; returns [Rt, G·S]
    i32. Shared by the standalone LUT-scan kernel and the fused
    scan-in-ring kernel."""
    lo = jax.lax.dot_general(
        bytes_f, sel_lo, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)          # [Rt, G·S]
    if pq_bits == 8:
        return lo.astype(jnp.int32)
    hi = jax.lax.dot_general(
        bytes_f, sel_hi, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)
    v16 = lo.astype(jnp.int32) | (hi.astype(jnp.int32) << 8)
    return jax.lax.shift_right_logical(v16, off_row) & (K - 1)


def _filter_unpack_operands(n_lanes: int):
    """Byte-column selection matrix + per-lane shift row unpacking one
    tile's PACKED filter bytes (``sample_filter.pack_mask_bytes``
    layout: bit j of byte b = candidate position 8·b + j) to per-lane
    keep bits — the filter's instance of the n-bit code unpack
    machinery (:func:`_lut_unpack_codes`): byte values are ≤ 255 so the
    f32 selection matmul is exact, then integer shift/mask."""
    sel = np.zeros((n_lanes // 8, n_lanes), np.float32)
    lanes = np.arange(n_lanes)
    sel[lanes // 8, lanes] = 1.0
    off = jnp.asarray((lanes % 8).astype(np.int32)[None, :])
    return jnp.asarray(sel), off


def _filter_vmem_bytes(G: int, Rt: int) -> int:
    """VMEM cost of one tier's in-kernel filter operands — the byte
    slots (double-buffered), the unpack selection matrix, and the
    shift row + unpacked keep bits (:func:`_filter_unpack_operands` /
    :func:`_lut_unpack_filter`). The ONE model both admission gates
    (``pallas_lut_scan_wanted``, ``ring_lut_scan_kernel_ok``) consult,
    so a layout change cannot leave one gate with a stale budget."""
    lanes = G * Rt
    return (2 * max(lanes // 8, _LANES)   # filter byte slots
            + (lanes // 8) * lanes * 4    # unpack selection matrix
            + 2 * lanes * 4)              # shift row + keep bits


def _lut_unpack_filter(fbytes_f, fsel, foff):
    """``fbytes_f`` [1, n_lanes/8] f32 byte values → [1, n_lanes] i32
    keep bits (1 = candidate may be returned). Shared by the standalone
    LUT-scan kernel and the fused scan-in-ring kernel."""
    b = jax.lax.dot_general(
        fbytes_f, fsel, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)          # [1, n_lanes]
    return jax.lax.shift_right_logical(b.astype(jnp.int32), foff) & 1


def _lut_tile_update(code, qv, qc, ids_row, norms_row, cbp_ref, t,
                     state, *, metric: str, pq_bits: int, S: int,
                     P: int, G: int, Sg: int, Kc: int, L: int, Rt: int,
                     rot: int, rotp: int, exact: bool, key_bias=None,
                     filt_row=None):
    """One code tile's ADC + 2-deep strided-bin update — the shared
    compute body of the LUT scan (steps 3–4 of
    :func:`_ivfpq_lut_scan_kernel`'s docstring), factored so the fused
    scan-in-ring kernel runs the identical math per tile.

    ``code`` [Rt, G·S] i32 unpacked code values; ``qv`` [rows, rotp]
    f32 rotated queries; ``qc`` [rows] ⟨q, c⟩; ``ids_row``/``norms_row``
    [1, G·Rt]; ``cbp_ref`` the grouped block-diagonal codebook operand
    (indexable per subspace group); ``t`` the code-tile index within
    the list (traced or static); ``state`` = (b1k, b1i, b2k, b2i)
    running 2-deep bin values; ``key_bias`` an optional [rows, 1]
    additive key column (the fused ring mode's per-query probe mask —
    un-probed rows get +``_LUT_MASK_BIG``); ``filt_row`` an optional
    [1, G·Rt] i32 keep-bit row (:func:`_lut_unpack_filter`) — filtered
    candidates join the invalid-id lanes in the ±inf/-1 sentinel
    epilogue, the exact pattern GL13 polices. Returns the updated
    state."""
    rows = qv.shape[0]
    n_sg = S // Sg
    slabs = Rt // _LANES
    K = 1 << pq_bits
    opd = jnp.float32 if exact else jnp.bfloat16
    prec = (jax.lax.Precision.HIGHEST if exact
            else jax.lax.Precision.DEFAULT)
    b1k, b1i, b2k, b2i = state
    one = jnp.asarray(1.0, opd)
    zero = jnp.asarray(0.0, opd)
    for si in range(slabs):
        for g in range(G):
            # decode this slab's fold group in VMEM: [128, rot]
            parts = []
            for sg in range(n_sg):
                cs = jax.lax.slice(
                    code, (si * _LANES, g * S + sg * Sg),
                    ((si + 1) * _LANES, g * S + (sg + 1) * Sg))
                tiled = cs
                for _ in range(Kc.bit_length() - 1):
                    tiled = jnp.concatenate([tiled, tiled], axis=1)
                acc = jnp.zeros((_LANES, Sg * P), jnp.float32)
                for kc in range(K // Kc):
                    kidx = (jax.lax.broadcasted_iota(
                        jnp.int32, (_LANES, Kc * Sg), 1) // Sg + kc * Kc)
                    oh = jnp.where(tiled == kidx, one, zero)
                    cbp = jax.lax.slice(
                        cbp_ref[sg], (kc * Kc * Sg, 0),
                        ((kc + 1) * Kc * Sg, Sg * P))
                    acc = acc + jax.lax.dot_general(
                        oh, cbp, (((1,), (0,)), ((), ())),
                        precision=prec,
                        preferred_element_type=jnp.float32)
                parts.append(acc)
            if rotp > rot:
                parts.append(jnp.zeros((_LANES, rotp - rot), jnp.float32))
            dec = jnp.concatenate(parts, axis=1)     # [128, rotp]
            qd = jax.lax.dot_general(
                qv, dec, (((1,), (1,)), ((), ())),
                precision=prec,
                preferred_element_type=jnp.float32)  # [rows, 128] ⟨q, d⟩
            lane0 = G * si * _LANES + g
            ids_g = _lane_pick(ids_row, lane0, G, _LANES)      # [1, 128]
            # list position of lane r: G·(t·Rt + si·128 + r) + g — OOB
            # tail lanes of the last tile carry garbage, mask them
            l_pos = (t * Rt + si * _LANES) * G + g \
                + G * jax.lax.broadcasted_iota(jnp.int32, (1, _LANES), 1)
            valid = (ids_g >= 0) & (l_pos < L)
            if filt_row is not None:
                keep = _lane_pick(filt_row, lane0, G, _LANES)
                valid = jnp.logical_and(valid, keep > 0)
            if metric == "ip":
                key = -(qc[:, None] + qd)
            else:  # l2: ‖c+d‖² − 2⟨q, c+d⟩ (caller adds ‖q‖²)
                norms_g = _lane_pick(norms_row, lane0, G, _LANES)
                key = norms_g - 2.0 * (qc[:, None] + qd)
            key = jnp.where(valid, key, jnp.inf)
            if key_bias is not None:
                key = key + key_bias
            idv = jnp.broadcast_to(jnp.where(valid, ids_g, -1),
                                   (rows, _LANES))
            # spread fold groups across bins: lane rotate by g·(128/G)
            sh = g * (_LANES // G)
            kn = _roll_lanes(key, sh)
            inew = _roll_lanes(idv, sh)
            # 2-deep running bin merge
            lt1 = kn < b1k
            lt2 = jnp.logical_and(jnp.logical_not(lt1), kn < b2k)
            b2k = jnp.where(lt1, b1k, jnp.where(lt2, kn, b2k))
            b2i = jnp.where(lt1, b1i, jnp.where(lt2, inew, b2i))
            b1k = jnp.where(lt1, kn, b1k)
            b1i = jnp.where(lt1, inew, b1i)
    return b1k, b1i, b2k, b2i


def _lut_scan_operands(codebooks: jax.Array, pq_bits: int, nb: int,
                       Wb: int, G: int, Sg: int, lut_dtype: str):
    """Host-side operand prep shared by the standalone LUT scan and the
    fused scan-in-ring kernel: the byte-column selection matrices +
    per-column shift row feeding :func:`_lut_unpack_codes`, and the
    grouped block-diagonal codebook operand feeding
    :func:`_lut_tile_update` (``cbp[gi, k·Sg + j, j·P : (j+1)·P] =
    cb[gi·Sg + j, k]`` — the one-hot's lane order is k-major, then j).
    One construction site keeps the two kernels' operands bit-identical
    — the fused tier's exact-parity contract with the standalone tier
    rides on it. Returns (sel_lo, sel_hi, off_arr, cbp)."""
    S, K, P = codebooks.shape
    s_idx = np.arange(S)
    byte_idx = (s_idx * pq_bits) // 8
    off_np = ((s_idx * pq_bits) % 8).astype(np.int32)
    sel_lo = np.zeros((Wb, G * S), np.float32)
    sel_hi = np.zeros((Wb, G * S), np.float32)
    for g in range(G):
        for s in range(S):
            sel_lo[g * nb + byte_idx[s], g * S + s] = 1.0
            if byte_idx[s] + 1 < nb:
                sel_hi[g * nb + byte_idx[s] + 1, g * S + s] = 1.0
    off_arr = jnp.asarray(np.tile(off_np, G)[None, :])
    opd = jnp.float32 if lut_dtype == "float32" else jnp.bfloat16
    cb = codebooks.astype(jnp.float32)
    if lut_dtype == "float8_e4m3":
        cb = cb.astype(jnp.float8_e4m3fn).astype(jnp.bfloat16)
    n_sg = S // Sg
    cb_t = cb.reshape(n_sg, Sg, K, P).transpose(0, 2, 1, 3)
    eye = jnp.eye(Sg, dtype=jnp.float32)
    cbp = (cb_t.astype(jnp.float32)[:, :, :, None, :]
           * eye[None, None, :, :, None]).reshape(
               n_sg, K * Sg, Sg * P).astype(opd)
    return jnp.asarray(sel_lo), jnp.asarray(sel_hi), off_arr, cbp


def _ivfpq_lut_scan_kernel(seg_list_ref, qv_ref, codes_ref, ids_ref,
                           norms_ref, ctr_ref, sel_lo_ref, sel_hi_ref,
                           off_ref, cbp_ref, *rest,
                           metric: str, pq_bits: int, S: int, P: int,
                           G: int, Sg: int, Kc: int, L: int, Rt: int,
                           rot: int, exact: bool, filtered: bool):
    """One (segment, code-tile) program of the fused IVF-PQ scan.

    Grid = (n_seg, n_tiles); the tile axis is the sequential minor axis,
    so the ``[seg, 2·128]`` output block is the running 2-deep bin buffer
    (same revisit pattern as ``_select_k_kernel``). Per step:

    1. the pipeline DMAs the owning list's next ``[Rt, Wb]`` block of
       PACKED u8 codes straight out of the full (possibly lane-folded)
       array via the scalar-prefetched ``seg_list`` index;
    2. bytes → code values with integer shifts/masks; the byte columns
       feeding each (fold-group, subspace) are picked by one exact f32
       selection matmul (Mosaic has no lane gather — a 0/1 matrix on the
       MXU is the TPU idiom for a static permutation);
    3. ADC accumulation Σ_s QLUT[s, code_s] in its MXU-factorized form:
       QLUT[s, k] = ⟨q_s, cb[s,k]⟩, so Σ_s QLUT[s, code_s] =
       ⟨q_rot, decoded⟩ with decoded built in VMEM by a grouped
       block-diagonal one-hot × codebook matmul (``[Rt, Kc·Sg] ×
       [Kc·Sg, Sg·P]``) — identical math to the reference's fused LUT
       gather (ivf_pq_compute_similarity-inl.cuh) with the per-code
       gather replaced by the one-hot contraction, and the decoded block
       never leaves VMEM (contrast: the XLA grouped path round-trips a
       decoded f32 chunk through HBM per segment chunk);
    4. metric epilogue against the streamed f32 norms + the in-kernel
       ⟨q, c⟩ term, then a 2-deep strided-bin running min with GLOBAL
       candidate ids (fold groups rotate lanes by 128/G so consecutive
       code rows land in distinct bins — see _segmented_scan_kernel's
       clustered-data note).

    ``filtered`` mode streams the list's PACKED per-candidate filter
    bytes (``sample_filter.list_filter_bytes``) alongside the codes —
    the same per-tile DMA pattern as the ids/norms rows — unpacks them
    in-kernel with the code-unpack shift/mask machinery
    (:func:`_lut_unpack_filter`), and masks filtered candidates to the
    +inf/-1 sentinel in the bin epilogue, exactly as invalid ids.
    """
    t = pl.program_id(1)
    if filtered:
        fbits_ref, fsel_ref, foff_ref, keys_ref, oids_ref = rest
    else:
        keys_ref, oids_ref = rest
        fbits_ref = fsel_ref = foff_ref = None
    seg = qv_ref.shape[1]
    K = 1 << pq_bits
    rotp = qv_ref.shape[2]

    @pl.when(t == 0)
    def _init():
        keys_ref[:] = jnp.full_like(keys_ref, jnp.inf)
        oids_ref[:] = jnp.full_like(oids_ref, -1)

    qv = qv_ref[0]                                   # [seg, rotp] f32
    ctr = ctr_ref[:]                                 # [1, rotp] f32
    qc = jnp.sum(qv * ctr, axis=1)                   # [seg] ⟨q, c⟩

    # bytes → code values: selection matmul (exact: values ≤ 255 in f32)
    # then integer shift/mask — the in-kernel unpack_bits
    bytes_f = codes_ref[0].astype(jnp.int32).astype(jnp.float32)
    code = _lut_unpack_codes(bytes_f, sel_lo_ref[:], sel_hi_ref[:],
                             off_ref[:], pq_bits, K)
    filt_row = None
    if filtered:
        fb_f = fbits_ref[:].astype(jnp.int32).astype(jnp.float32)
        filt_row = _lut_unpack_filter(fb_f, fsel_ref[:], foff_ref[:])

    cur_k = keys_ref[0]                              # [seg, 256]
    cur_i = oids_ref[0]
    state = (jax.lax.slice(cur_k, (0, 0), (seg, _LANES)),
             jax.lax.slice(cur_i, (0, 0), (seg, _LANES)),
             jax.lax.slice(cur_k, (0, _LANES), (seg, 2 * _LANES)),
             jax.lax.slice(cur_i, (0, _LANES), (seg, 2 * _LANES)))
    b1k, b1i, b2k, b2i = _lut_tile_update(
        code, qv, qc, ids_ref[:], norms_ref[:], cbp_ref, t, state,
        metric=metric, pq_bits=pq_bits, S=S, P=P, G=G, Sg=Sg, Kc=Kc,
        L=L, Rt=Rt, rot=rot, rotp=rotp, exact=exact, filt_row=filt_row)
    keys_ref[0] = jnp.concatenate([b1k, b2k], axis=1)
    oids_ref[0] = jnp.concatenate([b1i, b2i], axis=1)


@functools.partial(jax.jit, static_argnames=(
    "metric", "pq_bits", "pq_dim", "L", "lut_dtype", "interpret"))
def ivfpq_lut_scan_topk(seg_list: jax.Array, qv: jax.Array,
                        packed: jax.Array, ids: jax.Array,
                        norms: jax.Array, centers_rot: jax.Array,
                        codebooks: jax.Array, metric: str = "l2", *,
                        pq_bits: int, pq_dim: int, L: int,
                        lut_dtype: str = "float32",
                        filter_bytes=None,
                        interpret: bool = False
                        ) -> Tuple[jax.Array, jax.Array]:
    """Fused segmented IVF-PQ scan over PACKED codes (no recon cache).

    The oversampled DEEP-100M configs (n_probes 64–128, k_cand 400–1000)
    are hostile to the XLA grouped scan twice over: the decoded-f32 list
    chunks and the ``[n_seg, seg, k_cand]`` accumulators round-trip HBM
    (measured OOM at QB=2000 beside a 10.9 GB index), and the per-chunk
    one-hot decode re-materializes. This kernel streams the packed
    (optionally lane-folded) u8 codes per segment via scalar-prefetch
    DMA, unpacks ``pq_bits`` in-kernel, performs the ADC accumulation
    Σ_s QLUT[s, code_s] on-chip in its MXU-factorized form, and keeps a
    2-deep strided-bin top buffer per (segment, query) slot — nothing
    but the ``[n_seg, seg, 256]`` bin tables ever reaches HBM.

    seg_list [n_seg] i32 — owning list per segment (scalar-prefetched);
    qv [n_seg, seg, rot_dim] f32 — per-segment ROTATED queries;
    packed [n_lists, R, Wb] u8 — packed codes, native storage layout
    (``Wb = nb`` unfolded, ``Wb = 128`` lane-folded);
    ids / norms [n_lists, L] — global ids (-1 pad) and ‖c+d‖²;
    centers_rot [n_lists, rot_dim] f32; codebooks [S, K, P] f32
    (per_subspace only).

    ``lut_dtype`` is the reference's ``search_params::lut_dtype`` trade
    (ivf_pq_fp_8bit.cuh) mapped to TPU: it sets the dtype of the
    codebook operand and the one-hot/scan contraction ("float32" = exact
    f32 MXU passes, "bfloat16" = bf16 operands, "float8_e4m3" = fp8-
    quantized codebook values contracted in bf16). The XLA path
    quantizes the LUT entries ⟨q_s, cb[s,k]⟩ instead — same knob, same
    footprint trade, numerically a sibling rather than a twin.

    ``filter_bytes`` [n_lists, ceil(L/8)] u8 — optional per-candidate
    packed filter mask (``sample_filter.list_filter_bytes``): the words
    of the caller's ``filter_bitset`` re-packed to the per-list slot
    layout so the kernel streams them HBM→VMEM per code tile alongside
    the codes (1 bit/candidate — 32× less traffic than an f32 bias
    row), unpacks them with the code-unpack shift/mask machinery, and
    masks filtered candidates to the +inf/-1 sentinel at the bin
    epilogue. With a filter the emitted bins hold only KEPT candidates,
    so a selective filter no longer makes kept neighbors unreachable —
    the reason filtered searches used to be banned from this tier.

    Returns (keys [n_seg, seg, 256], ids [n_seg, seg, 256]): minimized
    sort keys per strided bin (l2: ‖c+d‖² − 2⟨q,c+d⟩, add ‖q‖²; ip:
    −⟨q,c+d⟩) and GLOBAL candidate ids (-1 invalid), two best per bin —
    merge like ``segmented_scan_topk``'s output.
    """
    n_seg, seg, rot = qv.shape
    S, K, P = codebooks.shape
    assert metric in ("l2", "ip")
    assert S == pq_dim and K == (1 << pq_bits)
    nb = (S * pq_bits + 7) // 8
    Wb = packed.shape[2]
    cfg = _lut_scan_config(S, K, P, nb, Wb, lut_dtype)
    if cfg is None:
        raise ValueError(
            f"unsupported packed-code layout for the LUT scan kernel: "
            f"nb={nb} Wb={Wb} (gate with pallas_lut_scan_wanted)")
    G, Sg, Kc = cfg
    exact = lut_dtype == "float32"

    R = packed.shape[1]
    Rt = 2 * _LANES if R >= 2 * _LANES else _LANES
    if R < Rt:  # tiny index (tests): pad to one full tile
        packed = _pad_to(packed, Rt, 1, 0)
        ids = _pad_to(ids, G * Rt, 1, -1)
        norms = _pad_to(norms, G * Rt, 1, 0.0)
    n_t = -(-packed.shape[1] // Rt)
    filtered = filter_bytes is not None
    Fbt = G * Rt // 8
    if filtered:
        # pad to WHOLE tiles (0 = filtered): ids/norms tolerate the
        # pipeline's OOB tail because garbage lanes are masked, but a
        # misread KEEP bit would admit a filtered candidate
        fbits = _pad_to(filter_bytes, n_t * Fbt, 1, 0)

    qvp = _pad_to(qv.astype(jnp.float32), _SUBLANES, 1, 0.0)
    qvp = _pad_to(qvp, _LANES, 2, 0.0)
    segp, rotp = qvp.shape[1], qvp.shape[2]
    ctr = _pad_to(centers_rot.astype(jnp.float32), _LANES, 1, 0.0)

    sel_lo, sel_hi, off_arr, cbp = _lut_scan_operands(
        codebooks, pq_bits, nb, Wb, G, Sg, lut_dtype)
    n_sg = S // Sg

    in_specs = [
        pl.BlockSpec((1, segp, rotp), lambda s, t, sl: (s, 0, 0)),
        pl.BlockSpec((1, Rt, Wb), lambda s, t, sl: (sl[s], t, 0)),
        pl.BlockSpec((1, G * Rt), lambda s, t, sl: (sl[s], t)),
        pl.BlockSpec((1, G * Rt), lambda s, t, sl: (sl[s], t)),
        pl.BlockSpec((1, rotp), lambda s, t, sl: (sl[s], 0)),
        pl.BlockSpec((Wb, G * S), lambda s, t, sl: (0, 0)),
        pl.BlockSpec((Wb, G * S), lambda s, t, sl: (0, 0)),
        pl.BlockSpec((1, G * S), lambda s, t, sl: (0, 0)),
        pl.BlockSpec((n_sg, K * Sg, Sg * P),
                     lambda s, t, sl: (0, 0, 0)),
    ]
    operands = [qvp, packed, ids, norms, ctr, sel_lo, sel_hi, off_arr,
                cbp]
    if filtered:
        fsel, foff = _filter_unpack_operands(G * Rt)
        in_specs += [
            pl.BlockSpec((1, Fbt), lambda s, t, sl: (sl[s], t)),
            pl.BlockSpec((Fbt, G * Rt), lambda s, t, sl: (0, 0)),
            pl.BlockSpec((1, G * Rt), lambda s, t, sl: (0, 0)),
        ]
        operands += [fbits, fsel, foff]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_seg, n_t),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, segp, LUT_SCAN_BINS),
                         lambda s, t, sl: (s, 0, 0)),
            pl.BlockSpec((1, segp, LUT_SCAN_BINS),
                         lambda s, t, sl: (s, 0, 0)),
        ],
    )
    keys, kids = pl.pallas_call(
        functools.partial(
            _ivfpq_lut_scan_kernel, metric=metric, pq_bits=pq_bits, S=S,
            P=P, G=G, Sg=Sg, Kc=Kc, L=L, Rt=Rt, rot=rot, exact=exact,
            filtered=filtered),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n_seg, segp, LUT_SCAN_BINS), jnp.float32),
            jax.ShapeDtypeStruct((n_seg, segp, LUT_SCAN_BINS), jnp.int32),
        ],
        interpret=interpret,
    )(seg_list.astype(jnp.int32), *operands)
    return keys[:, :seg], kids[:, :seg]


def pallas_lut_scan_wanted(S: int, K: int, P: int, nb: int, Wb: int,
                           L: int, rot: int, seg: int = 128,
                           lut_dtype: str = "float32",
                           filtered: bool = False) -> bool:
    """Dispatch for :func:`ivfpq_lut_scan_topk` — the ``scan_select=
    "pallas"`` tier. Needs a per_subspace packed layout the in-kernel
    unpack supports (byte width dividing the stored lane width, fold
    group ≤ 8) and a VMEM-sized working set (``filtered`` adds the
    filter-byte stream + its unpack selection matrix; the HBM side of
    a filtered dispatch is ``ivf_common.filtered_scan_mem_ok``'s job).
    Env override ``RAFT_TPU_PALLAS_LUTSCAN`` = always | never | auto
    (tri-state, see :func:`raft_tpu.obs.env_tristate`) — "on"/"always"
    runs interpreted off-TPU (tests)."""
    force = _env_tristate("RAFT_TPU_PALLAS_LUTSCAN")
    if force == "off":
        return False
    cfg = _lut_scan_config(S, K, P, nb, Wb, lut_dtype)
    if cfg is None:
        return False
    G, Sg, Kc = cfg
    op_bytes = 4 if lut_dtype == "float32" else 2
    rotp = -(-rot // _LANES) * _LANES
    Rt = 2 * _LANES
    vmem_f = _filter_vmem_bytes(G, Rt) if filtered else 0
    vmem = vmem_f + (
        2 * seg * rotp * 4            # qv block (+double buffer)
        + 2 * Rt * max(Wb, _LANES)    # u8 codes block
        + Rt * G * S * 8              # unpacked bytes + codes (f32+i32)
        + S * K * P * Sg * op_bytes   # grouped block-diag codebooks
        + _LANES * Kc * Sg * 8        # one-hot transient (+tiled codes)
        + _LANES * rotp * 4           # decoded block
        + seg * _LANES * 4            # qd block
        + 2 * seg * LUT_SCAN_BINS * 8  # running bin buffers (keys+ids)
        + 2 * Wb * G * S * 4          # selection matrices
    )
    if vmem > _GROUPED_VMEM_BUDGET:
        return False
    return True if force == "on" else _on_tpu()


def _extract_topk_block(comb_v: jax.Array, comb_i: jax.Array, k: int,
                        kpad: int) -> Tuple[jax.Array, jax.Array]:
    """k-round extraction merge of a combined candidate block: reduce
    ``comb_v``/``comb_i`` [rows, C] (minimized keys, +inf = empty slot)
    to the ascending top-k in a [rows, kpad] lane tile, ids resolved
    gather-free via the argmin one-hot (Mosaic has no general gather).
    The in-kernel merge shared by the fused gather-refine and the ring
    top-k exchange — k is static, so the loop unrolls to k VPU rounds."""
    rows = comb_v.shape[0]
    out_cols = jax.lax.broadcasted_iota(jnp.int32, (rows, kpad), 1)
    # sentinel init anchored on the candidate block rather than two bare
    # jnp.full broadcasts: XLA CPU's sharding propagation aborts on a
    # pair of broadcasted-constant stores in a discharged (interpret)
    # kernel that also issued a remote DMA — the predicate is constant-
    # false, so the values are identical
    out_v = jnp.where(out_cols < 0, comb_v[:, :kpad], jnp.inf)
    out_i = jnp.where(out_cols < 0, comb_i[:, :kpad], -1)
    imax = jnp.iinfo(jnp.int32).max
    for j in range(k):  # static unroll (see _select_k_kernel)
        mn = jnp.min(comb_v, axis=1)
        am = jnp.argmin(comb_v, axis=1)
        onehot = jax.lax.broadcasted_iota(
            jnp.int32, comb_v.shape, 1) == am[:, None]
        picked = jnp.min(jnp.where(onehot, comb_i, imax), axis=1)
        picked = jnp.where(jnp.isinf(mn), -1, picked)
        out_v = jnp.where(out_cols == j, mn[:, None], out_v)
        out_i = jnp.where(out_cols == j, picked[:, None], out_i)
        comb_v = jnp.where(onehot, jnp.inf, comb_v)
    return out_v, out_i


# ---------------------------------------------------------------------------
# fused gather-refine: per-query candidate rows streamed HBM→VMEM by id,
# exact distance epilogue + running top-k on-chip — the [m, C, d] gather
# buffer never exists
# ---------------------------------------------------------------------------

# Queries per program (one f32 sublane tile) and candidates gathered per
# sequential step (one lane tile).
GATHER_REFINE_BQ = 8
GATHER_REFINE_BC = 128
# Candidate-row DMAs kept in flight per program (row gathers are the
# bottleneck — ~512 B each — so the queue depth is what hides their
# issue latency behind the copy engine).
_GATHER_NBUF = 8
# In-kernel running-buffer width (one lane tile); the k-round merge
# extraction bounds serviceable k the same way _select_k_kernel does.
GATHER_REFINE_MAX_K = 64


def _gather_refine_kernel(q_ref, cand_ref, cand_hbm, data_hbm,
                          *rest, k: int, metric: str,
                          n_rows: int, filtered: bool):
    """One (query-tile, candidate-tile) program of the fused refine.

    Grid = (m_tiles, c_tiles); the candidate axis is the sequential
    minor axis, so the ``[bq, kpad]`` output block is the running top-k
    buffer (same revisit pattern as ``_select_k_kernel``). Per step:

    1. the tile's candidate ids are DMA'd HBM→SMEM (DMA row addresses
       must be scalar-readable — a VMEM operand cannot index an HBM
       ref);
    2. each candidate's dataset row streams HBM→VMEM through its own
       row DMA, ``_GATHER_NBUF`` in flight — the counterpart of
       ``refine_device.cuh``'s per-candidate global loads, and the step
       that replaces the XLA path's materialized ``[m, C, d]`` gather;
    3. exact distance epilogue on the VPU (all metrics minimized: ip
       keys are negated scores, cosine keys are 1 − cos; invalid ids
       masked to +inf) and a k-round merge of (running buffer ++ tile)
       by iterative extraction, ids resolved gather-free via the
       argmin one-hot.

    ``filtered`` mode rides the same row-DMA queue: each candidate's
    bitset WORD (its id is already scalar-readable in SMEM — the same
    address source the row DMA uses) streams HBM→VMEM through a
    parallel ``_GATHER_NBUF``-deep queue, and the metric epilogue
    poisons rows whose bit is clear to the +inf/-1 sentinel, exactly
    as invalid ids.
    """
    if filtered:
        (filt_hbm, vals_ref, ids_ref, ids_smem, rows_vmem, fw_vmem,
         sem_ids, sems, sems_f) = rest
    else:
        (vals_ref, ids_ref, ids_smem, rows_vmem, sem_ids, sems) = rest
        filt_hbm = fw_vmem = sems_f = None
    i = pl.program_id(0)
    jc = pl.program_id(1)
    bq, bc = cand_ref.shape
    total = bq * bc

    @pl.when(jc == 0)
    def _init():
        vals_ref[:] = jnp.full_like(vals_ref, jnp.inf)
        ids_ref[:] = jnp.full_like(ids_ref, -1)

    # 1. candidate ids HBM→SMEM (start/wait paired inline — GL08)
    cp = pltpu.make_async_copy(
        cand_hbm.at[pl.ds(i * bq, bq), pl.ds(jc * bc, bc)],
        ids_smem, sem_ids)
    cp.start()
    cp.wait()

    # 2. candidate rows HBM→VMEM, NBUF in flight. The wait recomputes
    # the identical copy descriptor (the documented double-buffer
    # idiom); a slot is always waited before its next start so two
    # copies never share a live semaphore — the graftlint GL08 lifetime
    # contract (the linter verifies the factory's starts all have
    # waits; the t/t+NBUF slot rotation below is the hand-managed part
    # it cannot prove, hence this invariant comment).
    def row_copy(t):
        qq = t // bc
        rr = jax.lax.rem(t, bc)
        row = jnp.clip(ids_smem[qq, rr], 0, n_rows - 1)
        return pltpu.make_async_copy(
            data_hbm.at[pl.ds(row, 1), :],
            rows_vmem.at[pl.ds(t, 1), :],
            sems.at[jax.lax.rem(t, _GATHER_NBUF)])

    def word_copy(t):
        # the candidate's bitset word, addressed off the same SMEM id
        # the row DMA reads (word index = row // 32 — int32-exact: the
        # kernel's ids are int32 by construction, core/ids policy)
        qq = t // bc
        rr = jax.lax.rem(t, bc)
        row = jnp.clip(ids_smem[qq, rr], 0, n_rows - 1)
        w = jnp.minimum(row // 32, filt_hbm.shape[0] - 1)
        return pltpu.make_async_copy(
            filt_hbm.at[pl.ds(w, 1), :],
            fw_vmem.at[pl.ds(qq, 1), pl.ds(rr, 1)],
            sems_f.at[jax.lax.rem(t, _GATHER_NBUF)])

    for t in range(_GATHER_NBUF):  # static warm-up fills the queue
        row_copy(t).start()
        if filtered:
            word_copy(t).start()

    def stream(t, carry):
        row_copy(t).wait()
        if filtered:
            word_copy(t).wait()

        @pl.when(t + _GATHER_NBUF < total)
        def _():
            row_copy(t + _GATHER_NBUF).start()
            if filtered:
                word_copy(t + _GATHER_NBUF).start()

        return carry

    jax.lax.fori_loop(0, total, stream, 0)

    # 3. exact epilogue + running top-k merge
    r3 = rows_vmem[:].astype(jnp.float32).reshape(bq, bc, -1)
    q = q_ref[:]                                       # [bq, dpad] f32
    s = jnp.sum(q[:, None, :] * r3, axis=-1)           # [bq, bc]
    if metric == "ip":
        key = -s
    else:
        rsq = jnp.sum(r3 * r3, axis=-1)                # [bq, bc]
        qsq = jnp.sum(q * q, axis=1)                   # [bq]
        if metric == "cos":
            # mirror _refine_rows' formula exactly (parity over speed:
            # rsqrt would drift ~1e-3 relative on near-duplicate rows)
            qn = jnp.sqrt(jnp.maximum(qsq, 1e-30))
            cn = jnp.sqrt(jnp.maximum(rsq, 1e-30))
            key = 1.0 - s / (qn[:, None] * cn)
        else:  # l2 (sqrt applied by the caller: selection order is equal)
            key = jnp.maximum(qsq[:, None] + rsq - 2.0 * s, 0.0)
    cand = cand_ref[:]                                 # [bq, bc] i32
    valid = cand >= 0
    if filtered:
        # poison masked rows in the metric epilogue: the candidate's
        # keep bit, tested against the word its DMA fetched — the same
        # ±inf/-1 sentinel path invalid ids take (GL13)
        bit = jax.lax.shift_right_logical(fw_vmem[:], cand & 31) & 1
        valid = jnp.logical_and(valid, bit > 0)
    key = jnp.where(valid, key, jnp.inf)
    gid = jnp.where(valid, cand, -1)

    kpad = vals_ref.shape[1]
    comb_v = jnp.concatenate([vals_ref[:], key], axis=1)
    comb_i = jnp.concatenate([ids_ref[:], gid], axis=1)
    out_v, out_i = _extract_topk_block(comb_v, comb_i, k, kpad)
    vals_ref[:] = out_v
    ids_ref[:] = out_i


@functools.partial(jax.jit, static_argnames=("k", "metric", "interpret"))
def gather_refine_topk(dataset: jax.Array, queries: jax.Array,
                       candidates: jax.Array, k: int, metric: str = "l2",
                       filter_bits=None,
                       interpret: bool = False
                       ) -> Tuple[jax.Array, jax.Array]:
    """Fused exact re-rank of per-query candidate ids — the streaming
    refine half of the oversampled IVF-PQ pipeline (reference: the
    device refine kernel, detail/refine_device.cuh).

    The XLA refine path (`refine.py:_refine_impl`) gathers candidates
    into a materialized ``[m, C, d]`` f32 HBM buffer before one batched
    einsum — at batch 10000 × k_cand 2000 × d 96 that is ~7.7 GB, the
    same accumulator-OOM shape the Pallas LUT scan eliminated on the
    scan side. This kernel instead streams each query tile's candidate
    ids HBM→SMEM and the corresponding ``dataset`` rows HBM→VMEM
    row-by-row (``_GATHER_NBUF`` copies in flight), computes the exact
    distance epilogue in VMEM and keeps a running top-k per query —
    nothing but the ``[m, kpad]`` result tables ever reaches HBM.

    ``dataset [n, d]`` — f32 rows or the bf16 reconstruction cache
    (dtype is preserved through the row DMAs; distances compute in
    f32); ``queries [m, d]``; ``candidates [m, C]`` i32 row ids, -1
    invalid (out-of-range ids are clamped for the DMA and masked only
    if negative, matching the XLA path's clip semantics). A dataset
    whose minor dim is not lane-aligned pays a PER-CALL padded
    ``[n, ceil(d/128)·128]`` HBM copy here (the row DMAs address
    lane-tiled rows) — dispatchers weigh it against the gather buffer
    via ``ivf_common.gather_refine_mem_ok``.

    ``filter_bits``: optional packed uint32 bitset over dataset rows
    (``core.bitset`` layout) — each candidate's word is fetched by the
    row-DMA queue and cleared bits are poisoned to +inf/-1 in the
    epilogue (the streamed filter half of the filtered oversampled
    pipeline).

    Returns (keys [m, k], ids [m, k]): minimized sort keys, sorted
    ascending (l2: squared distance — callers apply sqrt; ip: negated
    score; cos: cosine distance) and global candidate ids (-1 when a
    slot saw fewer than k valid candidates).
    """
    m, d = queries.shape
    n = dataset.shape[0]
    assert metric in ("l2", "ip", "cos")
    if k > GATHER_REFINE_MAX_K:
        raise ValueError(
            f"k={k} > {GATHER_REFINE_MAX_K} (the in-kernel merge is k "
            "extraction rounds per tile — gate with "
            "pallas_gather_refine_wanted)")
    filtered = filter_bits is not None
    bq, bc = GATHER_REFINE_BQ, GATHER_REFINE_BC
    kpad = _LANES
    qf = _pad_to(queries.astype(jnp.float32), bq, 0, 0.0)
    qf = _pad_to(qf, _LANES, 1, 0.0)
    data = _pad_to(dataset, _LANES, 1, 0.0)  # dtype preserved (f32/bf16)
    cand = _pad_to(candidates.astype(jnp.int32), bq, 0, -1)
    cand = _pad_to(cand, bc, 1, -1)
    mp, Cp = cand.shape
    dpad = data.shape[1]

    in_specs = [
        pl.BlockSpec((bq, dpad), lambda i, j: (i, 0)),
        # candidates ride twice: a VMEM block for the validity mask,
        # and the full array in HBM for the in-kernel id DMA (DMA
        # row addresses must come from scalar memory)
        pl.BlockSpec((bq, bc), lambda i, j: (i, j)),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    operands = [qf, cand, cand, data]
    scratch = [
        pltpu.SMEM((bq, bc), jnp.int32),
        pltpu.VMEM((bq * bc, dpad), data.dtype),
    ]
    if filtered:
        # [W, 1] i32 view of the packed words: per-candidate [1, 1]
        # word DMAs address rows of a 2-D array
        fw = jax.lax.bitcast_convert_type(
            filter_bits, jnp.int32).reshape(-1, 1)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        operands.append(fw)
        scratch.append(pltpu.VMEM((bq, bc), jnp.int32))
    scratch += [
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA((_GATHER_NBUF,)),
    ]
    if filtered:
        scratch.append(pltpu.SemaphoreType.DMA((_GATHER_NBUF,)))

    grid = (mp // bq, Cp // bc)
    vals, ids = pl.pallas_call(
        functools.partial(_gather_refine_kernel, k=k, metric=metric,
                          n_rows=n, filtered=filtered),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bq, kpad), lambda i, j: (i, 0)),
            pl.BlockSpec((bq, kpad), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mp, kpad), jnp.int32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    return vals[:m, :k], ids[:m, :k]


def pallas_gather_refine_wanted(m: int, C: int, d: int, k: int,
                                itemsize: int = 4,
                                filtered: bool = False) -> bool:
    """Dispatch for :func:`gather_refine_topk` — the fused refine tier.

    Needs k within the merge budget and a VMEM-sized gathered-row
    block; auto mode engages on TPU for the oversampled shapes whose
    ``[m, C, d]`` gather buffer is HBM-hostile (k_cand ≥ 400, the
    DEEP-100M refinement_rate regime, or a gather buffer past 1 GB) —
    the XLA einsum path keeps small candidate sets. Env override
    ``RAFT_TPU_PALLAS_REFINE`` = always | never | auto (tri-state, see
    :func:`raft_tpu.obs.env_tristate`) — "on"/"always" runs interpreted
    off-TPU (tests)."""
    force = _env_tristate("RAFT_TPU_PALLAS_REFINE")
    if force == "off" or k > GATHER_REFINE_MAX_K or C < 2 * _LANES:
        return False
    dpad = -(-d // _LANES) * _LANES
    bq, bc = GATHER_REFINE_BQ, GATHER_REFINE_BC
    vmem = (bq * bc * dpad * itemsize     # gathered rows scratch
            + 2 * bq * dpad * 4           # query block (+double buffer)
            + 2 * bq * bc * 4             # candidate id block
            + bq * bc * dpad * 4          # f32 row/broadcast transients
            + 4 * bq * _LANES * 8         # running buffers + extraction
            + (bq * bc * 4 if filtered else 0))  # per-candidate words
    if vmem > _GROUPED_VMEM_BUDGET:
        return False
    if force == "on":
        return True
    return _on_tpu() and (C >= 400 or m * C * d * itemsize >= (1 << 30))


@functools.partial(jax.jit,
                   static_argnames=("k", "select_min", "bm", "bl", "interpret"))
def select_k_pallas(scores: jax.Array, k: int, select_min: bool = True,
                    bm: int = 64, bl: int = 2048,
                    interpret: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Batched top-k over rows of ``scores`` [m, len] — Pallas counterpart
    of ``matrix::select_k`` (matrix/select_k.cuh:81).  Returns sorted
    (values [m, k], indices [m, k])."""
    m, n = scores.shape
    if k > n:
        raise ValueError(f"k={k} > len={n}")
    kpad = max(_LANES, ((k + _LANES - 1) // _LANES) * _LANES)
    s = _pad_to(scores.astype(jnp.float32), bm, 0, 0.0)
    s = _pad_to(s, bl, 1, jnp.inf if select_min else -jnp.inf)
    mp, npad = s.shape
    nvalid = jnp.full((1,), n, jnp.int32)

    grid = (mp // bm, npad // bl)
    vals, idx = pl.pallas_call(
        functools.partial(_select_k_kernel, k=k, select_min=select_min),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bl), lambda i, j: (i, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, kpad), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mp, kpad), jnp.int32),
        ],
        interpret=interpret,
    )(s, nvalid)
    return vals[:m, :k], idx[:m, :k]


# ---------------------------------------------------------------------------
# ring top-k exchange: reduce-scatter-of-top-k across a mesh axis — each
# device streams only its surviving [mc, k] block to its ring neighbor via
# async remote DMA; the [n_dev, m, k] allgather buffer never exists
# ---------------------------------------------------------------------------

# In-kernel merge budget (k extraction rounds per hop — the same bound the
# gather-refine merge carries).
RING_TOPK_MAX_K = 64
# VMEM working set: recv slots (double-buffered) + running/local blocks
# for vals+ids, all [mc, 128] lane tiles.
_RING_VMEM_BUDGET = 12 * 1024 * 1024


def ring_chunk_rows(m: int, n_dev: int) -> int:
    """Query rows per ring chunk: ceil(m / n_dev) padded to a sublane
    tile. Shared by the kernel, the ppermute fallback, and the comms
    byte accounting so all three agree on the per-hop block shape."""
    mc = -(-m // n_dev)
    return max(_SUBLANES, -(-mc // _SUBLANES) * _SUBLANES)


def ring_topk_kernel_ok(m: int, k: int, n_dev: int) -> bool:
    """Kernel-tier eligibility: merge budget (k extraction rounds per
    hop) and the VMEM working set of the double-buffered exchange.
    Multi-axis meshes are the caller's problem — the kernel addresses
    ring neighbors by LOGICAL device id, so the exchange axis must be
    the whole mesh (the ppermute fallback serves sub-axis rings)."""
    if k > RING_TOPK_MAX_K or n_dev < 2:
        return False
    mc = ring_chunk_rows(m, n_dev)
    vmem = (2 * mc * _LANES * 8      # recv slots (vals+ids, double buffer)
            + 2 * mc * _LANES * 8    # running + local staging blocks
            + 2 * mc * 3 * _LANES * 8)  # extraction transients
    return vmem <= _RING_VMEM_BUDGET


def ring_topk_inner_ok(m: int, k: int, n_inner: int) -> bool:
    """Eligibility of the ring kernel as the hier tier's per-pod
    (inner-axis) stage. Same merge/VMEM budget as
    :func:`ring_topk_kernel_ok`, but the exchange axis is a SUB-axis:
    the kernel's neighbor addressing is by logical device id, so the
    per-pod ring passes ``outer_axis`` to :func:`ring_topk_merge` and
    offsets neighbors by the pod base — which is only the right flat id
    when the inner axis is the MINOR (trailing) mesh axis, the layout
    :func:`raft_tpu.parallel.mesh.hier_mesh` guarantees (logical id =
    dcn_idx·n_inner + ici_idx). Callers on other layouts must use the
    ppermute fallback."""
    return ring_topk_kernel_ok(m, k, n_inner)


def ring_topk_splits(mc: int, schedule: str) -> Tuple[Tuple[int, int], ...]:
    """Row sub-blocks of one [mc, kpad] hop block, as (offset, rows)
    pairs. The ``serial`` schedule is one block — the PR-8 bulk-
    synchronous ring. The ``overlap`` schedule splits the block into two
    sublane-aligned halves so each half's hop-(s+1) transfer can start
    as soon as ITS merge lands, while the other half of hop s is still
    being merged — the compute/comms overlap. Chunks too short to split
    (mc < 16) degenerate to one block either way; the byte model is
    untouched (same rows cross the link per hop, in 2 DMAs instead
    of 1)."""
    if schedule == "serial" or mc < 2 * _SUBLANES:
        return ((0, mc),)
    mh = (mc // 2 // _SUBLANES) * _SUBLANES
    return ((0, mh), (mh, mc - mh))


def _ring_topk_kernel(vals_hbm, ids_hbm, out_v_ref, out_i_ref,
                      buf_v, buf_i, run_v, run_i, loc_v, loc_i,
                      send_sems, recv_sems, cap_sems, copy_sems, *,
                      k: int, n_dev: int, mc: int, axis_name: str,
                      flow_control: bool, splits,
                      outer_axis: Optional[str] = None):
    """One device's program of the ring reduce-scatter-of-top-k.

    The local [n_dev·mc, kpad] candidate table lives in HBM; chunk ``c``
    (rows [c·mc, (c+1)·mc)) is query chunk ``c``'s local top-k. Chunk
    ``c``'s partial starts at device ``(c+1) mod n_dev`` and travels the
    ring for ``n_dev−1`` hops, merged against each host device's local
    chunk on the way, landing fully merged at its owner ``c``.

    The hop block is cut into ``splits`` row sub-blocks (see
    :func:`ring_topk_splits`) and the schedule is software-pipelined
    across the hop boundary, per sub-block ``h``:

    1. hop s's transfers for ``h`` were started at the END of hop s−1
       (prologue for hop 0), so they are in flight while hop s−1's
       later sub-blocks are still being merged — with two halves, hop
       s's exchange rides under hop s−1's on-chip merge work and vice
       versa. The owning chunk's local HBM→VMEM copies start in the
       same breath and hide under the same transfer.
    2. recv slots are double-buffered (slot = s mod 2) per sub-block,
       so the LEFT neighbor — which may run a hop ahead — can land hop
       s+1's half in slot (s+1)%2 while this device still merges slot
       s%2;
    3. waits gate only slot reuse: the send wait (running sub-block
       about to be overwritten by ITS merge), the recv wait (this
       half's incoming partial landed — SPMD symmetry), and the local
       copy wait. Then the k-round extraction merge
       (``_extract_topk_block``) reduces incoming ++ local to the
       surviving top-k, and the NEXT hop's send/recv pair for this
       half starts immediately — before the next half's merge runs.

    ``flow_control``: on real hardware a capacity semaphore per
    (slot, half) guards slot reuse (the right neighbor confirms it
    consumed (s, h) before the step-s+2 send restarts that slot) and a
    neighbor barrier aligns kernel entry; interpret mode executes
    remote copies synchronously and implements neither remote signal,
    so both are compiled out there.
    """
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, n_dev)
    left = jax.lax.rem(my + n_dev - 1, n_dev)
    if outer_axis is not None:
        # per-pod ring on a (outer, inner) mesh: neighbor semaphores and
        # DMAs address LOGICAL (flat) device ids, and axis_index(inner)
        # is only pod-relative — offset by this pod's base so the ring
        # stays inside the pod (requires inner = minor mesh axis, see
        # ring_topk_inner_ok)
        base = jax.lax.axis_index(outer_axis) * n_dev
        right = base + right
        left = base + left
    H = len(splits)

    if flow_control:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    def chunk_copy(hbm, dst, c, h, which):
        off, rows = splits[h]
        return pltpu.make_async_copy(
            hbm.at[pl.ds(c * mc + off, rows)],
            dst.at[pl.ds(off, rows)], copy_sems.at[h, which])

    def ring_send(slot, h, which):
        off, rows = splits[h]
        src = run_v if which == 0 else run_i
        dst = buf_v if which == 0 else buf_i
        return pltpu.make_async_remote_copy(
            src_ref=src.at[pl.ds(off, rows)],
            dst_ref=dst.at[slot, pl.ds(off, rows)],
            send_sem=send_sems.at[slot, h, which],
            recv_sem=recv_sems.at[slot, h, which],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    def hop_chunk(s):
        # the partial arriving at hop s is chunk (my − s − 2)'s
        return jax.lax.rem(my + 2 * n_dev - s - 2, n_dev)

    # init: this device starts chunk (my−1)'s journey with its local block
    c0 = jax.lax.rem(my + n_dev - 1, n_dev)
    for h in range(H):
        chunk_copy(vals_hbm, run_v, c0, h, 0).start()
        chunk_copy(ids_hbm, run_i, c0, h, 1).start()
    for h in range(H):
        chunk_copy(vals_hbm, run_v, c0, h, 0).wait()
        chunk_copy(ids_hbm, run_i, c0, h, 1).wait()

    # prologue: hop 0's sends + local-chunk copies, all sub-blocks
    for h in range(H):
        ring_send(0, h, 0).start()
        ring_send(0, h, 1).start()
        chunk_copy(vals_hbm, loc_v, hop_chunk(0), h, 0).start()
        chunk_copy(ids_hbm, loc_i, hop_chunk(0), h, 1).start()

    for s in range(n_dev - 1):  # static unroll: n_dev−1 hops
        slot = s % 2
        nxt = (s + 1) % 2
        c = hop_chunk(s)
        for h in range(H):
            off, rows = splits[h]
            # waits gate slot reuse only: the send (its merge overwrites
            # run), the recv (this half's partial landed), the local copy
            ring_send(slot, h, 0).wait()
            ring_send(slot, h, 1).wait()
            chunk_copy(vals_hbm, loc_v, c, h, 0).wait()
            chunk_copy(ids_hbm, loc_i, c, h, 1).wait()
            comb_v = jnp.concatenate(
                [buf_v[slot, pl.ds(off, rows)], loc_v[pl.ds(off, rows)]],
                axis=1)
            comb_i = jnp.concatenate(
                [buf_i[slot, pl.ds(off, rows)], loc_i[pl.ds(off, rows)]],
                axis=1)
            mv, mi = _extract_topk_block(comb_v, comb_i, k,
                                         run_v.shape[1])
            run_v[pl.ds(off, rows)] = mv
            run_i[pl.ds(off, rows)] = mi
            if flow_control and s + 2 <= n_dev - 2:
                # this half's recv slot is consumed: free it for the
                # left neighbor's step-s+2 send
                pltpu.semaphore_signal(
                    cap_sems.at[slot, h], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            if s + 1 <= n_dev - 2:
                # start the NEXT hop's pair for this half NOW — before
                # the next half's merge — so hop s+1's transfer rides
                # under the remaining hop-s merge work
                if flow_control and s + 1 >= 2:
                    # right neighbor consumed (s−1, h) → slot reusable
                    pltpu.semaphore_wait(cap_sems.at[nxt, h], 1)
                ring_send(nxt, h, 0).start()
                ring_send(nxt, h, 1).start()
                chunk_copy(vals_hbm, loc_v, hop_chunk(s + 1), h,
                           0).start()
                chunk_copy(ids_hbm, loc_i, hop_chunk(s + 1), h,
                           1).start()
    out_v_ref[:] = run_v[:]
    out_i_ref[:] = run_i[:]


def ring_schedule(schedule: str = "auto") -> str:
    """Resolve the ring kernel's hop schedule: ``overlap`` (default —
    half-pipelined, hop i's merge runs under hop i+1's in-flight remote
    copy) or ``serial`` (the PR-8 bulk-synchronous schedule, kept for
    the bench comparison column). ``RAFT_TPU_RING_OVERLAP`` = auto | on
    | off (tri-state, :func:`raft_tpu.obs.env_tristate`) decides
    ``auto``; an explicit argument wins."""
    if schedule in ("overlap", "serial"):
        return schedule
    force = _env_tristate("RAFT_TPU_RING_OVERLAP")
    return "serial" if force == "off" else "overlap"


def ring_topk_merge(vals: jax.Array, ids: jax.Array, k: int,
                    axis_name: str, n_dev: int, select_min: bool = True,
                    interpret: bool = False, schedule: str = "auto",
                    outer_axis: Optional[str] = None
                    ) -> Tuple[jax.Array, jax.Array]:
    """Ring reduce-scatter-of-top-k over a mesh axis — the Pallas merge
    tier replacing allgather-and-select (reference: knn_merge_parts.cuh
    merged over NCCL in raft-dask; here the merge IS the transport).

    Must be called inside ``shard_map`` over ``axis_name`` (a 1-D mesh:
    neighbors are addressed by logical device id — see
    :func:`ring_topk_kernel_ok`). On a 2-D (outer, inner) hier mesh pass
    ``outer_axis`` so the per-pod ring offsets its neighbor ids by the
    pod base (inner must be the minor mesh axis —
    :func:`ring_topk_inner_ok`). ``vals``/``ids`` [m, k'] (k' ≥ k) are
    this device's local top-k table, ids -1 invalid, invalid keys at the
    select sentinel (+inf for ``select_min``, −inf otherwise). Returns
    this device's owned query chunk ([mc, k] — rows
    [rank·mc, (rank+1)·mc) of the padded query axis): callers emit
    ``P(axis)`` out_specs and slice the assembled [n_dev·mc, k] back to
    [m, k]. The allgather buffer is gone: per hop only the surviving
    [mc, k] block crosses the interconnect, counted per hop as
    ``comms.ops/bytes{op=ring_topk}`` by the dispatching merge tier.

    ``schedule`` = auto | overlap | serial (:func:`ring_schedule`):
    both are exact-parity, the overlap schedule pipelines each hop's
    merge under the next hop's in-flight exchange.
    """
    m, kin = vals.shape
    if k > kin:
        raise ValueError(f"k={k} > candidate width {kin}")
    if k > RING_TOPK_MAX_K:
        raise ValueError(
            f"k={k} > {RING_TOPK_MAX_K} (the in-kernel merge is k "
            "extraction rounds per hop — gate with ring_topk_kernel_ok)")
    mc = ring_chunk_rows(m, n_dev)
    splits = ring_topk_splits(mc, ring_schedule(schedule))
    H = len(splits)
    m_pad = mc * n_dev
    kpad = _LANES
    keys = vals.astype(jnp.float32)
    if not select_min:
        keys = -keys  # uniform ascending selection; −inf pads → +inf
    keys = _pad_to(keys, m_pad, 0, jnp.inf) if m_pad > m else keys
    keys = _pad_to(keys, kpad, 1, jnp.inf)
    idp = ids.astype(jnp.int32)
    idp = _pad_to(idp, m_pad, 0, -1) if m_pad > m else idp
    idp = _pad_to(idp, kpad, 1, -1)
    # invalid slots must carry the internal sentinel regardless of the
    # caller's pad value convention
    keys = jnp.where(idp < 0, jnp.inf, keys)

    kwargs = {}
    if not interpret:
        # the neighbor barrier needs a collective id (real hardware only)
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            collective_id=1)
    out_v, out_i = pl.pallas_call(
        functools.partial(_ring_topk_kernel, k=k, n_dev=n_dev, mc=mc,
                          axis_name=axis_name,
                          flow_control=not interpret, splits=splits,
                          outer_axis=outer_axis),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((mc, kpad), lambda: (0, 0)),
            pl.BlockSpec((mc, kpad), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mc, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mc, kpad), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, mc, kpad), jnp.float32),   # recv slots (vals)
            pltpu.VMEM((2, mc, kpad), jnp.int32),     # recv slots (ids)
            pltpu.VMEM((mc, kpad), jnp.float32),      # running block
            pltpu.VMEM((mc, kpad), jnp.int32),
            pltpu.VMEM((mc, kpad), jnp.float32),      # local chunk staging
            pltpu.VMEM((mc, kpad), jnp.int32),
            pltpu.SemaphoreType.DMA((2, H, 2)),       # send: slot×half×array
            pltpu.SemaphoreType.DMA((2, H, 2)),       # recv
            pltpu.SemaphoreType.REGULAR((2, H)),      # slot capacity
            pltpu.SemaphoreType.DMA((H, 2)),          # local chunk copies
        ],
        interpret=interpret,
        **kwargs,
    )(keys, idp)
    res_v = out_v[:, :k]
    res_i = out_i[:, :k]
    if not select_min:
        res_v = jnp.where(jnp.isinf(res_v), -jnp.inf, -res_v)
    return res_v, res_i


# ---------------------------------------------------------------------------
# fused scan-in-ring: the per-shard LUT scan folded INTO the ring schedule —
# chunk c_s's list scan hides under hop s's in-flight exchange, and the
# per-shard [m, k] candidate table handed from the scan stage to the merge
# never materializes in HBM
# ---------------------------------------------------------------------------

# Additive key bias marking un-probed (query, list) pairs in the fused
# scan-in-ring kernel. A finite sentinel rather than +inf: the bias rides
# through an f32 selection matmul (inf·0 = NaN) and real ADC keys are
# bounded by the data scale (≪ 1e29), so biased keys are thresholded back
# to the +inf/-1 empty-slot convention at segment extraction.
_LUT_MASK_BIG = 1e30
# Union-probe segments per ring chunk the fused kernel will serve: the
# scan loop is NS·n_t tiles per chunk, and the [n_dev, NS] list table
# must fit SMEM.
RING_FUSED_MAX_SEGS = 512


def _ring_lut_scan_kernel(cl_smem, ind_hbm, qv_hbm, codes_hbm, ids_hbm,
                          norms_hbm, ctr_hbm, *rest,
                          k: int, n_dev: int, mc: int, NS: int, n_t: int,
                          metric: str, pq_bits: int, S: int, P: int,
                          G: int, Sg: int, Kc: int, L: int, Rt: int,
                          rot: int, rotp: int, indl: int,
                          axis_name: str, flow_control: bool,
                          filtered: bool):
    """One device's program of the fused scan-in-ring search.

    The ring schedule is the serialized PR-8 exchange; what fills the
    dead time is the SCAN. Per ring step the device must merge the
    incoming partial against its local top-k of query chunk ``c`` — and
    in this kernel that local top-k does not pre-exist in HBM: it is
    computed ON THE SPOT, between the send start and the recv wait, by
    streaming the chunk's union probe lists' packed codes through the
    shared LUT-scan tile body (:func:`_lut_tile_update`). The chunk's
    candidates live only in the ``cand_*`` VMEM blocks; the per-shard
    ``[m, k]`` table the unfused pipeline hands from ``search`` to
    ``merge_topk`` never exists.

    Chunk scan: per segment p (one union list, −1 pads clamped and
    masked), the list's code tiles stream HBM→VMEM double-buffered
    (slots alternate per tile, each waited before reuse — GL08);
    per-query probe membership rides an additive ``_LUT_MASK_BIG`` key
    bias (the [1, mc] indicator row is transposed to a [mc, 1] column
    by an exact iota-eye matmul — Mosaic has no sublane gather), so a
    chunk query that did not probe the list contributes nothing after
    the segment extraction thresholds biased keys back to +inf/-1.
    Per-segment 2-deep strided bins (reset at tile 0, extracted at the
    last tile) keep candidate semantics identical to the standalone
    ``ivfpq_lut_scan_topk`` tier: per (query, probed list), the two
    best per strided bin, then a running k-merge across lists.

    Ring: identical slot/semaphore discipline to
    :func:`_ring_topk_kernel`'s serial schedule (double-buffered recv
    slots, capacity semaphores + entry barrier compiled out in
    interpret mode) — the overlap here comes from the scan, not from
    half-splitting.

    ``filtered`` streams each list's packed per-candidate filter bytes
    through the tile-copy queue (a 4th double-buffered slot beside
    codes/ids/norms), unpacked per tile with the code-unpack shift/mask
    machinery and folded into the shared tile body's sentinel epilogue
    — the per-shard bitset slice composed with the global→local remap
    happens host-side (``parallel.ivf``)."""
    if filtered:
        (fbytes_hbm, sel_lo_ref, sel_hi_ref, off_ref, cbp_ref,
         fsel_ref, foff_ref, out_v_ref, out_i_ref,
         qv_vmem, ctr_vmem, ind_vmem, code_sl, idrow_sl, nrow_sl,
         fb_sl, qc_col, bias_col, b1k, b1i, b2k, b2i, cand_v, cand_i,
         run_v, run_i, buf_v, buf_i, qv_sem, seg_sems, tile_sems,
         send_sems, recv_sems, cap_sems) = rest
    else:
        (sel_lo_ref, sel_hi_ref, off_ref, cbp_ref, out_v_ref, out_i_ref,
         qv_vmem, ctr_vmem, ind_vmem, code_sl, idrow_sl, nrow_sl,
         qc_col, bias_col, b1k, b1i, b2k, b2i, cand_v, cand_i,
         run_v, run_i, buf_v, buf_i, qv_sem, seg_sems, tile_sems,
         send_sems, recv_sems, cap_sems) = rest
        fbytes_hbm = fsel_ref = foff_ref = fb_sl = None
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, n_dev)
    left = jax.lax.rem(my + n_dev - 1, n_dev)
    K = 1 << pq_bits
    kpad = run_v.shape[1]
    T = NS * n_t

    if flow_control:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    # sentinel inits anchored on a real operand (see _extract_topk_block:
    # bare paired broadcasted-constant stores abort XLA CPU's sharding
    # propagation in a discharged kernel that also issued a remote DMA)
    def fill_bins(anchor_f, anchor_i):
        cols = jax.lax.broadcasted_iota(jnp.int32, (mc, _LANES), 1)
        b1k[:] = jnp.where(cols < 0, anchor_f[:, :_LANES], jnp.inf)
        b2k[:] = jnp.where(cols < 0, anchor_f[:, :_LANES], jnp.inf)
        b1i[:] = jnp.where(cols < 0, anchor_i[:, :_LANES], -1)
        b2i[:] = jnp.where(cols < 0, anchor_i[:, :_LANES], -1)

    Fbt = G * Rt // 8

    def tile_copies(c, t, sl):
        p = t // n_t
        tt = jax.lax.rem(t, n_t)
        lst = jnp.maximum(cl_smem[c, p], 0)
        copies = (
            pltpu.make_async_copy(
                codes_hbm.at[pl.ds(lst, 1), pl.ds(tt * Rt, Rt), :],
                code_sl.at[pl.ds(sl, 1)], tile_sems.at[sl, 0]),
            pltpu.make_async_copy(
                ids_hbm.at[pl.ds(lst, 1), pl.ds(tt * G * Rt, G * Rt)],
                idrow_sl.at[pl.ds(sl, 1)], tile_sems.at[sl, 1]),
            pltpu.make_async_copy(
                norms_hbm.at[pl.ds(lst, 1), pl.ds(tt * G * Rt, G * Rt)],
                nrow_sl.at[pl.ds(sl, 1)], tile_sems.at[sl, 2]),
        )
        if filtered:
            copies += (pltpu.make_async_copy(
                fbytes_hbm.at[pl.ds(lst, 1), pl.ds(tt * Fbt, Fbt)],
                fb_sl.at[pl.ds(sl, 1)], tile_sems.at[sl, 3]),)
        return copies

    def scan_chunk(c):
        """Stream chunk ``c``'s union probe lists; leaves the chunk's
        local top-k in ``cand_v``/``cand_i``."""
        cp = pltpu.make_async_copy(qv_hbm.at[pl.ds(c, 1)], qv_vmem,
                                   qv_sem)
        cp.start()
        cp.wait()
        qv = qv_vmem[0]                              # [mc, rotp]
        cols_k = jax.lax.broadcasted_iota(jnp.int32, (mc, kpad), 1)
        cand_v[:] = jnp.where(cols_k < 0, qv[:, :kpad], jnp.inf)
        cand_i[:] = jnp.where(cols_k < 0, cols_k, -1)
        for cc in tile_copies(c, 0, 0):
            cc.start()

        def step(t, sl):
            p = t // n_t
            tt = jax.lax.rem(t, n_t)

            @pl.when(tt == 0)
            def _seg_head():
                # per-segment operands: the list's rotated center row +
                # the chunk's probe-indicator row (blocking: once per
                # NS·n_t tiles), and fresh bins
                lst = jnp.maximum(cl_smem[c, p], 0)
                s1 = pltpu.make_async_copy(
                    ctr_hbm.at[pl.ds(lst, 1), :], ctr_vmem,
                    seg_sems.at[0])
                s2 = pltpu.make_async_copy(
                    ind_hbm.at[pl.ds(c, 1), pl.ds(p, 1), :], ind_vmem,
                    seg_sems.at[1])
                s1.start()
                s2.start()
                s1.wait()
                s2.wait()
                # per-segment scalars, staged once for the segment's
                # n_t tiles: ⟨q, c⟩ against the just-landed center row,
                # and the probe-indicator lane row → sublane column via
                # an exact iota-eye matmul (Mosaic has no sublane
                # gather) → the additive _LUT_MASK_BIG key bias
                ctr = ctr_vmem[:]                    # [1, rotp]
                qc_col[:] = jnp.broadcast_to(
                    jnp.sum(qv * ctr, axis=1)[:, None], (mc, _LANES))
                ind = ind_vmem[0]                    # [1, indl]
                eye = (jax.lax.broadcasted_iota(jnp.int32, (mc, indl), 0)
                       == jax.lax.broadcasted_iota(
                           jnp.int32, (mc, indl), 1)).astype(jnp.float32)
                mcol = jax.lax.dot_general(
                    eye, ind, (((1,), (1,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)  # [mc, 1]
                bias_col[:] = jnp.broadcast_to(
                    (1.0 - mcol) * _LUT_MASK_BIG, (mc, _LANES))
                fill_bins(qv, cols_k)

            for cc in tile_copies(c, t, sl):
                cc.wait()

            @pl.when(t + 1 < T)
            def _prefetch():  # next tile rides under this tile's compute
                for cc in tile_copies(c, t + 1, 1 - sl):
                    cc.start()

            bytes_f = code_sl[sl].astype(jnp.int32).astype(jnp.float32)
            code = _lut_unpack_codes(bytes_f, sel_lo_ref[:],
                                     sel_hi_ref[:], off_ref[:],
                                     pq_bits, K)
            filt_row = None
            if filtered:
                fb_f = fb_sl[pl.ds(sl, 1)].astype(jnp.int32).astype(
                    jnp.float32)
                filt_row = _lut_unpack_filter(fb_f, fsel_ref[:],
                                              foff_ref[:])
            # per-segment scalars staged by _seg_head (computed once
            # per NS·n_t tiles, not per tile)
            qc = qc_col[:, 0]                        # [mc] ⟨q, c⟩
            bias = bias_col[:, :1]                   # [mc, 1]
            state = (b1k[:], b1i[:], b2k[:], b2i[:])
            nb1k, nb1i, nb2k, nb2i = _lut_tile_update(
                code, qv, qc, idrow_sl[pl.ds(sl, 1)],
                nrow_sl[pl.ds(sl, 1)], cbp_ref, tt, state,
                metric=metric, pq_bits=pq_bits, S=S, P=P, G=G, Sg=Sg,
                Kc=Kc, L=L, Rt=Rt, rot=rot, rotp=rotp,
                exact=cbp_ref.dtype == jnp.float32, key_bias=bias,
                filt_row=filt_row)
            b1k[:] = nb1k
            b1i[:] = nb1i
            b2k[:] = nb2k
            b2i[:] = nb2i

            @pl.when(tt == n_t - 1)
            def _seg_tail():
                # extraction merge: this segment's bins ++ the chunk's
                # running candidates; biased (un-probed) keys threshold
                # back to the +inf/-1 empty-slot convention first
                bins_k = jnp.concatenate([b1k[:], b2k[:]], axis=1)
                bins_i = jnp.concatenate([b1i[:], b2i[:]], axis=1)
                drop = bins_k >= _LUT_MASK_BIG * 0.5
                bins_k2 = jnp.where(drop, jnp.inf, bins_k)
                bins_i2 = jnp.where(drop, -1, bins_i)
                comb_v = jnp.concatenate([cand_v[:], bins_k2], axis=1)
                comb_i = jnp.concatenate([cand_i[:], bins_i2], axis=1)
                mv, mi = _extract_topk_block(comb_v, comb_i, k, kpad)
                cand_v[:] = mv
                cand_i[:] = mi

        def pair_body(j, carry):
            # two tiles per iteration so the double-buffer slots stay
            # STATIC (dynamic leading-index VMEM reads are off the
            # Mosaic fast path); tile indices stay traced
            t0 = 2 * j
            step(t0, 0)

            @pl.when(t0 + 1 < T)
            def _odd():
                step(t0 + 1, 1)

            return carry

        jax.lax.fori_loop(0, (T + 1) // 2, pair_body, 0)

    def ring_send(slot, which):
        src = run_v if which == 0 else run_i
        dst = buf_v if which == 0 else buf_i
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=dst.at[slot],
            send_sem=send_sems.at[slot, which],
            recv_sem=recv_sems.at[slot, which],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    # init: chunk (my−1)'s journey starts with its freshly scanned top-k
    c0 = jax.lax.rem(my + n_dev - 1, n_dev)
    scan_chunk(c0)
    run_v[:] = cand_v[:]
    run_i[:] = cand_i[:]
    for s in range(n_dev - 1):  # static unroll: n_dev−1 hops
        slot = s % 2
        if flow_control and s >= 2:
            pltpu.semaphore_wait(cap_sems.at[slot], 1)
        ring_send(slot, 0).start()
        ring_send(slot, 1).start()
        # the hop's merge partner is chunk (my − s − 2)'s local top-k:
        # SCAN it now, under the in-flight exchange — this is the
        # compute the serialized pipeline ran before the ring started
        c = jax.lax.rem(my + 2 * n_dev - s - 2, n_dev)
        scan_chunk(c)
        ring_send(slot, 0).wait()
        ring_send(slot, 1).wait()
        comb_v = jnp.concatenate([buf_v[slot], cand_v[:]], axis=1)
        comb_i = jnp.concatenate([buf_i[slot], cand_i[:]], axis=1)
        mv, mi = _extract_topk_block(comb_v, comb_i, k, kpad)
        run_v[:] = mv
        run_i[:] = mi
        if flow_control and s + 2 <= n_dev - 2:
            pltpu.semaphore_signal(cap_sems.at[slot], inc=1,
                                   device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
    out_v_ref[:] = run_v[:]
    out_i_ref[:] = run_i[:]


def ring_lut_scan_kernel_ok(S: int, K: int, P: int, nb: int, Wb: int, mc: int,
                     NS: int, k: int, n_dev: int, rot: int,
                     lut_dtype: str = "float32",
                     filtered: bool = False) -> bool:
    """Admission for :func:`ring_lut_scan_merge`: the packed layout must
    be one the in-kernel unpack supports, the merge budget holds (k
    extraction rounds per segment and per hop), the union-segment table
    fits the scan loop, and the VMEM working set — chunk queries + code
    slots + codebook operand + bins + ring blocks (+ the filter-byte
    slots and unpack selection matrix when ``filtered``) — fits the
    budget."""
    if k > RING_TOPK_MAX_K or n_dev < 2 or NS > RING_FUSED_MAX_SEGS:
        return False
    cfg = _lut_scan_config(S, K, P, nb, Wb, lut_dtype)
    if cfg is None:
        return False
    G, Sg, Kc = cfg
    op_bytes = 4 if lut_dtype == "float32" else 2
    rotp = -(-rot // _LANES) * _LANES
    Rt = 2 * _LANES
    vmem_f = _filter_vmem_bytes(G, Rt) if filtered else 0
    vmem = vmem_f + (
        mc * rotp * 4                  # chunk queries
        + 2 * Rt * max(Wb, _LANES)     # u8 code slots (double buffer)
        + 2 * 2 * G * Rt * 8           # id + norm rows (2 slots)
        + Rt * G * S * 8               # unpacked bytes + codes (f32+i32)
        + S * K * P * Sg * op_bytes    # grouped block-diag codebooks
        + _LANES * Kc * Sg * 8         # one-hot transient (+tiled codes)
        + _LANES * rotp * 4            # decoded block
        + mc * _LANES * 4              # qd block
        + mc * indl_pad(mc) * 4        # probe-indicator eye transient
        + 2 * mc * _LANES * 4          # staged per-segment ⟨q,c⟩ + bias
        + 4 * mc * _LANES * 8          # 2-deep bins (keys+ids)
        + 10 * mc * _LANES * 8         # cand/run/recv ring blocks
        + 2 * Wb * G * S * 4           # selection matrices
    )
    return vmem <= _GROUPED_VMEM_BUDGET


def indl_pad(mc: int) -> int:
    """Lane padding of the probe-indicator rows (one lane per chunk
    query row)."""
    return -(-mc // _LANES) * _LANES


def ring_lut_scan_merge(chunk_lists: jax.Array, probe_ind: jax.Array,
                        qv_chunks: jax.Array, packed: jax.Array,
                        ids: jax.Array, norms: jax.Array,
                        centers_rot: jax.Array, codebooks: jax.Array,
                        k: int, metric: str = "l2", *, pq_bits: int,
                        pq_dim: int, L: int, axis_name: str, n_dev: int,
                        lut_dtype: str = "float32",
                        filter_bytes=None,
                        interpret: bool = False
                        ) -> Tuple[jax.Array, jax.Array]:
    """Fused per-shard LUT scan + ring top-k exchange — codes to merged
    top-k in ONE persistent kernel (ROADMAP item 5's end state for the
    sharded hot path).

    Must be called inside ``shard_map`` over ``axis_name`` on a 1-D
    mesh. The query axis is pre-split into the ring's n_dev chunks:

    - ``chunk_lists [n_dev, NS]`` i32 — each chunk's union of probed
      lists, −1 pad (replicated; lives in SMEM);
    - ``probe_ind [n_dev, NS, mc]`` f32 — 1 where chunk query row r
      probed that list (0 rows make a pad segment inert);
    - ``qv_chunks [n_dev, mc, rot]`` f32 — ROTATED queries per chunk;
    - ``packed`` / ``ids`` / ``norms`` / ``centers_rot`` /
      ``codebooks`` — this shard's index arrays, exactly as
      :func:`ivfpq_lut_scan_topk` takes them (ids must be GLOBAL row
      ids, as the sharded build bakes them).

    Per ring step the kernel scans the next chunk's lists UNDER the
    in-flight exchange and merges on arrival; the per-shard ``[m, k]``
    candidate table never reaches HBM — the only HBM traffic beyond
    the streamed index arrays is the [mc, 128] result block. Keys
    follow the LUT-scan convention (l2: ‖c+d‖² − 2⟨q,c+d⟩, caller adds
    ‖q‖²; ip: −⟨q,c+d⟩); comms bytes are the ring tier's (count via
    ``Comms.count_ring_topk``, byte model unchanged).

    ``filter_bytes`` [n_lists, ceil(L/8)] u8 — optional per-candidate
    packed filter mask over THIS SHARD's list slots
    (``sample_filter.pack_mask_bytes`` of the shard-sliced,
    local-id-remapped keep mask — see ``parallel.ivf``): streamed per
    code tile beside the codes and masked to the sentinel in the shared
    tile body, so filtered pod-scale search rides the ring kernel too.

    Returns (keys [mc, 128], ids [mc, 128]) — this device's owned query
    chunk, ascending, ids −1 for empty slots; callers emit ``P(axis)``
    out-specs and slice ``[:, :k]``.
    """
    n_dev2, mc, rot = qv_chunks.shape
    NS = chunk_lists.shape[1]
    S, K, Pl = codebooks.shape
    assert metric in ("l2", "ip")
    assert S == pq_dim and K == (1 << pq_bits) and n_dev2 == n_dev
    if k > RING_TOPK_MAX_K:
        raise ValueError(
            f"k={k} > {RING_TOPK_MAX_K} (the in-kernel merge is k "
            "extraction rounds per segment/hop — gate with "
            "ring_lut_scan_kernel_ok)")
    nb = (S * pq_bits + 7) // 8
    Wb = packed.shape[2]
    cfg = _lut_scan_config(S, K, Pl, nb, Wb, lut_dtype)
    if cfg is None:
        raise ValueError(
            f"unsupported packed-code layout for the fused scan-in-ring "
            f"kernel: nb={nb} Wb={Wb} (gate with ring_lut_scan_kernel_ok)")
    G, Sg, Kc = cfg

    R = packed.shape[1]
    Rt = 2 * _LANES if R >= 2 * _LANES else _LANES
    n_t = -(-R // Rt)
    # the manual tile DMAs address [tt·Rt, (tt+1)·Rt) directly — pad the
    # stored arrays to whole tiles (the grid pipeline clamps for the
    # standalone kernel; a raw make_async_copy must not read OOB)
    if packed.shape[1] < n_t * Rt:
        packed = _pad_to(packed, n_t * Rt, 1, 0)
    ids = _pad_to(ids, G * n_t * Rt, 1, -1)
    norms = _pad_to(norms, G * n_t * Rt, 1, 0.0)
    filtered = filter_bytes is not None
    Fbt = G * Rt // 8
    if filtered:
        fbits = _pad_to(filter_bytes, n_t * Fbt, 1, 0)
        fsel, foff = _filter_unpack_operands(G * Rt)

    qvp = _pad_to(qv_chunks.astype(jnp.float32), _LANES, 2, 0.0)
    rotp = qvp.shape[2]
    ctr = _pad_to(centers_rot.astype(jnp.float32), _LANES, 1, 0.0)
    indl = indl_pad(mc)
    ind = _pad_to(probe_ind.astype(jnp.float32), indl, 2, 0.0)

    sel_lo, sel_hi, off_arr, cbp = _lut_scan_operands(
        codebooks, pq_bits, nb, Wb, G, Sg, lut_dtype)
    n_sg = S // Sg

    kpad = _LANES
    kwargs = {}
    if not interpret:
        # distinct collective id from ring_topk_merge: a fused search
        # and a plain merge must never share a barrier semaphore
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            collective_id=2)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),    # chunk_lists
        pl.BlockSpec(memory_space=pltpu.ANY),     # probe indicator
        pl.BlockSpec(memory_space=pltpu.ANY),     # chunk queries
        pl.BlockSpec(memory_space=pltpu.ANY),     # packed codes
        pl.BlockSpec(memory_space=pltpu.ANY),     # ids
        pl.BlockSpec(memory_space=pltpu.ANY),     # norms
        pl.BlockSpec(memory_space=pltpu.ANY),     # rotated centers
    ]
    operands = [chunk_lists.astype(jnp.int32), ind, qvp, packed, ids,
                norms, ctr]
    if filtered:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))  # fbytes
        operands.append(fbits)
    in_specs += [
        pl.BlockSpec((Wb, G * S), lambda: (0, 0)),
        pl.BlockSpec((Wb, G * S), lambda: (0, 0)),
        pl.BlockSpec((1, G * S), lambda: (0, 0)),
        pl.BlockSpec((n_sg, K * Sg, Sg * Pl), lambda: (0, 0, 0)),
    ]
    operands += [sel_lo, sel_hi, off_arr, cbp]
    if filtered:
        in_specs += [
            pl.BlockSpec((Fbt, G * Rt), lambda: (0, 0)),
            pl.BlockSpec((1, G * Rt), lambda: (0, 0)),
        ]
        operands += [fsel, foff]
    scratch = [
        pltpu.VMEM((1, mc, rotp), jnp.float32),   # chunk queries
        pltpu.VMEM((1, rotp), jnp.float32),       # center row
        pltpu.VMEM((1, 1, indl), jnp.float32),    # probe indicator
        pltpu.VMEM((2, Rt, Wb), jnp.uint8),       # code tile slots
        pltpu.VMEM((2, G * Rt), jnp.int32),       # id row slots
        pltpu.VMEM((2, G * Rt), jnp.float32),     # norm row slots
    ]
    if filtered:
        scratch.append(pltpu.VMEM((2, Fbt), jnp.uint8))  # filter slots
    scratch += [
        pltpu.VMEM((mc, _LANES), jnp.float32),    # seg scalars: ⟨q,c⟩
        pltpu.VMEM((mc, _LANES), jnp.float32),    # seg scalars: bias
        pltpu.VMEM((mc, _LANES), jnp.float32),    # bins: best
        pltpu.VMEM((mc, _LANES), jnp.int32),
        pltpu.VMEM((mc, _LANES), jnp.float32),    # bins: second
        pltpu.VMEM((mc, _LANES), jnp.int32),
        pltpu.VMEM((mc, kpad), jnp.float32),      # chunk candidates
        pltpu.VMEM((mc, kpad), jnp.int32),
        pltpu.VMEM((mc, kpad), jnp.float32),      # ring running block
        pltpu.VMEM((mc, kpad), jnp.int32),
        pltpu.VMEM((2, mc, kpad), jnp.float32),   # recv slots
        pltpu.VMEM((2, mc, kpad), jnp.int32),
        pltpu.SemaphoreType.DMA,                  # chunk-query copy
        pltpu.SemaphoreType.DMA((2,)),            # center + indicator
        # code/id/norm (+filter) tile slots
        pltpu.SemaphoreType.DMA((2, 4 if filtered else 3)),
        pltpu.SemaphoreType.DMA((2, 2)),          # ring send
        pltpu.SemaphoreType.DMA((2, 2)),          # ring recv
        pltpu.SemaphoreType.REGULAR((2,)),        # slot capacity
    ]
    out_v, out_i = pl.pallas_call(
        functools.partial(
            _ring_lut_scan_kernel, k=k, n_dev=n_dev, mc=mc, NS=NS,
            n_t=n_t, metric=metric, pq_bits=pq_bits, S=S, P=Pl, G=G,
            Sg=Sg, Kc=Kc, L=L, Rt=Rt, rot=rot, rotp=rotp, indl=indl,
            axis_name=axis_name, flow_control=not interpret,
            filtered=filtered),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((mc, kpad), lambda: (0, 0)),
            pl.BlockSpec((mc, kpad), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mc, kpad), jnp.float32),
            jax.ShapeDtypeStruct((mc, kpad), jnp.int32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(*operands)
    return out_v, out_i
