"""Pallas TPU kernels for hot primitives (SURVEY.md §7)."""

from .pallas_kernels import (  # noqa: F401
    fused_l2_argmin,
    gather_refine_topk,
    grouped_scan_topk,
    ivfpq_lut_scan_topk,
    pallas_gather_refine_wanted,
    pallas_lut_scan_wanted,
    select_k_pallas,
)
