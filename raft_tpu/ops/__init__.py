"""Pallas TPU kernels for hot primitives (SURVEY.md §7)."""

from .pallas_kernels import fused_l2_argmin, select_k_pallas  # noqa: F401
