"""select_k — batched top-k selection, the primitive gating every ANN search.

TPU-native counterpart of ``raft::matrix::select_k`` (matrix/select_k.cuh:81).
The reference dispatches between radix-select and warp-bitonic-sort kernels
(matrix/detail/select_k-inl.cuh:293); on TPU the equivalents are:

- ``lax.top_k`` — XLA's sort-based top-k, the robust default for any (len, k);
- a two-phase tiled top-k for very wide rows: per-tile ``lax.top_k`` then a
  merge pass over the concatenated per-tile candidates, mirroring the
  reference's per-tile select + cross-tile merge (knn_brute_force.cuh:234,276).

Selection is over rows of a ``[batch, len]`` matrix; ``select_min=True``
selects smallest values (distances), ``False`` largest (similarities).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

from raft_tpu.core.tracing import traced
from raft_tpu.obs import spans as _obs_spans
import jax.numpy as jnp
from jax import lax


def _top_k_signed(scores: jax.Array, k: int, select_min: bool):
    if select_min:
        neg_vals, idx = lax.top_k(-scores, k)
        return -neg_vals, idx
    return lax.top_k(scores, k)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


# Pallas route thresholds: wide rows where the running-buffer kernel
# beats lax.top_k's full sort; small k keeps its extraction loop short.
_PALLAS_MIN_LEN = 8192
_PALLAS_MAX_K = 64

# Large-k tier thresholds (64 < k ≤ tile): two-phase tiled select for
# wide rows (see the dispatch comment in select_k).
_LARGE_K_TILE = 16384
_LARGE_K_MIN_LEN = 65536


@traced("raft_tpu.select_k")
def select_k(
    scores: jax.Array,
    k: int,
    select_min: bool = True,
    input_indices: Optional[jax.Array] = None,
    len_tile: Optional[int] = None,
    impl: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest/largest entries per row.

    Parameters
    ----------
    scores : [batch, len] array.
    k : number of entries to select per row (k <= len).
    select_min : smallest (distance) vs largest (similarity) selection.
    input_indices : optional [batch, len] int array of source ids; when given,
        returned indices are gathered from it (the reference's in-indices
        overload, used for cross-tile merges).
    len_tile : optional tile width to bound the sort size for very wide rows;
        when set and len > len_tile a two-phase per-tile select + merge runs.

    Returns
    -------
    (values, indices): both [batch, k]; indices are positions into the row
    (or entries of ``input_indices`` when provided).
    """
    batch, n = scores.shape
    if k > n:
        raise ValueError(f"k={k} > len={n}")

    # algorithm choice (the reference's choose_select_k_algorithm,
    # matrix/detail/select_k-inl.cuh:293): Pallas running-buffer kernel
    # for wide rows / small k on TPU, lax.top_k otherwise
    if impl is None:
        impl = (
            "pallas"
            if _on_tpu() and n >= _PALLAS_MIN_LEN and k <= _PALLAS_MAX_K
            else "xla"
        )
    if impl == "pallas":
        _obs_spans.count_dispatch("select_k", "pallas")
        from raft_tpu.ops import select_k_pallas

        vals, idx = select_k_pallas(scores, k, select_min=select_min)
        if input_indices is not None:
            idx = jnp.take_along_axis(input_indices, idx, axis=1)
        return vals, idx

    # large-k tier (the reference's radix path covers k ≤ 2048 at large
    # len, select_radix.cuh): the full-row sort's cost grows with len,
    # so tile + merge once rows are wide enough that the two-phase
    # cost (n·log(tile) + tiles·k·log(tiles·k)) wins
    if (len_tile is None and k > _PALLAS_MAX_K and n >= _LARGE_K_MIN_LEN
            and n >= 4 * _LARGE_K_TILE):
        len_tile = _LARGE_K_TILE
    if len_tile is not None and n > len_tile and n > k:
        # the tiled tier is a distinct engine — account it as such, not
        # as plain "xla" (large-k scan triage needs the distinction)
        _obs_spans.count_dispatch("select_k", "xla_tiled")
        return _select_k_tiled(scores, k, select_min, input_indices, len_tile)
    _obs_spans.count_dispatch("select_k", "xla")

    vals, idx = _top_k_signed(scores, k, select_min)
    if input_indices is not None:
        idx = jnp.take_along_axis(input_indices, idx, axis=1)
    return vals, idx


def _select_k_tiled(scores, k, select_min, input_indices, len_tile):
    """Two-phase: per-tile top-k then merge (reference: tiled select in
    knn_brute_force.cuh:234-276)."""
    batch, n = scores.shape
    pad_val = jnp.array(jnp.inf if select_min else -jnp.inf, scores.dtype)
    n_tiles = -(-n // len_tile)
    n_pad = n_tiles * len_tile - n
    padded = jnp.pad(scores, ((0, 0), (0, n_pad)), constant_values=pad_val)
    tiles = padded.reshape(batch, n_tiles, len_tile)
    kk = min(k, len_tile)
    tile_vals, tile_idx = _top_k_signed(tiles.reshape(batch * n_tiles, len_tile), kk, select_min)
    tile_vals = tile_vals.reshape(batch, n_tiles, kk)
    tile_idx = tile_idx.reshape(batch, n_tiles, kk)
    # translate per-tile positions to row positions
    tile_idx = tile_idx + (jnp.arange(n_tiles, dtype=tile_idx.dtype) * len_tile)[None, :, None]
    cand_vals = tile_vals.reshape(batch, n_tiles * kk)
    cand_idx = tile_idx.reshape(batch, n_tiles * kk)
    vals, pos = _top_k_signed(cand_vals, k, select_min)
    idx = jnp.take_along_axis(cand_idx, pos, axis=1)
    if input_indices is not None:
        idx = jnp.take_along_axis(input_indices, idx, axis=1)
    return vals, idx


def merge_parts(
    part_vals: jax.Array,
    part_idx: jax.Array,
    k: int,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-part top-k candidate lists into a final top-k.

    Counterpart of ``knn_merge_parts`` (neighbors/detail/knn_merge_parts.cuh):
    parts come from index chunks / shards / probes, each already holding its
    local top-k with *global* ids in ``part_idx``.

    Parameters
    ----------
    part_vals, part_idx : [n_parts, batch, k_part] candidate values and ids.

    Returns
    -------
    (values, indices): [batch, k].
    """
    n_parts, batch, k_part = part_vals.shape
    flat_vals = jnp.transpose(part_vals, (1, 0, 2)).reshape(batch, n_parts * k_part)
    flat_idx = jnp.transpose(part_idx, (1, 0, 2)).reshape(batch, n_parts * k_part)
    return select_k(flat_vals, k, select_min=select_min, input_indices=flat_idx)
