"""Matrix utilities (reference: cpp/include/raft/matrix/*.cuh).

Thin named XLA surfaces over the reference's per-file matrix ops: argmax/
argmin (matrix/argmax.cuh), gather/scatter (matrix/gather.cuh), col_wise_sort
(matrix/col_wise_sort.cuh), linewise_op (matrix/linewise_op.cuh), slice
(matrix/slice.cuh), norm (matrix/norm.cuh), reverse, sign_flip, triangular.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def argmax(m: jax.Array) -> jax.Array:
    """Row-wise argmax (reference: matrix/argmax.cuh)."""
    return jnp.argmax(m, axis=1)


def argmin(m: jax.Array) -> jax.Array:
    """Row-wise argmin (reference: matrix/argmin.cuh)."""
    return jnp.argmin(m, axis=1)


def gather(m: jax.Array, indices: jax.Array) -> jax.Array:
    """Gather rows by index (reference: matrix/gather.cuh)."""
    return jnp.take(m, indices, axis=0)


def scatter(m: jax.Array, indices: jax.Array, rows: jax.Array) -> jax.Array:
    """Scatter rows into a copy of ``m`` (reference: matrix/scatter.cuh —
    value-semantic here)."""
    return m.at[indices].set(rows)


def col_wise_sort(m: jax.Array, ascending: bool = True) -> jax.Array:
    """Sort each column (reference: matrix/col_wise_sort.cuh)."""
    s = jnp.sort(m, axis=0)
    return s if ascending else s[::-1]


def slice_matrix(m: jax.Array, r0: int, c0: int, r1: int, c1: int) -> jax.Array:
    """Sub-matrix [r0:r1, c0:c1] (reference: matrix/slice.cuh)."""
    return m[r0:r1, c0:c1]


def norm(m: jax.Array, norm_type: str = "l2", axis: int = 1) -> jax.Array:
    """Row/col norms (reference: matrix/norm.cuh): "l1" | "l2" | "l2sqrt" | "linf"."""
    if norm_type == "l1":
        return jnp.sum(jnp.abs(m), axis=axis)
    if norm_type == "l2":
        return jnp.sum(m * m, axis=axis)
    if norm_type == "l2sqrt":
        return jnp.sqrt(jnp.sum(m * m, axis=axis))
    if norm_type == "linf":
        return jnp.max(jnp.abs(m), axis=axis)
    raise ValueError(f"unknown norm type {norm_type!r}")


def linewise_op(m: jax.Array, vec: jax.Array, op, along_rows: bool = True) -> jax.Array:
    """Apply a binary op between each matrix line and a vector
    (reference: matrix/linewise_op.cuh)."""
    if along_rows:
        return op(m, vec[None, :])
    return op(m, vec[:, None])


def reverse(m: jax.Array, axis: int = 0) -> jax.Array:
    """Reverse along an axis (reference: matrix/reverse.cuh)."""
    return jnp.flip(m, axis=axis)


def sign_flip(m: jax.Array) -> jax.Array:
    """Flip column signs so the max-|.| element of each column is positive
    (reference: matrix/detail/math.cuh signFlip — deterministic eigenvector
    orientation)."""
    idx = jnp.argmax(jnp.abs(m), axis=0)
    signs = jnp.sign(m[idx, jnp.arange(m.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return m * signs[None, :]


def triangular_upper(m: jax.Array) -> jax.Array:
    """Upper-triangular copy (reference: matrix/triangular.cuh)."""
    return jnp.triu(m)
