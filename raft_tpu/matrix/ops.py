"""Matrix utilities (reference: cpp/include/raft/matrix/*.cuh).

Thin named XLA surfaces over the reference's per-file matrix ops: argmax/
argmin, gather/scatter, col_wise_sort, linewise_op, slice, norm, reverse,
sign_flip, triangular, diagonal, init/copy/eye, math (power/sqrt/
reciprocal/ratio/threshold). One name per reference header so ported
algorithms read the same; the implementations are the jnp one-liners the
TPU compiler wants (SURVEY.md §2.3 note: expose the API surface, don't
re-implement kernels).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def argmax(m: jax.Array) -> jax.Array:
    """Row-wise argmax (reference: matrix/argmax.cuh)."""
    return jnp.argmax(m, axis=1)


def argmin(m: jax.Array) -> jax.Array:
    """Row-wise argmin (reference: matrix/argmin.cuh)."""
    return jnp.argmin(m, axis=1)


def gather(m: jax.Array, indices: jax.Array) -> jax.Array:
    """Gather rows by index (reference: matrix/gather.cuh)."""
    return jnp.take(m, indices, axis=0)


def scatter(m: jax.Array, indices: jax.Array, rows: jax.Array) -> jax.Array:
    """Scatter rows into a copy of ``m`` (reference: matrix/scatter.cuh —
    value-semantic here)."""
    return m.at[indices].set(rows)


def col_wise_sort(m: jax.Array, ascending: bool = True) -> jax.Array:
    """Sort each column (reference: matrix/col_wise_sort.cuh)."""
    s = jnp.sort(m, axis=0)
    return s if ascending else s[::-1]


def slice_matrix(m: jax.Array, r0: int, c0: int, r1: int, c1: int) -> jax.Array:
    """Sub-matrix [r0:r1, c0:c1] (reference: matrix/slice.cuh)."""
    return m[r0:r1, c0:c1]


def norm(m: jax.Array, norm_type: str = "l2", axis: int = 1) -> jax.Array:
    """Row/col norms (reference: matrix/norm.cuh): "l1" | "l2" | "l2sqrt" | "linf"."""
    if norm_type == "l1":
        return jnp.sum(jnp.abs(m), axis=axis)
    if norm_type == "l2":
        return jnp.sum(m * m, axis=axis)
    if norm_type == "l2sqrt":
        return jnp.sqrt(jnp.sum(m * m, axis=axis))
    if norm_type == "linf":
        return jnp.max(jnp.abs(m), axis=axis)
    raise ValueError(f"unknown norm type {norm_type!r}")


def linewise_op(m: jax.Array, vec: jax.Array, op, along_rows: bool = True) -> jax.Array:
    """Apply a binary op between each matrix line and a vector
    (reference: matrix/linewise_op.cuh)."""
    if along_rows:
        return op(m, vec[None, :])
    return op(m, vec[:, None])


def reverse(m: jax.Array, axis: int = 0) -> jax.Array:
    """Reverse along an axis (reference: matrix/reverse.cuh)."""
    return jnp.flip(m, axis=axis)


def sign_flip(m: jax.Array) -> jax.Array:
    """Flip column signs so the max-|.| element of each column is positive
    (reference: matrix/detail/math.cuh signFlip — deterministic eigenvector
    orientation)."""
    idx = jnp.argmax(jnp.abs(m), axis=0)
    signs = jnp.sign(m[idx, jnp.arange(m.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return m * signs[None, :]


def triangular_upper(m: jax.Array) -> jax.Array:
    """Upper-triangular copy (reference: matrix/triangular.cuh)."""
    return jnp.triu(m)


def get_diagonal(m: jax.Array) -> jax.Array:
    """Main diagonal (reference: matrix/diagonal.cuh)."""
    return jnp.diagonal(m)


def set_diagonal(m: jax.Array, d) -> jax.Array:
    """Copy of ``m`` with the diagonal set (reference: matrix/diagonal.cuh
    set_diagonal — value-semantic here)."""
    k = min(m.shape[0], m.shape[1])
    return m.at[jnp.arange(k), jnp.arange(k)].set(d)


def invert_diagonal(m: jax.Array) -> jax.Array:
    """Reciprocal of the diagonal in place of it (reference:
    matrix/diagonal.cuh invert_diagonal)."""
    return set_diagonal(m, 1.0 / get_diagonal(m))


def fill(shape, value, dtype=jnp.float32) -> jax.Array:
    """Constant matrix (reference: matrix/init.cuh)."""
    return jnp.full(shape, value, dtype)


def eye(n: int, dtype=jnp.float32) -> jax.Array:
    """Identity (reference: matrix/init.cuh / matrix.cuh)."""
    return jnp.eye(n, dtype=dtype)


def copy(m: jax.Array) -> jax.Array:
    """Copy (reference: matrix/copy.cuh — value semantics make this an
    alias; it exists so ported call sites keep their name)."""
    return jnp.asarray(m)


def power(m: jax.Array, exponent: float) -> jax.Array:
    """Element-wise power (reference: matrix/power.cuh)."""
    return jnp.power(m, exponent)


def sqrt(m: jax.Array) -> jax.Array:
    """Element-wise sqrt (reference: matrix/sqrt.cuh)."""
    return jnp.sqrt(m)


def reciprocal(m: jax.Array, scalar: float = 1.0,
               thres: Optional[float] = None) -> jax.Array:
    """``scalar / m`` with optional small-value thresholding to zero
    (reference: matrix/reciprocal.cuh)."""
    r = scalar / m
    if thres is not None:
        r = jnp.where(jnp.abs(m) <= thres, 0.0, r)
    return r


def ratio(m: jax.Array) -> jax.Array:
    """Each element divided by the matrix sum (reference: matrix/ratio.cuh)."""
    return m / jnp.sum(m)


def zero_small_values(m: jax.Array, thres: float) -> jax.Array:
    """Zero entries below ``thres`` (reference: matrix/threshold.cuh)."""
    return jnp.where(jnp.abs(m) < thres, 0.0, m)


def print_matrix(m: jax.Array, name: str = "matrix") -> str:
    """Formatted dump (reference: matrix/print.cuh). Returns the string
    and prints it."""
    s = f"{name} {tuple(m.shape)}:\n{np_str(m)}"
    print(s)
    return s


def np_str(m: jax.Array) -> str:
    import numpy as np

    return np.array2string(np.asarray(m), precision=4, suppress_small=True)
